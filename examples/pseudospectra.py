"""Pseudospectral portrait of the Grcar matrix (upstream
``examples/lapack_like/... Pseudospectra`` drivers)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
n = args.input("--n", "matrix size", 60)
npts = args.input("--npts", "grid points per side", 12)
args.process(report=True)

F = np.asarray(el.to_global(el.matrices.grcar(n, grid=grid)), np.float64)
A = el.from_global(F, el.MC, el.MR, grid=grid)
Z, sigmin = el.pseudospectra(A, (-2.0, 3.0), (-3.5, 3.5), nx=npts, ny=npts)
report("pseudospectra", n=n, npts=npts,
       sigmin_min=float(np.asarray(sigmin).min()),
       sigmin_max=float(np.asarray(sigmin).max()))
