"""Dense Mehrotra LP (upstream ``examples/optimization/LP.cpp``-style)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
m = args.input("--m", "constraints", 20)
n = args.input("--n", "variables", 50)
args.process(report=True)

rng = np.random.default_rng(0)
A = rng.normal(size=(m, n))
x0 = rng.uniform(0.5, 1.5, n)
b = A @ x0
c = A.T @ rng.normal(size=m) + rng.uniform(0.1, 2.0, n)
g = lambda F: el.from_global(np.atleast_2d(F.T).T if F.ndim == 1 else F,
                             el.MC, el.MR, grid=grid)
x, y, z, info = el.lp(g(A), g(b.reshape(-1, 1)), g(c.reshape(-1, 1)))
report("lp", m=m, n=n, converged=info["converged"],
       rel_gap=info["rel_gap"], iters=info["iters"])
