"""Robust PCA via ADMM+SVT (upstream ``examples/optimization/RPCA.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
m = args.input("--m", "rows", 60)
n = args.input("--n", "cols", 60)
rk = args.input("--rank", "low rank", 3)
args.process(report=True)

rng = np.random.default_rng(0)
Lo = rng.normal(size=(m, rk)) @ rng.normal(size=(rk, n))
S0 = np.zeros((m, n))
idx = rng.choice(m * n, (m * n) // 20, replace=False)
S0.flat[idx] = rng.normal(size=idx.size) * 10
M = el.from_global(Lo + S0, el.MC, el.MR, grid=grid)
Lhat, Shat, info = el.rpca(M)
err = np.linalg.norm(np.asarray(el.to_global(Lhat)) - Lo) / np.linalg.norm(Lo)
report("rpca", m=m, n=n, rank=rk, recovery_err=err,
       iters=info.get("iters", -1))
