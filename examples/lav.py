"""Sparse least-absolute-value regression (upstream ``examples/optimization/LAV.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
m = args.input("--m", "rows", 400)
n = args.input("--n", "cols", 60)
nnz = args.input("--nnz", "nonzeros", 3000)
args.process(report=True)

from elemental_tpu.sparse.core import dist_sparse_from_coo
from elemental_tpu.core.multivec import mv_from_global, mv_to_global
rng = np.random.default_rng(0)
rows = rng.integers(0, m, nnz)
cols = rng.integers(0, n, nnz)
vals = rng.normal(size=nnz)
import scipy.sparse as sp
As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
xt = rng.normal(size=n)
b = As @ xt
out = rng.choice(m, m // 10, replace=False)
b[out] += rng.normal(size=out.size) * 20            # gross outliers
A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid, dtype=np.float64)
x, info = el.lav_sparse(A, mv_from_global(b.reshape(-1, 1), grid=grid),
                        el.MehrotraCtrl(tol=1e-6, max_iters=60))
xg = np.asarray(mv_to_global(x)).ravel()
report("lav", m=m, n=n, converged=info["converged"],
       rel_gap=info["rel_gap"],
       recovery_err=float(np.linalg.norm(xg - xt) / np.linalg.norm(xt)))
