"""Hermitian eigensolver (upstream ``examples/lapack_like/HermitianEig.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
n = args.input("--n", "matrix size", 200)
args.process(report=True)

rng = np.random.default_rng(0)
G = rng.normal(size=(n, n))
F = (G + G.T) / 2
A = el.from_global(F, el.MC, el.MR, grid=grid)
w, Z = el.herm_eig(A)
Zg = np.asarray(el.to_global(Z))
w = np.asarray(w)
resid = np.linalg.norm(F @ Zg - Zg * w[None, :]) / np.linalg.norm(F)
orth = np.linalg.norm(Zg.T @ Zg - np.eye(n))
report("herm_eig", n=n, resid=resid, orth=orth,
       w_min=float(w[0]), w_max=float(w[-1]))
