"""HPD factor + solve driver (upstream ``examples/lapack_like/Cholesky.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
n = args.input("--n", "matrix size", 300)
nrhs = args.input("--nrhs", "right-hand sides", 4)
args.process(report=True)

rng = np.random.default_rng(0)
F = el.to_global(el.matrices.hermitian_uniform_spectrum(n, 1.0, 10.0, grid=grid))
F = np.asarray(F, np.float64)
A = el.from_global(F, el.MC, el.MR, grid=grid)
L = el.cholesky(A)
Lg = np.asarray(el.to_global(L))
resid = np.linalg.norm(F - Lg @ Lg.T) / np.linalg.norm(F)
B = el.from_global(rng.normal(size=(n, nrhs)), el.MC, el.MR, grid=grid)
X = el.hpd_solve(A, B)
sres = np.linalg.norm(F @ np.asarray(el.to_global(X))
                      - np.asarray(el.to_global(B))) / np.linalg.norm(F)
report("cholesky", n=n, factor_resid=resid, solve_resid=sres)
