"""QR + least squares (upstream ``examples/lapack_like/QR.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
m = args.input("--m", "rows", 400)
n = args.input("--n", "cols", 120)
args.process(report=True)

rng = np.random.default_rng(0)
F = rng.normal(size=(m, n))
A = el.from_global(F, el.MC, el.MR, grid=grid)
Ap, tau = el.qr(A)
Q = el.explicit_q(Ap, tau)
Qg = np.asarray(el.to_global(Q))
orth = np.linalg.norm(Qg.T @ Qg - np.eye(m))
b = rng.normal(size=(m, 1))
X = el.least_squares(A, el.from_global(b, el.MC, el.MR, grid=grid))
xref, *_ = np.linalg.lstsq(F, b, rcond=None)
err = np.linalg.norm(np.asarray(el.to_global(X)) - xref) / np.linalg.norm(xref)
report("qr", m=m, n=n, orth=orth, lstsq_err=err)
