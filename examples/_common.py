"""Shared example scaffolding: device/grid setup + reporting.

The analog of the boilerplate every upstream driver repeats
(``El::Initialize`` + ``El::Input`` + grid construction; Elemental
``examples/**``).  Examples run on whatever devices are visible -- the
one real TPU chip, or a virtual CPU mesh via

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/cholesky.py --n 512
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup(argv=None):
    import jax
    import elemental_tpu as el
    args = el.Args(sys.argv[1:] if argv is None else argv)
    height = args.input("--grid-height", "grid height (0 = near-square)", 0)
    devs = jax.devices()
    grid = el.Grid(devs, height=height or None)
    return el, args, grid


def report(name, **metrics):
    parts = " ".join(f"{k}={v:.3e}" if isinstance(v, float) else f"{k}={v}"
                     for k, v in metrics.items())
    print(f"[{name}] {parts}")
