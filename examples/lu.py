"""LU with partial pivoting (upstream ``examples/lapack_like/LU.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
n = args.input("--n", "matrix size", 300)
args.process(report=True)

rng = np.random.default_rng(0)
F = rng.normal(size=(n, n))
A = el.from_global(F, el.MC, el.MR, grid=grid)
LU, perm = el.lu(A)
lug = np.asarray(el.to_global(LU))
L = np.tril(lug, -1) + np.eye(n)
U = np.triu(lug)
resid = np.linalg.norm(L @ U - F[np.asarray(perm)]) / np.linalg.norm(F)
X = el.lu_solve(A, el.from_global(np.ones((n, 2)), el.MC, el.MR, grid=grid))
sres = np.linalg.norm(F @ np.asarray(el.to_global(X)) - 1.0)
report("lu", n=n, factor_resid=resid, solve_resid=sres)
