"""Grid-shape sweep of the HPD solve (the topology-variation smoke the
reference gets from its --r grid-height flag, SURVEY.md §5)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
n = args.input("--n", "matrix size", 160)
args.process(report=True)

import jax
rng = np.random.default_rng(0)
G = rng.normal(size=(n, n))
F = G @ G.T + n * np.eye(n)
devs = jax.devices()
p = len(devs)
heights = sorted({h for h in range(1, p + 1) if p % h == 0})
for r in heights:
    g = el.Grid(devs, height=r)
    A = el.from_global(F, el.MC, el.MR, grid=g)
    B = el.from_global(np.ones((n, 1)), el.MC, el.MR, grid=g)
    X = el.hpd_solve(A, B)
    resid = np.linalg.norm(F @ np.asarray(el.to_global(X)) - 1.0)
    report("spd_sweep", grid=f"{r}x{p//r}", resid=float(resid))
