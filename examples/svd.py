"""SVD driver (upstream ``examples/lapack_like/SVD.cpp``)."""
import numpy as np
from _common import setup, report

el, args, grid = setup()
m = args.input("--m", "rows", 250)
n = args.input("--n", "cols", 120)
args.process(report=True)

rng = np.random.default_rng(0)
F = rng.normal(size=(m, n))
A = el.from_global(F, el.MC, el.MR, grid=grid)
U, s, V = el.svd(A)
Ug, Vg = np.asarray(el.to_global(U)), np.asarray(el.to_global(V))
s = np.asarray(s)
rec = np.linalg.norm(Ug @ np.diag(s) @ Vg.T - F) / np.linalg.norm(F)
sref = np.linalg.svd(F, compute_uv=False)
serr = np.abs(np.sort(s)[::-1] - sref).max() / sref.max()
report("svd", m=m, n=n, reconstruct=rec, sv_err=serr)
