"""Registry-driven golden-coverage check (ISSUE 18 satellite).

Fails LOUDLY when any registered analysis driver variant lacks a golden
snapshot on the audit grids (1x1 + 2x2) -- for BOTH golden families:
``comm_plan/v1`` under ``tests/golden/comm_plans/`` and
``memory_plan/v1`` under ``tests/golden/memory_plans/``.

This replaces the per-gate heredoc copies that ``tools/check.sh`` used
to carry: ONE check, driven by the registry itself, so a newly
registered variant (``gemm_slice``, ``qr_abft``, a future pallas-only
driver, anything) with no snapshot breaks the gate the day it lands
instead of whenever the full ``diff --all`` path happens to run.  The
pallas panel overrides deliberately share the xla variants' snapshots
(comm/memory plans are panel-impl invariant; ``tools/check.sh kernels``
pins that), so coverage is per REGISTERED DRIVER NAME, the unit the
registry defines.

    python tools/golden_coverage.py           # check both families
    python tools/golden_coverage.py comm      # comm_plan goldens only
    python tools/golden_coverage.py mem       # memory_plan goldens only

Exit 0 on full coverage, 1 with a per-variant remediation command
otherwise.
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    family = argv[0] if argv else "all"
    if family not in ("all", "comm", "mem"):
        raise SystemExit(f"unknown golden family {family!r}; "
                         f"expected comm|mem|all")
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    from perf.comm_audit import (GRIDS, _bootstrap, golden_path,
                                 mem_golden_path)
    _bootstrap()
    from elemental_tpu import analysis as an
    names = an.driver_names()
    families = []
    if family in ("all", "comm"):
        families.append(("comm_plan", golden_path, "diff"))
    if family in ("all", "mem"):
        families.append(("memory_plan", mem_golden_path, "mem-diff"))
    missing = []
    for label, path_fn, cmd in families:
        for d in names:
            for grid in GRIDS:
                if not os.path.exists(path_fn(d, grid)):
                    missing.append(
                        (f"{label} {d} {grid[0]}x{grid[1]}",
                         f"python -m perf.comm_audit {cmd} {d} "
                         f"--update-golden"))
    if missing:
        print("MISSING golden snapshot(s) for registered driver "
              "variant(s):")
        for what, fix in missing:
            print(f"  {what}   (run: {fix})")
        return 1
    print(f"golden coverage ok ({len(names)} drivers x {len(GRIDS)} "
          f"grids x {len(families)} famil"
          f"{'y' if len(families) == 1 else 'ies'})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
