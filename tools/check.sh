#!/usr/bin/env bash
# One-shot pre-commit gate (ISSUE 3): style lint + comm-plan lint +
# golden comm-plan diff.  Run from anywhere; exits non-zero on ANY
# finding.  Future PRs run this before committing -- it is the cheap
# static slice of CI (seconds, no device execution); the full test suite
# stays `python -m pytest tests/ -m 'not slow'`.
#
#   tools/check.sh          # everything
#   tools/check.sh style    # ruff (or the stdlib fallback) only
#   tools/check.sh comm     # comm-plan lint + golden diff only
set -u
cd "$(dirname "$0")/.."

what="${1:-all}"
rc=0

if [ "$what" = "all" ] || [ "$what" = "style" ]; then
    echo "== style lint =="
    if command -v ruff >/dev/null 2>&1; then
        ruff check . || rc=1
    else
        # container images without ruff: the stdlib AST fallback covers
        # the highest-signal subset of the configured rules
        python tools/pyflakes_lite.py || rc=1
    fi
fi

if [ "$what" = "all" ] || [ "$what" = "comm" ]; then
    echo "== comm-plan lint =="
    python -m perf.comm_audit lint --all || rc=1
    echo "== golden comm-plan diff =="
    python -m perf.comm_audit diff --all || rc=1
fi

if [ "$rc" -eq 0 ]; then
    echo "check.sh: all gates passed"
else
    echo "check.sh: FAILURES (see above)" >&2
fi
exit "$rc"
