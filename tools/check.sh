#!/usr/bin/env bash
# One-shot pre-commit gate (ISSUE 3 + 4 + 5 + 6 + 7 + 9): style lint +
# comm-plan lint + golden comm-plan diff + autotuner cost-model
# self-check + the tier-1 tests/tune subset + the calu/tsqr lapack gate
# (comm lint/diff on the lu/qr variants, golden-coverage check, lu/qr
# tests) + the observability smoke (perf.trace run on a tiny 1x1
# problem) + the bench-trajectory regression gate (bench_diff) + the
# resilience gate (certified-solve smoke on 1x1 + 2x2 grids incl. an
# injected fault, and the fault-injection/health test suite).  Run
# from anywhere; exits non-zero on ANY finding.  Future PRs run this
# before committing -- style/comm/explain are the cheap static slice (no
# device execution); the tune/obs/resilience tests execute small
# factorizations on the virtual-CPU mesh (~a minute warm); the full test
# suite stays `python -m pytest tests/ -m 'not slow'`.
#
#   tools/check.sh            # everything
#   tools/check.sh style      # ruff (or the stdlib fallback) only
#   tools/check.sh comm       # comm-plan lint + golden diff + the
#                             #   quantized-collective gate (codec tests,
#                             #   *_commq golden byte-ratio pins, and the
#                             #   certified-solve smoke whose first rung
#                             #   runs int8 wire precision)
#   tools/check.sh tune       # cost-model self-check + tests/tune only
#   tools/check.sh obs        # perf.trace smoke + the ISSUE-20 fleet
#                             #   telemetry smoke (perf.trace serve
#                             #   --smoke: lifecycle timelines, SLO
#                             #   snapshot, flight-record replay) +
#                             #   bench_diff gate + tests/obs
#   tools/check.sh lapack     # calu/tsqr gate: lu/qr comm lint + golden diff,
#                             #   golden-coverage check, lapack lu/qr tests
#   tools/check.sh resilience # certified-solve smoke (1x1 + 2x2, CPU-safe)
#                             #   + tests/resilience fault/health suite
#   tools/check.sh serve      # solver-service gate (ISSUE 9): serve smoke
#                             #   on 1x1 + 2x2, the chaos acceptance
#                             #   matrix ({bitflip,scale,nan} x
#                             #   {redistribute,compute} x {oneshot,
#                             #   persistent} + the qr op column), the
#                             #   bench_serve schema smoke, and tests/serve
#   tools/check.sh fleet      # solver-fleet gate (ISSUE 19): fleet-smoke
#                             #   (pipelined multi-grid routing, tenant
#                             #   quota rejects, grid-loss + saturation
#                             #   chaos cells with replay) and the fleet
#                             #   scheduler/routing/fairness/chaos tests
#   tools/check.sh abft       # ABFT gate (ISSUE 11): checksum-guarded
#                             #   lu/cholesky smoke (clean 1x1 + 2x2, zero
#                             #   violations; injected faults recovered at
#                             #   panel granularity, recompute count == 1)
#                             #   + the *_abft comm-plan golden diff +
#                             #   tests/resilience/test_abft.py
#   tools/check.sh gemm       # slicing-gemm gate (ISSUE 16): the
#                             #   gemm_slice comm-plan goldens (1x1 +
#                             #   2x2), the comm_audit gemm-prefix
#                             #   lint/diff coverage, the tuner-selection
#                             #   pins (auto->slice on tall-skinny 2x4,
#                             #   auto->dot on 1x1), and the slice
#                             #   correctness/plan/knob test files
#   tools/check.sh kernels    # fused-panel gate (ISSUE 17): pallas panel
#                             #   smoke (interpret-mode lu/cholesky/qr on
#                             #   1x1 + 2x2, pivot-identical LU), the
#                             #   comm-plan byte-invariance sweep under
#                             #   panel_impl='pallas', and tests/kernels
#   tools/check.sh static     # the one-stop static slice (ISSUE 18): ruff
#                             #   (or pyflakes_lite), comm-plan lint, the
#                             #   memory-plan lint (EL006-EL009: peak
#                             #   budgets, VMEM gate cross-check, missing
#                             #   donation, double materialization), the
#                             #   golden memory-plan diff, and the
#                             #   registry-driven golden-coverage check
#                             #   over BOTH golden families -- no device
#                             #   execution anywhere
#   tools/check.sh redist     # one-shot redistribution gate (ISSUE 12 +
#                             #   13): plan-compiler unit + direct-vs-
#                             #   chain bit-equivalence tests (incl.
#                             #   nonzero alignments), the LOUD
#                             #   LEGAL_PAIRS^2 coverage check, the
#                             #   *_direct comm-plan golden diffs (gemm
#                             #   round wins + qr_lq/trsm_r/herk wins +
#                             #   the redist_md ragged byte drop), the
#                             #   redist_path knob + measured-constants
#                             #   tests, the EL002 rewrite-hint smoke,
#                             #   and redist_bench --smoke
set -u
cd "$(dirname "$0")/.."

what="${1:-all}"
rc=0

if [ "$what" = "all" ] || [ "$what" = "style" ]; then
    echo "== style lint =="
    if command -v ruff >/dev/null 2>&1; then
        ruff check . || rc=1
    else
        # container images without ruff: the stdlib AST fallback covers
        # the highest-signal subset of the configured rules
        python tools/pyflakes_lite.py || rc=1
    fi
fi

if [ "$what" = "all" ] || [ "$what" = "comm" ]; then
    echo "== comm-plan lint =="
    python -m perf.comm_audit lint --all || rc=1
    echo "== golden comm-plan diff =="
    python -m perf.comm_audit diff --all || rc=1
    echo "== quantized-collective golden diff (*_commq variants) =="
    python -m perf.comm_audit diff lu_calu_commq || rc=1
    python -m perf.comm_audit diff cholesky_lookahead_commq || rc=1
    echo "== quantization codec + comm_precision tier-1 tests =="
    python -m pytest tests/core/test_comm_precision.py \
        tests/analysis/test_comm_precision_plan.py \
        -q -m 'not slow' -p no:cacheprovider || rc=1
    echo "== certified-solve smoke (quantized first rung) =="
    JAX_PLATFORMS=cpu python -m perf.certify smoke || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "tune" ]; then
    echo "== autotuner cost-model self-check =="
    # trace-only: exits non-zero if any candidate scores non-finite or the
    # golden-geometry lookahead+crossover <= classic invariant breaks
    python -m perf.tune explain cholesky || rc=1
    echo "== tune tier-1 tests =="
    python -m pytest tests/tune -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "lapack" ]; then
    echo "== calu/tsqr comm-plan lint + golden diff (lu + qr variants) =="
    python -m perf.comm_audit lint lu || rc=1
    python -m perf.comm_audit lint qr || rc=1
    python -m perf.comm_audit diff lu || rc=1
    python -m perf.comm_audit diff qr || rc=1
    echo "== golden coverage: every registered driver variant has snapshots =="
    # registry-driven, both golden families (comm_plan + memory_plan);
    # replaces the old per-gate heredoc copies (ISSUE 18 satellite)
    python tools/golden_coverage.py || rc=1
    echo "== lapack calu/tsqr tier-1 tests =="
    python -m pytest tests/lapack/test_lu.py tests/lapack/test_lu_calu.py \
        tests/lapack/test_qr.py tests/lapack/test_qr_tsqr.py \
        -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "obs" ]; then
    echo "== perf.trace smoke (tiny n, 1x1 grid, CPU-safe) =="
    JAX_PLATFORMS=cpu python -m perf.trace run cholesky --n 64 --nb 16 \
        --grid 1x1 --out /tmp/el_trace_smoke.json >/dev/null || rc=1
    echo "== perf.trace serve smoke (fleet lifecycle + SLO + flight, ISSUE 20) =="
    # self-checking: complete timelines, flow-linked export with >= 2
    # grid-worker tracks, per-tenant SLO snapshot, and a bit-identical
    # flight-record replay of the grid-loss chaos cell
    JAX_PLATFORMS=cpu python -m perf.trace serve --smoke \
        --out /tmp/el_serve_trace_smoke.json >/dev/null || rc=1
    echo "== bench-trajectory regression gate =="
    # newest recorded bench vs the best of the earlier rounds (10% default
    # threshold on the roofline-normalized ratios)
    latest=$(ls BENCH_r*.json 2>/dev/null | sort | tail -1)
    if [ -n "$latest" ]; then
        python tools/bench_diff.py --check "$latest" || rc=1
    else
        echo "no BENCH_r*.json trajectory; skipping"
    fi
    echo "== obs tier-1 tests =="
    python -m pytest tests/obs -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "resilience" ]; then
    echo "== certified-solve smoke (lu + hpd, 1x1 + 2x2 grids, CPU-safe) =="
    # clean runs must certify; a one-shot injected fault must be repaired
    # by the escalation ladder; persistent corruption must be SURFACED
    JAX_PLATFORMS=cpu python -m perf.certify smoke || rc=1
    echo "== resilience tier-1 tests (fault injection + health + certify) =="
    python -m pytest tests/resilience -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "abft" ]; then
    echo "== abft smoke (guarded lu + cholesky + qr, clean + injected, CPU-safe) =="
    # clean guarded runs: zero violations, zero recomputes; a windowed
    # one-shot fault must be detected AT the injected panel and repaired
    # by exactly ONE panel re-execution (qr's injected kind is a bitflip,
    # the class only the ISSUE-15 checksums catch)
    JAX_PLATFORMS=cpu python -m perf.abft smoke || rc=1
    echo "== abft comm-plan goldens (lu_abft / cholesky_abft / qr_abft, 1x1 + 2x2) =="
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff lu_abft || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff cholesky_abft || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff qr_abft || rc=1
    echo "== abft tier-1 tests (detection/recovery acceptance matrix) =="
    python -m pytest tests/resilience/test_abft.py -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "redist" ]; then
    echo "== one-shot plan compiler + direct-vs-chain equivalence tests =="
    python -m pytest tests/core/test_redist_direct.py \
        tests/analysis/test_direct_plan.py \
        tests/tune/test_redist_path_knob.py \
        tests/tune/test_redist_constants.py \
        -q -m 'not slow' -p no:cacheprovider || rc=1
    echo "== LEGAL_PAIRS^2 plan coverage (compile_plan total on 2x2) =="
    # fail LOUDLY on any legal endpoint pair the compiler cannot plan
    # (ISSUE 13 closed the matrix: MD/CIRC endpoints included) -- a new
    # Dist or pair added without plan support would otherwise only
    # surface as a silent chain fallback at runtime
    python - <<'PY' || rc=1
import os, sys
sys.path.insert(0, os.getcwd())
from elemental_tpu.core.dist import LEGAL_PAIRS
from elemental_tpu.redist.plan import compile_plan
missing = []
for src in LEGAL_PAIRS:
    for dst in LEGAL_PAIRS:
        if src == dst:
            continue
        if compile_plan(src, dst, (6, 5), (2, 2)) is None:
            missing.append(f"{src} -> {dst}")
if missing:
    print("compile_plan returned None for LEGAL endpoint pair(s):")
    for m in missing:
        print(f"  {m}")
    sys.exit(1)
print(f"plan coverage ok ({len(LEGAL_PAIRS)}^2 endpoint pairs on 2x2)")
PY
    echo "== *_direct comm-plan goldens (one-shot wins, 1x1 + 2x2) =="
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff gemm_a_direct || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff gemm_b_direct || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff gemm_dot_direct || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff qr_lq_direct || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff trsm_r_direct || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff herk_direct || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff redist_md_direct || rc=1
    echo "== EL002 rewrite-hint smoke (lint --fix-hint accepted, clean) =="
    JAX_PLATFORMS=cpu python -m perf.comm_audit lint gemm --fix-hint || rc=1
    echo "== redist_bench smoke (1x1, chain-vs-direct bit-match) =="
    JAX_PLATFORMS=cpu python -m perf.redist_bench --smoke --reps 1 \
        > /dev/null || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "gemm" ]; then
    echo "== gemm_slice comm-plan goldens (1x1 + 2x2) =="
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff gemm_slice || rc=1
    echo "== comm_audit gemm-prefix coverage (lint + diff over all gemm variants) =="
    JAX_PLATFORMS=cpu python -m perf.comm_audit lint gemm || rc=1
    JAX_PLATFORMS=cpu python -m perf.comm_audit diff gemm || rc=1
    echo "== tuner-selection pins (auto->slice tall-skinny 2x4, auto->dot 1x1) =="
    # resolve on the comm_audit virtual-device mesh: slice must win the
    # tall-skinny geometry on a 2x4 grid and the pinned dot early-out
    # must keep the 1x1 tie-break (slice joining the space is additive)
    python - <<'PY' || rc=1
import os, sys
sys.path.insert(0, os.getcwd())
from perf.comm_audit import _bootstrap
_bootstrap()
import jax
import jax.numpy as jnp
import elemental_tpu as el
from elemental_tpu import tune

def pick(gshape, r, c):
    grid = el.Grid(jax.devices()[: r * c], height=r)
    kn = tune.resolve_knobs("gemm", gshape=gshape, dtype=jnp.float32,
                            grid=grid,
                            knobs={"alg": "auto", "nb": None,
                                   "comm_precision": None,
                                   "redist_path": None})
    return kn["alg"]

bad = []
got = pick((8192, 512, 256), 2, 4)
if got != "slice":
    bad.append(f"tall-skinny 2x4: auto -> {got!r}, want 'slice'")
got = pick((8192, 512, 256), 1, 1)
if got != "dot":
    bad.append(f"1x1: auto -> {got!r}, want 'dot'")
if bad:
    print("TUNER-SELECTION PIN FAILURE:")
    for b in bad:
        print(f"  {b}")
    sys.exit(1)
print("tuner-selection pins ok (auto->slice 2x4 tall-skinny, auto->dot 1x1)")
PY
    echo "== slicing-gemm tier-1 tests (correctness + plans + knob) =="
    python -m pytest tests/blas/test_level3_slice.py \
        tests/core/test_slice_plan.py \
        tests/analysis/test_gemm_slice_plan.py \
        tests/tune/test_gemm_slice_knob.py \
        -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "kernels" ]; then
    echo "== pallas panel-kernel smoke (interpret mode, 1x1 + 2x2, CPU-safe) =="
    # clean pallas-panel runs of all three primitives through the real
    # drivers: residual-bounded factors, LU pivots bit-identical to xla
    JAX_PLATFORMS=cpu python -m perf.kernels smoke || rc=1
    echo "== comm-plan invariance under panel_impl='pallas' =="
    # panels are replicated-local compute: re-tracing every factorization
    # variant with the fused kernels selected must yield BYTE-identical
    # plan documents (and still pass the golden gate)
    python - <<'PY' || rc=1
import json, os, sys
sys.path.insert(0, os.getcwd())
from perf.comm_audit import GRIDS, _bootstrap, _grid, golden_path
_bootstrap()
from elemental_tpu import analysis as an
from elemental_tpu.analysis import diff_docs, golden_doc
from elemental_tpu.analysis.drivers import panel_impl_override
fams = [d for d in an.driver_names()
        if d.split("_")[0] in ("lu", "cholesky", "qr")
        and not d.startswith("qr_lq")]
bad = []
for d in fams:
    for grid in GRIDS:
        base, _, _ = an.trace_driver(d, _grid(*grid))
        base_doc = json.dumps(golden_doc(base), indent=1)
        with panel_impl_override("pallas"):
            plan, _, _ = an.trace_driver(d, _grid(*grid))
        doc = golden_doc(plan)
        if json.dumps(doc, indent=1) != base_doc:
            bad.append(f"{d} {grid[0]}x{grid[1]}: plan bytes changed")
        with open(golden_path(d, grid)) as f:
            if diff_docs(json.load(f), doc):
                bad.append(f"{d} {grid[0]}x{grid[1]}: golden diff")
if bad:
    print("COMM-PLAN INVARIANCE FAILURE under panel_impl='pallas':")
    for b in bad:
        print(f"  {b}")
    sys.exit(1)
print(f"comm-plan invariance ok ({len(fams)} variants x {len(GRIDS)} grids)")
PY
    echo "== kernels tests, full ladder incl. slow rungs =="
    python -m pytest tests/kernels -q -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "static" ]; then
    # the one-stop static slice (ISSUE 18): no device execution anywhere.
    # `check.sh static` alone also re-runs style + comm lint so it is a
    # self-contained pre-commit entry point; under `all` those two already
    # ran above and only the memory-side checks are new work here.
    if [ "$what" = "static" ]; then
        echo "== style lint =="
        if command -v ruff >/dev/null 2>&1; then
            ruff check . || rc=1
        else
            python tools/pyflakes_lite.py || rc=1
        fi
        echo "== comm-plan lint =="
        python -m perf.comm_audit lint --all || rc=1
    fi
    echo "== memory-plan lint (EL006-EL009) =="
    python -m perf.comm_audit mem-lint --all || rc=1
    echo "== golden memory-plan diff =="
    python -m perf.comm_audit mem-diff --all || rc=1
    echo "== golden coverage (comm + memory families) =="
    python tools/golden_coverage.py || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "serve" ]; then
    echo "== solver-service smoke (1x1 + 2x2, exec-cache reuse, CPU-safe) =="
    JAX_PLATFORMS=cpu python -m perf.serve smoke || rc=1
    echo "== chaos acceptance matrix (faults x targets x modes, 2x2) =="
    JAX_PLATFORMS=cpu python -m perf.serve chaos || rc=1
    echo "== bench_serve schema smoke (p50/p99 + solves/sec present) =="
    JAX_PLATFORMS=cpu python bench_serve.py --smoke > /dev/null || rc=1
    echo "== serve tier-1 tests (admission/executor/policy/service/chaos) =="
    python -m pytest tests/serve -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$what" = "all" ] || [ "$what" = "fleet" ]; then
    echo "== solver-fleet smoke (multi-grid routing, quota, chaos cells) =="
    JAX_PLATFORMS=cpu python -m perf.serve fleet-smoke || rc=1
    echo "== fleet tier-1 tests (scheduler/routing/fairness/chaos) =="
    python -m pytest tests/serve/test_fleet.py \
        tests/serve/test_fleet_fairness.py \
        tests/serve/test_fleet_chaos.py \
        -q -m 'not slow' -p no:cacheprovider || rc=1
fi

if [ "$rc" -eq 0 ]; then
    echo "check.sh: all gates passed"
else
    echo "check.sh: FAILURES (see above)" >&2
fi
exit "$rc"
