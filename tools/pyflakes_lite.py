"""Dependency-free fallback for ``ruff check`` (stdlib ast only).

The container images this repo targets do not ship ruff/pyflakes and
installing packages is off-limits, so ``tools/check.sh`` falls back to
this checker when ``ruff`` is absent.  It implements the highest-signal
subset of the configured ``[tool.ruff]`` rules:

  * E999  syntax errors (everything must parse)
  * F401  unused imports (module scope; ``__init__.py`` facades and
          ``# noqa`` lines exempt, matching the pyproject config)
  * F811  import redefinition at module scope
  * F632  ``is`` comparisons against str/int literals

It intentionally implements NO undefined-name analysis (F821 needs real
scope resolution; false positives would make the gate ignorable).  When
ruff is available it takes precedence and this file is not consulted.

Usage: python tools/pyflakes_lite.py [paths...]   (exit 1 on findings)
"""
from __future__ import annotations

import ast
import os
import sys

DEFAULT_PATHS = ("elemental_tpu", "perf", "examples", "tests", "tools",
                 "bench.py")


def _py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _noqa_lines(src: str) -> set:
    return {i + 1 for i, line in enumerate(src.splitlines())
            if "# noqa" in line}


class _ImportVisitor(ast.NodeVisitor):
    """Module-scope imports + every name/attribute-root used anywhere."""

    def __init__(self):
        self.imports: dict = {}        # name -> (lineno, display)
        self.used: set = set()
        self._depth = 0

    def visit_Import(self, node):
        if self._depth == 0:
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                self.imports[name] = (node.lineno, a.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if self._depth == 0 and node.module != "__future__":
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                disp = f"{node.module or '.'}.{a.name}"
                self.imports[name] = (node.lineno, disp)
        self.generic_visit(node)

    def _scoped(self, node):
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _scoped

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Load, ast.Del)):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def check_file(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")]
    findings = []
    noqa = _noqa_lines(src)
    base = os.path.basename(path)

    # F811: module-scope import redefinition
    seen: dict = {}
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name.split(".")[0]
                if name in seen and node.lineno not in noqa:
                    findings.append((path, node.lineno, "F811",
                                     f"redefinition of {name!r} "
                                     f"(first at line {seen[name]})"))
                seen[name] = node.lineno

    # F401: unused module-scope imports (skip package facades)
    if base != "__init__.py":
        v = _ImportVisitor()
        v.visit(tree)
        exported = set()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__" \
                            and isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant):
                                exported.add(str(elt.value))
        for name, (lineno, disp) in v.imports.items():
            if name.startswith("_") or name in exported:
                continue
            if name not in v.used and lineno not in noqa:
                findings.append((path, lineno, "F401",
                                 f"{disp!r} imported but unused"))

    # F632: `is` against literals
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) and node.lineno not in noqa:
            for op, cmp_ in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Is, ast.IsNot)) and \
                        isinstance(cmp_, ast.Constant) and \
                        type(cmp_.value) in (str, int, bytes):
                    findings.append((path, node.lineno, "F632",
                                     "use ==/!= to compare with literals"))
    return findings


def main(argv=None) -> int:
    paths = (argv or sys.argv[1:]) or list(DEFAULT_PATHS)
    findings = []
    for path in _py_files(paths):
        findings.extend(check_file(path))
    for path, lineno, code, msg in findings:
        print(f"{path}:{lineno}: {code} {msg}")
    print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
