#!/usr/bin/env python3
"""Bench regression gate: compare a bench run against the BENCH_r*.json
trajectory and exit non-zero on regression (ISSUE 5).

The repo records one ``BENCH_rNN.json`` per PR round (the driver wraps
``bench.py``'s single JSON line under a ``"parsed"`` key).  This tool
makes that trajectory a GATE instead of an archive::

    python tools/bench_diff.py --check BENCH_r05.json
        # BENCH_r05 vs the best of BENCH_r01..r04 (same directory,
        # lower round index); exit 1 if any gated metric regressed
        # more than the threshold
    python tools/bench_diff.py current.json BENCH_r04.json BENCH_r03.json
        # explicit current-vs-baselines comparison (current.json may be
        # the wrapped form or a raw bench.py output line)

Gated metrics default to the ROOFLINE-NORMALIZED ratios ``vs_baseline``
(cholesky), ``lu_vs_baseline`` and ``gemm_vs_baseline`` (the ISSUE-16
tall-skinny GEMM headline, whose named value
``gemm_tall_skinny_tflops_per_chip`` is gated on the same wide band as
the LU TFLOP/s) -- raw TFLOP/s on shared/tunneled chips
swings ~2x run to run (see bench.py), while the in-run-roofline ratio
isolates algorithmic regressions from chip weather.  Override with one
or more ``--metric NAME`` (e.g. ``--metric value`` for raw cholesky
TFLOP/s, ``--metric lu_value``).

Thresholds: ``--threshold 0.10`` sets the global relative-drop tolerance
(default 10%); ``--threshold NAME=X`` pins a per-metric override (both
forms may repeat; built-in per-metric defaults live in
:data:`DEFAULT_PER_METRIC`).  A metric regresses when

    current < (1 - threshold) * max(baselines)

i.e. the gate compares against the BEST recorded value, so a slow decay
across rounds cannot ratchet the bar down.  Latency-style metrics listed
in :data:`LOWER_IS_BETTER` (the ``bench_serve.py`` percentiles, ISSUE 9)
invert: best is the MINIMUM baseline and a regression is
``current > (1 + threshold) * best`` -- so ``serve_p99_ms`` and
``serve_solves_per_sec`` (plus their ``serve_async_*`` twins from the
ISSUE-14 pipelined front, and the windowed worst-per-tenant
``serve_slo_p99_ms`` from the ISSUE-20 SLO monitor) gate serving
latency/throughput alongside the TFLOP/s headlines.  Nested documents under the
``"obs"`` key (the ``obs_bench/v1`` trail, including ISSUE 8's
``redist_wire_bytes`` total) are accepted and surfaced as informational
lines, never gated -- byte estimates are schedule properties, not
chip-weather measurements.  The one exception (ISSUE 13) is the
MEASURED one-shot redistribution rate: :func:`load_doc` promotes
``obs.redist_p2p_gbps.direct`` to a top-level ``redist_p2p_gbps`` key
gated alongside the TFLOP/s headlines (wide 40% band -- interconnect
microbenchmarks swing with fabric weather; zero-rate 1x1 runs are
skipped, not compared).  Metrics absent from the
current run or from every baseline are skipped with a note (older rounds
predate some metrics) -- which is also how METRIC RENAMES stay
false-positive-free: the bench names its headline values
(``"metric"``/``"lu_metric"``), :func:`load_doc` promotes them to
top-level keys (``doc[doc["lu_metric"]] = doc["lu_value"]``), and a
renamed metric (e.g. ``lu_n16384_...`` -> ``lu_n32768_...`` when ISSUE 6
raised the LU headline to N=32768) simply has no baseline until the next
round records one.  Stdlib-only: no jax import, safe anywhere.
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys

DEFAULT_METRICS = ("vs_baseline", "lu_vs_baseline",
                   "lu_n32768_tflops_per_chip",
                   "gemm_vs_baseline",
                   "gemm_tall_skinny_tflops_per_chip",
                   "serve_p99_ms", "serve_solves_per_sec",
                   "serve_async_p99_ms", "serve_async_solves_per_sec",
                   "serve_fleet_p99_ms", "serve_fleet_solves_per_sec",
                   "serve_slo_p99_ms",
                   "redist_p2p_gbps")
DEFAULT_THRESHOLD = 0.10

#: built-in per-metric thresholds (user ``--threshold NAME=X`` overrides).
#: Raw TFLOP/s metrics on shared/tunneled chips swing with chip weather
#: (see bench.py), so the named LU headline gets a wider band than the
#: roofline-normalized default ratios; serving wall-clock metrics swing
#: with host weather and get the same wide band.
DEFAULT_PER_METRIC = {"lu_n32768_tflops_per_chip": 0.25,
                      "gemm_tall_skinny_tflops_per_chip": 0.25,
                      "serve_p99_ms": 0.25,
                      "serve_solves_per_sec": 0.25,
                      "serve_async_p99_ms": 0.25,
                      "serve_async_solves_per_sec": 0.25,
                      "serve_fleet_p99_ms": 0.25,
                      "serve_fleet_solves_per_sec": 0.25,
                      "serve_slo_p99_ms": 0.25,
                      "redist_p2p_gbps": 0.40}

#: metrics where SMALLER is better (latency percentiles from
#: bench_serve.py): the gate inverts -- best baseline is the MINIMUM and
#: a regression is ``current > (1 + threshold) * best``.
LOWER_IS_BETTER = {"serve_p50_ms", "serve_p99_ms",
                   "serve_async_p50_ms", "serve_async_p99_ms",
                   "serve_fleet_p50_ms", "serve_fleet_p99_ms",
                   "serve_slo_p99_ms"}

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def load_doc(path: str) -> dict:
    """The bench metric dict of one file (unwraps the driver's record).

    Named headline values are promoted to top-level keys so per-metric
    gating/thresholds address them by their bench-assigned names (which
    carry the problem size, e.g. ``lu_n32768_tflops_per_chip``)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    for prefix in ("", "lu_", "gemm_"):
        name, val = doc.get(prefix + "metric"), doc.get(prefix + "value")
        if isinstance(name, str) and isinstance(val, (int, float)) \
                and name not in doc:
            doc[name] = val
    # the measured one-shot redistribution rate joins the gated set
    # (ISSUE 13): a zero rate means a 1x1/no-wire run -- skip it so a
    # single-chip round cannot poison the baseline or fail the gate
    obs = doc.get("obs")
    if isinstance(obs, dict) and "redist_p2p_gbps" not in doc:
        p2p = obs.get("redist_p2p_gbps")
        if isinstance(p2p, dict) and isinstance(p2p.get("direct"),
                                                (int, float)) \
                and p2p["direct"] > 0:
            doc["redist_p2p_gbps"] = p2p["direct"]
    return doc


def round_index(path: str):
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def trajectory_before(path: str) -> list:
    """Sibling BENCH_r*.json files with a strictly lower round index."""
    idx = round_index(path)
    if idx is None:
        raise SystemExit(f"--check {path}: expected a *_rNN.json filename")
    d = os.path.dirname(os.path.abspath(path))
    out = []
    for cand in sorted(glob.glob(os.path.join(d, "BENCH_r*.json"))):
        ci = round_index(cand)
        if ci is not None and ci < idx:
            out.append(cand)
    return out


def compare(current: dict, baselines: list, metrics, thresholds) -> list:
    """[(metric, current, best, baseline_file, threshold, regressed)] for
    every gated metric comparable on both sides."""
    rows = []
    for name in metrics:
        cur = current.get(name)
        if not isinstance(cur, (int, float)):
            continue
        lower = name in LOWER_IS_BETTER
        best, src = None, None
        for path, doc in baselines:
            v = doc.get(name)
            if isinstance(v, (int, float)) and (
                    best is None or (v < best if lower else v > best)):
                best, src = v, path
        if best is None:
            continue
        thr = thresholds.get(name, thresholds.get(None, DEFAULT_THRESHOLD))
        regressed = cur > (1.0 + thr) * best if lower \
            else cur < (1.0 - thr) * best
        rows.append((name, cur, best, src, thr, regressed))
    return rows


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    check = None
    paths = []
    metrics: list = []
    thresholds: dict = {None: DEFAULT_THRESHOLD, **DEFAULT_PER_METRIC}
    it = iter(argv)
    for arg in it:
        if arg == "--check":
            check = next(it)
        elif arg == "--metric":
            metrics.append(next(it))
        elif arg == "--threshold":
            v = next(it)
            if "=" in v:
                name, x = v.split("=", 1)
                thresholds[name] = float(x)
            else:
                thresholds[None] = float(v)
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            paths.append(arg)
    if check is not None:
        current_path = check
        baseline_paths = trajectory_before(check)
    else:
        if len(paths) < 2:
            raise SystemExit("need --check FILE or CURRENT BASELINE...")
        current_path, baseline_paths = paths[0], paths[1:]
    current = load_doc(current_path)
    baselines = [(p, load_doc(p)) for p in baseline_paths]
    if not baselines:
        print(f"bench_diff: no baselines before {current_path}; nothing to gate")
        return 0
    gated = metrics or list(DEFAULT_METRICS)
    rows = compare(current, baselines, gated, thresholds)
    print(f"# current: {current_path}   baselines: "
          f"{', '.join(os.path.basename(p) for p in baseline_paths)}")
    obs = current.get("obs")
    if isinstance(obs, dict) \
            and isinstance(obs.get("redist_wire_bytes"), (int, float)):
        logical = obs.get("redist_bytes")
        note = ""
        if isinstance(logical, (int, float)) and logical:
            note = f"  (logical {logical}, " \
                   f"{logical / max(obs['redist_wire_bytes'], 1):.2f}x)"
        print(f"# redist_wire_bytes: {obs['redist_wire_bytes']}{note}")
    print(f"{'metric':20s} {'current':>10s} {'best':>10s} {'delta':>8s} "
          f"{'thresh':>7s}  {'best from'}")
    failed = 0
    for name, cur, best, src, thr, regressed in rows:
        delta = (cur - best) / best if best else 0.0
        flag = "  REGRESSION" if regressed else ""
        print(f"{name:20s} {cur:10.4f} {best:10.4f} {delta:+7.1%} "
              f"{thr:7.0%}  {os.path.basename(src)}{flag}")
        failed += bool(regressed)
    skipped = [m for m in gated if m not in {r[0] for r in rows}]
    if skipped:
        print(f"# skipped (absent on one side): {', '.join(skipped)}")
    if not rows:
        print("bench_diff: no comparable metrics; nothing gated")
        return 0
    if failed:
        print(f"bench_diff: {failed} metric(s) regressed beyond threshold",
              file=sys.stderr)
        return 1
    print("bench_diff: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
