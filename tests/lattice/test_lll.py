"""Lattice-reduction oracles (El::LLL tier, SURVEY.md §3.5 ※).

Oracles: unimodularity of U, exact basis relation B_red = B U, the LLL
conditions via the checker, and known short vectors.
"""
import numpy as np

import elemental_tpu as el


def _g(F, grid):
    return el.from_global(np.asarray(F, np.float64), el.MC, el.MR, grid=grid)


def test_lll_identities(grid24):
    rng = np.random.default_rng(0)
    n = 8
    B = rng.integers(-30, 30, (n, n)).astype(np.float64)
    while abs(np.linalg.det(B)) < 1:
        B = rng.integers(-30, 30, (n, n)).astype(np.float64)
    R, U, info = el.lll(_g(B, grid24))
    Rg, Ug = np.asarray(el.to_global(R)), np.asarray(el.to_global(U))
    assert np.allclose(Rg, B @ Ug, atol=1e-6)
    assert abs(abs(np.linalg.det(Ug)) - 1.0) < 1e-6      # unimodular
    assert np.allclose(Ug, np.round(Ug), atol=1e-9)      # integer
    assert el.is_lll_reduced(R)
    # same lattice determinant
    assert np.isclose(abs(np.linalg.det(Rg)), abs(np.linalg.det(B)),
                      rtol=1e-8)
    # the first reduced vector is no longer than the shortest input column
    assert info["first_norm"] <= np.linalg.norm(B, axis=0).min() + 1e-9


def test_lll_knapsack_short_vector(grid24):
    """Classic knapsack-style lattice: LLL finds the planted short vector."""
    rng = np.random.default_rng(1)
    n = 6
    big = 1000
    a = rng.integers(100, 500, n)
    x = rng.integers(0, 2, n)
    s = int(a @ x)
    # lattice: columns (e_i, big*a_i) and (0, -big*s); the planted combo
    # gives the short vector (x, 0)
    B = np.zeros((n + 1, n + 1))
    B[:n, :n] = np.eye(n)
    B[n, :n] = big * a
    B[:n, n] = 0
    B[n, n] = -big * s
    R, U, info = el.lll(_g(B, grid24), delta=0.99)
    Rg = np.asarray(el.to_global(R))
    norms = np.linalg.norm(Rg, axis=0)
    assert norms.min() <= np.sqrt(n) + 1e-6     # found a (x,0)-class vector


def test_lll_converged_flag(grid24):
    """info['converged'] is True on normal termination and False when the
    sweep cap exits with an unreduced basis (instead of a silent return)."""
    rng = np.random.default_rng(7)
    n = 8
    B = rng.integers(-30, 30, (n, n)).astype(np.float64)
    while abs(np.linalg.det(B)) < 1:
        B = rng.integers(-30, 30, (n, n)).astype(np.float64)
    R, U, info = el.lll(_g(B, grid24))
    assert info["converged"] is True
    assert el.is_lll_reduced(R)
    # max_sweeps=0: the loop cannot run, the unreduced input comes back,
    # and the flag (backed by an is_lll_reduced check on cap exit) says so
    R0, U0, info0 = el.lll(_g(B, grid24), max_sweeps=0)
    assert not el.is_lll_reduced(R0)
    assert info0["converged"] is False
    np.testing.assert_allclose(np.asarray(el.to_global(R0)), B)


def test_lll_deep_and_svp(grid24):
    rng = np.random.default_rng(2)
    n = 6
    B = rng.integers(-20, 20, (n, n)).astype(np.float64)
    while abs(np.linalg.det(B)) < 1:
        B = rng.integers(-20, 20, (n, n)).astype(np.float64)
    Rd, Ud, _ = el.lll(_g(B, grid24), deep=True)
    assert el.is_lll_reduced(Rd, delta=0.75)
    v, nv = el.shortest_vector(_g(B, grid24))
    # v must be a lattice vector: integer coordinates in the basis
    coef = np.linalg.solve(B, v)
    assert np.allclose(coef, np.round(coef), atol=1e-6)
    R, _, info = el.lll(_g(B, grid24))
    assert nv <= info["first_norm"] + 1e-9
