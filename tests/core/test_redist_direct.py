"""One-shot redistribution (ISSUE 12): plan-compiler unit behavior plus
the direct-vs-chain bit-equivalence conformance matrix.

The compiled plan replaces a multi-hop chain with a single collective, so
the contract is EXACT: for every legal (src, dst) pair, every grid shape,
and ragged extents, ``path='direct'`` must produce the same storage-form
locals bit for bit as the historical chain -- a permutation of the same
payload bytes admits no tolerance.  The comm_precision codec composes:
bf16 rides the direct plan bit-identically to the chained bf16 wire
(bf16 rounding is idempotent across hops), int8 block-scale stays inside
its published error bound (tiling differs from the chain's fused kernel,
so the int8 cross-check is against full precision, not chain-int8).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elemental_tpu import (LEGAL_PAIRS, Grid, from_global, to_global,
                           redistribute)
from elemental_tpu.core.dist import Dist
from elemental_tpu.redist import engine
from elemental_tpu.redist.plan import compile_plan, comm_axes_for

MC, MR, VC, VR = Dist.MC, Dist.MR, Dist.VC, Dist.VR
STAR, MD, CIRC = Dist.STAR, Dist.MD, Dist.CIRC

PAIR_IDS = [f"{p[0].value},{p[1].value}" for p in LEGAL_PAIRS]


def f(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 997.0 + j + 1).astype(np.float64)


@pytest.fixture(scope="module")
def g11():
    return Grid(jax.devices()[:1])


@pytest.fixture(scope="module")
def g22():
    return Grid(jax.devices()[:4], height=2)


# ---------------------------------------------------------------------
# plan compiler units (pure index math, no device execution)
# ---------------------------------------------------------------------

def test_plan_none_only_for_true_noop():
    """Phase 2 (ISSUE 13): the only whitelisted fallback is src == dst at
    identical alignments; the former [MD,*]/[CIRC,CIRC] bailouts compile."""
    assert compile_plan((MC, MR), (MC, MR), (16, 16), (2, 2)) is None
    assert compile_plan((MC, MR), (MD, STAR), (16, 16), (2, 2)) is not None
    assert compile_plan((CIRC, CIRC), (MC, MR), (16, 16), (2, 2)).kind \
        == "bridge"
    # same pair at DIFFERENT alignments is a real rotation, not a no-op
    assert compile_plan((MC, MR), (MC, MR), (16, 16), (2, 2),
                        (0, 0), (1, 0)).kind == "ppermute"


@pytest.mark.parametrize("grid_shape", [(1, 1), (2, 2), (2, 4)],
                         ids=["1x1", "2x2", "2x4"])
def test_full_legal_pairs_coverage(grid_shape):
    """THE coverage acceptance pin: every LEGAL_PAIRS x LEGAL_PAIRS move
    compiles a plan; only the src == dst diagonal stays None (whitelisted
    no-ops).  tools/check.sh runs the same sweep as a loud gate."""
    for src in LEGAL_PAIRS:
        for dst in LEGAL_PAIRS:
            p = compile_plan(src, dst, (13, 9), grid_shape)
            if src == dst:
                assert p is None, (src, dst)
            else:
                assert p is not None, (src, dst)
                assert p.kind in ("local", "ppermute", "a2a", "bridge")


def test_plan_kinds_2x2():
    # pure relabelings compile to one ppermute hop
    assert compile_plan((MC, MR), (MR, MC), (16, 16), (2, 2)).kind \
        == "ppermute"
    assert compile_plan((VC, STAR), (VR, STAR), (16, 16), (2, 2)).kind \
        == "ppermute"
    # genuine reshuffles compile to one all_to_all
    for dst in ((STAR, STAR), (MR, STAR)):
        p = compile_plan((MC, MR), dst, (16, 16), (2, 2))
        assert p.kind == "a2a" and p.rounds == 1 and p.nslots == 4
        assert set(p.comm_axes) == {"mc", "mr"}


def test_plan_local_on_1x1():
    p = compile_plan((MC, MR), (MR, STAR), (16, 16), (1, 1))
    assert p.kind == "local" and p.rounds == 0 and p.wire_bytes(8) == 0


def test_wire_bytes_ring_model():
    p = compile_plan((MC, MR), (STAR, STAR), (16, 16), (2, 2))
    R, C = p.slot_shape
    assert p.wire_bytes(4) == R * C * 4 * (p.nslots - 1)
    pp = compile_plan((VC, STAR), (VR, STAR), (16, 16), (2, 2))
    R, C = pp.slot_shape
    assert pp.wire_bytes(4) == R * C * 4


def test_chain_cost_mirror():
    """The engine's chain-round mirror prices the factored dispatch the
    'auto' arbiter and EL002 fix hints compare against."""
    assert engine.chain_cost((MC, MR), (MC, MR), (32, 32), (2, 2), 4) \
        == (0, 0)
    assert engine.chain_cost((MC, MR), (MR, STAR), (32, 32), (1, 1), 4) \
        == (0, 0)
    rounds, nbytes = engine.chain_cost(
        (MC, MR), (MR, STAR), (32, 32), (2, 2), 4)
    assert rounds == 3 and nbytes > 0        # the 3-hop gather chain
    rounds_ss, _ = engine.chain_cost(
        (MC, MR), (STAR, STAR), (32, 32), (2, 2), 4)
    assert rounds_ss == 1                    # fused gather-to-replicated
    # the one-shot plan strictly beats the 3-hop chain on rounds
    assert compile_plan((MC, MR), (MR, STAR), (32, 32), (2, 2)).rounds \
        < rounds


def test_comm_axes_subset_of_mesh():
    axes = comm_axes_for((MC, MR), (MR, STAR), 2, 2)
    assert axes and set(axes) <= {"mc", "mr"}


# ---------------------------------------------------------------------
# direct-vs-chain bit-equivalence matrix
# ---------------------------------------------------------------------

def _check_pair(grid, src, dst, F):
    A = from_global(F, *src, grid=grid)
    Bc = redistribute(A, *dst, path="chain")
    Bd = redistribute(A, *dst, path="direct")
    assert Bd.dist == dst and (Bd.calign, Bd.ralign) == (Bc.calign, Bc.ralign)
    np.testing.assert_array_equal(np.asarray(Bd.local), np.asarray(Bc.local))
    np.testing.assert_array_equal(np.asarray(to_global(Bd)), F)


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_direct_matches_chain_2x2(g22, src, dst):
    _check_pair(g22, src, dst, f(13, 9))


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_direct_matches_chain_1x1(g11, src, dst):
    _check_pair(g11, src, dst, f(13, 9))


#: cheap 2x4 tier: one representative per plan regime (gather chains,
#: relabelings, replication, transpose); the full matrix is slow-tier
_SUBSET_24 = (
    ((MC, MR), (MR, STAR)), ((MC, MR), (STAR, VC)),
    ((MC, MR), (STAR, STAR)), ((VC, STAR), (VR, STAR)),
    ((MC, MR), (MR, MC)), ((VC, STAR), (MC, STAR)),
    ((STAR, VR), (MC, MR)), ((MR, STAR), (VC, STAR)),
    ((STAR, MC), (MC, MR)), ((VR, STAR), (MC, MR)),
    ((MC, STAR), (STAR, MR)), ((STAR, STAR), (MC, MR)),
    ((STAR, VC), (VC, STAR)), ((MR, MC), (MC, MR)),
    ((VC, STAR), (STAR, STAR)), ((STAR, MR), (MR, STAR)),
    ((MD, STAR), (MC, MR)), ((MC, MR), (CIRC, CIRC)),
    ((CIRC, CIRC), (MC, MR)), ((MC, MR), (MD, STAR)),
)


@pytest.mark.parametrize(
    "src,dst", _SUBSET_24,
    ids=[f"{s[0].value},{s[1].value}->{d[0].value},{d[1].value}"
         for s, d in _SUBSET_24])
def test_direct_matches_chain_2x4_subset(grid24, src, dst):
    _check_pair(grid24, src, dst, f(19, 11))


@pytest.mark.slow
@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_direct_matches_chain_2x4_full(grid24, src, dst):
    _check_pair(grid24, src, dst, f(19, 11))


# ---------------------------------------------------------------------
# nonzero-alignment matrix (phase 2: ISSUE 13)
# ---------------------------------------------------------------------

def _aligned_case(src, dst, r, c):
    """((src calign, ralign), (dst calign, ralign)) stressing every
    legal alignment: the LARGEST per source dim against a shifted
    destination.  MD moves keep zero alignments on both endpoints (the
    engine's ``to_dist`` contract; ``compile_plan`` mirrors it)."""
    from elemental_tpu.core.dist import stride as dist_stride
    if MD in src or MD in dst:
        return (0, 0), (0, 0)

    def one(pair, which):
        out = []
        for d in pair:
            S = 1 if d is CIRC else dist_stride(d, r, c)
            out.append(max(S - 1, 0) if which == "max" else min(1, S - 1))
        return tuple(out)
    return one(src, "max"), one(dst, "one")


def _check_aligned_pair(grid, src, dst, F):
    r, c = grid.height, grid.width
    sal, dal = _aligned_case(src, dst, r, c)
    A = from_global(F, *src, grid=grid, calign=sal[0], ralign=sal[1])
    Bc = redistribute(A, *dst, dal[0], dal[1], path="chain")
    Bd = redistribute(A, *dst, dal[0], dal[1], path="direct")
    assert Bd.dist == dst and (Bd.calign, Bd.ralign) == dal
    np.testing.assert_array_equal(np.asarray(Bd.local), np.asarray(Bc.local))
    np.testing.assert_array_equal(np.asarray(to_global(Bd)), F)


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_aligned_direct_matches_chain_2x2(g22, src, dst):
    _check_aligned_pair(g22, src, dst, f(13, 9))


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_aligned_direct_matches_chain_1x1(g11, src, dst):
    _check_aligned_pair(g11, src, dst, f(13, 9))


@pytest.mark.slow
@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_aligned_direct_matches_chain_2x4_full(grid24, src, dst):
    _check_aligned_pair(grid24, src, dst, f(19, 11))


def test_ragged_slots_beat_padded_plan_bytes():
    """ISSUE 13 byte acceptance: for an incompatible-residue pair the
    trimmed + subgroup-packed slots ship STRICTLY fewer wire bytes than
    the PR-12 padded plan (full-mesh exchange at max-local slot shape)."""
    from elemental_tpu.core import indexing as ix
    from elemental_tpu.core.dist import stride as dist_stride
    p = compile_plan((MD, STAR), (STAR, MD), (7, 5), (2, 2))
    assert p.kind == "a2a" and p.groups       # subgroup-packed
    # padded PR-12 model: 4 slots of (max_local x max_local) on the ring
    R_pad = ix.max_local_length(7, dist_stride(MD, 2, 2))
    C_pad = ix.max_local_length(5, 1)
    padded = R_pad * C_pad * 4 * (4 - 1)
    assert 0 < p.wire_bytes(4) < padded
    # the trimmed slot is strictly smaller than the padded one too
    assert p.slot_shape[0] * p.slot_shape[1] < R_pad * C_pad


# ---------------------------------------------------------------------
# comm_precision codec composition
# ---------------------------------------------------------------------

def _frac(m, n):
    rng = np.random.default_rng(7)
    return (rng.standard_normal((m, n)) * 3).astype(np.float32)


@pytest.mark.parametrize("dst", [(MR, STAR), (STAR, VC), (STAR, STAR)],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_direct_bf16_bit_identical_to_chain_bf16(g22, dst):
    """bf16 rounding is idempotent, so one encode on the direct plan
    lands the same bits as the chain's per-hop narrow wire."""
    F = _frac(13, 9)
    A = from_global(F, MC, MR, grid=g22)
    Bc = redistribute(A, *dst, comm_precision="bf16", path="chain")
    Bd = redistribute(A, *dst, comm_precision="bf16", path="direct")
    np.testing.assert_array_equal(np.asarray(Bc.local), np.asarray(Bd.local))
    # and the narrow wire actually rounded something (the test is live)
    assert not np.array_equal(np.asarray(to_global(Bd)), F)


@pytest.mark.parametrize("dst", [(MR, STAR), (STAR, STAR)],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_direct_int8_within_block_scale_bound(g22, dst):
    """int8 on the direct plan block-scale-packs every slot; its tiling
    differs from the chain's fused gather kernel, so the cross-check is
    the published error bound against FULL precision."""
    F = _frac(13, 9)
    A = from_global(F, MC, MR, grid=g22)
    out = np.asarray(to_global(
        redistribute(A, *dst, comm_precision="int8", path="direct")))
    assert np.max(np.abs(out - F)) <= np.abs(F).max() / 127.0 + 1e-7


def test_unquantized_direct_ignores_codec_on_1x1(g11):
    F = _frac(13, 9)
    A = from_global(F, MC, MR, grid=g11)
    out = redistribute(A, MR, STAR, comm_precision="int8", path="direct")
    np.testing.assert_array_equal(np.asarray(to_global(out)), F)


# ---------------------------------------------------------------------
# routing: 'auto', validation, trace records
# ---------------------------------------------------------------------

def test_paths_registry_pinned():
    assert engine.REDIST_PATHS == (None, "chain", "direct", "auto")


def test_invalid_path_raises(g22):
    A = from_global(f(8, 8), MC, MR, grid=g22)
    with pytest.raises(ValueError, match="path"):
        redistribute(A, MR, STAR, path="oneshot")


@pytest.mark.parametrize("dst", [(MR, STAR), (STAR, STAR), (VR, STAR)],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_auto_path_correct_on_both_grids(g11, g22, dst):
    F = f(13, 9)
    for grid in (g11, g22):
        A = from_global(F, MC, MR, grid=grid)
        with engine.redist_trace() as log:
            B = redistribute(A, *dst, path="auto")
        np.testing.assert_array_equal(np.asarray(to_global(B)), F)
        assert log[-1].path in ("chain", "direct")


def test_trace_records_carry_path_rounds_bytes(g22):
    F = f(16, 8)
    A = from_global(F, MC, MR, grid=g22)
    with engine.redist_trace() as log:
        redistribute(A, MR, STAR, path="chain")
        redistribute(A, MR, STAR, path="direct")
    chain_rec, direct_rec = log[-2:]
    assert chain_rec.path == "chain" and chain_rec.rounds == 3
    assert direct_rec.path == "direct" and direct_rec.rounds == 1
    assert chain_rec.wire_bytes > 0 and direct_rec.wire_bytes > 0


def test_obs_comm_events_carry_path_fields(g22):
    """The obs tracer's CommEvent records which route each entry took
    (ADVICE.md: read ``path``/``rounds``/``engine_wire_bytes`` to tell
    one-shot plans from chains in a trace) without disturbing the
    ring-model wire_bytes accounting older tests pin."""
    from elemental_tpu.obs.tracer import Tracer
    F = f(16, 8)
    A = from_global(F, MC, MR, grid=g22)
    with Tracer() as tr:
        redistribute(A, MR, STAR, path="chain")
        redistribute(A, MR, STAR, path="direct")
    chain_ev, direct_ev = tr.comms[-2:]
    assert chain_ev.path == "chain" and chain_ev.rounds == 3
    assert direct_ev.path == "direct" and direct_ev.rounds == 1
    assert direct_ev.engine_wire_bytes > 0
    # the ring-model estimate is path-independent (same logical move)
    assert chain_ev.wire_bytes == direct_ev.wire_bytes == chain_ev.bytes


def test_fallback_reason_and_obs_counter(g22):
    """A 'direct'/'auto' request that ends on the chain is VISIBLE: the
    RedistRecord carries fallback_reason and the obs registry counts a
    redist_fallbacks increment labeled with it (ISSUE 13 satellite)."""
    from elemental_tpu.obs import metrics
    A = from_global(f(13, 9), MC, MR, grid=g22)
    with metrics.scoped() as reg:
        with engine.redist_trace() as log:
            redistribute(A, MC, MR, path="direct")       # a no-op move
        assert log[-1].path == "chain"
        assert log[-1].fallback_reason == "noop"
        assert reg.counter_value("redist_fallbacks", reason="noop") == 1
    # the happy path records NO reason
    with engine.redist_trace() as log:
        redistribute(A, MR, STAR, path="direct")
    assert log[-1].path == "direct" and log[-1].fallback_reason == ""


def test_auto_consults_measured_constants(g22, tmp_path, monkeypatch):
    """ISSUE 13 acceptance: 'auto' arbitration reads the recorded
    redist_constants/v1 -- injected constants demonstrably FLIP the
    winner for the same move, and the chain pick is labeled
    'arbitration' in the trace record."""
    import jax as _jax
    from elemental_tpu.tune import cache as tcache
    monkeypatch.setenv(tcache.ENV_DIR, str(tmp_path))
    tcache.clear_redist_constants_memo()
    backend = _jax.default_backend()
    A = from_global(f(13, 9), MC, MR, grid=g22)
    try:
        # latency-dominated fabric: the 1-round one-shot plan must win
        # over the 3-hop chain despite its larger byte total
        tcache.save_redist_constants((2, 2), backend, alpha_s=1.0,
                                     bw_bytes_per_s=1e18, nsamples=4)
        with engine.redist_trace() as log:
            redistribute(A, MR, STAR, path="auto")
        assert log[-1].path == "direct"
        # bandwidth-starved fabric: the chain's smaller byte total wins
        tcache.save_redist_constants((2, 2), backend, alpha_s=1e-12,
                                     bw_bytes_per_s=1.0, nsamples=4)
        with engine.redist_trace() as log:
            redistribute(A, MR, STAR, path="auto")
        assert log[-1].path == "chain"
        assert log[-1].fallback_reason == "arbitration"
    finally:
        tcache.clear_redist_constants_memo()


def test_row_permute_records_reach_observers_not_goldens(g22):
    """move_rows/permute_rows_storage publish their GSPMD-planned motion
    to engine observers (the obs tracer must account the traffic) but
    stay OUT of redist_trace -- the comm-plan goldens pin explicit
    collective rounds only."""
    A = from_global(f(13, 9), MC, MR, grid=g22)
    perm = np.arange(13)
    perm[[0, 5]] = perm[[5, 0]]
    seen = []
    unobserve = engine.add_redist_observer(seen.append)
    try:
        with engine.redist_trace() as log:
            engine.permute_rows_storage(A, jnp.asarray(perm))
    finally:
        unobserve()
    assert any(r.kind == "row_permute" for r in seen)
    assert not any(r.kind == "row_permute" for r in log)
