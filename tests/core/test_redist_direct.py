"""One-shot redistribution (ISSUE 12): plan-compiler unit behavior plus
the direct-vs-chain bit-equivalence conformance matrix.

The compiled plan replaces a multi-hop chain with a single collective, so
the contract is EXACT: for every legal (src, dst) pair, every grid shape,
and ragged extents, ``path='direct'`` must produce the same storage-form
locals bit for bit as the historical chain -- a permutation of the same
payload bytes admits no tolerance.  The comm_precision codec composes:
bf16 rides the direct plan bit-identically to the chained bf16 wire
(bf16 rounding is idempotent across hops), int8 block-scale stays inside
its published error bound (tiling differs from the chain's fused kernel,
so the int8 cross-check is against full precision, not chain-int8).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elemental_tpu import (LEGAL_PAIRS, Grid, from_global, to_global,
                           redistribute)
from elemental_tpu.core.dist import Dist
from elemental_tpu.redist import engine
from elemental_tpu.redist.plan import compile_plan, comm_axes_for

MC, MR, VC, VR = Dist.MC, Dist.MR, Dist.VC, Dist.VR
STAR, MD, CIRC = Dist.STAR, Dist.MD, Dist.CIRC

PAIR_IDS = [f"{p[0].value},{p[1].value}" for p in LEGAL_PAIRS]


def f(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 997.0 + j + 1).astype(np.float64)


@pytest.fixture(scope="module")
def g11():
    return Grid(jax.devices()[:1])


@pytest.fixture(scope="module")
def g22():
    return Grid(jax.devices()[:4], height=2)


# ---------------------------------------------------------------------
# plan compiler units (pure index math, no device execution)
# ---------------------------------------------------------------------

def test_plan_none_for_noop_and_root_only_dists():
    assert compile_plan((MC, MR), (MC, MR), (16, 16), (2, 2)) is None
    assert compile_plan((MC, MR), (MD, STAR), (16, 16), (2, 2)) is None
    assert compile_plan((CIRC, CIRC), (MC, MR), (16, 16), (2, 2)) is None


def test_plan_kinds_2x2():
    # pure relabelings compile to one ppermute hop
    assert compile_plan((MC, MR), (MR, MC), (16, 16), (2, 2)).kind \
        == "ppermute"
    assert compile_plan((VC, STAR), (VR, STAR), (16, 16), (2, 2)).kind \
        == "ppermute"
    # genuine reshuffles compile to one all_to_all
    for dst in ((STAR, STAR), (MR, STAR)):
        p = compile_plan((MC, MR), dst, (16, 16), (2, 2))
        assert p.kind == "a2a" and p.rounds == 1 and p.nslots == 4
        assert set(p.comm_axes) == {"mc", "mr"}


def test_plan_local_on_1x1():
    p = compile_plan((MC, MR), (MR, STAR), (16, 16), (1, 1))
    assert p.kind == "local" and p.rounds == 0 and p.wire_bytes(8) == 0


def test_wire_bytes_ring_model():
    p = compile_plan((MC, MR), (STAR, STAR), (16, 16), (2, 2))
    R, C = p.slot_shape
    assert p.wire_bytes(4) == R * C * 4 * (p.nslots - 1)
    pp = compile_plan((VC, STAR), (VR, STAR), (16, 16), (2, 2))
    R, C = pp.slot_shape
    assert pp.wire_bytes(4) == R * C * 4


def test_chain_cost_mirror():
    """The engine's chain-round mirror prices the factored dispatch the
    'auto' arbiter and EL002 fix hints compare against."""
    assert engine.chain_cost((MC, MR), (MC, MR), (32, 32), (2, 2), 4) \
        == (0, 0)
    assert engine.chain_cost((MC, MR), (MR, STAR), (32, 32), (1, 1), 4) \
        == (0, 0)
    rounds, nbytes = engine.chain_cost(
        (MC, MR), (MR, STAR), (32, 32), (2, 2), 4)
    assert rounds == 3 and nbytes > 0        # the 3-hop gather chain
    rounds_ss, _ = engine.chain_cost(
        (MC, MR), (STAR, STAR), (32, 32), (2, 2), 4)
    assert rounds_ss == 1                    # fused gather-to-replicated
    # the one-shot plan strictly beats the 3-hop chain on rounds
    assert compile_plan((MC, MR), (MR, STAR), (32, 32), (2, 2)).rounds \
        < rounds


def test_comm_axes_subset_of_mesh():
    axes = comm_axes_for((MC, MR), (MR, STAR), 2, 2)
    assert axes and set(axes) <= {"mc", "mr"}


# ---------------------------------------------------------------------
# direct-vs-chain bit-equivalence matrix
# ---------------------------------------------------------------------

def _check_pair(grid, src, dst, F):
    A = from_global(F, *src, grid=grid)
    Bc = redistribute(A, *dst, path="chain")
    Bd = redistribute(A, *dst, path="direct")
    assert Bd.dist == dst and (Bd.calign, Bd.ralign) == (Bc.calign, Bc.ralign)
    np.testing.assert_array_equal(np.asarray(Bd.local), np.asarray(Bc.local))
    np.testing.assert_array_equal(np.asarray(to_global(Bd)), F)


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_direct_matches_chain_2x2(g22, src, dst):
    _check_pair(g22, src, dst, f(13, 9))


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_direct_matches_chain_1x1(g11, src, dst):
    _check_pair(g11, src, dst, f(13, 9))


#: cheap 2x4 tier: one representative per plan regime (gather chains,
#: relabelings, replication, transpose); the full matrix is slow-tier
_SUBSET_24 = (
    ((MC, MR), (MR, STAR)), ((MC, MR), (STAR, VC)),
    ((MC, MR), (STAR, STAR)), ((VC, STAR), (VR, STAR)),
    ((MC, MR), (MR, MC)), ((VC, STAR), (MC, STAR)),
    ((STAR, VR), (MC, MR)), ((MR, STAR), (VC, STAR)),
    ((STAR, MC), (MC, MR)), ((VR, STAR), (MC, MR)),
    ((MC, STAR), (STAR, MR)), ((STAR, STAR), (MC, MR)),
    ((STAR, VC), (VC, STAR)), ((MR, MC), (MC, MR)),
    ((VC, STAR), (STAR, STAR)), ((STAR, MR), (MR, STAR)),
    ((MD, STAR), (MC, MR)), ((MC, MR), (CIRC, CIRC)),
    ((CIRC, CIRC), (MC, MR)), ((MC, MR), (MD, STAR)),
)


@pytest.mark.parametrize(
    "src,dst", _SUBSET_24,
    ids=[f"{s[0].value},{s[1].value}->{d[0].value},{d[1].value}"
         for s, d in _SUBSET_24])
def test_direct_matches_chain_2x4_subset(grid24, src, dst):
    _check_pair(grid24, src, dst, f(19, 11))


@pytest.mark.slow
@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_direct_matches_chain_2x4_full(grid24, src, dst):
    _check_pair(grid24, src, dst, f(19, 11))


# ---------------------------------------------------------------------
# comm_precision codec composition
# ---------------------------------------------------------------------

def _frac(m, n):
    rng = np.random.default_rng(7)
    return (rng.standard_normal((m, n)) * 3).astype(np.float32)


@pytest.mark.parametrize("dst", [(MR, STAR), (STAR, VC), (STAR, STAR)],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_direct_bf16_bit_identical_to_chain_bf16(g22, dst):
    """bf16 rounding is idempotent, so one encode on the direct plan
    lands the same bits as the chain's per-hop narrow wire."""
    F = _frac(13, 9)
    A = from_global(F, MC, MR, grid=g22)
    Bc = redistribute(A, *dst, comm_precision="bf16", path="chain")
    Bd = redistribute(A, *dst, comm_precision="bf16", path="direct")
    np.testing.assert_array_equal(np.asarray(Bc.local), np.asarray(Bd.local))
    # and the narrow wire actually rounded something (the test is live)
    assert not np.array_equal(np.asarray(to_global(Bd)), F)


@pytest.mark.parametrize("dst", [(MR, STAR), (STAR, STAR)],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_direct_int8_within_block_scale_bound(g22, dst):
    """int8 on the direct plan block-scale-packs every slot; its tiling
    differs from the chain's fused gather kernel, so the cross-check is
    the published error bound against FULL precision."""
    F = _frac(13, 9)
    A = from_global(F, MC, MR, grid=g22)
    out = np.asarray(to_global(
        redistribute(A, *dst, comm_precision="int8", path="direct")))
    assert np.max(np.abs(out - F)) <= np.abs(F).max() / 127.0 + 1e-7


def test_unquantized_direct_ignores_codec_on_1x1(g11):
    F = _frac(13, 9)
    A = from_global(F, MC, MR, grid=g11)
    out = redistribute(A, MR, STAR, comm_precision="int8", path="direct")
    np.testing.assert_array_equal(np.asarray(to_global(out)), F)


# ---------------------------------------------------------------------
# routing: 'auto', validation, trace records
# ---------------------------------------------------------------------

def test_paths_registry_pinned():
    assert engine.REDIST_PATHS == (None, "chain", "direct", "auto")


def test_invalid_path_raises(g22):
    A = from_global(f(8, 8), MC, MR, grid=g22)
    with pytest.raises(ValueError, match="path"):
        redistribute(A, MR, STAR, path="oneshot")


@pytest.mark.parametrize("dst", [(MR, STAR), (STAR, STAR), (VR, STAR)],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_auto_path_correct_on_both_grids(g11, g22, dst):
    F = f(13, 9)
    for grid in (g11, g22):
        A = from_global(F, MC, MR, grid=grid)
        with engine.redist_trace() as log:
            B = redistribute(A, *dst, path="auto")
        np.testing.assert_array_equal(np.asarray(to_global(B)), F)
        assert log[-1].path in ("chain", "direct")


def test_trace_records_carry_path_rounds_bytes(g22):
    F = f(16, 8)
    A = from_global(F, MC, MR, grid=g22)
    with engine.redist_trace() as log:
        redistribute(A, MR, STAR, path="chain")
        redistribute(A, MR, STAR, path="direct")
    chain_rec, direct_rec = log[-2:]
    assert chain_rec.path == "chain" and chain_rec.rounds == 3
    assert direct_rec.path == "direct" and direct_rec.rounds == 1
    assert chain_rec.wire_bytes > 0 and direct_rec.wire_bytes > 0


def test_obs_comm_events_carry_path_fields(g22):
    """The obs tracer's CommEvent records which route each entry took
    (ADVICE.md: read ``path``/``rounds``/``engine_wire_bytes`` to tell
    one-shot plans from chains in a trace) without disturbing the
    ring-model wire_bytes accounting older tests pin."""
    from elemental_tpu.obs.tracer import Tracer
    F = f(16, 8)
    A = from_global(F, MC, MR, grid=g22)
    with Tracer() as tr:
        redistribute(A, MR, STAR, path="chain")
        redistribute(A, MR, STAR, path="direct")
    chain_ev, direct_ev = tr.comms[-2:]
    assert chain_ev.path == "chain" and chain_ev.rounds == 3
    assert direct_ev.path == "direct" and direct_ev.rounds == 1
    assert direct_ev.engine_wire_bytes > 0
    # the ring-model estimate is path-independent (same logical move)
    assert chain_ev.wire_bytes == direct_ev.wire_bytes == chain_ev.bytes


def test_row_permute_records_reach_observers_not_goldens(g22):
    """move_rows/permute_rows_storage publish their GSPMD-planned motion
    to engine observers (the obs tracer must account the traffic) but
    stay OUT of redist_trace -- the comm-plan goldens pin explicit
    collective rounds only."""
    A = from_global(f(13, 9), MC, MR, grid=g22)
    perm = np.arange(13)
    perm[[0, 5]] = perm[[5, 0]]
    seen = []
    unobserve = engine.add_redist_observer(seen.append)
    try:
        with engine.redist_trace() as log:
            engine.permute_rows_storage(A, jnp.asarray(perm))
    finally:
        unobserve()
    assert any(r.kind == "row_permute" for r in seen)
    assert not any(r.kind == "row_permute" for r in log)
