"""Quantized block-scaled collectives (ISSUE 8): codec round-trip
invariants, engine wire routing, and the zero-overhead ``None`` pin.

Covers the satellite acceptance list verbatim: bf16 exactness on
bf16-representable values, the int8 block-scale error bound against the
documented ``amax_tile / 127`` factor, NaN/Inf payloads passing through
un-masked (so the resilience health guards still see them), and
redist-count equality pinning ``comm_precision=None`` as the
bit-identical zero-overhead path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.core.dist import STAR, VC
from elemental_tpu.redist import engine
from elemental_tpu.redist.quantize import (COMM_PRECISIONS, QUANT_TILE,
                                           q8_decode, q8_encode, q8_pack,
                                           q8_unpack)

RNG = np.random.default_rng(1234)


def _grid(r, c):
    return el.Grid(jax.devices()[: r * c], height=r)


# ---------------------------------------------------------------------
# codec invariants (pure, device-free semantics)
# ---------------------------------------------------------------------

def test_comm_precision_vocabulary_pinned():
    assert COMM_PRECISIONS == (None, "bf16", "int8")
    from elemental_tpu.tune.knobs import COMM_PRECISIONS as TUNE_CP
    assert TUNE_CP == COMM_PRECISIONS


def test_int8_block_scale_error_bound():
    """|x - decode(encode(x))| <= amax_tile / 127 per element -- the
    documented bound (round-to-nearest actually achieves half of it; the
    full factor is what the README promises)."""
    x = RNG.normal(size=(3 * QUANT_TILE + 7, 2 * QUANT_TILE + 5))
    x = (x * np.logspace(0, 3, x.shape[1])[None, :]).astype(np.float32)
    q, scales = q8_encode(jnp.asarray(x))
    back = np.asarray(q8_decode(q, scales, jnp.float32))
    tr, tc = -(-x.shape[0] // QUANT_TILE), -(-x.shape[1] // QUANT_TILE)
    for ti in range(tr):
        for tj in range(tc):
            blk = x[ti * QUANT_TILE:(ti + 1) * QUANT_TILE,
                    tj * QUANT_TILE:(tj + 1) * QUANT_TILE]
            dec = back[ti * QUANT_TILE:(ti + 1) * QUANT_TILE,
                       tj * QUANT_TILE:(tj + 1) * QUANT_TILE]
            bound = np.abs(blk).max() / 127.0 + 1e-12
            assert np.abs(blk - dec).max() <= bound, (ti, tj)


def test_int8_zero_tiles_roundtrip_exactly():
    x = jnp.zeros((QUANT_TILE * 2, QUANT_TILE), jnp.float32)
    q, scales = q8_encode(x)
    assert np.asarray(q8_decode(q, scales, jnp.float32)).max() == 0.0


def test_q8_pack_unpack_is_encode_decode():
    """The bitcast scale-packing transport is lossless: unpack(pack(x))
    equals decode(encode(x)) bit for bit, at ragged shapes too."""
    for shape in ((QUANT_TILE, QUANT_TILE), (70, 33), (5, 129)):
        x = jnp.asarray(RNG.normal(size=shape).astype(np.float32)) * 100
        q, scales = q8_encode(x)
        via_codec = np.asarray(q8_decode(q, scales, jnp.float32))
        via_pack = np.asarray(q8_unpack(q8_pack(x), shape, jnp.float32))
        assert (via_codec == via_pack).all(), shape
        assert q8_pack(x).dtype == jnp.int8


def test_nan_inf_pass_through_unmasked():
    """Non-finite payloads must stay non-finite after decode (tile
    granular): the health guards' NaN/Inf scans keep their teeth under
    quantized wire."""
    x = RNG.normal(size=(2 * QUANT_TILE, 2 * QUANT_TILE)).astype(np.float32)
    x[3, 5] = np.nan
    x[QUANT_TILE + 2, QUANT_TILE + 9] = np.inf
    q, scales = q8_encode(jnp.asarray(x))
    back = np.asarray(q8_decode(q, scales, jnp.float32))
    assert not np.isfinite(back[3, 5])
    assert not np.isfinite(back[QUANT_TILE + 2, QUANT_TILE + 9])
    # clean tiles stay clean (corruption is tile-granular, not global)
    assert np.isfinite(back[:QUANT_TILE, QUANT_TILE:]).all()


def test_bad_mode_raises():
    g = _grid(1, 1)
    A = from_global(np.eye(8, dtype=np.float32), MC, MR, grid=g)
    with pytest.raises(ValueError, match="comm_precision"):
        engine.redistribute(A, STAR, STAR, comm_precision="fp8")
    with pytest.raises(ValueError, match="comm_precision"):
        el.lu(A, nb=4, comm_precision="fp8")


# ---------------------------------------------------------------------
# engine routing
# ---------------------------------------------------------------------

def test_bf16_exact_on_representable_values(grid24):
    """bf16 wire is EXACT for bf16-representable payloads (small ints,
    powers of two): the cast is the only perturbation."""
    vals = RNG.integers(-128, 128, size=(32, 32)).astype(np.float32)
    A = from_global(vals, MC, MR, grid=grid24)
    out = engine.redistribute(A, STAR, STAR, comm_precision="bf16")
    assert out.dtype == A.dtype
    assert (np.asarray(to_global(out)) == vals).all()


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_quantized_gather_roundtrip_error_bound(grid24, mode):
    arr = (RNG.normal(size=(48, 40)) * 10).astype(np.float32)
    A = from_global(arr, MC, MR, grid=grid24)
    out = np.asarray(to_global(engine.redistribute(A, STAR, STAR,
                                                   comm_precision=mode)))
    bound = np.abs(arr).max() * (1 / 127.0 if mode == "int8" else 1 / 128.0)
    assert np.abs(out - arr).max() <= bound + 1e-12
    # wire dtype is recorded on the trace record
    with engine.redist_trace() as log:
        engine.redistribute(A, STAR, STAR, comm_precision=mode)
    assert log[-1].wire_dtype == {"bf16": "bfloat16", "int8": "int8"}[mode]
    assert log[-1].dtype == "float32"


def test_panel_spread_quantized(grid24):
    arr = (RNG.normal(size=(64, 8)) * 3).astype(np.float32)
    P = from_global(arr, VC, STAR, grid=grid24)
    mc0, mr0 = engine.panel_spread(P)
    for mode in ("bf16", "int8"):
        mc, mr = engine.panel_spread(P, comm_precision=mode)
        bound = np.abs(arr).max() / (127.0 if mode == "int8" else 128.0)
        assert np.abs(np.asarray(to_global(mc))
                      - np.asarray(to_global(mc0))).max() <= bound + 1e-12
        assert np.abs(np.asarray(to_global(mr))
                      - np.asarray(to_global(mr0))).max() <= bound + 1e-12


def test_wire_mode_noops(grid24):
    """The knob is a no-op (bit-identical) where it cannot save a byte:
    1x1 grids, replicated sources, non-real-float payloads."""
    arr = RNG.normal(size=(16, 16)).astype(np.float32)
    # 1x1 grid: collectives elide, so quantization would only cost bits
    g1 = _grid(1, 1)
    A1 = from_global(arr, MC, MR, grid=g1)
    out = engine.redistribute(A1, STAR, STAR, comm_precision="int8")
    assert (np.asarray(to_global(out)) == arr).all()
    # replicated source: every target is a pure-local filter
    ss = from_global(arr, STAR, STAR, grid=grid24)
    out = engine.redistribute(ss, MC, MR, comm_precision="int8")
    assert (np.asarray(to_global(out)) == arr).all()
    # complex payload: the codec does not apply
    carr = (arr + 1j * arr).astype(np.complex64)
    Ac = from_global(carr, MC, MR, grid=grid24)
    outc = engine.redistribute(Ac, STAR, STAR, comm_precision="bf16")
    assert (np.asarray(to_global(outc)) == carr).all()


def test_int8_falls_back_to_bf16_off_the_gather_family(grid24):
    """Pairs without a fused int8 kernel degrade to the accuracy-safer
    bf16 cast -- recorded as bfloat16 wire, never silently full fat."""
    arr = RNG.normal(size=(32, 32)).astype(np.float32)
    A = from_global(arr, MC, MR, grid=grid24)
    with engine.redist_trace() as log:
        engine.redistribute(A, VC, STAR, comm_precision="int8")
    assert log[-1].wire_dtype == "bfloat16"


# ---------------------------------------------------------------------
# comm_precision=None: the bit-identical zero-overhead path
# ---------------------------------------------------------------------

def test_none_is_bit_identical_and_count_equal(grid24, redist_counter):
    """lu/cholesky with comm_precision=None produce bit-identical results
    through the SAME redistribution schedule (count equality) as the
    knob-free call -- None costs nothing, pinned."""
    n, nb = 32, 8
    F = RNG.normal(size=(n, n)).astype(np.float32)
    spd = (F @ F.T / n + n * np.eye(n)).astype(np.float32)
    A = from_global(F + n * np.eye(n, dtype=np.float32), MC, MR, grid=grid24)
    S = from_global(spd, MC, MR, grid=grid24)

    with engine.redist_counts() as c0:
        LU0, p0 = el.lu(A, nb=nb)
        L0 = el.cholesky(S, nb=nb)
    with engine.redist_counts() as c1:
        LU1, p1 = el.lu(A, nb=nb, comm_precision=None)
        L1 = el.cholesky(S, nb=nb, comm_precision=None)
    assert dict(c0) == dict(c1)
    assert (np.asarray(LU0.local) == np.asarray(LU1.local)).all()
    assert (np.asarray(p0) == np.asarray(p1)).all()
    assert (np.asarray(L0.local) == np.asarray(L1.local)).all()


# ---------------------------------------------------------------------
# end-to-end quantized drivers: documented residual class
# ---------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_lu_quantized_residual_class(grid24, mode):
    n, nb = 48, 8
    m = (RNG.normal(size=(n, n)) + n * np.eye(n)).astype(np.float32)
    A = from_global(m, MC, MR, grid=grid24)
    LU, perm = el.lu(A, nb=nb, comm_precision=mode)
    lu_g = np.asarray(to_global(LU), dtype=np.float64)
    L = np.tril(lu_g, -1) + np.eye(n)
    U = np.triu(lu_g)
    pa = m.astype(np.float64)[np.asarray(perm)]
    resid = np.linalg.norm(pa - L @ U) / np.linalg.norm(m)
    assert resid <= 5e-2, resid          # documented ~1e-2..1e-3 class
    assert np.isfinite(lu_g).all()


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_cholesky_quantized_residual_class(grid24, mode):
    n, nb = 48, 8
    F = RNG.normal(size=(n, n))
    spd = (F @ F.T / n + n * np.eye(n)).astype(np.float32)
    S = from_global(spd, MC, MR, grid=grid24)
    L = np.asarray(to_global(el.cholesky(S, nb=nb, comm_precision=mode)),
                   dtype=np.float64)
    resid = np.linalg.norm(spd - L @ L.T) / np.linalg.norm(spd)
    assert resid <= 5e-2, resid
    assert np.isfinite(L).all()


def test_qr_trsm_herk_gemm_accept_the_knob(grid24):
    """Every driver in the tuner's registry accepts comm_precision and
    stays within the quantized residual class."""
    n, nb = 32, 8
    m = RNG.normal(size=(n, n)).astype(np.float32)
    A = from_global(m, MC, MR, grid=grid24)
    B = from_global(RNG.normal(size=(n, n)).astype(np.float32), MC, MR,
                    grid=grid24)
    packed, tau = el.qr(A, nb=nb, comm_precision="bf16")
    R = np.triu(np.asarray(to_global(packed), dtype=np.float64))[:n]
    # |R| diag magnitudes match numpy's to the quantized class
    Rn = np.linalg.qr(m.astype(np.float64))[1]
    assert np.abs(np.abs(np.diag(R)) - np.abs(np.diag(Rn))).max() \
        <= 5e-2 * np.abs(np.diag(Rn)).max()
    T = from_global(np.tril(m) + n * np.eye(n, dtype=np.float32), MC, MR,
                    grid=grid24)
    X = el.trsm("L", "L", "N", T, B, nb=nb, comm_precision="bf16")
    tn = np.tril(m).astype(np.float64) + n * np.eye(n)
    assert np.linalg.norm(tn @ np.asarray(to_global(X), dtype=np.float64)
                          - np.asarray(to_global(B))) \
        / np.linalg.norm(np.asarray(to_global(B))) <= 5e-2
    H = el.herk("L", A, nb=nb, comm_precision="bf16")
    ref = np.tril(m.astype(np.float64) @ m.astype(np.float64).T)
    got = np.tril(np.asarray(to_global(H), dtype=np.float64))
    assert np.linalg.norm(got - ref) / np.linalg.norm(ref) <= 5e-2
    G = el.gemm(A, B, alg="C", nb=nb, comm_precision="bf16")
    refg = m.astype(np.float64) @ np.asarray(to_global(B), dtype=np.float64)
    assert np.linalg.norm(np.asarray(to_global(G), dtype=np.float64) - refg) \
        / np.linalg.norm(refg) <= 5e-2


# ---------------------------------------------------------------------
# obs: wire bytes are measured end-to-end
# ---------------------------------------------------------------------

def test_tracer_reports_wire_vs_logical_bytes(grid24):
    from elemental_tpu.obs import metrics as obs_metrics
    from elemental_tpu.obs.tracer import Tracer
    n, nb = 32, 8
    F = RNG.normal(size=(n, n))
    spd = (F @ F.T / n + n * np.eye(n)).astype(np.float32)
    S = from_global(spd, MC, MR, grid=grid24)
    with obs_metrics.scoped() as reg:
        with Tracer() as tr:
            el.cholesky(S, nb=nb, comm_precision="bf16")
    assert tr.redist_bytes_total() > 0
    assert 0 < tr.redist_wire_bytes_total() < tr.redist_bytes_total()
    # bf16 halves every quantized entry; diagonal-block and panel moves
    # all quantize here, so the total is half (small slack for any
    # entry the engine declined to quantize)
    assert tr.redist_wire_bytes_total() <= 0.75 * tr.redist_bytes_total()
    wire = sum(v for (name, _), v in reg.counters().items()
               if name == "redist_wire_bytes")
    assert wire == tr.redist_wire_bytes_total()
    # unquantized runs: wire == logical
    with Tracer() as tr0:
        el.cholesky(S, nb=nb)
    assert tr0.redist_wire_bytes_total() == tr0.redist_bytes_total()
