"""Environment layer: blocksize stack, Timer, CLI Args, Ctrl dataclasses.

Reference test analog: the reference exercises these through every driver
(``El::Input``/``ProcessInput`` in each test main; blocksize via
``SetBlocksize`` flags) rather than a dedicated unit file.
"""
import io
import time

import numpy as np
import pytest

import elemental_tpu as el


class TestBlocksize:
    def test_default(self):
        assert el.blocksize() == 128

    def test_push_pop(self):
        el.push_blocksize(64)
        assert el.blocksize() == 64
        assert el.pop_blocksize() == 64
        assert el.blocksize() == 128

    def test_scope(self):
        with el.blocksize_scope(32):
            assert el.blocksize() == 32
            with el.blocksize_scope(16):
                assert el.blocksize() == 16
            assert el.blocksize() == 32
        assert el.blocksize() == 128

    def test_underflow_and_validation(self):
        with pytest.raises(RuntimeError):
            el.pop_blocksize()
        with pytest.raises(ValueError):
            el.set_blocksize(0)

    def test_feeds_blocked_algorithms(self, grid24):
        """nb=None resolves through the stack: a tiny blocksize must change
        the blocked-loop trip count but not the factorization result."""
        rng = np.random.default_rng(0)
        G = rng.normal(size=(24, 24))
        A = G @ G.T + 24 * np.eye(24)
        Ad = el.from_global(A, el.MC, el.MR, grid=grid24)
        with el.blocksize_scope(4):
            L4 = np.asarray(el.to_global(el.cholesky(Ad)))
        L128 = np.asarray(el.to_global(el.cholesky(Ad)))
        np.testing.assert_allclose(np.tril(L4), np.tril(L128), atol=1e-10)


class TestTimer:
    def test_accumulates(self):
        t = el.Timer("x")
        t.start(); time.sleep(0.01); s = t.stop()
        assert s >= 0.009 and t.total() >= 0.009
        with t:
            time.sleep(0.005)
        assert t.total() >= 0.014
        t.reset()
        assert t.total() == 0.0

    def test_misuse(self):
        t = el.Timer()
        with pytest.raises(RuntimeError):
            t.stop()
        t.start()
        with pytest.raises(RuntimeError):
            t.start()


class TestArgs:
    def test_typed_parsing(self):
        a = el.Args(["--m", "500", "--tol", "1e-6", "--upper", "--name", "hi"])
        assert a.input("--m", "height", 100) == 500
        assert a.input("--tol", "tolerance", 1e-8) == 1e-6
        assert a.input("--upper", "uplo", False) is True
        assert a.input("--name", "label", "x") == "hi"
        assert a.input("--nb", "blocksize", 128) == 128   # default
        a.process()

    def test_unknown_flag_rejected(self):
        a = el.Args(["--bogus", "1"])
        a.input("--m", "height", 100)
        with pytest.raises(ValueError, match="unknown flag"):
            a.process()

    def test_required_missing(self):
        a = el.Args([])
        a.input("--m", "height", required=True)
        with pytest.raises(ValueError, match="missing required"):
            a.process()

    def test_dashed_value_consistency(self):
        """A non-bool flag consumes the next token as its value even when it
        starts with '--'; process() must tokenize identically."""
        a = el.Args(["--name", "--weird"])
        assert a.input("--name", "label", "d") == "--weird"
        a.process()   # must not reject '--weird' as an unknown flag

    def test_report(self):
        a = el.Args(["--m", "3"])
        a.input("--m", "height", 100)
        buf = io.StringIO()
        a.print_report(stream=buf)
        assert "--m" in buf.getvalue() and "height" in buf.getvalue()


class TestCtrl:
    def test_hashable_and_kwargs(self):
        c = el.HermitianEigCtrl(vectors=False, approach="tridiag")
        assert hash(c) is not None
        kw = c.kwargs()
        assert kw == {"vectors": False, "approach": "tridiag"}

    def test_threads_into_driver(self, grid24):
        rng = np.random.default_rng(1)
        A = rng.normal(size=(16, 16))
        A = A + A.T
        Ad = el.from_global(A, el.MC, el.MR, grid=grid24)
        c = el.HermitianEigCtrl(vectors=False, approach="tridiag", nb=8)
        w = el.herm_eig(Ad, **c.kwargs())
        np.testing.assert_allclose(np.sort(np.asarray(w)),
                                   np.linalg.eigvalsh(A), atol=1e-8)


class TestProgressLog:
    def test_records(self):
        p = el.ProgressLog("ipm")
        p.log(0, gap=1.0); p.log(1, gap=0.1)
        assert p.history("gap") == [1.0, 0.1]
