"""Interior (arbitrary-offset) extract/embed conformance.

Oracle: numpy slicing of the global array (the same known-f(i,j) style as
the redistribution conformance matrix, tests/core/test_redist.py).
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.core.dist import MC, MR, VC, VR, STAR
from elemental_tpu.redist.interior import (interior_view, interior_update,
                                           vstack, hstack)


PAIRS = [(MC, MR), (MR, MC), (VC, STAR), (STAR, VR), (MC, STAR), (STAR, STAR)]


def _mat(m, n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(m, n)).astype(np.float64)


RANGES = [((0, 5), (0, 7)), ((3, 11), (2, 9)), ((1, 13), (5, 6)),
          ((7, 13), (0, 11)), ((5, 6), (10, 11))]


@pytest.mark.parametrize("pair", PAIRS, ids=lambda p: f"{p[0].value}_{p[1].value}")
def test_interior_view(any_grid, pair):
    m, n = 13, 11
    F = _mat(m, n)
    A = el.from_global(F, *pair, grid=any_grid)
    for rows, cols in RANGES:
        B = interior_view(A, rows, cols)
        assert B.dist == A.dist and (B.calign, B.ralign) == (0, 0)
        got = np.asarray(el.to_global(B))
        np.testing.assert_allclose(got, F[rows[0]:rows[1], cols[0]:cols[1]])
        # padding-is-zero invariant
        assert B.local.shape == (B.col_stride * B.local_rows,
                                 B.row_stride * B.local_cols)


@pytest.mark.parametrize("pair", PAIRS, ids=lambda p: f"{p[0].value}_{p[1].value}")
def test_interior_update(any_grid, pair):
    m, n = 13, 11
    F = _mat(m, n)
    A = el.from_global(F, *pair, grid=any_grid)
    for rows, cols in RANGES:
        h, w = rows[1] - rows[0], cols[1] - cols[0]
        G = _mat(h, w, seed=7)
        B = el.from_global(G, *pair, grid=any_grid)
        out = interior_update(A, B, (rows[0], cols[0]))
        ref = F.copy()
        ref[rows[0]:rows[1], cols[0]:cols[1]] = G
        np.testing.assert_allclose(np.asarray(el.to_global(out)), ref)


def test_view_update_roundtrip(grid24):
    F = _mat(17, 15, seed=3)
    A = el.from_global(F, MC, MR, grid=grid24)
    B = interior_view(A, (4, 12), (3, 14))
    out = interior_update(A, B, (4, 3))
    np.testing.assert_allclose(np.asarray(el.to_global(out)), F)


def test_stacks(grid24):
    F, G = _mat(9, 6), _mat(5, 6, seed=1)
    A = el.from_global(F, MC, MR, grid=grid24)
    B = el.from_global(G, MC, MR, grid=grid24)
    np.testing.assert_allclose(np.asarray(el.to_global(vstack(A, B))),
                               np.vstack([F, G]))
    H = _mat(9, 4, seed=2)
    C = el.from_global(H, MC, MR, grid=grid24)
    np.testing.assert_allclose(np.asarray(el.to_global(hstack(A, C))),
                               np.hstack([F, H]))
