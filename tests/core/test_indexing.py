"""Property tests for the cyclic index math (Shift/Length semantics)."""
import numpy as np

from elemental_tpu.core import indexing as ix


def test_partition_is_exact():
    # every global index owned by exactly one rank, local indices contiguous
    rng = np.random.default_rng(0)
    for _ in range(200):
        n = int(rng.integers(0, 40))
        stride = int(rng.integers(1, 9))
        align = int(rng.integers(0, stride))
        seen = {}
        for q in range(stride):
            s = ix.shift(q, align, stride)
            l = ix.length(n, s, stride)
            assert l <= ix.max_local_length(n, stride)
            for il in range(l):
                i = il * stride + s
                assert i < n
                assert ix.owner(i, align, stride) == q
                assert i not in seen
                seen[i] = (q, il)
        assert len(seen) == n


def test_max_local_length_bounds():
    for n in range(0, 30):
        for stride in range(1, 9):
            ml = ix.max_local_length(n, stride)
            assert ml * stride >= n
            assert (ml - 1) * stride < n or n == 0
