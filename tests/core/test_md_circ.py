"""True [MD,STAR]/[STAR,MD] and [CIRC,CIRC] storage conformance.

Reference test style: ``tests/core/DistMatrix.cpp`` fills A[U,V] with a
known f(i,j) and checks every entry after ``B[U',V'] = A`` (SURVEY.md §5).
Here additionally: the MD storage leaf is genuinely distributed (each
device's slot range holds only its CRT-owned entries), the CIRC leaf
lives on the root device only, and [MD,STAR] diagonal extraction
allocates O(k/lcm) per device.
"""

import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.core.dist import (MD, CIRC, STAR, MC, MR, VC,
                                     md_slot_of_global, stride)


def _f(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 1000.0 + j).astype(np.float64)


@pytest.mark.parametrize("pair", [(MD, STAR), (STAR, MD)])
def test_md_roundtrip(any_grid, pair):
    m, n = (23, 1) if pair == (MD, STAR) else (1, 23)
    F = _f(m, n)
    A = el.from_global(F, *pair, grid=any_grid)
    assert np.allclose(np.asarray(el.to_global(A)), F)


@pytest.mark.parametrize("dst", [(MC, MR), (STAR, STAR), (VC, STAR)])
def test_md_to_dists_and_back(any_grid, dst):
    m = 29
    F = _f(m, 1)
    A = el.from_global(F, MD, STAR, grid=any_grid)
    B = el.redistribute(A, *dst)
    assert np.allclose(np.asarray(el.to_global(B)), F)
    C = el.redistribute(B, MD, STAR)
    assert np.allclose(np.asarray(el.to_global(C)), F)


def test_md_storage_is_distributed(any_grid):
    """Each device's slot range holds exactly its CRT-owned entries (and
    devices off the diagonal comm hold zeros)."""
    r, c = any_grid.height, any_grid.width
    m = 31
    F = _f(m, 1)
    A = el.from_global(F, MD, STAR, grid=any_grid)
    L = stride(MD, r, c)
    l = -(-m // L)
    stor = np.asarray(A.local).ravel()
    assert stor.shape[0] == r * c * l
    expect = np.zeros(r * c * l)
    expect[np.asarray(md_slot_of_global(r, c, m))] = F.ravel()
    assert np.allclose(stor, expect)
    # ownership: slot range of device (i, j) only holds k = i (mod r),
    # k = j (mod c)
    for dev in range(r * c):
        i, j = dev // c, dev % c
        seg = stor[dev * l:(dev + 1) * l]
        for t, v in enumerate(seg):
            if v != 0:
                k = int(v)  # f(k, 0) = 1000*k
                k = round(v / 1000.0)
                assert k % r == i and k % c == j


def test_circ_root_only(any_grid):
    F = _f(9, 7)
    A = el.from_global(F, CIRC, CIRC, grid=any_grid)
    assert np.allclose(np.asarray(el.to_global(A)), F)
    # storage lives on exactly one device
    shardings = {s.device for s in A.local.addressable_shards
                 if s.data.size}
    assert len(A.local.devices()) == 1
    B = el.redistribute(A, MC, MR)
    assert np.allclose(np.asarray(el.to_global(B)), F)
    C = el.redistribute(B, CIRC, CIRC)
    assert len(C.local.devices()) == 1
    assert np.allclose(np.asarray(el.to_global(C)), F)


# ---------------------------------------------------------------------
# ISSUE 14 satellite: CIRC endpoints folded into the jitted shard_map
# path (the eager to_global/from_global bridge is gone)
# ---------------------------------------------------------------------

@pytest.mark.parametrize("dst", [(MC, MR), (VC, STAR), (STAR, STAR)])
def test_circ_fold_equivalence(any_grid, dst):
    """Both CIRC legs through the jitted path are bit-identical to the
    global-bridge reference: gather-to-root stores exactly F on one
    device; scatter-from-root lands the same local storage as
    ``from_global`` at the same pair/alignment -- ragged shape included."""
    F = _f(19, 13)
    A = el.from_global(F, MC, MR, grid=any_grid)
    C = el.redistribute(A, CIRC, CIRC)
    assert len(C.local.devices()) == 1
    np.testing.assert_array_equal(np.asarray(C.local), F)
    B = el.redistribute(C, *dst)
    ref = el.from_global(F, *dst, grid=any_grid)
    np.testing.assert_array_equal(np.asarray(B.local),
                                  np.asarray(ref.local))


def test_circ_fold_honors_alignment(any_grid):
    F = _f(11, 9)
    C = el.from_global(F, CIRC, CIRC, grid=any_grid)
    B = el.redistribute(C, MC, MR, calign=1, ralign=1)
    ref = el.from_global(F, MC, MR, grid=any_grid, calign=1, ralign=1)
    assert (B.calign, B.ralign) == (1, 1)
    np.testing.assert_array_equal(np.asarray(B.local),
                                  np.asarray(ref.local))


def test_circ_fold_never_calls_eager_bridge(any_grid, monkeypatch):
    """The fold's whole point: neither CIRC leg may fall back to the
    eager global bridges (the pre-ISSUE-14 host-sync edge)."""
    from elemental_tpu.core import distmatrix as dm

    F = _f(9, 7)
    A = el.from_global(F, MC, MR, grid=any_grid)

    def _boom(*a, **kw):
        raise AssertionError("CIRC leg escaped to the eager bridge")

    monkeypatch.setattr(dm, "to_global", _boom)
    monkeypatch.setattr(dm, "from_global", _boom)
    C = el.redistribute(A, CIRC, CIRC)
    B = el.redistribute(C, VC, STAR)
    S = el.redistribute(B, STAR, STAR)
    monkeypatch.undo()
    np.testing.assert_array_equal(np.asarray(C.local), F)
    np.testing.assert_array_equal(np.asarray(S.local), F)


def test_get_diagonal_md(any_grid):
    r, c = any_grid.height, any_grid.width
    m = 26
    rng = np.random.default_rng(0)
    F = rng.normal(size=(m, m))
    A = el.from_global(F, MC, MR, grid=any_grid)
    d = el.get_diagonal(A, dist="md")
    assert (d.cdist, d.rdist) == (MD, STAR)
    L = stride(MD, r, c)
    assert d.local.shape[0] == r * c * (-(-m // L))      # O(k/lcm) slots
    assert np.allclose(np.asarray(el.to_global(d)).ravel(), np.diag(F))
    # round-trip through the engine
    ds = el.redistribute(d, STAR, STAR)
    assert np.allclose(np.asarray(ds.local).ravel(), np.diag(F))


def test_md_non_square_grid_gcd(any_grid):
    """Grids with gcd(r,c) > 1 leave some devices outside the diagonal
    comm; conversions must still round-trip exactly."""
    m = 17
    F = _f(m, 1)
    A = el.from_global(F, MD, STAR, grid=any_grid)
    B = el.redistribute(A, STAR, STAR)
    assert np.allclose(np.asarray(B.local), F)
