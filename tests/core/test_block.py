"""Tiled BlockMatrix layout: round-trips + gemm read-proxy.

Reference test style: the BLOCK wrap's conformance is the same fill-f(i,j)
round-trip matrix as ``tests/core/DistMatrix.cpp`` (SURVEY.md §5), plus
the proxy-conversion path upstream exercises whenever an elemental routine
receives a BLOCK operand.
"""
import numpy as np
import jax
import pytest

import elemental_tpu as el


def _f(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 1000.0 + j).astype(np.float64)


@pytest.mark.parametrize("shape", [(16, 24), (13, 7), (1, 9), (8, 8)])
def test_block_roundtrip(any_grid, shape):
    F = _f(*shape)
    B = el.block_from_global(F, grid=any_grid)
    assert np.allclose(np.asarray(el.block_to_global(B)), F)


@pytest.mark.parametrize("shape", [(16, 24), (13, 7), (23, 5)])
def test_block_cyclic_roundtrip(any_grid, shape):
    F = _f(*shape)
    B = el.block_from_global(F, grid=any_grid)
    A = el.block_to_cyclic(B)
    assert (A.cdist, A.rdist) == (el.MC, el.MR)
    assert np.allclose(np.asarray(el.to_global(A)), F)
    B2 = el.block_from_cyclic(A)
    assert np.allclose(np.asarray(el.block_to_global(B2)), F)


def test_cyclic_block_roundtrip(any_grid):
    F = _f(19, 11)
    A = el.from_global(F, el.MC, el.MR, grid=any_grid)
    B = el.block_from_cyclic(A)
    assert np.allclose(np.asarray(el.block_to_global(B)), F)
    A2 = el.block_to_cyclic(B)
    assert np.allclose(np.asarray(el.to_global(A2)), F)
    assert np.allclose(np.asarray(A2.local), np.asarray(A.local))


def test_block_sharding_is_tiled(any_grid):
    """The leaf is the padded global array under P('mc','mr') -- each
    device owns one contiguous tile (the XLA-native interop form)."""
    r, c = any_grid.height, any_grid.width
    F = _f(12, 20)
    B = el.block_from_global(F, grid=any_grid)
    tr, tc = B.tile_rows, B.tile_cols
    shards = B.local.addressable_shards
    assert len(shards) == r * c
    for s in shards:
        assert s.data.shape == (tr, tc)


def test_gemm_accepts_tiled(any_grid):
    rng = np.random.default_rng(0)
    Fa = rng.normal(size=(18, 12))
    Fb = rng.normal(size=(12, 10))
    Ba = el.block_from_global(Fa, grid=any_grid)
    Bb = el.block_from_global(Fb, grid=any_grid)
    C = el.gemm(Ba, Bb)
    assert isinstance(C, el.BlockMatrix)       # all-tiled in -> tiled out
    assert np.allclose(np.asarray(el.block_to_global(C)), Fa @ Fb)
    # mixed operands return elemental
    Ae = el.from_global(Fa, el.MC, el.MR, grid=any_grid)
    C2 = el.gemm(Ae, Bb)
    assert isinstance(C2, el.DistMatrix)
    assert np.allclose(np.asarray(el.to_global(C2)), Fa @ Fb)


def test_block_adopt_xla_array(any_grid):
    """Zero-copy adoption of an already-tiled XLA array."""
    r, c = any_grid.height, any_grid.width
    m, n = 8 * r, 4 * c
    F = _f(m, n)
    arr = jax.device_put(
        F, any_grid.sharding(jax.sharding.PartitionSpec("mc", "mr")))
    B = el.block_from_array(arr, grid=any_grid)
    assert np.allclose(np.asarray(el.block_to_global(B)), F)
    A = el.block_to_cyclic(B)
    assert np.allclose(np.asarray(el.to_global(A)), F)
