"""THE redistribution conformance matrix.

Port of the semantics of the reference's ``tests/core/DistMatrix.cpp`` (the
single most important test, per SURVEY.md §5): fill A[U,V] with a known
f(i,j), set B[U',V'] = A for every legal pair, and verify every entry.
Swept over all src x dst pairs, several grid shapes, and alignments.
"""
import numpy as np
import pytest

from elemental_tpu import LEGAL_PAIRS, from_global, to_global, redistribute, transpose_dist
from elemental_tpu.redist import engine


def f(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 997.0 + j + 1).astype(np.float64)


PAIR_IDS = [f"{p[0].value},{p[1].value}" for p in LEGAL_PAIRS]


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
@pytest.mark.parametrize("src", LEGAL_PAIRS, ids=PAIR_IDS)
def test_conformance_grid24(grid24, src, dst):
    F = f(13, 9)
    A = from_global(F, *src, grid=grid24)
    B = redistribute(A, *dst)
    assert B.dist == dst
    np.testing.assert_array_equal(np.asarray(to_global(B)), F)


@pytest.mark.parametrize("dst", LEGAL_PAIRS, ids=PAIR_IDS)
def test_conformance_from_mcmr_all_grids(any_grid, dst):
    from elemental_tpu import MC, MR

    F = f(17, 5)
    A = from_global(F, MC, MR, grid=any_grid)
    B = redistribute(A, *dst)
    C = redistribute(B, MC, MR)
    np.testing.assert_array_equal(np.asarray(to_global(B)), F)
    np.testing.assert_array_equal(np.asarray(to_global(C)), F)


@pytest.mark.parametrize("calign,ralign", [(1, 1), (0, 3), (1, 2)])
@pytest.mark.parametrize("dst", [p for p in LEGAL_PAIRS if p[0].value in ("MC", "VC", "STAR")][:6],
                         ids=lambda p: f"{p[0].value},{p[1].value}")
def test_conformance_aligned(grid24, dst, calign, ralign):
    """Nonzero alignments exercise the generic engine path."""
    from elemental_tpu import MC, MR

    F = f(11, 7)
    A = from_global(F, MC, MR, grid=grid24, calign=1, ralign=2)
    B = redistribute(A, *dst, calign=calign % 2, ralign=ralign)
    np.testing.assert_array_equal(np.asarray(to_global(B)), F)


def test_transpose_dist(grid24):
    from elemental_tpu import MC, MR
    import jax

    F = f(12, 8)
    A = from_global(F, MC, MR, grid=grid24)

    def tfn(a):
        return transpose_dist(a)

    out_meta = transpose_dist(A)  # storage-level transpose has same semantics
    np.testing.assert_array_equal(np.asarray(to_global(out_meta)), F.T)


@pytest.mark.parametrize("conj", [True, False])
@pytest.mark.parametrize("shape", [(24, 8), (19, 5)])
def test_panel_spread_matches_separate_redists(any_grid, shape, conj):
    """The fused one-collective panel spread must produce bitwise the same
    [MC,STAR] / [STAR,MR]-adjoint locals as the three-redistribute route it
    replaces, on every grid shape incl. ragged extents."""
    from elemental_tpu import MC, MR, VC, STAR, panel_spread

    m, k = shape
    rng = np.random.default_rng(31)
    F = rng.normal(size=(m, k)) + 1j * rng.normal(size=(m, k))
    A_vc = redistribute(from_global(F, MC, MR, grid=any_grid), VC, STAR)
    mc, mrH = panel_spread(A_vc, conj=conj)
    assert mc.dist == (MC, STAR) and mrH.dist == (STAR, MR)
    assert mc.gshape == (m, k) and mrH.gshape == (k, m)
    mc_ref = redistribute(A_vc, MC, STAR)
    mr_ref = redistribute(transpose_dist(A_vc, conj=conj), STAR, MR)
    np.testing.assert_array_equal(np.asarray(mc.local),
                                  np.asarray(mc_ref.local))
    np.testing.assert_array_equal(np.asarray(mrH.local),
                                  np.asarray(mr_ref.local))
    want = np.conj(F.T) if conj else F.T
    np.testing.assert_array_equal(np.asarray(to_global(mrH)), want)


def test_panel_spread_rejects_wrong_dist(grid24):
    from elemental_tpu import MC, MR, panel_spread

    A = from_global(f(8, 4), MC, MR, grid=grid24)
    with pytest.raises(ValueError):
        panel_spread(A)


def test_contract_mc_star(grid24):
    """Partial [MC,STAR] summed over MR comm lands on [MC,MR]."""
    import jax
    from jax.sharding import PartitionSpec as P
    from elemental_tpu import MC, MR, STAR, zeros

    F = f(9, 10)
    # every device in a grid row holds partial = F/c restricted to its rows
    c = grid24.width
    A = from_global(F / c, MC, STAR, grid=grid24)

    def fn(a):
        return engine.contract(a, MC, MR)

    out_meta = zeros(9, 10, MC, MR, grid=grid24, dtype=F.dtype)
    from elemental_tpu.core.compat import shard_map
    B = shard_map(fn, mesh=grid24.mesh, in_specs=(A.spec,),
                  out_specs=out_meta.spec, check_vma=False)(A)
    np.testing.assert_allclose(np.asarray(to_global(B)), F, rtol=1e-12)


# ---------------------------------------------------------------------
# scoped call counting + dist-metadata trace hooks (ISSUE 3 satellites)
# ---------------------------------------------------------------------

def test_redist_counts_scoped_and_isolated(grid24):
    """redist_counts() swaps a fresh counter in, readable during and
    after the block; the enclosing counter never sees inner counts."""
    from elemental_tpu import MC, MR, STAR

    F = f(8, 8)
    A = from_global(F, MC, MR, grid=grid24)
    with engine.redist_counts() as outer:
        redistribute(A, STAR, STAR)
        assert sum(outer.values()) == 1
        with engine.redist_counts() as inner:
            redistribute(A, STAR, STAR)
            redistribute(A, STAR, STAR)
            assert sum(inner.values()) == 2        # live inside the block
        assert sum(inner.values()) == 2            # and after it
        assert sum(outer.values()) == 1            # no leak outward
    assert engine.REDIST_COUNTS is not inner
    # the backward-compatible module global still counts outside any scope
    before = sum(engine.REDIST_COUNTS.values())
    redistribute(A, STAR, STAR)
    assert sum(engine.REDIST_COUNTS.values()) == before + 1


def test_redist_counter_fixture(grid24, redist_counter):
    """The pytest fixture wires the scoped counter through a test body."""
    from elemental_tpu import MC, MR, STAR

    A = from_global(f(8, 8), MC, MR, grid=grid24)
    assert sum(redist_counter.values()) == 0
    redistribute(A, STAR, STAR)
    assert redist_counter[((MC, MR), (STAR, STAR))] == 1


def test_redist_trace_records_metadata(grid24):
    """redist_trace() captures per-call dist metadata with object
    identities that prove data-flow adjacency (the analyzer's EL002
    evidence)."""
    from elemental_tpu import MC, MR, STAR, VC

    A = from_global(f(12, 12), MC, MR, grid=grid24)
    with engine.redist_trace() as log:
        V = redistribute(A, VC, STAR)
        redistribute(V, MC, MR)
    assert [r.label for r in log] == ["[MC,MR]->[VC,STAR]",
                                      "[VC,STAR]->[MC,MR]"]
    assert log[0].gshape == (12, 12) and log[0].dtype == "float64"
    assert log[1].in_id in log[0].out_ids          # fed back untouched
    assert engine._REDIST_TRACE is None            # restored on exit
