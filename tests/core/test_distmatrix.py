"""from_global/to_global storage-permutation roundtrips for every pair."""
import numpy as np
import pytest

from elemental_tpu import LEGAL_PAIRS, from_global, to_global


def checkerboard(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 1000 + j).astype(np.float64)


@pytest.mark.parametrize("pair", LEGAL_PAIRS, ids=lambda p: f"{p[0].value}_{p[1].value}")
def test_roundtrip(any_grid, pair):
    F = checkerboard(13, 9)
    A = from_global(F, *pair, grid=any_grid)
    np.testing.assert_array_equal(np.asarray(to_global(A)), F)


@pytest.mark.parametrize("calign,ralign", [(1, 0), (0, 1), (1, 3)])
def test_roundtrip_aligned(grid24, calign, ralign):
    from elemental_tpu import MC, MR

    F = checkerboard(10, 11)
    A = from_global(F, MC, MR, grid=grid24,
                    calign=calign % 2, ralign=ralign % 4)
    np.testing.assert_array_equal(np.asarray(to_global(A)), F)


def test_local_blocks_are_cyclic_slices(grid24):
    """Each device's storage tile equals the Elemental local matrix."""
    from elemental_tpu import MC, MR

    F = checkerboard(13, 9)
    A = from_global(F, MC, MR, grid=grid24)
    r, c = 2, 4
    lr, lc = A.local_rows, A.local_cols
    stor = np.asarray(A.local)
    for pr in range(r):
        for pc in range(c):
            tile = stor[pr * lr:(pr + 1) * lr, pc * lc:(pc + 1) * lc]
            want = np.zeros_like(tile)
            loc = F[pr::r, pc::c]
            want[: loc.shape[0], : loc.shape[1]] = loc
            np.testing.assert_array_equal(tile, want)


class TestRemoteUpdates:
    """AxpyInterface analog on DistMatrix (SURVEY §3.2 row 16)."""

    def test_batched_updates(self, any_grid):
        import elemental_tpu as el
        from elemental_tpu.core.distmatrix import remote_updates
        rng = np.random.default_rng(0)
        m, n = 13, 9
        F = rng.normal(size=(m, n))
        A = el.from_global(F, el.MC, el.MR, grid=any_grid)
        k = 40
        rows = rng.integers(0, m, k)
        cols = rng.integers(0, n, k)
        vals = rng.normal(size=k)
        B = remote_updates(A, rows, cols, vals)
        ref = F.copy()
        np.add.at(ref, (rows, cols), vals)      # duplicates accumulate
        assert np.allclose(np.asarray(to_global(B)), ref)

    def test_out_of_bounds_raises(self, any_grid):
        import elemental_tpu as el
        from elemental_tpu.core.distmatrix import remote_updates
        A = el.from_global(np.zeros((4, 4)), el.MC, el.MR, grid=any_grid)
        with pytest.raises(ValueError):
            remote_updates(A, [4], [0], [1.0])

    def test_vc_star_layout(self, any_grid):
        import elemental_tpu as el
        from elemental_tpu.core.distmatrix import remote_updates
        rng = np.random.default_rng(1)
        m, n = 17, 3
        F = rng.normal(size=(m, n))
        A = el.from_global(F, el.VC, el.STAR, grid=any_grid)
        rows = np.array([0, 16, 5, 5])
        cols = np.array([0, 2, 1, 1])
        vals = np.array([1.0, -2.0, 0.5, 0.5])
        B = remote_updates(A, rows, cols, vals)
        ref = F.copy()
        np.add.at(ref, (rows, cols), vals)
        assert np.allclose(np.asarray(to_global(B)), ref)
