"""from_global/to_global storage-permutation roundtrips for every pair."""
import numpy as np
import pytest

from elemental_tpu import LEGAL_PAIRS, DistMatrix, from_global, to_global


def checkerboard(m, n):
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    return (i * 1000 + j).astype(np.float64)


@pytest.mark.parametrize("pair", LEGAL_PAIRS, ids=lambda p: f"{p[0].value}_{p[1].value}")
def test_roundtrip(any_grid, pair):
    F = checkerboard(13, 9)
    A = from_global(F, *pair, grid=any_grid)
    np.testing.assert_array_equal(np.asarray(to_global(A)), F)


@pytest.mark.parametrize("calign,ralign", [(1, 0), (0, 1), (1, 3)])
def test_roundtrip_aligned(grid24, calign, ralign):
    from elemental_tpu import MC, MR

    F = checkerboard(10, 11)
    A = from_global(F, MC, MR, grid=grid24,
                    calign=calign % 2, ralign=ralign % 4)
    np.testing.assert_array_equal(np.asarray(to_global(A)), F)


def test_local_blocks_are_cyclic_slices(grid24):
    """Each device's storage tile equals the Elemental local matrix."""
    from elemental_tpu import MC, MR

    F = checkerboard(13, 9)
    A = from_global(F, MC, MR, grid=grid24)
    r, c = 2, 4
    lr, lc = A.local_rows, A.local_cols
    stor = np.asarray(A.local)
    for pr in range(r):
        for pc in range(c):
            tile = stor[pr * lr:(pr + 1) * lr, pc * lc:(pc + 1) * lc]
            want = np.zeros_like(tile)
            loc = F[pr::r, pc::c]
            want[: loc.shape[0], : loc.shape[1]] = loc
            np.testing.assert_array_equal(tile, want)
