"""ISSUE 16: slice-set compilation (``compile_slice_plan``) + the ragged
FFD slot-packing edge cases the slicing gemm newly exercises."""
import numpy as np
import pytest

from elemental_tpu.core.dist import MC, MR, VC, STAR
from elemental_tpu.redist.plan import (compile_plan, compile_slice_plan,
                                       gemm_slice_plans, slice_row_mode)


def _same_plan(a, b):
    assert a.kind == b.kind and a.gshape == b.gshape
    assert a.slot_shape == b.slot_shape and a.comm_axes == b.comm_axes
    assert a.groups == b.groups
    for f in ("send_rows", "send_cols", "recv_rows", "recv_cols"):
        assert np.array_equal(getattr(a, f), getattr(b, f)), f


def test_subrange_is_shifted_alignment():
    """A contiguous sub-range compiles to EXACTLY the plan of the
    trimmed matrix at the offset-shifted alignments (the view identity:
    owner of global g at align a == zero-aligned owner of g + a)."""
    got = compile_slice_plan((MC, MR), (VC, STAR), (64, 64), (2, 2),
                             rows=(17, 49))
    want = compile_plan((MC, MR), (VC, STAR), (32, 64), (2, 2),
                        (17 % 2, 0), (17 % 4, 0))
    _same_plan(got, want)
    assert got.gshape == (32, 64)
    # column sub-range shifts the column alignment under the col stride
    got2 = compile_slice_plan((MC, MR), (STAR, MR), (32, 48), (2, 4),
                              cols=(5, 21))
    want2 = compile_plan((MC, MR), (STAR, MR), (32, 16), (2, 4),
                         (0, 5 % 4), (0, 5 % 4))
    _same_plan(got2, want2)


def test_full_range_defaults_equal_compile_plan():
    got = compile_slice_plan((MC, MR), (STAR, STAR), (24, 40), (2, 2))
    want = compile_plan((MC, MR), (STAR, STAR), (24, 40), (2, 2),
                        (0, 0), (0, 0))
    _same_plan(got, want)


def test_out_of_range_raises():
    with pytest.raises(ValueError):
        compile_slice_plan((MC, MR), (VC, STAR), (64, 64), (2, 2),
                           rows=(8, 80))
    with pytest.raises(ValueError):
        compile_slice_plan((MC, MR), (VC, STAR), (64, 64), (2, 2),
                           cols=(-1, 8))
    with pytest.raises(ValueError):
        compile_slice_plan((MC, MR), (VC, STAR), (64, 64), (2, 2),
                           rows=(40, 8))


def test_empty_slot_device_ships_sentinel_only():
    """m < p under [VC,STAR]: the tail devices own ZERO rows of the
    destination -- their recv tables are pure sentinel padding (sentinel
    == the local extent) and the plan still compiles/prices honestly."""
    plan = compile_plan((MC, MR), (VC, STAR), (3, 8), (2, 2))
    assert plan.kind == "a2a"
    R = plan.recv_rows.shape[-1]
    sent_r = plan.dst_local[0]
    empty = [d for d in range(4)
             if (plan.recv_rows[d] >= sent_r).all()]
    assert empty == [3]                    # VC owner of rows 0,1,2 = devs 0-2
    assert plan.wire_bytes(4) > 0          # padded slots still ship


def test_single_bin_degenerate_pack():
    """A full-bipartite traffic graph (every device needs every sender:
    the [STAR,STAR] broadcast) cannot FFD-decompose: one bin, no
    axis_index_groups, slot count == the full comm size."""
    plan = compile_plan((MC, MR), (STAR, STAR), (64, 16), (2, 4))
    assert plan.kind == "a2a"
    assert plan.groups == ()               # single-bin degenerate pack
    assert plan.nslots == 8


def test_ragged_trailing_trim():
    """Ragged extents trim the trailing all-sentinel tail: the slot of a
    (5, 3) slice over 4 VC ranks is ceil(5/4) x 3, not the padded
    storage extent."""
    plan = compile_plan((MC, MR), (VC, STAR), (5, 3), (2, 2))
    assert plan.slot_shape[0] <= 2 and plan.slot_shape[1] <= 3


@pytest.mark.parametrize("grid_shape,mode,collectives",
                         [((1, 1), "local", 0), ((2, 2), "rows", 3),
                          ((2, 4), "rows", 3), ((4, 1), "rows", 1),
                          ((1, 4), "cols", 1)])
def test_gemm_slice_plan_set(grid_shape, mode, collectives):
    """The plan-set helper: mode rule + collective count per grid class
    (1x1 zero plans; Nx1/1xN exactly one collective; 2-D grids three)."""
    got_mode, plans = gemm_slice_plans(2048, 64, 16, grid_shape)
    assert got_mode == mode
    assert sum(p.rounds for _, p in plans if p is not None) == collectives


def test_slice_row_mode_rule():
    assert slice_row_mode(2048, 16, (2, 2))      # tall: rows
    assert not slice_row_mode(16, 2048, (2, 2))  # wide: cols
    assert slice_row_mode(16, 2048, (4, 1))      # Nx1 forces rows
    assert not slice_row_mode(2048, 16, (1, 4))  # 1xN forces cols
    assert slice_row_mode(64, 64, (2, 2))        # square ties to rows
