"""Matrix-Market + Display/Spy IO (SURVEY.md §3.5 IO row completion)."""
import os
import numpy as np

import elemental_tpu as el


def test_mm_dense_roundtrip(grid24, tmp_path):
    rng = np.random.default_rng(0)
    F = rng.normal(size=(9, 5))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    p = str(tmp_path / "a.mtx")
    el.write_matrix_market(A, p, comment="test")
    B = el.read_matrix_market(p, grid=grid24)
    assert np.allclose(np.asarray(el.to_global(B)), F)


def test_mm_dense_complex_roundtrip(grid24, tmp_path):
    rng = np.random.default_rng(1)
    F = rng.normal(size=(6, 7)) + 1j * rng.normal(size=(6, 7))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    p = str(tmp_path / "c.mtx")
    el.write_matrix_market(A, p)
    B = el.read_matrix_market(p, grid=grid24)
    assert np.allclose(np.asarray(el.to_global(B)), F)


def test_mm_sparse_roundtrip(grid24, tmp_path):
    from elemental_tpu.sparse.core import dist_sparse_from_coo
    rng = np.random.default_rng(2)
    m, n, nnz = 20, 14, 60
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.normal(size=nnz)
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    ref = np.zeros((m, n))
    np.add.at(ref, (rows, cols), vals)
    p = str(tmp_path / "s.mtx")
    el.write_matrix_market(A, p)
    B = el.read_matrix_market(p, grid=grid24)          # sparse by default
    Bg = np.asarray(el.to_global(B.to_dense()))
    assert np.allclose(Bg, ref)
    Bd = el.read_matrix_market(p, grid=grid24, sparse=False)
    assert np.allclose(np.asarray(el.to_global(Bd)), ref)


def test_mm_symmetric_expansion(grid24, tmp_path):
    p = str(tmp_path / "sym.mtx")
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix coordinate real symmetric\n")
        f.write("3 3 4\n1 1 2.0\n2 1 -1.0\n3 2 -1.0\n3 3 2.0\n")
    B = el.read_matrix_market(p, grid=grid24, sparse=False)
    Bg = np.asarray(el.to_global(B))
    ref = np.array([[2.0, -1, 0], [-1, 0, -1], [0, -1, 2.0]])
    assert np.allclose(Bg, ref)


def test_mm_sparse_complex_roundtrip(grid24, tmp_path):
    """Complex coordinate write/read through the vectorized body paths."""
    from elemental_tpu.sparse.core import dist_sparse_from_coo
    rng = np.random.default_rng(7)
    m, n, nnz = 17, 11, 40
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.normal(size=nnz) + 1j * rng.normal(size=nnz)
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.complex128)
    ref = np.zeros((m, n), np.complex128)
    np.add.at(ref, (rows, cols), vals)
    p = str(tmp_path / "sc.mtx")
    el.write_matrix_market(A, p)
    B = el.read_matrix_market(p, grid=grid24, sparse=False)
    assert np.allclose(np.asarray(el.to_global(B)), ref)


def test_mm_pattern_field(grid24, tmp_path):
    p = str(tmp_path / "pat.mtx")
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix coordinate pattern general\n")
        f.write("3 4 3\n1 1\n2 3\n3 4\n")
    B = el.read_matrix_market(p, grid=grid24, sparse=False)
    ref = np.zeros((3, 4))
    ref[0, 0] = ref[1, 2] = ref[2, 3] = 1.0
    assert np.allclose(np.asarray(el.to_global(B)), ref)


def test_mm_dense_large_roundtrip(grid24, tmp_path):
    """A larger dense body exercising the bulk (vectorized) formatter with
    full 17-significant-digit fidelity."""
    rng = np.random.default_rng(8)
    F = rng.normal(size=(64, 48)) * 10.0 ** rng.integers(-12, 12, (64, 48))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    p = str(tmp_path / "big.mtx")
    el.write_matrix_market(A, p)
    B = el.read_matrix_market(p, grid=grid24)
    np.testing.assert_allclose(np.asarray(el.to_global(B)), F, rtol=0,
                               atol=0)       # %.17g is exact for float64


def test_display_and_spy(grid24, tmp_path):
    rng = np.random.default_rng(3)
    F = rng.normal(size=(12, 12)) * (rng.uniform(size=(12, 12)) < 0.2)
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    p1 = el.display(A, "disp", path=str(tmp_path / "d.png"))
    p2 = el.spy(A, title="spy", path=str(tmp_path / "s.png"))
    assert os.path.getsize(p1) > 1000
    assert os.path.getsize(p2) > 1000


def test_mm_symmetric_array_packed(grid24, tmp_path):
    """'array symmetric' files store only the packed lower triangle
    (column-major) -- the spec-conforming layout must unpack."""
    p = str(tmp_path / "syma.mtx")
    # lower triangle of [[2,-1,0],[-1,2,-1],[0,-1,2]] column-major:
    # col0: 2,-1,0; col1: 2,-1; col2: 2
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix array real symmetric\n")
        f.write("3 3\n2\n-1\n0\n2\n-1\n2\n")
    B = el.read_matrix_market(p, grid=grid24)
    ref = np.array([[2.0, -1, 0], [-1, 2, -1], [0, -1, 2]])
    assert np.allclose(np.asarray(el.to_global(B)), ref)


def test_mm_skew_symmetric_array_packed(grid24, tmp_path):
    """'array skew-symmetric' stores only the strictly-lower triangle."""
    p = str(tmp_path / "skew.mtx")
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix array real skew-symmetric\n")
        f.write("3 3\n2\n3\n4\n")
    B = el.read_matrix_market(p, grid=grid24)
    ref = np.array([[0.0, -2, -3], [2, 0, -4], [3, 4, 0]])
    assert np.allclose(np.asarray(el.to_global(B)), ref)
