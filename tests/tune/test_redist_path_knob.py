"""The redist_path knob in the tuning subsystem (ISSUE 12): registry
coverage, candidate enumeration rules, 'auto' resolution, and the
one-a2a-round-vs-k-gather-rounds cost-model term."""
import jax
import jax.numpy as jnp

import elemental_tpu as el
from elemental_tpu import tune
from elemental_tpu.tune import cost_model
from elemental_tpu.tune.knobs import (OPS, REDIST_PATHS, TuneContext,
                                      candidate_configs)


def _grid(r, c):
    return el.Grid(jax.devices()[: r * c], height=r)


def _ctx(op, dims, grid_shape):
    return TuneContext(op=op, dims=dims, dtype="float32",
                       grid_shape=grid_shape, backend="cpu")


def test_knob_registered_on_all_six_drivers():
    # ISSUE 13: qr/trsm/herk joined lu/cholesky/gemm -- every driver's
    # operand moves are plan-shaped now, so every space carries the knob
    for op in ("cholesky", "lu", "gemm", "qr", "trsm", "herk"):
        assert "redist_path" in OPS[op].knobs, op


def test_knob_values_sync_with_engine():
    """Every tunable value must be a legal engine route (the engine also
    accepts 'chain'/'auto' spellings the tuner never emits)."""
    from elemental_tpu.redist.engine import REDIST_PATHS as ENGINE_PATHS
    assert REDIST_PATHS == (None, "direct")
    assert set(REDIST_PATHS) <= set(ENGINE_PATHS)


def test_candidates_dead_on_1x1_full_on_2x2():
    ctx1 = _ctx("cholesky", (64, 64), (1, 1))
    assert {c.get("redist_path") for c in candidate_configs(ctx1)} == {None}
    ctx2 = _ctx("cholesky", (64, 64), (2, 2))
    assert {c.get("redist_path") for c in candidate_configs(ctx2)} \
        == set(REDIST_PATHS)


def test_pinned_value_freezes_the_dimension():
    ctx = _ctx("lu", (64, 64), (2, 2))
    cands = candidate_configs(ctx, {"redist_path": "direct"})
    assert {c["redist_path"] for c in cands} == {"direct"}
    # pinning None (the driver default) keeps the space un-doubled
    base = candidate_configs(ctx, {"redist_path": None})
    assert len(cands) == len(base)


def test_auto_resolves_to_a_legal_route():
    kn = tune.resolve_knobs(
        "cholesky", gshape=(64, 64), dtype=jnp.float32, grid=_grid(1, 1),
        knobs={"nb": 16, "lookahead": True, "crossover": 0,
               "comm_precision": None, "redist_path": "auto"})
    assert kn["redist_path"] is None          # 1x1: no wire to optimize
    kn2 = tune.resolve_knobs(
        "cholesky", gshape=(256, 256), dtype=jnp.float32, grid=_grid(2, 2),
        knobs={"nb": 64, "lookahead": True, "crossover": 0,
               "comm_precision": None, "redist_path": "auto"})
    assert kn2["redist_path"] in REDIST_PATHS


def test_gemm_cost_model_swaps_gather_sites_for_one_shot_plans():
    """For a 'direct' config the closed-form gemm cost replaces each
    chained operand move with its compiled plan's single collective --
    alg C's 8 per-panel all_gathers become 8 one-shot all_to_alls (one
    plan per operand panel; fewer ROUNDS shows up on the multi-hop
    chains of alg A/B and the traced factorizations)."""
    ctx = _ctx("gemm", (512, 512, 512), (2, 2))
    base = cost_model.score_config(
        "gemm", {"alg": "C", "nb": 128, "comm_precision": None,
                 "redist_path": None}, ctx=ctx, dtype=jnp.float32)
    direct = cost_model.score_config(
        "gemm", {"alg": "C", "nb": 128, "comm_precision": None,
                 "redist_path": "direct"}, ctx=ctx, dtype=jnp.float32)
    assert base.prim_counts == {"all_gather": 8}
    assert direct.prim_counts == {"all_to_all": 8}
    assert direct.rounds == base.rounds


def test_path_none_closed_form_unchanged_by_the_knob_plumbing():
    """The path-None score must stay byte-identical whether or not the
    config dict carries the new key (the cost-model pinning tests
    elsewhere compare against abstract traces)."""
    ctx = _ctx("gemm", (512, 512, 512), (2, 2))
    bare = cost_model.score_config(
        "gemm", {"alg": "C", "nb": 128, "comm_precision": None},
        ctx=ctx, dtype=jnp.float32)
    keyed = cost_model.score_config(
        "gemm", {"alg": "C", "nb": 128, "comm_precision": None,
                 "redist_path": None}, ctx=ctx, dtype=jnp.float32)
    assert bare.comm_bytes == keyed.comm_bytes
    assert bare.rounds == keyed.rounds
    assert bare.prim_counts == keyed.prim_counts


def test_traced_qr_trsm_herk_price_the_one_shot_schedule():
    """ISSUE 13: the three remaining drivers price 'direct' by re-tracing
    their REAL schedules with the knob threaded through.  herk's
    per-panel [VC,STAR]-hop + spread pair (2 rounds) collapses into ONE
    exchange, so its round count strictly drops; qr/trsm panel moves are
    already single-round, so their round counts hold while the prim mix
    swaps the fused gathers for one-shot plans."""
    g2 = _grid(2, 2)
    cases = {"qr": {"nb": 16, "panel": "classic", "comm_precision": None},
             "trsm": {"nb": 16, "comm_precision": None},
             "herk": {"nb": 16, "comm_precision": None}}
    scores = {}
    for op, cfg in cases.items():
        ctx = _ctx(op, (64, 64), (2, 2))
        base = cost_model.score_config(
            op, dict(cfg, redist_path=None), ctx=ctx, grid=g2,
            dtype=jnp.float32)
        direct = cost_model.score_config(
            op, dict(cfg, redist_path="direct"), ctx=ctx, grid=g2,
            dtype=jnp.float32)
        assert direct.rounds <= base.rounds, op
        assert direct.prim_counts != base.prim_counts, op
        scores[op] = (base, direct)
    base, direct = scores["herk"]
    assert direct.rounds < base.rounds
    assert base.prim_counts.get("all_gather", 0) > 0
    assert direct.prim_counts.get("all_gather", 0) == 0


def test_candidates_carry_the_knob_for_qr_trsm_herk():
    for op in ("qr", "trsm", "herk"):
        ctx1 = _ctx(op, (64, 64), (1, 1))
        assert {c.get("redist_path")
                for c in candidate_configs(ctx1)} == {None}, op
        ctx2 = _ctx(op, (64, 64), (2, 2))
        assert {c.get("redist_path")
                for c in candidate_configs(ctx2)} == set(REDIST_PATHS), op


def test_traced_lu_direct_prices_the_real_one_shot_schedule():
    """lu/cholesky price 'direct' by re-tracing the ACTUAL schedule with
    the knob threaded through -- the gather hops disappear from the
    prim mix in favor of one-shot all_to_alls."""
    g2 = _grid(2, 2)
    ctx = _ctx("lu", (64, 64), (2, 2))
    cfg = {"nb": 16, "lookahead": True, "crossover": 0, "panel": "classic",
           "comm_precision": None}
    base = cost_model.score_config(
        "lu", dict(cfg, redist_path=None), ctx=ctx, grid=g2,
        dtype=jnp.float32)
    direct = cost_model.score_config(
        "lu", dict(cfg, redist_path="direct"), ctx=ctx, grid=g2,
        dtype=jnp.float32)
    assert base.prim_counts.get("all_gather", 0) > 0
    assert direct.prim_counts.get("all_gather", 0) == 0
    assert direct.prim_counts.get("all_to_all", 0) \
        > base.prim_counts.get("all_to_all", 0)
