"""'auto' knob plumbing smoke tests: every tunable driver accepts 'auto'
on 1x1 and 2x2 grids, resolves from the analytic cost model when the
cache is empty (no device timing), and still computes the right answer.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import elemental_tpu as el
from elemental_tpu.tune import cache as tc


@pytest.fixture(params=[(1, 1), (2, 2)], ids=["grid1x1", "grid2x2"])
def auto_grid(request, tmp_path, monkeypatch):
    """1x1 + 2x2 grids with an EMPTY cache dir (cost-model-only path)."""
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    from elemental_tpu.tune.policy import clear_memo
    clear_memo()
    r, c = request.param
    yield el.Grid(jax.devices()[: r * c], height=r)
    clear_memo()


def _dist(grid, a):
    return el.from_global(jnp.asarray(a), el.MC, el.MR, grid=grid)


def _np(rng, *shape):
    return rng.normal(size=shape).astype(np.float32)


N = 24


def test_cholesky_auto(auto_grid):
    rng = np.random.default_rng(0)
    G = _np(rng, N, N)
    S = G @ G.T + N * np.eye(N, dtype=np.float32)
    L = el.cholesky(_dist(auto_grid, S), nb="auto", lookahead="auto",
                    crossover="auto")
    Lg = np.tril(np.asarray(el.to_global(L)))
    np.testing.assert_allclose(Lg @ Lg.T, S, rtol=0, atol=2e-3)


def test_lu_auto(auto_grid):
    rng = np.random.default_rng(1)
    A = _np(rng, N, N)
    LU, perm = el.lu(_dist(auto_grid, A), nb="auto", lookahead="auto",
                     crossover="auto")
    lu_ = np.asarray(el.to_global(LU))
    L = np.tril(lu_, -1) + np.eye(N, dtype=np.float32)
    U = np.triu(lu_)
    np.testing.assert_allclose(L @ U, A[np.asarray(perm)], rtol=0, atol=2e-4)


def test_qr_auto(auto_grid):
    rng = np.random.default_rng(2)
    A = _np(rng, N, 16)
    Ap, tau = el.qr(_dist(auto_grid, A), nb="auto")
    # the resolved block size is recorded for apply_q's default
    assert isinstance(getattr(Ap, "_qr_nb", None), int)
    R = np.triu(np.asarray(el.to_global(Ap)))[:16, :]
    np.testing.assert_allclose(np.abs(R), np.abs(np.linalg.qr(A, mode="r")),
                               rtol=0, atol=2e-4)
    # apply_q with the recorded default: Q (Q^H B) == B round trip
    B = _np(rng, N, 4)
    Bd = _dist(auto_grid, B)
    out = el.apply_q(Ap, tau, el.apply_q(Ap, tau, Bd, orient="C"))
    np.testing.assert_allclose(np.asarray(el.to_global(out)), B,
                               rtol=0, atol=2e-4)


def test_gemm_auto(auto_grid):
    rng = np.random.default_rng(3)
    A, B = _np(rng, N, 32), _np(rng, 32, 20)
    C = el.gemm(_dist(auto_grid, A), _dist(auto_grid, B), alg="auto",
                nb="auto")
    np.testing.assert_allclose(np.asarray(el.to_global(C)), A @ B,
                               rtol=0, atol=2e-4)


def test_trsm_auto(auto_grid):
    rng = np.random.default_rng(4)
    A = np.tril(_np(rng, N, N)) + N * np.eye(N, dtype=np.float32)
    B = _np(rng, N, 8)
    X = el.trsm("L", "L", "N", _dist(auto_grid, A), _dist(auto_grid, B),
                nb="auto")
    np.testing.assert_allclose(A @ np.asarray(el.to_global(X)), B,
                               rtol=0, atol=2e-4)


def test_herk_auto(auto_grid):
    rng = np.random.default_rng(5)
    A = _np(rng, N, 32)
    C = el.herk("L", _dist(auto_grid, A), nb="auto")
    got = np.asarray(el.to_global(C))
    np.testing.assert_allclose(np.tril(got), np.tril(A @ A.T),
                               rtol=0, atol=2e-3)


def test_auto_resolution_is_cost_model_cold(auto_grid):
    """Empty cache on CPU: 'auto' must resolve WITHOUT device timing,
    purely from the analytic model (the acceptance criterion)."""
    from elemental_tpu import tune
    res = tune.resolve("lu", gshape=(N, N), dtype=jnp.float32,
                       grid=auto_grid,
                       requested={"nb": "auto", "lookahead": "auto",
                                  "crossover": "auto"})
    assert res.source == "cost_model"
    assert isinstance(res.config["nb"], int) and res.config["nb"] >= 1
    assert isinstance(res.config["lookahead"], bool)
    assert isinstance(res.config["crossover"], int)
    assert res.scores                       # breakdowns kept for explain


def test_unresolved_auto_is_a_driver_bug():
    """blocksize_policy refuses a raw 'auto' (drivers must resolve first)."""
    from elemental_tpu.tune import blocksize_policy
    with pytest.raises(TypeError):
        blocksize_policy("auto", 2, 64)
