"""Measured redist constants (ISSUE 13): the ``redist_constants/v1``
cache round-trip, its defensive load paths, the redist_bench
least-squares fit, and the ``--record`` CLI wiring.

The contract: ``perf.redist_bench --record`` fits ``seconds =
alpha * rounds + bytes / bw`` over measured rows and persists one
per-(grid, backend) doc that :func:`engine._machine_terms` consults
BEFORE the static ring model -- so 'auto' arbitration runs on the
machine actually measured, not on TPU-ish defaults.  The arbitration
flip itself is pinned in tests/core/test_redist_direct.py.
"""
import json
import os

import jax
import pytest

from elemental_tpu.tune import cache as tcache

GRID = (2, 2)


@pytest.fixture()
def cache_env(tmp_path, monkeypatch):
    monkeypatch.setenv(tcache.ENV_DIR, str(tmp_path))
    tcache.clear_redist_constants_memo()
    yield str(tmp_path)
    tcache.clear_redist_constants_memo()


def test_save_load_round_trip(cache_env):
    backend = jax.default_backend()
    path = tcache.save_redist_constants(GRID, backend, alpha_s=3e-6,
                                        bw_bytes_per_s=1.25e10, nsamples=12)
    assert os.path.dirname(path) == cache_env
    doc = tcache.load_redist_constants(GRID, backend)
    assert doc["schema"] == tcache.REDIST_SCHEMA
    assert doc["alpha_s"] == pytest.approx(3e-6)
    assert doc["bw_bytes_per_s"] == pytest.approx(1.25e10)
    assert doc["nsamples"] == 12
    # a rewrite invalidates the memo (save pops the entry)
    tcache.save_redist_constants(GRID, backend, alpha_s=5e-6,
                                 bw_bytes_per_s=1e10)
    assert tcache.load_redist_constants(GRID, backend)["alpha_s"] \
        == pytest.approx(5e-6)


def test_load_is_defensive(cache_env):
    backend = jax.default_backend()
    # absent file -> None (memoized None included)
    assert tcache.load_redist_constants(GRID, backend) is None
    # wrong backend / wrong grid -> None
    tcache.save_redist_constants(GRID, backend, 1e-6, 1e10)
    assert tcache.load_redist_constants((4, 2), backend) is None
    assert tcache.load_redist_constants(GRID, backend + "_other") is None
    # corrupt JSON -> None, never raises
    name = tcache.redist_constants_filename(GRID, backend)
    with open(os.path.join(cache_env, name), "w") as fh:
        fh.write("{not json")
    tcache.clear_redist_constants_memo()
    assert tcache.load_redist_constants(GRID, backend) is None
    # non-finite / non-positive constants -> None
    doc = {"schema": tcache.REDIST_SCHEMA, "grid": list(GRID),
           "backend": backend, "alpha_s": 1e-6, "bw_bytes_per_s": 0.0}
    with open(os.path.join(cache_env, name), "w") as fh:
        json.dump(doc, fh)
    tcache.clear_redist_constants_memo()
    assert tcache.load_redist_constants(GRID, backend) is None


def test_scan_skips_constants_files(cache_env):
    """scan() enumerates measured OP entries only; the constants doc has
    its own schema and must not surface as a tuning entry."""
    tcache.save_redist_constants(GRID, jax.default_backend(), 1e-6, 1e10)
    docs, rejects = tcache.scan()
    assert docs == [] and rejects == []


def test_fit_constants_recovers_planted_terms():
    from perf.redist_bench import fit_constants
    alpha, bw = 5e-6, 2e10
    rows = [{"rounds": r, "model_bytes": b,
             "seconds": alpha * r + b / bw}
            for r, b in ((1, 1 << 20), (3, 1 << 18), (2, 1 << 22),
                         (4, 1 << 16), (1, 1 << 24))]
    fit = fit_constants(rows)
    assert fit is not None
    a_fit, bw_fit, nsamples = fit
    assert a_fit == pytest.approx(alpha, rel=1e-6)
    assert bw_fit == pytest.approx(bw, rel=1e-6)
    assert nsamples == len(rows)


def test_fit_constants_degenerate_returns_none():
    from perf.redist_bench import fit_constants
    # all-zero rounds (a 1x1 grid's rows) -> nothing to fit
    assert fit_constants([{"rounds": 0, "model_bytes": 0, "seconds": 0.0}
                          for _ in range(4)]) is None
    # a single usable sample is rank-deficient
    assert fit_constants([{"rounds": 1, "model_bytes": 100,
                           "seconds": 1e-4}]) is None


def test_record_constants_persists_and_reloads(cache_env):
    from perf.redist_bench import record_constants
    alpha, bw = 2e-6, 4e10
    rows = [{"rounds": r, "model_bytes": b,
             "seconds": alpha * r + b / bw}
            for r, b in ((1, 1 << 20), (3, 1 << 19), (2, 1 << 21))]
    doc = record_constants(GRID, rows)
    assert doc is not None and os.path.exists(doc["_path"])
    reloaded = tcache.load_redist_constants(GRID, jax.default_backend())
    assert reloaded["alpha_s"] == pytest.approx(alpha, rel=1e-5)
    assert reloaded["bw_bytes_per_s"] == pytest.approx(bw, rel=1e-5)


@pytest.mark.slow
def test_cli_record_writes_the_cache(cache_env):
    """End to end: ``python -m perf.redist_bench --record`` (tiny n) lands
    a loadable redist_constants/v1 doc for the measured grid."""
    from perf.redist_bench import main
    rc = main(["--grid", "2x2", "--n", "32", "--reps", "1", "--record"])
    assert rc == 0
    doc = tcache.load_redist_constants(GRID, jax.default_backend())
    assert doc is not None and doc["nsamples"] >= 2
