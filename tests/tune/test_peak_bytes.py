"""Memory-aware tuning (ISSUE 18): the cost model's ``peak_bytes`` term.

Every scored candidate now carries the statically-derived per-device peak
(traced ops: the liveness walk + replication census at the probe
geometry, byte-scaled to the request; gemm: closed form).  Candidates
whose peak exceeds the machine's HBM are PRUNED -- ranked behind every
fitting candidate regardless of modeled time -- because an OOM is not a
slow configuration.  All-pruned still resolves (best effort beats a
crash).
"""
import dataclasses

import jax
import jax.numpy as jnp

from elemental_tpu import Grid
from elemental_tpu.tune import TuneContext, policy
from elemental_tpu.tune import cost_model as cm


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


def _ctx(op, dims, grid):
    return TuneContext(op, dims, "float32",
                       (grid.height, grid.width), "cpu")


def _tiny_machine(hbm=1024.0):
    return dataclasses.replace(cm.machine_for("cpu"), hbm_bytes=hbm)


def test_traced_breakdown_carries_peak_bytes():
    g = _grid(2, 2)
    b = cm.score_config("cholesky", {"nb": 16, "lookahead": False,
                                     "crossover": 0},
                        ctx=_ctx("cholesky", (64, 64), g),
                        grid=g, dtype=jnp.float32)
    assert b.peak_bytes > 0
    assert not b.pruned, "a 64x64 f32 factor fits 64 GiB of HBM"
    doc = b.to_doc()
    assert doc["peak_bytes"] == b.peak_bytes
    assert doc["pruned"] is False


def test_gemm_closed_form_peak_is_sane():
    """gemm's peak = per-device operand residency + the largest staged
    communication buffer: at least the A+B+C shards, well under the
    whole-matrix total."""
    g = _grid(2, 2)
    m = k = n = 256
    b = cm.score_config("gemm", {"alg": "A", "nb": 64,
                                 "comm_precision": None,
                                 "redist_path": "gather"},
                        ctx=_ctx("gemm", (m, k, n), g), dtype=jnp.float32)
    shards = (m * k + k * n + m * n) * 4 / 4
    assert b.peak_bytes >= shards
    assert b.peak_bytes < 3 * (m * k + k * n + m * n) * 4
    assert not b.pruned


def test_tiny_hbm_prunes_candidates():
    g = _grid(2, 2)
    tiny = _tiny_machine()
    for op, dims, config in [
            ("cholesky", (64, 64), {"nb": 16, "lookahead": False,
                                    "crossover": 0}),
            ("gemm", (256, 256, 256), {"alg": "A", "nb": 64,
                                       "comm_precision": None,
                                       "redist_path": "gather"})]:
        b = cm.score_config(op, config, ctx=_ctx(op, dims, g),
                            grid=g, dtype=jnp.float32, machine=tiny)
        assert b.pruned, (op, b.peak_bytes)
        assert b.to_doc()["pruned"] is True


def test_explain_ranks_pruned_candidates_last():
    g = _grid(2, 2)
    _, scored = policy.explain("cholesky", gshape=(64, 64),
                               dtype=jnp.float32, grid=g,
                               machine=_tiny_machine(hbm=2.0e4))
    flags = [b.pruned for b in scored]
    if any(flags) and not all(flags):
        assert flags == sorted(flags), \
            "a pruned candidate outranked a fitting one"


def test_all_pruned_still_resolves():
    """With 1 KiB of 'HBM' every candidate is over budget; resolution
    must still pick one (the fastest) instead of erroring."""
    g = _grid(2, 2)
    res = policy.resolve("cholesky", gshape=(64, 64), dtype=jnp.float32,
                         grid=g,
                         requested={"nb": "auto", "lookahead": "auto",
                                    "crossover": "auto"},
                         machine=_tiny_machine())
    assert res.config["nb"] is not None
    choice, scored = policy.explain("cholesky", gshape=(64, 64),
                                    dtype=jnp.float32, grid=g,
                                    machine=_tiny_machine())
    assert all(b.pruned for b in scored)
    assert choice is not None


def test_pruning_overrides_modeled_time():
    """Between a fast-but-OOM candidate and a slow-but-fitting one the
    tuner must take the fitting one: sort key is (pruned, total_s)."""
    g = _grid(2, 2)
    ctx = _ctx("cholesky", (64, 64), g)
    fast = cm.score_config("cholesky", {"nb": 32, "lookahead": True,
                                        "crossover": 0},
                           ctx=ctx, grid=g, dtype=jnp.float32)
    slow = cm.score_config("cholesky", {"nb": 8, "lookahead": False,
                                        "crossover": 0},
                           ctx=ctx, grid=g, dtype=jnp.float32)
    a, b = sorted([fast, slow], key=lambda x: x.total_s)
    forced = dataclasses.replace(a, pruned=True)
    order = sorted([forced, b], key=lambda x: (x.pruned, x.total_s))
    assert order[0] is b, "OOM risk must dominate modeled speed"
