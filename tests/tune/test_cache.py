"""Persistent tuning-cache behavior: round-trip, version/key rejection,
atomic writes, env-var dir override, clear."""
import json
import os

import pytest

from elemental_tpu.tune import cache as tc


@pytest.fixture
def cache_env(tmp_path, monkeypatch):
    """Point the cache at a fresh temp dir and drop the resolver memo."""
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    from elemental_tpu.tune.policy import clear_memo
    clear_memo()
    yield tmp_path
    clear_memo()


def _key(op="cholesky", dims=(3000, 3000), dtype="float32",
         grid=(2, 2), backend="cpu"):
    return tc.make_key(op, dims, dtype, grid, backend)


def test_round_trip(cache_env):
    key = _key()
    cfg = {"nb": 1024, "lookahead": True, "crossover": 4096}
    path = tc.save(key, cfg, source="measured",
                   metric={"seconds": 0.5, "tflops": 1.25})
    assert os.path.dirname(path) == str(cache_env)
    doc = tc.load(key)
    assert doc is not None
    assert doc["config"] == cfg
    assert doc["source"] == "measured"
    assert doc["schema"] == tc.SCHEMA
    assert doc["metric"]["tflops"] == 1.25
    # no torn/leftover temp files from the atomic write
    leftovers = [f for f in os.listdir(cache_env) if f.endswith(".tmp")]
    assert leftovers == []


def test_shape_bucketing_shares_entries(cache_env):
    """Dims bucket to the next power of two: 3000^2 and 4096^2 share a key."""
    tc.save(_key(dims=(3000, 3000)), {"nb": 512})
    assert tc.load(_key(dims=(4096, 4096)))["config"] == {"nb": 512}
    assert tc.load(_key(dims=(4097, 4097))) is None      # next bucket
    assert tc.shape_bucket((1, 2, 3, 64, 65)) == (1, 2, 4, 64, 128)


def test_version_mismatch_rejected(cache_env):
    key = _key()
    tc.save(key, {"nb": 256})
    path = key.path()
    with open(path) as f:
        doc = json.load(f)
    doc["schema"] = "tuning_cache/v0"
    with open(path, "w") as f:
        json.dump(doc, f)
    assert tc.load(key) is None            # stale schema never steers v1


def test_key_field_mismatch_rejected(cache_env):
    """A file renamed/copied onto another key's path is rejected."""
    a, b = _key(op="cholesky"), _key(op="lu")
    tc.save(a, {"nb": 256})
    os.replace(a.path(), b.path())
    assert tc.load(b) is None
    assert tc.load(a) is None              # and the original is gone


def test_corrupt_file_rejected(cache_env):
    key = _key()
    os.makedirs(tc.cache_dir(), exist_ok=True)
    with open(key.path(), "w") as f:
        f.write("{not json")
    assert tc.load(key) is None


def test_clear_by_op(cache_env):
    tc.save(_key(op="cholesky"), {"nb": 256})
    tc.save(_key(op="lu"), {"nb": 512})
    assert len(tc.entries()) == 2
    assert tc.clear("cholesky") == 1
    ops = [d["op"] for d in tc.entries()]
    assert ops == ["lu"]
    assert tc.clear() == 1
    assert tc.entries() == []


def test_resolver_prefers_cache_and_explicit_wins(cache_env):
    """resolve(): empty cache -> cost model; measured entry -> cache; an
    explicit knob is never overridden by either."""
    import jax
    import jax.numpy as jnp
    from elemental_tpu import Grid
    from elemental_tpu import tune

    grid = Grid(jax.devices()[:4], height=2)
    req = {"nb": "auto", "lookahead": "auto", "crossover": "auto"}
    r0 = tune.resolve("cholesky", gshape=(64, 64), dtype=jnp.float32,
                      grid=grid, requested=req)
    assert r0.source == "cost_model"
    assert isinstance(r0.config["nb"], int)

    key = tc.make_key("cholesky", (64, 64), "float32", (2, 2), "cpu")
    tc.save(key, {"nb": 32, "lookahead": False, "crossover": 0})
    tune.clear_memo()
    r1 = tune.resolve("cholesky", gshape=(64, 64), dtype=jnp.float32,
                      grid=grid, requested=req)
    assert r1.source == "cache"
    assert r1.config == {"nb": 32, "lookahead": False, "crossover": 0}

    # explicit always wins: nb pinned, only the 'auto' knobs resolve
    kn = tune.resolve_knobs("cholesky", gshape=(64, 64), dtype=jnp.float32,
                            grid=grid,
                            knobs={"nb": 16, "lookahead": "auto",
                                   "crossover": "auto"})
    assert kn["nb"] == 16
    assert kn["lookahead"] is False
    assert kn["crossover"] == 0


# ---------------------------------------------------------------------
# unwritable-directory degradation (ISSUE 7): warn-once + in-memory
# fallback instead of raising mid-solve
# ---------------------------------------------------------------------

@pytest.fixture
def unwritable_cache(tmp_path, monkeypatch):
    """Point the cache at a path UNDER A FILE: makedirs fails with
    NotADirectoryError on any uid (read-only-dir chmod tricks do not
    stop root, which CI may run as)."""
    blocker = tmp_path / "blocker.txt"
    blocker.write_text("not a directory\n")
    bad = str(blocker / "cache")
    monkeypatch.setenv(tc.ENV_DIR, bad)
    from elemental_tpu.tune.policy import clear_memo
    clear_memo()
    tc._MEM_FALLBACK.clear()
    tc._WARNED_DIRS.discard(bad)
    yield bad
    tc._MEM_FALLBACK.clear()
    tc._WARNED_DIRS.discard(bad)
    clear_memo()


def test_unwritable_dir_save_never_raises(unwritable_cache):
    import warnings
    from elemental_tpu.obs import metrics_scope
    key = _key()
    cfg = {"nb": 128, "lookahead": True, "crossover": 0}
    with metrics_scope() as reg:
        with pytest.warns(RuntimeWarning, match="not writable"):
            tc.save(key, cfg)
        # warn-once: a second save to the same dir stays silent
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            tc.save(_key(op="lu"), {"nb": 64})
        assert not [w for w in caught
                    if issubclass(w.category, RuntimeWarning)]
        # loads are served from the in-process fallback
        doc = tc.load(key)
        assert doc is not None and doc["config"] == cfg
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="write_fallback") == 1
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="mem_hit") == 1
    # clear() drops fallback entries too
    assert tc.clear("cholesky") == 0
    assert tc.load(key) is None


def test_unwritable_dir_auto_resolution_survives(unwritable_cache):
    """The mid-solve path: 'auto' knob resolution (which may write a
    measured winner) must not raise on the broken cache dir."""
    import jax
    import jax.numpy as jnp
    from elemental_tpu import Grid, tune
    grid = Grid(jax.devices()[:4], height=2)
    r = tune.resolve("cholesky", gshape=(32, 32), dtype=jnp.float32,
                     grid=grid,
                     requested={"nb": "auto", "lookahead": "auto",
                                "crossover": "auto"})
    assert r.source == "cost_model"
    key = tc.make_key("cholesky", (32, 32), "float32", (2, 2), "cpu")
    with pytest.warns(RuntimeWarning, match="not writable"):
        tc.save(key, {"nb": 16, "lookahead": False, "crossover": 0})
    tune.clear_memo()
    r2 = tune.resolve("cholesky", gshape=(32, 32), dtype=jnp.float32,
                      grid=grid,
                      requested={"nb": "auto", "lookahead": "auto",
                                 "crossover": "auto"})
    assert r2.source == "cache"            # served from the memory fallback
    assert r2.config["nb"] == 16
