"""ISSUE 16: 'slice' in the gemm alg space -- cost-model ranking pins.

``alg='auto'`` must pick 'slice' exactly where its three one-shot plans
win (tall-skinny / non-square-grid geometry) and keep every existing
winner elsewhere: gspmd on square and long-k grids, the pinned dot
early-out on 1x1 (candidate-order tie-break, byte-identical)."""
import math

import jax
import jax.numpy as jnp

import elemental_tpu as el
from elemental_tpu import tune
from elemental_tpu.tune import TuneContext
from elemental_tpu.tune.knobs import (DOT_ELEMENT_CAP, GEMM_ALGS,
                                      _gemm_space)


def _grid(r, c):
    return el.Grid(jax.devices()[: r * c], height=r)


def _pick(gshape, grid, **extra):
    kn = tune.resolve_knobs("gemm", gshape=gshape, dtype=jnp.float32,
                            grid=grid,
                            knobs={"alg": "auto", "nb": None,
                                   "comm_precision": None,
                                   "redist_path": None, **extra})
    return kn["alg"]


def test_slice_registered_last():
    """'slice' appends at the END of GEMM_ALGS: every pre-existing exact
    tie keeps its historical winner, and 'dot' still leads the 1x1
    zero-comm tie-break."""
    assert GEMM_ALGS == ("dot", "C", "A", "B", "gspmd", "slice")


def test_auto_picks_slice_on_tall_skinny_2x4():
    assert _pick((8192, 512, 256), _grid(2, 4)) == "slice"


def test_auto_picks_slice_on_tall_skinny_2x2():
    assert _pick((8192, 512, 256), _grid(2, 2)) == "slice"


def test_auto_picks_slice_on_bench_headline_class():
    """The bench.py gemm_tall_skinny headline geometry resolves 'slice'
    (provenance recorded in the bench JSON)."""
    assert _pick((65536, 512, 512), _grid(2, 4)) == "slice"


def test_auto_keeps_dot_on_1x1():
    assert _pick((256, 256, 256), _grid(1, 1)) == "dot"
    assert _pick((8192, 512, 256), _grid(1, 1)) == "dot"


def test_auto_keeps_existing_winners_elsewhere():
    """Square and long-k geometry keep their pre-slice winners at full
    wire precision (slice ties gspmd byte-for-byte on squares; the
    candidate order breaks the tie the historical way)."""
    assert _pick((256, 256, 256), _grid(2, 2)) == "gspmd"
    assert _pick((4096, 4096, 4096), _grid(2, 2)) == "gspmd"
    assert _pick((32, 8192, 32), _grid(2, 2)) in ("dot", "gspmd")


def test_slice_priced_identically_across_redist_path():
    """The slice gathers ARE one-shot plans: the redist_path crossing
    must not change its score (deterministic resolution)."""
    from elemental_tpu.tune import cost_model as cm
    ctx = TuneContext("gemm", (8192, 512, 256), "float32", (2, 4), "cpu")
    scores = [cm.score_config("gemm", {"alg": "slice", "nb": None,
                                       "redist_path": rp},
                              ctx=ctx, grid=None, dtype=jnp.float32)
              for rp in (None, "direct")]
    assert scores[0].total_s == scores[1].total_s
    assert scores[0].comm_bytes == scores[1].comm_bytes


def test_slice_nb_collapsed():
    """nb is dead for the one-shot slice schedule: the space holds ONE
    slice candidate per (cp, rp) crossing, not one per nb rung."""
    ctx = TuneContext("gemm", (1024, 256, 128), "float32", (2, 2), "cpu")
    space = _gemm_space(ctx, {})
    slice_nbs = {c.get("nb") for c in space if c["alg"] == "slice"}
    assert len(slice_nbs) == 1
    c_nbs = {c.get("nb") for c in space if c["alg"] == "C"}
    assert len(c_nbs) > 1                  # the panel algs DO sweep nb


def test_slice_replicated_operand_memory_guard():
    """The mode rule replicates the small operand [STAR,STAR]; when even
    that exceeds the replication cap the candidate is skipped (same
    guard class as dot's replicated-C cap) -- unless explicitly pinned."""
    k = n = 1 << 12                        # k*n = 16M elems > cap
    m = 1 << 20
    assert k * n > DOT_ELEMENT_CAP
    ctx = TuneContext("gemm", (m, k, n), "float32", (2, 4), "cpu")
    assert not [c for c in _gemm_space(ctx, {}) if c["alg"] == "slice"]
    pinned = [c for c in _gemm_space(ctx, {"alg": "slice"})
              if c["alg"] == "slice"]
    assert pinned                          # explicit pin bypasses the guard
    # and within the cap the candidate exists
    ctx_ok = TuneContext("gemm", (m, 512, 512), "float32", (2, 4), "cpu")
    assert [c for c in _gemm_space(ctx_ok, {}) if c["alg"] == "slice"]


def test_slice_zero_comm_on_1x1_candidates():
    """Every slice candidate on a 1x1 grid scores zero rounds and zero
    comm bytes (the finite-positive invariant the shared tune test pins
    across the whole space)."""
    from elemental_tpu.tune import cost_model as cm
    ctx = TuneContext("gemm", (2048, 64, 16), "float32", (1, 1), "cpu")
    b = cm.score_config("gemm", {"alg": "slice", "nb": None}, ctx=ctx,
                        grid=None, dtype=jnp.float32)
    assert b.rounds == 0 and b.comm_bytes == 0
    assert math.isfinite(b.total_s) and b.compute_s > 0
