"""Cost-model sanity: golden comm-plan agreement + schedule ranking.

The acceptance pins (ISSUE 4): with an empty cache the 'auto' knobs
resolve purely from the analytic cost model, and on 2x2 grids the model
ranks the lookahead+crossover schedules at or above classic -- CONSISTENT
with the golden comm plans' all_gather counts (the cost model's traced
collective counts at the golden geometry must equal the snapshots').
"""
import json
import math

import jax
import jax.numpy as jnp
import pytest

from elemental_tpu import Grid
from elemental_tpu.tune import TuneContext
from elemental_tpu.tune import cost_model as cm
from perf.comm_audit import golden_path

N, NB, XO = 64, 16, 32            # the golden comm-plan geometry


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


def _score(op, la, xo, grid, nb=NB, n=N):
    ctx = TuneContext(op, (n, n), "float32", (grid.height, grid.width),
                      "cpu")
    return cm.score_config(op, {"nb": nb, "lookahead": la, "crossover": xo},
                           ctx=ctx, grid=grid, dtype=jnp.float32)


#: (op, schedule knobs) -> the golden snapshot each must agree with
_GOLDEN_VARIANTS = [
    ("cholesky", False, 0, "cholesky_classic"),
    ("cholesky", True, 0, "cholesky_lookahead"),
    ("cholesky", True, XO, "cholesky_crossover"),
    ("lu", False, 0, "lu_classic"),
    ("lu", True, 0, "lu_lookahead"),
    ("lu", True, XO, "lu_crossover"),
]


@pytest.mark.parametrize("op,la,xo,golden", _GOLDEN_VARIANTS,
                         ids=[g for *_, g in _GOLDEN_VARIANTS])
@pytest.mark.parametrize("grid_shape", [(1, 1), (2, 2)],
                         ids=["1x1", "2x2"])
def test_traced_counts_agree_with_golden(op, la, xo, golden, grid_shape):
    """The cost model's comm term comes from the same abstract traces the
    golden snapshots pin: per-collective counts must match exactly."""
    b = _score(op, la, xo, _grid(*grid_shape))
    with open(golden_path(golden, grid_shape)) as f:
        doc = json.load(f)
    expect = {prim: t["count"] for prim, t in doc["totals"].items()}
    assert b.prim_counts == expect, (b.prim_counts, expect)


@pytest.mark.parametrize("op", ["cholesky", "lu"])
def test_lookahead_crossover_ranks_at_or_above_classic_2x2(op):
    """THE acceptance pin: on a 2x2 grid the pipelined tail-crossover
    schedule scores <= classic at the golden geometry, for the same
    reason its golden plan has strictly fewer all_gathers."""
    g = _grid(2, 2)
    classic = _score(op, False, 0, g)
    xover = _score(op, True, XO, g)
    assert xover.prim_counts["all_gather"] < classic.prim_counts["all_gather"]
    assert xover.total_s <= classic.total_s, (
        xover.to_doc(), classic.to_doc())
    # and the comm terms alone agree with the ranking (flop term is equal)
    assert (xover.latency_s + xover.bandwidth_s
            <= classic.latency_s + classic.bandwidth_s)


@pytest.mark.parametrize("op", ["cholesky", "lu", "qr", "trsm", "herk",
                                "gemm"])
@pytest.mark.parametrize("grid_shape", [(1, 1), (2, 2)],
                         ids=["1x1", "2x2"])
def test_all_candidates_finite_positive(op, grid_shape):
    from elemental_tpu import tune
    g = _grid(*grid_shape)
    dims = (256, 256, 256) if op == "gemm" else (256, 256)
    _, scored = tune.explain(op, gshape=dims, dtype=jnp.float32, grid=g)
    assert scored, "no candidates"
    for b in scored:
        assert math.isfinite(b.total_s) and b.total_s > 0, b.to_doc()
        assert b.compute_s > 0
        assert b.latency_s >= 0 and b.bandwidth_s >= 0
    if grid_shape == (1, 1):
        # degenerate grid: no collectives at all
        assert all(b.rounds == 0 and b.comm_bytes == 0 for b in scored)


def test_large_problem_extrapolates_without_tracing_full_size():
    """n=32768 must score via the scaled trace geometry (bounded step
    count), with latency extrapolated to the real step count."""
    g = _grid(2, 2)
    b = _score("cholesky", True, 0, g, nb=2048, n=32768)
    assert max(b.detail["trace_dims"]) <= 128
    assert b.detail["lat_scale"] > 1
    # 16 real steps vs <= 6 traced: rounds extrapolate beyond the trace
    assert b.rounds > sum(b.prim_counts.values())


def test_gemm_closed_form_matches_traced_plan_shape():
    """The gemm closed form is calibrated against the abstract traces:
    at the golden geometry its all_gather ROUND COUNT for the stationary-C
    schedule matches the traced gemm_c plan (2 gathers per k-panel)."""
    from elemental_tpu import analysis as an
    g = _grid(2, 2)
    ctx = TuneContext("gemm", (N, N, N), "float32", (2, 2), "cpu")
    b = cm.score_config("gemm", {"alg": "C", "nb": NB}, ctx=ctx,
                        grid=g, dtype=jnp.float32)
    plan, _, _ = an.trace_driver("gemm_c", g, n=N, nb=NB)
    assert b.prim_counts.get("all_gather") == plan.count("all_gather")
    # and the ring-model byte estimate agrees to first order (same model)
    traced = sum(t["bytes"] for t in plan.totals().values())
    assert 0.5 <= b.comm_bytes / traced <= 2.0, (b.comm_bytes, traced)


def test_gemm_regime_selection():
    """The small-C / long-k regime on p > 1 must avoid the stationary
    panel sweeps (the SUMMA_NNDot rationale; the ring model ranks the
    one-shot 'gspmd' relayout of B cheapest, with 'dot' next); on 1x1
    grids dot leads by the zero-comm tie-break (the pinned
    one-local-matmul early-out)."""
    from elemental_tpu import tune
    g2 = _grid(2, 2)
    kn = tune.resolve_knobs("gemm", gshape=(32, 8192, 32),
                            dtype=jnp.float32, grid=g2,
                            knobs={"alg": "auto", "nb": None})
    assert kn["alg"] in ("dot", "gspmd")
    assert kn["nb"] is None                 # pinned default passes through
    g1 = _grid(1, 1)
    kn1 = tune.resolve_knobs("gemm", gshape=(256, 256, 256),
                             dtype=jnp.float32, grid=g1,
                             knobs={"alg": "auto", "nb": None})
    assert kn1["alg"] == "dot"


def test_crossover_default_matches_driver_constants():
    """The knob registry's literal DEFAULT_CROSSOVER must track the
    drivers' _CROSSOVER (they are deliberately not imported)."""
    from elemental_tpu.tune.knobs import DEFAULT_CROSSOVER
    from elemental_tpu.lapack.cholesky import _CROSSOVER as CHOL
    from elemental_tpu.lapack.lu import _CROSSOVER as LU
    assert DEFAULT_CROSSOVER == CHOL == LU
