"""The ``panel`` knob (ISSUE 6): registry space, cost-model pivot-latency
term, and the pinned 'auto' ranking -- calu/tsqr on multi-row grids,
classic on single-row ones (where the tree panels degenerate).
"""
import jax
import jax.numpy as jnp
import pytest

import elemental_tpu as el
from elemental_tpu import tune
from elemental_tpu.tune import TuneContext
from elemental_tpu.tune import cost_model as cm
from elemental_tpu.tune.knobs import (LU_PANELS, QR_PANELS, OPS,
                                      candidate_configs)


@pytest.fixture
def empty_cache(tmp_path, monkeypatch):
    from elemental_tpu.tune import cache as tc
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    from elemental_tpu.tune.policy import clear_memo
    clear_memo()
    yield
    clear_memo()


def _grid(r, c):
    return el.Grid(jax.devices()[: r * c], height=r)


def _ctx(op, grid_shape, n=64):
    return TuneContext(op, (n, n), "float32", grid_shape, "cpu")


# ---------------------------------------------------------------------
# registry space
# ---------------------------------------------------------------------

def test_lu_space_has_panel_dimension():
    assert "panel" in OPS["lu"].knobs
    assert "panel" in OPS["qr"].knobs
    cfgs = candidate_configs(_ctx("lu", (2, 2)))
    panels = {c["panel"] for c in cfgs}
    assert panels == set(LU_PANELS)
    qcfgs = candidate_configs(_ctx("qr", (2, 2)))
    assert {c["panel"] for c in qcfgs} == set(QR_PANELS)


def test_single_row_grids_enumerate_classic_only():
    """On r == 1 the tree panels degenerate to classic, so the candidate
    space drops them (unless explicitly pinned)."""
    for gs in [(1, 1), (1, 8)]:
        assert {c["panel"] for c in candidate_configs(_ctx("lu", gs))} \
            == {"classic"}
        assert {c["panel"] for c in candidate_configs(_ctx("qr", gs))} \
            == {"classic"}
    pinned = candidate_configs(_ctx("lu", (1, 1)), {"panel": "calu"})
    assert all(c["panel"] == "calu" for c in pinned)


# ---------------------------------------------------------------------
# cost-model pivot-latency term
# ---------------------------------------------------------------------

def _score(op, grid, panel, n=64, nb=16):
    ctx = _ctx(op, (grid.height, grid.width), n)
    cfg = {"nb": nb, "panel": panel}
    if op == "lu":
        cfg.update(lookahead=True, crossover=0)
    return cm.score_config(op, cfg, ctx=ctx, grid=grid, dtype=jnp.float32)


def test_pivot_term_prefers_calu_on_multi_row_grids():
    g = _grid(2, 2)
    calu = _score("lu", g, "calu")
    classic = _score("lu", g, "classic")
    assert calu.pivot_s < classic.pivot_s
    assert calu.total_s < classic.total_s
    # the comm term agrees: the traced calu schedule has strictly fewer
    # collective rounds (the one-psum solve replaces two rounds)
    assert calu.rounds < classic.rounds


def test_pivot_term_ties_on_single_row_grids():
    g = _grid(1, 1)
    calu = _score("lu", g, "calu")
    classic = _score("lu", g, "classic")
    assert calu.pivot_s == classic.pivot_s


def test_qr_pivot_term_prefers_tsqr_on_multi_row_grids():
    g = _grid(2, 2)
    tsqr = _score("qr", g, "tsqr")
    classic = _score("qr", g, "classic")
    assert tsqr.pivot_s < classic.pivot_s
    assert tsqr.total_s < classic.total_s


# ---------------------------------------------------------------------
# the pinned 'auto' ranking
# ---------------------------------------------------------------------

def test_auto_picks_calu_on_multi_row_classic_on_single_row(empty_cache):
    res = tune.resolve("lu", gshape=(64, 64), dtype=jnp.float32,
                       grid=_grid(2, 2), requested={"panel": "auto"})
    assert res.source == "cost_model"
    assert res.config["panel"] == "calu"
    for grid in [_grid(1, 1), _grid(1, 8)]:
        res1 = tune.resolve("lu", gshape=(64, 64), dtype=jnp.float32,
                            grid=grid, requested={"panel": "auto"})
        assert res1.config["panel"] == "classic"


def test_auto_picks_tsqr_on_multi_row_grids(empty_cache):
    res = tune.resolve("qr", gshape=(64, 64), dtype=jnp.float32,
                       grid=_grid(2, 2), requested={"panel": "auto"})
    assert res.config["panel"] == "tsqr"
    res1 = tune.resolve("qr", gshape=(64, 64), dtype=jnp.float32,
                        grid=_grid(1, 1), requested={"panel": "auto"})
    assert res1.config["panel"] == "classic"


def test_lu_driver_accepts_panel_auto(empty_cache):
    """End-to-end: lu(panel='auto') resolves and factors correctly on a
    multi-row grid (where 'auto' selects the tournament panel)."""
    import numpy as np
    g = _grid(2, 2)
    rng = np.random.default_rng(80)
    F = rng.normal(size=(24, 24)).astype(np.float32)
    A = el.from_global(jnp.asarray(F), el.MC, el.MR, grid=g)
    LU, perm = el.lu(A, nb=8, panel="auto")
    lu_ = np.asarray(el.to_global(LU))
    L = np.tril(lu_, -1) + np.eye(24, dtype=np.float32)
    U = np.triu(lu_)
    np.testing.assert_allclose(L @ U, F[np.asarray(perm)], rtol=0, atol=2e-4)
