"""The comm_precision knob in the tuning subsystem (ISSUE 8): registry
coverage, candidate enumeration rules, 'auto' resolution, and the
bytes-vs-decode-flops cost-model term."""
import jax
import jax.numpy as jnp
import pytest

import elemental_tpu as el
from elemental_tpu import tune
from elemental_tpu.tune import cost_model
from elemental_tpu.tune.knobs import (COMM_PRECISIONS, OPS, TuneContext,
                                      candidate_configs)


def _grid(r, c):
    return el.Grid(jax.devices()[: r * c], height=r)


def _ctx(op, dims, grid_shape):
    return TuneContext(op=op, dims=dims, dtype="float32",
                       grid_shape=grid_shape, backend="cpu")


def test_every_op_registers_the_knob():
    for op, spec in OPS.items():
        assert "comm_precision" in spec.knobs, op


def test_candidates_dead_on_1x1_full_on_2x2():
    ctx1 = _ctx("cholesky", (64, 64), (1, 1))
    assert {c["comm_precision"] for c in candidate_configs(ctx1)} == {None}
    ctx2 = _ctx("cholesky", (64, 64), (2, 2))
    assert {c["comm_precision"] for c in candidate_configs(ctx2)} \
        == set(COMM_PRECISIONS)


def test_pinned_value_freezes_the_dimension():
    ctx = _ctx("lu", (64, 64), (2, 2))
    cands = candidate_configs(ctx, {"comm_precision": "bf16"})
    assert {c["comm_precision"] for c in cands} == {"bf16"}
    # pinning None (the driver default) keeps the space un-tripled
    base = candidate_configs(ctx, {"comm_precision": None})
    assert len(cands) == len(base)


def test_auto_resolves_none_on_1x1_and_quantized_when_bandwidth_bound():
    g1 = _grid(1, 1)
    kn = tune.resolve_knobs("cholesky", gshape=(64, 64), dtype=jnp.float32,
                            grid=g1, knobs={"nb": 16, "lookahead": True,
                                            "crossover": 0,
                                            "comm_precision": "auto"})
    assert kn["comm_precision"] is None
    g2 = _grid(2, 2)
    kn = tune.resolve_knobs("cholesky", gshape=(4096, 4096),
                            dtype=jnp.float32, grid=g2,
                            knobs={"nb": 256, "lookahead": True,
                                   "crossover": 0,
                                   "comm_precision": "auto"})
    # a big bandwidth-bound geometry buys the narrower wire
    assert kn["comm_precision"] in ("bf16", "int8")


def test_explicit_none_always_wins():
    """A user who did not opt in (driver default None) never gets a
    quantized wire from resolving OTHER knobs."""
    g2 = _grid(2, 2)
    kn = tune.resolve_knobs("cholesky", gshape=(2048, 2048),
                            dtype=jnp.float32, grid=g2,
                            knobs={"nb": "auto", "lookahead": "auto",
                                   "crossover": "auto",
                                   "comm_precision": None})
    assert kn["comm_precision"] is None
    assert isinstance(kn["nb"], int)


@pytest.mark.parametrize("mode,factor", sorted(cost_model.WIRE_FACTORS.items()))
def test_cost_model_wire_term(mode, factor):
    """The quantized candidate's bandwidth term shrinks by the mode's
    factor and gains a decode term -- scored WITHOUT re-tracing (the
    closed-form gemm path makes this cheap to pin exactly)."""
    ctx = _ctx("gemm", (512, 512, 512), (2, 2))
    base = cost_model.score_config("gemm", {"alg": "C", "nb": 128,
                                            "comm_precision": None},
                                   ctx=ctx, dtype=jnp.float32)
    quant = cost_model.score_config("gemm", {"alg": "C", "nb": 128,
                                             "comm_precision": mode},
                                    ctx=ctx, dtype=jnp.float32)
    # gemm alg C moves only all_gathers -> the whole byte total scales
    # (both modes price at the bf16 factor: gemm's pairs degrade int8)
    assert quant.comm_bytes == pytest.approx(0.5 * base.comm_bytes)
    assert quant.bandwidth_s < base.bandwidth_s
    assert quant.decode_s > 0 and base.decode_s == 0.0
    assert quant.rounds == base.rounds


def test_traced_driver_wire_term_orthogonal():
    """For the traced factorizations the wire factor scales bytes without
    re-tracing: prim counts and rounds are identical across modes."""
    g2 = _grid(2, 2)
    ctx = _ctx("cholesky", (64, 64), (2, 2))
    outs = {}
    for mode in COMM_PRECISIONS:
        outs[mode] = cost_model.score_config(
            "cholesky", {"nb": 16, "lookahead": True, "crossover": 0,
                         "comm_precision": mode},
            ctx=ctx, grid=g2, dtype=jnp.float32)
    assert outs["bf16"].prim_counts == outs[None].prim_counts
    assert outs["bf16"].rounds == outs[None].rounds
    assert outs["bf16"].comm_bytes == pytest.approx(
        cost_model.WIRE_FACTORS["bf16"] * outs[None].comm_bytes)
    assert outs["int8"].comm_bytes < outs["bf16"].comm_bytes
    assert outs["int8"].decode_s > outs["bf16"].decode_s
