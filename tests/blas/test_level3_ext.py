"""Oracles for the level-3 completeness pass: Her2k/Syr2k/Trr2k, Hemm/Symm,
Trmm, TwoSidedTrsm/Trmm, MultiShiftTrsm (cf. reference tests/blas_like)."""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, STAR, from_global, to_global, redistribute


def _mat(rng, m, n, dtype):
    A = rng.normal(size=(m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        A = A + 1j * rng.normal(size=(m, n))
    return A.astype(dtype)


def _tri(x, uplo, k=0):
    return np.tril(x, k) if uplo == "L" else np.triu(x, -k)


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("orient", ["N", "C"])
def test_her2k(grid24, uplo, orient):
    rng = np.random.default_rng(0)
    A = _mat(rng, 10, 6, np.complex128) if orient == "N" else _mat(rng, 6, 10, np.complex128)
    B = A * 0 + _mat(rng, *A.shape, np.complex128)
    C0 = _mat(rng, 10, 10, np.complex128)
    a = 0.7 - 0.2j
    Ad = from_global(A, MC, MR, grid=grid24)
    Bd = from_global(B, MC, MR, grid=grid24)
    Cd = from_global(C0, MC, MR, grid=grid24)
    out = el.her2k(uplo, Ad, Bd, alpha=a, beta=0.5, C=Cd, orient=orient, nb=4)
    opA = A if orient == "N" else A.conj().T
    opB = B if orient == "N" else B.conj().T
    full = a * opA @ opB.conj().T + np.conj(a) * opB @ opA.conj().T + 0.5 * C0
    got = np.asarray(to_global(out))
    np.testing.assert_allclose(_tri(got, uplo), _tri(full, uplo), rtol=1e-11)
    untouched = (lambda x: np.triu(x, 1)) if uplo == "L" else (lambda x: np.tril(x, -1))
    np.testing.assert_allclose(untouched(got), untouched(C0), rtol=0)


def test_syr2k(grid42):
    rng = np.random.default_rng(1)
    A = _mat(rng, 9, 5, np.complex128)
    B = _mat(rng, 9, 5, np.complex128)
    out = el.syr2k("U", from_global(A, MC, MR, grid=grid42),
                   from_global(B, MC, MR, grid=grid42), alpha=1.5, nb=4)
    full = 1.5 * (A @ B.T + B @ A.T)
    np.testing.assert_allclose(np.triu(np.asarray(to_global(out))),
                               np.triu(full), rtol=1e-11)


def test_trr2k(grid24):
    rng = np.random.default_rng(2)
    A = _mat(rng, 8, 5, np.float64)
    B = _mat(rng, 5, 8, np.float64)
    C = _mat(rng, 8, 5, np.float64)
    D = _mat(rng, 5, 8, np.float64)
    E0 = _mat(rng, 8, 8, np.float64)
    Amc = redistribute(from_global(A, MC, MR, grid=grid24), MC, STAR)
    Bmr = redistribute(from_global(B, MC, MR, grid=grid24), STAR, MR)
    Cmc = redistribute(from_global(C, MC, MR, grid=grid24), MC, STAR)
    Dmr = redistribute(from_global(D, MC, MR, grid=grid24), STAR, MR)
    Ed = from_global(E0, MC, MR, grid=grid24)
    out = el.trr2k("L", 2.0, Amc, Bmr, -1.0, Cmc, Dmr, 0.5, Ed)
    full = 2.0 * A @ B - C @ D + 0.5 * E0
    got = np.asarray(to_global(out))
    np.testing.assert_allclose(np.tril(got), np.tril(full), rtol=1e-12)
    np.testing.assert_allclose(np.triu(got, 1), np.triu(E0, 1), rtol=0)


@pytest.mark.parametrize("side", ["L", "R"])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hemm(grid24, side, uplo):
    rng = np.random.default_rng(3)
    H = _mat(rng, 8, 8, np.complex128)
    H = H + H.conj().T
    B = _mat(rng, 8, 6, np.complex128) if side == "L" else _mat(rng, 6, 8, np.complex128)
    P = H.copy()    # poison unstored triangle
    mask = np.tril(np.ones((8, 8), bool), -1) if uplo == "U" \
        else np.triu(np.ones((8, 8), bool), 1)
    P[mask] = 99.0
    out = el.hemm(side, uplo, from_global(P, MC, MR, grid=grid24),
                  from_global(B, MC, MR, grid=grid24), alpha=1.25)
    want = 1.25 * (H @ B if side == "L" else B @ H)
    np.testing.assert_allclose(np.asarray(to_global(out)), want, rtol=1e-11)


def test_symm_complex_symmetric(grid24):
    rng = np.random.default_rng(4)
    S = _mat(rng, 7, 7, np.complex128)
    S = S + S.T
    B = _mat(rng, 7, 4, np.complex128)
    out = el.symm("L", "U", from_global(np.triu(S), MC, MR, grid=grid24),
                  from_global(B, MC, MR, grid=grid24))
    np.testing.assert_allclose(np.asarray(to_global(out)), S @ B, rtol=1e-11)


@pytest.mark.parametrize("side,uplo,orient,unit",
                         [("L", "L", "N", False), ("L", "U", "C", False),
                          ("R", "U", "N", True), ("R", "L", "T", True)])
def test_trmm(grid24, side, uplo, orient, unit):
    rng = np.random.default_rng(5)
    T = _mat(rng, 8, 8, np.complex128)
    B = _mat(rng, 8, 8, np.complex128)
    Tm = _tri(T, uplo)
    if unit:
        np.fill_diagonal(Tm, 1.0)
    op = {"N": Tm, "T": Tm.T, "C": Tm.conj().T}[orient]
    want = 2.0 * (op @ B if side == "L" else B @ op)
    out = el.trmm(side, uplo, orient, from_global(T, MC, MR, grid=grid24),
                  from_global(B, MC, MR, grid=grid24), alpha=2.0, unit=unit, nb=4)
    np.testing.assert_allclose(np.asarray(to_global(out)), want, rtol=1e-11)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_two_sided_trsm_generalized_eig(grid24, uplo):
    """Reduce A x = lambda B x to standard form and check the eigenvalues
    match scipy's generalized solve (the reference's TwoSidedTrsm test)."""
    rng = np.random.default_rng(6)
    n = 8
    G = rng.normal(size=(n, n))
    A = G + G.T
    Fb = rng.normal(size=(n, n))
    B = Fb @ Fb.T / n + n * np.eye(n)
    Ad = from_global(A, MC, MR, grid=grid24)
    Bd = from_global(B, MC, MR, grid=grid24)
    F = el.cholesky(Bd, uplo, nb=4)
    S = el.two_sided_trsm(uplo, Ad, F, nb=4)
    got = np.sort(np.linalg.eigvalsh(np.asarray(to_global(S))))
    import scipy.linalg
    want = np.sort(scipy.linalg.eigh(A, B, eigvals_only=True))
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_two_sided_trmm_oracle(grid24, uplo):
    """lower: L^H A L; upper: U A U^H (the reference's TwoSidedTrmm)."""
    rng = np.random.default_rng(7)
    n = 8
    G = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    A = G + G.conj().T
    T = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    T = (np.tril(T) if uplo == "L" else np.triu(T)) + 2 * np.eye(n)
    Ad = from_global(A, MC, MR, grid=grid24)
    Td = from_global(T, MC, MR, grid=grid24)
    got = np.asarray(to_global(el.two_sided_trmm(uplo, Ad, Td, nb=4)))
    want = T.conj().T @ A @ T if uplo == "L" else T @ A @ T.conj().T
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("uplo,orient", [("L", "N"), ("U", "N"), ("U", "C"),
                                         ("L", "T")])
def test_multishift_trsm(grid24, uplo, orient):
    rng = np.random.default_rng(8)
    m, nrhs = 12, 7
    T = _mat(rng, m, m, np.complex128)
    T = _tri(T, uplo) + 4 * np.eye(m)
    B = _mat(rng, m, nrhs, np.complex128)
    shifts = (rng.normal(size=nrhs) + 1j * rng.normal(size=nrhs)) * 0.5
    out = el.multishift_trsm(uplo, orient, from_global(T, MC, MR, grid=grid24),
                             shifts, from_global(B, MC, MR, grid=grid24),
                             alpha=1.0, nb=4)
    X = np.asarray(to_global(out))
    op = {"N": T, "T": T.T, "C": T.conj().T}[orient]
    for j in range(nrhs):
        np.testing.assert_allclose((op - shifts[j] * np.eye(m)) @ X[:, j],
                                   B[:, j], rtol=1e-10, atol=1e-10)


def test_multishift_trsm_matches_trsm_at_zero_shift(two_grids):
    rng = np.random.default_rng(9)
    m, nrhs = 8, 4
    T = np.tril(rng.normal(size=(m, m))) + 3 * np.eye(m)
    B = rng.normal(size=(m, nrhs))
    Td = from_global(T, MC, MR, grid=two_grids)
    Bd = from_global(B, MC, MR, grid=two_grids)
    ms = el.multishift_trsm("L", "N", Td, np.zeros(nrhs), Bd, nb=4)
    ts = el.trsm("L", "L", "N", Td, Bd, nb=4)
    np.testing.assert_allclose(np.asarray(to_global(ms)),
                               np.asarray(to_global(ts)), rtol=1e-12)
