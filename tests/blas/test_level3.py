"""Level-3 correctness vs NumPy oracles.

Mirrors the reference's ``tests/blas_like/Gemm.cpp`` strategy: run every
SUMMA variant against the sequential product on a gathered copy
(``--correctness`` residual), plus Trsm/Herk drivers (SURVEY.md §5).
"""
import numpy as np
import pytest

from elemental_tpu import MC, MR, STAR, from_global, to_global
from elemental_tpu.blas import level3 as l3
from elemental_tpu.redist.engine import redist_counts as _redist_counts


def _rng(seed=0):
    return np.random.default_rng(seed)


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


@pytest.mark.parametrize("alg", ["C", "A", "B", "gspmd", "auto"])
def test_gemm_algs(grid24, alg):
    rng = _rng(1)
    m, k, n = 24, 17, 20
    A = rng.normal(size=(m, k))
    B = rng.normal(size=(k, n))
    C = l3.gemm(_dist(grid24, A), _dist(grid24, B), alg=alg, nb=8)
    np.testing.assert_allclose(np.asarray(to_global(C)), A @ B, rtol=1e-12)


@pytest.mark.parametrize("oa,ob", [("N", "T"), ("T", "N"), ("C", "C"), ("T", "T")])
def test_gemm_orientations(grid42, oa, ob):
    rng = _rng(2)
    m, k, n = 12, 10, 14
    A = rng.normal(size=(k, m) if oa != "N" else (m, k)) \
        + 1j * rng.normal(size=(k, m) if oa != "N" else (m, k))
    B = rng.normal(size=(n, k) if ob != "N" else (k, n)) \
        + 1j * rng.normal(size=(n, k) if ob != "N" else (k, n))
    op = {"N": lambda X: X, "T": lambda X: X.T, "C": lambda X: X.conj().T}
    C = l3.gemm(_dist(grid42, A), _dist(grid42, B), orient_a=oa, orient_b=ob, nb=8)
    np.testing.assert_allclose(np.asarray(to_global(C)), op[oa](A) @ op[ob](B), rtol=1e-12)


def test_gemm_alpha_beta(grid24):
    rng = _rng(3)
    m, k, n = 16, 9, 11
    A, B, C0 = rng.normal(size=(m, k)), rng.normal(size=(k, n)), rng.normal(size=(m, n))
    out = l3.gemm(_dist(grid24, A), _dist(grid24, B), alpha=2.0, beta=-0.5,
                  C=_dist(grid24, C0), alg="C", nb=8)
    np.testing.assert_allclose(np.asarray(to_global(out)), 2.0 * A @ B - 0.5 * C0,
                               rtol=1e-12)


def test_gemm_dot_complex_beta(grid24):
    """alg='dot' honors a complex beta against the oracle (the [STAR,VC]
    contraction path used to be the only one without coverage here)."""
    rng = _rng(41)
    m, k, n = 6, 40, 5
    A = rng.normal(size=(m, k)) + 1j * rng.normal(size=(m, k))
    B = rng.normal(size=(k, n)) + 1j * rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))
    alpha, beta = 1.5 - 0.5j, 0.7 - 0.3j
    out = l3.gemm(_dist(grid24, A), _dist(grid24, B), alpha=alpha, beta=beta,
                  C=_dist(grid24, C0), alg="dot")
    np.testing.assert_allclose(np.asarray(to_global(out)),
                               alpha * A @ B + beta * C0, rtol=1e-12)


def test_gemm_dot_complex_zero_beta_real_c(grid24):
    """beta=0j on a REAL C must behave as beta=0 (no complex accumulator
    forced through _safe_astype)."""
    rng = _rng(42)
    m, k, n = 6, 40, 5
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n))
    out = l3.gemm(_dist(grid24, A), _dist(grid24, B), beta=0j,
                  C=_dist(grid24, C0), alg="dot")
    np.testing.assert_allclose(np.asarray(to_global(out)), A @ B, rtol=1e-12)


def test_gemm_dot_p1_early_out():
    """On a 1x1 grid alg='dot' multiplies the storage arrays directly --
    zero redistribute calls (pinned via the engine's call counts)."""
    import jax
    from elemental_tpu import Grid

    g1 = Grid([jax.devices()[0]])
    rng = _rng(43)
    m, k, n = 6, 40, 5
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n))
    Ad, Bd, Cd = _dist(g1, A), _dist(g1, B), _dist(g1, C0)
    with _redist_counts() as counter:
        out = l3.gemm(Ad, Bd, alpha=2.0, beta=-0.5, C=Cd, alg="dot")
    assert not counter, dict(counter)
    np.testing.assert_allclose(np.asarray(to_global(out)),
                               2.0 * A @ B - 0.5 * C0, rtol=1e-12)


def test_herk_uses_fused_panel_spread(grid24):
    """The herk per-panel [MC,STAR]/[STAR,MR] pair must ride the fused
    panel_spread (one collective round), not the three-redistribute chain."""
    from elemental_tpu import VC

    rng = _rng(44)
    n, k, nb = 12, 16, 8
    A = rng.normal(size=(n, k))
    Ad = _dist(grid24, A)
    with _redist_counts() as counter:
        C = l3.herk("L", Ad, nb=nb)
    counts = dict(counter)
    npanels = -(-k // nb)
    assert counts.get("panel_spread") == npanels
    assert counts.get(((MC, MR), (VC, STAR))) == npanels
    assert ((VC, STAR), (MC, STAR)) not in counts
    assert ((STAR, VC), (STAR, MR)) not in counts
    got = np.asarray(to_global(C))
    np.testing.assert_allclose(np.tril(got), np.tril(A @ A.T), rtol=1e-12)


def test_gemm_two_grids(two_grids):
    rng = _rng(4)
    m, k, n = 13, 21, 8
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C = l3.gemm(_dist(two_grids, A), _dist(two_grids, B), nb=16)
    np.testing.assert_allclose(np.asarray(to_global(C)), A @ B, rtol=1e-12)


@pytest.mark.parametrize("side", ["L", "R"])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("orient", ["N", "T", "C"])
def test_trsm(grid24, side, uplo, orient):
    rng = _rng(5)
    m, n = 20, 12
    d = m if side == "L" else n
    T = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
    T = np.tril(T) if uplo == "L" else np.triu(T)
    T += (2 * d) * np.eye(d)                      # well-conditioned
    B = rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))
    op = {"N": T, "T": T.T, "C": T.conj().T}[orient]
    X = l3.trsm(side, uplo, orient, _dist(grid24, T), _dist(grid24, B),
                alpha=1.5, nb=8)
    want = 1.5 * (np.linalg.solve(op, B) if side == "L"
                  else np.linalg.solve(op.T, B.T).T)
    np.testing.assert_allclose(np.asarray(to_global(X)), want, rtol=1e-11)


def test_trsm_unit_diagonal(grid42):
    rng = _rng(6)
    m, n = 16, 7
    B = rng.normal(size=(m, n))
    # unit-diag: solver must ignore the stored diagonal
    Tstored = np.tril(rng.normal(size=(m, m)))
    np.fill_diagonal(Tstored, rng.normal(size=m) + 5)
    Tunit = np.tril(Tstored, -1) + np.eye(m)
    Xu = l3.trsm("L", "L", "N", _dist(grid42, Tstored), _dist(grid42, B),
                 unit=True, nb=8)
    np.testing.assert_allclose(np.asarray(to_global(Xu)),
                               np.linalg.solve(Tunit, B), rtol=1e-11)


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("orient", ["N", "C"])
def test_herk(grid24, uplo, orient):
    rng = _rng(7)
    m, k = 18, 10
    A = rng.normal(size=(m, k)) + 1j * rng.normal(size=(m, k))
    if orient == "C":
        A = A.conj().T.copy()      # op(A) A is m x k either way
        Aop = A.conj().T
    else:
        Aop = A
    C0 = rng.normal(size=(m, m)) + 1j * rng.normal(size=(m, m))
    out = l3.herk(uplo, _dist(grid24, A), alpha=2.0, beta=0.5,
                  C=_dist(grid24, C0), orient=orient, nb=8)
    got = np.asarray(to_global(out))
    want_tri = 2.0 * Aop @ Aop.conj().T + 0.5 * C0
    tri = np.tril if uplo == "L" else np.triu
    anti = np.triu if uplo == "L" else np.tril
    np.testing.assert_allclose(tri(got), tri(want_tri), rtol=1e-12)
    # other (strict) triangle untouched
    np.testing.assert_allclose(anti(got, 1 if uplo == "L" else -1),
                               anti(C0, 1 if uplo == "L" else -1), rtol=1e-12)


def test_syrk(grid42):
    rng = _rng(8)
    m, k = 14, 9
    A = rng.normal(size=(m, k)) + 1j * rng.normal(size=(m, k))
    out = l3.syrk("L", _dist(grid42, A), nb=8)
    got = np.asarray(to_global(out))
    np.testing.assert_allclose(np.tril(got), np.tril(A @ A.T), rtol=1e-12)


def test_trrk(grid24):
    from elemental_tpu import redistribute, VC
    rng = _rng(9)
    m, k = 16, 8
    A = rng.normal(size=(m, k))
    B = rng.normal(size=(k, m))
    C0 = rng.normal(size=(m, m))
    A_mc = redistribute(_dist(grid24, A), MC, STAR)
    B_mr = redistribute(_dist(grid24, B), STAR, MR)
    out = l3.trrk("L", -1.0, A_mc, B_mr, 1.0, _dist(grid24, C0))
    got = np.asarray(to_global(out))
    np.testing.assert_allclose(np.tril(got), np.tril(C0 - A @ B), rtol=1e-12)
    np.testing.assert_allclose(np.triu(got, 1), np.triu(C0, 1), rtol=1e-12)
