"""Level-2 oracles vs numpy (cf. reference tests/blas_like drivers)."""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global


def _vec(rng, m, dtype):
    v = rng.normal(size=(m, 1))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        v = v + 1j * rng.normal(size=(m, 1))
    return v.astype(dtype)


def _mat(rng, m, n, dtype):
    A = rng.normal(size=(m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        A = A + 1j * rng.normal(size=(m, n))
    return A.astype(dtype)


@pytest.mark.parametrize("orient", ["N", "T", "C"])
def test_gemv(grid24, orient):
    rng = np.random.default_rng(0)
    A = _mat(rng, 13, 9, np.complex128)
    x = _vec(rng, 9 if orient == "N" else 13, np.complex128)
    y = _vec(rng, 13 if orient == "N" else 9, np.complex128)
    Ad = from_global(A, MC, MR, grid=grid24)
    xd = from_global(x, MC, MR, grid=grid24)
    yd = from_global(y, MC, MR, grid=grid24)
    opA = {"N": A, "T": A.T, "C": A.conj().T}[orient]
    out = el.gemv(Ad, xd, alpha=2.0, beta=-1.5, y=yd, orient=orient)
    np.testing.assert_allclose(np.asarray(to_global(out)),
                               2.0 * opA @ x - 1.5 * y, rtol=1e-12)


def test_gemv_real_two_grids(two_grids):
    rng = np.random.default_rng(1)
    A = _mat(rng, 17, 6, np.float64)
    x = _vec(rng, 6, np.float64)
    Ad = from_global(A, MC, MR, grid=two_grids)
    xd = from_global(x, MC, MR, grid=two_grids)
    np.testing.assert_allclose(np.asarray(to_global(el.gemv(Ad, xd))),
                               A @ x, rtol=1e-12)


@pytest.mark.parametrize("conj", [True, False])
def test_ger(grid42, conj):
    rng = np.random.default_rng(2)
    A = _mat(rng, 11, 7, np.complex128)
    x = _vec(rng, 11, np.complex128)
    y = _vec(rng, 7, np.complex128)
    Ad = from_global(A, MC, MR, grid=grid42)
    out = el.ger(0.5 + 0.25j, from_global(x, MC, MR, grid=grid42),
                 from_global(y, MC, MR, grid=grid42), Ad, conj=conj)
    yrow = y.conj().T if conj else y.T
    np.testing.assert_allclose(np.asarray(to_global(out)),
                               A + (0.5 + 0.25j) * x @ yrow, rtol=1e-12)


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_hemv_reads_one_triangle(grid24, uplo, dtype):
    rng = np.random.default_rng(3)
    H = _mat(rng, 12, 12, dtype)
    H = H + H.conj().T
    x = _vec(rng, 12, dtype)
    # poison the unstored triangle: hemv must not read it
    P = H.copy()
    mask = np.tril(np.ones((12, 12), bool), -1) if uplo == "U" \
        else np.triu(np.ones((12, 12), bool), 1)
    P[mask] = 1e6
    Ad = from_global(P, MC, MR, grid=grid24)
    xd = from_global(x, MC, MR, grid=grid24)
    out = el.hemv(uplo, Ad, xd, alpha=1.5)
    np.testing.assert_allclose(np.asarray(to_global(out)), 1.5 * H @ x, rtol=1e-11)


def test_symv_complex_is_transpose_not_conj(grid24):
    rng = np.random.default_rng(4)
    S = _mat(rng, 10, 10, np.complex128)
    S = S + S.T                       # complex symmetric (not hermitian)
    x = _vec(rng, 10, np.complex128)
    Ad = from_global(np.tril(S), MC, MR, grid=grid24)
    out = el.symv("L", Ad, from_global(x, MC, MR, grid=grid24))
    np.testing.assert_allclose(np.asarray(to_global(out)), S @ x, rtol=1e-11)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_her2(grid24, uplo):
    rng = np.random.default_rng(5)
    H = _mat(rng, 9, 9, np.complex128)
    H = H + H.conj().T
    x = _vec(rng, 9, np.complex128)
    y = _vec(rng, 9, np.complex128)
    a = 0.3 - 0.7j
    Ad = from_global(H, MC, MR, grid=grid24)
    out = el.her2(uplo, a, from_global(x, MC, MR, grid=grid24),
                  from_global(y, MC, MR, grid=grid24), Ad)
    full = H + a * x @ y.conj().T + np.conj(a) * y @ x.conj().T
    got = np.asarray(to_global(out))
    tri = np.tril if uplo == "L" else np.triu
    anti = np.triu if uplo == "L" else np.tril
    np.testing.assert_allclose(tri(got), tri(full), rtol=1e-12)
    np.testing.assert_allclose(anti(got, 1 if uplo == "L" else -1),
                               anti(H, 1 if uplo == "L" else -1), rtol=1e-12)


@pytest.mark.parametrize("uplo,orient,unit", [("L", "N", False), ("U", "N", True),
                                              ("U", "C", False), ("L", "T", True)])
def test_trmv_trsv_roundtrip(grid24, uplo, orient, unit):
    rng = np.random.default_rng(6)
    T = _mat(rng, 8, 8, np.complex128)
    T = (np.tril(T) if uplo == "L" else np.triu(T)) + 3 * np.eye(8)
    x = _vec(rng, 8, np.complex128)
    Td = from_global(T, MC, MR, grid=grid24)
    xd = from_global(x, MC, MR, grid=grid24)
    Tm = T.copy()
    if unit:
        np.fill_diagonal(Tm, 1.0)
    op = {"N": Tm, "T": Tm.T, "C": Tm.conj().T}[orient]
    y = el.trmv(uplo, orient, Td, xd, unit=unit)
    np.testing.assert_allclose(np.asarray(to_global(y)), op @ x, rtol=1e-11)
    back = el.trsv(uplo, orient, Td, y, unit=unit, nb=4)
    np.testing.assert_allclose(np.asarray(to_global(back)), x, rtol=1e-9)
