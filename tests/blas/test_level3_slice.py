"""ISSUE 16: the slicing gemm (``alg='slice'``) -- correctness pins.

Identity vs the stationary-C reference across the full acceptance
matrix {square, tall-skinny, outer-product} x {1x1, 2x2, 2x4} x
{None, bf16, int8}; the degenerate-grid / ragged edge cases the slice
path newly exercises; and the complex-beta bugfix sweep for the
stationary-A/B and gspmd schedules (mirror of the PR 2 ``_summa_dot``
fix)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.blas import level3 as l3
from elemental_tpu.redist.engine import redist_counts


def _rng(seed):
    return np.random.default_rng(seed)


def _dist(g, arr):
    return from_global(jnp.asarray(arr), MC, MR, grid=g)


@pytest.fixture(params=[(1, 1), (2, 2), (2, 4)],
                ids=["1x1", "2x2", "2x4"])
def slice_grid(request):
    r, c = request.param
    return el.Grid(jax.devices()[: r * c], height=r)


#: the acceptance shape classes: square, tall-skinny (m >> n),
#: outer-product (k small)
SHAPES = {"square": (48, 48, 48),
          "tall_skinny": (256, 32, 8),
          "outer_product": (40, 4, 48)}


@pytest.mark.parametrize("shape", sorted(SHAPES), ids=sorted(SHAPES))
def test_slice_identical_to_stationary_c(slice_grid, shape):
    """Full precision (f64): slice agrees with the alg='C' reference to
    roundoff across every shape class x grid of the acceptance matrix."""
    rng = _rng(7)
    m, k, n = SHAPES[shape]
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n))
    args = dict(alpha=1.25, beta=-0.5)
    ref = l3.gemm(_dist(slice_grid, A), _dist(slice_grid, B),
                  C=_dist(slice_grid, C0), alg="C", nb=16, **args)
    got = l3.gemm(_dist(slice_grid, A), _dist(slice_grid, B),
                  C=_dist(slice_grid, C0), alg="slice", **args)
    np.testing.assert_allclose(np.asarray(to_global(got)),
                               np.asarray(to_global(ref)), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(to_global(got)),
                               1.25 * A @ B - 0.5 * C0, rtol=1e-11)


@pytest.mark.parametrize("cp", ["bf16", "int8"])
@pytest.mark.parametrize("shape", sorted(SHAPES), ids=sorted(SHAPES))
def test_slice_comm_precision_residual_class(slice_grid, shape, cp):
    """Quantized wires (bf16 cast / int8 block-scale-pack compose per
    plan slot on the slice gathers): the result stays in the quantized
    residual class of the family (the 5e-2 relative-Frobenius bound the
    other drivers pin)."""
    rng = _rng(11)
    m, k, n = SHAPES[shape]
    A = rng.normal(size=(m, k)).astype(np.float32)
    B = rng.normal(size=(k, n)).astype(np.float32)
    got = np.asarray(to_global(
        l3.gemm(_dist(slice_grid, A), _dist(slice_grid, B), alg="slice",
                comm_precision=cp)), dtype=np.float64)
    ref = A.astype(np.float64) @ B.astype(np.float64)
    assert np.linalg.norm(got - ref) / np.linalg.norm(ref) <= 5e-2
    # 1x1 grids: the knob is a no-op and the early-out is bit-identical
    if slice_grid.size == 1:
        exact = np.asarray(to_global(
            l3.gemm(_dist(slice_grid, A), _dist(slice_grid, B),
                    alg="slice")))
        assert np.array_equal(got.astype(np.float32), exact)


def test_slice_1x1_zero_redistributes():
    """1x1 degeneracy (pinned): slice is ONE local matmul -- zero
    redistribute calls, byte-identical to the dot early-out."""
    g = el.Grid(jax.devices()[:1], height=1)
    rng = _rng(3)
    A, B = rng.normal(size=(33, 17)), rng.normal(size=(17, 21))
    with redist_counts() as counter:
        got = l3.gemm(_dist(g, A), _dist(g, B), alg="slice")
    assert not counter
    dot = l3.gemm(_dist(g, A), _dist(g, B), alg="dot")
    assert np.array_equal(np.asarray(to_global(got)),
                          np.asarray(to_global(dot)))


def test_auto_1x1_keeps_dot_early_out_byte_identical():
    """alg='auto' on 1x1 still resolves to 'dot' and its p==1 early-out:
    zero redistributes, bitwise-equal output (the acceptance pin that
    'slice' joining the space does not perturb the degenerate grid)."""
    g = el.Grid(jax.devices()[:1], height=1)
    rng = _rng(5)
    A, B = rng.normal(size=(64, 32)), rng.normal(size=(32, 48))
    with redist_counts() as counter:
        got = l3.gemm(_dist(g, A), _dist(g, B), alg="auto")
    assert not counter
    dot = l3.gemm(_dist(g, A), _dist(g, B), alg="dot")
    assert np.array_equal(np.asarray(to_global(got)),
                          np.asarray(to_global(dot)))


@pytest.mark.parametrize("r,c", [(4, 1), (1, 8), (8, 1), (1, 4)])
def test_slice_degenerate_1d_grids(r, c):
    """Nx1 / 1xN grids: the mode rule makes two of the three legs local
    relabelings; the answer stays exact (incl. ragged extents)."""
    g = el.Grid(jax.devices()[: r * c], height=r)
    rng = _rng(13)
    for m, k, n in [(64, 16, 48), (23, 9, 31)]:
        A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
        got = l3.gemm(_dist(g, A), _dist(g, B), alg="slice")
        np.testing.assert_allclose(np.asarray(to_global(got)), A @ B,
                                   rtol=1e-11)


def test_slice_empty_slot_devices():
    """Ragged FFD edge case: extents SMALLER than the 1-D cyclic order
    leave whole devices with zero owned rows of the [VC,STAR] slice
    (their a2a slots are pure sentinel padding) -- the plan must still
    execute exactly."""
    g = el.Grid(jax.devices()[:4], height=2)
    rng = _rng(17)
    for m in (3, 5, 2):                    # m < p or barely above
        A, B = rng.normal(size=(m, 7)), rng.normal(size=(7, 2))
        got = l3.gemm(_dist(g, A), _dist(g, B), alg="slice")
        np.testing.assert_allclose(np.asarray(to_global(got)), A @ B,
                                   rtol=1e-11)


def test_slice_ignores_nb():
    """'slice' is a one-shot schedule: nb is dead (any value, same
    plan, same bits)."""
    g = el.Grid(jax.devices()[:4], height=2)
    rng = _rng(19)
    A, B = rng.normal(size=(96, 24)), rng.normal(size=(24, 8))
    a = l3.gemm(_dist(g, A), _dist(g, B), alg="slice", nb=8)
    b = l3.gemm(_dist(g, A), _dist(g, B), alg="slice", nb=None)
    assert np.array_equal(np.asarray(to_global(a)),
                          np.asarray(to_global(b)))


# ---------------------------------------------------------------------
# bugfix sweep: beta accumulation on the stationary-A/B + gspmd paths
# (mirror of the PR 2 _summa_dot complex-beta fix)
# ---------------------------------------------------------------------

@pytest.mark.parametrize("alg", ["A", "B", "slice", "gspmd"])
def test_gemm_complex_beta_real_c_raises(grid24, alg):
    """A complex beta cannot silently land in a REAL C: _safe_astype
    must raise (the stationary-A/B seeds used to skip the check and
    return a complex-typed result)."""
    rng = _rng(23)
    m, k, n = 24, 16, 20
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n))
    with pytest.raises(TypeError):
        l3.gemm(_dist(grid24, A), _dist(grid24, B), beta=0.5j,
                C=_dist(grid24, C0), alg=alg, nb=8)


@pytest.mark.parametrize("alg", ["A", "B", "slice", "gspmd"])
def test_gemm_complex_zero_beta_real_c(grid24, alg):
    """beta=0j on a REAL C behaves as beta=0 on every schedule (the
    gspmd branch used to raise spuriously; A/B used to go complex)."""
    rng = _rng(29)
    m, k, n = 24, 16, 20
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n))
    out = l3.gemm(_dist(grid24, A), _dist(grid24, B), beta=0j,
                  C=_dist(grid24, C0), alg=alg, nb=8)
    assert np.asarray(to_global(out)).dtype.kind == "f"
    np.testing.assert_allclose(np.asarray(to_global(out)), A @ B,
                               rtol=1e-12)


@pytest.mark.parametrize("alg", ["A", "B", "slice", "gspmd"])
def test_gemm_complex_c_real_operands_complex_beta(grid24, alg):
    """Complex C with REAL A, B and complex alpha/beta accumulates
    exactly on every schedule (the previously untested A/B cases)."""
    rng = _rng(31)
    m, k, n = 24, 16, 20
    A, B = rng.normal(size=(m, k)), rng.normal(size=(k, n))
    C0 = rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))
    alpha, beta = 1.5 - 0.5j, 0.7 - 0.3j
    out = l3.gemm(_dist(grid24, A), _dist(grid24, B), alpha=alpha,
                  beta=beta, C=_dist(grid24, C0), alg=alg, nb=8)
    np.testing.assert_allclose(np.asarray(to_global(out)),
                               alpha * A @ B + beta * C0, rtol=1e-12)
