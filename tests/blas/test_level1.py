"""Level-1 zoo oracle tests.

Reference analog: the reference exercises level-1 through every driver; the
conformance style here is entry-for-entry agreement with the numpy oracle
on the gathered global matrix, swept over distributions where layout
matters.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.blas import level1 as l1


def _mk(grid, m=13, n=9, dtype=np.float64, seed=0, dist=None):
    rng = np.random.default_rng(seed)
    if np.issubdtype(dtype, np.complexfloating):
        G = (rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))).astype(dtype)
    else:
        G = rng.normal(size=(m, n)).astype(dtype)
    d = dist or (el.MC, el.MR)
    return G, el.from_global(G, *d, grid=grid)


def _g(A):
    return np.asarray(el.to_global(A))


class TestElementwise:
    def test_axpy_scale_hadamard(self, grid24):
        X, Xd = _mk(grid24, seed=1)
        Y, Yd = _mk(grid24, seed=2)
        np.testing.assert_allclose(_g(l1.axpy(2.5, Xd, Yd)), 2.5 * X + Y)
        np.testing.assert_allclose(_g(l1.scale(-3.0, Xd)), -3.0 * X)
        np.testing.assert_allclose(_g(l1.hadamard(Xd, Yd)), X * Y)

    def test_fill_and_entrywise(self, grid24):
        X, Xd = _mk(grid24)
        np.testing.assert_allclose(_g(l1.fill(Xd, 7.0)), np.full(X.shape, 7.0))
        np.testing.assert_allclose(_g(l1.entrywise_map(Xd, lambda a: a ** 3)),
                                   X ** 3)

    def test_round_swap_parts(self, grid24):
        X, Xd = _mk(grid24, dtype=np.complex128)
        np.testing.assert_allclose(_g(l1.real_part(Xd)), X.real)
        np.testing.assert_allclose(_g(l1.imag_part(Xd)), X.imag)
        R = _g(l1.round_entries(Xd))
        np.testing.assert_allclose(R, np.round(X.real) + 1j * np.round(X.imag))
        Y, Yd = _mk(grid24, dtype=np.complex128, seed=5)
        A2, B2 = l1.swap(Xd, Yd)
        np.testing.assert_allclose(_g(A2), Y)
        np.testing.assert_allclose(_g(B2), X)


class TestOrientation:
    @pytest.mark.parametrize("dist", [(el.MC, el.MR), (el.MR, el.MC),
                                      (el.VC, el.STAR)],
                             ids=["mcmr", "mrmc", "vcstar"])
    def test_transpose_adjoint(self, grid24, dist):
        X, Xd = _mk(grid24, dtype=np.complex128, dist=dist)
        T = l1.transpose(Xd)
        assert T.dist == Xd.dist and T.gshape == (9, 13)
        np.testing.assert_allclose(_g(T), X.T)
        np.testing.assert_allclose(_g(l1.adjoint(Xd)), X.conj().T)


class TestLocReductions:
    def test_max_abs_loc(self, any_grid):
        X, Xd = _mk(any_grid, seed=3)
        v, (i, j) = l1.max_abs_loc(Xd)
        fi, fj = np.unravel_index(np.argmax(np.abs(X)), X.shape)
        assert (int(i), int(j)) == (fi, fj)
        np.testing.assert_allclose(float(v), np.abs(X).max())

    def test_min_abs_and_minmax_loc(self, grid24):
        X, Xd = _mk(grid24, seed=4)
        v, (i, j) = l1.min_abs_loc(Xd)
        fi, fj = np.unravel_index(np.argmin(np.abs(X)), X.shape)
        assert (int(i), int(j)) == (fi, fj)
        v, (i, j) = l1.max_loc(Xd)
        fi, fj = np.unravel_index(np.argmax(X), X.shape)
        assert (int(i), int(j)) == (fi, fj)
        v, (i, j) = l1.min_loc(Xd)
        fi, fj = np.unravel_index(np.argmin(X), X.shape)
        assert (int(i), int(j)) == (fi, fj)

    def test_norms_and_dots(self, grid24):
        X, Xd = _mk(grid24, dtype=np.complex128, seed=6)
        Y, Yd = _mk(grid24, dtype=np.complex128, seed=7)
        np.testing.assert_allclose(float(l1.frobenius_norm(Xd)),
                                   np.linalg.norm(X))
        np.testing.assert_allclose(float(l1.one_norm(Xd)),
                                   np.abs(X).sum(0).max())
        np.testing.assert_allclose(float(l1.infinity_norm(Xd)),
                                   np.abs(X).sum(1).max())
        np.testing.assert_allclose(float(l1.max_norm(Xd)), np.abs(X).max())
        np.testing.assert_allclose(complex(l1.dot(Xd, Yd)),
                                   np.sum(X.conj() * Y))
        np.testing.assert_allclose(complex(l1.dotu(Xd, Yd)), np.sum(X * Y))


class TestTrapezoid:
    @pytest.mark.parametrize("uplo,off", [("L", 0), ("U", 0), ("L", -2),
                                          ("U", 3)])
    def test_make_scale_axpy(self, grid24, uplo, off):
        X, Xd = _mk(grid24, m=11, n=11, seed=8)
        Y, Yd = _mk(grid24, m=11, n=11, seed=9)
        tri = np.tril(X, off) if uplo == "L" else np.triu(X, off)
        np.testing.assert_allclose(_g(l1.make_trapezoidal(Xd, uplo, off)), tri)
        exp = np.where(tri != 0, 2.0 * X, X)
        np.testing.assert_allclose(_g(l1.scale_trapezoid(2.0, Xd, uplo, off)),
                                   exp)
        np.testing.assert_allclose(_g(l1.axpy_trapezoid(3.0, Xd, Yd, uplo, off)),
                                   Y + 3.0 * tri)

    def test_safe_scale_extreme(self, grid24):
        X, Xd = _mk(grid24, seed=10)
        out = l1.safe_scale(1e-300, 1e-10, Xd)      # ratio 1e-290: stages
        np.testing.assert_allclose(_g(out), X * 1e-290, rtol=1e-12)
        out = l1.safe_scale(3.0, 2.0, Xd)
        np.testing.assert_allclose(_g(out), X * 1.5)
        with pytest.raises(ValueError, match="nonzero"):
            l1.safe_scale(1.0, 0.0, Xd)


class TestDiagonal:
    def test_get_set_update(self, grid24):
        X, Xd = _mk(grid24, m=10, n=10, seed=11)
        d = l1.get_diagonal(Xd)
        np.testing.assert_allclose(np.asarray(el.to_global(d)).ravel(),
                                   np.diag(X))
        dnew = el.from_global(np.arange(10.0).reshape(10, 1),
                              el.STAR, el.STAR, grid=grid24)
        S = l1.set_diagonal(Xd, dnew)
        exp = X.copy(); np.fill_diagonal(exp, np.arange(10.0))
        np.testing.assert_allclose(_g(S), exp)
        U = l1.update_diagonal(Xd, dnew)
        exp = X + np.diag(np.arange(10.0))
        np.testing.assert_allclose(_g(U), exp)

    def test_diagonal_scale_solve(self, grid24):
        X, Xd = _mk(grid24, m=8, n=5, seed=12)
        dv = np.arange(1.0, 9.0).reshape(8, 1)
        dd = el.from_global(dv, el.STAR, el.STAR, grid=grid24)
        np.testing.assert_allclose(_g(l1.diagonal_scale("L", dd, Xd)),
                                   dv * X)
        np.testing.assert_allclose(_g(l1.diagonal_solve("L", dd, Xd)),
                                   X / dv)
        dr = np.arange(1.0, 6.0).reshape(5, 1)
        ddr = el.from_global(dr, el.STAR, el.STAR, grid=grid24)
        np.testing.assert_allclose(_g(l1.diagonal_scale("R", ddr, Xd)),
                                   X * dr.T)


class TestSubmatrix:
    def test_get_set_roundtrip(self, grid24):
        X, Xd = _mk(grid24, m=12, n=10, seed=13)
        S = l1.get_submatrix(Xd, 3, 2, 6, 5)
        np.testing.assert_allclose(_g(S), X[3:9, 2:7])
        B = el.from_global(np.ones((6, 5)), el.MC, el.MR, grid=grid24)
        W = l1.set_submatrix(Xd, 3, 2, B)
        exp = X.copy(); exp[3:9, 2:7] = 1.0
        np.testing.assert_allclose(_g(W), exp)
