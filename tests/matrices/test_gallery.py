"""Gallery generators vs numpy/scipy constructions; device-RNG properties;
IO round-trips."""
import os
import tempfile

import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import matrices as M


def _t(A):
    return np.asarray(el.to_global(A))


def test_fourier(grid24):
    n = 8
    F = _t(M.fourier(n, grid=grid24))
    ref = np.exp(-2j * np.pi * np.outer(np.arange(n), np.arange(n)) / n) \
        / np.sqrt(n)
    assert np.linalg.norm(F - ref) < 1e-14
    assert np.linalg.norm(F @ F.conj().T - np.eye(n)) < 1e-13


def test_toeplitz_hankel_circulant(grid24):
    sla = pytest.importorskip("scipy.linalg")
    rng = np.random.default_rng(0)
    c = rng.normal(size=5)
    r = rng.normal(size=7)
    r[0] = c[0]
    assert np.allclose(_t(M.toeplitz(c, r, grid=grid24)), sla.toeplitz(c, r))
    assert np.allclose(_t(M.circulant(c, grid=grid24)), sla.circulant(c))
    rh = rng.normal(size=6)
    rh[0] = c[-1]
    assert np.allclose(_t(M.hankel(c, rh, grid=grid24)), sla.hankel(c, rh))


def test_cauchy_walsh_wilkinson(grid24):
    rng = np.random.default_rng(1)
    x = rng.normal(size=6)
    y = rng.normal(size=5) + 10
    C = _t(M.cauchy(x, y, grid=grid24))
    assert np.allclose(C, 1.0 / (x[:, None] - y[None, :]))
    W = _t(M.walsh(3, grid=grid24))
    assert np.allclose(W @ W.T, 8 * np.eye(8))
    Wk = _t(M.wilkinson(3, grid=grid24))
    assert np.allclose(np.diag(Wk), [3, 2, 1, 0, 1, 2, 3])
    assert np.allclose(np.diag(Wk, 1), 1)


def test_laplacians_spd(grid24):
    L1 = _t(M.laplacian_1d(9, grid=grid24))
    assert np.all(np.linalg.eigvalsh(L1) > 0)
    L2 = _t(M.laplacian_2d(3, 4, grid=grid24))
    assert np.allclose(L2, L2.T)
    assert np.all(np.linalg.eigvalsh(L2) > 0)


def test_structured_misc(grid24):
    J = _t(M.jordan(5, 2.5, grid=grid24))
    assert np.allclose(J, 2.5 * np.eye(5) + np.eye(5, k=1))
    K = _t(M.kahan(6, 0.5, grid=grid24))
    assert np.allclose(np.diag(K), (np.sqrt(0.75)) ** np.arange(6))
    G = _t(M.grcar(7, grid=grid24))
    assert np.allclose(np.diag(G, -1), -1) and np.allclose(np.diag(G), 1)
    P = _t(M.pei(5, 3.0, grid=grid24))
    assert np.allclose(P, 3 * np.eye(5) + np.ones((5, 5)))
    R = _t(M.redheffer(8, grid=grid24))
    assert R[0].sum() == 8 and R[3, 7] == 1 and R[3, 6] == 0
    T = _t(M.triw(5, -2.0, grid=grid24))
    assert np.allclose(T, np.eye(5) - 2 * np.triu(np.ones((5, 5)), 1))
    GG = _t(M.gepp_growth(6, grid=grid24))
    LU = np.linalg.qr(GG)  # just ensure well-formed; growth checked in lu tests
    assert GG[-1, -1] == 1 and GG[2, 0] == -1


def test_device_rng(grid24, grid42):
    A = M.gaussian_device(32, 24, grid=grid24, seed=7)
    Ag = _t(A)
    assert 0.8 < Ag.std() < 1.2
    # deterministic per (grid, seed)
    B = M.gaussian_device(32, 24, grid=grid24, seed=7)
    assert np.array_equal(Ag, _t(B))
    # different seed -> different draw
    C = M.gaussian_device(32, 24, grid=grid24, seed=8)
    assert not np.array_equal(Ag, _t(C))
    U = _t(M.uniform_device(16, grid=grid24, lo=2.0, hi=3.0))
    assert U.min() >= 2.0 and U.max() <= 3.0
    Rm = _t(M.rademacher(16, grid=grid24))
    assert set(np.unique(Rm)) <= {-1.0, 1.0}


def test_wigner_haar_spectrum(grid24):
    W = _t(M.wigner(16, grid=grid24))
    assert np.allclose(W, W.T)
    H = _t(M.haar(12, grid=grid24))
    assert np.linalg.norm(H.T @ H - np.eye(12)) < 1e-13
    N = _t(M.normal_uniform_spectrum(10, center=1.0, radius=0.5, grid=grid24))
    ev = np.linalg.eigvals(N)
    assert np.all(np.abs(ev - 1.0) <= 0.5 + 1e-10)
    assert np.linalg.norm(N @ N.conj().T - N.conj().T @ N) < 1e-12


def test_io_roundtrips(grid24):
    rng = np.random.default_rng(2)
    F = rng.normal(size=(13, 9))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    with tempfile.TemporaryDirectory() as td:
        el.write_matrix(A, os.path.join(td, "a"), format="npy")
        B = el.read_matrix(os.path.join(td, "a"), grid=grid24)
        assert np.array_equal(_t(B), F)
        el.write_matrix(A, os.path.join(td, "s"), format="shards")
        C = el.read_matrix(os.path.join(td, "s"), grid=grid24)
        assert np.array_equal(_t(C), F)
        el.checkpoint(os.path.join(td, "ck"), x=A, y=B)
        got = el.restore(os.path.join(td, "ck"), ["x", "y"], grid=grid24)
        assert np.array_equal(_t(got["x"]), F)
    # wrong-grid shard reload is refused with a clear error
    import jax
    with tempfile.TemporaryDirectory() as td:
        el.write_matrix(A, os.path.join(td, "s"), format="shards")
        g2 = el.Grid(jax.devices(), height=4)
        with pytest.raises(ValueError, match="grid"):
            el.read_matrix(os.path.join(td, "s"), grid=g2)


def test_print_matrix(grid24, capsys):
    import io as _io
    F = np.arange(6.0).reshape(2, 3)
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    buf = _io.StringIO()
    el.print_matrix(A, title="T", stream=buf)
    out = buf.getvalue()
    assert "T" in out and "5." in out


# ---------------------------------------------------------------------
# round-5 breadth generators
# ---------------------------------------------------------------------

class TestGalleryBreadth:
    def test_demmel(self, grid24):
        import numpy as np
        D = np.asarray(el.to_global(el.matrices.demmel(8, grid=grid24)))
        beta = 10.0 ** (4.0 / 7)
        assert np.allclose(np.diag(D), 1.0)
        assert np.isclose(D[0, 7], beta ** 7)
        assert np.allclose(np.tril(D, -1), 0)

    def test_druinsky_toledo(self, grid24):
        import numpy as np
        G = np.asarray(el.to_global(
            el.matrices.druinsky_toledo(4, grid=grid24)))
        assert G.shape == (8, 8)
        assert np.allclose(np.diag(G[:4, :4]), 1.0)
        assert np.allclose(G[:4, 4:], np.eye(4))
        assert np.allclose(G[4:, :4], np.eye(4))
        assert np.allclose(G[4:, 4:], 0)

    def test_extended_kahan_triangular(self, grid24):
        import numpy as np
        R = np.asarray(el.to_global(
            el.matrices.extended_kahan(4, grid=grid24)))
        assert R.shape == (12, 12)
        assert np.allclose(np.tril(R, -1), 0)   # upper triangular
        assert np.linalg.matrix_rank(R) == 12

    def test_fiedler(self, grid24):
        import numpy as np
        c = np.array([0.0, 1.0, 3.0, 7.0])
        F = np.asarray(el.to_global(el.matrices.fiedler(c, grid=grid24)))
        assert np.allclose(F, np.abs(c[:, None] - c[None, :]))

    def test_fox_li_nonnormal(self, grid24):
        import numpy as np
        A = np.asarray(el.to_global(el.matrices.fox_li(24, grid=grid24)))
        assert A.shape == (24, 24)
        assert np.linalg.norm(A @ A.conj().T - A.conj().T @ A) > 1e-8

    def test_gks(self, grid24):
        import numpy as np
        G = np.asarray(el.to_global(el.matrices.gks(6, grid=grid24)))
        assert np.allclose(np.diag(G), 1 / np.sqrt(np.arange(1, 7)))
        assert np.isclose(G[0, 3], -0.5)

    def test_hanowa_spectrum(self, grid24):
        import numpy as np
        H = np.asarray(el.to_global(
            el.matrices.hanowa(8, mu=-1.0, grid=grid24)))
        w = np.linalg.eigvals(H)
        assert np.allclose(np.sort(w.real), -np.ones(8))
        assert np.allclose(np.sort(np.abs(w.imag)),
                           np.sort(np.abs(np.r_[1:5, 1:5] * 1.0)))

    def test_helmholtz_shift(self, grid24):
        import numpy as np
        L = np.asarray(el.to_global(
            el.matrices.laplacian_1d(9, grid=grid24)))
        H = np.asarray(el.to_global(
            el.matrices.helmholtz_1d(9, 2.5, grid=grid24)))
        assert np.allclose(H, L - 2.5 * np.eye(9))

    def test_laplacian_3d_spd(self, grid24):
        import numpy as np
        L = np.asarray(el.to_global(
            el.matrices.laplacian_3d(3, 3, 3, grid=grid24)))
        assert np.allclose(L, L.T)
        assert np.linalg.eigvalsh(L).min() > 0
        # 7-point stencil: interior row has exactly 7 nonzeros
        assert (np.abs(L[13]) > 0).sum() == 7

    def test_jordan_cholesky(self, grid24):
        import numpy as np
        C = np.asarray(el.to_global(
            el.matrices.jordan_cholesky(6, grid=grid24)))
        # C = B^T B with B the Jordan block (diag 2, superdiag 1)
        B = np.eye(6) * 2.0
        B[np.arange(5), np.arange(1, 6)] = 1.0
        assert np.allclose(C, B.T @ B)

    def test_lauchli(self, grid24):
        import numpy as np
        A = np.asarray(el.to_global(
            el.matrices.lauchli(5, mu=1e-4, grid=grid24)))
        assert A.shape == (6, 5)
        assert np.allclose(A[0], 1.0)
        assert np.allclose(A[1:], 1e-4 * np.eye(5))

    def test_legendre_eigs_in_unit_interval(self, grid24):
        import numpy as np
        J = np.asarray(el.to_global(el.matrices.legendre(12, grid=grid24)))
        assert np.allclose(J, J.T)
        w = np.linalg.eigvalsh(J)
        assert w.min() > -1 and w.max() < 1     # Gauss-Legendre nodes

    def test_lotkin(self, grid24):
        import numpy as np
        L = np.asarray(el.to_global(el.matrices.lotkin(5, grid=grid24)))
        assert np.allclose(L[0], 1.0)
        H = 1.0 / (np.arange(5)[:, None] + np.arange(5)[None, :] + 1.0)
        assert np.allclose(L[1:], H[1:])

    def test_one_two_one_spectrum(self, grid24):
        import numpy as np
        T = np.asarray(el.to_global(el.matrices.one_two_one(10, grid=grid24)))
        w = np.linalg.eigvalsh(T)
        k = np.arange(1, 11)
        assert np.allclose(np.sort(w), np.sort(2 + 2 * np.cos(k * np.pi / 11)))

    def test_riffle_stochastic(self, grid24):
        """El::Riffle semantics: the Eulerian-normalized transition matrix
        P[i,j] = 2^{-n} C(n+1, 2j-i+1) A(n,j)/A(n,i) is row-stochastic with
        stationary law A(n,i)/n! (the descent distribution)."""
        import math
        import numpy as np
        n = 6
        P = np.asarray(el.to_global(el.matrices.riffle(n, grid=grid24)))
        assert np.all(P >= 0)
        np.testing.assert_allclose(P.sum(axis=1), np.ones(n), rtol=1e-12)
        # pin against the exact integer Eulerian numbers
        A = [1]
        for m in range(2, n + 1):
            A = [(k + 1) * (A[k] if k < len(A) else 0)
                 + (m - k) * (A[k - 1] if k >= 1 else 0) for k in range(m)]
        assert A == [1, 57, 302, 302, 57, 1] and sum(A) == math.factorial(n)
        ref = np.zeros((n, n))
        for i in range(n):
            for j in range(n):
                k = 2 * i - j + 1
                if 0 <= k <= n + 1:
                    ref[i, j] = math.comb(n + 1, k) * A[j] / (2 ** n * A[i])
        np.testing.assert_allclose(P, ref, rtol=1e-12)
        # exact known entries: P[0,0] = C(7,1)/2^6 = 7/64 and
        # P[0,1] = C(7,0) A(6,1)/(2^6 A(6,0)) = 57/64
        assert np.isclose(P[0, 0], 7 / 64)
        assert np.isclose(P[0, 1], 57 / 64)
        # stationary distribution: pi_i = A(n,i)/n!
        pi = np.asarray(A) / math.factorial(n)
        np.testing.assert_allclose(pi @ P, pi, rtol=1e-12)

    def test_ris(self, grid24):
        import numpy as np
        R = np.asarray(el.to_global(el.matrices.ris(6, grid=grid24)))
        i, j = np.meshgrid(np.arange(6), np.arange(6), indexing="ij")
        assert np.allclose(R, 0.5 / (6 - i - j - 0.5))

    def test_whale_banded_toeplitz(self, grid24):
        import numpy as np
        W = np.asarray(el.to_global(el.matrices.whale(12, grid=grid24)))
        assert np.isclose(W[1, 0], 10.0)        # z^1 coefficient below diag
        assert np.isclose(W[0, 1], 1.0)         # z^{-1} above
        assert np.isclose(W[0, 4], 1.0)         # z^{-4}
        # Toeplitz: constant diagonals
        assert np.allclose(np.diag(W, 2), W[0, 2])
        assert np.allclose(np.diag(W, -2), W[2, 0])

    def test_hatano_nelson(self, grid24):
        import numpy as np
        H = np.asarray(el.to_global(
            el.matrices.hatano_nelson(8, g=0.5, grid=grid24)))
        assert np.allclose(np.diag(H, 1), np.exp(0.5))
        assert np.allclose(np.diag(H, -1), np.exp(-0.5))
        assert np.isclose(H[7, 0], np.exp(0.5))     # periodic wrap

    def test_three_valued(self, grid24):
        import numpy as np
        T = np.asarray(el.to_global(
            el.matrices.three_valued(40, 40, grid=grid24)))
        assert set(np.unique(T)).issubset({-1.0, 0.0, 1.0})
        frac = (T != 0).mean()
        assert 0.4 < frac < 0.9

    def test_kms_inverse_tridiagonal(self, grid24):
        import numpy as np
        K = np.asarray(el.to_global(el.matrices.kms(8, 0.5, grid=grid24)))
        # KMS inverses are tridiagonal -- the classic identity
        Kinv = np.linalg.inv(K)
        off2 = Kinv - np.diag(np.diag(Kinv)) \
            - np.diag(np.diag(Kinv, 1), 1) - np.diag(np.diag(Kinv, -1), -1)
        assert np.abs(off2).max() < 1e-10

    def test_egorov_unimodular(self, grid24):
        import numpy as np
        import jax.numpy as jnp
        A = np.asarray(el.to_global(el.matrices.egorov(
            lambda i, j: (i * j).astype(jnp.float64) * 0.1, 10,
            grid=grid24)))
        assert np.allclose(np.abs(A), 1.0 / np.sqrt(10))
