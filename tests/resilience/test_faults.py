"""Deterministic fault injection (ISSUE 7): seed determinism, the
corruption-class x target acceptance matrix, ladder escalation pinning,
and the no-silent-garbage invariant."""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.resilience import (FaultPlan, FaultSpec, certified_solve,
                                      fault_injection, logs_identical)


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


def _problem(rng, n, op, nrhs=2):
    F = rng.normal(size=(n, n))
    A = F @ F.T / n + n * np.eye(n) if op == "hpd" else F + n * np.eye(n)
    B = rng.normal(size=(n, nrhs))
    return A, B


def _clean_resid(An, Bn, X):
    Xn = np.asarray(to_global(X), dtype=np.float64)
    return np.linalg.norm(Bn - An @ Xn) / (
        np.linalg.norm(An) * np.linalg.norm(Xn) + np.linalg.norm(Bn))


# the op whose solve path exercises each engine target: lu routes through
# redistribute; the cholesky trailing chain is THE panel_spread caller
_OP_FOR_TARGET = {"redistribute": "lu", "panel_spread": "hpd"}


# ---------------------------------------------------------------------
# plan mechanics
# ---------------------------------------------------------------------

def test_spec_validation():
    with pytest.raises(ValueError):
        FaultSpec("bogus_target", "nan")
    with pytest.raises(ValueError):
        FaultSpec("redistribute", "bogus_kind")
    with pytest.raises(ValueError):
        FaultSpec("redistribute", "nan", call=-1)
    with pytest.raises(TypeError):
        FaultPlan(0, ["not a spec"])


def test_injection_scoped_and_counted(grid24):
    """Corruption happens only inside the context manager, on exactly the
    requested call, and the log records the bit-level change."""
    rng = np.random.default_rng(101)
    F = rng.normal(size=(16, 16)) + 16 * np.eye(16)
    A = _dist(grid24, F)
    plan = FaultPlan(seed=3, faults=[FaultSpec("redistribute", "nan",
                                               call=0, nelem=2)])
    LU0, _ = el.lu(A, nb=8)                        # outside: untouched
    with fault_injection(plan):
        LU1, _ = el.lu(A, nb=8)
    LU2, _ = el.lu(A, nb=8)                        # after: untouched again
    assert plan.fired() == 1
    ev = plan.log[0]
    assert ev.target == "redistribute" and ev.call == 0 and ev.kind == "nan"
    assert ev.indices.size == 2
    assert np.isnan(ev.after).all() and np.isfinite(ev.before).all()
    assert not np.isfinite(np.asarray(to_global(LU1))).all()
    assert np.isfinite(np.asarray(to_global(LU0))).all()
    np.testing.assert_array_equal(np.asarray(to_global(LU0)),
                                  np.asarray(to_global(LU2)))


@pytest.mark.parametrize("kind", ["bitflip", "scale", "nan"])
def test_corruption_kinds_change_payload(grid24, kind):
    rng = np.random.default_rng(102)
    F = rng.normal(size=(16, 16)) + 16 * np.eye(16)
    plan = FaultPlan(seed=11, faults=[FaultSpec("redistribute", kind,
                                                call=1, nelem=3)])
    with fault_injection(plan):
        el.lu(_dist(grid24, F), nb=8)
    assert plan.fired() == 1
    ev = plan.log[0]
    assert ev.kind == kind
    assert not np.array_equal(ev.before, ev.after)
    if kind == "nan":
        assert np.isnan(ev.after).all()
    if kind == "scale":
        np.testing.assert_allclose(ev.after, ev.before * 1e12)


# ---------------------------------------------------------------------
# SATELLITE: determinism -- identical seed => bit-identical corrupted
# payloads AND identical escalation ladder outcome across two runs
# ---------------------------------------------------------------------

@pytest.mark.parametrize("target", ["redistribute", "panel_spread"])
def test_fault_determinism_two_runs(grid24, target):
    op = _OP_FOR_TARGET[target]
    rng = np.random.default_rng(103)
    An, Bn = _problem(rng, 24, op)
    A, B = _dist(grid24, An), _dist(grid24, Bn)

    def run(plan):
        with fault_injection(plan):
            X, info = certified_solve(op, A, B, nb=8)
        return X, info

    mk = lambda: FaultPlan(seed=42, faults=[
        FaultSpec(target, "scale", call=0),
        FaultSpec(target, "bitflip", call=2, nelem=2)])
    p1, p2 = mk(), mk()
    X1, i1 = run(p1)
    X2, i2 = run(p2)
    assert p1.fired() > 0
    assert logs_identical(p1, p2)                 # bit-identical payloads
    # identical ladder outcome
    assert i1["certified"] == i2["certified"]
    assert i1["rung"] == i2["rung"]
    assert [a["rung"] for a in i1["attempts"]] \
        == [a["rung"] for a in i2["attempts"]]
    assert [a["refine_iters"] for a in i1["attempts"]] \
        == [a["refine_iters"] for a in i2["attempts"]]
    if X1 is not None:
        np.testing.assert_array_equal(np.asarray(to_global(X1)),
                                      np.asarray(to_global(X2)))
    # the SAME plan object replays after reset()
    p1.reset()
    _, i3 = run(p1)
    assert logs_identical(p1, p2) and i3["rung"] == i1["rung"]


def test_different_seed_different_payload(grid24):
    rng = np.random.default_rng(104)
    F = rng.normal(size=(16, 16)) + 16 * np.eye(16)
    logs = []
    for seed in (1, 2):
        plan = FaultPlan(seed=seed, faults=[FaultSpec(
            "redistribute", "bitflip", call=0, nelem=4)])
        with fault_injection(plan):
            el.lu(_dist(grid24, F), nb=8)
        logs.append(plan)
    ea, eb = logs[0].log[0], logs[1].log[0]
    assert not (np.array_equal(ea.indices, eb.indices)
                and ea.after.tobytes() == eb.after.tobytes())


# ---------------------------------------------------------------------
# ACCEPTANCE MATRIX: every corruption class x target on a 2x2 grid --
# certified within tolerance after escalation, or a structured health
# report naming the failing phase.  ZERO silent NaN/garbage returns.
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["bitflip", "scale", "nan"])
@pytest.mark.parametrize("target", ["redistribute", "panel_spread"])
@pytest.mark.parametrize("mode", ["oneshot", "persistent"])
def test_fault_matrix_no_silent_garbage(grid24, target, kind, mode):
    op = _OP_FOR_TARGET[target]
    rng = np.random.default_rng(105)
    An, Bn = _problem(rng, 24, op)
    A, B = _dist(grid24, An), _dist(grid24, Bn)
    spec = FaultSpec(target, kind, call=0 if target == "panel_spread" else 2,
                     every=(mode == "persistent"), nelem=2)
    plan = FaultPlan(seed=13, faults=[spec])
    with fault_injection(plan):
        X, info = certified_solve(op, A, B, nb=8)
    assert plan.fired() > 0, "fault never landed: the matrix is vacuous"
    if info["certified"]:
        # certificate must be INDEPENDENTLY true (clean-path residual)
        assert X is not None
        assert np.isfinite(np.asarray(to_global(X))).all()
        assert _clean_resid(An, Bn, X) <= info["tol"]
    else:
        # structured failure: the report names the failing phase
        assert info["failing_phase"] is not None
        assert info["attempts"], "no attempts recorded"


def test_oneshot_fault_escalation_order_pinned(grid24):
    """One-shot NaNs on the first TWO panel_spreads corrupt the 'quant'
    and 'fast' factors (one spread per factorization at this geometry);
    'refine' (sharing fast's factor) cannot fix it; 'abft' (ISSUE 11)
    refactors under the checksum-guarded schedule -- the one-shot faults
    are spent, so it certifies BEFORE the fp32 escalation -- the ladder
    order quant -> fast -> refine -> abft pinned, including the
    shares-the-factor semantics of 'refine'."""
    rng = np.random.default_rng(106)
    An, Bn = _problem(rng, 24, "hpd")
    plan = FaultPlan(seed=5, faults=[FaultSpec("panel_spread", "nan",
                                               call=0),
                                     FaultSpec("panel_spread", "nan",
                                               call=1)])
    with fault_injection(plan):
        X, info = certified_solve("hpd", _dist(grid24, An),
                                  _dist(grid24, Bn), nb=8)
    assert info["certified"] is True
    assert info["rung"] == "abft"
    assert [a["rung"] for a in info["attempts"]] == ["quant", "fast",
                                                     "refine", "abft"]
    assert _clean_resid(An, Bn, X) <= info["tol"]
    # the corrupted attempts carry their health evidence
    assert info["attempts"][0]["health"]["ok"] is False
    assert info["attempts"][1]["health"]["ok"] is False


def test_persistent_corruption_surfaced_with_phase(grid24):
    """every=True NaN corruption can never certify; the certificate names
    the failing phase from the health reports."""
    rng = np.random.default_rng(107)
    An, Bn = _problem(rng, 24, "lu")
    plan = FaultPlan(seed=5, faults=[FaultSpec("redistribute", "nan",
                                               call=1, every=True)])
    with fault_injection(plan):
        X, info = certified_solve("lu", _dist(grid24, An),
                                  _dist(grid24, Bn), nb=8)
    assert info["certified"] is False
    assert info["failing_phase"] is not None
    assert info["health"] is not None
    assert [a["rung"] for a in info["attempts"]] \
        == ["quant", "fast", "refine", "abft", "fp32", "classic"]


# ---------------------------------------------------------------------
# SATELLITE (ISSUE 9): the 'compute' fault target -- local panel-kernel
# outputs corrupted through engine.apply_fault, same seeded bit-identical
# replay contract as the collective targets
# ---------------------------------------------------------------------

def test_compute_target_registered():
    from elemental_tpu.resilience import FAULT_TARGETS
    assert FAULT_TARGETS == ("redistribute", "panel_spread", "compute")
    FaultSpec("compute", "nan")          # validates
    # appending 'compute' must NOT have moved the original targets' seed
    # words (the determinism contract of recorded plans)
    from elemental_tpu.resilience.faults import _TARGET_WORD
    assert _TARGET_WORD["redistribute"] == 1
    assert _TARGET_WORD["panel_spread"] == 2
    assert _TARGET_WORD["compute"] == 3


@pytest.mark.parametrize("driver", ["lu", "cholesky", "qr"])
def test_compute_fault_corrupts_local_panel(grid24, driver):
    """A compute-target fault lands in the driver's LOCAL panel kernel
    output (no engine payload involved) and propagates into the factor;
    outside the context the driver is untouched."""
    rng = np.random.default_rng(120)
    n = 16
    arr = rng.normal(size=(n, n)) + n * np.eye(n)
    if driver == "cholesky":
        arr = arr @ arr.T / n + n * np.eye(n)

    def run():
        A = _dist(grid24, arr)
        if driver == "lu":
            return np.asarray(to_global(el.lu(A, nb=8)[0]))
        if driver == "qr":
            return np.asarray(to_global(el.qr(A, nb=8)[0]))
        return np.asarray(to_global(el.cholesky(A, nb=8)))

    clean = run()
    plan = FaultPlan(seed=9, faults=[FaultSpec("compute", "nan", call=0,
                                               nelem=2)])
    with fault_injection(plan):
        dirty = run()
    after = run()
    assert plan.fired() >= 1
    assert all(ev.target == "compute" for ev in plan.log)
    assert not np.array_equal(clean, dirty)
    np.testing.assert_array_equal(clean, after)


def test_compute_fault_replay_bit_identical(grid24):
    rng = np.random.default_rng(121)
    arr = rng.normal(size=(16, 16)) + 16 * np.eye(16)

    def run(plan):
        # crossover=0: both panels stay in the distributed loop (the
        # tail finish would otherwise absorb panel 1 locally)
        with fault_injection(plan):
            LU, _ = el.lu(_dist(grid24, arr), nb=8, crossover=0)
        return np.asarray(to_global(LU))

    mk = lambda: FaultPlan(seed=77, faults=[
        FaultSpec("compute", "bitflip", call=0, every=True, nelem=2)])
    p1, p2 = mk(), mk()
    d1, d2 = run(p1), run(p2)
    assert p1.fired() >= 2               # one per panel at nb=8, n=16
    assert logs_identical(p1, p2)
    np.testing.assert_array_equal(d1, d2)


def test_compute_vs_redistribute_streams_differ(grid24):
    """Same seed, same call index: the compute target draws from its OWN
    seed stream (target word), not redistribute's."""
    rng = np.random.default_rng(122)
    arr = rng.normal(size=(16, 16)) + 16 * np.eye(16)
    logs = {}
    for target in ("compute", "redistribute"):
        plan = FaultPlan(seed=55, faults=[FaultSpec(target, "bitflip",
                                                    call=0, nelem=3)])
        with fault_injection(plan):
            el.lu(_dist(grid24, arr), nb=8)
        assert plan.fired() == 1
        logs[target] = plan.log[0]
    ea, eb = logs["compute"], logs["redistribute"]
    assert not (ea.shape == eb.shape
                and np.array_equal(ea.indices, eb.indices)
                and ea.after.tobytes() == eb.after.tobytes())


@pytest.mark.parametrize("mode", ["oneshot", "persistent"])
def test_compute_fault_matrix_certified_or_surfaced(grid24, mode):
    """certified_solve over a compute-corrupted LOCAL kernel: same
    no-silent-garbage invariant as the engine targets."""
    rng = np.random.default_rng(123)
    An, Bn = _problem(rng, 24, "lu")
    A, B = _dist(grid24, An), _dist(grid24, Bn)
    plan = FaultPlan(seed=13, faults=[FaultSpec(
        "compute", "nan", call=0, every=(mode == "persistent"), nelem=2)])
    with fault_injection(plan):
        X, info = certified_solve("lu", A, B, nb=8)
    assert plan.fired() > 0
    if info["certified"]:
        assert _clean_resid(An, Bn, X) <= info["tol"]
    else:
        assert info["failing_phase"] is not None


# ---------------------------------------------------------------------
# step-scoped (windowed) rules (ISSUE 11): the injection vehicle the
# ABFT panel-recovery acceptance tests drive
# ---------------------------------------------------------------------

def test_window_validation():
    with pytest.raises(ValueError):
        FaultSpec("redistribute", "nan", window=(2, 1))
    with pytest.raises(ValueError):
        FaultSpec("redistribute", "nan", window=(-1, 3))
    with pytest.raises(ValueError):
        FaultSpec("redistribute", "nan", window=(0,))
    assert FaultSpec("redistribute", "nan", window=(1, 2)).window == (1, 2)


def test_window_scopes_to_announced_steps(grid24):
    """A windowed rule fires ONLY inside its panel-step window, exactly
    once when one-shot -- and never outside a set_fault_step scope (a
    plain unguarded driver announces no steps)."""
    rng = np.random.default_rng(124)
    arr = (rng.normal(size=(16, 16)) + 16 * np.eye(16)).astype(np.float32)
    # plain lu announces no steps: the windowed rule is inert
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("redistribute", "nan", nelem=2, window=(1, 2))])
    with fault_injection(plan):
        el.lu(_dist(grid24, arr), nb=4)
    assert plan.fired() == 0
    # the abft driver announces steps: in-window fires once...
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("redistribute", "nan", nelem=2, window=(1, 2))])
    with fault_injection(plan):
        el.lu(_dist(grid24, arr), nb=4, abft=True)
    assert plan.fired() == 1
    assert all(e.step == 1 for e in plan.log)
    # ...and an out-of-range window never does
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("redistribute", "nan", nelem=2, window=(99, 100))])
    with fault_injection(plan):
        el.lu(_dist(grid24, arr), nb=4, abft=True)
    assert plan.fired() == 0


def test_windowed_plan_replay_bit_identical(grid24):
    """Same seed, same windowed plan, same guarded run: fault log AND
    recovered factor replay bit-identically."""
    rng = np.random.default_rng(125)
    arr = (rng.normal(size=(16, 16)) + 16 * np.eye(16)).astype(np.float32)

    def run(plan):
        with fault_injection(plan):
            LU, _ = el.lu(_dist(grid24, arr), nb=4, abft=True)
        return np.asarray(to_global(LU))

    mk = lambda: FaultPlan(seed=77, faults=[
        FaultSpec("compute", "bitflip", nelem=2, window=(1, 3))])
    p1, p2 = mk(), mk()
    d1, d2 = run(p1), run(p2)
    assert p1.fired() == 1
    assert logs_identical(p1, p2)
    np.testing.assert_array_equal(d1, d2)
