"""ABFT checksum-guarded factorizations (ISSUE 11 + 15): the acceptance
matrix {bitflip, scale, nan} x {redistribute, compute} inside
abft-enabled lu/cholesky/qr detects at the injected panel and recovers
by re-executing ONLY that panel (recompute_count == 1), the abft=None
path is bit-identical to the plain drivers, quantized wire produces no
false positives, and unrecovered persistent faults surface through
health_report/v1.  ISSUE 15 grows the matrix the qr op: both panel
strategies ('classic' larfg and the 'tsqr' tree) are guarded, and
``FaultSpec(window=)`` step scoping works for qr exactly as for
lu/cholesky (the transaction layer announces panel steps)."""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.obs import Tracer, metrics_scope
from elemental_tpu.resilience import (ABFT_SCHEMA, AbftGuard, FaultPlan,
                                      FaultSpec, HealthMonitor,
                                      fault_injection, last_abft_report)


def _build(op, n, dtype=np.float32, seed=0):
    """A well-conditioned host matrix + its MC/MR distribution."""
    rng = np.random.default_rng(seed)
    F = rng.standard_normal((n, n)).astype(dtype)
    M = F @ F.T / n + n * np.eye(n, dtype=dtype) if op == "hpd" \
        else F + n * np.eye(n, dtype=dtype)
    return M


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


def _lu_residual(M, LU, perm):
    n = M.shape[0]
    lu_g = np.asarray(to_global(LU))
    L = np.tril(lu_g, -1) + np.eye(n, dtype=lu_g.dtype)
    U = np.triu(lu_g)
    return np.linalg.norm(M[np.asarray(perm)] - L @ U) / np.linalg.norm(M)


def _chol_residual(M, Lc):
    Lg = np.asarray(to_global(Lc))
    return np.linalg.norm(M - Lg @ Lg.conj().T) / np.linalg.norm(M)


def _qr_residual(M, Ap, tau):
    Q = np.asarray(to_global(el.explicit_q(Ap, tau)))
    R = np.triu(np.asarray(to_global(Ap)))
    return np.linalg.norm(M - Q @ R) / np.linalg.norm(M)


# ---------------------------------------------------------------------
# clean guarded runs: ok reports, zero violations, bitwise-plain output
# ---------------------------------------------------------------------

def test_clean_lu_abft_ok(grid24):
    M = _build("lu", 16)
    LU, perm = el.lu(_dist(grid24, M), nb=4, abft=True)
    rep = last_abft_report("lu")
    assert rep["schema"] == ABFT_SCHEMA
    assert rep["ok"] is True and rep["driver"] == "lu"
    assert rep["panels"] == 4 and rep["checks"] > 0
    assert rep["violations"] == [] and rep["recompute_count"] == 0
    assert rep["quantized_wire"] is False
    assert _lu_residual(M, LU, perm) < 1e-5


def test_clean_cholesky_abft_ok(grid24):
    M = _build("hpd", 16)
    Lc = el.cholesky(_dist(grid24, M), nb=4, abft=True)
    rep = last_abft_report("cholesky")
    assert rep["ok"] is True and rep["driver"] == "cholesky"
    assert rep["violations"] == [] and rep["recompute_count"] == 0
    assert _chol_residual(M, Lc) < 1e-5


@pytest.mark.parametrize("panel", ["classic", "tsqr"])
def test_clean_qr_abft_ok(grid24, panel):
    """Both panel strategies are guarded: the TSQR tree preserves column
    sums leaf-to-root, so the single reconstruction check covers it."""
    M = _build("lu", 12)
    Ap, tau = el.qr(_dist(grid24, M), nb=4, panel=panel, abft=True)
    rep = last_abft_report("qr")
    assert rep["schema"] == ABFT_SCHEMA
    assert rep["ok"] is True and rep["driver"] == "qr"
    assert rep["panels"] == 3 and rep["checks"] > 0
    assert rep["violations"] == [] and rep["recompute_count"] == 0
    assert _qr_residual(M, Ap, tau) < 1e-5


def test_report_schema_pin(grid24):
    el.lu(_dist(grid24, _build("lu", 16)), nb=4, abft=True)
    rep = last_abft_report("lu")
    assert set(rep) == {"schema", "driver", "ok", "panels", "checks",
                        "violations", "recovered_panels",
                        "unrecovered_panels", "recompute_count",
                        "max_retries", "quantized_wire"}


def test_abft_true_output_bitwise_plain(grid24):
    """The guarded path only OBSERVES: checksum maintenance never
    perturbs the factorization itself.  abft forces the classic
    right-looking schedule, so the bitwise reference is lookahead=False
    (the lookahead pipeline reorders last-bit rounding)."""
    M = _build("lu", 16, dtype=np.float64, seed=3)
    LU0, p0 = el.lu(_dist(grid24, M), nb=4, lookahead=False)
    LU1, p1 = el.lu(_dist(grid24, M), nb=4, abft=True)
    np.testing.assert_array_equal(np.asarray(to_global(LU0)),
                                  np.asarray(to_global(LU1)))
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    S = _build("hpd", 16, dtype=np.float64, seed=3)
    np.testing.assert_array_equal(
        np.asarray(to_global(el.cholesky(_dist(grid24, S), nb=4,
                                         lookahead=False))),
        np.asarray(to_global(el.cholesky(_dist(grid24, S), nb=4,
                                         abft=True))))


def test_qr_abft_output_bitwise_plain(grid24):
    """qr's guarded path only OBSERVES too: same blocked Householder
    schedule, so plain qr IS the bitwise reference (no lookahead to
    disable), and abft=None stays the plain dispatch."""
    M = _build("lu", 16, dtype=np.float64, seed=3)
    Ap0, tau0 = el.qr(_dist(grid24, M), nb=4)
    Ap1, tau1 = el.qr(_dist(grid24, M), nb=4, abft=True)
    Ap2, tau2 = el.qr(_dist(grid24, M), nb=4, abft=None)
    np.testing.assert_array_equal(np.asarray(to_global(Ap0)),
                                  np.asarray(to_global(Ap1)))
    np.testing.assert_array_equal(np.asarray(tau0), np.asarray(tau1))
    np.testing.assert_array_equal(np.asarray(to_global(Ap0)),
                                  np.asarray(to_global(Ap2)))


def test_abft_none_is_plain_dispatch(grid24):
    """abft=None is the NULL path: same code, bit-identical output."""
    M = _build("lu", 16, dtype=np.float64, seed=5)
    LU0, _ = el.lu(_dist(grid24, M), nb=8)
    LU1, _ = el.lu(_dist(grid24, M), nb=8, abft=None)
    np.testing.assert_array_equal(np.asarray(to_global(LU0)),
                                  np.asarray(to_global(LU1)))


# ---------------------------------------------------------------------
# THE ACCEPTANCE MATRIX: one-shot {bitflip, scale, nan} x
# {redistribute, compute} inside the guarded drivers -> detected at the
# injected panel, recovered by re-executing ONLY that panel.
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["bitflip", "scale", "nan"])
@pytest.mark.parametrize("target", ["redistribute", "compute"])
@pytest.mark.parametrize("op", ["lu", "hpd", "qr"])
def test_acceptance_matrix_panel_recovery(grid24, op, target, kind):
    """The ISSUE-11 acceptance pin, grown the qr op by ISSUE 15: a
    one-shot fault scoped to panel step 1 is detected AT step 1 and
    repaired by exactly ONE panel re-execution (the recovery-cost
    counter), with a clean factor."""
    n = 12
    M = _build(op, n)
    plan = FaultPlan(seed=7, faults=[
        FaultSpec(target, kind, nelem=2, window=(1, 2))])
    with fault_injection(plan):
        if op == "lu":
            LU, perm = el.lu(_dist(grid24, M), nb=4, abft=True)
            rep = last_abft_report("lu")
            res = _lu_residual(M, LU, perm)
        elif op == "qr":
            Ap, tau = el.qr(_dist(grid24, M), nb=4, abft=True)
            rep = last_abft_report("qr")
            res = _qr_residual(M, Ap, tau)
        else:
            Lc = el.cholesky(_dist(grid24, M), nb=4, abft=True)
            rep = last_abft_report("cholesky")
            res = _chol_residual(M, Lc)
    assert plan.fired() >= 1, "fault never landed: the cell is vacuous"
    assert sorted({v["step"] for v in rep["violations"]}) == [1]
    assert rep["recompute_count"] == 1       # ONLY the corrupted panel
    assert rep["recovered_panels"] == [1]
    assert rep["unrecovered_panels"] == []
    assert rep["ok"] is True
    assert res < 1e-5


def test_violation_doc_shape(grid24):
    M = _build("lu", 16)
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("redistribute", "nan", nelem=2, window=(1, 2))])
    with fault_injection(plan):
        el.lu(_dist(grid24, M), nb=4, abft=True)
    rep = last_abft_report("lu")
    assert rep["violations"]
    for v in rep["violations"]:
        assert set(v) == {"step", "attempt", "phase", "kind", "value",
                          "nonfinite", "columns"}
        assert v["step"] == 1 and v["attempt"] == 0


# ---------------------------------------------------------------------
# quantized wire: the widened threshold absorbs block-scaled rounding
# ---------------------------------------------------------------------

@pytest.mark.parametrize("op", ["lu", "hpd", "qr"])
def test_quantized_wire_no_false_positives(grid24, op):
    M = _build(op, 16, dtype=np.float64, seed=9)
    if op == "lu":
        el.lu(_dist(grid24, M), nb=8, abft=True, comm_precision="bf16")
        rep = last_abft_report("lu")
    elif op == "qr":
        el.qr(_dist(grid24, M), nb=8, abft=True, comm_precision="bf16")
        rep = last_abft_report("qr")
    else:
        el.cholesky(_dist(grid24, M), nb=8, abft=True,
                    comm_precision="bf16")
        rep = last_abft_report("cholesky")
    assert rep["quantized_wire"] is True
    assert rep["violations"] == [] and rep["ok"] is True


# ---------------------------------------------------------------------
# persistent faults: retries exhaust, the panel commits UNRECOVERED and
# surfaces through the bound health monitor
# ---------------------------------------------------------------------

def test_persistent_fault_surfaces_through_health(grid24):
    M = _build("lu", 16)
    mon = HealthMonitor()
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("redistribute", "nan", every=True, nelem=2)])
    with fault_injection(plan):
        el.lu(_dist(grid24, M), nb=4, abft=AbftGuard(max_retries=1),
              health=mon)
    rep = last_abft_report("lu")
    assert rep["ok"] is False
    assert rep["unrecovered_panels"]
    # every unrecovered step burned the full retry budget
    assert rep["recompute_count"] >= rep["max_retries"]
    hrep = mon.report()
    assert hrep["ok"] is False
    flags = [f for f in hrep["flags"] if f["kind"] == "abft"]
    assert flags
    assert hrep["failing_phase"] == flags[0]["phase"]


def test_qr_persistent_fault_surfaces_through_health(grid24):
    M = _build("qr", 12)
    mon = HealthMonitor()
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("redistribute", "nan", every=True, nelem=2)])
    with fault_injection(plan):
        el.qr(_dist(grid24, M), nb=4, abft=AbftGuard(max_retries=1),
              health=mon)
    rep = last_abft_report("qr")
    assert rep["ok"] is False
    assert rep["unrecovered_panels"]
    assert rep["recompute_count"] >= rep["max_retries"]
    hrep = mon.report()
    assert hrep["ok"] is False
    flags = [f for f in hrep["flags"] if f["kind"] == "abft"]
    assert flags
    assert hrep["failing_phase"] == flags[0]["phase"]


# ---------------------------------------------------------------------
# qr specifics: the tsqr tree panel recovers too, and FaultSpec window
# step-scoping works for qr exactly as for lu/cholesky (satellite: the
# transaction layer announces panel steps, fires exactly once, replays
# bit-identically)
# ---------------------------------------------------------------------

def test_qr_tsqr_panel_recovery(grid24):
    """The TSQR tree panel is guarded by the same reconstruction check:
    a corrupted tree output violates the packed-factor invariant and the
    panel re-executes."""
    M = _build("qr", 12)
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("compute", "scale", nelem=2, window=(1, 2))])
    with fault_injection(plan):
        Ap, tau = el.qr(_dist(grid24, M), nb=4, panel="tsqr", abft=True)
    rep = last_abft_report("qr")
    assert plan.fired() >= 1
    assert sorted({v["step"] for v in rep["violations"]}) == [1]
    assert rep["recompute_count"] == 1
    assert rep["recovered_panels"] == [1] and rep["ok"] is True
    assert _qr_residual(M, Ap, tau) < 1e-5


def test_qr_windowed_fault_fires_once_replay_identical(grid24):
    """window=(1, 2) scopes the one-shot to panel step 1 -- it fires
    EXACTLY once (the qr schedule announces steps through the
    transaction layer), and a same-seed replay is bit-identical in both
    fault log and committed factor."""
    from elemental_tpu.resilience import logs_identical
    M = _build("qr", 12, dtype=np.float64, seed=5)

    def run():
        plan = FaultPlan(seed=7, faults=[
            FaultSpec("redistribute", "bitflip", nelem=2, window=(1, 2))])
        with fault_injection(plan):
            Ap, tau = el.qr(_dist(grid24, M), nb=4, abft=True)
        return plan, np.asarray(to_global(Ap)), np.asarray(tau)

    p1, A1, t1 = run()
    p2, A2, t2 = run()
    assert p1.fired() == 1 and p2.fired() == 1
    assert logs_identical(p1, p2)
    np.testing.assert_array_equal(A1, A2)
    np.testing.assert_array_equal(t1, t2)


# ---------------------------------------------------------------------
# observability: metrics counters + the abft:recover span
# ---------------------------------------------------------------------

def test_metrics_emitted(grid24):
    M = _build("lu", 16)
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("compute", "scale", nelem=2, window=(1, 2))])
    with metrics_scope() as reg:
        with fault_injection(plan):
            el.lu(_dist(grid24, M), nb=4, abft=True)
        rep = last_abft_report("lu")
        assert reg.counter_value("abft_checks", driver="lu") \
            == rep["checks"]
        assert reg.counter_value("abft_violations", driver="lu") \
            == len(rep["violations"])
        assert reg.counter_value("abft_recovered_panels", driver="lu") == 1


def test_recovery_span_on_tracer(grid24):
    M = _build("hpd", 16)
    plan = FaultPlan(seed=7, faults=[
        FaultSpec("compute", "nan", nelem=2, window=(1, 2))])
    tr = Tracer()
    with tr:
        with fault_injection(plan):
            el.cholesky(_dist(grid24, M), nb=4, abft=True)
    spans = [s for s in tr.spans if s.name == "abft:recover"]
    assert len(spans) == 1                   # one retry, one span
    assert spans[0].attrs["step"] == 1 and spans[0].attrs["attempt"] == 1
    assert spans[0].attrs["violated"]


# ---------------------------------------------------------------------
# guard plumbing: explicit AbftGuard pass-through + report retrieval
# ---------------------------------------------------------------------

def test_explicit_guard_passthrough(grid24):
    g = AbftGuard(max_retries=1)
    el.lu(_dist(grid24, _build("lu", 16)), nb=4, abft=g)
    rep = g.report()
    assert rep["driver"] == "lu" and rep["max_retries"] == 1
    assert last_abft_report("lu") is rep
    assert last_abft_report() is rep         # the "_latest" alias
