"""Numerical-health guards (ISSUE 7): flag detection, schema pin,
metrics/tracer emission, and the health-off zero-overhead invariant."""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global
from elemental_tpu.obs import Tracer, metrics_scope
from elemental_tpu.resilience import (HEALTH_SCHEMA, HealthMonitor,
                                      last_health_report)


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


def _spd(rng, n):
    F = rng.normal(size=(n, n))
    return F @ F.T / n + n * np.eye(n)


# ---------------------------------------------------------------------
# clean runs: ok reports, sane estimates
# ---------------------------------------------------------------------

def test_clean_lu_report_ok(grid24):
    rng = np.random.default_rng(71)
    F = rng.normal(size=(24, 24)) + 24 * np.eye(24)
    mon = HealthMonitor()
    el.lu(_dist(grid24, F), nb=8, health=mon)
    rep = mon.report()
    assert rep["schema"] == HEALTH_SCHEMA
    assert rep["ok"] is True
    assert rep["flags"] == []
    assert rep["failing_phase"] is None
    assert rep["checks"] > 0
    # diagonally dominant matrix: no meaningful growth
    assert rep["growth_estimate"] is not None
    assert 0.5 < rep["growth_estimate"] < 100.0
    assert rep["scale"] == pytest.approx(np.max(np.abs(F)))


def test_clean_cholesky_report_ok(grid24):
    rng = np.random.default_rng(72)
    mon = HealthMonitor()
    el.cholesky(_dist(grid24, _spd(rng, 24)), nb=8, health=mon)
    rep = mon.report()
    assert rep["ok"] is True and rep["driver"] == "cholesky"
    assert rep["min_diag"] is not None and rep["min_diag"] > 0


def test_report_schema_pin(grid24):
    """health_report/v1 key set is stable (consumers parse it)."""
    rng = np.random.default_rng(73)
    mon = HealthMonitor()
    el.lu(_dist(grid24, rng.normal(size=(16, 16))), nb=8, health=mon)
    rep = mon.report()
    assert set(rep) == {"schema", "driver", "ok", "checks", "flags",
                        "growth_estimate", "scale", "min_diag",
                        "failing_phase"}


# ---------------------------------------------------------------------
# flag detection
# ---------------------------------------------------------------------

def test_nan_input_flags_nonfinite(grid24):
    rng = np.random.default_rng(74)
    F = rng.normal(size=(24, 24))
    F[5, 7] = np.nan
    mon = HealthMonitor()
    el.lu(_dist(grid24, F), nb=8, health=mon)
    rep = mon.report()
    assert rep["ok"] is False
    kinds = {f["kind"] for f in rep["flags"]}
    assert "nonfinite" in kinds
    assert rep["failing_phase"] in ("panel", "swap", "solve", "update",
                                    "tail", "tournament")


def test_cholesky_nonpd_flagged(grid24):
    """A non-PD input NaNs out of the diag-block cholesky; the guard
    surfaces it instead of letting the NaN factor flow downstream."""
    n = 16
    A = -np.eye(n)
    mon = HealthMonitor()
    el.cholesky(_dist(grid24, A), nb=8, health=mon)
    rep = mon.report()
    assert rep["ok"] is False
    kinds = {f["kind"] for f in rep["flags"]}
    assert kinds & {"nonfinite", "nonpositive_diag"}


def test_lu_small_pivot_flagged(grid24):
    """An exactly-singular matrix surfaces a (near-)zero pivot flag on a
    panel tick."""
    rng = np.random.default_rng(75)
    F = rng.normal(size=(16, 16))
    F[9] = F[2]                          # duplicate row: exact singularity
    mon = HealthMonitor()
    # crossover=0: the final panel (where the zero pivot lands) must run
    # in the distributed loop so its packed factor hits a panel tick
    el.lu(_dist(grid24, F), nb=8, crossover=0, health=mon)
    rep = mon.report()
    assert rep["ok"] is False
    assert any(f["kind"] == "small_pivot" for f in rep["flags"])


def test_growth_flag_on_blowup(grid24):
    """A huge injected blowup in the input trips the growth estimate --
    the monitor's anchor is max |A|, so scale the BLOWUP mid-run via a
    tiny growth_limit instead (the estimate itself is what's pinned)."""
    rng = np.random.default_rng(76)
    F = rng.normal(size=(16, 16))
    mon = HealthMonitor(growth_limit=1e-3)   # everything trips
    el.lu(_dist(grid24, F), nb=8, health=mon)
    rep = mon.report()
    assert any(f["kind"] == "growth" for f in rep["flags"])
    assert rep["growth_estimate"] > 1e-3


# ---------------------------------------------------------------------
# emission: metrics registry, tracer instants, last_health_report
# ---------------------------------------------------------------------

def test_metrics_and_last_report(grid24):
    rng = np.random.default_rng(77)
    F = rng.normal(size=(16, 16))
    F[3, 3] = np.inf
    with metrics_scope() as reg:
        el.lu(_dist(grid24, F), nb=8, health=True)   # internal monitor
        assert reg.counter_value("health_checks", driver="lu") > 0
        flags = reg.counters("health_flags")
        assert flags and all(k[0] == "health_flags" for k in flags)
    rep = last_health_report("lu")
    assert rep is not None and rep["ok"] is False
    assert last_health_report() is not None


def test_tracer_instant_events(grid24):
    rng = np.random.default_rng(78)
    F = rng.normal(size=(16, 16))
    F[2, 5] = np.nan
    tr = Tracer()
    with tr:
        el.lu(_dist(grid24, F), nb=8, health=True)
    names = [ev.name for ev in tr.instants]
    assert any(nm.startswith("health:") for nm in names)
    from elemental_tpu.obs import chrome_trace_doc
    doc = chrome_trace_doc(tr)
    evs = [ev for ev in doc["traceEvents"]
           if ev.get("ph") == "i" and ev["name"].startswith("health:")]
    assert evs
    lanes = {ev["tid"]: ev["args"]["name"] for ev in doc["traceEvents"]
             if ev.get("ph") == "M" and ev.get("name") == "thread_name"}
    assert lanes[evs[0]["tid"]] == "events"


# ---------------------------------------------------------------------
# health off == zero overhead (the acceptance invariant: redist counts
# unchanged; comm-plan goldens are covered by tests/analysis)
# ---------------------------------------------------------------------

@pytest.mark.parametrize("driver", ["lu", "cholesky", "qr"])
def test_health_off_redist_counts_unchanged(grid24, driver, redist_counter):
    rng = np.random.default_rng(79)
    n = 24
    arr = _spd(rng, n) if driver == "cholesky" else \
        rng.normal(size=(n, n)) + n * np.eye(n)
    fn = getattr(el, driver)
    from elemental_tpu.redist.engine import redist_counts
    with redist_counts() as off:
        fn(_dist(grid24, arr), nb=8)
    with redist_counts() as on:
        fn(_dist(grid24, arr), nb=8, health=True)
    assert dict(off) == dict(on)


def test_monitor_engine_free(grid24, redist_counter):
    """The monitor itself issues no engine calls: attaching it adds ZERO
    redistribute/panel_spread entries (checked above) and its report()
    runs off-line on host scalars."""
    rng = np.random.default_rng(80)
    mon = HealthMonitor()
    el.lu(_dist(grid24, rng.normal(size=(16, 16))), nb=8, health=mon)
    before = dict(redist_counter)
    mon.report()
    assert dict(redist_counter) == before


def test_monitor_reuse_resets(grid24):
    """Rebinding a monitor to a second driver call resets its state."""
    rng = np.random.default_rng(81)
    mon = HealthMonitor()
    F = rng.normal(size=(16, 16))
    F[1, 1] = np.nan
    el.lu(_dist(grid24, F), nb=8, health=mon)
    assert mon.report()["ok"] is False
    el.lu(_dist(grid24, rng.normal(size=(16, 16)) + 16 * np.eye(16)),
          nb=8, health=mon)
    assert mon.report()["ok"] is True


# ---------------------------------------------------------------------
# SATELLITE (ISSUE 9): qr(..., health=) parity with lu/cholesky --
# NaN/Inf scans on panel/update ticks + near-zero R-diagonal detection
# ---------------------------------------------------------------------

def test_qr_clean_report_ok(grid24):
    rng = np.random.default_rng(130)
    mon = HealthMonitor()
    el.qr(_dist(grid24, rng.normal(size=(24, 24))), nb=8, health=mon)
    rep = mon.report()
    assert rep["schema"] == HEALTH_SCHEMA
    assert rep["driver"] == "qr" and rep["ok"] is True
    assert rep["checks"] > 0
    assert rep["min_diag"] is not None and rep["min_diag"] > 0
    assert rep["growth_estimate"] is not None


@pytest.mark.parametrize("panel", ["classic", "tsqr"])
def test_qr_nan_input_flags_nonfinite(grid24, panel):
    rng = np.random.default_rng(131)
    F = rng.normal(size=(24, 24))
    F[3, 5] = np.nan
    mon = HealthMonitor()
    el.qr(_dist(grid24, F), nb=8, panel=panel, health=mon)
    rep = mon.report()
    assert rep["ok"] is False
    assert any(fl["kind"] == "nonfinite" for fl in rep["flags"])
    assert rep["failing_phase"] in ("panel", "update")


def test_qr_rank_deficiency_flags_small_rdiag(grid24):
    """A rank-deficient input's R diagonal hits (near-)zero: the packed
    panel diagonal check flags it as small_pivot -- the QR image of the
    LU near-zero-pivot guard."""
    rng = np.random.default_rng(132)
    F = rng.normal(size=(24, 24))
    F[:, 13] = F[:, 4]                   # duplicated column: rank 23
    mon = HealthMonitor()
    el.qr(_dist(grid24, F), nb=8, health=mon)
    rep = mon.report()
    assert rep["ok"] is False
    flags = [fl for fl in rep["flags"] if fl["kind"] == "small_pivot"]
    assert flags and flags[0]["phase"] == "panel"
    # the clean sibling does not flag
    mon2 = HealthMonitor()
    el.qr(_dist(grid24, rng.normal(size=(24, 24))), nb=8, health=mon2)
    assert mon2.report()["ok"] is True


def test_qr_health_true_lands_in_last_report(grid24):
    rng = np.random.default_rng(133)
    el.qr(_dist(grid24, rng.normal(size=(16, 16))), nb=8, health=True)
    rep = last_health_report("qr")
    assert rep is not None and rep["driver"] == "qr"
