"""Certified solves (ISSUE 7): clean certification, certificate schema,
ladder pinning, tolerance semantics, singular escalation."""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.resilience import (CERT_SCHEMA, LADDER_NAMES, Rung,
                                      certified_solve, default_ladder,
                                      default_tol)


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


def _problem(rng, n, nrhs=3, op="lu"):
    F = rng.normal(size=(n, n))
    A = F @ F.T / n + n * np.eye(n) if op == "hpd" else F + n * np.eye(n)
    B = rng.normal(size=(n, nrhs))
    return A, B


def _clean_resid(An, Bn, X):
    Xn = np.asarray(to_global(X), dtype=np.float64)
    return np.linalg.norm(Bn - An @ Xn) / (
        np.linalg.norm(An) * np.linalg.norm(Xn) + np.linalg.norm(Bn))


# ---------------------------------------------------------------------
# the ladder itself is pinned:
#   quant -> fast -> refine -> abft -> fp32 -> classic
# ---------------------------------------------------------------------

def test_ladder_order_pinned():
    assert LADDER_NAMES == ("quant", "fast", "refine", "abft", "fp32",
                            "classic")
    for op in ("lu", "hpd"):
        rungs = default_ladder(op)
        assert tuple(r.name for r in rungs) == LADDER_NAMES
        # 'refine' escalates WITHOUT refactorization; the rest refactor
        assert [r.refactor for r in rungs] == [True, True, False, True,
                                               True, True]
        # the abft rung (ISSUE 11) re-factors under the checksum-guarded
        # schedule: panel-granular recovery before full-ladder escalation
        ab = rungs[3]
        assert ab.config.get("abft") is True
        assert "comm_precision" not in ab.config    # attested rung
        # the quant rung (ISSUE 8) is the wire-quantized twin of 'fast':
        # int8 comm_precision, a refinement budget sized for the
        # quantization error, and NO other config difference
        q, f = rungs[0], rungs[1]
        assert q.config["comm_precision"] == "int8"
        assert {k: v for k, v in q.config.items()
                if k != "comm_precision"} == f.config
        assert q.refine >= f.refine
    # rung configs speak the tuner's knob vocabulary (ISSUE 4/6/8 reuse)
    from elemental_tpu.tune.knobs import LU_PANELS, OPS
    lu_rungs = default_ladder("lu")
    assert lu_rungs[0].config["panel"] == LU_PANELS[1]      # calu
    assert lu_rungs[-1].config["panel"] == LU_PANELS[0]     # classic
    tunable = set(OPS["lu"].knobs)
    for r in lu_rungs:
        assert set(r.config) <= tunable | {"update_precision", "precision",
                                           "lookahead", "abft"}


# ---------------------------------------------------------------------
# clean problems certify at the QUANT (int8-wire) rung, on 1x1 and 2x2
# grids -- the ISSUE 8 acceptance pin: aggressive wire precision plus the
# residual certificate yields the SAME certified tolerance
# ---------------------------------------------------------------------

@pytest.mark.parametrize("op", ["lu", "hpd"])
def test_clean_certifies_quant_2x2(grid24, op):
    rng = np.random.default_rng(91)
    An, Bn = _problem(rng, 24, op=op)
    X, info = certified_solve(op, _dist(grid24, An), _dist(grid24, Bn), nb=8)
    assert info["certified"] is True
    assert info["rung"] == "quant"
    assert info["residual"] <= info["tol"]
    assert info["failing_phase"] is None
    assert _clean_resid(An, Bn, X) <= info["tol"]
    assert np.isfinite(np.asarray(to_global(X))).all()


@pytest.mark.parametrize("op", ["lu", "hpd"])
def test_clean_certifies_1x1(op):
    import jax
    g1 = el.Grid([jax.devices()[0]])
    rng = np.random.default_rng(92)
    An, Bn = _problem(rng, 20, op=op)
    X, info = certified_solve(op, _dist(g1, An), _dist(g1, Bn), nb=8)
    # on 1x1 grids comm_precision is a no-op, so the quant rung is
    # bit-identical to 'fast' and certifies without refinement
    assert info["certified"] is True and info["rung"] == "quant"
    assert info["refine_iters"] == 0


def test_certificate_schema_pin(grid24):
    rng = np.random.default_rng(93)
    An, Bn = _problem(rng, 16)
    _, info = certified_solve("lu", _dist(grid24, An), _dist(grid24, Bn),
                              nb=8)
    assert info["schema"] == CERT_SCHEMA
    assert set(info) == {"schema", "op", "certified", "rung", "residual",
                         "tol", "refine_iters", "ladder", "attempts",
                         "singular", "timed_out", "failing_phase", "health"}
    assert info["timed_out"] is False
    assert info["ladder"] == list(LADDER_NAMES)
    att = info["attempts"][0]
    assert set(att) == {"rung", "residual", "refine_iters", "singular",
                        "diag_index", "health"}
    assert att["health"]["schema"] == "health_report/v1"
    assert info["tol"] == pytest.approx(default_tol(16, np.float64))


# ---------------------------------------------------------------------
# failure semantics: impossible tolerance, singular input
# ---------------------------------------------------------------------

def test_impossible_tol_exhausts_ladder(grid24):
    """tol=0 can never certify: the ladder runs every rung (refinement
    stalls and escalates) and the failure names 'residual' -- the
    measurement, not a health flag -- as the failing phase."""
    rng = np.random.default_rng(94)
    An, Bn = _problem(rng, 16)
    X, info = certified_solve("lu", _dist(grid24, An), _dist(grid24, Bn),
                              nb=8, tol=0.0)
    assert info["certified"] is False
    assert info["rung"] is None
    assert [a["rung"] for a in info["attempts"]] == list(LADDER_NAMES)
    assert info["failing_phase"] == "residual"
    assert info["singular"] is False
    # the solution is still returned (and is actually fine)
    assert _clean_resid(An, Bn, X) < 1e-12


def test_singular_input_structured_failure(grid24):
    rng = np.random.default_rng(95)
    F = rng.normal(size=(16, 16))
    F[11] = F[4]                         # exactly singular
    B = rng.normal(size=(16, 2))
    X, info = certified_solve("lu", _dist(grid24, F), _dist(grid24, B), nb=8)
    assert info["certified"] is False
    assert info["singular"] is True      # every FULL-WIRE factor was singular
    assert info["failing_phase"] in ("diag", "panel")
    # the quant rung's int8 wire perturbs the exact zero pivot into a
    # small nonzero one, so its diag verdict is inconclusive -- the
    # certificate's singularity attestation ignores it (and its garbage
    # solve is suppressed); every full-precision-wire rung attests
    atts = info["attempts"]
    assert [a["rung"] for a in atts] == list(info["ladder"])
    full_wire = [a for a in atts if a["rung"] != "quant"]
    assert all(a["singular"] for a in full_wire)
    assert all(a["diag_index"] is not None for a in full_wire)
    assert X is None                     # no attested non-singular factor


def test_custom_ladder_and_tol(grid24):
    """Explicit ladder + tol are honored; a single classic rung works."""
    rng = np.random.default_rng(96)
    An, Bn = _problem(rng, 16)
    ladder = (Rung("classic", {"panel": "classic",
                               "update_precision": None}, refine=2),)
    X, info = certified_solve("lu", _dist(grid24, An), _dist(grid24, Bn),
                              nb=8, ladder=ladder, tol=1e-10)
    assert info["certified"] is True
    assert info["rung"] == "classic"
    assert info["ladder"] == ["classic"]
    assert info["tol"] == 1e-10


# ---------------------------------------------------------------------
# the structured singular signal on the plain solve drivers
# ---------------------------------------------------------------------

def test_lu_solve_info_singular_pinned(grid24):
    rng = np.random.default_rng(97)
    F = rng.normal(size=(16, 16))
    F[9] = F[2]
    B = rng.normal(size=(16, 2))
    X, inf = el.lu_solve(_dist(grid24, F), _dist(grid24, B), nb=8, info=True)
    assert inf["singular"] is True
    # the zero pivot of a rank-(n-1) matrix lands on the LAST diagonal
    assert inf["diag_index"] == 15
    assert inf["finite"] is True         # the FACTOR is finite; X is not
    # and the well-posed sibling is clean
    F2 = rng.normal(size=(16, 16)) + 16 * np.eye(16)
    X2, inf2 = el.lu_solve(_dist(grid24, F2), _dist(grid24, B), nb=8,
                           info=True)
    assert inf2 == {"singular": False, "diag_index": None, "finite": True}
    assert np.isfinite(np.asarray(to_global(X2))).all()


def test_hpd_solve_info_singular(grid24):
    rng = np.random.default_rng(98)
    v = rng.normal(size=(16, 2))
    S = v @ v.T                          # rank-2 PSD: not PD
    B = rng.normal(size=(16, 2))
    X, inf = el.hpd_solve(_dist(grid24, S), _dist(grid24, B), nb=8,
                          info=True)
    assert inf["singular"] is True
    assert inf["diag_index"] is not None
    Sg = v @ v.T + 16 * np.eye(16)
    X2, inf2 = el.hpd_solve(_dist(grid24, Sg), _dist(grid24, B), nb=8,
                            info=True)
    assert inf2["singular"] is False and inf2["finite"] is True


def test_solve_info_default_unchanged(grid24):
    """info defaults off: the historical single-return contract holds."""
    rng = np.random.default_rng(99)
    An, Bn = _problem(rng, 16)
    X = el.lu_solve(_dist(grid24, An), _dist(grid24, Bn), nb=8)
    from elemental_tpu.core.distmatrix import DistMatrix
    assert isinstance(X, DistMatrix)
    Sn, _ = _problem(rng, 16, op="hpd")
    X2 = el.hpd_solve(_dist(grid24, Sn), _dist(grid24, Bn), nb=8)
    assert isinstance(X2, DistMatrix)


# ---------------------------------------------------------------------
# SATELLITE (ISSUE 9): deadline-bounded certification -- exhausted
# budget returns best-so-far with timed_out, never the silent full ladder
# ---------------------------------------------------------------------

class _Clock:
    """Manually advanced fake clock (and a per-call ticking variant)."""

    def __init__(self, tick=0.0):
        self.t = 0.0
        self.tick = tick

    def __call__(self):
        self.t += self.tick
        return self.t


def test_deadline_pre_expired_no_attempts(grid24):
    from elemental_tpu.serve import Deadline
    rng = np.random.default_rng(110)
    An, Bn = _problem(rng, 16)
    clk = _Clock()
    dl = Deadline(1.0, clock=clk)
    clk.t = 5.0
    X, info = certified_solve("lu", _dist(grid24, An), _dist(grid24, Bn),
                              nb=8, deadline=dl)
    assert info["certified"] is False
    assert info["timed_out"] is True
    assert info["attempts"] == [] and X is None
    assert info["failing_phase"] == "deadline"
    assert info["residual"] is None


def test_deadline_mid_ladder_best_so_far(grid24):
    """tol=0 would normally run EVERY rung; a deadline expiring after
    the first rung stops the ladder there, returns the best-so-far
    solution, and stamps timed_out -- strictly fewer attempts than the
    undeadlined run."""
    from elemental_tpu.serve import Deadline
    rng = np.random.default_rng(111)
    An, Bn = _problem(rng, 16)
    A, B = _dist(grid24, An), _dist(grid24, Bn)
    _, full = certified_solve("lu", A, B, nb=8, tol=0.0)
    assert [a["rung"] for a in full["attempts"]] == list(LADDER_NAMES)
    clk = _Clock(tick=0.3)               # every remaining() check costs 0.3
    dl = Deadline(1.0, clock=clk)
    X, info = certified_solve("lu", A, B, nb=8, tol=0.0, deadline=dl)
    assert info["certified"] is False and info["timed_out"] is True
    assert 0 < len(info["attempts"]) < len(LADDER_NAMES)
    assert info["failing_phase"] == "deadline"
    # best-so-far: the returned X is real and useful (tol=0 is
    # unreachable and the deadline also cut refinement short, so this is
    # the quant rung's partially-refined answer, not fp64-class)
    assert X is not None
    assert _clean_resid(An, Bn, X) < 1e-6
    assert info["residual"] == pytest.approx(
        min(a["residual"] for a in info["attempts"]
            if a["residual"] is not None))


def test_deadline_loose_budget_is_inert(grid24):
    """A generous deadline changes nothing: same rung, certified, no
    timed_out flag."""
    from elemental_tpu.serve import Deadline
    rng = np.random.default_rng(112)
    An, Bn = _problem(rng, 16)
    A, B = _dist(grid24, An), _dist(grid24, Bn)
    _, base = certified_solve("lu", A, B, nb=8)
    X, info = certified_solve("lu", A, B, nb=8,
                              deadline=Deadline(3600.0))
    assert info["certified"] is True and info["timed_out"] is False
    assert info["rung"] == base["rung"]
