"""tools/bench_diff.py regression gating (ISSUE 5): a synthetic >= 10%
cholesky TFLOP/s drop must flag (exit non-zero); in-tolerance runs pass.
The tool is stdlib-only, loaded straight from tools/."""
import importlib.util
import json
import os

import pytest

_TOOL = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                     "tools", "bench_diff.py")


@pytest.fixture(scope="module")
def bd():
    spec = importlib.util.spec_from_file_location("bench_diff", _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, name, value, vs_baseline, lu_value=5.0,
           lu_vs_baseline=0.35, wrapped=True):
    doc = {"metric": "cholesky_n32768_tflops_per_chip", "value": value,
           "unit": "TFLOP/s", "vs_baseline": vs_baseline,
           "lu_value": lu_value, "lu_vs_baseline": lu_vs_baseline}
    if wrapped:
        doc = {"n": 1, "cmd": "python bench.py", "rc": 0, "parsed": doc}
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


def test_flags_synthetic_cholesky_regression(bd, tmp_path, capsys):
    """>= 10% drop in cholesky TFLOP/s (and its roofline-normalized
    ratio) vs the trajectory best -> exit 1, named in the output."""
    _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.70)
    cur = _write(tmp_path, "BENCH_r02.json", value=8.9, vs_baseline=0.62)
    assert bd.main(["--check", cur]) == 1
    out = capsys.readouterr().out
    assert "vs_baseline" in out and "REGRESSION" in out
    # the raw-TFLOP/s metric gates the same synthetic drop explicitly
    assert bd.main(["--check", cur, "--metric", "value"]) == 1
    out = capsys.readouterr().out
    assert "value" in out and "REGRESSION" in out


def test_within_threshold_passes(bd, tmp_path, capsys):
    _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.70)
    cur = _write(tmp_path, "BENCH_r02.json", value=9.5, vs_baseline=0.665)
    assert bd.main(["--check", cur]) == 0
    assert "REGRESSION" not in capsys.readouterr().out


def test_gate_compares_against_trajectory_best(bd, tmp_path):
    """A slow decay cannot ratchet the bar down: the gate uses the BEST
    baseline in the trajectory, not the latest."""
    _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.75)
    _write(tmp_path, "BENCH_r02.json", value=9.3, vs_baseline=0.70)
    # within 10% of r02, but 10.7% below r01's best
    cur = _write(tmp_path, "BENCH_r03.json", value=8.93, vs_baseline=0.67)
    assert bd.main(["--check", cur]) == 1


def test_threshold_flags_global_and_per_metric(bd, tmp_path):
    _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.70,
           lu_vs_baseline=0.40)
    cur = _write(tmp_path, "BENCH_r02.json", value=8.9, vs_baseline=0.62,
                 lu_vs_baseline=0.39)
    # loosening the global threshold passes the same drop
    assert bd.main(["--check", cur, "--threshold", "0.20"]) == 0
    # per-metric override: only lu gets the tight threshold -> its 2.5%
    # drop passes, cholesky's 11% drop still fails under the default
    assert bd.main(["--check", cur,
                    "--threshold", "lu_vs_baseline=0.01"]) == 1
    assert bd.main(["--check", cur, "--threshold", "0.20",
                    "--threshold", "lu_vs_baseline=0.01"]) == 1


def test_explicit_current_vs_baselines(bd, tmp_path):
    base = _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.70)
    cur = _write(tmp_path, "current.json", value=6.0, vs_baseline=0.45,
                 wrapped=False)                 # raw bench.py line form
    assert bd.main([cur, base]) == 1
    assert bd.main([base, base]) == 0


def test_no_baselines_or_metrics_is_not_an_error(bd, tmp_path, capsys):
    cur = _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.70)
    assert bd.main(["--check", cur]) == 0       # nothing earlier to gate
    assert "no baselines" in capsys.readouterr().out
    _write(tmp_path, "BENCH_r00.json", value=1.0, vs_baseline=0.1)
    # metrics absent on both sides are skipped with a note, not a crash
    assert bd.main(["--check", cur, "--metric", "does_not_exist"]) == 0
    assert "no comparable metrics" in capsys.readouterr().out


def test_repo_trajectory_gates_clean(bd):
    """The real recorded trajectory must pass its own gate (this is the
    same invocation tools/check.sh runs)."""
    repo = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir)
    cur = os.path.join(repo, "BENCH_r05.json")
    assert bd.main(["--check", cur]) == 0


def test_obs_wire_bytes_key_accepted_not_gated(bd, tmp_path, capsys):
    """ISSUE 8: a current doc carrying the new obs.redist_wire_bytes
    total (and a comm_precision tuner provenance field) passes the gate
    against baselines that predate the key -- surfaced as an
    informational line, never a regression (the rename guard stays
    false-positive-free)."""
    _write(tmp_path, "BENCH_r01.json", value=10.0, vs_baseline=0.70)
    doc = {"metric": "cholesky_n32768_tflops_per_chip", "value": 10.0,
           "unit": "TFLOP/s", "vs_baseline": 0.70, "lu_value": 5.0,
           "lu_vs_baseline": 0.35,
           "tuner": {"ran_with": {"nb": 2048, "comm_precision": None},
                     "lu": {"config": {"comm_precision": "bf16"},
                            "source": "cost_model"}},
           "obs": {"schema": "obs_bench/v1", "redist_bytes": 1000,
                   "redist_wire_bytes": 500}}
    path = tmp_path / "BENCH_r02.json"
    path.write_text(json.dumps({"parsed": doc}))
    assert bd.main(["--check", str(path)]) == 0
    out = capsys.readouterr().out
    assert "redist_wire_bytes: 500" in out and "2.00x" in out
    assert "REGRESSION" not in out
