"""Chrome-trace/Perfetto export schema pins + the perf.trace CLI
(ISSUE 5).  The trace document must stay loadable by Perfetto: JSON
object format, ``traceEvents`` with micros timestamps, complete ("X")
and instant ("i") events, thread-name metadata per lane."""
import json

import pytest

from elemental_tpu import obs


class FakeClock:
    def __init__(self, step=1.0):
        self.t = 0.0
        self.step = step

    def __call__(self):
        self.t += self.step
        return self.t


def _traced_run():
    tr = obs.Tracer(metrics=False, clock=FakeClock())
    with tr.span("run", driver="lu", n=64):
        ch = tr.channel("lu")
        ch.start()
        ch.tick("panel", 0)
        ch.tick("swap", 0)
        ch.tick("update", 0)
        ch.tick("panel", 1)
        from elemental_tpu.core.dist import MC, MR, STAR
        from elemental_tpu.redist.engine import RedistRecord
        tr._on_redist(RedistRecord(
            kind="redistribute", src=(MC, MR), dst=(STAR, STAR),
            gshape=(64, 64), dtype="float32", in_id=1, out_ids=(2,),
            grid_shape=(2, 2)))
    return tr


def test_chrome_trace_schema_pin():
    tr = _traced_run()
    doc = obs.chrome_trace_doc(tr, driver="lu", n=64)
    json.loads(json.dumps(doc))                     # round-trippable
    assert set(doc) == {"schema", "traceEvents", "displayTimeUnit",
                        "otherData"}
    assert doc["schema"] == obs.CHROME_SCHEMA == "obs_chrome_trace/v1"
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"] == {"driver": "lu", "n": 64}
    phs = {ev["ph"] for ev in doc["traceEvents"]}
    assert phs == {"M", "X", "i"}
    for ev in doc["traceEvents"]:
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str)
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0
        if ev["ph"] == "i":
            assert ev["s"] == "t"
            assert {"kind", "gshape", "dtype", "bytes"} <= set(ev["args"])


def test_chrome_trace_one_track_per_phase_lane():
    tr = _traced_run()
    doc = obs.chrome_trace_doc(tr)
    names = {ev["tid"]: ev["args"]["name"] for ev in doc["traceEvents"]
             if ev["ph"] == "M" and ev["name"] == "thread_name"}
    lanes = set(names.values())
    assert {"drivers", "steps", "phase:panel", "phase:swap", "phase:update",
            "collectives"} == lanes
    # canonical phase ordering: panel lane before swap before update
    by_name = {v: k for k, v in names.items()}
    assert by_name["phase:panel"] < by_name["phase:swap"] \
        < by_name["phase:update"]
    # each phase record landed on its own lane
    for ev in doc["traceEvents"]:
        if ev["ph"] == "X" and ev["name"] in ("panel", "swap", "update"):
            assert names[ev["tid"]] == f"phase:{ev['name']}"
    # synthesized driver span + explicit run span share the driver track
    driver_rows = [ev for ev in doc["traceEvents"]
                   if ev["ph"] == "X" and names[ev["tid"]] == "drivers"]
    assert {ev["name"] for ev in driver_rows} == {"lu", "run"}
    # per-step spans cover their phases
    steps = [ev for ev in doc["traceEvents"]
             if ev["ph"] == "X" and names[ev["tid"]] == "steps"]
    assert {ev["name"] for ev in steps} == {"lu[0]", "lu[1]"}


def test_phase_timings_to_chrome():
    ph = {"schema": "phase_timings/v1",
          "steps": [{"step": 0, "panel": 0.25, "update": 0.75},
                    {"step": 1, "panel": 0.5}],
          "totals": {"panel": 0.75, "update": 0.75},
          "total_seconds": 1.5, "driver": "cholesky", "n": 64, "nb": 16}
    doc = obs.phase_timings_to_chrome(ph)
    assert doc["schema"] == obs.CHROME_SCHEMA
    assert doc["otherData"]["synthesized"] is True
    assert doc["otherData"]["driver"] == "cholesky"
    xs = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    # sequential layout: step-0 panel at 0, update right after, step-1 next
    phase_rows = [ev for ev in xs if ev["name"] in ("panel", "update")]
    assert [(ev["ts"], ev["dur"]) for ev in phase_rows] == \
        [(0.0, 0.25e6), (0.25e6, 0.75e6), (1e6, 0.5e6)]
    driver_row = [ev for ev in xs if ev["name"] == "cholesky"]
    assert driver_row and driver_row[0]["dur"] == 1.5e6


def test_phase_timings_to_chrome_rejects_wrong_schema():
    with pytest.raises(ValueError):
        obs.phase_timings_to_chrome({"schema": "comm_plan/v1"})


# ---------------------------------------------------------------------
# perf.trace CLI (CPU-safe smoke; check.sh runs the same in-process)
# ---------------------------------------------------------------------

def test_perf_trace_run_summary_export(tmp_path, capsys):
    from perf import trace as trace_cli
    out = tmp_path / "trace.json"
    mout = tmp_path / "metrics.json"
    rc = trace_cli.cmd_run("cholesky", 64, 16, "1x1", "float32", "auto",
                           True, None, str(out), str(mout))
    assert rc == 0
    stdout = capsys.readouterr().out
    mdoc = json.loads(stdout.strip().splitlines()[-1])
    assert mdoc["schema"] == "obs_metrics/v1"
    ops = {c["labels"]["op"]: c["value"] for c in mdoc["counters"]
           if c["name"] == "op_calls"}
    assert ops.get("cholesky") == 1
    tdoc = json.loads(out.read_text())
    assert tdoc["schema"] == obs.CHROME_SCHEMA
    assert any(ev.get("ph") == "X" for ev in tdoc["traceEvents"])
    assert json.loads(mout.read_text())["schema"] == "obs_metrics/v1"
    # summary reads the written trace back
    assert trace_cli.cmd_summary(str(out)) == 0
    summary = capsys.readouterr().out
    assert "phase:" in summary and "drivers" in summary
    # export converts a phase_timings doc into the same trace format
    ph = tmp_path / "phases.json"
    ph.write_text(json.dumps({
        "schema": "phase_timings/v1", "driver": "lu",
        "steps": [{"step": 0, "panel": 0.1}], "totals": {"panel": 0.1},
        "total_seconds": 0.1}))
    out2 = tmp_path / "trace2.json"
    assert trace_cli.cmd_export(str(ph), str(out2)) == 0
    assert json.loads(out2.read_text())["schema"] == obs.CHROME_SCHEMA
