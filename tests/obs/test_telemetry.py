"""ISSUE 20 telemetry units: request lifecycle traces, windowed SLO
estimators, the fault-triggered flight recorder, per-family histogram
ladders, and the registry/tracer thread-safety hammers."""
import json
import sys
import threading

import pytest

from elemental_tpu.obs import Tracer, chrome_trace_doc
from elemental_tpu.obs import metrics as _metrics
from elemental_tpu.obs.flight import FlightRecorder
from elemental_tpu.obs.lifecycle import (EDGES, RequestTrace,
                                         check_timeline)
from elemental_tpu.obs.slo import SLOMonitor, SLOTarget


class StepClock:
    """Deterministic clock: every read advances by ``dt``."""

    def __init__(self, t=0.0, dt=1.0):
        self.t, self.dt = float(t), float(dt)

    def __call__(self):
        self.t += self.dt
        return self.t


# ---------------------------------------------------------------------
# RequestTrace + check_timeline
# ---------------------------------------------------------------------

def test_trace_marks_render_stable_doc():
    tr = RequestTrace(id="r1", clock=StepClock(), tenant="acme", op="hpd")
    tr.annotate(grid="g0", bucket=(16, 2))
    for e in ("submitted", "tenant_queued", "admitted", "staged",
              "dispatched", "collected", "certified", "done"):
        assert e in EDGES
        tr.mark(e)
    doc = tr.to_doc()
    assert doc["schema"] == "serve_timeline/v1"
    assert (doc["id"], doc["tenant"], doc["grid"]) == ("r1", "acme", "g0")
    assert doc["bucket"] == [16, 2]
    rows = doc["edges"]
    assert [r["edge"] for r in rows][0] == "submitted"
    assert rows[0]["dt"] == 0.0
    ts = [r["t"] for r in rows]
    assert ts == sorted(ts)
    # dt is relative to the first mark, in clock units
    assert rows[-1]["dt"] == pytest.approx(ts[-1] - ts[0])
    assert check_timeline(doc, path="fastpath", fleet=True) == []
    # the doc is JSON-serializable as-is
    json.dumps(doc)


def test_trace_annotate_none_is_noop_and_attrs_survive():
    tr = RequestTrace(clock=StepClock(), tenant="t0")
    tr.annotate(grid=None, tenant=None, bucket=None)
    assert tr.tenant == "t0" and tr.grid is None
    tr.mark("submitted", op="hpd")
    tr.mark("shed", reason="quota")
    edges = tr.edges()
    assert edges[1][0] == "shed" and edges[1][2] == {"reason": "quota"}
    assert tr.edge_t("shed") == edges[1][1]
    assert tr.edge_t("done") is None


@pytest.mark.parametrize("rows,kw,frag", [
    # wrong first edge
    ([("admitted", 1.0), ("done", 2.0)], {}, "not 'submitted'"),
    # non-terminal tail
    ([("submitted", 1.0), ("admitted", 2.0)], {}, "terminal edge"),
    # clock ran backwards
    ([("submitted", 2.0), ("admitted", 1.0), ("done", 3.0)], {},
     "not monotone"),
    # ok path missing admission
    ([("submitted", 1.0), ("done", 2.0)], {}, "missing required edge"),
    # reject without a shed attribution
    ([("submitted", 1.0), ("rejected", 2.0)], {}, "without a 'shed'"),
    # fleet timelines must cross the tenant lane
    ([("submitted", 1.0), ("admitted", 2.0), ("done", 3.0)],
     {"fleet": True}, "tenant_queued"),
    # fastpath implies the batch edges
    ([("submitted", 1.0), ("admitted", 2.0), ("done", 3.0)],
     {"path": "fastpath"}, "fastpath missing edge"),
    # escalated/grid paths imply the escalation edge
    ([("submitted", 1.0), ("admitted", 2.0), ("done", 3.0)],
     {"path": "escalated"}, "missing 'escalated'"),
])
def test_check_timeline_catches(rows, kw, frag):
    doc = {"schema": "serve_timeline/v1",
           "edges": [{"edge": e, "t": t} for e, t in rows]}
    problems = check_timeline(doc, **kw)
    assert any(frag in p for p in problems), problems


def test_check_timeline_rejects_foreign_docs():
    assert check_timeline(None) != []
    assert check_timeline({"schema": "serve_result/v1"}) != []
    assert check_timeline({"schema": "serve_timeline/v1", "edges": []}) \
        == ["timeline has no edges"]


def test_trace_mirrors_flight_and_active_tracer():
    clk = StepClock()
    fl = FlightRecorder(clock=clk)
    tr = RequestTrace(id="f7", clock=clk, tenant="acme", flight=fl)
    tracer = Tracer(metrics=False, clock=clk)
    with tracer:
        tr.mark("submitted", op="hpd")
        # a mark's own attr must win over stale attribution (regression:
        # duplicate-kwarg crash when both supplied ``grid``)
        tr.mark("admitted", grid="g1")
    ev = fl.events()
    assert [e["kind"] for e in ev] == ["edge:submitted", "edge:admitted"]
    assert ev[0]["id"] == "f7" and ev[0]["tenant"] == "acme"
    assert ev[1]["grid"] == "g1"
    names = [i.name for i in tracer.instants]
    assert names == ["lifecycle:submitted", "lifecycle:admitted"]
    assert all(i.attrs["flow"] == "f7" for i in tracer.instants)


def test_trace_silent_without_tracer_or_flight():
    tr = RequestTrace(clock=StepClock())
    tr.mark("submitted")       # no active tracer, no flight: no crash
    assert len(tr.edges()) == 1


# ---------------------------------------------------------------------
# SLOMonitor
# ---------------------------------------------------------------------

def _ok(lat_s, tenant="t0", grid="g0", bucket="16x2", status="ok"):
    return {"status": status, "latency_s": lat_s, "tenant": tenant,
            "grid": grid, "bucket": bucket}


def _shed(tenant="t0", grid="g0", bucket="16x2"):
    return {"reason": "quota", "tenant": tenant, "grid": grid,
            "bucket": bucket}


def test_slo_percentiles_nearest_rank():
    mon = SLOMonitor(window=64)
    for ms in range(1, 101):               # 1..100 ms
        mon.record(_ok(ms / 1e3))
    # window=64 keeps the LAST 64 outcomes: 37..100 ms
    doc = mon.snapshot(gauges=False, source="test")
    assert doc["schema"] == "serve_slo/v1" and doc["window"] == 64
    assert doc["source"] == "test"
    (row,) = doc["series"]
    assert row["count"] == 64 and row["sheds"] == 0
    assert row["p50_ms"] == pytest.approx(68.0)
    assert row["p99_ms"] == pytest.approx(100.0)
    assert mon.worst_p99_ms() == pytest.approx(100.0)


def test_slo_burn_rates_and_budgets():
    tgt = SLOTarget(p99_ms=50.0, latency_objective=0.9,
                    error_budget=0.1, shed_budget=0.5)
    mon = SLOMonitor(window=16, targets={"acme": tgt})
    for _ in range(6):
        mon.record(_ok(0.010, tenant="acme"))       # under target
    for _ in range(2):
        mon.record(_ok(0.100, tenant="acme"))       # over 50 ms
    mon.record(_ok(0.010, tenant="acme", status="failed"))
    mon.record(_shed(tenant="acme"))
    (row,) = mon.snapshot(gauges=False)["series"]
    assert row["target"]["p99_ms"] == 50.0
    # 2 of 9 latencies over target, objective allows 10% -> burn 20/9
    assert row["burn"]["latency"] == pytest.approx((2 / 9) / 0.1)
    # 1 failed of 9 completions against a 10% budget
    assert row["error_rate"] == pytest.approx(1 / 9)
    assert row["burn"]["error"] == pytest.approx((1 / 9) / 0.1)
    # 1 shed of 10 outcomes against a 50% budget
    assert row["shed_rate"] == pytest.approx(0.1)
    assert row["burn"]["shed"] == pytest.approx(0.2)


def test_slo_series_keyed_and_sorted_per_tenant_grid_bucket():
    mon = SLOMonitor()
    mon.record(_ok(0.002, tenant="b", grid="g1", bucket="32x2"))
    mon.record(_ok(0.001, tenant="a", grid="g0"))
    mon.record(_shed(tenant="a", grid="g1"))
    rows = mon.snapshot(gauges=False)["series"]
    keys = [(r["tenant"], r["grid"], r["bucket"]) for r in rows]
    assert keys == sorted(keys) and len(keys) == 3
    per = mon.per_tenant_p99_ms()
    assert per == {"a": pytest.approx(1.0), "b": pytest.approx(2.0)}
    assert mon.worst_p99_ms() == pytest.approx(2.0)


def test_slo_gauges_mirrored_to_scoped_registry():
    mon = SLOMonitor()
    mon.record(_ok(0.004, tenant="acme"))
    with _metrics.scoped() as reg:
        mon.snapshot(gauges=True)
        gauges = {r["name"] for r in reg.to_doc()["gauges"]}
    assert {"serve_slo_p99_ms", "serve_slo_burn_latency",
            "serve_slo_burn_error", "serve_slo_burn_shed"} <= gauges


def test_slo_rejects_degenerate_window():
    with pytest.raises(ValueError):
        SLOMonitor(window=0)


# ---------------------------------------------------------------------
# FlightRecorder
# ---------------------------------------------------------------------

def test_flight_ring_bounds_and_dump_accounting():
    clk = StepClock()
    fl = FlightRecorder(capacity=4, clock=clk)
    for i in range(10):
        fl.record("edge:submitted", id=i)
    assert len(fl) == 4
    doc = fl.trigger("manual", source="test")
    assert doc["schema"] == "flight_record/v1"
    assert doc["capacity"] == 4 and doc["recorded"] == 10
    assert doc["dropped"] == 6
    assert [e["id"] for e in doc["events"]] == [6, 7, 8, 9]
    assert [e["seq"] for e in doc["events"]] == [7, 8, 9, 10]
    assert doc["trigger"]["reason"] == "manual"
    assert fl.last_dump() is doc


def test_flight_quota_storm_needs_consecutive_rejects():
    dumped = []
    fl = FlightRecorder(clock=StepClock(), quota_storm_threshold=3,
                        on_dump=dumped.append)
    fl.record("reject", reason="quota")
    fl.record("reject", reason="quota")
    fl.record("reject", reason="shutdown")   # breaks the run
    fl.record("reject", reason="quota")
    fl.record("reject", reason="quota")
    assert not fl.dumps
    fl.record("reject", reason="quota")      # third consecutive: storm
    assert [d["trigger"]["reason"] for d in fl.dumps] == ["quota_storm"]
    assert fl.dumps[0]["trigger"]["rejects"] == 3
    assert dumped == fl.dumps
    # lifecycle-edge mirrors must NOT arm the detector
    fl2 = FlightRecorder(clock=StepClock(), quota_storm_threshold=2)
    for _ in range(5):
        fl2.record("edge:shed", reason="quota")
    assert not fl2.dumps


def test_flight_dump_bit_identical_under_virtual_clock():
    def run():
        fl = FlightRecorder(capacity=8, clock=StepClock())
        for i in range(12):
            fl.record("edge:admitted", id=f"f{i}", grid="g0")
        return fl.trigger("chaos_fault", source="replay")

    assert json.dumps(run(), sort_keys=True) \
        == json.dumps(run(), sort_keys=True)


def test_flight_unknown_trigger_reason_still_dumps():
    fl = FlightRecorder(clock=StepClock())
    doc = fl.trigger("novel_reason")
    assert doc["trigger"]["reason"] == "novel_reason"
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


# ---------------------------------------------------------------------
# per-family histogram ladders (ISSUE 20 satellite)
# ---------------------------------------------------------------------

def test_histogram_family_resolution():
    assert _metrics.hist_family("phase_seconds") == "seconds"
    assert _metrics.hist_family("redist_event_bytes") == "bytes"
    assert _metrics.hist_family("batch_count") == "count"
    assert _metrics.hist_family("op_calls") == "count"


def test_histogram_families_use_their_ladders():
    reg = _metrics.MetricsRegistry()
    reg.observe("stage_seconds", 0.02)
    reg.observe("payload_bytes", 5000.0)
    reg.observe("batch_count", 3.0)
    reg.observe("odd_name", 7.0, family="count")   # explicit override
    hists = {h["name"]: h for h in reg.to_doc()["histograms"]}
    assert hists["stage_seconds"]["family"] == "seconds"
    assert hists["payload_bytes"]["family"] == "bytes"
    assert hists["batch_count"]["family"] == "count"
    assert hists["odd_name"]["family"] == "count"
    # a 5000-byte observation lands in the 65536 bucket of the byte
    # ladder instead of saturating the seconds ladder's top bucket
    ladder = [b["le"] for b in hists["payload_bytes"]["buckets"]]
    assert ladder[:3] == [256, 4096, 65536]
    by_le = {b["le"]: b["count"] for b in hists["payload_bytes"]["buckets"]}
    assert by_le[4096] == 0 and by_le[65536] == 1


def test_set_hist_family_pins_and_validates():
    name = "telemetry_test_seconds"      # suffix says seconds...
    _metrics.set_hist_family(name, "bytes")
    try:
        assert _metrics.hist_family(name) == "bytes"
    finally:
        _metrics._FAMILY_OVERRIDES.pop(name, None)
    with pytest.raises(ValueError):
        _metrics.set_hist_family("x", "fortnights")


# ---------------------------------------------------------------------
# thread-safety hammers (ISSUE 20 satellite: these fail without the
# registry/tracer locks -- every update is a read-modify-write)
# ---------------------------------------------------------------------

@pytest.fixture
def aggressive_switching():
    """Shrink the GIL switch interval so lost updates surface reliably."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        yield
    finally:
        sys.setswitchinterval(prev)


def _hammer(fn, nthreads=8):
    start = threading.Barrier(nthreads)

    def body():
        start.wait()
        fn()

    ts = [threading.Thread(target=body) for _ in range(nthreads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_metrics_registry_hammer(aggressive_switching):
    reg = _metrics.MetricsRegistry()
    iters, nthreads = 3000, 8

    def body():
        for i in range(iters):
            reg.inc("hits", op="hpd")
            reg.observe("lat_seconds", 0.001)

    _hammer(body, nthreads)
    total = iters * nthreads
    assert reg.counter_value("hits", op="hpd") == total
    (h,) = reg.to_doc()["histograms"]
    assert h["count"] == total
    assert h["sum"] == pytest.approx(0.001 * total)
    assert h["buckets"][-1]["count"] == total


def test_tracer_hammer_unique_calls_and_no_lost_records(
        aggressive_switching):
    tracer = Tracer(metrics=False)
    iters, nthreads = 400, 8

    def body():
        for i in range(iters):
            ch = tracer.channel("lu")
            ch.start()
            ch.tick("panel", i)
            with tracer.span("work", i=i):
                tracer.instant("health:ok", i=i)

    _hammer(body, nthreads)
    total = iters * nthreads
    # channel ids are allocated under the lock: all distinct, none lost
    calls = [r.call for r in tracer.phases]
    assert len(calls) == total and len(set(calls)) == total
    assert len(tracer.spans) == total
    assert len(tracer.instants) == total
    # nesting state is thread-local: concurrent spans never stack
    assert {s.depth for s in tracer.spans} == {0}
    assert len({s.thread for s in tracer.spans}) == nthreads


def test_request_trace_hammer_keeps_every_mark(aggressive_switching):
    clk = StepClock()
    fl = FlightRecorder(capacity=100_000, clock=clk)
    tr = RequestTrace(clock=clk, flight=fl)
    iters, nthreads = 500, 8
    _hammer(lambda: [tr.mark("staged") for _ in range(iters)], nthreads)
    assert len(tr.edges()) == iters * nthreads
    assert len(fl) == iters * nthreads
    seqs = [e["seq"] for e in fl.events()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


# ---------------------------------------------------------------------
# exporter: per-thread tracks + flow events
# ---------------------------------------------------------------------

def test_export_threads_get_own_tracks_and_flow_chain():
    clk = StepClock(dt=0.001)
    tracer = Tracer(metrics=False, clock=clk)

    def worker(tag):
        with tracer.span(f"serve:{tag}"):
            tracer.instant("lifecycle:admitted", flow="f0", grid=tag)

    with tracer.span("serve:fleet"):
        tracer.instant("lifecycle:submitted", flow="f0")
        for tag in ("w0", "w1"):
            t = threading.Thread(
                target=worker, args=(tag,),
                name=f"elemental-serve-worker:{tag}")
            t.start()
            t.join()
        tracer.instant("lifecycle:done", flow="f0")
        tracer.instant("health:flag")          # flowless: never linked

    doc = chrome_trace_doc(tracer, mode="test")
    evs = doc["traceEvents"]
    tracks = {e["args"]["name"]: e["tid"] for e in evs
              if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "elemental-serve-worker:w0" in tracks
    assert "elemental-serve-worker:w1" in tracks
    assert tracks["elemental-serve-worker:w0"] \
        != tracks["elemental-serve-worker:w1"]
    # each worker's span rides ITS track, not the home thread's
    spans = {e["name"]: e["tid"] for e in evs if e["ph"] == "X"}
    assert spans["serve:w0"] == tracks["elemental-serve-worker:w0"]
    assert spans["serve:w1"] == tracks["elemental-serve-worker:w1"]

    flow = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flow] == ["s", "t", "t", "f"]
    assert all(e["name"] == "serve:req" and e["cat"] == "lifecycle"
               and e["id"] == "f0" for e in flow)
    ts = [e["ts"] for e in flow]
    assert ts == sorted(ts)
    # the middle hops land on the workers' event tracks: Perfetto draws
    # arrows crossing track groups, the acceptance criterion
    assert {flow[1]["tid"], flow[2]["tid"]} \
        == {tracks["elemental-serve-worker:w0 events"],
            tracks["elemental-serve-worker:w1 events"]}
    assert flow[0]["tid"] == flow[3]["tid"]    # submit/done: home events


def test_export_single_instant_flow_not_linked():
    tracer = Tracer(metrics=False, clock=StepClock())
    tracer.instant("lifecycle:submitted", flow="lonely")
    evs = chrome_trace_doc(tracer)["traceEvents"]
    assert not [e for e in evs if e["ph"] in ("s", "t", "f")]
