"""Metrics registry behavior + the ``obs_metrics/v1`` schema pin, and the
tuning-cache hit/miss/stale counters (ISSUE 5 satellite)."""
import json
import os

import pytest

from elemental_tpu.obs import metrics as m


# ---------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------

def test_counters_gauges_histograms():
    reg = m.MetricsRegistry()
    reg.inc("op_calls", op="lu")
    reg.inc("op_calls", op="lu")
    reg.inc("op_calls", op="qr")
    reg.inc("redist_bytes", 100, label="x")
    reg.set_gauge("cache_entries", 3)
    reg.set_gauge("cache_entries", 5)
    reg.observe("phase_seconds", 0.5, driver="lu", phase="panel")
    reg.observe("phase_seconds", 1.5, driver="lu", phase="panel")
    assert reg.counter_value("op_calls", op="lu") == 2
    assert reg.counter_value("op_calls", op="qr") == 1
    assert reg.counter_value("op_calls", op="absent") == 0
    assert reg.counter_value("redist_bytes", label="x") == 100
    doc = reg.to_doc()
    gauges = {g["name"]: g["value"] for g in doc["gauges"]}
    assert gauges == {"cache_entries": 5}           # gauge = last write
    (h,) = doc["histograms"]
    assert h["count"] == 2 and h["sum"] == 2.0
    assert h["min"] == 0.5 and h["max"] == 1.5 and h["mean"] == 1.0
    assert h["labels"] == {"driver": "lu", "phase": "panel"}
    # cumulative buckets end at +Inf with the full count
    assert h["buckets"][-1] == {"le": "+Inf", "count": 2}
    by_le = {b["le"]: b["count"] for b in h["buckets"]}
    assert by_le[1.0] == 1 and by_le[10.0] == 2


def test_schema_pin_round_trip():
    reg = m.MetricsRegistry()
    reg.inc("op_calls", op="gemm")
    reg.observe("phase_seconds", 1e-7, driver="gemm", phase="panel")
    doc = json.loads(reg.to_json(run="r6"))
    assert doc["schema"] == m.SCHEMA == "obs_metrics/v1"
    assert set(doc) == {"schema", "counters", "gauges", "histograms", "run"}
    for row in doc["counters"] + doc["gauges"]:
        assert set(row) == {"name", "labels", "value"}
    for h in doc["histograms"]:
        assert {"name", "labels", "count", "sum", "min", "max", "mean",
                "buckets"} <= set(h)
        for b in h["buckets"]:
            assert set(b) == {"le", "count"}
    # sub-1us observation lands in the first bucket
    assert doc["histograms"][0]["buckets"][0]["count"] == 1


def test_scoped_isolation():
    m.inc("outer_counter", outer=True)
    with m.scoped() as reg:
        m.inc("inner_counter")
        assert m.current() is reg
        assert reg.counter_value("inner_counter") == 1
        assert reg.counter_value("outer_counter", outer=True) == 0
    assert m.current().counter_value("inner_counter") == 0


def test_label_coercion_keeps_json_safe():
    reg = m.MetricsRegistry()
    reg.inc("c", label=(1, 2))              # non-scalar label -> str()
    doc = reg.to_doc()
    json.dumps(doc)
    assert doc["counters"][0]["labels"] == {"label": "(1, 2)"}


# ---------------------------------------------------------------------
# tune-cache events (satellite: visibility for silently rejected files)
# ---------------------------------------------------------------------

@pytest.fixture
def cache_env(tmp_path, monkeypatch):
    from elemental_tpu.tune import cache as tc
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    from elemental_tpu.tune.policy import clear_memo
    clear_memo()
    yield tmp_path
    clear_memo()


def _key():
    from elemental_tpu.tune import cache as tc
    return tc.make_key("cholesky", (4096, 4096), "float32", (2, 2), "cpu")


def test_cache_load_counts_hit_miss(cache_env):
    from elemental_tpu.tune import cache as tc
    key = _key()
    with m.scoped() as reg:
        assert tc.load(key) is None
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="miss") == 1
        tc.save(key, {"nb": 512})
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="write") == 1
        assert tc.load(key) is not None
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="hit") == 1


def test_cache_load_counts_stale_schema_and_mismatch(cache_env):
    from elemental_tpu.tune import cache as tc
    key = _key()
    path = key.path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with m.scoped() as reg:
        with open(path, "w") as f:
            json.dump({"schema": "tuning_cache/v0", "config": {"nb": 1}}, f)
        assert tc.load(key) is None
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="stale_schema") == 1
        with open(path, "w") as f:
            json.dump({"schema": tc.SCHEMA, "op": "lu",
                       "bucket": [4096, 4096], "dtype": "float32",
                       "grid": [2, 2], "backend": "cpu",
                       "config": {"nb": 1}}, f)
        assert tc.load(key) is None
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="key_mismatch") == 1
        with open(path, "w") as f:
            f.write("{torn json")
        assert tc.load(key) is None
        assert reg.counter_value("tune_cache_events", op="cholesky",
                                 event="unparsable") == 1


def test_cache_scan_reports_rejects(cache_env):
    from elemental_tpu.tune import cache as tc
    tc.save(_key(), {"nb": 512})
    with open(os.path.join(cache_env, "lu__stale.json"), "w") as f:
        json.dump({"schema": "tuning_cache/v0"}, f)
    with open(os.path.join(cache_env, "qr__torn.json"), "w") as f:
        f.write("{")
    with m.scoped() as reg:
        docs, rejects = tc.scan()
        assert [d["op"] for d in docs] == ["cholesky"]
        assert {(r["file"], r["reason"]) for r in rejects} == {
            ("lu__stale.json", "stale_schema"), ("qr__torn.json", "unparsable")}
        assert reg.counter_value("tune_cache_events", op="lu",
                                 event="stale_schema") == 1
        assert reg.counter_value("tune_cache_events", op="qr",
                                 event="unparsable") == 1
    # entries() keeps its historical valid-only contract
    assert [d["op"] for d in tc.entries()] == ["cholesky"]


def test_tune_show_surfaces_invalid_files(cache_env, capsys):
    """`python -m perf.tune show` prints INVALID rows for rejected files
    (previously: silent) plus the process event counters."""
    from elemental_tpu.tune import cache as tc
    from perf.tune import cmd_show
    tc.save(_key(), {"nb": 512})
    with open(os.path.join(cache_env, "lu__stale.json"), "w") as f:
        json.dump({"schema": "tuning_cache/v0"}, f)
    with m.scoped():
        assert cmd_show(None) == 0
    out = capsys.readouterr().out
    assert "1 invalid" in out
    assert "INVALID lu__stale.json" in out and "stale_schema" in out
    assert "tune_cache_events (this process):" in out
    # filtered view keeps the reject visible only for its own op
    with m.scoped():
        cmd_show("lu")
    out = capsys.readouterr().out
    assert "INVALID lu__stale.json" in out
    with m.scoped():
        cmd_show("cholesky")
    out = capsys.readouterr().out
    assert "INVALID" not in out
