"""Runtime-vs-static cross-check (ISSUE 5): the redistribute counts the
span tracer records during a REAL eager run must equal the golden
``comm_plan/v1`` ``redistributes`` tables that the abstract jaxpr-level
analyzer pinned (tests/golden/comm_plans/).

Both sides count Python-level public-entry calls into the redistribution
engine, so an eager execution and a ``make_jaxpr`` trace of the same
driver at the same geometry must agree exactly -- if they ever diverge,
either the runtime observer or the static analyzer is lying about the
communication schedule.  Geometry matches the goldens: n=64, nb=16,
float32, 1x1 and 2x2 grids, same variant knobs as
``analysis.drivers.DRIVERS``.
"""
import json
import os

import numpy as np
import pytest

import jax

import elemental_tpu as el
from elemental_tpu import obs

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "golden",
                          "comm_plans")
N, NB = 64, 16


def _golden(driver: str, rc: tuple) -> dict:
    path = os.path.join(GOLDEN_DIR, f"{driver}__{rc[0]}x{rc[1]}.json")
    with open(path) as f:
        return json.load(f)


@pytest.fixture(scope="module", params=[(1, 1), (2, 2)],
                ids=lambda rc: f"grid{rc[0]}x{rc[1]}")
def rc_grid(request):
    r, c = request.param
    return (r, c), el.Grid(jax.devices()[: r * c], height=r)


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    G = rng.normal(size=(n, n))
    return G @ G.T / n + n * np.eye(n)


def _traced(fn, *outs_of):
    """Run ``fn`` eagerly under a fresh active tracer; return its
    redistribute label counts."""
    tr = obs.Tracer(metrics=False)
    with obs.metrics_scope():
        with tr:
            out = fn()
            jax.block_until_ready(jax.tree_util.tree_leaves(out))
    return tr.redist_counts()


CHOL_VARIANTS = {"classic": dict(lookahead=False, crossover=0),
                 "lookahead": dict(lookahead=True, crossover=0),
                 "crossover": dict(lookahead=True, crossover=32)}


@pytest.mark.parametrize("variant", sorted(CHOL_VARIANTS))
def test_cholesky_runtime_matches_golden(rc_grid, variant):
    rc, grid = rc_grid
    A = el.from_global(_spd(N, 1), el.MC, el.MR, grid=grid)
    kw = CHOL_VARIANTS[variant]
    counts = _traced(lambda: el.cholesky(A, nb=NB, **kw).local)
    assert counts == _golden(f"cholesky_{variant}", rc)["redistributes"]


LU_VARIANTS = {"classic": dict(lookahead=False, crossover=0),
               "lookahead": dict(lookahead=True, crossover=0),
               "crossover": dict(lookahead=True, crossover=32)}


@pytest.mark.parametrize("variant", sorted(LU_VARIANTS))
def test_lu_runtime_matches_golden(rc_grid, variant):
    rc, grid = rc_grid
    rng = np.random.default_rng(2)
    F = rng.normal(size=(N, N)) + N * np.eye(N)
    A = el.from_global(F, el.MC, el.MR, grid=grid)
    kw = LU_VARIANTS[variant]
    counts = _traced(lambda: el.lu(A, nb=NB, **kw)[0].local)
    assert counts == _golden(f"lu_{variant}", rc)["redistributes"]


@pytest.mark.parametrize("alg", ["c", "dot"])
def test_gemm_runtime_matches_golden(rc_grid, alg):
    rc, grid = rc_grid
    rng = np.random.default_rng(3)
    A = el.from_global(rng.normal(size=(N, N)), el.MC, el.MR, grid=grid)
    B = el.from_global(rng.normal(size=(N, N)), el.MC, el.MR, grid=grid)
    counts = _traced(lambda: el.gemm(A, B, alg=alg.upper() if alg != "dot"
                                     else "dot", nb=NB).local)
    golden = _golden(f"gemm_{alg}", rc)["redistributes"]
    assert counts == golden
    if alg == "dot" and rc == (1, 1):
        # the pinned p==1 early-out: zero redistributes at runtime too
        assert counts == {}


def test_runtime_counts_also_match_a_fresh_abstract_trace(rc_grid):
    """Belt and braces: compare against a live analyzer trace (not just
    the snapshot) so a regenerated golden can never mask a divergence."""
    from elemental_tpu import analysis as an
    rc, grid = rc_grid
    plan, _, _ = an.trace_driver("cholesky_lookahead", grid, n=N, nb=NB)
    A = el.from_global(_spd(N, 4), el.MC, el.MR, grid=grid)
    counts = _traced(
        lambda: el.cholesky(A, nb=NB, lookahead=True, crossover=0).local)
    assert counts == plan.to_doc(events=False)["redistributes"]
