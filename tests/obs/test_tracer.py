"""Span tracer unit behavior: nesting/ordering, tick channels, hook
dispatch, the PhaseTimer shim's byte-compatible output (ISSUE 5)."""
import json

import pytest

from elemental_tpu import obs
from elemental_tpu.obs.tracer import NULL_HOOK, _Fanout, phase_hook


class FakeClock:
    """Deterministic monotone clock: each read advances by ``step``."""

    def __init__(self, step=1.0):
        self.t = 0.0
        self.step = step

    def __call__(self):
        self.t += self.step
        return self.t


# ---------------------------------------------------------------------
# explicit spans
# ---------------------------------------------------------------------

def test_span_nesting_and_ordering():
    tr = obs.Tracer(metrics=False, clock=FakeClock())
    with tr.span("outer", kind="run"):
        with tr.span("inner", k=1):
            pass
        with tr.span("inner2"):
            pass
    assert [s.name for s in tr.spans] == ["outer", "inner", "inner2"]
    assert [s.depth for s in tr.spans] == [0, 1, 1]
    o, i1, i2 = tr.spans
    # children are strictly contained in the parent interval and ordered
    assert o.t0 < i1.t0 < i1.t1 < i2.t0 < i2.t1 < o.t1
    assert o.attrs == {"kind": "run"} and i1.attrs == {"k": 1}


def test_span_sync_blocks_on_outputs():
    import jax.numpy as jnp
    tr = obs.Tracer(metrics=False)
    with tr.span("phase", sync=(jnp.zeros(4),)) as s:
        pass
    assert s.t1 is not None and s.t1 >= s.t0


# ---------------------------------------------------------------------
# tick channels (the driver hook protocol)
# ---------------------------------------------------------------------

def test_tick_channel_intervals():
    clock = FakeClock()
    tr = obs.Tracer(metrics=False, clock=clock)
    ch = tr.channel("lu")
    ch.start()                      # t=1
    ch.tick("panel", 0)             # t=2: [1, 2]
    ch.tick("update", 0)            # t=3: [2, 3]
    ch.tick("panel", 1)             # t=4: [3, 4]
    recs = tr.phases
    assert [(r.driver, r.phase, r.step) for r in recs] == \
        [("lu", "panel", 0), ("lu", "update", 0), ("lu", "panel", 1)]
    assert [(r.t0, r.t1) for r in recs] == [(1.0, 2.0), (2.0, 3.0), (3.0, 4.0)]
    # driver span synthesis: one call spanning first t0 .. last t1
    calls = tr.driver_calls()
    assert calls == [(1, "lu", 1.0, 4.0, [0, 1])]
    assert tr.phase_totals() == {"lu": {"panel": 2.0, "update": 1.0}}


def test_tick_without_start_charges_zero():
    tr = obs.Tracer(metrics=False, clock=FakeClock())
    ch = tr.channel("qr")
    ch.tick("panel", 0)             # unarmed: zero-length interval
    assert tr.phases[0].seconds == 0.0


def test_two_channels_are_separate_driver_calls():
    tr = obs.Tracer(metrics=False, clock=FakeClock())
    a, b = tr.channel("gemm"), tr.channel("trsm")
    a.start()
    b.start()
    a.tick("panel", 0)
    b.tick("solve", 0)
    calls = tr.driver_calls()
    assert [c[1] for c in calls] == ["gemm", "trsm"]
    assert calls[0][0] != calls[1][0]


# ---------------------------------------------------------------------
# phase_hook dispatch
# ---------------------------------------------------------------------

def test_phase_hook_null_when_inactive():
    with obs.metrics_scope() as reg:
        assert phase_hook("lu") is NULL_HOOK
        assert reg.counter_value("op_calls", op="lu") == 1


def test_phase_hook_returns_timer_when_inactive():
    t = obs.PhaseTimer()
    with obs.metrics_scope():
        assert phase_hook("cholesky", t) is t


def test_phase_hook_routes_to_active_tracer():
    tr = obs.Tracer(metrics=False)
    with obs.metrics_scope():
        with tr:
            hk = phase_hook("herk")
            hk.tick("spread", 0)
    assert [(r.driver, r.phase) for r in tr.phases] == [("herk", "spread")]
    assert obs.active_tracer() is None      # deactivated on exit


def test_phase_hook_fans_out_to_both():
    tr = obs.Tracer(metrics=False)
    t = obs.PhaseTimer()
    with obs.metrics_scope():
        with tr:
            hk = phase_hook("lu", t)
            assert isinstance(hk, _Fanout)
            hk.start()
            hk.tick("panel", 0)
    assert [r.phase for r in tr.phases] == ["panel"]
    assert [r["phase"] for r in t.records] == ["panel"]


def test_nested_activation_restores_previous():
    t1, t2 = obs.Tracer(metrics=False), obs.Tracer(metrics=False)
    with obs.metrics_scope():
        with t1:
            with t2:
                assert obs.active_tracer() is t2
            assert obs.active_tracer() is t1
    assert obs.active_tracer() is None


# ---------------------------------------------------------------------
# collective events
# ---------------------------------------------------------------------

def _fake_record(grid_shape=(2, 2)):
    from elemental_tpu.core.dist import MC, MR, STAR
    from elemental_tpu.redist.engine import RedistRecord
    return RedistRecord(kind="redistribute", src=(MC, MR), dst=(STAR, STAR),
                        gshape=(64, 64), dtype="float32", in_id=1,
                        out_ids=(2,), grid_shape=grid_shape)


def test_ring_bytes():
    assert obs.ring_bytes((64, 64), "float32", (1, 1)) == 0
    assert obs.ring_bytes((64, 64), "float32", (2, 2)) == 64 * 64 * 4 * 3 // 4
    assert obs.ring_bytes((8, 8), "float64", (2, 1)) == 8 * 8 * 8 // 2
    assert obs.ring_bytes((8, 8), "not-a-dtype", (2, 2)) == 8 * 8 * 4 * 3 // 4


def test_comm_event_attribution_and_metrics():
    tr = obs.Tracer()
    with obs.metrics_scope() as reg:
        with tr:
            ch = tr.channel("cholesky")
            ch.start()
            with tr.span("step0"):
                tr._on_redist(_fake_record())
    ev = tr.comms[0]
    assert ev.label == "[MC,MR]->[STAR,STAR]"
    assert ev.span == "step0" and ev.driver == "cholesky"
    assert ev.bytes == 64 * 64 * 4 * 3 // 4
    assert tr.redist_counts() == {"[MC,MR]->[STAR,STAR]": 1}
    assert reg.counter_value("redist_calls",
                             label="[MC,MR]->[STAR,STAR]") == 1
    assert reg.counter_value("redist_bytes",
                             label="[MC,MR]->[STAR,STAR]") == ev.bytes


def test_engine_observer_fires_on_real_redistribute(grid24):
    import numpy as np
    import elemental_tpu as el
    A = el.from_global(np.arange(64.0).reshape(8, 8), el.MC, el.MR,
                       grid=grid24)
    tr = obs.Tracer(metrics=False)
    with obs.metrics_scope():
        with tr:
            el.redistribute(A, el.STAR, el.STAR)
    assert tr.redist_counts() == {"[MC,MR]->[STAR,STAR]": 1}
    # observer removed on exit: further redistributes are not recorded
    with obs.metrics_scope():
        el.redistribute(A, el.VC, el.STAR)
    assert sum(tr.redist_counts().values()) == 1


# ---------------------------------------------------------------------
# PhaseTimer shim (byte-compatible phase_timings/v1)
# ---------------------------------------------------------------------

def test_phase_timer_shim_reexport_identity():
    from perf.phase_timer import PHASES, SCHEMA, PhaseTimer
    from elemental_tpu.obs import phase_timer as obs_pt
    assert PhaseTimer is obs_pt.PhaseTimer
    assert SCHEMA == obs_pt.SCHEMA == "phase_timings/v1"
    assert PHASES == obs_pt.PHASES


def test_phase_timer_report_structure():
    t = obs.PhaseTimer(tracer=obs.Tracer(metrics=False, clock=FakeClock()))
    t.start()                       # t=1
    t.tick("panel", 0)              # [1,2] -> 1.0
    t.tick("swap", 0)               # [2,3] -> 1.0
    t.tick("panel", 1)              # [3,4] -> 1.0
    t.tick("update", 0)             # [4,5] -> 1.0
    doc = json.loads(t.json(driver="lu", n=64, nb=16))
    assert doc == {
        "schema": "phase_timings/v1",
        "steps": [{"step": 0, "panel": 1.0, "swap": 1.0, "update": 1.0},
                  {"step": 1, "panel": 1.0}],
        "totals": {"panel": 2.0, "swap": 1.0, "update": 1.0},
        "total_seconds": 4.0,
        "driver": "lu", "n": 64, "nb": 16,
    }
    # canonical phase ordering in totals (diag..tail first, extras after)
    assert list(doc["totals"]) == ["panel", "swap", "update"]
    assert t.records == [
        {"phase": "panel", "step": 0, "seconds": 1.0},
        {"phase": "swap", "step": 0, "seconds": 1.0},
        {"phase": "panel", "step": 1, "seconds": 1.0},
        {"phase": "update", "step": 0, "seconds": 1.0},
    ]


def test_phase_timer_tick_before_start_is_zero():
    t = obs.PhaseTimer(tracer=obs.Tracer(metrics=False, clock=FakeClock()))
    t.tick("panel", 0)
    assert t.records == [{"phase": "panel", "step": 0, "seconds": 0.0}]


@pytest.mark.parametrize("driver", ["qr", "gemm", "trsm", "herk"])
def test_new_driver_hooks_emit_phases(driver, grid24):
    """The four newly instrumented drivers emit spans under an active
    tracer (cholesky/lu are covered by tests/perf and the cross-check)."""
    import numpy as np
    import elemental_tpu as el
    n, nb = 16, 8
    rng = np.random.default_rng(3)
    F = rng.normal(size=(n, n))
    S = F @ F.T / n + n * np.eye(n)
    A = el.from_global(S, el.MC, el.MR, grid=grid24)
    B = el.from_global(F, el.MC, el.MR, grid=grid24)
    tr = obs.Tracer(metrics=False)
    with obs.metrics_scope():
        with tr:
            if driver == "qr":
                el.qr(B, nb=nb)
            elif driver == "gemm":
                el.gemm(B, B, alg="C", nb=nb)
            elif driver == "trsm":
                el.trsm("L", "L", "N", A, B, nb=nb)
            else:
                el.herk("L", B, nb=nb)
    drivers = {r.driver for r in tr.phases}
    assert drivers == {driver}
    assert len(tr.phases) >= 1
    # phases nest under synthesized per-step spans with monotone intervals
    for r in tr.phases:
        assert r.t1 >= r.t0
