"""Fleet chaos column (ISSUE 19): saturation sheds structured and keeps
admitted tails flat; grid loss re-routes to the healthy member with zero
sheds; both replay bit-identically under the virtual clock."""
from elemental_tpu.serve import (fleet_replay_identical,
                                 run_fleet_grid_loss_cell,
                                 run_fleet_saturation_cell)


def test_saturation_sheds_structured_latency_flat():
    doc, fleet = run_fleet_saturation_cell()
    assert doc["violations"] == []
    assert doc["verdict"] == "isolated"
    assert doc["column"] == "fleet" and doc["grids"] == 2
    # the overload waves actually shed, every shed grid-attributed
    assert doc["fired"] > 0
    sheds = [v for v in doc["outcomes"].values()
             if v.startswith("reject:")]
    assert len(sheds) == doc["fired"]
    assert all(v.split(":")[2] in ("g0", "g1") for v in sheds)
    # the light wave shed nothing; admitted p99 never stretched
    assert doc["waves"][0]["sheds"] == 0
    bound = doc["budget_s"] + 2.0
    assert all(w["p99_s"] <= bound for w in doc["waves"])
    # shedding rises with offered load
    assert doc["waves"][-1]["sheds"] > doc["waves"][1]["sheds"]


def test_grid_loss_reroutes_without_drops():
    doc, fleet = run_fleet_grid_loss_cell()
    assert doc["violations"] == []
    assert doc["verdict"] == "isolated"
    # every request (both phases) ended ok -- the poisoned member's
    # work recovered through escalation, nothing shed, nothing dropped
    assert doc["ok"] == doc["requests"]
    assert doc["fired"] > 0              # phase A really touched g0
    phase_b = [v for k, v in doc["outcomes"].items()
               if k.startswith("b:")]
    assert phase_b and all(v == "ok:g1:fastpath" for v in phase_b)
    # the lost member's breaker is OPEN in the surviving fleet handle
    from elemental_tpu.serve import OPEN
    assert any(b.state == OPEN
               for b in fleet.services[0].breakers.values())


def test_fleet_replay_bit_identical():
    assert fleet_replay_identical()
