"""SolverFleet (ISSUE 19 tentpole): device partitioning into isolated
members, deterministic load-aware routing with grid/tenant provenance,
quota enforcement at submit, and zero-silent-drop shutdown in both the
sync (chaos) and pipelined modes."""
import threading

import jax
import numpy as np
import pytest

from elemental_tpu.serve import (REJECT_SCHEMA, DEFAULT_TENANT,
                                 SolverFleet, TenantQuota,
                                 partition_devices)

from .conftest import spd


def _workload(rng, count, n=12, nrhs=2):
    return [(spd(rng, n), rng.normal(size=(n, nrhs)))
            for _ in range(count)]


def _no_leak():
    return not any(t.name.startswith("elemental-serve-worker") and t.is_alive()
                   for t in threading.enumerate())


# ---- partitioning ------------------------------------------------------

def test_partition_equal_split():
    parts = partition_devices(grids=2)
    devs = jax.devices()
    assert len(parts) == 2
    assert [d for p in parts for d in p] == devs  # consecutive, disjoint
    assert len(parts[0]) == len(parts[1]) == len(devs) // 2


def test_partition_explicit_sizes_leave_leftovers():
    parts = partition_devices(grids=[4, 2])
    assert [len(p) for p in parts] == [4, 2]
    flat = [d for p in parts for d in p]
    assert len(set(flat)) == 6  # 2 devices deliberately unused


def test_partition_errors():
    with pytest.raises(ValueError):
        partition_devices(grids=3)           # 3 does not divide 8
    with pytest.raises(ValueError):
        partition_devices(grids=[8, 8])      # more than available
    with pytest.raises(ValueError):
        partition_devices(grids=[4, 0])      # degenerate member


# ---- member isolation --------------------------------------------------

def test_members_are_isolated():
    """Each member owns its name, tuner namespace, executor cache, and
    breaker table -- nothing shared, so one member's state cannot bleed
    into another's."""
    fleet = SolverFleet(grids=2, pipelined=False, shed=False)
    try:
        a, b = fleet.services
        assert (a.name, b.name) == ("g0", "g1")
        assert a.tune_ns != b.tune_ns
        assert a.executor is not b.executor
        assert a.executor.cache is not b.executor.cache
        assert a.breakers is not b.breakers
        assert a.admission is not b.admission
        assert not set(a.grid.devices) & set(b.grid.devices)
    finally:
        fleet.shutdown(drain=True)


# ---- sync routing + provenance -----------------------------------------

def test_sync_roundtrip_provenance():
    """Submit/drain through a 2-member sync fleet: every future
    resolves ok, docs carry the member that served them and the billing
    tenant, and solutions pass an independent residual check."""
    rng = np.random.default_rng(71)
    work = _workload(rng, 8)
    fleet = SolverFleet(grids=2, pipelined=False, shed=False, max_batch=2)
    try:
        futs = [fleet.submit("hpd", A, B) for A, B in work]
        fleet.drain()
        grids = set()
        for f, (A, B) in zip(futs, work):
            assert f.done()
            X, doc = f.result(timeout=0)
            assert doc["status"] == "ok"
            assert doc["grid"] in ("g0", "g1") and doc["grid"] == f.grid
            assert doc["tenant"] == DEFAULT_TENANT
            grids.add(doc["grid"])
            r = np.linalg.norm(A @ np.asarray(X) - B)
            assert r / np.linalg.norm(B) < 1e-6
        # backlog-tie alternation spreads an even workload
        assert grids == {"g0", "g1"}
        assert sorted(f.fleet_id for f in futs) == list(range(8))
        assert set(fleet.results) == set(range(8))
    finally:
        fleet.shutdown(drain=True)


def test_routing_balances_even_load():
    """Equal-cost requests against cold (equal) latency estimates split
    evenly across members via the deterministic backlog tie-break."""
    rng = np.random.default_rng(72)
    fleet = SolverFleet(grids=2, pipelined=False, shed=False, max_batch=4)
    try:
        futs = [fleet.submit("hpd", A, B) for A, B in _workload(rng, 8)]
        fleet.drain()
        by_grid = {}
        for f in futs:
            by_grid[f.grid] = by_grid.get(f.grid, 0) + 1
        assert by_grid == {"g0": 4, "g1": 4}
    finally:
        fleet.shutdown(drain=True)


# ---- tenant quotas -----------------------------------------------------

def test_quota_rejects_structured_and_released():
    """max_outstanding=2 draws 'quota' rejects for the overflow, billed
    to the right tenant, BEFORE anything queues; slots free once the
    tenant's work settles."""
    rng = np.random.default_rng(73)
    fleet = SolverFleet(grids=2, pipelined=False, shed=False,
                        quotas={"q": TenantQuota(max_outstanding=2)})
    try:
        futs = [fleet.submit("hpd", A, B, tenant="q")
                for A, B in _workload(rng, 5)]
        rejects = [f for f in futs if f.done()]
        assert len(rejects) == 3
        for f in rejects:
            _, doc = f.result(timeout=0)
            assert doc["schema"] == REJECT_SCHEMA
            assert doc["reason"] == "quota"
            assert doc["tenant"] == "q"
            assert doc["grid"] is None          # rejected before routing
        fleet.drain()
        assert all(f.result(0)[1]["status"] == "ok"
                   for f in futs if f not in rejects)
        # settled work released the quota slots
        f2 = fleet.submit("hpd", *_workload(rng, 1)[0], tenant="q")
        assert not f2.done()
        fleet.drain()
        assert f2.result(0)[1]["status"] == "ok"
    finally:
        fleet.shutdown(drain=True)


def test_bad_request_rejects_with_tenant():
    fleet = SolverFleet(grids=2, pipelined=False, shed=False)
    try:
        f = fleet.submit("hpd", np.eye(4), np.zeros((5, 1)), tenant="t")
        assert f.done()
        _, doc = f.result(timeout=0)
        assert doc["schema"] == REJECT_SCHEMA
        assert doc["reason"] == "bad_request"
        assert doc["tenant"] == "t"
    finally:
        fleet.shutdown(drain=True)


def test_memory_pressure_routes_around_then_rejects_with_grid_id():
    """Grid-local HBM budgets: a member whose budget cannot fit the
    bucket's static peak sheds what its pool-mate still admits --
    traffic routes around it -- and when EVERY member is over budget the
    reject is structured ``memory_pressure`` carrying a grid id."""
    rng = np.random.default_rng(76)
    work = _workload(rng, 4)
    fleet = SolverFleet(grids=2, pipelined=False, max_batch=2)
    try:
        fleet.services[0].admission.hbm_bytes = 1.0   # g0 cannot fit it
        futs = [fleet.submit("hpd", A, B) for A, B in work]
        fleet.drain()
        for f in futs:
            _, doc = f.result(timeout=0)
            assert doc["status"] == "ok" and doc["grid"] == "g1"
        fleet.services[1].admission.hbm_bytes = 1.0   # now nobody can
        f = fleet.submit("hpd", *_workload(rng, 1)[0])
        assert f.done()
        _, doc = f.result(timeout=0)
        assert doc["schema"] == REJECT_SCHEMA
        assert doc["reason"] == "memory_pressure"
        assert doc["grid"] in ("g0", "g1")
    finally:
        fleet.shutdown(drain=True)


# ---- shutdown ----------------------------------------------------------

def test_shutdown_flush_resolves_every_future():
    """shutdown(drain=False) flushes scheduler-held work as structured
    shutdown rejects and emergency-stops members: zero silent drops."""
    rng = np.random.default_rng(74)
    fleet = SolverFleet(grids=2, pipelined=False, shed=False, max_batch=2)
    futs = [fleet.submit("hpd", A, B) for A, B in _workload(rng, 8)]
    fleet.shutdown(drain=False)
    assert all(f.done() for f in futs)
    reasons = set()
    for f in futs:
        _, doc = f.result(timeout=0)
        if doc.get("schema") == REJECT_SCHEMA:
            reasons.add(doc["reason"])
            assert doc["tenant"] == DEFAULT_TENANT
    assert reasons <= {"shutdown"}
    # post-shutdown submits reject-fast, and shutdown is idempotent
    f = fleet.submit("hpd", *_workload(rng, 1)[0])
    assert f.done() and f.result(0)[1]["reason"] == "shutdown"
    fleet.shutdown(drain=False)


# ---- pipelined mode ----------------------------------------------------

def test_pipelined_end_to_end_no_leak():
    """Depth-2 pipelined members: every future resolves ok with grid +
    tenant provenance; shutdown drains and leaks no worker thread."""
    rng = np.random.default_rng(75)
    work = _workload(rng, 6)
    fleet = SolverFleet(grids=2, depth=2, shed=False, max_batch=2)
    futs = [fleet.submit("hpd", A, B, tenant=f"t{i % 2}")
            for i, (A, B) in enumerate(work)]
    outs = [f.result(timeout=300.0) for f in futs]
    fleet.shutdown(drain=True)
    for i, ((X, doc), (A, B)) in enumerate(zip(outs, work)):
        assert doc["status"] == "ok"
        assert doc["grid"] in ("g0", "g1")
        assert doc["tenant"] == f"t{i % 2}"
        r = np.linalg.norm(A @ np.asarray(X) - B)
        assert r / np.linalg.norm(B) < 1e-6
    assert _no_leak()


# ---- introspection -----------------------------------------------------

def test_stats_snapshot_shape():
    fleet = SolverFleet(grids=2, pipelined=False, shed=False)
    try:
        s = fleet.stats()
        assert [m["grid"] for m in s["members"]] == ["g0", "g1"]
        for m in s["members"]:
            assert m["devices"] == len(jax.devices()) // 2
            assert m["outstanding"] == 0
            assert m["capacity"] == fleet.max_batch
        assert s["scheduler"]["tenants"] == []
        assert s["tenants_outstanding"] == {}
        assert s["pipelined"] is False
    finally:
        fleet.shutdown(drain=True)
