"""ISSUE 20 end-to-end fleet telemetry: every settled document carries a
complete, attributed timeline; the Chrome-trace export links a request's
lifecycle across grid-worker tracks; the flight recorder dumps
bit-identically under the chaos harness's virtual clock."""
import json

import numpy as np
import pytest

from elemental_tpu.obs import Tracer, chrome_trace_doc
from elemental_tpu.obs.lifecycle import check_timeline
from elemental_tpu.serve import SolverFleet
from elemental_tpu.serve.chaos import (fleet_replay_identical,
                                       run_fleet_grid_loss_cell)

from .conftest import spd


def _workload(rng, count, n=12, nrhs=2):
    return [(spd(rng, n), rng.normal(size=(n, nrhs)))
            for _ in range(count)]


# ---------------------------------------------------------------------
# 2-grid fleet attribution (the ISSUE-20 acceptance run)
# ---------------------------------------------------------------------

def test_pipelined_fleet_timelines_complete_and_attributed():
    """Every result of a 2-grid pipelined fleet run carries a complete
    monotone timeline whose grid/tenant attribution matches the routing
    provenance, and the trace export links each request's lifecycle
    instants into one serve:req flow chain crossing worker tracks."""
    rng = np.random.default_rng(91)
    tenants = ("acme", "blue")
    tracer = Tracer(metrics=False)
    fleet = SolverFleet(grids=2, pipelined=True, depth=2, max_batch=2,
                        shed=False, retries=0)
    try:
        with tracer:
            futs = [fleet.submit("hpd", A, B, tenant=tenants[i % 2])
                    for i, (A, B) in enumerate(_workload(rng, 8))]
            docs = [f.result(timeout=30)[1] for f in futs]
    finally:
        fleet.shutdown(drain=True)

    grids_seen = set()
    for f, doc in zip(futs, docs):
        assert doc["status"] == "ok"
        tl = doc["timeline"]
        assert check_timeline(tl, path=doc.get("path"), fleet=True) \
            == [], (doc.get("path"), tl)
        assert tl["id"] == f"f{f.fleet_id}"
        assert tl["tenant"] == doc["tenant"] == f.tenant
        assert tl["grid"] == doc["grid"] == f.grid
        grids_seen.add(tl["grid"])
    assert grids_seen <= {"g0", "g1"} and grids_seen

    evs = chrome_trace_doc(tracer, mode="serve")["traceEvents"]
    tracks = [e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "thread_name"]
    workers = [t for t in tracks
               if t.startswith("elemental-serve-worker")]
    assert len(workers) >= 2          # one track block per grid worker
    flow = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert {e["ph"] for e in flow} >= {"s", "f"}
    assert all(e["name"] == "serve:req" and e["cat"] == "lifecycle"
               for e in flow)
    # one linked chain per request, start to finish
    by_id = {}
    for e in flow:
        by_id.setdefault(e["id"], []).append(e["ph"])
    assert set(by_id) == {f"f{f.fleet_id}" for f in futs}
    for phs in by_id.values():
        assert phs[0] == "s" and phs[-1] == "f"


def test_fleet_rejects_carry_timelines_too():
    fleet = SolverFleet(grids=2, pipelined=False, shed=False)
    try:
        fut = fleet.submit("hpd", np.eye(3), np.ones((4, 1)))  # mismatch
        _, doc = fut.result(timeout=0)
        assert doc["reason"] == "bad_request"
        tl = doc["timeline"]
        assert check_timeline(tl) == []
        edges = [r["edge"] for r in tl["edges"]]
        assert edges[0] == "submitted" and edges[-1] == "rejected"
        assert "shed" in edges
    finally:
        fleet.shutdown(drain=True)


def test_fleet_slo_monitor_fed_by_settlement():
    rng = np.random.default_rng(17)
    fleet = SolverFleet(grids=2, pipelined=False, shed=False)
    try:
        for i, (A, B) in enumerate(_workload(rng, 4)):
            fleet.submit("hpd", A, B, tenant=("acme", "blue")[i % 2])
        fleet.drain()
        sdoc = fleet.slo.snapshot(gauges=False, source="test")
        assert sdoc["schema"] == "serve_slo/v1"
        assert {r["tenant"] for r in sdoc["series"]} == {"acme", "blue"}
        assert all(r["count"] >= 1 for r in sdoc["series"])
        per = fleet.slo.per_tenant_p99_ms()
        assert set(per) == {"acme", "blue"}
        assert fleet.slo.worst_p99_ms() == max(per.values())
    finally:
        fleet.shutdown(drain=True)


def test_fleet_quota_storm_dumps_flight_record():
    """Hammering past a tenant quota long enough trips the quota_storm
    trigger: the shared flight recorder auto-dumps with the reject run
    visible in the ring."""
    from elemental_tpu.serve import TenantQuota
    rng = np.random.default_rng(23)
    (A, B) = _workload(rng, 1)[0]
    fleet = SolverFleet(grids=2, pipelined=False, shed=False,
                        quotas={"noisy": TenantQuota(max_outstanding=1)})
    fleet.flight.quota_storm_threshold = 4
    try:
        fleet.submit("hpd", A, B, tenant="noisy")      # fills the quota
        for _ in range(4):
            fleet.submit("hpd", A, B, tenant="noisy")  # all quota-shed
        dump = fleet.flight.last_dump()
        assert dump is not None
        assert dump["schema"] == "flight_record/v1"
        assert dump["trigger"]["reason"] == "quota_storm"
        kinds = [e["kind"] for e in dump["events"]]
        assert kinds.count("reject") == 4
    finally:
        fleet.shutdown(drain=True)


# ---------------------------------------------------------------------
# chaos: breaker-open flight dump, bit-identical under replay
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_grid_loss_cell_dumps_flight_on_breaker_open():
    cell, fleet_doc = run_fleet_grid_loss_cell(requests=6, seed=13)
    assert cell["verdict"] == "isolated" and cell["ok"]
    dump = cell["flight"]
    assert dump is not None and dump["schema"] == "flight_record/v1"
    reasons = {dump["trigger"]["reason"]}
    assert "breaker_open" in reasons
    # the seconds before the fault are reconstructable: lifecycle edges
    # of the poisoned requests precede the trigger in the ring
    kinds = {e["kind"] for e in dump["events"]}
    assert any(k.startswith("edge:") for k in kinds)
    assert dump["trigger"]["seq"] >= len(dump["events"])


@pytest.mark.slow
def test_fleet_chaos_flight_replay_bit_identical():
    """The determinism acceptance criterion: the same seeded grid-loss
    cell replays to a byte-identical flight_record/v1 (virtual clock,
    lock-ordered sequence numbers, no wall time anywhere)."""
    c1, _ = run_fleet_grid_loss_cell(requests=6, seed=13)
    c2, _ = run_fleet_grid_loss_cell(requests=6, seed=13)
    assert json.dumps(c1["flight"], sort_keys=True) \
        == json.dumps(c2["flight"], sort_keys=True)
    assert fleet_replay_identical(requests=6, seed=13)
