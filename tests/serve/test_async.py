"""AsyncSolverService (ISSUE 14 tentpole): the double-buffered pipeline
must be semantically invisible -- bit-identical solutions and unchanged
``serve_result/v1`` docs vs the sync core -- while completions stream,
deadlines keep their semantics under concurrency, the breaker stays
deterministic under an injected clock, and shutdown never leaks the
worker thread or silently drops a future."""
import threading

import numpy as np

from elemental_tpu.obs import metrics as _metrics
from elemental_tpu.serve import (AsyncSolverService, SolverService,
                                 donation_safe, serve_async)

from .conftest import FakeClock, diag_dom, spd

#: serve_result/v1 keys that must be identical sync vs async (timing
#: keys excluded -- wall clock legitimately differs); mirrors the
#: bench_serve.py payload-identity contract
SEM_KEYS = ("op", "n", "nrhs", "bucket", "status", "path", "rung",
            "residual", "tol", "retries", "bisected", "timed_out")


def _workload(rng, count=10):
    out = []
    for i in range(count):
        n = (12, 16, 9)[i % 3]
        if i % 2:
            out.append(("lu", diag_dom(rng, n), rng.normal(size=(n, 2))))
        else:
            out.append(("hpd", spd(rng, n), rng.normal(size=(n, 2))))
    return out


def _no_leak():
    return not any(t.name.startswith("elemental-serve-worker") and t.is_alive()
                   for t in threading.enumerate())


def test_async_bit_identical_to_sync(grid24):
    """The pipelined front (donated buffers, overlapped staging) returns
    bit-identical solutions and semantically identical docs for the same
    workload as the synchronous core."""
    rng = np.random.default_rng(40)
    work = _workload(rng, count=10)
    sync = SolverService(grid24, max_batch=4)
    rids = [sync.submit(op, A, B) for op, A, B in work]
    sdocs = sync.drain()

    front = AsyncSolverService(grid=grid24, max_batch=4)
    futs = [front.submit(op, A, B) for op, A, B in work]
    outs = [f.result(timeout=300.0) for f in futs]
    front.shutdown()
    for rid, (x2, d2) in zip(rids, outs):
        d1 = sdocs[rid]
        for k in SEM_KEYS:
            assert d1[k] == d2[k], k
        assert d1["dispatch"]["route"] == d2["dispatch"]["route"]
        x1 = sync.solutions[rid]
        assert x1.dtype == x2.dtype
        np.testing.assert_array_equal(x1, x2)
    assert _no_leak()


def test_completions_stream_before_shutdown(grid24):
    """Futures resolve as their batch certifies -- not at shutdown --
    and pre-registered callbacks fire on the worker thread; callbacks
    added AFTER resolution fire immediately on the caller's thread."""
    rng = np.random.default_rng(41)
    front = AsyncSolverService(grid=grid24, max_batch=2)
    seen: list = []
    futs = [front.submit(op, A, B, callback=lambda f: seen.append(
                (f.id, threading.current_thread().name)))
            for op, A, B in _workload(rng, count=6)]
    outs = [f.result(timeout=300.0) for f in futs]
    # every future resolved while the service is still accepting
    assert all(f.done() for f in futs) and not front._stop
    assert all(d["status"] == "ok" for _, d in outs)
    late: list = []
    futs[0].add_done_callback(lambda f: late.append(
        threading.current_thread().name))
    assert late == [threading.current_thread().name]   # immediate, caller
    front.shutdown()
    assert sorted(i for i, _ in seen) == sorted(f.id for f in futs)
    assert {name for _, name in seen} == {"elemental-serve-worker"}
    assert _no_leak()


def test_expired_at_ingest_rejects_while_mates_complete(grid24):
    """A deadline that lapses in the SUBMISSION queue (before admission)
    resolves with the structured serve_reject/v1 while its batch-mates
    complete ok -- deterministic via the injected clock."""
    clk = FakeClock()
    rng = np.random.default_rng(42)
    svc = SolverService(grid24, clock=clk, sleep=clk.sleep, max_batch=4)
    front = AsyncSolverService(svc, autostart=False)
    A, B = diag_dom(rng, 12), rng.normal(size=(12, 2))
    f_ok = front.submit("lu", A, B)                    # no budget
    f_dead = front.submit("lu", A, B, budget_s=1.0)
    clk.advance(2.0)                                   # lapses queued
    front.start()
    x1, d1 = f_ok.result(timeout=300.0)
    x2, d2 = f_dead.result(timeout=300.0)
    front.shutdown()
    assert d1["status"] == "ok" and x1 is not None
    assert d2["schema"] == "serve_reject/v1"
    assert d2["reason"] == "deadline_expired" and x2 is None
    assert d2["deadline"]["remaining_s"] < 0
    assert _no_leak()


def test_deadline_lapse_mid_pipeline_drops_structured(grid24):
    """A deadline that lapses AFTER admission, while earlier batches are
    in flight, is finalized as a structured timed_out serve_result (path
    'dropped') without paying a dispatch -- batch-mates unaffected.  The
    clock advances inside batch 0's completion callback (worker thread),
    which double buffering orders after batch 1's dispatch and before
    batch 2's staging: fully deterministic."""
    clk = FakeClock()
    rng = np.random.default_rng(43)
    svc = SolverService(grid24, clock=clk, sleep=clk.sleep, max_batch=1)
    front = AsyncSolverService(svc, autostart=False)
    A, B = diag_dom(rng, 12), rng.normal(size=(12, 2))
    f0 = front.submit("lu", A, B, callback=lambda f: clk.advance(2.0))
    f1 = front.submit("lu", A, B)
    f2 = front.submit("lu", A, B, budget_s=1.0)        # dies in queue
    front.start()
    front.shutdown(drain=True)
    assert f0.result(timeout=0)[1]["status"] == "ok"
    assert f1.result(timeout=0)[1]["status"] == "ok"
    x2, d2 = f2.result(timeout=0)
    assert d2["status"] == "timed_out" and d2["path"] == "dropped"
    assert d2["timed_out"] is True and x2 is None
    assert d2["deadline"]["remaining_s"] < 0
    assert f2.id not in svc.solutions                  # never dispatched
    assert _no_leak()


def test_breaker_deterministic_under_pipelining(grid24):
    """The pipelining price, pinned: batch k+1's fastpath decision is
    made BEFORE batch k's outcome lands, so the request staged while the
    trip was in flight still certifies on the fastpath; the next batch
    sees the open breaker and bypasses to escalation; the racing
    request's success then closes the breaker again (collected after the
    trip).  Bit-deterministic across runs under the injected clock."""
    rng = np.random.default_rng(44)
    n = 8
    Asing = np.ones((n, n))
    Agood = diag_dom(rng, n)
    B = rng.normal(size=(n, 1))

    def run_once():
        clk = FakeClock()
        svc = SolverService(grid24, clock=clk, sleep=clk.sleep,
                            breaker_threshold=1, breaker_cooldown_s=1e9,
                            retries=0, max_batch=1)
        front = AsyncSolverService(svc, autostart=False)
        f_bad = front.submit("lu", Asing, B)
        f_racing = front.submit("lu", Agood, B)   # staged during the trip
        f_after = front.submit("lu", Agood, B)    # staged after the trip
        front.start()
        front.shutdown(drain=True)
        db = f_bad.result(timeout=0)[1]
        dr = f_racing.result(timeout=0)[1]
        da = f_after.result(timeout=0)[1]
        key = "lu__b8x1__float64"
        return (db["status"], dr["status"], dr["path"], da["status"],
                da["path"], svc.breakers[key].state,
                f_racing.result(timeout=0)[0].tobytes(),
                f_after.result(timeout=0)[0].tobytes())

    r1 = run_once()
    r2 = run_once()
    assert r1 == r2                                # deterministic replay
    # batch 1 rode the fastpath (staged pre-trip), batch 2 saw the open
    # breaker and escalated, and batch 1's collected success closed it
    assert r1[:6] == ("failed", "ok", "fastpath", "ok", "escalated",
                      "closed")
    assert _no_leak()


def test_donation_gated_to_accelerator_backends(grid24, monkeypatch):
    """``donate=True`` is honored only where :func:`donation_safe` says
    the backend donates correctly under overlapped dispatch: never on
    the CPU client (whose donated buffers can be recycled while batch k
    is still in flight), always on accelerators."""
    import jax
    assert donation_safe() is (jax.default_backend() != "cpu")
    front = AsyncSolverService(grid=grid24, autostart=False, donate=True)
    assert front.donate is donation_safe()
    front.shutdown()
    from elemental_tpu.serve import async_front
    monkeypatch.setattr(async_front, "donation_safe", lambda: True)
    front = AsyncSolverService(grid=grid24, autostart=False, donate=True)
    assert front.donate is True
    front.shutdown()
    front = AsyncSolverService(grid=grid24, autostart=False)
    assert front.donate is True                    # donation is the default
    front.shutdown()
    front = AsyncSolverService(grid=grid24, autostart=False, donate=False)
    assert front.donate is False                   # explicit opt-out wins
    front.shutdown()
    assert _no_leak()


def test_shutdown_drain_false_flushes_structured(grid24):
    """Emergency stop: everything still queued resolves with a
    structured shutdown reject -- zero silent drops -- and post-shutdown
    submissions resolve immediately with the same."""
    rng = np.random.default_rng(45)
    front = AsyncSolverService(grid=grid24, autostart=False, max_batch=2)
    futs = [front.submit(op, A, B) for op, A, B in _workload(rng, 6)]
    with _metrics.scoped() as reg:
        done = front.shutdown(drain=False)
        assert reg.counter_value("serve_rejects", reason="shutdown") == 6
    assert done == {}                              # nothing was admitted
    for f in futs:
        x, doc = f.result(timeout=0)
        assert x is None
        assert doc["schema"] == "serve_reject/v1"
        assert doc["reason"] == "shutdown"
    assert front.service.solutions == {}           # nothing executed
    assert _no_leak()
    assert front.shutdown() == {}                  # idempotent
    f = front.submit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)))
    assert f.done()
    assert f.result(timeout=0)[1]["reason"] == "shutdown"


def test_shutdown_drain_true_completes_everything(grid24):
    """Graceful stop: queued work COMPLETES through the pipeline; the
    returned ledger covers every admitted id."""
    rng = np.random.default_rng(46)
    front = AsyncSolverService(grid=grid24, autostart=False, max_batch=2)
    futs = [front.submit(op, A, B) for op, A, B in _workload(rng, 6)]
    done = front.shutdown(drain=True)
    assert all(f.done() for f in futs)
    assert set(done) == {f.id for f in futs}
    assert all(d["status"] == "ok" for _, d in
               (f.result(timeout=0) for f in futs))
    assert _no_leak()


def test_serve_async_convenience(grid24):
    rng = np.random.default_rng(47)
    work = _workload(rng, 5)
    docs, xs = serve_async(work, grid=grid24)
    assert len(docs) == len(xs) == 5
    for (op, A, B), doc, x in zip(work, docs, xs):
        assert doc["status"] == "ok" and doc["op"] == op
        np.testing.assert_allclose(x, np.linalg.solve(A, B),
                                   rtol=1e-8, atol=1e-10)
    assert _no_leak()


def test_pipeline_stats_and_gauges(grid24):
    rng = np.random.default_rng(48)
    with _metrics.scoped() as reg:
        front = AsyncSolverService(grid=grid24, max_batch=2)
        futs = [front.submit(op, A, B) for op, A, B in _workload(rng, 8)]
        for f in futs:
            f.result(timeout=300.0)
        front.shutdown()
        stats = front.pipeline_stats()
        assert stats["wall_s"] >= 0.0 and stats["device_busy_s"] >= 0.0
        # busy windows open at dispatch-call time, the wall clock starts
        # once the first dispatch returns -- occupancy may nose slightly
        # above 1.0, never wildly
        assert 0.0 <= stats["occupancy"] <= 1.2
        gauges = {r["name"]: r["value"] for r in reg.to_doc()["gauges"]}
        assert "serve_pipeline_occupancy" in gauges
        assert gauges["serve_async_inflight"] == 0
        assert gauges["serve_async_submit_queue"] == 0
    assert _no_leak()
