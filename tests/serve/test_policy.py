"""Resilience policy (ISSUE 9): deterministic backoff, breaker state
machine (trip / half-open probe / close) with structured metrics, the
load-aware degradation ladder."""
import pytest

from elemental_tpu.obs import metrics as _metrics
from elemental_tpu.serve import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker,
                                 Deadline, RetryPolicy, select_ladder)
from elemental_tpu.resilience import LADDER_NAMES


# ---------------------------------------------------------------------
# backoff
# ---------------------------------------------------------------------

def test_backoff_deterministic_and_exponential():
    p = RetryPolicy(retries=3, base_s=0.1, jitter=0.5, seed=42)
    d1 = p.delay_s(7, 1)
    assert d1 == p.delay_s(7, 1)                 # same stream, same delay
    assert p.delay_s(7, 1) != p.delay_s(8, 1)    # per-request stream
    assert p.delay_s(7, 1) != RetryPolicy(retries=3, base_s=0.1,
                                          jitter=0.5, seed=43).delay_s(7, 1)
    # base * 2^(k-1) <= delay <= base * 2^(k-1) * (1 + jitter)
    for k in (1, 2, 3):
        d = p.delay_s(7, k)
        lo = 0.1 * 2 ** (k - 1)
        assert lo <= d <= lo * 1.5
    assert p.delay_s(7, 2) > p.delay_s(7, 1)


def test_backoff_deadline_clamped(fake_clock):
    p = RetryPolicy(retries=2, base_s=10.0, jitter=0.0, seed=0)
    dl = Deadline(12.0, clock=fake_clock)
    # clamped so the retry itself still has ~base_s of budget
    assert p.delay_s(0, 1, dl) == pytest.approx(2.0)
    fake_clock.advance(13.0)
    assert p.delay_s(0, 1, dl) < 0               # expired: no retry


# ---------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------

def test_breaker_trip_halfopen_close_cycle(fake_clock):
    with _metrics.scoped() as reg:
        br = CircuitBreaker("lu__b16x2__float64", threshold=3,
                            cooldown_s=5.0, clock=fake_clock)
        assert br.state == CLOSED and br.allow()
        br.record_failure()
        br.record_failure()
        assert br.state == CLOSED                # below threshold
        br.record_failure()                      # 3rd consecutive: trip
        assert br.state == OPEN
        assert not br.allow()
        fake_clock.advance(4.9)
        assert not br.allow()                    # cooldown not elapsed
        fake_clock.advance(0.2)
        assert br.allow()                        # -> half-open, ONE probe
        assert br.state == HALF_OPEN
        assert not br.allow()                    # probe already in flight
        br.record_success()                      # probe passed
        assert br.state == CLOSED and br.allow()

        # trip again, fail the probe: straight back to open
        for _ in range(3):
            br.record_failure()
        assert br.state == OPEN
        fake_clock.advance(5.1)
        assert br.allow() and br.state == HALF_OPEN
        br.record_failure()
        assert br.state == OPEN and not br.allow()

        # metrics: gauge encodes the state, transitions counted
        gauges = [r["value"] for r in reg.to_doc()["gauges"]
                  if r["name"] == "serve_breaker_state"
                  and r["labels"] == {"bucket": "lu__b16x2__float64"}]
        assert gauges == [1]                     # open
        trans = {dict(lb)["to"]: v for (nm, lb), v in
                 reg.counters("serve_breaker_transitions").items()}
        assert trans == {"open": 3, "half_open": 2, "closed": 1}


def test_breaker_success_resets_consecutive_count(fake_clock):
    br = CircuitBreaker("b", threshold=3, clock=fake_clock)
    br.record_failure()
    br.record_failure()
    br.record_success()                          # streak broken
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED                    # 2 consecutive, not 3
    br.record_failure()
    assert br.state == OPEN
    doc = br.to_doc()
    assert doc["state"] == "open" and doc["threshold"] == 3


# ---------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------

@pytest.mark.parametrize("op", ["lu", "hpd"])
def test_select_ladder_pressure_gates_quant(op):
    """Under pressure the full ladder runs quant-first (the EQuARX
    cheap-but-narrow trade); unloaded it starts at the full-wire fast
    rung."""
    hot = select_ladder(op, pressure=0.9)
    assert tuple(r.name for r in hot) == LADDER_NAMES
    cold = select_ladder(op, pressure=0.1)
    assert tuple(r.name for r in cold) == tuple(
        n for n in LADDER_NAMES if n != "quant")
    assert cold[0].name == "fast"
    # the boundary is inclusive-hot
    assert tuple(r.name for r in select_ladder(op, 0.5)) == LADDER_NAMES
    # custom threshold
    assert len(select_ladder(op, 0.2, degrade_pressure=0.1)) == \
        len(LADDER_NAMES)
