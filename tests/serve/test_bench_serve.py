"""bench_serve.py schema + bench_diff gating of the serve metrics
(ISSUE 9): the p50/p99/solves-per-sec keys exist, and bench_diff treats
the latency percentiles as lower-is-better."""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir)


def _load(name, *rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, *rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench_serve():
    return _load("bench_serve_mod", "bench_serve.py")


@pytest.fixture(scope="module")
def bd():
    return _load("bench_diff_mod", "tools", "bench_diff.py")


def test_run_bench_schema(grid24, bench_serve):
    doc = bench_serve.run_bench(
        requests=6, n=16, grid_spec=f"{grid24.height}x{grid24.width}",
        seed=0)
    assert doc["schema"] == bench_serve.BENCH_SERVE_SCHEMA
    for key in ("serve_p50_ms", "serve_p99_ms", "serve_solves_per_sec"):
        assert isinstance(doc[key], float) and doc[key] > 0
    assert doc["serve_p50_ms"] <= doc["serve_p99_ms"]
    assert doc["ok"] == doc["requests"] == 6
    # warmup compiled every geometry: the measured window compiles nothing
    assert doc["exec_compiles"] == 0
    assert doc["exec_hits"] >= doc["batches"] >= 1
    # ISSUE 14: the async pass rides every bench run -- its measured
    # window reuses the sync warmup's executables (zero compiles), its
    # payloads are semantically identical, and the worker never leaks
    for key in ("serve_async_p50_ms", "serve_async_p99_ms",
                "serve_async_solves_per_sec"):
        assert isinstance(doc[key], float) and doc[key] > 0
    assert doc["serve_async_ok"] == 6
    assert doc["serve_async_exec_compiles"] == 0
    assert doc["serve_async_payload_identical"] is True
    assert doc["serve_async_thread_leak"] is False
    assert doc["serve_async_speedup"] > 0
    assert doc["serve_pipeline_occupancy"] >= 0.0


def _doc(tmp_path, path, **kv):
    p = tmp_path / path
    p.write_text(json.dumps(kv))
    return str(p)


def test_bench_diff_gates_serve_metrics(tmp_path, bd):
    """serve_p99_ms regresses UPWARD (lower-is-better); solves/sec
    regresses downward; both gated by default."""
    assert "serve_p99_ms" in bd.DEFAULT_METRICS
    assert "serve_solves_per_sec" in bd.DEFAULT_METRICS
    assert "serve_p99_ms" in bd.LOWER_IS_BETTER
    # ISSUE 14: the async pipeline's metrics gate too
    assert "serve_async_p99_ms" in bd.DEFAULT_METRICS
    assert "serve_async_solves_per_sec" in bd.DEFAULT_METRICS
    assert "serve_async_p99_ms" in bd.LOWER_IS_BETTER
    base = _doc(tmp_path, "BENCH_r01.json", serve_p99_ms=10.0,
                serve_solves_per_sec=100.0)
    # p99 doubled + throughput halved: both regress
    cur = _doc(tmp_path, "cur.json", serve_p99_ms=20.0,
               serve_solves_per_sec=50.0)
    rows = bd.compare(bd.load_doc(cur), [(base, bd.load_doc(base))],
                      ["serve_p99_ms", "serve_solves_per_sec"],
                      {None: 0.25})
    verdicts = {name: regressed for name, _, _, _, _, regressed in rows}
    assert verdicts == {"serve_p99_ms": True, "serve_solves_per_sec": True}
    # p99 IMPROVED (halved) + throughput doubled: clean
    cur2 = _doc(tmp_path, "cur2.json", serve_p99_ms=5.0,
                serve_solves_per_sec=200.0)
    rows2 = bd.compare(bd.load_doc(cur2), [(base, bd.load_doc(base))],
                       ["serve_p99_ms", "serve_solves_per_sec"],
                       {None: 0.25})
    assert all(not r[-1] for r in rows2)
    # within threshold: clean
    cur3 = _doc(tmp_path, "cur3.json", serve_p99_ms=12.0,
                serve_solves_per_sec=90.0)
    rows3 = bd.compare(bd.load_doc(cur3), [(base, bd.load_doc(base))],
                       ["serve_p99_ms", "serve_solves_per_sec"],
                       {None: 0.25})
    assert all(not r[-1] for r in rows3)


def test_bench_diff_best_baseline_inverts_for_latency(tmp_path, bd):
    """best = MIN across baselines for lower-is-better metrics."""
    b1 = _doc(tmp_path, "BENCH_r01.json", serve_p99_ms=30.0)
    b2 = _doc(tmp_path, "BENCH_r02.json", serve_p99_ms=10.0)
    cur = _doc(tmp_path, "cur.json", serve_p99_ms=14.0)
    rows = bd.compare(bd.load_doc(cur),
                      [(b1, bd.load_doc(b1)), (b2, bd.load_doc(b2))],
                      ["serve_p99_ms"], {None: 0.25})
    name, curv, best, src, thr, regressed = rows[0]
    assert best == 10.0 and os.path.basename(src) == "BENCH_r02.json"
    assert regressed is True                     # 14 > 1.25 * 10
    # and a tflops-style metric still gates downward on the same docs
    b3 = _doc(tmp_path, "BENCH_r03.json", vs_baseline=0.7)
    cur4 = _doc(tmp_path, "cur4.json", vs_baseline=0.6)
    rows4 = bd.compare(bd.load_doc(cur4), [(b3, bd.load_doc(b3))],
                       ["vs_baseline"], {None: 0.10})
    assert rows4[0][-1] is True


@pytest.mark.slow
def test_bench_serve_cli_smoke():
    """The subprocess path check.sh runs (slow-marked: own jax boot)."""
    out = subprocess.run(
        [sys.executable, "bench_serve.py", "--smoke"],
        cwd=_ROOT, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["schema"] == "bench_serve/v1"
