"""SolverService end-to-end (ISSUE 9): batched fastpath certification,
result schema, deadline drops, shedding, breaker integration, structured
failure for unsolvable requests."""
import numpy as np
import pytest

from elemental_tpu.obs import metrics as _metrics
from elemental_tpu.serve import RESULT_SCHEMA, SolverService

from .conftest import diag_dom, spd


def _mixed_workload(rng, count=6):
    out = []
    for i in range(count):
        n = (12, 16, 9)[i % 3]
        if i % 2:
            out.append(("lu", diag_dom(rng, n), rng.normal(size=(n, 2))))
        else:
            out.append(("hpd", spd(rng, n), rng.normal(size=(n, 2))))
    return out


def test_fastpath_serving_end_to_end(grid24):
    rng = np.random.default_rng(20)
    svc = SolverService(grid24)
    work = _mixed_workload(rng)
    ids = [svc.submit(op, A, B) for op, A, B in work]
    assert all(isinstance(i, int) for i in ids)
    done = svc.drain()
    assert set(done) == set(ids)
    for (op, A, B), rid in zip(work, ids):
        doc = done[rid]
        assert doc["status"] == "ok" and doc["path"] == "fastpath"
        assert doc["rung"] == "fastpath"
        assert doc["residual"] <= doc["tol"]
        X = svc.solutions[rid]
        np.testing.assert_allclose(X, np.linalg.solve(A, B),
                                   rtol=1e-8, atol=1e-10)
        assert doc["latency_s"] >= 0.0
    assert svc.queue_depth() == 0


def test_result_schema_pin(grid24):
    rng = np.random.default_rng(21)
    svc = SolverService(grid24)
    X, doc = svc.solve("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)))
    assert doc["schema"] == RESULT_SCHEMA
    assert set(doc) == {"schema", "id", "op", "n", "nrhs", "bucket",
                        "status", "path", "rung", "residual", "tol",
                        "retries", "bisected", "timed_out", "latency_s",
                        "deadline", "certificate", "breaker", "dispatch",
                        "grid", "tenant", "timeline"}
    # lifecycle timeline (ISSUE 20): a complete serve_timeline/v1
    from elemental_tpu.obs.lifecycle import check_timeline
    assert check_timeline(doc["timeline"], path=doc["path"]) == []
    # fleet provenance (ISSUE 19): None on a direct single service
    assert doc["grid"] is None and doc["tenant"] is None
    assert doc["bucket"] == "lu__b8x1__float64"
    assert doc["deadline"] is None and doc["certificate"] is None
    assert doc["breaker"] == "closed"
    # tuner-fed dispatch provenance (ISSUE 14): fastpath requests carry
    # the resolved route; a cold tuning cache routes vmap with an empty
    # tune token
    disp = doc["dispatch"]
    assert disp is not None and disp["route"] in ("vmap", "grid")
    assert {"route", "driver_op", "tune_token", "source"} <= set(disp)
    assert X is not None


def test_expired_deadline_dropped_before_launch(grid24, fake_clock):
    """A request whose deadline lapses in the queue is finalized as a
    structured timed_out WITHOUT paying for a dispatch."""
    rng = np.random.default_rng(22)
    svc = SolverService(grid24, clock=fake_clock, sleep=fake_clock.sleep)
    ok_id = svc.submit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)))
    dead_id = svc.submit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)),
                         budget_s=1.0)
    fake_clock.advance(2.0)
    done = svc.drain()
    assert done[dead_id]["status"] == "timed_out"
    assert done[dead_id]["path"] == "dropped"
    assert done[dead_id]["timed_out"] is True
    assert done[dead_id]["deadline"]["remaining_s"] < 0
    assert dead_id not in svc.solutions
    assert done[ok_id]["status"] == "ok"        # no collateral


def test_submit_sheds_under_queue_pressure(grid24, fake_clock):
    """With a hopeless throughput estimate, deadline'd submissions shed
    fast once the bucket queue is deep; the structured reject counts."""
    rng = np.random.default_rng(23)
    svc = SolverService(grid24, clock=fake_clock, sleep=fake_clock.sleep,
                        flops_per_s=1.0, max_batch=2)
    A, B = diag_dom(rng, 8), rng.normal(size=(8, 1))
    with _metrics.scoped() as reg:
        assert isinstance(svc.submit("lu", A, B), int)   # no deadline
        rej = svc.submit("lu", A, B, budget_s=5.0)
        assert isinstance(rej, dict)
        assert rej["reason"] == "queue_pressure"
        assert reg.counter_value("serve_rejects",
                                 reason="queue_pressure") == 1


def test_unsolvable_request_fails_structured(grid24):
    """A singular system can never certify: fastpath fails, bisect
    isolates it, escalation exhausts the ladder, and the result is a
    structured failure WITH the certificate -- while a batch-mate in the
    same bucket still certifies (fault isolation without faults)."""
    rng = np.random.default_rng(24)
    n = 12
    Asing = np.ones((n, n))                      # rank 1
    B = rng.normal(size=(n, 1))
    svc = SolverService(grid24, retries=0)
    good_id = svc.submit("lu", diag_dom(rng, n), B)
    bad_id = svc.submit("lu", Asing, B)
    done = svc.drain()
    assert done[good_id]["status"] == "ok"
    bad = done[bad_id]
    assert bad["status"] == "failed"
    assert bad["path"] == "escalated" and bad["bisected"] is True
    cert = bad["certificate"]
    assert cert is not None and cert["certified"] is False
    assert cert["singular"] is True
    assert bad_id not in svc.solutions           # zero silent garbage


def test_breaker_trips_rejects_then_recovers(grid24, fake_clock):
    """Consecutive fastpath certification failures trip the bucket's
    breaker: new submissions reject fast; after the cooldown a probe
    batch closes it again.  Deterministic under the fake clock."""
    rng = np.random.default_rng(25)
    n = 8
    Asing = np.ones((n, n))
    B = rng.normal(size=(n, 1))
    svc = SolverService(grid24, clock=fake_clock, sleep=fake_clock.sleep,
                        breaker_threshold=2, breaker_cooldown_s=10.0,
                        retries=0, max_batch=1)
    # two failing batches (max_batch=1 => one request per batch)
    for _ in range(2):
        rid = svc.submit("lu", Asing, B)
        assert isinstance(rid, int)
        svc.drain()
    key = "lu__b8x1__float64"
    assert svc.breakers[key].state == "open"
    rej = svc.submit("lu", diag_dom(rng, n), B)
    assert isinstance(rej, dict) and rej["reason"] == "breaker_open"
    # queued work admitted after cooldown runs as the half-open probe
    fake_clock.advance(11.0)
    rid = svc.submit("lu", diag_dom(rng, n), B)
    assert isinstance(rid, int)
    done = svc.drain()
    assert done[rid]["status"] == "ok"
    assert svc.breakers[key].state == "closed"   # probe success closed it


def test_open_breaker_routes_queued_to_escalation(grid24, fake_clock):
    """Requests already queued when the breaker opens are NOT dropped:
    they bypass the poisoned fastpath straight to the certified path."""
    rng = np.random.default_rng(26)
    n = 8
    Asing = np.ones((n, n))
    B = rng.normal(size=(n, 1))
    svc = SolverService(grid24, clock=fake_clock, sleep=fake_clock.sleep,
                        breaker_threshold=1, breaker_cooldown_s=1e9,
                        retries=0, max_batch=1)
    bad = svc.submit("lu", Asing, B)
    good = svc.submit("lu", diag_dom(rng, n), B)  # queued before the trip
    done = svc.drain()
    assert done[bad]["status"] == "failed"
    gd = done[good]
    assert gd["status"] == "ok"
    assert gd["path"] == "escalated"             # fastpath was bypassed
    assert gd["rung"] in ("quant", "fast", "refine", "abft", "fp32",
                          "classic")


def test_pressure_and_gauges(grid24):
    rng = np.random.default_rng(27)
    svc = SolverService(grid24, capacity=4)
    with _metrics.scoped() as reg:
        for _ in range(3):
            svc.submit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)))
        assert svc.pressure() == pytest.approx(3 / 4)
        gauges = {r["name"]: r["value"] for r in reg.to_doc()["gauges"]}
        assert gauges["serve_queue_depth"] == 3
        assert gauges["serve_pressure"] == pytest.approx(0.75)
        svc.drain()
        gauges = {r["name"]: r["value"] for r in reg.to_doc()["gauges"]}
        assert gauges["serve_queue_depth"] == 0
        assert reg.counter_value("serve_requests", op="lu",
                                 status="ok") == 3


def test_fifo_across_buckets(grid24, fake_clock):
    """drain picks the bucket holding the OLDEST queued request first."""
    rng = np.random.default_rng(28)
    svc = SolverService(grid24, clock=fake_clock, sleep=fake_clock.sleep)
    a = svc.submit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)))
    fake_clock.advance(1.0)
    b = svc.submit("hpd", spd(rng, 8), rng.normal(size=(8, 1)))
    fake_clock.advance(1.0)
    done = svc.drain()
    # the lu request waited longer than the hpd one
    assert done[a]["latency_s"] > done[b]["latency_s"]
    assert done[a]["status"] == done[b]["status"] == "ok"


# ---------------------------------------------------------------------
# ISSUE 14: tuner-fed dispatch + the lstsq serving path
# ---------------------------------------------------------------------

def test_measured_winner_routes_bucket_to_grid(grid24, tmp_path,
                                               monkeypatch):
    """A MEASURED tuning-cache winner that beats the vmap estimate pulls
    the request off the batch path onto the distributed driver, and the
    decision lands in serve_result/v1 provenance."""
    import jax
    from elemental_tpu.tune import cache as tc
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    rng = np.random.default_rng(31)
    svc = SolverService(grid24)
    key = tc.make_key("cholesky", (16, 16), "float64",
                      (grid24.height, grid24.width), jax.default_backend())
    tc.save(key, {"nb": 8}, source="measured", metric={"seconds": 1e-12})
    X, doc = svc.solve("hpd", spd(rng, 16), rng.normal(size=(16, 2)))
    assert doc["status"] == "ok"
    assert doc["path"] == "grid"
    disp = doc["dispatch"]
    assert disp["route"] == "grid" and disp["source"] == "measured"
    assert disp["measured_s"] == pytest.approx(1e-12)
    assert X is not None and doc["residual"] <= doc["tol"]


def test_lstsq_fastpath_and_grid_qr_escalation(grid24):
    """Tall least-squares requests serve through the batched QR fast
    path; with the fastpath off they escalate to the distributed QR
    rung ('grid_qr') -- both certify on the normal-equations residual."""
    rng = np.random.default_rng(32)
    A = rng.normal(size=(24, 10))
    B = rng.normal(size=(24, 2))
    Xref = np.linalg.lstsq(A, B, rcond=None)[0]
    svc = SolverService(grid24)
    X, doc = svc.solve("lstsq", A, B)
    assert doc["status"] == "ok" and doc["path"] == "fastpath"
    assert doc["bucket"].startswith("lstsq__b")
    np.testing.assert_allclose(X, Xref, rtol=1e-8, atol=1e-10)
    svc2 = SolverService(grid24, fastpath=False)
    X2, doc2 = svc2.solve("qr", A, B)            # 'qr' aliases lstsq
    assert doc2["status"] == "ok" and doc2["path"] == "escalated"
    assert doc2["rung"] == "grid_qr"
    np.testing.assert_allclose(X2, Xref, rtol=1e-6, atol=1e-8)


# ---------------------------------------------------------------------
# SATELLITE (ISSUE 11): graceful shutdown -- zero lost requests
# ---------------------------------------------------------------------

def test_shutdown_drain_completes_everything(grid24):
    """shutdown(drain=True): every queued request COMPLETES through the
    normal path; nothing is lost, and new submits are rejected."""
    rng = np.random.default_rng(29)
    svc = SolverService(grid24)
    work = _mixed_workload(rng, count=4)
    ids = [svc.submit(op, A, B) for op, A, B in work]
    done = svc.shutdown(drain=True)
    # zero lost: every accepted id is settled, all executed ok
    assert set(done) == set(ids)
    assert all(done[i]["status"] == "ok" for i in ids)
    assert svc.queue_depth() == 0
    # post-shutdown submissions get the structured reject
    rej = svc.submit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)))
    assert isinstance(rej, dict)
    assert rej["schema"] == "serve_reject/v1"
    assert rej["reason"] == "shutdown"


def test_shutdown_flush_rejects_queued(grid24):
    """shutdown(drain=False): queued requests are NOT executed but each
    gets a structured serve_reject/v1 (reason='shutdown') carrying its
    id -- zero silent drops, pinned against the accepted-id set."""
    rng = np.random.default_rng(30)
    svc = SolverService(grid24)
    work = _mixed_workload(rng, count=5)
    ids = [svc.submit(op, A, B) for op, A, B in work]
    with _metrics.scoped() as reg:
        done = svc.shutdown(drain=False)
        assert reg.counter_value("serve_rejects",
                                 reason="shutdown") == len(ids)
    assert set(done) == set(ids)
    for rid in ids:
        doc = svc.results[rid]
        assert doc["schema"] == "serve_reject/v1"
        assert doc["reason"] == "shutdown"
        assert doc["id"] == rid
        assert rid not in svc.solutions          # never executed
    assert svc.queue_depth() == 0
    # idempotent: a second shutdown settles nothing new
    assert svc.shutdown() == {}
