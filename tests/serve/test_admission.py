"""Admission layer (ISSUE 9): deadlines, tuner-aligned bucketing, load
shedding, structured rejects."""
import numpy as np
import pytest

from elemental_tpu.serve import admission as adm
from elemental_tpu.serve import AdmissionController, Deadline, make_bucket

from .conftest import diag_dom


# ---------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------

def test_deadline_budget_elapsed_remaining(fake_clock):
    dl = Deadline(2.0, clock=fake_clock)
    assert dl.elapsed() == 0.0 and dl.remaining() == 2.0
    assert not dl.expired()
    fake_clock.advance(1.5)
    assert dl.elapsed() == pytest.approx(1.5)
    assert dl.remaining() == pytest.approx(0.5)
    fake_clock.advance(1.0)
    assert dl.expired() and dl.remaining() == pytest.approx(-0.5)
    doc = dl.to_doc()
    assert set(doc) == {"budget_s", "elapsed_s", "remaining_s"}
    assert doc["budget_s"] == 2.0


# ---------------------------------------------------------------------
# bucketing: pow2 per dim, EXACTLY the tuner's shape_bucket
# ---------------------------------------------------------------------

@pytest.mark.parametrize("n,nrhs,bn,brhs", [
    (100, 3, 128, 4), (16, 2, 16, 2), (17, 1, 32, 1), (1, 1, 1, 1),
    (2048, 5, 2048, 8),
])
def test_bucket_pow2(n, nrhs, bn, brhs):
    b = make_bucket("lu", n, nrhs, np.float32)
    assert (b.n, b.nrhs) == (bn, brhs)
    assert b.dtype == "float32"
    from elemental_tpu.tune.cache import shape_bucket
    assert (b.n, b.nrhs) == shape_bucket((n, nrhs))


def test_bucket_key_and_flops():
    b = make_bucket("hpd", 100, 2, np.float64)
    assert b.key() == "hpd__b128x2__float64"
    # hpd factor ~ n^3/3, lu ~ 2n^3/3
    blu = make_bucket("lu", 100, 2, np.float64)
    assert blu.solve_flops() > b.solve_flops()


# ---------------------------------------------------------------------
# admit: validation, rejects, shedding
# ---------------------------------------------------------------------

def test_admit_happy_path_ids_increment(fake_clock):
    ctrl = AdmissionController(clock=fake_clock)
    rng = np.random.default_rng(0)
    A = diag_dom(rng, 12)
    B = rng.normal(size=(12, 2))
    r1 = ctrl.admit("lu", A, B)
    r2 = ctrl.admit("cholesky", A @ A.T, B)    # alias -> hpd
    assert (r1.id, r2.id) == (0, 1)
    assert r2.op == "hpd"
    assert r1.bucket.key() == "lu__b16x2__float64"
    assert r1.n == 12 and r1.nrhs == 2


def test_admit_promotes_vector_rhs():
    rng = np.random.default_rng(1)
    ctrl = AdmissionController()
    req = ctrl.admit("lu", diag_dom(rng, 8), rng.normal(size=8))
    assert req.B.shape == (8, 1)


def test_admit_bad_request_structured():
    ctrl = AdmissionController()
    rng = np.random.default_rng(2)
    rej = ctrl.admit("svd", diag_dom(rng, 8), rng.normal(size=(8, 1)))
    assert rej["schema"] == adm.REJECT_SCHEMA
    assert rej["reason"] == "bad_request"
    rej2 = ctrl.admit("lu", rng.normal(size=(8, 4)), rng.normal(size=(8, 1)))
    assert rej2["reason"] == "bad_request"
    rej3 = ctrl.admit("lu", diag_dom(rng, 8), rng.normal(size=(6, 1)))
    assert rej3["reason"] == "bad_request"
    # lstsq accepts tall A only: a WIDE system is underdetermined
    rej4 = ctrl.admit("lstsq", rng.normal(size=(5, 12)),
                      rng.normal(size=(5, 1)))
    assert rej4["reason"] == "bad_request"


def test_admit_lstsq_and_qr_alias(fake_clock):
    """ISSUE 14: 'qr' aliases lstsq; tall systems bucket with the padded
    row count M >= m + (N - n) so the identity pad always fits."""
    ctrl = AdmissionController(clock=fake_clock)
    rng = np.random.default_rng(4)
    req = ctrl.admit("qr", rng.normal(size=(12, 5)),
                     rng.normal(size=(12, 2)))
    assert req.op == "lstsq"
    assert req.bucket.key() == "lstsq__b16x8x2__float64"
    assert (req.bucket.m, req.bucket.n) == (16, 8)
    assert req.bucket.m >= 12 + (req.bucket.n - 5)
    # square systems are legal least-squares problems too (m == n)
    sq = ctrl.admit("lstsq", rng.normal(size=(8, 8)),
                    rng.normal(size=(8, 1)))
    assert sq.bucket.key() == "lstsq__b8x8x1__float64"
    # lstsq flops scale with m (QR of the tall pad), square ops with n^3
    assert req.bucket.solve_flops() > 0.0


def test_admit_expired_deadline_rejects(fake_clock):
    ctrl = AdmissionController(clock=fake_clock)
    rng = np.random.default_rng(3)
    dl = Deadline(1.0, clock=fake_clock)
    fake_clock.advance(2.0)
    rej = ctrl.admit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)),
                     deadline=dl)
    assert rej["reason"] == "deadline_expired"
    assert rej["deadline"]["remaining_s"] == pytest.approx(-1.0)


def test_load_shedding_queue_pressure(fake_clock):
    """queue depth x bucket estimate > remaining budget => reject-fast
    with the estimate in the document; shed=False admits anyway."""
    rng = np.random.default_rng(4)
    A, B = diag_dom(rng, 8), rng.normal(size=(8, 1))
    # 1 flop/s: any queue wait estimate dwarfs any budget
    ctrl = AdmissionController(clock=fake_clock, flops_per_s=1.0,
                               max_batch=4)
    dl = Deadline(10.0, clock=fake_clock)
    rej = ctrl.admit("lu", A, B, deadline=dl, queue_depth=7)
    assert rej["reason"] == "queue_pressure"
    assert rej["estimate_s"] > 10.0
    assert rej["queue_depth"] == 7
    # no deadline => nothing to shed against
    assert not isinstance(ctrl.admit("lu", A, B, queue_depth=7), dict)
    # shedding disabled => admitted despite the hopeless estimate
    loose = AdmissionController(clock=fake_clock, flops_per_s=1.0,
                                shed=False)
    assert not isinstance(
        loose.admit("lu", A, B, deadline=Deadline(10.0, clock=fake_clock),
                    queue_depth=7), dict)


def test_queue_depth_callable_resolved_per_bucket(fake_clock):
    ctrl = AdmissionController(clock=fake_clock, flops_per_s=1.0)
    rng = np.random.default_rng(5)
    seen = []

    def depth(bucket):
        seen.append(bucket.key())
        return 3

    rej = ctrl.admit("lu", diag_dom(rng, 8), rng.normal(size=(8, 1)),
                     deadline=Deadline(1.0, clock=fake_clock),
                     queue_depth=depth)
    assert rej["reason"] == "queue_pressure"
    assert seen == ["lu__b8x1__float64"]


# ---------------------------------------------------------------------
# cost model: cold flops seed -> measured EWMA
# ---------------------------------------------------------------------

def test_estimate_cold_then_ewma():
    ctrl = AdmissionController(max_batch=4, flops_per_s=1e9)
    b = make_bucket("lu", 64, 1, np.float32)
    cold = ctrl.estimate_batch_s(b)
    assert cold == pytest.approx(b.solve_flops() * 4 / 1e9)
    ctrl.observe_batch(b, 0.5)
    assert ctrl.estimate_batch_s(b) == pytest.approx(0.5)
    ctrl.observe_batch(b, 1.0)
    est = ctrl.estimate_batch_s(b)
    assert 0.5 < est < 1.0                   # EWMA, not last-write
    # wait estimate counts whole batches (the request rides the last one)
    assert ctrl.estimated_wait_s(b, 0) == pytest.approx(est)
    assert ctrl.estimated_wait_s(b, 4) == pytest.approx(2 * est)


def test_reject_doc_schema_pin():
    doc = adm.reject_doc("queue_pressure", queue_depth=2, estimate_s=1.5)
    assert set(doc) == {"schema", "reason", "bucket", "queue_depth",
                        "estimate_s", "deadline", "detail",
                        "grid", "tenant", "timeline"}
    # single-service rejects carry the fleet fields as None (ISSUE 19):
    # absent grid == not fleet-routed, absent tenant == direct caller
    assert doc["grid"] is None and doc["tenant"] is None
    # no lifecycle trace attached -> timeline rides as None (ISSUE 20)
    assert doc["timeline"] is None
    with pytest.raises(ValueError):
        adm.reject_doc("bogus_reason")
