"""Batched executor (ISSUE 9): lossless identity padding, vmap batch
correctness vs numpy, AOT executable-cache reuse, the compute fault
seam."""
import numpy as np
import pytest

from elemental_tpu.obs import metrics as _metrics
from elemental_tpu.serve import (AdmissionController, Executor, batch_slots,
                                 make_bucket, pad_problem, residual)

from .conftest import diag_dom, spd


def _reqs(ctrl, op, problems):
    out = []
    for A, B in problems:
        r = ctrl.admit(op, A, B)
        assert not isinstance(r, dict)
        out.append(r)
    return out


@pytest.mark.parametrize("k,slots", [(1, 1), (2, 2), (3, 4), (8, 8),
                                     (9, 16)])
def test_batch_slots_pow2(k, slots):
    assert batch_slots(k) == slots


def test_pad_problem_lossless():
    """[[A,0],[0,I]] padding: the padded solution's head IS the original
    solution, its tail exactly zero."""
    rng = np.random.default_rng(10)
    A = diag_dom(rng, 12)
    B = rng.normal(size=(12, 2))
    bucket = make_bucket("lu", 12, 2, A.dtype)
    Ap, Bp = pad_problem(A, B, bucket)
    assert Ap.shape == (16, 16) and Bp.shape == (16, 2)
    np.testing.assert_array_equal(Ap[:12, :12], A)
    np.testing.assert_array_equal(Ap[12:, 12:], np.eye(4))
    assert not Ap[:12, 12:].any() and not Ap[12:, :12].any()
    Xp = np.linalg.solve(Ap, Bp)
    np.testing.assert_allclose(Xp[:12], np.linalg.solve(A, B), rtol=1e-10)
    np.testing.assert_array_equal(Xp[12:], 0)


@pytest.mark.parametrize("op", ["lu", "hpd"])
def test_run_batch_matches_numpy(op):
    """Mixed-actual-size requests of one bucket solve correctly in ONE
    batched dispatch."""
    rng = np.random.default_rng(11)
    ctrl = AdmissionController()
    probs = []
    for n in (12, 16, 9, 14):
        A = spd(rng, n) if op == "hpd" else diag_dom(rng, n)
        probs.append((A, rng.normal(size=(n, 2))))
    reqs = _reqs(ctrl, op, probs)
    assert len({r.bucket for r in reqs}) == 1        # one bucket: 16x2
    ex = Executor()
    xs, seconds = ex.run(reqs[0].bucket, reqs)
    assert seconds >= 0.0
    for (A, B), X in zip(probs, xs):
        assert X.shape == B.shape
        np.testing.assert_allclose(X, np.linalg.solve(A, B),
                                   rtol=1e-8, atol=1e-10)
        assert residual(A, B, X) < 1e-12


def test_exec_cache_compile_once_then_hits():
    rng = np.random.default_rng(12)
    ctrl = AdmissionController()
    probs = [(diag_dom(rng, 12), rng.normal(size=(12, 1)))
             for _ in range(3)]
    ex = Executor()
    with _metrics.scoped() as reg:
        reqs = _reqs(ctrl, "lu", probs)
        b = reqs[0].bucket
        ex.run(b, reqs)                       # compile (slots=4)
        ex.run(b, reqs)                       # hit
        ex.run(b, reqs[:1])                   # new slot count: compile
        ex.run(b, reqs[:1])                   # hit

        def count(event):
            return sum(v for (nm, lb), v in
                       reg.counters("serve_exec_cache_events").items()
                       if dict(lb).get("event") == event)

        assert count("compile") == 2
        assert count("miss") == 2
        assert count("hit") == 2
    assert len(ex.cache.stats()["entries"]) == 2
    ex.cache.clear()
    assert ex.cache.stats()["entries"] == []


def test_exec_cache_key_vocabulary():
    """Keys carry (op, bucket, slots, dtype, backend) -- the
    tuning_cache/v1 style."""
    from elemental_tpu.serve.executor import ExecutableCache
    b = make_bucket("hpd", 100, 2, np.float32)
    key = ExecutableCache.key("hpd", b, 8, "cpu")
    assert key == "hpd__b128x2__x8__float32__cpu"


def test_residual_semantics():
    rng = np.random.default_rng(13)
    A = diag_dom(rng, 8)
    B = rng.normal(size=(8, 1))
    X = np.linalg.solve(A, B)
    assert residual(A, B, X) < 1e-14
    assert residual(A, B, np.full_like(X, np.nan)) == float("inf")
    assert residual(A, B, X + 1.0) > 1e-3


def test_compute_fault_seam_on_batch_output():
    """The executor's batch output crosses the 'compute' fault target:
    corruption lands in the returned solutions, is logged with the batch
    shape, and replays bit-identically."""
    from elemental_tpu.resilience import (FaultPlan, FaultSpec,
                                          fault_injection, logs_identical)
    rng = np.random.default_rng(14)
    ctrl = AdmissionController()
    probs = [(diag_dom(rng, 16), rng.normal(size=(16, 2)))
             for _ in range(4)]
    ex = Executor()

    def run(plan):
        reqs = _reqs(AdmissionController(), "lu", probs)
        with fault_injection(plan):
            xs, _ = ex.run(reqs[0].bucket, reqs)
        return xs

    mk = lambda: FaultPlan(seed=7, faults=[
        FaultSpec("compute", "nan", call=0, nelem=3)])
    p1, p2 = mk(), mk()
    xs1 = run(p1)
    xs2 = run(p2)
    assert p1.fired() == 1
    ev = p1.log[0]
    assert ev.target == "compute" and ev.shape == (4, 16, 2)
    assert np.isnan(ev.after).all()
    assert any(not np.isfinite(x).all() for x in xs1)
    assert logs_identical(p1, p2)
    for a, b in zip(xs1, xs2):
        np.testing.assert_array_equal(a, b)
    # and without a plan the output is clean again
    reqs = _reqs(AdmissionController(), "lu", probs)
    xs3, _ = ex.run(reqs[0].bucket, reqs)
    assert all(np.isfinite(x).all() for x in xs3)
