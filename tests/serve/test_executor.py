"""Batched executor (ISSUE 9): lossless identity padding, vmap batch
correctness vs numpy, AOT executable-cache reuse, the compute fault
seam."""
import numpy as np
import pytest

from elemental_tpu.obs import metrics as _metrics
from elemental_tpu.serve import (AdmissionController, Executor, batch_slots,
                                 make_bucket, pad_problem, residual)

from .conftest import diag_dom, spd


def _reqs(ctrl, op, problems):
    out = []
    for A, B in problems:
        r = ctrl.admit(op, A, B)
        assert not isinstance(r, dict)
        out.append(r)
    return out


@pytest.mark.parametrize("k,slots", [(1, 1), (2, 2), (3, 4), (8, 8),
                                     (9, 16)])
def test_batch_slots_pow2(k, slots):
    assert batch_slots(k) == slots


def test_pad_problem_lossless():
    """[[A,0],[0,I]] padding: the padded solution's head IS the original
    solution, its tail exactly zero."""
    rng = np.random.default_rng(10)
    A = diag_dom(rng, 12)
    B = rng.normal(size=(12, 2))
    bucket = make_bucket("lu", 12, 2, A.dtype)
    Ap, Bp = pad_problem(A, B, bucket)
    assert Ap.shape == (16, 16) and Bp.shape == (16, 2)
    np.testing.assert_array_equal(Ap[:12, :12], A)
    np.testing.assert_array_equal(Ap[12:, 12:], np.eye(4))
    assert not Ap[:12, 12:].any() and not Ap[12:, :12].any()
    Xp = np.linalg.solve(Ap, Bp)
    np.testing.assert_allclose(Xp[:12], np.linalg.solve(A, B), rtol=1e-10)
    np.testing.assert_array_equal(Xp[12:], 0)


@pytest.mark.parametrize("op", ["lu", "hpd"])
def test_run_batch_matches_numpy(op):
    """Mixed-actual-size requests of one bucket solve correctly in ONE
    batched dispatch."""
    rng = np.random.default_rng(11)
    ctrl = AdmissionController()
    probs = []
    for n in (12, 16, 9, 14):
        A = spd(rng, n) if op == "hpd" else diag_dom(rng, n)
        probs.append((A, rng.normal(size=(n, 2))))
    reqs = _reqs(ctrl, op, probs)
    assert len({r.bucket for r in reqs}) == 1        # one bucket: 16x2
    ex = Executor()
    xs, seconds = ex.run(reqs[0].bucket, reqs)
    assert seconds >= 0.0
    for (A, B), X in zip(probs, xs):
        assert X.shape == B.shape
        np.testing.assert_allclose(X, np.linalg.solve(A, B),
                                   rtol=1e-8, atol=1e-10)
        assert residual(A, B, X) < 1e-12


def test_exec_cache_compile_once_then_hits():
    rng = np.random.default_rng(12)
    ctrl = AdmissionController()
    probs = [(diag_dom(rng, 12), rng.normal(size=(12, 1)))
             for _ in range(3)]
    ex = Executor()
    with _metrics.scoped() as reg:
        reqs = _reqs(ctrl, "lu", probs)
        b = reqs[0].bucket
        ex.run(b, reqs)                       # compile (slots=4)
        ex.run(b, reqs)                       # hit
        ex.run(b, reqs[:1])                   # new slot count: compile
        ex.run(b, reqs[:1])                   # hit

        def count(event):
            return sum(v for (nm, lb), v in
                       reg.counters("serve_exec_cache_events").items()
                       if dict(lb).get("event") == event)

        assert count("compile") == 2
        assert count("miss") == 2
        assert count("hit") == 2
    assert len(ex.cache.stats()["entries"]) == 2
    ex.cache.clear()
    assert ex.cache.stats()["entries"] == []


def test_exec_cache_key_vocabulary():
    """Keys carry (op, bucket, slots, dtype, backend) -- the
    tuning_cache/v1 style."""
    from elemental_tpu.serve.executor import ExecutableCache
    b = make_bucket("hpd", 100, 2, np.float32)
    key = ExecutableCache.key("hpd", b, 8, "cpu")
    assert key == "hpd__b128x2__x8__float32__cpu"


def test_residual_semantics():
    rng = np.random.default_rng(13)
    A = diag_dom(rng, 8)
    B = rng.normal(size=(8, 1))
    X = np.linalg.solve(A, B)
    assert residual(A, B, X) < 1e-14
    assert residual(A, B, np.full_like(X, np.nan)) == float("inf")
    assert residual(A, B, X + 1.0) > 1e-3


# ---------------------------------------------------------------------
# ISSUE 14: batched QR least-squares + donation + tuner-provenance keys
# ---------------------------------------------------------------------

def test_pad_problem_ls_lossless():
    """The lstsq pad puts an identity in the EXTRA rows x EXTRA columns:
    pad columns are orthogonal to A's, the padded normal equations
    decouple, and the padded minimizer's head IS the original LS
    minimizer (tail exactly zero)."""
    from elemental_tpu.serve import pad_problem_ls
    rng = np.random.default_rng(15)
    A = rng.normal(size=(13, 5))
    B = rng.normal(size=(13, 2))
    bucket = make_bucket("lstsq", 5, 2, A.dtype, m=13)
    assert (bucket.m, bucket.n, bucket.nrhs) == (16, 8, 2)
    Ap, Bp = pad_problem_ls(A, B, bucket)
    assert Ap.shape == (16, 8) and Bp.shape == (16, 2)
    np.testing.assert_array_equal(Ap[:13, :5], A)
    np.testing.assert_array_equal(Ap[13:16, 5:8], np.eye(3))
    assert not Ap[:13, 5:].any() and not Ap[13:, :5].any()
    assert not Bp[13:].any()
    Xp = np.linalg.lstsq(Ap, Bp, rcond=None)[0]
    np.testing.assert_allclose(Xp[:5], np.linalg.lstsq(A, B, rcond=None)[0],
                               rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(Xp[5:], 0, atol=1e-11)


def test_run_batch_lstsq_matches_numpy():
    """Mixed-actual-shape tall systems of one lstsq bucket solve to the
    LS minimizer in ONE batched QR dispatch."""
    from elemental_tpu.serve import ls_residual
    rng = np.random.default_rng(16)
    ctrl = AdmissionController()
    probs = [(rng.normal(size=(m, n)), rng.normal(size=(m, 2)))
             for m, n in ((12, 5), (16, 8), (10, 7), (14, 8))]
    reqs = _reqs(ctrl, "lstsq", probs)
    assert len({r.bucket for r in reqs}) == 1        # one bucket: 16x8x2
    ex = Executor()
    xs, seconds = ex.run(reqs[0].bucket, reqs)
    assert seconds >= 0.0
    for (A, B), X in zip(probs, xs):
        assert X.shape == (A.shape[1], 2)
        np.testing.assert_allclose(X, np.linalg.lstsq(A, B, rcond=None)[0],
                                   rtol=1e-7, atol=1e-9)
        assert ls_residual(A, B, X) < 1e-12


def test_ls_residual_semantics():
    from elemental_tpu.serve import ls_residual
    rng = np.random.default_rng(17)
    A = rng.normal(size=(20, 6))
    B = rng.normal(size=(20, 2))
    X = np.linalg.lstsq(A, B, rcond=None)[0]
    # vanishes at the minimizer even though B - A X cannot
    assert ls_residual(A, B, X) < 1e-14
    assert np.linalg.norm(B - A @ X) > 1e-3
    assert ls_residual(A, B, X + 1.0) > 1e-3
    assert ls_residual(A, B, np.full_like(X, np.nan)) == float("inf")


def test_exec_cache_key_tune_and_donate_variants():
    """Default keys are byte-identical to PR 9; a tuner-provenance token
    and the donation flag each append their own suffix (distinct cache
    entries, never a stale or non-donating executable)."""
    from elemental_tpu.serve.executor import ExecutableCache
    b = make_bucket("hpd", 100, 2, np.float32)
    base = ExecutableCache.key("hpd", b, 8, "cpu")
    assert base == "hpd__b128x2__x8__float32__cpu"
    assert ExecutableCache.key("hpd", b, 8, "cpu", tune="0a1b2c3d") \
        == base + "__t0a1b2c3d"
    assert ExecutableCache.key("hpd", b, 8, "cpu", donate=True) \
        == base + "__donated"
    assert ExecutableCache.key("hpd", b, 8, "cpu", tune="0a1b2c3d",
                               donate=True) == base + "__t0a1b2c3d__donated"
    # lstsq buckets carry the padded row count in the geometry
    bl = make_bucket("lstsq", 5, 2, np.float32, m=13)
    assert ExecutableCache.key("lstsq", bl, 4, "cpu") \
        == "lstsq__b16x8x2__x4__float32__cpu"


def test_donated_executable_distinct_entry_same_bits():
    """donate=True compiles its own __donated executable; the solutions
    are bit-identical to the non-donating path."""
    rng = np.random.default_rng(18)
    ctrl = AdmissionController()
    probs = [(diag_dom(rng, 12), rng.normal(size=(12, 2)))
             for _ in range(3)]
    reqs = _reqs(ctrl, "lu", probs)
    b = reqs[0].bucket
    ex = Executor()
    xs0, _ = ex.run(b, reqs)
    xs1, _ = ex.run(b, reqs, donate=True)
    entries = ex.cache.stats()["entries"]
    assert len(entries) == 2
    assert sum(k.endswith("__donated") for k in entries) == 1
    for a, c in zip(xs0, xs1):
        np.testing.assert_array_equal(a, c)


def test_tune_token_resweep_invalidates_executable(tmp_path, monkeypatch):
    """SATELLITE: executable keys carry the resolved tuner provenance --
    a tuning-cache re-sweep (save bumps the in-process epoch) changes the
    token, so the next batch compiles FRESH instead of serving the stale
    executable; a second re-sweep re-keys again."""
    import jax
    from elemental_tpu.serve.executor import tune_token
    from elemental_tpu.tune import cache as tc
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    backend = jax.default_backend()
    rng = np.random.default_rng(19)
    ctrl = AdmissionController()
    reqs = _reqs(ctrl, "hpd", [(spd(rng, 12), rng.normal(size=(12, 1)))])
    b = reqs[0].bucket                               # hpd 16x1 float64
    assert tune_token("hpd", b, backend) == ""       # cold cache: PR-9 key
    ex = Executor()
    with _metrics.scoped() as reg:
        def compiles():
            return sum(v for (nm, lb), v in
                       reg.counters("serve_exec_cache_events").items()
                       if dict(lb).get("event") == "compile")

        ex.run(b, reqs)
        ex.run(b, reqs)
        assert compiles() == 1                       # warm: hit
        key = tc.make_key("cholesky", (16, 16), b.dtype, (1, 1), backend)
        tc.save(key, {"nb": 8}, source="measured",
                metric={"seconds": 1e-3})            # tuner re-sweep
        tok = tune_token("hpd", b, backend)
        assert tok != ""
        ex.run(b, reqs)
        assert compiles() == 2                       # stale binary retired
        assert any(f"__t{tok}" in k for k in ex.cache.stats()["entries"])
        tc.save(key, {"nb": 4}, source="measured",
                metric={"seconds": 2e-3})            # different winner
        tok2 = tune_token("hpd", b, backend)
        assert tok2 not in ("", tok)
        ex.run(b, reqs)
        assert compiles() == 3


def test_route_for_tuner_fed_dispatch(tmp_path, monkeypatch):
    """SATELLITE: dispatch consults the tuning cache -- only a MEASURED
    winner whose seconds beat the vmap estimate flips the route to the
    grid path, and the provenance doc records the decision inputs."""
    import jax
    from elemental_tpu.serve import route_for
    from elemental_tpu.tune import cache as tc
    monkeypatch.setenv(tc.ENV_DIR, str(tmp_path))
    backend = jax.default_backend()
    b = make_bucket("hpd", 12, 1, np.float64)
    key = tc.make_key("cholesky", (16, 16), b.dtype, (2, 2), backend)

    route, prov = route_for(b, (2, 2), backend, est_vmap_s=1e-3)
    assert (route, prov["source"], prov["tune_token"]) \
        == ("vmap", "default", "")
    assert prov["driver_op"] == "cholesky" and prov["grid"] == [2, 2]
    # a measured winner SLOWER than the vmap estimate stays on vmap
    tc.save(key, {"nb": 8}, source="measured", metric={"seconds": 5e-3})
    route, prov = route_for(b, (2, 2), backend, est_vmap_s=1e-3)
    assert route == "vmap" and prov["source"] == "measured"
    assert prov["measured_s"] == pytest.approx(5e-3)
    # a faster measured winner flips the bucket to the grid path
    tc.save(key, {"nb": 8}, source="measured", metric={"seconds": 1e-6})
    route, prov = route_for(b, (2, 2), backend, est_vmap_s=1e-3)
    assert route == "grid" and prov["route"] == "grid"
    # non-measured winners never flip the route, however fast
    tc.save(key, {"nb": 8}, source="manual", metric={"seconds": 1e-9})
    route, _ = route_for(b, (2, 2), backend, est_vmap_s=1e-3)
    assert route == "vmap"


def test_compute_fault_seam_on_batch_output():
    """The executor's batch output crosses the 'compute' fault target:
    corruption lands in the returned solutions, is logged with the batch
    shape, and replays bit-identically."""
    from elemental_tpu.resilience import (FaultPlan, FaultSpec,
                                          fault_injection, logs_identical)
    rng = np.random.default_rng(14)
    ctrl = AdmissionController()
    probs = [(diag_dom(rng, 16), rng.normal(size=(16, 2)))
             for _ in range(4)]
    ex = Executor()

    def run(plan):
        reqs = _reqs(AdmissionController(), "lu", probs)
        with fault_injection(plan):
            xs, _ = ex.run(reqs[0].bucket, reqs)
        return xs

    mk = lambda: FaultPlan(seed=7, faults=[
        FaultSpec("compute", "nan", call=0, nelem=3)])
    p1, p2 = mk(), mk()
    xs1 = run(p1)
    xs2 = run(p2)
    assert p1.fired() == 1
    ev = p1.log[0]
    assert ev.target == "compute" and ev.shape == (4, 16, 2)
    assert np.isnan(ev.after).all()
    assert any(not np.isfinite(x).all() for x in xs1)
    assert logs_identical(p1, p2)
    for a, b in zip(xs1, xs2):
        np.testing.assert_array_equal(a, b)
    # and without a plan the output is clean again
    reqs = _reqs(AdmissionController(), "lu", probs)
    xs3, _ = ex.run(reqs[0].bucket, reqs)
    assert all(np.isfinite(x).all() for x in xs3)
