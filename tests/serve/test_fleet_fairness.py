"""Tenant fairness (ISSUE 19): FairScheduler's deficit round robin unit
pins, and the fleet-level starvation bound -- a burst tenant cannot move
another tenant's tail latency beyond its fair share, deterministic under
injected clocks."""
import numpy as np
import pytest

from elemental_tpu.serve import SolverFleet, TenantQuota
from elemental_tpu.serve.chaos import _ChaosClock, _TimedExecutor
from elemental_tpu.serve.scheduler import FairScheduler

from .conftest import spd


# ---- DRR unit pins -----------------------------------------------------

def test_equal_shares_interleave():
    """Uniform costs, equal shares: strict alternation -- the first
    tenant's backlog cannot hold the turn past its per-round quantum."""
    s = FairScheduler()
    for x in ("a1", "a2", "a3"):
        s.push("a", x)
    for x in ("b1", "b2"):
        s.push("b", x)
    assert [s.pop() for _ in range(5)] == ["a1", "b1", "a2", "b2", "a3"]
    assert s.pop() is None


def test_weighted_shares_drain_proportionally():
    """share=2 drains two items per round for every one of share=1."""
    s = FairScheduler(quotas={"a": TenantQuota(share=2.0)})
    for i in range(6):
        s.push("a", f"a{i}")
    for i in range(3):
        s.push("b", f"b{i}")
    got = [s.pop() for _ in range(9)]
    assert got == ["a0", "a1", "b0", "a2", "a3", "b1", "a4", "a5", "b2"]


def test_cost_weighted_fairness():
    """Fairness is in COMPUTE: a tenant of cost-4 items gets one item
    per round while a cost-1 tenant gets four (auto quantum = max head
    cost)."""
    s = FairScheduler()
    for i in range(3):
        s.push("big", f"B{i}", cost=4.0)
    for i in range(9):
        s.push("small", f"s{i}", cost=1.0)
    got = [s.pop() for _ in range(10)]
    assert got == ["B0", "s0", "s1", "s2", "s3",
                   "B1", "s4", "s5", "s6", "s7"]


def test_push_front_refunds_deficit():
    """The router's un-pop: the item returns to the head of its queue
    and the deficit it spent comes back, so waiting for capacity never
    costs a tenant its turn."""
    s = FairScheduler()
    s.push("a", "a1", cost=5.0)
    s.push("a", "a2", cost=5.0)
    s.push("b", "b1", cost=5.0)
    assert s.pop() == "a1"
    s.push_front("a", "a1", cost=5.0)
    assert s.pending("a") == 2
    assert s.pop() == "a1"               # same item, already-paid credit
    assert s.pop() == "b1"


def test_small_share_terminates():
    """A tiny share accumulates credit over sweeps instead of spinning
    (and the anti-spin escape serves the head in bounded visits)."""
    s = FairScheduler(quotas={"slow": TenantQuota(share=0.05)})
    s.push("slow", "x", cost=1.0)
    assert s.pop() == "x"
    s.push("slow", "y", cost=1.0)
    s.push("fast", "f", cost=1.0)
    got = {s.pop(), s.pop()}
    assert got == {"y", "f"}


def test_flush_arrival_order_and_quota_validation():
    s = FairScheduler()
    s.push("b", "b1")
    s.push("a", "a1")
    s.push("b", "b2")
    assert s.flush() == ["b1", "b2", "a1"]  # tenant arrival, FIFO within
    assert s.pending() == 0
    with pytest.raises(ValueError):
        TenantQuota(share=0.0)
    with pytest.raises(ValueError):
        TenantQuota(max_outstanding=0)
    doc = s.to_doc()
    assert set(doc) == {"tenants", "depths", "deficits", "shares"}


# ---- fleet-level starvation bound --------------------------------------

def _burst_vs_steady(seed):
    """16-request burst submitted BEFORE 4 steady requests, 2-member
    sync fleet under a virtual clock where every batch costs exactly
    1s.  Returns (steady latencies, burst latencies) in virtual
    seconds."""
    clock = _ChaosClock()
    fleet = SolverFleet(grids=2, pipelined=False, max_batch=2, shed=False,
                        breaker_threshold=99, retries=0,
                        backoff_base_s=0.0, clock=clock, sleep=clock.sleep)
    try:
        for svc in fleet.services:
            svc.executor = _TimedExecutor(svc.executor, clock, 1.0)
        rng = np.random.default_rng(seed)
        n = 12

        def mk():
            return spd(rng, n), rng.normal(size=(n, 2))

        burst = [fleet.submit("hpd", *mk(), tenant="burst")
                 for _ in range(16)]
        steady = [fleet.submit("hpd", *mk(), tenant="steady")
                  for _ in range(4)]
        fleet.drain()
        assert all(f.result(0)[1]["status"] == "ok"
                   for f in burst + steady)
        lat = [f.result(0)[1]["latency_s"] for f in steady]
        blat = [f.result(0)[1]["latency_s"] for f in burst]
        return lat, blat
    finally:
        fleet.shutdown(drain=True)


def test_burst_cannot_starve_steady_tenant():
    """The starvation pin.  20 equal-cost requests, 10 one-second
    batches total: FIFO would finish the late-arriving steady tenant
    last (p99 ~= 10s, the full burst ahead of it).  Under DRR with
    equal shares the steady tenant's 4 requests interleave one-per-
    round, so its tail is bounded by its fair share of each round --
    capacity head start (first 2 batches are all-burst: the burst
    filled both members before the steady tenant existed) plus one
    steady request per member per round thereafter: p99 <= 6 virtual
    seconds, well under the burst's own tail."""
    lat, blat = _burst_vs_steady(5)
    assert max(lat) <= 6.0
    assert max(blat) >= 9.0              # the burst pays its own queue
    assert max(lat) < max(blat)


def test_fairness_deterministic_under_injected_clock():
    """Same seed, same virtual clock -> bit-identical latency ledgers
    (the scheduler reads no wall clock)."""
    assert _burst_vs_steady(7) == _burst_vs_steady(7)
