"""Chaos under batching (ISSUE 9): the serve acceptance matrix
{bitflip, scale, nan} x {redistribute, compute} x {oneshot, persistent},
fault isolation of batch-mates, and deterministic replay of both fault
logs and breaker transitions.  ISSUE 11 grows the matrix a ``qr`` op
column (qr has no serve admission path, so the cells drive the driver
directly); ISSUE 15 upgrades it to ``qr(..., abft=True)``: every kind
gates -- bitflip included -- and each one-shot cell must be ABSORBED
via exactly one recomputed panel with a clean trusted residual."""
import numpy as np
import pytest

from elemental_tpu.resilience import (FaultPlan, FaultSpec,
                                      fault_injection, logs_identical)
from elemental_tpu.serve import SolverService, chaos_matrix, run_cell
from elemental_tpu.serve.chaos import replay_identical

from .conftest import diag_dom

#: trimmed-cost service knobs for the tier-1 matrix (no retry loop --
#: escalation's own ladder is the repair path being pinned)
_CELL_KW = {"retries": 0}


# ---------------------------------------------------------------------
# THE ACCEPTANCE MATRIX -- every cell: fault fired, zero silent garbage,
# zero collateral damage, every failure structured.
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["bitflip", "scale", "nan"])
@pytest.mark.parametrize("target", ["redistribute", "compute"])
@pytest.mark.parametrize("mode", ["oneshot", "persistent"])
def test_acceptance_matrix_cell(grid24, target, kind, mode):
    cell, plan, svc = run_cell(
        grid24, kind=kind, target=target, mode=mode,
        call=2 if target == "redistribute" else 0,
        service_kw=_CELL_KW)
    assert cell["fired"] > 0, "fault never landed: the cell is vacuous"
    assert cell["violations"] == []
    assert cell["verdict"] in ("absorbed", "isolated", "surfaced")
    # independent re-verification of every ok result (belt + braces on
    # top of the classifier's own check)
    from elemental_tpu.serve.executor import residual as _residual
    from elemental_tpu.serve.chaos import build_workload
    workload = build_workload(cell["op"], 16, 2, cell["requests"], 13)
    for rid, (A, B) in enumerate(workload):
        doc = svc.results[rid]
        if doc["status"] == "ok":
            assert _residual(A, B, svc.solutions[rid]) <= doc["tol"]
        else:
            assert doc["status"] in ("failed", "timed_out")
            assert doc["certificate"] is not None or doc["timed_out"]


def test_persistent_redist_nan_surfaced_for_all(grid24):
    """every=True NaN on the engine can never certify anything on the
    distributed path: every request fails STRUCTURED, with the
    certificate naming a failing phase."""
    cell, plan, svc = run_cell(grid24, kind="nan", target="redistribute",
                               mode="persistent", call=2,
                               service_kw=_CELL_KW)
    assert cell["verdict"] == "surfaced" and cell["ok"] == 0
    for doc in svc.results.values():
        assert doc["status"] == "failed"
        cert = doc["certificate"]
        assert cert["certified"] is False
        assert cert["failing_phase"] is not None


def test_oneshot_compute_isolates_batch_mates(grid24):
    """A one-shot corruption of the FIRST batched dispatch: batch-mates
    whose slots the fault never touched all certify ok, and the touched
    requests are absorbed by bisect re-execution (the fault does not
    re-fire) -- zero collateral damage under batching."""
    from elemental_tpu.serve.chaos import compute_slots
    cell, plan, svc = run_cell(grid24, kind="nan", target="compute",
                               mode="oneshot", nelem=4,
                               service_kw=_CELL_KW)
    assert cell["fired"] >= 1
    hit = compute_slots(plan, 16, 2)
    assert hit, "corruption landed nowhere?"
    assert cell["violations"] == []
    # untouched slots ended ok
    for slot in range(cell["requests"]):
        if slot not in hit:
            assert svc.results[slot]["status"] == "ok"
    # touched slots were absorbed by fresh re-execution, not escalation
    for slot in hit:
        doc = svc.results[slot]
        assert doc["status"] == "ok"
        assert doc["path"] == "fastpath"


@pytest.mark.slow
def test_full_matrix_report_clean(grid24):
    """The aggregated chaos_report/v1: 12 serve cells, zero violations,
    zero vacuous cells.  Slow tier: every one of the 12 cells already
    runs individually in tier-1 (test_acceptance_matrix_cell above), and
    the full 18-cell report with the qr column (ISSUE 11, abft-guarded
    since ISSUE 15) is what ``perf.serve chaos`` gates in check.sh."""
    report = chaos_matrix(grid24, seed=13, service_kw=_CELL_KW,
                          qr_column=False, async_column=False)
    assert report["schema"] == "chaos_report/v1"
    assert len(report["cells"]) == 12
    assert report["ok"] is True
    assert report["violations_total"] == 0
    assert report["vacuous_cells"] == 0
    assert all(c["op"] in ("lu", "hpd") for c in report["cells"])


# ---------------------------------------------------------------------
# THE QR COLUMN (ISSUE 11, abft-guarded since ISSUE 15) --
# qr(..., abft=True, health=True) under injection: checksum detection +
# panel-transaction recovery, every kind gated.
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kind", [
    "bitflip",
    pytest.param("scale", marks=pytest.mark.slow),
    pytest.param("nan", marks=pytest.mark.slow)])
@pytest.mark.parametrize("target", ["redistribute", "compute"])
def test_qr_column_cell(grid24, target, kind):
    """Every qr cell fires, violates nothing, and is ABSORBED: the
    checksum checks detect the corrupted panel (bitflip included -- the
    former sub-growth-threshold gap the ISSUE-15 checksums close), the
    transaction layer re-executes exactly that one panel, and the
    committed factor carries a clean trusted residual."""
    from elemental_tpu.serve.chaos import QR_DETECTED_KINDS, run_qr_cell
    assert QR_DETECTED_KINDS == ("bitflip", "scale", "nan")
    cell, plan = run_qr_cell(grid24, kind=kind, target=target)
    assert cell["fired"] > 0, "fault never landed: the cell is vacuous"
    assert cell["violations"] == []
    assert cell["op"] == "qr"
    assert cell["verdict"] == "absorbed"
    assert cell["abft"]["ok"] is True
    assert cell["abft"]["violations"] >= 1   # the fault WAS detected
    assert cell["abft"]["recompute_count"] == 1
    assert np.isfinite(cell["residual"])


def test_qr_column_replay_bit_identical(grid24):
    """The qr cell is seeded end to end: replaying it reproduces the
    SAME verdict and a bit-identical fault log."""
    from elemental_tpu.serve.chaos import run_qr_cell
    c1, p1 = run_qr_cell(grid24, kind="scale", target="redistribute")
    c2, p2 = run_qr_cell(grid24, kind="scale", target="redistribute")
    assert c1 == c2
    assert logs_identical(p1, p2)


# ---------------------------------------------------------------------
# THE ASYNC COLUMN (ISSUE 14) -- faults landing MID-PIPELINE, while the
# next batch is already dispatched behind the corrupted one.
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["bitflip", "scale", "nan"])
@pytest.mark.parametrize("mode", ["oneshot", "persistent"])
def test_async_column_cell(grid24, kind, mode):
    """Every async cell runs two pipelined batches, fires, and violates
    nothing: zero silent garbage, zero silent drops, every failure
    structured."""
    from elemental_tpu.serve import run_async_cell
    cell, plan, front = run_async_cell(grid24, kind=kind, mode=mode,
                                       service_kw=_CELL_KW)
    assert cell["fired"] > 0, "fault never landed: the cell is vacuous"
    assert cell["violations"] == []
    assert cell["column"] == "async" and cell["batches"] == 2
    assert cell["verdict"] in ("absorbed", "isolated", "surfaced")


def test_async_oneshot_spares_neighbor_batch(grid24):
    """A one-shot NaN on batch 0's compute seam: batch 1 was ALREADY
    dispatched behind it (double buffering) when the corruption landed
    -- and every batch-1 request still certifies ok.  Mid-pipeline
    faults stay isolated to their own batch."""
    from elemental_tpu.serve import run_async_cell
    cell, plan, front = run_async_cell(grid24, kind="nan", mode="oneshot",
                                       requests=8, nelem=4,
                                       service_kw=_CELL_KW)
    assert cell["violations"] == []
    # the fault hit batch 0's 4-slot dispatch, not the 8-request set
    assert plan.log[0].target == "compute"
    assert plan.log[0].shape == (4, 16, 2)
    # batch-1 requests (ids 4..7 -- FIFO ingest fixes membership) all ok
    results = front.service.results
    for rid in range(4, 8):
        assert results[rid]["status"] == "ok", f"neighbor {rid} poisoned"


def test_async_cell_replay_bit_identical(grid24):
    """Pre-loaded submission queue + single worker: the async cell is
    deterministic -- same outcomes, same verdict, bit-identical fault
    logs across runs."""
    from elemental_tpu.resilience import logs_identical as _li
    from elemental_tpu.serve import run_async_cell
    c1, p1, _ = run_async_cell(grid24, kind="scale", mode="oneshot",
                               service_kw=_CELL_KW)
    c2, p2, _ = run_async_cell(grid24, kind="scale", mode="oneshot",
                               service_kw=_CELL_KW)
    assert c1 == c2
    assert _li(p1, p2)


def test_async_shutdown_under_load_cell(grid24):
    """The hard-stop cell: batches 0 and 1 complete ok, batch 2 flushes
    with structured shutdown rejects, zero silent drops, post-shutdown
    submits reject -- deterministic via the parked-worker gate."""
    from elemental_tpu.serve import run_async_shutdown_cell
    cell, front = run_async_shutdown_cell(grid24, requests=12,
                                          service_kw=_CELL_KW)
    assert cell["violations"] == []
    assert cell["verdict"] == "isolated"
    assert cell["ok"] == 8 and cell["flushed"] == 4
    assert cell["column"] == "async" and cell["mode"] == "drain_false"


@pytest.mark.slow
def test_full_matrix_with_async_column(grid24):
    """The 19-cell report chaos gates in check.sh: 12 sync cells + 6
    async fault cells + the shutdown cell (qr column covered per-cell
    above).  Slow-marked: every cell above runs individually in tier-1;
    the aggregate is what ``perf.serve chaos`` gates."""
    report = chaos_matrix(grid24, seed=13, service_kw=_CELL_KW,
                          qr_column=False, async_column=True)
    assert len(report["cells"]) == 19
    assert report["ok"] is True
    assert report["violations_total"] == 0
    async_cells = [c for c in report["cells"]
                   if c.get("column") == "async"]
    assert len(async_cells) == 7
    assert sum(c["kind"] == "shutdown" for c in async_cells) == 1


# ---------------------------------------------------------------------
# determinism under replay
# ---------------------------------------------------------------------

def test_chaos_replay_bit_identical(grid24):
    assert replay_identical(grid24, kind="bitflip", target="compute",
                            mode="persistent", service_kw=_CELL_KW)
    assert replay_identical(grid24, kind="scale", target="redistribute",
                            mode="oneshot", service_kw=_CELL_KW)


def test_breaker_transitions_deterministic_under_replay(grid24, fake_clock):
    """The SAME persistent fault plan replayed over the SAME submission
    schedule produces the SAME breaker transition sequence (trip ->
    half-open -> re-open), pinned via the per-request breaker snapshots
    and the transition counters."""
    from elemental_tpu.obs import metrics as _metrics
    rng0 = np.random.default_rng(31)
    probs = [(diag_dom(rng0, 16), rng0.normal(size=(16, 2)))
             for _ in range(6)]

    def run():
        clk = type(fake_clock)()
        svc = SolverService(grid24, clock=clk, sleep=clk.sleep,
                            breaker_threshold=2, breaker_cooldown_s=5.0,
                            retries=0, max_batch=1)
        plan = FaultPlan(seed=3, faults=[
            FaultSpec("compute", "nan", call=0, every=True, nelem=40)])
        trail = []
        with _metrics.scoped() as reg:
            with fault_injection(plan):
                for i, (A, B) in enumerate(probs):
                    rid = svc.submit("lu", A, B)
                    if isinstance(rid, dict):
                        trail.append(("reject", rid["reason"]))
                        clk.advance(6.0)     # wait out the cooldown
                        continue
                    svc.drain()
                    trail.append((svc.results[rid]["status"],
                                  svc.results[rid]["breaker"]))
            trans = sorted((dict(lb)["to"], v) for (nm, lb), v in
                           reg.counters("serve_breaker_transitions").items())
        return trail, trans, plan

    t1, tr1, p1 = run()
    t2, tr2, p2 = run()
    assert t1 == t2
    assert tr1 == tr2
    assert logs_identical(p1, p2)
    assert ("reject", "breaker_open") in t1   # the breaker actually tripped
    assert any(to == "half_open" for to, _ in tr1)
