"""Memory-aware admission (ISSUE 18): the shed decision consults
statically-derived peak bytes.

``bucket_peak_bytes`` liveness-walks the executor's vmapped kernel for
one max_batch batch -- no device execution -- and ``admit`` sheds with a
structured ``memory_pressure`` reject when TWO such batches (the
double-buffer depth) cannot fit the per-device HBM budget.  An
unavailable estimate is never a reason to shed."""
import numpy as np

from elemental_tpu.serve import AdmissionController, make_bucket

from .conftest import diag_dom


def _request(rng, n=12):
    return diag_dom(rng, n), rng.normal(size=(n, 2))


def test_bucket_peak_bytes_positive_and_memoized():
    ctrl = AdmissionController()
    b = make_bucket("lu", 12, 2, np.float64)
    peak = ctrl.bucket_peak_bytes(b)
    assert peak is not None and peak > 0
    # at least the two operand buffers of one batch must be resident
    operands = ctrl.max_batch * (b.n * b.n + b.n * b.nrhs) * 8
    assert peak >= operands
    assert ctrl.bucket_peak_bytes(b) is peak or \
        ctrl.bucket_peak_bytes(b) == peak
    assert b.key() in ctrl._peak_memo


def test_default_budget_admits():
    rng = np.random.default_rng(0)
    ctrl = AdmissionController()
    req = ctrl.admit("lu", *_request(rng))
    assert not isinstance(req, dict), req


def test_tiny_hbm_sheds_with_structured_reject():
    rng = np.random.default_rng(1)
    ctrl = AdmissionController(hbm_bytes=1024)
    doc = ctrl.admit("lu", *_request(rng))
    assert isinstance(doc, dict)
    assert doc["reason"] == "memory_pressure"
    assert doc["bucket"] == "lu__b16x2__float64"
    assert "double buffer" in doc["detail"]
    assert "HBM budget" in doc["detail"]


def test_threshold_is_double_buffered():
    """The shed line is 2x one batch's static peak: a budget between
    1x and 2x must shed, a budget above 2x must admit."""
    rng = np.random.default_rng(2)
    probe = AdmissionController()
    peak = probe.bucket_peak_bytes(make_bucket("lu", 12, 2, np.float64))
    assert peak is not None
    shed = AdmissionController(hbm_bytes=1.5 * peak)
    assert isinstance(shed.admit("lu", *_request(rng)), dict)
    ok = AdmissionController(hbm_bytes=2.5 * peak)
    assert not isinstance(ok.admit("lu", *_request(rng)), dict)


def test_shed_false_disables_memory_pressure():
    rng = np.random.default_rng(3)
    ctrl = AdmissionController(shed=False, hbm_bytes=1024)
    assert ctrl.memory_pressure(make_bucket("lu", 12, 2, np.float64)) is None
    req = ctrl.admit("lu", *_request(rng))
    assert not isinstance(req, dict)


def test_unavailable_estimate_never_sheds(monkeypatch):
    """If the abstract trace fails, peak is None and admission proceeds:
    degraded observability must not become an outage."""
    rng = np.random.default_rng(4)
    ctrl = AdmissionController(hbm_bytes=1024)
    monkeypatch.setattr("elemental_tpu.serve.executor.batch_peak_bytes",
                        lambda bucket, slots: (_ for _ in ()).throw(
                            RuntimeError("trace backend down")))
    req = ctrl.admit("lu", *_request(rng))
    assert not isinstance(req, dict)
    assert ctrl._peak_memo[req.bucket.key()] is None


def test_service_threads_hbm_budget():
    """SolverService(hbm_bytes=...) reaches the admission controller."""
    from elemental_tpu.serve import SolverService
    svc = SolverService(hbm_bytes=1024)
    assert svc.admission.hbm_bytes == 1024
    default = SolverService()
    assert default.admission.hbm_bytes is None
