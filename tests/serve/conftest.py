"""Shared helpers for the solver-service tests (ISSUE 9)."""
import numpy as np
import pytest


class FakeClock:
    """A manually advanced clock: deterministic deadlines/breakers."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t

    def sleep(self, dt: float) -> None:
        """Injectable ``sleep=``: advancing the clock IS sleeping."""
        self.advance(dt)


@pytest.fixture
def fake_clock():
    return FakeClock()


def spd(rng, n: int) -> np.ndarray:
    F = rng.normal(size=(n, n))
    return F @ F.T / n + n * np.eye(n)


def diag_dom(rng, n: int) -> np.ndarray:
    return rng.normal(size=(n, n)) + n * np.eye(n)
