"""Golden memory-plan gates (ISSUE 18): every registered driver's
per-device peak live bytes, high-water attribution and replicated-
materialization census pinned at the jaxpr level on 1x1 and 2x2 grids.

Trace-only like the comm-plan twins: a PR that silently doubles a
driver's resident footprint (an extra gathered slab, a new replicated
form, a dropped buffer reuse) fails here instead of OOMing on hardware.
Regenerate after an INTENTIONAL change with
``python -m perf.comm_audit mem-diff --update-golden``.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from perf.comm_audit import GRIDS, mem_golden_path


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


_CASES = [(d, g) for d in an.driver_names() for g in GRIDS]


@pytest.mark.parametrize("driver,grid", _CASES,
                         ids=[f"{d}-{r}x{c}" for d, (r, c) in _CASES])
def test_memory_plan_matches_golden(driver, grid):
    mplan, _, _ = an.trace_memory(driver, _grid(*grid))
    path = mem_golden_path(driver, grid)
    with open(path) as f:
        golden = json.load(f)
    lines = an.diff_mem_docs(golden, an.golden_mem_doc(mplan))
    assert not lines, "memory plan drifted from golden " \
        f"({path}):\n" + "\n".join(lines) + \
        "\nIf intentional: python -m perf.comm_audit mem-diff " \
        "--update-golden"


def test_diff_detects_seeded_drift():
    """mem-diff must FAIL on drift, not just pass on agreement: a seeded
    peak/census/timeline perturbation each produces a mismatch line."""
    mplan, _, _ = an.trace_memory("gemm_a", _grid(2, 2))
    doc = an.golden_mem_doc(mplan)
    assert an.diff_mem_docs(doc, doc) == []
    drifted = json.loads(json.dumps(doc))
    drifted["peak_bytes"] += 4096
    assert any("peak_bytes" in ln for ln in an.diff_mem_docs(doc, drifted))
    drifted = json.loads(json.dumps(doc))
    drifted["replicated"]["count"] += 1
    assert any("replicated" in ln for ln in an.diff_mem_docs(doc, drifted))
    drifted = json.loads(json.dumps(doc))
    drifted["timeline"] = drifted["timeline"][:-1]
    assert any("timeline" in ln for ln in an.diff_mem_docs(doc, drifted))


# ---------------------------------------------------------------------
# liveness-walk unit behavior
# ---------------------------------------------------------------------

def test_walk_counts_args_and_peak():
    """A chain that frees its intermediate peaks below sum-of-all."""
    def chain(x):
        y = x * 2.0          # x, y live
        z = y + 1.0          # y frees after this
        return z * z

    closed = jax.make_jaxpr(chain)(
        jax.ShapeDtypeStruct((64, 64), jnp.float32))
    stats = an.analyze_jaxpr(closed)
    one = 64 * 64 * 4
    assert stats.args_bytes == one
    assert stats.outs_bytes == one
    # x + y + z live at the z allocation, never all four values at once
    assert stats.peak_bytes == 3 * one
    assert stats.static
    assert stats.timeline[-1].live_bytes == stats.peak_bytes


def test_walk_fanout_holds_operand():
    """An operand consumed twice stays live until its LAST use."""
    def fan(x):
        y = x * 2.0
        z = y + x            # x's last use
        return z - 1.0

    closed = jax.make_jaxpr(fan)(
        jax.ShapeDtypeStruct((32, 32), jnp.float32))
    one = 32 * 32 * 4
    assert an.analyze_jaxpr(closed).peak_bytes == 3 * one


def test_walk_divides_by_grid_size():
    def f(x):
        return x * 2.0

    closed = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((64, 64), jnp.float32))
    s1 = an.analyze_jaxpr(closed, grid_size=1)
    s4 = an.analyze_jaxpr(closed, grid_size=4)
    assert s1.peak_bytes == 4 * s4.peak_bytes


def test_walk_scan_body_once():
    """A scan body is steady-state: its footprint counts once, not
    length times (buffers free between iterations)."""
    def f(x):
        def body(c, _):
            return c * 2.0 + 1.0, None
        out, _ = jax.lax.scan(body, x, None, length=8)
        return out

    closed = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((16, 16), jnp.float32))
    stats = an.analyze_jaxpr(closed)
    one = 16 * 16 * 4
    assert stats.peak_bytes < 8 * one


def test_walk_cond_branches_max_not_sum():
    def f(x):
        return jax.lax.cond(x.sum() > 0.0,
                            lambda v: v * 2.0 + 1.0,
                            lambda v: v - 1.0, x)

    closed = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((16, 16), jnp.float32))
    stats = an.analyze_jaxpr(closed)
    one = 16 * 16 * 4
    # x + the busier branch's two intermediates, NOT both branches at once
    assert stats.peak_bytes <= 3 * one + 8


def test_peak_attribution_names_scope():
    mplan, _, _ = an.trace_memory("gemm_slice", _grid(2, 2))
    doc = mplan.to_doc()
    assert doc["peak_path"], "peak must be attributed to a nesting path"
    assert doc["peak_prim"]
    assert doc["timeline"], "high-water timeline must be non-empty"
    marks = [t["live_bytes"] for t in doc["timeline"]]
    assert marks == sorted(marks), "timeline marks are monotone peaks"
    assert marks[-1] == doc["walk_peak_bytes"]


# ---------------------------------------------------------------------
# replicated-materialization census
# ---------------------------------------------------------------------

def test_census_star_star_replication():
    """A [*,*] gather on 2x2 keeps p=4 replicas: extra = 3/4 of the
    operand per device, and star_star counts it."""
    mplan, _, log = an.trace_memory("gemm_slice", _grid(2, 2))
    rep = mplan.replicated
    assert rep["star_star"] >= 1
    star = [s for s in rep["sites"] if s["dst"] == "[STAR,STAR]"]
    assert star
    m, n = star[0]["gshape"]
    z = np.dtype(star[0]["dtype"]).itemsize
    assert star[0]["extra_bytes"] == m * n * z * 3 // 4 * star[0]["count"]
    assert mplan.peak_bytes == mplan.stats.peak_bytes \
        + rep["max_extra_bytes"]


def test_census_empty_on_1x1():
    """No replication exists on one device: census must be silent."""
    for driver in ("gemm_a", "cholesky_classic", "lu_classic"):
        mplan, _, _ = an.trace_memory(driver, _grid(1, 1))
        assert mplan.replicated["count"] == 0
        assert mplan.replicated["max_extra_bytes"] == 0


def test_census_panel_spread_counts_both_forms():
    """panel_spread produces BOTH panel forms from one entry; each
    replicated form contributes extra bytes."""
    mplan, _, log = an.trace_memory("cholesky_classic", _grid(2, 2))
    spreads = [r for r in log if r.kind == "panel_spread"]
    assert spreads, "cholesky's trailing update uses panel_spread"
    assert mplan.replicated["count"] >= 2 * len(spreads)


# ---------------------------------------------------------------------
# EL007 support: the VMEM gate cross-check helpers
# ---------------------------------------------------------------------

def test_gate_bytes_reproduce_use_pallas():
    """check_panel_vmem's `admitted` IS the PanelPlan gate's decision
    at the default budget, for every op and a spread of shapes."""
    from elemental_tpu.kernels import PanelPlan
    plan = PanelPlan(impl="pallas", inners=(512, 64), source="test")
    for op, copies in an.PANEL_GATE_COPIES.items():
        for shape in ((64, 16), (512, 128), (2048, 512), (8192, 1024)):
            chk = an.check_panel_vmem(op, shape, "float32")
            assert chk.admitted == plan.use_pallas(shape, jnp.float32,
                                                   copies=copies), \
                (op, shape)


def test_kernel_bytes_exceed_gate_for_cholesky_odd_width():
    """The genuine gate/kernel divergence EL007 exists to catch: potrf's
    pad_square LANE-pads BOTH axes, so non-128-multiple widths allocate
    MORE than the (8,128) tile pricing admits."""
    chk = an.check_panel_vmem("cholesky", (72, 72), "float32")
    assert chk.kernel_bytes > chk.gate_bytes
    # at the default 16 MiB budget the slack absorbs it: no overflow
    assert chk.admitted and chk.fits and not chk.overflow


def test_panel_shapes_enumerate_sweep():
    shapes = an.panel_shapes("lu", 64, 16)
    assert shapes == [(64, 16), (48, 16), (32, 16), (16, 16)]
    assert an.panel_shapes("cholesky", 64, 16) == [(16, 16)] * 4
    # ragged tail
    assert an.panel_shapes("qr", 40, 16) == [(40, 16), (24, 16), (8, 8)]
