"""Unit tests for the recursive jaxpr collective walker (ISSUE 3)."""
import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from elemental_tpu import Grid
from elemental_tpu.analysis import (collect_events, count_pjit_calls,
                                    estimate_bytes,
                                    find_loop_invariant_collectives)
from elemental_tpu.core.compat import shard_map


@pytest.fixture(scope="module")
def g22():
    return Grid(jax.devices()[:4], height=2)


def _smap(g, fn, n_in=1):
    def outer(*args):
        return shard_map(fn, mesh=g.mesh, in_specs=(P(),) * n_in,
                         out_specs=P(), check_vma=False)(*args)
    return outer


def test_psum_event_axes_and_bytes(g22):
    fn = _smap(g22, lambda x: lax.psum(x, ("mc", "mr")))
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 8), jnp.float32))
    evs = collect_events(closed)
    assert len(evs) == 1
    ev = evs[0]
    assert ev.prim == "psum" and set(ev.axes) == {"mc", "mr"}
    assert ev.axis_size == 4 and ev.shape == (8, 8)
    assert ev.dtype == "float32" and ev.count == 1 and ev.static
    assert ev.bytes_per_call == estimate_bytes("psum", 8 * 8 * 4, 4)


def test_scan_multiplies_count(g22):
    def body(x):
        def step(c, _):
            return c + lax.psum(c, "mc"), None
        out, _ = lax.scan(step, x, None, length=5)
        return out

    closed = jax.make_jaxpr(_smap(g22, body))(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    evs = collect_events(closed)
    assert len(evs) == 1
    assert evs[0].count == 5 and evs[0].static
    assert any(p.startswith("scan[5]") for p in evs[0].path)


def test_while_marks_non_static(g22):
    def body(x):
        def cond(c):
            return c[0] < 3

        def step(c):
            return (c[0] + 1, c[1] + lax.psum(c[1], "mr"))
        return lax.while_loop(cond, step, (0, x))[1]

    closed = jax.make_jaxpr(_smap(g22, body))(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    evs = collect_events(closed)
    assert len(evs) == 1 and not evs[0].static


def test_nested_pjit_recursion_and_count(g22):
    @jax.jit
    def inner(x):
        return lax.psum(x, "mc")

    def body(x):
        return inner(x) + inner(x)

    closed = jax.make_jaxpr(_smap(g22, body))(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    evs = collect_events(closed)
    assert [e.prim for e in evs] == ["psum", "psum"]
    assert all("pjit:inner" in e.path for e in evs)
    assert count_pjit_calls(closed, "inner") == 2


def test_estimate_bytes_formulas():
    nb = 1000
    assert estimate_bytes("all_gather", nb, 4) == 3000
    assert estimate_bytes("reduce_scatter", nb, 4) == 750
    assert estimate_bytes("psum", nb, 4) == 1500
    assert estimate_bytes("all_to_all", nb, 4) == 750
    assert estimate_bytes("ppermute", nb, 4) == nb
    assert estimate_bytes("all_gather", nb, 1) == 0


def test_loop_invariant_collective_found(g22):
    def body(x, y):
        def step(c, _):
            # psum of the loop-INVARIANT y: hoistable
            return c + lax.psum(y, "mc"), None
        out, _ = lax.scan(step, x, None, length=3)
        return out

    closed = jax.make_jaxpr(_smap(g22, body, n_in=2))(
        jax.ShapeDtypeStruct((4,), jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.float32))
    found = find_loop_invariant_collectives(closed)
    assert len(found) == 1 and found[0][0] == "psum"


def test_loop_variant_collective_not_flagged(g22):
    def body(x):
        def step(c, _):
            # psum of the CARRY: genuinely per-iteration
            return c + lax.psum(c, "mc"), None
        out, _ = lax.scan(step, x, None, length=3)
        return out

    closed = jax.make_jaxpr(_smap(g22, body))(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    assert find_loop_invariant_collectives(closed) == []


# ---------------------------------------------------------------------
# payload-dtype-aware byte estimates (ISSUE 8 satellite): the estimator
# reads the ACTUAL collective operand dtype(s), so convert-before-
# collective patterns (the comm_precision encode path, PR 1's bf16
# updates) are priced at their true wire bytes
# ---------------------------------------------------------------------

def test_convert_before_collective_prices_wire_dtype(g22):
    """Casting to bf16 right before the all_gather halves the estimated
    bytes: the walker must read the collective operand's aval, never
    assume the traced program's input dtype."""
    def body(x):
        return lax.all_gather(x.astype(jnp.bfloat16), ("mc", "mr"),
                              axis=0).astype(jnp.float32).sum(0)

    fn = _smap(g22, body)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 8), jnp.float32))
    evs = collect_events(closed)
    assert len(evs) == 1
    ev = evs[0]
    assert ev.dtype == "bfloat16"
    assert ev.bytes_per_call == estimate_bytes("all_gather", 8 * 8 * 2, 4)


def test_multi_operand_psum_sums_all_payloads(g22):
    """A tuple psum is ONE equation with several array operands: the byte
    estimate sums every payload at its own dtype (the old first-operand
    shortcut under-reported mixed-dtype reductions)."""
    def body(x):
        a, b = lax.psum((x, (2 * x).astype(jnp.bfloat16)), ("mc", "mr"))
        return a + b.astype(jnp.float32)

    fn = _smap(g22, body)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8, 8), jnp.float32))
    evs = [e for e in collect_events(closed) if e.prim == "psum"]
    assert len(evs) == 1
    nbytes = 8 * 8 * 4 + 8 * 8 * 2          # f32 operand + bf16 operand
    assert evs[0].bytes_per_call == estimate_bytes("psum", nbytes, 4)
