"""Lint-rule tests (ISSUE 3): each rule fires on a seeded bad pattern and
stays quiet once the pattern is removed -- the planted-regression gate."""
import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from elemental_tpu.core.compat import shard_map
from elemental_tpu.core.dist import Dist
from elemental_tpu.core.distmatrix import DistMatrix
from elemental_tpu.redist.engine import redistribute, transpose_dist

MC, MR, VC, STAR = Dist.MC, Dist.MR, Dist.VC, Dist.STAR
N = 16


@pytest.fixture(scope="module")
def g22():
    return Grid(jax.devices()[:4], height=2)


def _arg(g, n=N, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(an.storage_shape(n, n, MC, MR, g), dtype)


def _toy(g, round_trip: bool):
    """A toy driver that optionally plants the redundant
    [MC,MR] -> [VC,STAR] -> [MC,MR] round trip of the ISSUE's seeded
    regression: the intermediate is fed back UNTOUCHED, so the pair is
    pure wasted communication."""
    def fn(a):
        A = DistMatrix(a, (N, N), MC, MR, 0, 0, g)
        if round_trip:
            A = redistribute(redistribute(A, VC, STAR), MC, MR)
        ss = redistribute(A, STAR, STAR)
        return ss.local @ ss.local
    return fn


def _lint(g, fn, meta=None):
    plan, closed, log = an.trace_callable(fn, (_arg(g),), grid=g, meta=meta)
    return an.lint_plan(plan, log, closed)


def test_seeded_round_trip_reported(g22):
    findings = _lint(g22, _toy(g22, round_trip=True))
    assert any(f.rule == "EL002" for f in findings), \
        [str(f) for f in findings]
    # the finding names the planted pair
    msg = next(str(f) for f in findings if f.rule == "EL002")
    assert "[MC,MR]->[VC,STAR]" in msg and "[VC,STAR]->[MC,MR]" in msg


def test_round_trip_fix_hint_quotes_the_direct_plan(g22):
    """ISSUE 12: the EL002 finding carries the one-shot rewrite -- the
    compiled direct plan's kind/rounds/bytes next to the chain's."""
    findings = _lint(g22, _toy(g22, round_trip=True))
    hint = next(f.fix_hint for f in findings if f.rule == "EL002")
    assert "path='direct'" in hint
    assert "[MC,MR]->[VC,STAR]" in hint
    assert "'a2a'" in hint or "'ppermute'" in hint
    assert "round(s)" in hint and "vs the chain's" in hint


def test_round_trip_fix_hint_quotes_the_slice_plan(g22):
    """ISSUE 18: on a slice-legal src->dst pair the hint ALSO quotes the
    compile_slice_plan sub-range rewrite, with its compiled kind/cost --
    pay for the block you touch, not the matrix."""
    from elemental_tpu.redist.plan import compile_slice_plan
    findings = _lint(g22, _toy(g22, round_trip=True))
    hint = next(f.fix_hint for f in findings if f.rule == "EL002")
    assert "compile_slice_plan" in hint
    assert f"rows=(0, {N // 2})" in hint
    assert "pay for the block you touch" in hint
    # the quoted numbers are the COMPILED slice plan's, not boilerplate
    splan = compile_slice_plan((MC, MR), (VC, STAR), (N, N), (2, 2),
                               rows=(0, N // 2))
    assert f"'{splan.kind}'" in hint
    assert f"{splan.rounds} round(s)" in hint


def test_round_trip_removed_passes(g22):
    assert _lint(g22, _toy(g22, round_trip=False)) == []


def test_round_trip_with_intervening_compute_not_flagged(g22):
    """Touching the intermediate (any compute) legitimizes the pattern:
    the object-identity proof of adjacency must not fire."""
    def fn(a):
        A = DistMatrix(a, (N, N), MC, MR, 0, 0, g22)
        V = redistribute(A, VC, STAR)
        V = V.with_local(V.local * 2.0)          # compute on the panel
        B = redistribute(V, MC, MR)
        return B.local
    assert [f.rule for f in _lint(g22, fn)] == []


def test_adjacent_panel_spreads_flag_fusion(g22):
    """The pre-PR2 cholesky/herk chain: the [VC,STAR] panel spread to
    [MC,STAR] and its adjoint spread issued as separate redistributions
    -- EL001 says fuse into panel_spread()."""
    def fn(a):
        A = DistMatrix(a, (N, N), MC, MR, 0, 0, g22)
        V = redistribute(A, VC, STAR)
        P_mc = redistribute(V, MC, STAR)
        P_mr = redistribute(transpose_dist(V, conj=True), STAR, MR)
        return P_mc.local, P_mr.local
    findings = _lint(g22, fn)
    assert any(f.rule == "EL001" and "panel_spread" in f.message
               for f in findings), [str(f) for f in findings]


def test_f64_promotion_flagged(g22):
    def fn(a):
        A = DistMatrix(a, (N, N), MC, MR, 0, 0, g22)
        A64 = A.astype(jnp.float64)              # unintended promotion
        return redistribute(A64, STAR, STAR).local
    findings = _lint(g22, fn)
    assert any(f.rule == "EL004" for f in findings)


def test_bf16_leak_flagged_and_opt_in(g22):
    def fn(a):
        A = DistMatrix(a, (N, N), MC, MR, 0, 0, g22)
        return redistribute(A.astype(jnp.bfloat16), STAR, STAR).local
    findings = _lint(g22, fn)
    assert any(f.rule == "EL005" for f in findings)
    # the update_precision paths opt in via allow_bf16
    assert _lint(g22, fn, meta={"allow_bf16": True}) == []


def test_loop_invariant_collective_flagged(g22):
    def fn(x, y):
        def body(x, y):
            def step(c, _):
                return c + lax.psum(y, "mc"), None   # y is loop-invariant
            return lax.scan(step, x, None, length=4)[0]
        return shard_map(body, mesh=g22.mesh, in_specs=(P(), P()),
                         out_specs=P(), check_vma=False)(x, y)
    arg = jax.ShapeDtypeStruct((8,), jnp.float32)
    plan, closed, log = an.trace_callable(fn, (arg, arg), grid=g22)
    findings = an.lint_plan(plan, log, closed)
    assert any(f.rule == "EL003" for f in findings)


def test_comm_audit_lint_cli_exit_codes(g22, monkeypatch, capsys):
    """End-to-end CLI contract: lint exits 0 on the clean registry and
    the diff gate exits 0 against the committed goldens."""
    from perf import comm_audit
    assert comm_audit.main(["lint", "cholesky_crossover", "--grid", "2x2"]) == 0
    assert comm_audit.main(["diff", "cholesky", "--grid", "2x2"]) == 0
    # --fix-hint is accepted (clean registry: nothing to print)
    assert comm_audit.main(["lint", "cholesky_crossover", "--grid", "2x2",
                            "--fix-hint"]) == 0
    capsys.readouterr()
