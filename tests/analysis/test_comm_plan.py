"""Golden comm-plan gates (ISSUE 3): every registered driver's collective
schedule is pinned at the jaxpr level on 1x1 and 2x2 grids.

These are TRACE-ONLY tests (no device execution), so the full registry
sweep rides in tier 1: a PR that silently reintroduces a redistribution
round, changes a collective's operand shape, or promotes a dtype fails
here instead of in a benchmark.  Regenerate after an INTENTIONAL schedule
change with ``python -m perf.comm_audit diff --update-golden`` and review
the JSON diff.
"""
import json

import jax
import pytest

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from perf.comm_audit import GRIDS, golden_path


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


_CASES = [(d, g) for d in an.driver_names() for g in GRIDS]


@pytest.mark.parametrize("driver,grid", _CASES,
                         ids=[f"{d}-{r}x{c}" for d, (r, c) in _CASES])
def test_plan_matches_golden(driver, grid):
    plan, _, _ = an.trace_driver(driver, _grid(*grid))
    path = golden_path(driver, grid)
    with open(path) as f:
        golden = json.load(f)
    lines = an.diff_docs(golden, an.golden_doc(plan))
    assert not lines, "comm plan drifted from golden " \
        f"({path}):\n" + "\n".join(lines) + \
        "\nIf intentional: python -m perf.comm_audit diff --update-golden"


@pytest.mark.parametrize("la,classic", an.LOOKAHEAD_PAIRS)
def test_lookahead_strictly_fewer_all_gathers(la, classic):
    """The PR 1-2 fusions, pinned at the jaxpr level: the look-ahead
    (crossover-tail) schedules issue strictly fewer all_gather rounds
    than classic at equal n/nb on a real 2-D grid."""
    g = _grid(2, 2)
    plan_la, _, _ = an.trace_driver(la, g)
    plan_cl, _, _ = an.trace_driver(classic, g)
    assert plan_la.count("all_gather") < plan_cl.count("all_gather"), (
        la, plan_la.totals(), classic, plan_cl.totals())
    total_la = sum(t["count"] for t in plan_la.totals().values())
    total_cl = sum(t["count"] for t in plan_cl.totals().values())
    assert total_la < total_cl


def _rounds(plan):
    """Collective rounds = executed collectives (size-1 axes elide)."""
    return sum(ev.count for ev in plan.events if ev.axis_size > 1)


@pytest.mark.parametrize("calu,baselines", an.CALU_PAIRS,
                         ids=[c for c, _ in an.CALU_PAIRS])
def test_calu_strictly_fewer_rounds_per_panel(calu, baselines):
    """ISSUE 6's acceptance pin: at equal n/nb (equal panel count) the
    tournament-pivoted CALU schedule issues strictly fewer collective
    rounds than BOTH classic-panel baselines on a real 2-D grid -- i.e.
    strictly fewer rounds per panel.  The win is structural: the panel
    permutation is one batched storage pass (zero explicit rounds) and
    the row-block solve is one psum instead of the classic
    all_to_all + all_gather pair."""
    g = _grid(2, 2)
    plan_ca, _, _ = an.trace_driver(calu, g)
    for base in baselines:
        plan_cl, _, _ = an.trace_driver(base, g)
        assert _rounds(plan_ca) < _rounds(plan_cl), (
            calu, plan_ca.totals(), base, plan_cl.totals())
    # and strictly fewer all_gathers than even the pipelined baseline
    plan_xo, _, _ = an.trace_driver("lu_crossover", g)
    assert plan_ca.count("all_gather") < plan_xo.count("all_gather")
    # the psum solve fully replaces the [STAR,VR] all_to_all dance
    assert plan_ca.count("all_to_all") == 0
    assert plan_ca.count("psum") > 0


def test_tsqr_adds_no_collective_rounds():
    """The QR tree panel is a replicated reduction: its comm plan must be
    identical in round count to the classic panel's (the tree wins on
    serial depth and MXU shape, never by adding communication)."""
    g = _grid(2, 2)
    plan_ts, _, _ = an.trace_driver("qr_tsqr", g)
    plan_cl, _, _ = an.trace_driver("qr", g)
    assert _rounds(plan_ts) == _rounds(plan_cl)


def test_every_registered_driver_has_goldens():
    """Registering an analysis variant without snapshotting its goldens
    must fail loudly here (and in tools/check.sh's coverage gate), not
    silently skip the new variant."""
    import os
    missing = [f"{d}@{r}x{c}" for d in an.driver_names() for (r, c) in GRIDS
               if not os.path.exists(golden_path(d, (r, c)))]
    assert not missing, (
        f"registered driver variants without golden snapshots: {missing}; "
        "run python -m perf.comm_audit diff <driver> --update-golden")


@pytest.mark.parametrize("name", ["cholesky", "lu"])
def test_driver_default_config_fewer_rounds_than_classic(name):
    """The DRIVER DEFAULTS (lookahead=True, crossover=None -> 4096) beat
    classic at small n too -- the tail collapse is on by default."""
    import jax.numpy as jnp
    from elemental_tpu.core.dist import Dist
    from elemental_tpu.core.distmatrix import DistMatrix
    g = _grid(2, 2)
    n, nb = 64, 16
    shape = an.storage_shape(n, n, Dist.MC, Dist.MR, g)

    def make(lookahead):
        def fn(a):
            A = DistMatrix(a, (n, n), Dist.MC, Dist.MR, 0, 0, g)
            if name == "cholesky":
                from elemental_tpu.lapack.cholesky import cholesky
                return cholesky(A, nb=nb, lookahead=lookahead)
            from elemental_tpu.lapack.lu import lu
            return lu(A, nb=nb, lookahead=lookahead)
        return fn

    arg = jax.ShapeDtypeStruct(shape, jnp.float32)
    plan_la, _, _ = an.trace_callable(make(True), (arg,), grid=g)
    plan_cl, _, _ = an.trace_callable(make(False), (arg,), grid=g)
    assert plan_la.count("all_gather") < plan_cl.count("all_gather")


@pytest.mark.parametrize("driver", ["cholesky_classic", "cholesky_crossover",
                                    "lu_classic", "lu_crossover", "herk"])
def test_analyzer_agrees_with_redist_counts(driver):
    """Cross-check the jaxpr view against the Python-call counters: each
    public redistribute()/panel_spread() call must appear as exactly one
    correspondingly named pjit equation in the traced program."""
    plan, closed, log = an.trace_driver(driver, _grid(2, 2))
    n_redist = sum(1 for r in log if r.kind == "redistribute")
    n_spread = sum(1 for r in log if r.kind == "panel_spread")
    assert an.count_pjit_calls(closed, "_redistribute_jit") == n_redist
    assert an.count_pjit_calls(closed, "_panel_spread_jit") == n_spread
    # and the plan's aggregated labels reproduce the counter totals
    assert sum(plan.redistributes.values()) == n_redist + n_spread


def test_plans_are_static_and_clean():
    """No driver hides collectives behind unbounded while loops, and the
    full registry is lint-clean on both grids."""
    for driver, grid in _CASES:
        plan, closed, log = an.trace_driver(driver, _grid(*grid))
        assert plan.static, driver
        findings = an.lint_plan(plan, log, closed)
        assert findings == [], (driver, grid, [str(f) for f in findings])


def test_size_one_axis_collectives_cost_zero():
    """1x1-grid plans may contain degenerate (axis_size==1) collective
    equations; the byte model prices them at zero."""
    plan, _, _ = an.trace_driver("gemm_a", _grid(1, 1))
    for ev in plan.events:
        assert ev.axis_size == 1 and ev.bytes_per_call == 0
