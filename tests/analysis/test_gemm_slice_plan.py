"""ISSUE 16 acceptance pins: the slicing gemm's comm plan.

At the tall-skinny golden geometry (the ``gemm_slice`` driver's
``(32n, n, n/4)`` extents) the slice schedule must run STRICTLY fewer
collective rounds than every SUMMA twin on both golden grids, and move
>= 1.5x fewer wire bytes than the stationary-C twin -- the honest
apples-to-apples baseline: stationary-C is the bit-identity reference of
the family and the only twin whose ABSTRACT TRACE carries its full wire
traffic (stationary-A/B and dot contract through GSPMD-inserted psums
that ``jax.make_jaxpr`` cannot see, so their traced bytes undercount;
the closed-form comparison below prices those psums and pins slice
cheapest against ALL five).
"""
import jax
import jax.numpy as jnp
import pytest

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from elemental_tpu.analysis.drivers import (DEFAULT_N, DEFAULT_NB,
                                            _mcmr_input,
                                            gemm_slice_extents)
from elemental_tpu.core.distmatrix import DistMatrix
from elemental_tpu.core.dist import MC, MR
from elemental_tpu.redist.plan import gemm_slice_plans
from elemental_tpu.tune import TuneContext
from elemental_tpu.tune import cost_model as cm

M, K, N = gemm_slice_extents(DEFAULT_N)          # (2048, 64, 16)
TWINS = ("C", "A", "B", "dot", "gspmd")


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


def _trace_alg(alg, grid, m=M, k=K, n=N):
    """Trace one gemm schedule at the tall-skinny geometry."""
    from elemental_tpu.blas.level3 import gemm

    def fn(a, b):
        A = DistMatrix(a, (m, k), MC, MR, 0, 0, grid)
        B = DistMatrix(b, (k, n), MC, MR, 0, 0, grid)
        return gemm(A, B, alg=alg, nb=DEFAULT_NB)
    args = (_mcmr_input(grid, m, k, jnp.float32),
            _mcmr_input(grid, k, n, jnp.float32))
    plan, _, _ = an.trace_callable(fn, args, name=f"gemm_{alg}", grid=grid)
    return plan


def _rounds_bytes(plan):
    tot = plan.totals()
    return (sum(t["count"] for t in tot.values()),
            sum(t["bytes"] for t in tot.values()))


def _psums(alg, grid_shape):
    """Closed-form psum count of one schedule (the contraction reductions
    GSPMD inserts at runtime -- INVISIBLE to the abstract trace, so the
    honest round count is traced hops + these)."""
    ctx = TuneContext("gemm", (M, K, N), "float32", grid_shape, "cpu")
    b = cm.score_config("gemm", {"alg": alg, "nb": DEFAULT_NB}, ctx=ctx,
                        grid=None, dtype=jnp.float32)
    return b.prim_counts.get("psum", 0)


@pytest.mark.parametrize("grid_shape", [(2, 2), (2, 4)],
                         ids=["2x2", "2x4"])
def test_slice_strictly_fewer_rounds_than_every_twin(grid_shape):
    g = _grid(*grid_shape)
    s_rounds, _ = _rounds_bytes(_trace_alg("slice", g))
    assert s_rounds == 3                    # the three one-shot plans
    assert _psums("slice", grid_shape) == 0  # k unsharded: NO hidden psum
    for alg in TWINS:
        t_rounds, _ = _rounds_bytes(_trace_alg(alg, g))
        t_rounds += _psums(alg, grid_shape)
        assert s_rounds < t_rounds, (alg, s_rounds, t_rounds)


@pytest.mark.parametrize("grid_shape", [(2, 2), (2, 4)],
                         ids=["2x2", "2x4"])
def test_slice_1p5x_fewer_wire_bytes_than_stationary_c(grid_shape):
    """>= 1.5x vs the stationary-C twin on both golden grids, traced.
    (Stationary-A/B/dot traced bytes omit their invisible GSPMD psums --
    the closed-form pin below covers those honestly.)"""
    g = _grid(*grid_shape)
    _, s_bytes = _rounds_bytes(_trace_alg("slice", g))
    _, c_bytes = _rounds_bytes(_trace_alg("C", g))
    assert c_bytes >= 1.5 * s_bytes, (s_bytes, c_bytes)


def test_slice_closed_form_beats_every_twin_on_2x4():
    """Psums priced in (the ring model's 2B(S-1)/S), slice still moves
    >= 1.5x fewer comm bytes than the BEST twin on the non-square grid."""
    ctx = TuneContext("gemm", (M, K, N), "float32", (2, 4), "cpu")
    def score(alg):
        return cm.score_config("gemm", {"alg": alg, "nb": DEFAULT_NB},
                               ctx=ctx, grid=None, dtype=jnp.float32)
    s = score("slice")
    best_twin = min(score(a).comm_bytes for a in TWINS)
    assert best_twin >= 1.5 * s.comm_bytes, (s.comm_bytes, best_twin)
    # the closed form collapses each twin's multi-hop operand chain to
    # one gather, so rounds there are a LOWER bound; slice still never
    # exceeds any twin, and the traced pin above is strict.
    assert all(s.rounds <= score(a).rounds for a in TWINS)


@pytest.mark.parametrize("grid_shape", [(2, 2), (2, 4)],
                         ids=["2x2", "2x4"])
def test_traced_bytes_equal_compiled_plan_bytes(grid_shape):
    """The trace and the plan compiler agree EXACTLY: what the tuner
    prices is what the executor ships (no hidden psum on the slice path)."""
    g = _grid(*grid_shape)
    _, s_bytes = _rounds_bytes(_trace_alg("slice", g))
    mode, plans = gemm_slice_plans(M, K, N, grid_shape)
    assert mode == "rows"                   # m >= n: row slices
    compiled = sum(p.wire_bytes(4) for _, p in plans
                   if p is not None and p.kind != "local")
    assert s_bytes == compiled, (s_bytes, compiled)


@pytest.mark.parametrize("grid_shape,mode", [((4, 1), "rows"),
                                             ((1, 4), "cols"),
                                             ((1, 8), "cols"),
                                             ((8, 1), "rows")],
                         ids=["4x1", "1x4", "1x8", "8x1"])
def test_degenerate_grids_single_collective(grid_shape, mode):
    """Nx1 / 1xN: two of the three legs are pure local relabelings, so
    the whole gemm is ONE collective (the small-operand broadcast)."""
    g = _grid(*grid_shape)
    rounds, _ = _rounds_bytes(_trace_alg("slice", g))
    assert rounds == 1
    got_mode, plans = gemm_slice_plans(M, K, N, grid_shape)
    assert got_mode == mode
    assert sum(p.rounds for _, p in plans if p is not None) == 1


def test_slice_golden_matches_live_trace():
    """The checked-in golden is the live trace (check.sh gate mirror)."""
    import json
    from perf.comm_audit import golden_path
    plan = _trace_alg("slice", _grid(2, 2))
    with open(golden_path("gemm_slice", (2, 2))) as f:
        doc = json.load(f)
    assert {p: t["count"] for p, t in doc["totals"].items()} == \
        {p: t["count"] for p, t in plan.totals().items()}
    assert {p: t["bytes"] for p, t in doc["totals"].items()} == \
        {p: t["bytes"] for p, t in plan.totals().items()}
