"""Memory lint rules EL006-EL009 (ISSUE 18).

Each rule is exercised positively (a seeded violation fires) and
negatively (the registry is clean / the guard conditions hold).  EL007
additionally pins GATE AGREEMENT: the static cross-check and the dynamic
``use_pallas`` gate must reach the same verdict on the same oversized
panel -- the lint is only trustworthy if it models the gate exactly.
"""
import jax
import jax.numpy as jnp
import pytest

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from elemental_tpu.analysis.lint import (rule_mem_budget,
                                         rule_vmem_overflow,
                                         rule_missing_donation,
                                         rule_double_materialization)
from elemental_tpu.kernels import PanelPlan
from elemental_tpu.kernels.common import PANEL_VMEM_BUDGET


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


# ---------------------------------------------------------------------
# EL006 peak-over-budget
# ---------------------------------------------------------------------

def test_el006_fires_on_tight_budget():
    mplan, closed, log = an.trace_memory("gemm_slice", _grid(2, 2))
    findings = an.lint_memory(mplan, log, closed, budget_factor=1.0)
    el6 = [f for f in findings if f.rule == "EL006"]
    assert len(el6) == 1
    assert "exceeds the declared budget" in el6[0].message
    assert "MEM_BUDGET_FACTORS" in el6[0].fix_hint


def test_el006_quiet_at_declared_budget():
    mplan, closed, log = an.trace_memory("gemm_slice", _grid(2, 2))
    assert an.lint_memory(mplan, log, closed) == []


def test_el006_names_high_water_scope():
    mplan, _, _ = an.trace_memory("gemm_slice", _grid(2, 2))
    (f,) = rule_mem_budget(mplan, 1.0)
    assert "high-water at" in f.message
    assert mplan.stats.peak_prim in f.message


def test_declared_factors_cover_both_grids():
    """Every override in MEM_BUDGET_FACTORS is load-bearing AND
    sufficient: the driver exceeds the 4.0 default on some grid and
    fits its declared factor on all."""
    for name, factor in an.MEM_BUDGET_FACTORS.items():
        ratios = []
        for grid in ((1, 1), (2, 2)):
            mplan, _, _ = an.trace_memory(name, _grid(*grid))
            base = mplan.stats.args_bytes + mplan.stats.outs_bytes
            ratios.append(mplan.peak_bytes / max(base, 1))
            assert rule_mem_budget(mplan, factor) == [], (name, grid)
        assert max(ratios) > 4.0, \
            f"{name}: override {factor} no longer needed (max ratio " \
            f"{max(ratios):.2f}) -- delete it from MEM_BUDGET_FACTORS"


# ---------------------------------------------------------------------
# EL007 vmem-overflow + dynamic-gate agreement
# ---------------------------------------------------------------------

#: a panel the 16 MiB gate ADMITS (3 tile-padded f32 copies of
#: 1024x1024 = 12 MiB) but whose qr kernel -- with its square (tp, tp)
#: larft accumulator on top -- actually allocates ~16.2 MiB: the exact
#: divergence class EL007 exists to catch
_OVERSIZED = ("qr", (1024, 1024), "float32")


def test_el007_fires_on_oversized_panel():
    op, shape, dtype = _OVERSIZED
    chk = an.check_panel_vmem(op, shape, dtype)
    assert chk.admitted and not chk.fits and chk.overflow
    (f,) = rule_vmem_overflow([chk])
    assert f.rule == "EL007" and f.severity == "error"
    assert str(chk.kernel_bytes) in f.message


def test_el007_dynamic_gate_agrees_on_oversized_panel():
    """The dynamic gate verdict for the seeded EL007 panel: use_pallas
    ADMITS it (that is the bug class -- the kernel would overflow), and
    pricing at the kernel's honest resident count (4 copies: 3 panels +
    the square larft T) makes the SAME gate refuse it."""
    op, shape, _ = _OVERSIZED
    gate_copies = an.PANEL_GATE_COPIES[op]
    plan = PanelPlan(impl="pallas", inners=(512, 64), source="test")
    chk = an.check_panel_vmem(op, shape, "float32")
    # the dynamic gate at the dispatch site's copies ADMITS the panel --
    # same verdict as the static check (that IS the bug class)
    assert plan.use_pallas(shape, jnp.float32, copies=gate_copies)
    assert chk.admitted
    # priced at the kernel's honest resident count, the SAME dynamic
    # gate refuses it -- the fix EL007's hint prescribes
    per_copy = chk.gate_bytes // gate_copies
    honest = -(-chk.kernel_bytes // per_copy)
    assert honest > gate_copies
    assert not plan.use_pallas(shape, jnp.float32, copies=honest)


def test_el007_quiet_on_default_sweeps():
    """Every panel shape the registered drivers actually dispatch at
    their default geometry passes the cross-check (goldens stay clean)."""
    for op in an.PANEL_GATE_COPIES:
        for chk in an.panel_vmem_checks(op, an.DEFAULT_N, an.DEFAULT_NB):
            assert not chk.overflow, chk


def test_el007_not_admitted_is_not_overflow():
    """A panel the gate already REJECTS is the fallback working as
    designed, not a finding."""
    chk = an.check_panel_vmem("cholesky", (4096, 4096), "float64",
                              budget=PANEL_VMEM_BUDGET)
    assert not chk.admitted and not chk.fits
    assert rule_vmem_overflow([chk]) == []


# ---------------------------------------------------------------------
# EL008 missing-donation
# ---------------------------------------------------------------------

def _aba_plan(donated):
    """A jit-style entry whose output aval equals input 0's aval."""
    def fn(a, b):
        return (a * 2.0 + b).astype(a.dtype)

    args = (jax.ShapeDtypeStruct((32, 32), jnp.float32),
            jax.ShapeDtypeStruct((32, 32), jnp.float32))
    closed = jax.make_jaxpr(fn)(*args)
    meta = {"n": 32, "dtype": "float32"}
    if donated is not None:
        meta["donated"] = donated
    mplan = an.memory_plan("toy_entry", (1, 1), meta, closed)
    return mplan, closed


def test_el008_fires_on_undonated_matching_input():
    mplan, closed = _aba_plan(donated=())
    findings = rule_missing_donation(mplan, closed)
    # BOTH f32 (32,32) inputs match the output aval and neither is donated
    assert [f.rule for f in findings] == ["EL008", "EL008"]
    assert "donate_argnums" in findings[0].fix_hint


def test_el008_quiet_when_donated():
    mplan, closed = _aba_plan(donated=(0, 1))
    assert rule_missing_donation(mplan, closed) == []


def test_el008_skips_undeclared_entries():
    """No meta['donated'] = the entry never claimed donation semantics;
    the registry drivers stay out of scope (and lint clean)."""
    mplan, closed = _aba_plan(donated=None)
    assert rule_missing_donation(mplan, closed) == []


def test_el008_serve_executor_paths_lintable():
    """The serve exec-cache kernels, linted through the same rule: the
    donated build is clean, the undonated build of the same kernel has
    findings -- the `__donated` convention is now checkable."""
    from elemental_tpu.serve.executor import _kernel

    args = (jax.ShapeDtypeStruct((4, 16, 16), jnp.float64),
            jax.ShapeDtypeStruct((4, 16, 2), jnp.float64))
    closed = jax.make_jaxpr(jax.vmap(_kernel("hpd")))(*args)
    meta = {"n": 16, "dtype": "float64"}
    donated = an.memory_plan("serve_hpd", (1, 1),
                             dict(meta, donated=(0, 1)), closed)
    undonated = an.memory_plan("serve_hpd", (1, 1),
                               dict(meta, donated=()), closed)
    assert rule_missing_donation(donated, closed) == []
    assert any(f.rule == "EL008"
               for f in rule_missing_donation(undonated, closed))


# ---------------------------------------------------------------------
# EL009 double-materialization
# ---------------------------------------------------------------------

def test_el009_fires_on_repeated_full_gather():
    """Two [*,*] gathers of the same DistMatrix = p replicas paid twice."""
    from elemental_tpu.core.dist import Dist
    from elemental_tpu.redist.engine import redistribute, redist_trace
    import elemental_tpu as el

    g = _grid(2, 2)
    STAR = Dist.STAR

    def fn(a):
        A = el.DistMatrix(a, (16, 16), Dist.MC, Dist.MR, 0, 0, g)
        F1 = redistribute(A, STAR, STAR)
        F2 = redistribute(A, STAR, STAR)
        return F1.local + F2.local

    from elemental_tpu.analysis.drivers import storage_shape
    arg = jax.ShapeDtypeStruct(
        storage_shape(16, 16, Dist.MC, Dist.MR, g), jnp.float32)
    with redist_trace() as log:
        closed = jax.make_jaxpr(fn)(arg)
    mplan = an.memory_plan("toy_double", (2, 2), {"n": 16}, closed, log)
    findings = rule_double_materialization(mplan, log)
    assert [f.rule for f in findings] == ["EL009"]
    assert "2 separate [*,*] gathers" in findings[0].message
    assert "hoist" in findings[0].fix_hint


def test_el009_quiet_on_distinct_operands():
    from elemental_tpu.core.dist import Dist
    from elemental_tpu.redist.engine import redistribute, redist_trace
    from elemental_tpu.analysis.drivers import storage_shape
    import elemental_tpu as el

    g = _grid(2, 2)

    def fn(a, b):
        A = el.DistMatrix(a, (16, 16), Dist.MC, Dist.MR, 0, 0, g)
        B = el.DistMatrix(b, (16, 16), Dist.MC, Dist.MR, 0, 0, g)
        FA = redistribute(A, Dist.STAR, Dist.STAR)
        FB = redistribute(B, Dist.STAR, Dist.STAR)
        return FA.local + FB.local

    arg = jax.ShapeDtypeStruct(
        storage_shape(16, 16, Dist.MC, Dist.MR, g), jnp.float32)
    with redist_trace() as log:
        closed = jax.make_jaxpr(fn)(arg, arg)
    mplan = an.memory_plan("toy_two", (2, 2), {"n": 16}, closed, log)
    assert rule_double_materialization(mplan, log) == []


# ---------------------------------------------------------------------
# the registry stays clean end to end
# ---------------------------------------------------------------------

@pytest.mark.parametrize("grid", [(1, 1), (2, 2)], ids=["1x1", "2x2"])
def test_registry_lints_clean(grid):
    for driver in an.driver_names():
        mplan, closed, log = an.trace_memory(driver, _grid(*grid))
        findings = an.lint_memory(mplan, log, closed)
        assert findings == [], \
            f"{driver} {grid}: " + "; ".join(str(f) for f in findings)
