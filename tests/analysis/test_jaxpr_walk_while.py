"""While-loop handling of the jaxpr walkers, in isolation (ISSUE 18
satellite).

A ``while`` body has NO static trip count, so everything inside it is
unquantifiable at trace time.  The contract, pinned here end to end:

* the collective walker marks while-body events ``static=False`` and
  counts the body ONCE (never a guessed multiplier), and the plan's
  ``static`` flag -- part of every golden document -- flips to False;
* the MEMORY walker excludes while-body allocations from the pinned
  golden byte totals (``peak_bytes`` / ``walk_peak_bytes``) and routes
  them to ``nonstatic_peak_bytes`` instead;
* lint still SEES them: EL006 folds ``nonstatic_peak_bytes`` into the
  budget check, so non-static growth surfaces as a finding even though
  it never moves a golden number.

Previously this behavior was only crossed incidentally by driver traces;
these tests isolate it on minimal jaxprs so a walker refactor cannot
silently change the accounting.
"""
import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from elemental_tpu.core.compat import shard_map

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from elemental_tpu.analysis.jaxpr_walk import collect_events
from elemental_tpu.analysis.lint import rule_mem_budget
from elemental_tpu.analysis.plan import plan_from_parts


@pytest.fixture(scope="module")
def g22():
    return Grid(jax.devices()[:4], height=2)


def _smap(g, fn):
    return shard_map(fn, mesh=g.mesh, in_specs=P(),
                     out_specs=P(), check_vma=False)


def _while_program(g22):
    """A while body that both ALLOCATES (a fresh (8, 8) intermediate per
    iteration) and COMMUNICATES (one psum), behind a static prologue."""
    def body(x):
        pre = x * 2.0                        # static allocation

        def cond(c):
            return c[0] < 3

        def step(c):
            grown = c[1] @ c[1].T            # non-static allocation
            return (c[0] + 1, grown + lax.psum(c[1], "mr"))

        return lax.while_loop(cond, step, (0, pre))[1]

    return jax.make_jaxpr(_smap(g22, body))(
        jax.ShapeDtypeStruct((8, 8), jnp.float32))


def test_while_events_count_once_not_multiplied(g22):
    closed = _while_program(g22)
    evs = collect_events(closed)
    psums = [ev for ev in evs if ev.prim == "psum"]
    assert len(psums) == 1
    assert psums[0].count == 1, "while bodies must never guess a trip count"
    assert not psums[0].static
    assert any(p.startswith("while") for p in psums[0].path)


def test_while_flips_plan_static_flag(g22):
    closed = _while_program(g22)
    plan = plan_from_parts("toy_while", (2, 2), {"n": 8},
                           collect_events(closed), ())
    assert plan.static is False
    assert plan.to_doc(events=False)["static"] is False
    # the events still participate in totals at their once-counted size:
    # the golden doc records them, flagged, rather than hiding them
    assert plan.totals()["psum"]["count"] == 1


def test_scan_stays_static_for_contrast(g22):
    """The sibling construct WITH a static trip count keeps static=True
    and multiplies -- the walker distinguishes the two loop prims."""
    def body(x):
        def step(c, _):
            return c + lax.psum(c, "mr"), None
        return lax.scan(step, x, None, length=4)[0]

    closed = jax.make_jaxpr(_smap(g22, body))(
        jax.ShapeDtypeStruct((8, 8), jnp.float32))
    (ev,) = collect_events(closed)
    assert ev.static and ev.count == 4


# ---------------------------------------------------------------------
# memory walker: excluded from goldens, surfaced in lint
# ---------------------------------------------------------------------

def test_while_allocations_excluded_from_golden_peak(g22):
    """Body-internal intermediates are NON-static (the loop may run any
    number of times); the while's carry OUTPUTS are static (they exist
    after the loop regardless).  Pin the split by blowing up only the
    body's scratch: the golden peak must not move, the non-static
    component must."""
    def make(scratch):
        def body(x):
            pre = x * 2.0

            def cond(c):
                return c[0] < 3

            def step(c):
                big = jnp.zeros((scratch, scratch), jnp.float32)
                return (c[0] + 1,
                        c[1] + lax.psum(c[1], "mr") + big[:8, :8])

            return lax.while_loop(cond, step, (0, pre))[1]

        closed = jax.make_jaxpr(_smap(g22, body))(
            jax.ShapeDtypeStruct((8, 8), jnp.float32))
        return an.analyze_jaxpr(closed, grid_size=4)

    small, big = make(8), make(64)
    assert small.nonstatic_peak_bytes > 0
    assert not small.static
    assert big.peak_bytes == small.peak_bytes, \
        "body scratch leaked into the pinned golden peak"
    assert big.nonstatic_peak_bytes > small.nonstatic_peak_bytes


def test_while_memory_doc_carries_nonstatic_field(g22):
    closed = _while_program(g22)
    mplan = an.memory_plan("toy_while", (2, 2), {"n": 8}, closed)
    doc = mplan.to_doc()
    assert doc["static"] is False
    assert doc["nonstatic_peak_bytes"] == mplan.stats.nonstatic_peak_bytes
    assert doc["nonstatic_peak_bytes"] > 0
    assert doc["walk_peak_bytes"] == mplan.stats.peak_bytes


def test_while_allocations_surface_in_el006(g22):
    """EL006 folds the non-static high water into the budget check: a
    budget the static peak fits but static+nonstatic exceeds FIRES, and
    the finding names the while-body component."""
    closed = _while_program(g22)
    mplan = an.memory_plan("toy_while", (2, 2), {"n": 8}, closed)
    static_peak = mplan.peak_bytes
    ns = mplan.stats.nonstatic_peak_bytes
    base = max(mplan.stats.args_bytes + mplan.stats.outs_bytes, 1)
    # budget strictly between the static peak and the folded total
    factor = (static_peak + ns / 2) / base
    assert static_peak <= factor * base < static_peak + ns
    (f,) = rule_mem_budget(mplan, factor)
    assert f.rule == "EL006"
    assert "NO static trip count" in f.message
    # while a budget covering the folded total stays quiet
    assert rule_mem_budget(mplan, (static_peak + ns) * 1.01 / base) == []
