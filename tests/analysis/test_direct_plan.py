"""The ``*_direct`` analysis drivers (ISSUE 12): each one-shot gemm
variant pins STRICTLY fewer total collective rounds than its chained twin
on the 2x2 grid (the acceptance criterion of the plan compiler), stays
no worse on 1x1 (where every plan is 'local' and the chain still issues
degenerate 1-participant collectives), and every registered ``*_direct``
driver has committed comm-plan goldens for every audit grid."""
import os

import jax
import pytest

from elemental_tpu import Grid
from elemental_tpu import analysis as an
from elemental_tpu.analysis import DIRECT_PAIRS

DIRECT_IDS = [d for d, _ in DIRECT_PAIRS]


def _total_rounds(driver, grid):
    g = Grid(jax.devices()[: grid[0] * grid[1]], height=grid[0])
    plan, _, _ = an.trace_driver(driver, g)
    return sum(v["count"] for v in plan.totals().values())


def test_direct_variants_registered():
    names = set(an.driver_names())
    assert {"gemm_a_direct", "gemm_b_direct", "gemm_dot_direct"} <= names
    for direct, chain in DIRECT_PAIRS:
        assert direct in names and chain in names


@pytest.mark.parametrize("direct,chain", DIRECT_PAIRS, ids=DIRECT_IDS)
def test_direct_strictly_fewer_rounds_on_2x2(direct, chain):
    """THE acceptance pin: the one-shot schedule issues strictly fewer
    collective rounds than the multi-hop chain on a real 2-D grid."""
    assert _total_rounds(direct, (2, 2)) < _total_rounds(chain, (2, 2))


@pytest.mark.parametrize("direct,chain", DIRECT_PAIRS, ids=DIRECT_IDS)
def test_direct_no_worse_on_1x1(direct, chain):
    """On 1x1 every compiled plan is 'local' (zero collectives), while
    the chain still emits degenerate 1-participant rounds -- the direct
    variant must be <=, never more."""
    assert _total_rounds(direct, (1, 1)) <= _total_rounds(chain, (1, 1))


def test_direct_uses_one_shot_collectives_on_2x2():
    """The direct gemm schedules move operands via all_to_all/ppermute
    plans, never the chain's per-hop all_gather."""
    totals = {}
    for direct, _ in DIRECT_PAIRS:
        g = Grid(jax.devices()[:4], height=2)
        plan, _, _ = an.trace_driver(direct, g)
        totals[direct] = plan.totals()
    for direct, t in totals.items():
        assert "all_gather" not in t, (direct, t)


def test_redist_md_direct_ragged_byte_drop():
    """ISSUE 13: the redist_md driver round-trips a RAGGED [MD,STAR]
    matrix (extents n-1 x n-3, incompatible with every grid residue).
    Its direct twin is pinned on BYTES, not rounds: the ragged-slot
    a2a packs trimmed slots over subgroups, so the traced wire bytes
    drop strictly below the chain's padded hops.  (The pair is
    deliberately NOT in DIRECT_PAIRS -- its win is the byte axis.)"""
    g = Grid(jax.devices()[:4], height=2)
    bytes_ = {}
    for name in ("redist_md", "redist_md_direct"):
        plan, _, _ = an.trace_driver(name, g)
        bytes_[name] = sum(v["bytes"] for v in plan.totals().values())
    assert 0 < bytes_["redist_md_direct"] < bytes_["redist_md"]


def test_every_direct_driver_has_goldens():
    """tools/check.sh's golden-coverage sweep runs driver_names() x GRIDS;
    a *_direct variant without committed goldens breaks the gate -- catch
    it here with a named message instead."""
    from perf.comm_audit import GRIDS, golden_path
    missing = [
        os.path.relpath(golden_path(name, grid))
        for name in an.driver_names() if name.endswith("_direct")
        for grid in GRIDS
        if not os.path.exists(golden_path(name, grid))
    ]
    assert not missing, f"regenerate with --update-golden: {missing}"
