"""Quantized-collective comm plans (ISSUE 8): the jaxpr-level pin of the
EQuARX win.

The ``*_commq`` registry variants trace the SAME schedules as their
full-precision twins with ``comm_precision='bf16'``; these tests pin, on
the 2x2 grid, identical per-collective round counts with >= 1.9x lower
total estimated wire bytes -- bytes drop because the collective operands
in the traced program really ARE bfloat16 (payload-dtype-aware byte
estimates), not because any round disappeared or was re-counted.
"""
import jax
import pytest

from elemental_tpu import Grid
from elemental_tpu import analysis as an


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


@pytest.mark.parametrize("commq,base", an.COMMQ_PAIRS,
                         ids=[c for c, _ in an.COMMQ_PAIRS])
def test_commq_byte_drop_at_identical_rounds(commq, base):
    g = _grid(2, 2)
    plan_q, _, _ = an.trace_driver(commq, g)
    plan_b, _, _ = an.trace_driver(base, g)
    tq, tb = plan_q.totals(), plan_b.totals()
    # identical collective schedule: same primitives, same round counts
    assert {k: v["count"] for k, v in tq.items()} \
        == {k: v["count"] for k, v in tb.items()}, (tq, tb)
    # and the same Python-level redistribution call structure
    assert plan_q.redistributes == plan_b.redistributes
    bytes_q = sum(v["bytes"] for v in tq.values())
    bytes_b = sum(v["bytes"] for v in tb.values())
    assert bytes_q > 0
    ratio = bytes_b / bytes_q
    assert ratio >= an.COMMQ_MIN_BYTE_RATIO, (
        f"{commq}: wire bytes dropped only {ratio:.2f}x vs {base} "
        f"({bytes_b} -> {bytes_q}); the acceptance bar is "
        f">= {an.COMMQ_MIN_BYTE_RATIO}x")


@pytest.mark.parametrize("commq,base", an.COMMQ_PAIRS,
                         ids=[c for c, _ in an.COMMQ_PAIRS])
def test_commq_collectives_move_bf16(commq, base):
    """Every executed collective of a commq plan carries a bfloat16
    payload (gathers ride the wire cast, the CALU row-block psum reduces
    at bf16) -- no full-precision leak in the quantized schedule."""
    g = _grid(2, 2)
    plan, _, _ = an.trace_driver(commq, g)
    moved = [ev for ev in plan.events if ev.axis_size > 1]
    assert moved
    assert all(ev.dtype == "bfloat16" for ev in moved), \
        sorted({(ev.prim, ev.dtype) for ev in moved})


@pytest.mark.parametrize("commq,base", an.COMMQ_PAIRS,
                         ids=[c for c, _ in an.COMMQ_PAIRS])
def test_commq_noop_on_1x1(commq, base):
    """On a 1x1 grid the knob is dead (no collectives execute): the commq
    plan's totals equal the baseline's exactly."""
    g = _grid(1, 1)
    plan_q, _, _ = an.trace_driver(commq, g)
    plan_b, _, _ = an.trace_driver(base, g)
    assert plan_q.totals() == plan_b.totals()
    assert plan_q.redistributes == plan_b.redistributes


def test_commq_variants_registered_with_bf16_optin():
    """The commq specs opt into EL005 (bf16 on the wire is intentional
    here); their full-precision twins do not."""
    for commq, base in an.COMMQ_PAIRS:
        assert an.DRIVERS[commq].allow_bf16 is True
        assert an.DRIVERS[base].allow_bf16 is False
    assert an.COMMQ_MIN_BYTE_RATIO == 1.9
