"""Sparse core + Krylov solver oracles.

Reference analog: the reference tests its sparse layer through driver
programs solving Laplacian/Helmholtz systems and checking residuals
(SURVEY.md §5); same oracles here, on the 8-device mesh.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.core.multivec import (mv_axpy, mv_dot, mv_nrm2,
                                         mv_remote_updates)


def _laplacian_1d(n):
    """Tridiagonal 1-D Laplacian (SPD): the reference's standard sparse
    test operator (``El::Laplacian``)."""
    rows, cols, vals = [], [], []
    for i in range(n):
        rows.append(i); cols.append(i); vals.append(2.0)
        if i > 0:
            rows.append(i); cols.append(i - 1); vals.append(-1.0)
        if i < n - 1:
            rows.append(i); cols.append(i + 1); vals.append(-1.0)
    return rows, cols, vals


class TestDistMultiVec:
    def test_roundtrip_and_ops(self, grid24):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(13, 3))     # 13 does not divide 8: padding
        Y = rng.normal(size=(13, 3))
        Xd = el.mv_from_global(X, grid=grid24)
        Yd = el.mv_from_global(Y, grid=grid24)
        np.testing.assert_allclose(np.asarray(el.mv_to_global(Xd)), X)
        np.testing.assert_allclose(np.asarray(el.mv_to_global(
            mv_axpy(2.0, Xd, Yd))), 2.0 * X + Y)
        np.testing.assert_allclose(float(mv_dot(Xd, Yd)), np.sum(X * Y))
        np.testing.assert_allclose(float(mv_nrm2(Xd)), np.linalg.norm(X))

    def test_remote_updates(self, grid24):
        v = el.mv_zeros(10, 2, grid=grid24, dtype=np.float64)
        # duplicate updates must SUM (queued RemoteUpdate semantics)
        v = mv_remote_updates(v, [3, 3, 9], [0, 0, 1], [1.0, 2.0, 5.0])
        out = np.asarray(el.mv_to_global(v))
        assert out[3, 0] == 3.0 and out[9, 1] == 5.0 and out.sum() == 8.0
        # writes into the padding tail (rows m..p*blk) or beyond must raise,
        # not silently corrupt the padding-oblivious reductions
        with pytest.raises(ValueError):
            mv_remote_updates(v, [12], [0], [5.0])
        with pytest.raises(ValueError):
            mv_remote_updates(v, [3], [2], [5.0])

    def test_remote_updates_traced_indices(self, grid24):
        """Traced (jit) indices skip the host-side bounds check via the
        CONCRETE TracerArrayConversionError -- the validator must neither
        swallow unrelated errors (the old bare ``except Exception``) nor
        reject tracers."""
        import jax
        import jax.numpy as jnp
        from elemental_tpu.core.multivec import _validate_update_indices

        v = el.mv_zeros(10, 2, grid=grid24, dtype=np.float64)

        @jax.jit
        def upd(v, rows, cols, vals):
            return mv_remote_updates(v, rows, cols, vals)

        out = upd(v, jnp.array([3, 3]), jnp.array([0, 0]),
                  jnp.array([1.0, 2.0]))
        assert np.asarray(el.mv_to_global(out))[3, 0] == 3.0
        # non-Tracer conversion failures now propagate instead of being
        # silently treated as "traced"
        with pytest.raises(ValueError):
            _validate_update_indices(np.array([[1], [2]]),   # ragged object
                                     [[3, 4], [5]], 10, 2, (10, 2))

    def test_distmatrix_bridges(self, grid24):
        X = np.arange(24.0).reshape(12, 2)
        v = el.mv_from_global(X, grid=grid24)
        A = el.mv_to_distmatrix(v)
        assert A.dist == (el.MC, el.MR)
        np.testing.assert_allclose(np.asarray(el.to_global(A)), X)
        v2 = el.mv_from_distmatrix(A)
        np.testing.assert_allclose(np.asarray(el.mv_to_global(v2)), X)


class TestGraphAndMap:
    def test_graph_dedup(self):
        g = el.Graph(4)
        g.queue_connection(0, 1)
        g.queue_connection(0, 1)          # duplicate edge
        g.queue_connection(2, 3)
        s, t = g.process_queues()
        assert g.num_edges == 2
        assert s.tolist() == [0, 2] and t.tolist() == [1, 3]
        with pytest.raises(ValueError):
            g.queue_connection(4, 0)

    def test_dist_map(self, grid24):
        perm = [2, 0, 3, 1, 4]
        M = el.DistMap(perm, grid24)
        X = np.arange(10.0).reshape(5, 2)
        v = el.mv_from_global(X, grid=grid24)
        w = np.asarray(el.mv_to_global(M.translate(v)))
        exp = np.empty_like(X)
        for i, pi in enumerate(perm):
            exp[pi] = X[i]
        np.testing.assert_allclose(w, exp)
        Minv = M.inverse()
        np.testing.assert_allclose(
            np.asarray(el.mv_to_global(Minv.translate(M.translate(v)))), X)


class TestSparseMatrix:
    def test_builder_coalesce_and_dense(self, grid24):
        S = el.SparseMatrix(4, 5)
        S.queue_update(0, 0, 1.0)
        S.queue_update(0, 0, 2.0)         # duplicate sums -> 3.0
        S.queue_update(3, 4, -1.0)
        S.queue_update(2, 1, 0.5)
        A = S.freeze(grid24, dtype=np.float64)
        assert A.nnz == 3
        D = np.asarray(el.to_global(A.to_dense()))
        exp = np.zeros((4, 5))
        exp[0, 0], exp[3, 4], exp[2, 1] = 3.0, -1.0, 0.5
        np.testing.assert_allclose(D, exp)

    @pytest.mark.parametrize("shape", [(17, 17), (23, 11), (8, 16)])
    def test_spmv_vs_dense(self, grid24, shape):
        m, n = shape
        rng = np.random.default_rng(m * n)
        nnz = 3 * max(m, n)
        rows = rng.integers(0, m, nnz)
        cols = rng.integers(0, n, nnz)
        vals = rng.normal(size=nnz)
        A = el.dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                                    dtype=np.float64)
        D = np.zeros((m, n)); np.add.at(D, (rows, cols), vals)
        X = rng.normal(size=(n, 2))
        Y = np.asarray(el.mv_to_global(
            A.spmv(el.mv_from_global(X, grid=grid24))))
        np.testing.assert_allclose(Y, D @ X, atol=1e-12)
        Z = rng.normal(size=(m, 2))
        W = np.asarray(el.mv_to_global(
            A.spmv_adjoint(el.mv_from_global(Z, grid=grid24))))
        np.testing.assert_allclose(W, D.T @ Z, atol=1e-12)

    def test_with_values_refactor_path(self, grid24):
        rows, cols, vals = _laplacian_1d(9)
        A = el.dist_sparse_from_coo(rows, cols, vals, 9, 9, grid=grid24,
                                    dtype=np.float64)
        A2 = A.with_values(2.0 * A.vals)
        x = el.mv_from_global(np.ones((9, 1)), grid=grid24)
        y1 = np.asarray(el.mv_to_global(A.spmv(x)))
        y2 = np.asarray(el.mv_to_global(A2.spmv(x)))
        np.testing.assert_allclose(y2, 2.0 * y1)


class TestSolvers:
    def test_cg_laplacian(self, grid24):
        n = 40
        rows, cols, vals = _laplacian_1d(n)
        A = el.dist_sparse_from_coo(rows, cols, vals, n, n, grid=grid24,
                                    dtype=np.float64)
        rng = np.random.default_rng(1)
        b = rng.normal(size=(n, 1))
        x, info = el.cg(A, el.mv_from_global(b, grid=grid24), tol=1e-12)
        assert info["converged"], info
        D = np.asarray(el.to_global(A.to_dense()))
        xg = np.asarray(el.mv_to_global(x))
        assert np.linalg.norm(D @ xg - b) / np.linalg.norm(b) < 1e-9

    def test_cgls_least_squares(self, grid24):
        rng = np.random.default_rng(2)
        m, n = 30, 12
        rows = rng.integers(0, m, 5 * m)
        cols = rng.integers(0, n, 5 * m)
        vals = rng.normal(size=5 * m)
        # ensure full column rank: add the identity block
        rows = np.concatenate([rows, np.arange(n)])
        cols = np.concatenate([cols, np.arange(n)])
        vals = np.concatenate([vals, 3.0 * np.ones(n)])
        A = el.dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                                    dtype=np.float64)
        b = rng.normal(size=(m, 1))
        x, info = el.cgls(A, el.mv_from_global(b, grid=grid24), tol=1e-12)
        assert info["converged"], info
        D = np.zeros((m, n)); np.add.at(D, (rows, cols), vals)
        xref, *_ = np.linalg.lstsq(D, b, rcond=None)
        np.testing.assert_allclose(np.asarray(el.mv_to_global(x)), xref,
                                   atol=1e-7)

    def test_gmres_nonsymmetric(self, grid24):
        n = 25
        rows, cols, vals = _laplacian_1d(n)
        # break symmetry: convection term on the superdiagonal
        rows = list(rows) + list(range(n - 1))
        cols = list(cols) + list(range(1, n))
        vals = list(vals) + [0.4] * (n - 1)
        A = el.dist_sparse_from_coo(rows, cols, vals, n, n, grid=grid24,
                                    dtype=np.float64)
        rng = np.random.default_rng(3)
        b = rng.normal(size=(n, 1))
        x, info = el.gmres(A, el.mv_from_global(b, grid=grid24), tol=1e-11)
        assert info["converged"], info
        D = np.asarray(el.to_global(A.to_dense()))
        xg = np.asarray(el.mv_to_global(x))
        assert np.linalg.norm(D @ xg - b) / np.linalg.norm(b) < 1e-8

    def test_gmres_complex(self, grid24):
        """Complex Arnoldi must keep complex H: full Krylov convergence in
        <= n steps, not restart-driven refinement."""
        n = 8
        rng = np.random.default_rng(4)
        rows, cols = np.nonzero(np.ones((n, n)))
        vals = (rng.normal(size=n * n) + 1j * rng.normal(size=n * n))
        vals += np.where(rows == cols, 4.0 * n, 0.0)
        A = el.dist_sparse_from_coo(rows, cols, vals, n, n, grid=grid24,
                                    dtype=np.complex128)
        b = rng.normal(size=(n, 1)) + 1j * rng.normal(size=(n, 1))
        x, info = el.gmres(A, el.mv_from_global(b, grid=grid24), tol=1e-10)
        assert info["converged"] and info["iters"] <= n + 1, info
        D = np.asarray(el.to_global(A.to_dense()))
        xg = np.asarray(el.mv_to_global(x))
        assert np.linalg.norm(D @ xg - b) / np.linalg.norm(b) < 1e-8

    def test_iters_reporting(self, grid24):
        n = 30
        rows, cols, vals = _laplacian_1d(n)
        A = el.dist_sparse_from_coo(rows, cols, vals, n, n, grid=grid24,
                                    dtype=np.float64)
        b = el.mv_from_global(np.ones((n, 1)), grid=grid24)
        _, info = el.cg(A, b, tol=1e-14, maxiter=5)
        assert info["iters"] == 5 and not info["converged"]
        _, info = el.cgls(A, b, tol=1e-14, maxiter=4)
        assert info["iters"] == 4 and not info["converged"]


class TestSparseDirect:
    """Sequential sparse-direct solve (El::SparseMatrix LinearSolve path)."""

    def test_laplacian_solve(self, grid24):
        import numpy as np
        import scipy.sparse as sp
        from elemental_tpu.sparse.core import dist_sparse_from_coo
        from elemental_tpu.core.multivec import mv_from_global, mv_to_global
        n = 400
        main = 2.0 * np.ones(n)
        off = -np.ones(n - 1)
        L = sp.diags([off, main, off], [-1, 0, 1]).tocoo()
        A = dist_sparse_from_coo(L.row, L.col, L.data, n, n, grid=grid24,
                                 dtype=np.float64)
        rng = np.random.default_rng(0)
        xt = rng.normal(size=n)
        b = L.tocsr() @ xt
        x, info = el.sparse_direct_solve(A, mv_from_global(
            b.reshape(-1, 1), grid=grid24))
        assert info["converged"], info
        xg = np.asarray(mv_to_global(x)).ravel()
        assert np.linalg.norm(xg - xt) / np.linalg.norm(xt) < 1e-10

    def test_nonsymmetric(self, grid24):
        import numpy as np
        import scipy.sparse as sp
        from elemental_tpu.sparse.core import dist_sparse_from_coo
        from elemental_tpu.core.multivec import mv_from_global, mv_to_global
        rng = np.random.default_rng(1)
        n, nnz = 200, 1400
        rows = np.concatenate([rng.integers(0, n, nnz), np.arange(n)])
        cols = np.concatenate([rng.integers(0, n, nnz), np.arange(n)])
        vals = np.concatenate([rng.normal(size=nnz) * 0.1,
                               4.0 * np.ones(n)])    # diagonally dominant
        As = sp.coo_matrix((vals, (rows, cols)), shape=(n, n)).tocsr()
        A = dist_sparse_from_coo(rows, cols, vals, n, n, grid=grid24,
                                 dtype=np.float64)
        xt = rng.normal(size=n)
        b = As @ xt
        x, info = el.sparse_direct_solve(A, mv_from_global(
            b.reshape(-1, 1), grid=grid24))
        assert info["converged"], info
        xg = np.asarray(mv_to_global(x)).ravel()
        assert np.linalg.norm(xg - xt) / np.linalg.norm(xt) < 1e-10
