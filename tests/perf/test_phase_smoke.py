"""Perf-observability smoke: tiny LU/Cholesky through the phase-timing hook.

Slow-tier guard for the ``perf/phase_timer.py`` + ``lu/cholesky(...,
timer=...)`` paths (ISSUE 1/2 CI satellites): asserts the
``phase_timings/v1`` JSON schema so the attribution tooling future perf
PRs rely on cannot silently rot.
"""
import json

import numpy as np
import pytest

import elemental_tpu as el

pytestmark = pytest.mark.slow


def _check_schema(doc, n, nb, nsteps):
    from perf.phase_timer import SCHEMA, PHASES
    assert doc["schema"] == SCHEMA
    assert doc["driver"] == "lu"
    assert doc["n"] == n and doc["nb"] == nb
    steps = doc["steps"]
    assert [s["step"] for s in steps] == list(range(nsteps))
    for srec in steps:
        phases = set(srec) - {"step"}
        assert phases <= set(PHASES)
        assert "panel" in phases and "swap" in phases
        for p in phases:
            assert isinstance(srec[p], float) and srec[p] >= 0.0
    totals = doc["totals"]
    assert set(totals) <= set(PHASES) and "panel" in totals
    assert doc["total_seconds"] >= sum(totals.values()) - 1e-9
    json.dumps(doc)          # round-trippable


@pytest.mark.parametrize("lookahead", [True, False])
def test_lu_phase_timer_schema_distributed(grid24, lookahead):
    from perf.phase_timer import PhaseTimer
    n, nb = 48, 16
    rng = np.random.default_rng(0)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    t = PhaseTimer()
    LU, perm = el.lu(A, nb=nb, lookahead=lookahead, crossover=0, timer=t)
    doc = json.loads(t.json(driver="lu", n=n, nb=nb, lookahead=lookahead))
    _check_schema(doc, n, nb, nsteps=n // nb)
    # the timed run is still a correct factorization
    LUh = np.asarray(el.to_global(LU))
    L = np.tril(LUh, -1) + np.eye(n)
    U = np.triu(LUh)
    p = np.asarray(perm)
    assert np.linalg.norm(F[p, :] - L @ U) < 1e-11 * np.linalg.norm(F)


def test_lu_phase_timer_schema_local():
    """Same schema off the sequential (1x1-grid) driver."""
    import jax
    from perf.phase_timer import PhaseTimer
    g1 = el.Grid([jax.devices()[0]])
    n, nb = 64, 16
    rng = np.random.default_rng(1)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    A = el.from_global(F, el.MC, el.MR, grid=g1)
    t = PhaseTimer()
    LU, perm = el.lu(A, nb=nb, timer=t)
    doc = json.loads(t.json(driver="lu", n=n, nb=nb))
    _check_schema(doc, n, nb, nsteps=n // nb)


def test_lu_phase_timer_tail_crossover(grid24):
    """The LU crossover step attributes its gathered local finish to
    'tail' (the ISSUE-3 rider mirroring the cholesky PR-2 tail)."""
    from perf.phase_timer import PhaseTimer
    n, nb = 48, 16
    rng = np.random.default_rng(7)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    t = PhaseTimer()
    LU, perm = el.lu(A, nb=nb, crossover=nb, timer=t)
    doc = json.loads(t.json(driver="lu", n=n, nb=nb))
    # steps 0 and 1 run distributed; the 16-wide tail crosses over at step 1
    steps = doc["steps"]
    assert [s["step"] for s in steps] == [0, 1]
    assert "tail" in steps[-1] and "tail" in doc["totals"]
    LUh = np.asarray(el.to_global(LU))
    L = np.tril(LUh, -1) + np.eye(n)
    U = np.triu(LUh)
    p = np.asarray(perm)
    assert np.linalg.norm(F[p, :] - L @ U) < 1e-11 * np.linalg.norm(F)


def _spd(n, seed):
    rng = np.random.default_rng(seed)
    G = rng.normal(size=(n, n))
    return G @ G.T / n + n * np.eye(n)


def _check_chol_schema(doc, n, nb, nsteps, tail=False):
    from perf.phase_timer import SCHEMA, PHASES
    assert doc["schema"] == SCHEMA
    assert doc["driver"] == "cholesky"
    assert doc["n"] == n and doc["nb"] == nb
    steps = doc["steps"]
    assert [s["step"] for s in steps] == list(range(nsteps))
    for srec in steps:
        phases = set(srec) - {"step"}
        assert phases <= set(PHASES)
        assert "diag" in phases
        for p in phases:
            assert isinstance(srec[p], float) and srec[p] >= 0.0
    totals = doc["totals"]
    assert set(totals) <= set(PHASES) and "diag" in totals
    assert ("tail" in totals) == tail
    assert doc["total_seconds"] >= sum(totals.values()) - 1e-9
    json.dumps(doc)          # round-trippable


@pytest.mark.parametrize("lookahead", [True, False])
def test_cholesky_phase_timer_schema_distributed(grid24, lookahead):
    from perf.phase_timer import PhaseTimer
    n, nb = 48, 16
    F = _spd(n, 2)
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    t = PhaseTimer()
    L = el.cholesky(A, nb=nb, lookahead=lookahead, crossover=0, timer=t)
    doc = json.loads(t.json(driver="cholesky", n=n, nb=nb,
                            lookahead=lookahead))
    _check_chol_schema(doc, n, nb, nsteps=n // nb)
    # non-final steps must also carry the panel/spread/update phases
    for srec in doc["steps"][:-1]:
        assert {"panel", "spread", "update"} <= set(srec)
    # the timed run is still a correct factorization
    Lh = np.asarray(el.to_global(L))
    assert np.linalg.norm(F - Lh @ Lh.T) < 1e-11 * np.linalg.norm(F)


def test_cholesky_phase_timer_tail_crossover(grid24):
    """The crossover step attributes its gathered local finish to 'tail'."""
    from perf.phase_timer import PhaseTimer
    n, nb = 48, 16
    F = _spd(n, 3)
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    t = PhaseTimer()
    L = el.cholesky(A, nb=nb, crossover=nb, timer=t)
    doc = json.loads(t.json(driver="cholesky", n=n, nb=nb))
    # steps 0 and 1 run distributed; the 16-wide tail crosses over at step 1
    _check_chol_schema(doc, n, nb, nsteps=2, tail=True)
    assert "tail" in doc["steps"][-1]
    Lh = np.asarray(el.to_global(L))
    assert np.linalg.norm(F - Lh @ Lh.T) < 1e-11 * np.linalg.norm(F)


def test_cholesky_phase_timer_schema_local():
    """Same schema off the sequential (1x1-grid) driver."""
    import jax
    from perf.phase_timer import PhaseTimer
    g1 = el.Grid([jax.devices()[0]])
    n, nb = 64, 16
    F = _spd(n, 4)
    A = el.from_global(F, el.MC, el.MR, grid=g1)
    t = PhaseTimer()
    L = el.cholesky(A, nb=nb, timer=t)
    doc = json.loads(t.json(driver="cholesky", n=n, nb=nb))
    _check_chol_schema(doc, n, nb, nsteps=n // nb)
