"""perf.trace CLI robustness (ISSUE 7 satellite): an unknown driver name
prints the registered driver list and exits 1 -- no traceback, no jax
bootstrap, no input building."""
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_unknown_driver_lists_registry_and_exits_1():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "perf.trace", "run", "nosuchdriver"],
        capture_output=True, text=True, cwd=_REPO, env=env, timeout=120)
    assert p.returncode == 1
    assert "unknown driver 'nosuchdriver'" in p.stderr
    assert "registered drivers" in p.stderr
    for d in ("cholesky", "lu", "qr", "gemm", "trsm", "herk"):
        assert d in p.stderr
    assert "Traceback" not in p.stderr
    assert "Traceback" not in p.stdout


def test_known_driver_not_rejected_by_the_guard():
    """The guard must not eat valid names: a real driver passes the
    registry check (run with a bogus FLAG so the command still exits
    fast, at argument parsing, before any device work)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "perf.trace", "run", "cholesky",
         "--bogus-flag"],
        capture_output=True, text=True, cwd=_REPO, env=env, timeout=120)
    assert p.returncode != 0
    assert "unknown flag" in p.stderr
    assert "registered drivers" not in p.stderr
