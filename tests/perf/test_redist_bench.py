"""perf.redist_bench smoke (ISSUE 12 satellite): the chain-vs-direct
microbench emits well-formed ``redist_bench/v1`` rows with the bit-match
cross-check green, and the ``p2p_gbps`` helper feeding bench.py's obs
block returns both paths."""
import json


def test_run_pair_rows_and_match(grid24):
    from perf.redist_bench import run_pair, _dist_pair
    rows = run_pair(grid24, 24, _dist_pair("MC,MR"), _dist_pair("MR,STAR"),
                    ("chain", "direct"), reps=1, check=True)
    assert [r["path"] for r in rows] == ["chain", "direct"]
    for row in rows:
        assert row["schema"] == "redist_bench/v1"
        assert row["pair"] == "[MC,MR]->[MR,STAR]"
        assert row["match"] is True
        assert row["seconds"] > 0 and row["model_bytes"] >= 0
        json.dumps(row)                      # one JSON line per row
    chain, direct = rows
    assert chain["rounds"] >= direct["rounds"]
    assert direct["plan"] in ("a2a", "ppermute", "local")


def test_p2p_gbps_reports_both_paths(grid24):
    from perf.redist_bench import p2p_gbps
    doc = p2p_gbps(grid24, n=24, reps=1)
    assert set(doc) >= {"pair", "n", "grid", "chain", "direct"}
    assert doc["chain"] >= 0.0 and doc["direct"] >= 0.0


def test_cli_smoke_exits_zero(capsys):
    """``--smoke`` is the tools/check.sh gate: tiny 1x1 matrix, every
    row parses, exit 0."""
    from perf import redist_bench
    assert redist_bench.main(["--smoke", "--reps", "1"]) == 0
    out = capsys.readouterr().out
    rows = [json.loads(ln) for ln in out.splitlines() if ln.strip()]
    assert rows and all(r["schema"] == "redist_bench/v1" for r in rows)
    assert all(r["match"] for r in rows)
