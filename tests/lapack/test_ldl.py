"""LDL^T / LDL^H (Bunch-Kaufman) oracles.

Reference test style: ``tests/lapack_like/LDL.cpp`` -- reconstruction
residual ||P A P^T - L D L^H|| / ||A|| on indefinite matrices (incl.
pivot-stress cases), solve residuals, and Sylvester-law inertia counts.
"""
import numpy as np

import elemental_tpu as el
from elemental_tpu.lapack.ldl import (ldl, symmetric_solve,
                                      hermitian_solve, inertia)


def _g(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def _reconstruct(F, Lp, d, e, perm, conj):
    n = F.shape[0]
    Lg = np.tril(_t(Lp), -1) + np.eye(n)
    dn, en, p = np.asarray(d), np.asarray(e), np.asarray(perm)
    D = np.diag(dn.astype(complex) if np.iscomplexobj(F) else dn)
    for j in range(n - 1):
        if en[j] != 0:
            D[j + 1, j] = en[j]
            D[j, j + 1] = np.conj(en[j]) if conj else en[j]
    PAP = F[np.ix_(p, p)]
    rec = Lg @ D @ (Lg.conj().T if conj else Lg.T)
    return np.linalg.norm(rec - PAP) / np.linalg.norm(F)


def _sym(n, seed=0, cplx=False):
    rng = np.random.default_rng(seed)
    if cplx:
        G = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
        return (G + G.conj().T) / 2
    G = rng.normal(size=(n, n))
    return (G + G.T) / 2


def test_ldl_symmetric(grid24):
    F = _sym(24, 0)
    Lp, d, e, perm = ldl(_g(F, grid24), conjugate=False, nb=8)
    assert _reconstruct(F, Lp, d, e, perm, False) < 1e-13


def test_ldl_full_panel(grid24):
    """nb >= n: LAPACK-faithful pivot sequence (no boundary rule)."""
    F = _sym(24, 1)
    Lp, d, e, perm = ldl(_g(F, grid24), conjugate=False, nb=32)
    assert _reconstruct(F, Lp, d, e, perm, False) < 1e-13


def test_ldl_hermitian_complex(grid24):
    F = _sym(16, 2, cplx=True)
    Lp, d, e, perm = ldl(_g(F, grid24), conjugate=True, nb=8)
    assert _reconstruct(F, Lp, d, e, perm, True) < 1e-13
    assert np.max(np.abs(np.imag(np.asarray(d)))) == 0  # real D diagonal


def test_ldl_complex_symmetric(grid24):
    rng = np.random.default_rng(3)
    G = rng.normal(size=(16, 16)) + 1j * rng.normal(size=(16, 16))
    F = (G + G.T) / 2                       # complex SYMMETRIC (no conj)
    Lp, d, e, perm = ldl(_g(F, grid24), conjugate=False, nb=8)
    assert _reconstruct(F, Lp, d, e, perm, False) < 1e-13


def test_ldl_pivot_stress(grid24):
    """Tiny diagonal forces pervasive 2x2 pivots."""
    F = _sym(24, 4)
    np.fill_diagonal(F, 1e-12)
    Lp, d, e, perm = ldl(_g(F, grid24), conjugate=False, nb=8)
    assert _reconstruct(F, Lp, d, e, perm, False) < 1e-12
    assert np.any(np.asarray(e) != 0)       # 2x2 blocks actually used


def test_ldl_zero_diagonal_saddle(grid24):
    """[[0, I], [I, 0]]-like saddle: unpivoted LDL would divide by zero."""
    n = 8
    F = np.zeros((2 * n, 2 * n))
    F[:n, n:] = np.eye(n)
    F[n:, :n] = np.eye(n)
    Lp, d, e, perm = ldl(_g(F, grid24), conjugate=False, nb=16)
    assert _reconstruct(F, Lp, d, e, perm, False) < 1e-13


def test_symmetric_solve(grid24):
    rng = np.random.default_rng(5)
    F = _sym(24, 5)
    B = rng.normal(size=(24, 3))
    X = symmetric_solve(_g(F, grid24), _g(B, grid24), nb=8)
    assert np.linalg.norm(F @ _t(X) - B) / np.linalg.norm(B) < 1e-12


def test_hermitian_solve(grid24):
    rng = np.random.default_rng(6)
    F = _sym(16, 6, cplx=True)
    B = rng.normal(size=(16, 3)) + 1j * rng.normal(size=(16, 3))
    X = hermitian_solve(_g(F, grid24), _g(B, grid24), nb=8)
    assert np.linalg.norm(F @ _t(X) - B) / np.linalg.norm(B) < 1e-12


def test_inertia(grid24):
    F = _sym(24, 7)
    _, d, e, _ = ldl(_g(F, grid24), conjugate=False, nb=8)
    npos, nneg, nzero = inertia(d, e)
    wn = np.linalg.eigvalsh(F)
    assert (npos, nneg) == (int((wn > 0).sum()), int((wn < 0).sum()))
    assert nzero == 0


def test_ldl_uplo_upper(grid24):
    """uplo='U' reads only the upper triangle (poison the lower)."""
    F = _sym(16, 8)
    P = F.copy()
    P[np.tril_indices(16, -1)] = np.nan
    Lp, d, e, perm = ldl(_g(P, grid24), uplo="U", conjugate=False, nb=8)
    assert _reconstruct(F, Lp, d, e, perm, False) < 1e-13
