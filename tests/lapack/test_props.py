"""Props oracles: determinant/condition/inertia/norm estimates."""
import numpy as np
import pytest

import elemental_tpu as el


def _dm(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def test_determinant(grid24):
    rng = np.random.default_rng(0)
    A = rng.normal(size=(12, 12))
    det = complex(np.asarray(el.determinant(_dm(A, grid24))))
    ref = np.linalg.det(A)
    assert abs(det - ref) / abs(ref) < 1e-12


def test_safe_determinant(grid24):
    rng = np.random.default_rng(1)
    A = rng.normal(size=(10, 10)) * 1e3       # would overflow naive prod^n
    rho, kappa, n = el.safe_determinant(_dm(A, grid24))
    sign_ref, logabs_ref = np.linalg.slogdet(A)
    assert abs(complex(np.asarray(rho)) - sign_ref) < 1e-10
    assert abs(float(np.asarray(kappa)) * n - logabs_ref) < 1e-8


def test_hpd_determinant(grid24):
    rng = np.random.default_rng(2)
    G = rng.normal(size=(12, 12))
    A = G @ G.T / 12 + 2 * np.eye(12)
    det = float(np.asarray(el.hpd_determinant(_dm(A, grid24))))
    assert abs(det - np.linalg.det(A)) / np.linalg.det(A) < 1e-12


@pytest.mark.slow
def test_condition(grid24):
    rng = np.random.default_rng(3)
    A = rng.normal(size=(12, 12))
    c2 = float(np.asarray(el.condition(_dm(A, grid24), "two")))
    assert abs(c2 - np.linalg.cond(A)) / np.linalg.cond(A) < 1e-10
    c1 = float(np.asarray(el.condition(_dm(A, grid24), "one")))
    assert abs(c1 - np.linalg.cond(A, 1)) / np.linalg.cond(A, 1) < 1e-10


def test_two_norm_estimate(grid24):
    rng = np.random.default_rng(4)
    A = rng.normal(size=(16, 10))
    est = float(np.asarray(el.two_norm_estimate(_dm(A, grid24), iters=40)))
    ref = np.linalg.norm(A, 2)
    assert abs(est - ref) / ref < 1e-6


def test_matrix_inertia(grid24):
    rng = np.random.default_rng(5)
    G = rng.normal(size=(14, 14))
    A = (G + G.T) / 2
    npos, nneg, nzero = el.lapack.matrix_inertia(_dm(A, grid24), nb=8)
    w = np.linalg.eigvalsh(A)
    assert (npos, nneg) == (int((w > 0).sum()), int((w < 0).sum()))


@pytest.mark.slow
def test_schatten_norms(grid24):
    rng = np.random.default_rng(6)
    A = rng.normal(size=(12, 9))
    s = np.linalg.svd(A, compute_uv=False)
    assert abs(float(np.asarray(el.nuclear_norm(_dm(A, grid24)))) - s.sum()) < 1e-10
    assert abs(float(np.asarray(el.two_norm(_dm(A, grid24)))) - s[0]) < 1e-11
    p3 = float(np.asarray(el.schatten_norm(_dm(A, grid24), 3.0)))
    assert abs(p3 - (s ** 3).sum() ** (1 / 3)) < 1e-10
