"""Cuppen D&C tridiagonal eigensolver oracles.

Reference test style (SURVEY.md §5): known-spectrum matrices (Wilkinson,
1-2-1 Toeplitz), residual ||T Z - Z diag(w)||/||T||, orthogonality
||I - Z^T Z||, agreement with the sequential oracle -- the analogs of the
checks around upstream ``external/pmrrr`` in
``tests/lapack_like/HermitianEig.cpp``.  Covers both the replicated batched
phase (n <= repl_max) and the distributed [MC,MR] phase (n > repl_max), and
the herm_eig wiring end-to-end.
"""
import numpy as np

import elemental_tpu as el
from elemental_tpu.lapack.tridiag_eig import tridiag_eig


def _trid(d, e):
    return np.diag(d) + np.diag(e, 1) + np.diag(e, -1)


def _check(d, e, w, Z, tol=1e-10):
    n = len(d)
    T = _trid(d, e)
    w = np.asarray(w)
    wref = np.linalg.eigvalsh(T)
    assert np.abs(w - wref).max() / max(np.abs(wref).max(), 1) < tol
    if Z is not None:
        Zg = np.asarray(el.to_global(Z)) if not isinstance(Z, np.ndarray) \
            else Z
        assert np.linalg.norm(T @ Zg - Zg * w[None, :]) \
            / max(np.linalg.norm(T), 1) < tol
        assert np.linalg.norm(Zg.T @ Zg - np.eye(n)) < tol * n


def test_replicated_random():
    rng = np.random.default_rng(0)
    n = 300
    d, e = rng.standard_normal(n), rng.standard_normal(n - 1)
    w, Z = tridiag_eig(d, e, grid=None, vectors=True)
    _check(d, e, w, np.asarray(Z))


def test_values_only_matches_vectors_path():
    rng = np.random.default_rng(1)
    n = 260
    d, e = rng.standard_normal(n), rng.standard_normal(n - 1)
    w = tridiag_eig(d, e, grid=None, vectors=False)
    wref = np.linalg.eigvalsh(_trid(d, e))
    assert np.abs(np.asarray(w) - wref).max() < 1e-10


def test_wilkinson():
    """W21+ has pathologically close eigenvalue pairs -- the classic
    deflation stress (upstream gallery ``Wilkinson``)."""
    m = 10
    n = 2 * m + 1
    d = np.abs(np.arange(n) - m).astype(np.float64)
    e = np.ones(n - 1)
    w, Z = tridiag_eig(d, e, grid=None, vectors=True, leaf_max=8)
    _check(d, e, w, np.asarray(Z))


def test_toeplitz_121_known_spectrum():
    """tridiag(1,2,1) has eigenvalues 2 - 2 cos(k pi/(n+1)) exactly."""
    n = 128
    d, e = 2.0 * np.ones(n), np.ones(n - 1)
    w = tridiag_eig(d, e, grid=None, vectors=False, leaf_max=16)
    k = np.arange(1, n + 1)
    wref = 2.0 - 2.0 * np.cos(k * np.pi / (n + 1))
    assert np.abs(np.sort(np.asarray(w)) - np.sort(wref)).max() < 1e-10


def test_tiny_couplings_and_zero_e():
    """Zero off-diagonals (fully deflated case) must not 0/0."""
    n = 96
    d = np.linspace(-3, 5, n)
    e = np.zeros(n - 1)
    w = tridiag_eig(d, e, grid=None, vectors=False, leaf_max=16)
    assert np.abs(np.sort(np.asarray(w)) - np.sort(d)).max() < 1e-10


def test_distributed_phase(any_grid):
    """n > repl_max: merges run as [MC,MR] SUMMA gemms on every grid."""
    rng = np.random.default_rng(2)
    n = 350
    d, e = rng.standard_normal(n), rng.standard_normal(n - 1)
    w, Zd = tridiag_eig(d, e, grid=any_grid, vectors=True,
                        leaf_max=48, repl_max=128)
    _check(d, e, w, Zd, tol=1e-9)


def test_herm_eig_dc_path(grid24):
    """herm_eig end-to-end through the D&C tridiagonal stage (dc_min=0
    forces it), including the distributed >repl_max phase."""
    rng = np.random.default_rng(3)
    n = 200
    G = rng.standard_normal((n, n))
    F = (G + G.T) / 2
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    w, Z = el.herm_eig(A, dc_min=0, repl_max=96)
    wref = np.linalg.eigvalsh(F)
    assert np.abs(np.asarray(w) - wref).max() < 1e-9
    Zg = np.asarray(el.to_global(Z))
    assert np.linalg.norm(F @ Zg - Zg * np.asarray(w)[None, :]) \
        / np.linalg.norm(F) < 1e-10
    assert np.linalg.norm(Zg.T @ Zg - np.eye(n)) < 1e-10 * n


def test_herm_eig_dc_subset(grid24):
    rng = np.random.default_rng(4)
    n = 150
    G = rng.standard_normal((n, n))
    F = (G + G.T) / 2
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    w, Z = el.herm_eig(A, subset=("index", 10, 29), dc_min=0, repl_max=64)
    wref = np.linalg.eigvalsh(F)[10:30]
    assert np.abs(np.asarray(w) - wref).max() < 1e-9
    Zg = np.asarray(el.to_global(Z))
    assert Zg.shape == (n, 20)
    assert np.linalg.norm(F @ Zg - Zg * np.asarray(w)[None, :]) \
        / np.linalg.norm(F) < 1e-10


def test_herm_eig_dc_values_only(grid24):
    rng = np.random.default_rng(5)
    n = 180
    G = rng.standard_normal((n, n))
    F = (G + G.T) / 2
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    w = el.herm_eig(A, vectors=False, dc_min=0, repl_max=64)
    wref = np.linalg.eigvalsh(F)
    assert np.abs(np.asarray(w) - wref).max() < 1e-9
