"""Factorization-variant cross-checks (VERDICT r4 item 7).

Reference oracle style (SURVEY.md §5): agreement between independent
algorithm variants (``tests/blas_like/Gemm.cpp`` runs every SUMMA variant
against each other) and residual identities per factorization.
"""
import numpy as np
import pytest

import elemental_tpu as el


def _g(F, grid):
    return el.from_global(np.asarray(F, np.float64), el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


# ---------------------------------------------------------------------
# SUMMA-Dot
# ---------------------------------------------------------------------

def test_gemm_dot_vs_variants(two_grids):
    """Small C, long inner dim: the SUMMA-Dot case, cross-checked against
    every other schedule."""
    rng = np.random.default_rng(0)
    m, k, n = 6, 300, 5
    Fa = rng.normal(size=(m, k))
    Fb = rng.normal(size=(k, n))
    ref = Fa @ Fb
    A, B = _g(Fa, two_grids), _g(Fb, two_grids)
    for alg in ("dot", "A", "B", "C", "auto", "gspmd"):
        C = el.gemm(A, B, alg=alg)
        assert np.allclose(_t(C), ref, atol=1e-10), alg


def test_gemm_dot_accumulates(two_grids):
    rng = np.random.default_rng(1)
    Fa = rng.normal(size=(4, 120))
    Fb = rng.normal(size=(120, 3))
    Fc = rng.normal(size=(4, 3))
    C = el.gemm(_g(Fa, two_grids), _g(Fb, two_grids), alpha=2.0, beta=-1.0,
                C=_g(Fc, two_grids), alg="dot")
    assert np.allclose(_t(C), 2 * Fa @ Fb - Fc, atol=1e-10)


# ---------------------------------------------------------------------
# QuasiTrsm
# ---------------------------------------------------------------------

def _quasi_upper(rng, n, nblocks2x2):
    """Random well-conditioned upper quasi-triangular (real Schur-like)."""
    T = np.triu(rng.normal(size=(n, n))) + 3 * np.eye(n)
    pos = rng.choice(n - 1, nblocks2x2, replace=False)
    pos = [p for p in sorted(pos) if p == 0 or (p - 1 not in pos)]
    for p in pos:
        # complex-pair 2x2 block [a b; -b a]
        a, b = T[p, p], 1.0 + abs(rng.normal())
        T[p + 1, p + 1] = a
        T[p, p + 1] = b
        T[p + 1, p] = -b
    return T


@pytest.mark.parametrize("side,orient", [("L", "N"), ("L", "T"),
                                         ("R", "N"), ("R", "T")])
def test_quasi_trsm(two_grids, side, orient):
    rng = np.random.default_rng(2)
    n, k = 37, 5
    T = _quasi_upper(rng, n, 6)
    B = rng.normal(size=(n, k) if side == "L" else (k, n))
    X = el.quasi_trsm(side, orient, _g(T, two_grids), _g(B, two_grids),
                      nb=8)
    opT = T.T if orient == "T" else T
    ref = np.linalg.solve(opT, B) if side == "L" \
        else (B @ np.linalg.inv(opT))
    assert np.allclose(_t(X), ref, atol=1e-9)


def _quasi_upper_complex(rng, n, nblocks2x2):
    """Random well-conditioned COMPLEX upper quasi-triangular matrix."""
    T = np.triu(rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))) \
        + 4 * np.eye(n)
    pos = rng.choice(n - 1, nblocks2x2, replace=False)
    pos = [p for p in sorted(pos) if p == 0 or (p - 1 not in pos)]
    for p in pos:
        a, b = T[p, p], (1.0 + abs(rng.normal())) * (1 + 0.5j)
        T[p + 1, p + 1] = a
        T[p, p + 1] = b
        T[p + 1, p] = -np.conj(b)
    return T


@pytest.mark.parametrize("side,orient", [("L", "C"), ("R", "C"),
                                         ("L", "N"), ("R", "T")])
def test_quasi_trsm_complex_conj(two_grids, side, orient):
    """quasi_trsm with complex operands, exercising the conj branches of
    the panel solve and off-panel update (orient 'C': op(T) = T^H), vs
    numpy.linalg.solve on the conjugate-transposed system."""
    rng = np.random.default_rng(9)
    n, k = 37, 5
    T = _quasi_upper_complex(rng, n, 6)
    B = rng.normal(size=(n, k) if side == "L" else (k, n)) \
        + 1j * rng.normal(size=(n, k) if side == "L" else (k, n))
    def _gc(F):          # complex-preserving (module _g casts to float64)
        return el.from_global(np.asarray(F, np.complex128), el.MC, el.MR,
                              grid=two_grids)

    X = el.quasi_trsm(side, orient, _gc(T), _gc(B), nb=8)
    opT = {"N": T, "T": T.T, "C": np.conj(T).T}[orient]
    ref = np.linalg.solve(opT, B) if side == "L" \
        else (B @ np.linalg.inv(opT))
    assert np.allclose(_t(X), ref, atol=1e-9)


def test_quasi_trsm_matches_trsm_on_triangular(two_grids):
    """With zero subdiagonal, quasi_trsm must agree with plain trsm."""
    rng = np.random.default_rng(3)
    n, k = 24, 4
    T = np.triu(rng.normal(size=(n, n))) + 3 * np.eye(n)
    B = rng.normal(size=(n, k))
    X1 = el.quasi_trsm("L", "N", _g(T, two_grids), _g(B, two_grids), nb=8)
    X2 = el.trsm("L", "U", "N", _g(T, two_grids), _g(B, two_grids), nb=8)
    assert np.allclose(_t(X1), _t(X2), atol=1e-10)


# ---------------------------------------------------------------------
# pivoted Cholesky
# ---------------------------------------------------------------------

def test_cholesky_pivoted_hpd(two_grids):
    rng = np.random.default_rng(4)
    n = 30
    G = rng.normal(size=(n, n))
    F = G @ G.T + n * np.eye(n)
    L, perm, rank = el.cholesky_pivoted(_g(F, two_grids))
    Lg = _t(L)
    p = np.asarray(perm)
    assert int(rank) == n
    assert np.allclose(Lg @ Lg.T, F[np.ix_(p, p)], atol=1e-9)
    assert np.allclose(Lg, np.tril(Lg))
    # pivoted diag is non-increasing (the full-pivot invariant)
    d = np.diag(Lg)
    assert np.all(d[:-1] >= d[1:] - 1e-12)
    # cross-check against the unpivoted variant through the permutation
    L0 = _t(el.cholesky(_g(F[np.ix_(p, p)], two_grids)))
    assert np.allclose(Lg, L0, atol=1e-8)


def test_cholesky_pivoted_rank_deficient(two_grids):
    rng = np.random.default_rng(5)
    n, rk = 24, 9
    G = rng.normal(size=(n, rk))
    F = G @ G.T                     # PSD, rank rk
    L, perm, rank = el.cholesky_pivoted(_g(F, two_grids), tol=1e-10)
    Lg = _t(L)
    p = np.asarray(perm)
    assert int(rank) == rk
    assert np.allclose(Lg @ Lg.T, F[np.ix_(p, p)], atol=1e-8)


# ---------------------------------------------------------------------
# LU with complete pivoting
# ---------------------------------------------------------------------

def test_lu_full_pivot(two_grids):
    rng = np.random.default_rng(6)
    m = 29
    F = rng.normal(size=(m, m))
    LU, rp, cp = el.lu_full_pivot(_g(F, two_grids))
    lug = _t(LU)
    L = np.tril(lug, -1) + np.eye(m)
    U = np.triu(lug)
    rp, cp = np.asarray(rp), np.asarray(cp)
    assert np.allclose(L @ U, F[np.ix_(rp, cp)], atol=1e-9)
    # complete pivoting controls growth: |L| <= 1 everywhere
    assert np.abs(L).max() <= 1 + 1e-12
    # cross-check vs partial pivoting: both must reconstruct F through
    # their permutations
    LU2, perm2 = el.lu(_g(F[:, cp], two_grids))
    L2 = np.tril(_t(LU2), -1) + np.eye(m)
    U2 = np.triu(_t(LU2))
    assert np.allclose(L2 @ U2, F[np.ix_(np.asarray(perm2), cp)],
                       atol=1e-9)


def test_lu_full_pivot_growth_matrix(two_grids):
    """gepp_growth defeats partial pivoting's growth bound; complete
    pivoting keeps |U| bounded (the classic Wilkinson example)."""
    n = 16
    F = np.eye(n) - np.tril(np.ones((n, n)), -1)
    F[:, -1] = 1.0
    LU, rp, cp = el.lu_full_pivot(_g(F, two_grids))
    U = np.triu(_t(LU))
    assert np.abs(U).max() < 8          # partial pivoting gives 2^(n-1)
    L = np.tril(_t(LU), -1) + np.eye(n)
    assert np.allclose(L @ U, F[np.ix_(np.asarray(rp), np.asarray(cp))],
                       atol=1e-10)


# ---------------------------------------------------------------------
# RQ
# ---------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(12, 20), (15, 15), (20, 12)])
def test_rq(two_grids, shape):
    rng = np.random.default_rng(7)
    m, n = shape
    F = rng.normal(size=(m, n))
    R, Q = el.rq(_g(F, two_grids))
    Rg, Qg = _t(R), _t(Q)
    k = min(m, n)
    assert Rg.shape == (m, k) and Qg.shape == (k, n)
    assert np.allclose(Qg @ Qg.T, np.eye(k), atol=1e-9)
    assert np.allclose(Rg @ Qg, F, atol=1e-9)
    # R is upper-triangular against the bottom-right corner
    if m <= n:
        assert np.allclose(Rg, np.triu(Rg), atol=1e-10)
    else:
        assert np.allclose(Rg[m - k:], np.triu(Rg[m - k:]), atol=1e-10)


def test_quasi_trsm_bump_at_panel_boundary(two_grids):
    """A 2x2 block straddling a panel split must extend the panel by a
    whole distribution grain (view offsets are stride-multiples)."""
    rng = np.random.default_rng(8)
    n, k = 16, 3
    T = np.triu(rng.normal(size=(n, n))) + 3 * np.eye(n)
    T[8, 7] = -1.5                     # bump exactly at the nb=8 split
    T[8, 8] = T[7, 7]
    T[7, 8] = 1.5
    B = rng.normal(size=(n, k))
    X = el.quasi_trsm("L", "N", _g(T, two_grids), _g(B, two_grids), nb=8)
    assert np.allclose(_t(X), np.linalg.solve(T, B), atol=1e-9)


def test_cholesky_pivoted_scaled_identity(two_grids):
    """Rank threshold anchors on A's original diagonal scale: a tiny but
    perfectly conditioned matrix is full rank (pstrf semantics)."""
    n = 8
    F = 1e-20 * np.eye(n)
    L, perm, rank = el.cholesky_pivoted(_g(F, two_grids), tol=1e-6)
    assert int(rank) == n
    Lg = _t(L)
    p = np.asarray(perm)
    assert np.allclose(Lg @ Lg.T, F[np.ix_(p, p)], rtol=1e-10)


def test_cholesky_mod_up_and_downdate(two_grids):
    """Rank-k update then the inverse downdate returns the original
    factor (El::CholeskyMod oracle)."""
    rng = np.random.default_rng(9)
    n, k = 22, 3
    G0 = rng.normal(size=(n, n))
    F = G0 @ G0.T + n * np.eye(n)
    V = rng.normal(size=(n, k))
    L = el.cholesky(_g(F, two_grids))
    L2 = el.cholesky_mod(L, _g(V, two_grids), 1.5)
    L2g = _t(L2)
    assert np.allclose(L2g @ L2g.T, F + 1.5 * V @ V.T, atol=1e-9)
    L3 = el.cholesky_mod(L2, _g(V, two_grids), -1.5)
    L3g = _t(L3)
    assert np.allclose(L3g @ L3g.T, F, atol=1e-8)
    assert np.allclose(L3g, _t(L), atol=1e-8)


def test_cholesky_mod_indefinite_downdate_raises(two_grids):
    L = el.cholesky(_g(np.eye(6), two_grids))
    V = np.zeros((6, 1)); V[0] = 2.0
    with pytest.raises(ValueError):
        el.cholesky_mod(L, _g(V, two_grids), -1.0)
