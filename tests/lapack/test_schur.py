"""Schur / TriangEig / Eig / Pseudospectra oracles.

Reference test style: Schur residual ||A - Q T Q^H||/||A||, unitarity,
triangularity, eigenvalue-multiset agreement; TriangEig residuals; a
pseudospectra map checked against directly computed sigma_min values.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.lapack.schur import schur, triang_eig, eig, pseudospectra


def _dm(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def _check_schur(F, T, Q, tol=1e-12):
    n = F.shape[0]
    Tg, Qg = _t(T), _t(Q)
    assert np.linalg.norm(np.tril(Tg, -1)) == 0
    assert np.linalg.norm(Qg.conj().T @ Qg - np.eye(n)) < tol * n
    assert np.linalg.norm(F - Qg @ Tg @ Qg.conj().T) / np.linalg.norm(F) < tol
    ev = np.linalg.eigvals(F)
    got = np.diag(Tg)
    d = np.abs(ev[:, None] - got[None, :])
    assert d.min(axis=1).max() < 1e-10 * max(np.abs(ev).max(), 1)


@pytest.mark.slow
def test_schur_sdc_real(grid24):
    """base=12 forces >= 2 SDC levels on a real nonsymmetric matrix."""
    rng = np.random.default_rng(0)
    F = rng.normal(size=(40, 40))
    T, Q = schur(_dm(F, grid24), base=12)
    _check_schur(F, T, Q)


@pytest.mark.slow
def test_schur_sdc_complex(grid24):
    rng = np.random.default_rng(1)
    F = rng.normal(size=(24, 24)) + 1j * rng.normal(size=(24, 24))
    T, Q = schur(_dm(F, grid24), base=8)
    _check_schur(F, T, Q)


def test_schur_replicated_base(grid24):
    rng = np.random.default_rng(2)
    F = rng.normal(size=(16, 16))
    T, Q = schur(_dm(F, grid24))         # n < default base: hseqr fallback
    _check_schur(F, T, Q)


def test_triang_eig(grid24):
    import scipy.linalg
    rng = np.random.default_rng(3)
    F = rng.normal(size=(40, 40))
    Tn, _ = scipy.linalg.schur(F, output="complex")
    w, V = triang_eig(_dm(Tn, grid24), nb=8)
    Vg, wg = _t(V), np.asarray(w)
    R = Tn @ Vg - Vg @ np.diag(wg)
    assert np.linalg.norm(R, axis=0).max() < 1e-12 * np.linalg.norm(Tn)
    assert np.allclose(np.linalg.norm(Vg, axis=0), 1.0, atol=1e-12)


def test_triang_eig_defective(grid24):
    """Repeated/defective eigenvalues (Jordan block) must yield finite,
    unit-norm vectors via the smin pivot clamp, not NaN columns."""
    T = np.triu(np.ones((8, 8))) * 0.3
    np.fill_diagonal(T, [1.0, 1.0, 2.0, 2.0, 2.0, 3.0, 4.0, 5.0])
    T[0, 1] = 1.0                                  # explicit Jordan coupling
    w, V = triang_eig(_dm(T.astype(complex), grid24), nb=8)
    Vg = _t(V)
    assert np.all(np.isfinite(Vg))
    assert np.allclose(np.linalg.norm(Vg, axis=0), 1.0, atol=1e-10)
    # distinct-eigenvalue columns are still exact eigenvectors
    R = T @ Vg - Vg @ np.diag(np.asarray(w))
    cols = np.linalg.norm(R, axis=0)
    assert cols[[5, 6, 7]].max() < 1e-10


@pytest.mark.slow
def test_eig_general(grid24):
    rng = np.random.default_rng(4)
    F = rng.normal(size=(40, 40))
    w, V = eig(_dm(F, grid24), base=12)
    Vg, wg = _t(V), np.asarray(w)
    r = F.astype(complex) @ Vg - Vg @ np.diag(wg)
    assert np.linalg.norm(r) / np.linalg.norm(F) < 1e-11


def test_pseudospectra_map(grid24):
    rng = np.random.default_rng(5)
    F = rng.normal(size=(32, 32))
    Z, sm = pseudospectra(_dm(F, grid24), (-3, 3), (-3, 3), nx=4, ny=4,
                          iters=14, base=64)
    direct = np.array([[np.linalg.svd(F - z * np.eye(32),
                                      compute_uv=False)[-1]
                        for z in row] for row in Z])
    assert np.max(np.abs(sm - direct) / np.maximum(direct, 1e-12)) < 1e-3


def test_pseudospectra_quiet_checks_gate_deflation(grid24):
    """A shift quiet on ONE check is a plateau, not convergence: with every
    check quiet (huge tol), quiet_checks=K must keep the whole batch alive
    for K consecutive checks before freezing it (pinned via the per-check
    snapshot hook), instead of deflating everything at the first check."""
    rng = np.random.default_rng(12)
    n = 16
    F = rng.normal(size=(n, n))
    A = _dm(F, grid24)

    def run(K):
        checks = []
        el.pseudospectra(A, (-2, 2), (-2, 2), nx=3, ny=2, iters=30,
                         tol=1e30, check_every=2, quiet_checks=K,
                         snapshot=lambda it, Z, S: checks.append(it))
        return checks

    # check 1 is always loud (prev = inf: a plateau needs two estimates),
    # so K quiet checks freeze the batch at check K+1
    assert run(1) == [2, 4]
    assert run(3) == [2, 4, 6, 8]


def test_pseudospectra_deflation_matches(grid24):
    """Deflated and non-deflated runs agree; snapshots fire (the
    SnapshotCtrl analog)."""
    import numpy as np
    rng = np.random.default_rng(11)
    n = 24
    F = rng.normal(size=(n, n))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    snaps = []
    Z1, s1 = el.pseudospectra(A, (-3, 3), (-3, 3), nx=5, ny=4, iters=24,
                              tol=1e-5, deflate=True,
                              snapshot=lambda it, Z, S: snaps.append(it))
    Z2, s2 = el.pseudospectra(A, (-3, 3), (-3, 3), nx=5, ny=4, iters=24,
                              tol=1e-5, deflate=False)
    assert snaps, "snapshot callback never fired"
    ok = (s1 > 0) & (s2 > 0)
    assert ok.mean() > 0.9
    rel = np.abs(s1[ok] - s2[ok]) / np.maximum(s2[ok], 1e-300)
    assert np.median(rel) < 5e-2
