"""LU with partial pivoting: residual + pivot-correctness oracles.

Mirrors ``tests/lapack_like/LU.cpp``: ||P A - L U|| / ||A||, solve
residuals, agreement of pivot choices with LAPACK on deterministic cases.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.lapack.lu import lu, lu_solve, lu_solve_after, permute_rows


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


def _unpack(LUh):
    m, n = LUh.shape
    k = min(m, n)
    L = np.tril(LUh[:, :k], -1) + np.eye(m, k)
    U = np.triu(LUh[:k, :])
    return L, U


@pytest.mark.parametrize("shape", [(24, 24), (32, 20), (20, 32), (19, 19),
                                   (19, 32), (32, 19), (18, 30)])
def test_lu_residual(grid24, shape):
    m, n = shape
    rng = np.random.default_rng(11)
    F = rng.normal(size=(m, n))
    LUd, perm = lu(_dist(grid24, F), nb=8)
    LUh = np.asarray(to_global(LUd))
    p = np.asarray(perm)
    L, U = _unpack(LUh)
    PA = F[p, :]
    assert np.linalg.norm(PA - L @ U) / np.linalg.norm(F) < 1e-13
    # partial pivoting => |L| <= 1
    assert np.max(np.abs(L)) <= 1 + 1e-14


def test_lu_vs_numpy_pivots(grid42):
    # deterministic matrix with forced pivoting (growth-factor style)
    n = 16
    F = np.eye(n) * 1e-3 + np.tril(-np.ones((n, n)), -1) + np.triu(np.ones((n, n)), 1)
    import scipy.linalg as sla
    P, L, U = sla.lu(F)
    LUd, perm = lu(_dist(grid42, F), nb=8)
    LUh = np.asarray(to_global(LUd))
    Ld, Ud = _unpack(LUh)
    p = np.asarray(perm)
    np.testing.assert_allclose(F[p, :], Ld @ Ud, atol=1e-13)
    np.testing.assert_allclose(np.abs(Ud[-1, -1]), np.abs(U[-1, -1]), rtol=1e-10)


def test_lu_solve(grid24):
    n, nrhs = 24, 5
    rng = np.random.default_rng(12)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    B = rng.normal(size=(n, nrhs))
    X = lu_solve(_dist(grid24, F), _dist(grid24, B), nb=8)
    Xh = np.asarray(to_global(X))
    assert np.linalg.norm(F @ Xh - B) / np.linalg.norm(B) < 1e-12


def test_lu_solve_complex_two_grids(two_grids):
    n, nrhs = 13, 3
    rng = np.random.default_rng(13)
    F = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n)) + 2 * n * np.eye(n)
    B = rng.normal(size=(n, nrhs)) + 1j * rng.normal(size=(n, nrhs))
    X = lu_solve(_dist(two_grids, F), _dist(two_grids, B), nb=4)
    assert np.linalg.norm(F @ np.asarray(to_global(X)) - B) < 1e-11 * np.linalg.norm(B)


def test_lu_solve_after_reuse(grid24):
    n = 20
    rng = np.random.default_rng(14)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    LUd, perm = lu(_dist(grid24, F), nb=8)
    for seed in (1, 2):
        B = np.random.default_rng(seed).normal(size=(n, 2))
        X = lu_solve_after(LUd, perm, _dist(grid24, B), nb=8)
        assert np.linalg.norm(F @ np.asarray(to_global(X)) - B) < 1e-12 * np.linalg.norm(B)


def test_permute_rows_roundtrip(grid42):
    m, n = 18, 7
    rng = np.random.default_rng(15)
    F = rng.normal(size=(m, n))
    p = rng.permutation(m)
    import jax.numpy as jnp
    Bp = permute_rows(_dist(grid42, F), jnp.asarray(p))
    np.testing.assert_allclose(np.asarray(to_global(Bp)), F[p, :], rtol=1e-14)
    back = permute_rows(Bp, jnp.asarray(p), inverse=True)
    np.testing.assert_allclose(np.asarray(to_global(back)), F, rtol=1e-14)


@pytest.mark.parametrize("shape", [(24, 24), (32, 20), (20, 32), (19, 19),
                                   (18, 30)])
def test_lu_lookahead_matches_classic(grid24, shape):
    """The pipelined schedule reorders ops but computes the same update
    matmuls element-for-element: factors and pivots must agree with the
    classic right-looking driver to roundoff (crossover disabled so both
    run the full distributed loop)."""
    m, n = shape
    rng = np.random.default_rng(21)
    F = rng.normal(size=(m, n))
    LUa, pa = lu(_dist(grid24, F), nb=8, lookahead=True, crossover=0)
    LUb, pb = lu(_dist(grid24, F), nb=8, lookahead=False)
    np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    np.testing.assert_allclose(np.asarray(to_global(LUa)),
                               np.asarray(to_global(LUb)),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("shape", [
    pytest.param((48, 48), marks=pytest.mark.slow),
    pytest.param((40, 40), marks=pytest.mark.slow),
    (48, 32), (32, 48)])
def test_lu_crossover_boundary(grid24, shape):
    """Tail crossover-to-local at thresholds just below / at / above the
    remaining-block sizes: pivots match classic exactly and factors to
    roundoff at every threshold (incl. 0 = never and huge = tail on the
    first step)."""
    m, n = shape
    rng = np.random.default_rng(31)
    F = rng.normal(size=(m, n))
    LUref, pref = lu(_dist(grid24, F), nb=8, lookahead=False)
    ref = np.asarray(to_global(LUref))
    for xo in [0, 7, 8, 9, 16, 31, 32, 33, 10_000]:
        LU, p = lu(_dist(grid24, F), nb=8, lookahead=True, crossover=xo)
        np.testing.assert_array_equal(np.asarray(p), np.asarray(pref))
        np.testing.assert_allclose(np.asarray(to_global(LU)), ref,
                                   rtol=1e-12, atol=1e-12)
        k = min(m, n)
        got = np.asarray(to_global(LU))
        L = np.tril(got[:, :k], -1) + np.eye(m, k)
        U = np.triu(got[:k, :])
        res = np.linalg.norm(F[np.asarray(p)] - L @ U)
        assert res < 1e-12 * np.linalg.norm(F) * max(m, n)


def test_lu_crossover_classic_opt_in(grid24):
    """Explicit crossover also applies to the classic schedule (mirrors
    cholesky): default classic never crosses over."""
    n = 40
    rng = np.random.default_rng(32)
    F = rng.normal(size=(n, n))
    LUa, pa = lu(_dist(grid24, F), nb=8, lookahead=False, crossover=16)
    LUb, pb = lu(_dist(grid24, F), nb=8, lookahead=False)
    np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    np.testing.assert_allclose(np.asarray(to_global(LUa)),
                               np.asarray(to_global(LUb)),
                               rtol=1e-12, atol=1e-12)


def test_lu_lookahead_matches_classic_local():
    """Same agreement on the sequential (1x1 grid) fast path."""
    import jax
    import elemental_tpu as el
    g1 = el.Grid([jax.devices()[0]])
    rng = np.random.default_rng(22)
    for m, n in [(40, 40), (40, 56), (56, 40), (37, 37)]:
        F = rng.normal(size=(m, n))
        LUa, pa = lu(_dist(g1, F), nb=16, lookahead=True)
        LUb, pb = lu(_dist(g1, F), nb=16, lookahead=False)
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
        np.testing.assert_allclose(np.asarray(LUa.local),
                                   np.asarray(LUb.local),
                                   rtol=1e-12, atol=1e-12)
        L, U = _unpack(np.asarray(LUa.local))
        assert np.linalg.norm(F[np.asarray(pa), :n] - (L @ U)[:, :n]) \
            < 1e-12 * np.linalg.norm(F)


def test_lu_update_precision_knob(grid24):
    """update_precision only relaxes the trailing updates: on CPU f64 the
    DEFAULT and HIGHEST paths coincide, so this pins the API and the
    factorization residual, not a bf16 error model."""
    import jax
    n = 24
    rng = np.random.default_rng(23)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    LUd, perm = lu(_dist(grid24, F), nb=8,
                   precision=jax.lax.Precision.HIGHEST,
                   update_precision=jax.lax.Precision.DEFAULT)
    L, U = _unpack(np.asarray(to_global(LUd)))
    p = np.asarray(perm)
    assert np.linalg.norm(F[p, :] - L @ U) / np.linalg.norm(F) < 1e-10


def test_lu_jit(grid24):
    import jax
    n = 16
    rng = np.random.default_rng(16)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    A = _dist(grid24, F)
    LUd, perm = jax.jit(lambda a: lu(a, nb=8))(A)
    LUh = np.asarray(to_global(LUd))
    L, U = _unpack(LUh)
    assert np.linalg.norm(F[np.asarray(perm), :] - L @ U) < 1e-12 * np.linalg.norm(F)
