"""Spectral-layer oracles.

Reference test style (SURVEY.md §5): ``tests/lapack_like/HermitianEig.cpp``
residuals ||A Z - Z diag(w)||/||A||, orthogonality ||I - Z^H Z||, subset
consistency; SVD drivers check singular values against the sequential
oracle and the reconstruction residual.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.lapack.funcs import _qdwh_eig


def _g(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def _sym(n, seed=0, cplx=False):
    rng = np.random.default_rng(seed)
    if cplx:
        G = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
        return (G + G.conj().T) / 2
    G = rng.normal(size=(n, n))
    return (G + G.T) / 2


def _check_eig(F, w, Z, tol=1e-12):
    n = F.shape[0]
    wn = np.linalg.eigvalsh(F)
    assert np.linalg.norm(np.asarray(w) - wn) / max(np.linalg.norm(wn), 1) < tol
    Zg = _t(Z)
    assert np.linalg.norm(F @ Zg - Zg @ np.diag(np.asarray(w))) \
        / np.linalg.norm(F) < tol
    assert np.linalg.norm(Zg.conj().T @ Zg - np.eye(n)) < tol * n


def test_herm_eig_real(grid24):
    F = _sym(24, 0)
    w, Z = el.herm_eig(_g(F, grid24))
    _check_eig(F, w, Z)


def test_herm_eig_complex(grid24):
    F = _sym(24, 1, cplx=True)
    w, Z = el.herm_eig(_g(F, grid24))
    _check_eig(F, w, Z)


def test_herm_eig_one_triangle(grid24):
    """Only the selected triangle may be read (poison the other)."""
    F = _sym(24, 2)
    P = F.copy()
    P[np.triu_indices(24, 1)] = np.nan
    w, Z = el.herm_eig(_g(P, grid24), uplo="L")
    _check_eig(F, w, Z)


def test_herm_eig_subset_index(grid24):
    F = _sym(24, 3)
    wn = np.linalg.eigvalsh(F)
    w, Z = el.herm_eig(_g(F, grid24), subset=("index", 2, 6))
    assert np.allclose(np.asarray(w), wn[2:7], atol=1e-12)
    Zg = _t(Z)
    assert Zg.shape == (24, 5)
    assert np.linalg.norm(F @ Zg - Zg @ np.diag(np.asarray(w))) < 1e-11


def test_herm_eig_subset_value_half_open(grid24):
    """range='V' selects (lo, hi]: lo itself excluded, hi included."""
    d = np.arange(1.0, 25.0)
    F = np.diag(d)
    w = el.herm_eig(_g(F, grid24), vectors=False, subset=("value", 5.0, 9.0))
    assert np.allclose(np.sort(np.asarray(w)), [6.0, 7.0, 8.0, 9.0])


def test_skew_herm_eig_subset(grid24):
    """ADVICE repro: subset=('index',0,3) must return the 4 SMALLEST
    imaginary parts, not the largest."""
    rng = np.random.default_rng(4)
    G = rng.normal(size=(16, 16))
    F = G - G.T                                   # skew-symmetric
    imag_all = np.sort(np.linalg.eigvals(F).imag)
    w, Z = el.skew_herm_eig(_g(F, grid24), subset=("index", 0, 3))
    assert np.allclose(np.asarray(w), imag_all[:4], atol=1e-11)
    Zg = _t(Z)
    # residual: A z = (i w) z
    r = F.astype(complex) @ Zg - Zg @ np.diag(1j * np.asarray(w))
    assert np.linalg.norm(r) / max(np.linalg.norm(F), 1) < 1e-11
    # value window on the imaginary parts: (lo, hi]
    lo, hi = imag_all[5], imag_all[9]
    wv = el.skew_herm_eig(_g(F, grid24), vectors=False,
                          subset=("value", lo, hi))
    assert np.allclose(np.asarray(wv), imag_all[6:10], atol=1e-11)


def test_herm_gen_def_eig(grid24):
    rng = np.random.default_rng(5)
    A = _sym(16, 6)
    G = rng.normal(size=(16, 16))
    B = G @ G.T / 16 + 2 * np.eye(16)
    w, X = el.herm_gen_def_eig(_g(A, grid24), _g(B, grid24))
    Xg = _t(X)
    r = A @ Xg - B @ Xg @ np.diag(np.asarray(w))
    assert np.linalg.norm(r) / np.linalg.norm(A) < 1e-11
    assert np.linalg.norm(Xg.T @ B @ Xg - np.eye(16)) < 1e-10


def test_hermitian_svd(grid24):
    F = _sym(24, 7)
    U, s, V = el.hermitian_svd(_g(F, grid24))
    sn = np.linalg.svd(F, compute_uv=False)
    assert np.allclose(np.asarray(s), sn, atol=1e-12)
    Ug, Vg = _t(U), _t(V)
    rec = Ug @ np.diag(np.asarray(s)) @ Vg.T
    assert np.linalg.norm(rec - F) / np.linalg.norm(F) < 1e-12


def _check_svd(F, U, s, V, tol=1e-12):
    sn = np.linalg.svd(F, compute_uv=False)
    k = len(np.asarray(s))
    assert np.allclose(np.asarray(s), sn[:k], atol=tol * max(sn[0], 1))
    Ug, Vg = _t(U), _t(V)
    rec = Ug @ np.diag(np.asarray(s)) @ Vg.conj().T
    assert np.linalg.norm(rec - F) / np.linalg.norm(F) < tol
    assert np.linalg.norm(Ug.conj().T @ Ug - np.eye(k)) < tol * k
    assert np.linalg.norm(Vg.conj().T @ Vg - np.eye(k)) < tol * k


def test_svd_square(grid24):
    """Round-2 regression: svd() on square input crashed (missing funcs)."""
    rng = np.random.default_rng(8)
    F = rng.normal(size=(24, 24))
    U, s, V = el.svd(_g(F, grid24))
    _check_svd(F, U, s, V)


@pytest.mark.slow
def test_svd_square_complex(grid24):
    rng = np.random.default_rng(9)
    F = rng.normal(size=(16, 16)) + 1j * rng.normal(size=(16, 16))
    U, s, V = el.svd(_g(F, grid24))
    _check_svd(F, U, s, V)


def test_svd_tall_chan(grid24):
    rng = np.random.default_rng(10)
    F = rng.normal(size=(48, 16))
    U, s, V = el.svd(_g(F, grid24), approach="chan")
    _check_svd(F, U, s, V)


def test_svd_wide(grid24):
    rng = np.random.default_rng(11)
    F = rng.normal(size=(16, 40))
    U, s, V = el.svd(_g(F, grid24))
    _check_svd(F, U, s, V)


def test_svd_values_only(grid24):
    rng = np.random.default_rng(12)
    F = rng.normal(size=(24, 24))
    s = el.svd(_g(F, grid24), vectors=False)
    assert np.allclose(np.asarray(s), np.linalg.svd(F, compute_uv=False),
                       atol=1e-12)


# ---------------------------------------------------------------------
# QDWH-eig: the scalable (PMRRR-replacement) path
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_qdwh_eig_recursive(grid24):
    """Small base forces >= 2 levels of spectral divide-and-conquer."""
    F = _sym(48, 13)
    A = _g(F, grid24)
    w, Z = _qdwh_eig(A, "L", True, base=12)
    _check_eig(F, w, Z, tol=1e-12)
    # subset rides the same path
    wn = np.linalg.eigvalsh(F)
    ws = _qdwh_eig(A, "L", False, subset=("index", 3, 9), base=12)
    assert np.allclose(np.asarray(ws), wn[3:10], atol=1e-12)


def test_qdwh_eig_public_api(grid24):
    F = _sym(24, 14)
    w, Z = el.herm_eig(_g(F, grid24), approach="qdwh")
    _check_eig(F, w, Z)


@pytest.mark.slow
def test_qdwh_eig_clustered(grid24):
    """Near-multiple-of-identity blocks must deflate, not loop."""
    rng = np.random.default_rng(15)
    Q, _ = np.linalg.qr(rng.normal(size=(32, 32)))
    d = np.concatenate([np.full(16, 2.0), np.full(16, 5.0)])
    F = (Q * d) @ Q.T
    F = (F + F.T) / 2
    w, Z = _qdwh_eig(_g(F, grid24), "L", True, base=8)
    assert np.allclose(np.sort(np.asarray(w)), np.sort(d), atol=1e-10)
    Zg = _t(Z)
    assert np.linalg.norm(F @ Zg - Zg @ np.diag(np.asarray(w))) \
        / np.linalg.norm(F) < 1e-10
