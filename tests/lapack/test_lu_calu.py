"""CALU tournament-pivoted LU (ISSUE 6): validity, stability vs the
classic partial-pivot baseline, and round-trip coverage.

CALU's pivots come from a log-depth tournament over grid-row slabs, not
from a global per-column argmax, so its growth factor bound is weaker
than partial pivoting's (2^{b log r}-class instead of 2^k-class, cf.
Grigori/Demmel/Xiang).  The suite certifies the residual anyway: on the
random / graded / Wilkinson-adversarial stability matrices the backward
error ``||P A - L U|| / ||A||`` must stay within a documented factor of
classic's (and near roundoff in absolute terms) -- the factorization is
algebra-exact for ANY row choice; what the bound guards is growth in the
factors feeding the solve path.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.lapack.lu import lu, lu_solve, lu_solve_after, permute_rows

#: documented stability bound: calu residual may exceed classic's by at
#: most this factor (plus an absolute roundoff floor) on the suite below.
#: The theoretical growth ratio is 2^{b(log2 r)} worst-case; on these
#: matrices the observed ratio is O(1) -- the margin catches a broken
#: tournament (wrong winners => catastrophic growth), not noise.
CALU_RESIDUAL_FACTOR = 64.0
_FLOOR = 1e-14


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


def _unpack(LUh):
    m, n = LUh.shape
    k = min(m, n)
    L = np.tril(LUh[:, :k], -1) + np.eye(m, k)
    U = np.triu(LUh[:k, :])
    return L, U


def _resid(F, LUd, perm):
    LUh = np.asarray(to_global(LUd))
    L, U = _unpack(LUh)
    p = np.asarray(perm)
    assert sorted(p.tolist()) == list(range(F.shape[0]))
    return np.linalg.norm(F[p, :] - L @ U) / np.linalg.norm(F)


# ---------------------------------------------------------------------
# validity: PA = LU across shapes / schedules
# ---------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(24, 24), (32, 20), (20, 32), (19, 19),
                                   (19, 32), (32, 19), (18, 30)])
def test_calu_residual(grid24, shape):
    m, n = shape
    rng = np.random.default_rng(61)
    F = rng.normal(size=(m, n))
    LUd, perm = lu(_dist(grid24, F), nb=8, panel="calu")
    assert _resid(F, LUd, perm) < 1e-13


def test_calu_lookahead_matches_classic_schedule(grid24):
    """The pipelined schedule reorders ops, not math: calu pivots and
    factors agree between lookahead and classic schedules (crossover
    disabled so both run the full distributed loop)."""
    rng = np.random.default_rng(62)
    F = rng.normal(size=(32, 32))
    LUa, pa = lu(_dist(grid24, F), nb=8, panel="calu", lookahead=True,
                 crossover=0)
    LUb, pb = lu(_dist(grid24, F), nb=8, panel="calu", lookahead=False)
    np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    np.testing.assert_allclose(np.asarray(to_global(LUa)),
                               np.asarray(to_global(LUb)),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("xo", [0, 16, 10_000])
def test_calu_crossover_tail_valid(grid24, xo):
    """The crossover tail finishes with the local classic kernel, so the
    pivot SET differs from pure calu past the tail boundary -- but the
    factorization must stay residual-exact at every threshold."""
    rng = np.random.default_rng(63)
    F = rng.normal(size=(48, 48))
    LUd, perm = lu(_dist(grid24, F), nb=8, panel="calu", lookahead=True,
                   crossover=xo)
    assert _resid(F, LUd, perm) < 1e-13


def test_calu_degenerates_to_classic_on_single_row_grid():
    """One grid row: the slab IS the panel, the tournament IS partial
    pivoting -- pivots and factors must match classic exactly."""
    import jax
    g18 = el.Grid(jax.devices(), height=1)
    rng = np.random.default_rng(64)
    F = rng.normal(size=(24, 24))
    LUa, pa = lu(_dist(g18, F), nb=8, panel="calu", lookahead=False)
    LUb, pb = lu(_dist(g18, F), nb=8, panel="classic", lookahead=False)
    np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    np.testing.assert_allclose(np.asarray(to_global(LUa)),
                               np.asarray(to_global(LUb)),
                               rtol=1e-13, atol=1e-13)


# ---------------------------------------------------------------------
# stability suite: random / graded (ill-conditioned) / Wilkinson-adversarial
# ---------------------------------------------------------------------

def _stability_cases(n):
    rng = np.random.default_rng(65)
    random = rng.normal(size=(n, n))
    # graded: geometrically scaled rows+cols, cond ~ 1e12
    grade = np.logspace(0, -6, n)
    graded = grade[:, None] * rng.normal(size=(n, n)) * grade[None, :]
    # Wilkinson growth matrix: partial pivoting never swaps and the last
    # column doubles every step (growth 2^{n-1}); a tournament that picks
    # bad rows here blows the residual up immediately
    wilk = np.eye(n) + np.tril(-np.ones((n, n)), -1)
    wilk[:, -1] = 1.0
    return [("random", random), ("graded", graded), ("wilkinson", wilk)]


@pytest.mark.parametrize("case", ["random", "graded", "wilkinson"])
def test_calu_stability_vs_classic(grid24, case):
    n = 32
    F = dict(_stability_cases(n))[case]
    LUc, pc = lu(_dist(grid24, F), nb=8, panel="classic", lookahead=False)
    LUt, pt = lu(_dist(grid24, F), nb=8, panel="calu", lookahead=False)
    r_classic = _resid(F, LUc, pc)
    r_calu = _resid(F, LUt, pt)
    assert r_calu <= CALU_RESIDUAL_FACTOR * r_classic + _FLOOR, (
        case, r_calu, r_classic)


# ---------------------------------------------------------------------
# solve / permutation round trips with tournament permutations
# ---------------------------------------------------------------------

def test_calu_lu_solve(grid24):
    n, nrhs = 24, 4
    rng = np.random.default_rng(66)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    B = rng.normal(size=(n, nrhs))
    X = lu_solve(_dist(grid24, F), _dist(grid24, B), nb=8, panel="calu")
    Xh = np.asarray(to_global(X))
    assert np.linalg.norm(F @ Xh - B) / np.linalg.norm(B) < 1e-12


def test_calu_lu_solve_after_reuse(grid24):
    n = 24
    rng = np.random.default_rng(67)
    F = rng.normal(size=(n, n)) + n * np.eye(n)
    LUd, perm = lu(_dist(grid24, F), nb=8, panel="calu")
    for seed in (1, 2):
        B = np.random.default_rng(seed).normal(size=(n, 2))
        X = lu_solve_after(LUd, perm, _dist(grid24, B), nb=8)
        assert np.linalg.norm(F @ np.asarray(to_global(X)) - B) \
            < 1e-12 * np.linalg.norm(B)


def test_calu_permute_rows_inverse_roundtrip(grid24):
    """permute_rows(inverse=True) undoes a tournament permutation (the
    engine's storage-level one-shot fast path on both directions)."""
    n = 24
    rng = np.random.default_rng(68)
    F = rng.normal(size=(n, n))
    B = rng.normal(size=(n, 5))
    _, perm = lu(_dist(grid24, F), nb=8, panel="calu")
    Bd = _dist(grid24, B)
    Bp = permute_rows(Bd, perm)
    np.testing.assert_allclose(np.asarray(to_global(Bp)),
                               B[np.asarray(perm), :], rtol=1e-14)
    back = permute_rows(Bp, perm, inverse=True)
    np.testing.assert_allclose(np.asarray(to_global(back)), B, rtol=1e-14)


# ---------------------------------------------------------------------
# knob plumbing + obs
# ---------------------------------------------------------------------

def test_calu_rejects_unknown_panel(grid24):
    rng = np.random.default_rng(69)
    F = rng.normal(size=(16, 16))
    with pytest.raises(ValueError, match="panel"):
        lu(_dist(grid24, F), nb=8, panel="tournament")


def test_calu_tournament_phase_tick(grid24):
    """The tournament phase is observable: an eager run with a timer hook
    sees 'tournament' ticks between pivot selection and the unpivoted
    panel refactorization (ISSUE 6's obs rider)."""
    class Hook:
        def __init__(self):
            self.phases = []

        def start(self):
            pass

        def tick(self, phase, step, *arrays):
            self.phases.append(str(phase))

    rng = np.random.default_rng(70)
    F = rng.normal(size=(32, 32))
    hook = Hook()
    lu(_dist(grid24, F), nb=8, panel="calu", crossover=0, timer=hook)
    assert "tournament" in hook.phases
    assert "panel" in hook.phases and "solve" in hook.phases
    # classic never ticks the tournament phase
    hook2 = Hook()
    lu(_dist(grid24, F), nb=8, panel="classic", crossover=0, timer=hook2)
    assert "tournament" not in hook2.phases
