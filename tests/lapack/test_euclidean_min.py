"""Ridge / Tikhonov / LSE / GLM oracles (closed-form cross-checks)."""
import numpy as np

import elemental_tpu as el


def _dm(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def test_ridge(grid24):
    rng = np.random.default_rng(0)
    A = rng.normal(size=(20, 8))
    b = rng.normal(size=(20, 2))
    x = _t(el.ridge(_dm(A, grid24), _dm(b, grid24), 1.5))
    ref = np.linalg.solve(A.T @ A + 1.5 ** 2 * np.eye(8), A.T @ b)
    assert np.linalg.norm(x - ref) < 1e-12


def test_tikhonov(grid24):
    rng = np.random.default_rng(1)
    A = rng.normal(size=(20, 8))
    b = rng.normal(size=(20, 1))
    G = rng.normal(size=(5, 8))
    x = _t(el.tikhonov(_dm(A, grid24), _dm(b, grid24), _dm(G, grid24)))
    ref = np.linalg.solve(A.T @ A + G.T @ G, A.T @ b)
    assert np.linalg.norm(x - ref) < 1e-12


def test_lse(grid24):
    rng = np.random.default_rng(2)
    A = rng.normal(size=(20, 8))
    b = rng.normal(size=(20, 1))
    C = rng.normal(size=(3, 8))
    d = rng.normal(size=(3, 1))
    x = _t(el.lse(_dm(A, grid24), _dm(b, grid24), _dm(C, grid24),
                  _dm(d, grid24)))
    K = np.block([[A.T @ A, C.T], [C, np.zeros((3, 3))]])
    ref = np.linalg.solve(K, np.vstack([A.T @ b, d]))[:8]
    assert np.linalg.norm(x - ref) < 1e-11
    assert np.linalg.norm(C @ x - d) < 1e-12


def test_glm(grid24):
    rng = np.random.default_rng(3)
    A = rng.normal(size=(12, 4))
    B = rng.normal(size=(12, 12))
    d = rng.normal(size=(12, 1))
    x, y = el.glm(_dm(A, grid24), _dm(B, grid24), _dm(d, grid24))
    xg, yg = _t(x), _t(y)
    assert np.linalg.norm(A @ xg + B @ yg - d) < 1e-12
    # x matches the GLS closed form with covariance W = B B^T
    W = B @ B.T
    Wi = np.linalg.inv(W)
    ref = np.linalg.solve(A.T @ Wi @ A, A.T @ Wi @ d)
    assert np.linalg.norm(xg - ref) < 1e-10


def test_lse_complex(grid24):
    """Regression: the KKT blocks must use conjugate transposes."""
    rng = np.random.default_rng(4)
    A = rng.normal(size=(12, 5)) + 1j * rng.normal(size=(12, 5))
    b = rng.normal(size=(12, 1)) + 1j * rng.normal(size=(12, 1))
    C = rng.normal(size=(2, 5)) + 1j * rng.normal(size=(2, 5))
    d = rng.normal(size=(2, 1)) + 1j * rng.normal(size=(2, 1))
    x = _t(el.lse(_dm(A, grid24), _dm(b, grid24), _dm(C, grid24),
                  _dm(d, grid24)))
    K = np.block([[A.conj().T @ A, C.conj().T],
                  [C, np.zeros((2, 2), complex)]])
    ref = np.linalg.solve(K, np.vstack([A.conj().T @ b, d]))[:5]
    assert np.linalg.norm(x - ref) < 1e-11
    assert np.linalg.norm(C @ x - d) < 1e-12
