"""TSQR/CAQR tree panel for QR (ISSUE 6 rider): the tree-reduced panel
must land in the SAME geqrf packing as the classic larfg panel, so every
downstream consumer (apply_q, explicit_q, least_squares) works unchanged.

R's diagonal signs may differ from the classic reduction (the tree fixes
signs so the Householder reconstruction's LU is stable), hence the
comparisons below are |R|-level plus exact self-consistency identities
(orthogonality, A = Q R, apply_q round trip).
"""
import numpy as np
import pytest

from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.lapack.qr import qr, apply_q, explicit_q


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


@pytest.mark.parametrize("shape", [(24, 16), (32, 32), (19, 13), (30, 18)])
def test_tsqr_residual_orthogonality(grid24, shape):
    m, n = shape
    rng = np.random.default_rng(71)
    F = rng.normal(size=(m, n))
    Ap, tau = qr(_dist(grid24, F), nb=8, panel="tsqr")
    Q = np.asarray(to_global(explicit_q(Ap, tau)))
    k = min(m, n)
    R = np.triu(np.asarray(to_global(Ap)))[:k, :]
    assert np.linalg.norm(Q.T @ Q - np.eye(m)) < 1e-12
    assert np.linalg.norm(Q[:, :k] @ R - F) < 1e-12 * np.linalg.norm(F)


def test_tsqr_R_matches_numpy_abs(grid42):
    rng = np.random.default_rng(72)
    F = rng.normal(size=(28, 12))
    Ap, _ = qr(_dist(grid42, F), nb=4, panel="tsqr")
    R = np.triu(np.asarray(to_global(Ap)))[:12, :]
    np.testing.assert_allclose(np.abs(R), np.abs(np.linalg.qr(F, mode="r")),
                               atol=1e-11)


def test_tsqr_complex(grid24):
    rng = np.random.default_rng(73)
    F = rng.normal(size=(20, 12)) + 1j * rng.normal(size=(20, 12))
    Ap, tau = qr(_dist(grid24, F), nb=4, panel="tsqr")
    Q = np.asarray(to_global(explicit_q(Ap, tau)))
    R = np.triu(np.asarray(to_global(Ap)))[:12, :]
    assert np.linalg.norm(Q.conj().T @ Q - np.eye(20)) < 1e-11
    assert np.linalg.norm(Q[:, :12] @ R - F) < 1e-11 * np.linalg.norm(F)


def test_tsqr_apply_q_roundtrip_records_nb(grid24):
    """Q (Q^H B) == B through the packed tree factor, using the recorded
    ``_qr_nb`` default blocking (the reused tuner plumbing)."""
    rng = np.random.default_rng(74)
    F = rng.normal(size=(24, 16))
    Ap, tau = qr(_dist(grid24, F), nb=8, panel="tsqr")
    assert getattr(Ap, "_qr_nb", None) == 8
    B = rng.normal(size=(24, 3))
    Bd = _dist(grid24, B)
    out = apply_q(Ap, tau, apply_q(Ap, tau, Bd, orient="C"))
    np.testing.assert_allclose(np.asarray(to_global(out)), B, atol=1e-12)


def test_tsqr_rejects_unknown_panel(grid24):
    rng = np.random.default_rng(75)
    F = rng.normal(size=(16, 8))
    with pytest.raises(ValueError, match="panel"):
        qr(_dist(grid24, F), nb=8, panel="caqr2")


def test_tsqr_least_squares_path(grid24):
    """A tsqr factor drives the same triangular solve as classic: solve a
    tall LS problem both ways and compare the minimizers."""
    rng = np.random.default_rng(76)
    F = rng.normal(size=(30, 10))
    B = rng.normal(size=(30, 2))
    X_np, *_ = np.linalg.lstsq(F, B, rcond=None)
    Ap, tau = qr(_dist(grid24, F), nb=4, panel="tsqr")
    Y = apply_q(Ap, tau, _dist(grid24, B), orient="C")
    from elemental_tpu.redist.interior import interior_view
    from elemental_tpu.blas.level1 import make_trapezoidal
    from elemental_tpu.blas.level3 import trsm
    R = make_trapezoidal(interior_view(Ap, (0, 10), (0, 10)), "U")
    X = trsm("L", "U", "N", R, interior_view(Y, (0, 10), (0, 2)), nb=4)
    np.testing.assert_allclose(np.asarray(to_global(X)), X_np, atol=1e-10)
