"""HermitianTridiag / Hessenberg oracles.

Model: reference ``tests/lapack_like/HermitianTridiag.cpp`` -- residual
``||A - Q T Q^H||/||A||`` + orthogonality ``||I - Q^H Q||``, real & complex.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from elemental_tpu import from_global, to_global, MC, MR
from elemental_tpu.lapack.condense import (
    hermitian_tridiag, apply_q_herm_tridiag, hessenberg, apply_q_hessenberg)
from elemental_tpu.matrices.basic import identity


def _herm(n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n))
    if jnp.issubdtype(dtype, jnp.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    A = (A + A.conj().T) / 2
    return A.astype(dtype)


def _tridiag_full(d, e):
    return np.diag(np.asarray(d)) + np.diag(np.asarray(e), -1) + np.diag(np.asarray(e), 1)


@pytest.mark.parametrize("dtype", [jnp.float64, pytest.param(jnp.complex128, marks=pytest.mark.slow)])
@pytest.mark.parametrize("n", [24, pytest.param(37, marks=pytest.mark.slow)])
def test_hermitian_tridiag(grid24, dtype, n):
    A = _herm(n, dtype)
    Ad = from_global(A, MC, MR, grid24)
    Ap, d, e, tau = hermitian_tridiag(Ad, nb=8)
    T = _tridiag_full(d, e)
    # Q explicit via back-transform of the identity
    Q = apply_q_herm_tridiag(Ap, tau, identity(n, grid=grid24, dtype=dtype), nb=8)
    Qg = np.asarray(to_global(Q))
    resid = np.linalg.norm(A - Qg @ T @ Qg.conj().T) / max(np.linalg.norm(A), 1)
    orth = np.linalg.norm(np.eye(n) - Qg.conj().T @ Qg)
    assert resid < 1e-12
    assert orth < 1e-12
    # eigenvalues preserved
    np.testing.assert_allclose(np.linalg.eigvalsh(T), np.linalg.eigvalsh(A),
                               rtol=1e-10, atol=1e-10)


def test_hermitian_tridiag_uplo_upper(grid24):
    n = 24
    A = _herm(n, jnp.float64, seed=3)
    # poison the lower strict triangle: 'U' must only read the upper
    Abad = A.copy()
    Abad[np.tril_indices(n, -1)] = 99.0
    Ad = from_global(Abad, MC, MR, grid24)
    Ap, d, e, tau = hermitian_tridiag(Ad, uplo="U", nb=8)
    T = _tridiag_full(d, e)
    np.testing.assert_allclose(np.linalg.eigvalsh(T), np.linalg.eigvalsh(A),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
def test_hessenberg(grid24, dtype):
    n = 21
    rng = np.random.default_rng(7)
    A = rng.standard_normal((n, n))
    if jnp.issubdtype(dtype, jnp.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    A = A.astype(dtype)
    Ad = from_global(A, MC, MR, grid24)
    H, Qp, tau = hessenberg(Ad)
    Hg = np.asarray(to_global(H))
    assert np.abs(np.tril(Hg, -2)).max() < 1e-12
    Q = apply_q_hessenberg(Qp, tau, identity(n, grid=grid24, dtype=dtype))
    Qg = np.asarray(to_global(Q))
    resid = np.linalg.norm(A - Qg @ Hg @ Qg.conj().T) / np.linalg.norm(A)
    orth = np.linalg.norm(np.eye(n) - Qg.conj().T @ Qg)
    assert resid < 1e-12
    assert orth < 1e-12


# ---------------------------------------------------------------------
# Bidiag (the SVD condense step)
# ---------------------------------------------------------------------

def _check_bidiag(F, grid, nb):
    import elemental_tpu as el
    from elemental_tpu.lapack.condense import bidiag, apply_p_bidiag
    from elemental_tpu.lapack.qr import apply_q
    m, n = F.shape
    A = el.from_global(F, el.MC, el.MR, grid=grid)
    Ap, d, e, tauq, taup = bidiag(A, nb=nb)
    dn, en = np.asarray(d), np.asarray(e)
    assert np.isrealobj(dn) and np.isrealobj(en)
    B = np.zeros((m, n), F.dtype)
    B[:n, :n] = np.diag(dn.astype(F.dtype)) + np.diag(en.astype(F.dtype), 1)
    I_m = el.from_global(np.eye(m, dtype=F.dtype), el.MC, el.MR, grid=grid)
    I_n = el.from_global(np.eye(n, dtype=F.dtype), el.MC, el.MR, grid=grid)
    Q = np.asarray(el.to_global(apply_q(Ap, tauq, I_m, orient="N")))
    P = np.asarray(el.to_global(apply_p_bidiag(Ap, taup, I_n, orient="N")))
    assert np.linalg.norm(Q.conj().T @ Q - np.eye(m)) < 1e-12 * m
    assert np.linalg.norm(P.conj().T @ P - np.eye(n)) < 1e-12 * n
    rec = Q @ B @ P.conj().T
    assert np.linalg.norm(rec - F) / np.linalg.norm(F) < 1e-13
    sa = np.linalg.svd(F, compute_uv=False)
    sb = np.linalg.svd(B, compute_uv=False)
    assert np.linalg.norm(sa - sb) < 1e-12 * max(sa[0], 1)


def test_bidiag_tall(grid24):
    rng = np.random.default_rng(20)
    _check_bidiag(rng.normal(size=(24, 16)), grid24, nb=8)


def test_bidiag_square_full_panel(grid24):
    rng = np.random.default_rng(21)
    _check_bidiag(rng.normal(size=(16, 16)), grid24, nb=16)


@pytest.mark.slow
def test_bidiag_complex(grid24):
    rng = np.random.default_rng(22)
    F = rng.normal(size=(20, 12)) + 1j * rng.normal(size=(20, 12))
    _check_bidiag(F, grid24, nb=4)


@pytest.mark.slow
def test_svd_golub_kahan(grid24):
    import elemental_tpu as el
    rng = np.random.default_rng(23)
    F = rng.normal(size=(32, 20))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    U, s, V = el.svd(A, approach="golub")
    rec = np.asarray(el.to_global(U)) @ np.diag(np.asarray(s)) \
        @ np.asarray(el.to_global(V)).T
    assert np.linalg.norm(rec - F) / np.linalg.norm(F) < 1e-13
    assert np.allclose(np.asarray(s), np.linalg.svd(F, compute_uv=False),
                       atol=1e-12)
    # values-only + the scalable eig path
    s2 = el.svd(A, vectors=False, approach="golub", eig_approach="qdwh")
    assert np.allclose(np.asarray(s2), np.linalg.svd(F, compute_uv=False),
                       atol=1e-10)
