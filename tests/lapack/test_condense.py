"""HermitianTridiag / Hessenberg oracles.

Model: reference ``tests/lapack_like/HermitianTridiag.cpp`` -- residual
``||A - Q T Q^H||/||A||`` + orthogonality ``||I - Q^H Q||``, real & complex.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from elemental_tpu import from_global, to_global, MC, MR
from elemental_tpu.lapack.condense import (
    hermitian_tridiag, apply_q_herm_tridiag, hessenberg, apply_q_hessenberg)
from elemental_tpu.matrices.basic import identity


def _herm(n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n))
    if jnp.issubdtype(dtype, jnp.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    A = (A + A.conj().T) / 2
    return A.astype(dtype)


def _tridiag_full(d, e):
    return np.diag(np.asarray(d)) + np.diag(np.asarray(e), -1) + np.diag(np.asarray(e), 1)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
@pytest.mark.parametrize("n", [24, 37])
def test_hermitian_tridiag(grid24, dtype, n):
    A = _herm(n, dtype)
    Ad = from_global(A, MC, MR, grid24)
    Ap, d, e, tau = hermitian_tridiag(Ad, nb=8)
    T = _tridiag_full(d, e)
    # Q explicit via back-transform of the identity
    Q = apply_q_herm_tridiag(Ap, tau, identity(n, grid=grid24, dtype=dtype), nb=8)
    Qg = np.asarray(to_global(Q))
    resid = np.linalg.norm(A - Qg @ T @ Qg.conj().T) / max(np.linalg.norm(A), 1)
    orth = np.linalg.norm(np.eye(n) - Qg.conj().T @ Qg)
    assert resid < 1e-12
    assert orth < 1e-12
    # eigenvalues preserved
    np.testing.assert_allclose(np.linalg.eigvalsh(T), np.linalg.eigvalsh(A),
                               rtol=1e-10, atol=1e-10)


def test_hermitian_tridiag_uplo_upper(grid24):
    n = 24
    A = _herm(n, jnp.float64, seed=3)
    # poison the lower strict triangle: 'U' must only read the upper
    Abad = A.copy()
    Abad[np.tril_indices(n, -1)] = 99.0
    Ad = from_global(Abad, MC, MR, grid24)
    Ap, d, e, tau = hermitian_tridiag(Ad, uplo="U", nb=8)
    T = _tridiag_full(d, e)
    np.testing.assert_allclose(np.linalg.eigvalsh(T), np.linalg.eigvalsh(A),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
def test_hessenberg(grid24, dtype):
    n = 21
    rng = np.random.default_rng(7)
    A = rng.standard_normal((n, n))
    if jnp.issubdtype(dtype, jnp.complexfloating):
        A = A + 1j * rng.standard_normal((n, n))
    A = A.astype(dtype)
    Ad = from_global(A, MC, MR, grid24)
    H, Qp, tau = hessenberg(Ad)
    Hg = np.asarray(to_global(H))
    assert np.abs(np.tril(Hg, -2)).max() < 1e-12
    Q = apply_q_hessenberg(Qp, tau, identity(n, grid=grid24, dtype=dtype))
    Qg = np.asarray(to_global(Q))
    resid = np.linalg.norm(A - Qg @ Hg @ Qg.conj().T) / np.linalg.norm(A)
    orth = np.linalg.norm(np.eye(n) - Qg.conj().T @ Qg)
    assert resid < 1e-12
    assert orth < 1e-12
