"""Matrix-function oracles (funcs layer).

Reference test style: residual/identity oracles as in Elemental's
``tests/lapack_like`` drivers (``Polar``: ||U^H U - I||, ||A - UH||;
``Sign``: agreement with the eigen-constructed truth; inverses: ||A X - I||).
"""
import numpy as np
import pytest

import elemental_tpu as el


def _g(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def test_polar_square(grid24):
    rng = np.random.default_rng(0)
    F = rng.normal(size=(24, 24))
    U, H = el.polar(_g(F, grid24))
    Ug, Hg = _t(U), _t(H)
    assert np.linalg.norm(Ug.T @ Ug - np.eye(24)) < 1e-13
    assert np.linalg.norm(Ug @ Hg - F) / np.linalg.norm(F) < 1e-14
    assert np.linalg.norm(Hg - Hg.T) < 1e-13
    assert np.min(np.linalg.eigvalsh(Hg)) > -1e-12


@pytest.mark.slow
def test_polar_tall_wide_complex(grid24):
    rng = np.random.default_rng(1)
    F = rng.normal(size=(32, 16))
    U, H = el.polar(_g(F, grid24))
    Ug, Hg = _t(U), _t(H)
    assert np.linalg.norm(Ug.T @ Ug - np.eye(16)) < 1e-13
    assert np.linalg.norm(Ug @ Hg - F) / np.linalg.norm(F) < 1e-14
    W = rng.normal(size=(16, 32))
    U2, H2 = el.polar(_g(W, grid24))
    U2g, H2g = _t(U2), _t(H2)
    assert np.linalg.norm(U2g @ U2g.T - np.eye(16)) < 1e-13
    assert np.linalg.norm(U2g @ H2g - W) / np.linalg.norm(W) < 1e-13
    C = rng.normal(size=(24, 24)) + 1j * rng.normal(size=(24, 24))
    U3, H3 = el.polar(_g(C, grid24))
    U3g, H3g = _t(U3), _t(H3)
    assert np.linalg.norm(U3g.conj().T @ U3g - np.eye(24)) < 1e-13
    assert np.linalg.norm(U3g @ H3g - C) / np.linalg.norm(C) < 1e-14


def test_polar_ill_conditioned(grid24):
    rng = np.random.default_rng(2)
    Q1, _ = np.linalg.qr(rng.normal(size=(24, 24)))
    Q2, _ = np.linalg.qr(rng.normal(size=(24, 24)))
    s = np.logspace(0, -10, 24)          # cond 1e10
    F = (Q1 * s) @ Q2.T
    U, H = el.polar(_g(F, grid24))
    Ug, Hg = _t(U), _t(H)
    assert np.linalg.norm(Ug.T @ Ug - np.eye(24)) < 1e-10
    assert np.linalg.norm(Ug @ Hg - F) / np.linalg.norm(F) < 1e-12


def test_sign(grid24):
    rng = np.random.default_rng(3)
    V = rng.normal(size=(16, 16)) + 3 * np.eye(16)
    d = np.concatenate([rng.uniform(0.5, 2, 8), -rng.uniform(0.5, 2, 8)])
    A = V @ np.diag(d) @ np.linalg.inv(V)
    S_true = V @ np.diag(np.sign(d)) @ np.linalg.inv(V)
    Sg = _t(el.sign(_g(A, grid24)))
    assert np.linalg.norm(Sg - S_true) / np.linalg.norm(S_true) < 1e-10
    assert np.linalg.norm(Sg @ Sg - np.eye(16)) < 1e-10


def test_inverse(grid24):
    rng = np.random.default_rng(4)
    F = rng.normal(size=(24, 24)) + 6 * np.eye(24)
    X = _t(el.inverse(_g(F, grid24)))
    assert np.linalg.norm(F @ X - np.eye(24)) < 1e-12


def test_triangular_inverse(grid24):
    rng = np.random.default_rng(5)
    L = np.tril(rng.normal(size=(24, 24))) + 4 * np.eye(24)
    X = _t(el.triangular_inverse("L", _g(L, grid24)))
    assert np.linalg.norm(np.tril(X) @ L - np.eye(24)) < 1e-12
    U = np.triu(rng.normal(size=(24, 24))) + 4 * np.eye(24)
    Xu = _t(el.triangular_inverse("U", _g(U, grid24)))
    assert np.linalg.norm(np.triu(Xu) @ U - np.eye(24)) < 1e-12


def test_hpd_inverse(grid24):
    rng = np.random.default_rng(6)
    G = rng.normal(size=(24, 24))
    F = G @ G.T / 24 + 2 * np.eye(24)
    X = _t(el.hpd_inverse(_g(F, grid24)))
    assert np.linalg.norm(F @ X - np.eye(24)) < 1e-12


def test_pseudoinverse(grid24):
    rng = np.random.default_rng(7)
    F = rng.normal(size=(32, 16))                 # tall full rank
    P = _t(el.pseudoinverse(_g(F, grid24)))
    assert np.linalg.norm(P @ F - np.eye(16)) < 1e-10
    # rank deficient: A pinv(A) A == A
    B = rng.normal(size=(24, 8)) @ rng.normal(size=(8, 24))
    Pb = _t(el.pseudoinverse(_g(B, grid24)))
    assert np.linalg.norm(B @ Pb @ B - B) / np.linalg.norm(B) < 1e-10


def test_square_root(grid24):
    rng = np.random.default_rng(8)
    G = rng.normal(size=(24, 24))
    F = G @ G.T / 24 + 2 * np.eye(24)
    Y = _t(el.square_root(_g(F, grid24)))
    assert np.linalg.norm(Y @ Y - F) / np.linalg.norm(F) < 1e-11
    Y2 = _t(el.hpd_square_root(_g(F, grid24)))
    assert np.linalg.norm(Y2 @ Y2 - F) / np.linalg.norm(F) < 1e-11
    assert np.linalg.norm(Y2 - Y2.T) < 1e-11
