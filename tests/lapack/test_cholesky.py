"""Cholesky / HPDSolve residual oracles.

Mirrors the reference's ``tests/lapack_like/Cholesky.cpp``: factor a
known-conditioned HPD matrix (HermitianUniformSpectrum), check
  ||A - L L^H||_F / ||A||_F  and solve residuals  ||A X - B|| / ||B||.
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.matrices import hermitian_uniform_spectrum


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_residual(grid24, uplo, dtype):
    n = 28
    A = hermitian_uniform_spectrum(n, 1, 10, grid24, dtype=dtype, seed=3)
    F = np.asarray(to_global(A))
    L = el.cholesky(A, uplo=uplo, nb=8)
    Lh = np.asarray(to_global(L))
    if uplo == "L":
        assert np.allclose(np.triu(Lh, 1), 0)
        resid = np.linalg.norm(F - Lh @ Lh.conj().T) / np.linalg.norm(F)
    else:
        assert np.allclose(np.tril(Lh, -1), 0)
        resid = np.linalg.norm(F - Lh.conj().T @ Lh) / np.linalg.norm(F)
    assert resid < 1e-13


def test_cholesky_reads_only_triangle(grid42):
    n = 16
    A = hermitian_uniform_spectrum(n, 1, 5, grid42, dtype=np.float64, seed=4)
    F = np.asarray(to_global(A))
    garbage = F + np.triu(np.random.default_rng(0).normal(size=(n, n)), 1)
    Ld = el.cholesky(from_global(garbage, MC, MR, grid42), "L", nb=8)
    want = np.linalg.cholesky(F)
    np.testing.assert_allclose(np.asarray(to_global(Ld)), want, rtol=1e-10)


def test_cholesky_two_grids_ragged(two_grids):
    n = 19     # deliberately not a multiple of any grid dim
    A = hermitian_uniform_spectrum(n, 1, 4, two_grids, dtype=np.float64, seed=5)
    F = np.asarray(to_global(A))
    L = np.asarray(to_global(el.cholesky(A, nb=8)))
    assert np.linalg.norm(F - L @ L.T) / np.linalg.norm(F) < 1e-13


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hpd_solve(grid24, uplo):
    n, nrhs = 24, 7
    A = hermitian_uniform_spectrum(n, 1, 8, grid24, dtype=np.complex128, seed=6)
    F = np.asarray(to_global(A))
    rng = np.random.default_rng(7)
    B = rng.normal(size=(n, nrhs)) + 1j * rng.normal(size=(n, nrhs))
    X = el.hpd_solve(A, from_global(B, MC, MR, grid24), uplo=uplo, nb=8)
    Xh = np.asarray(to_global(X))
    assert np.linalg.norm(F @ Xh - B) / np.linalg.norm(B) < 1e-12


def test_cholesky_solve_after(grid24):
    n, nrhs = 20, 3
    A = hermitian_uniform_spectrum(n, 1, 6, grid24, dtype=np.float64, seed=8)
    F = np.asarray(to_global(A))
    L = el.cholesky(A, nb=8)
    B = np.random.default_rng(9).normal(size=(n, nrhs))
    X = el.cholesky_solve_after(L, from_global(B, MC, MR, grid24), nb=8)
    assert np.linalg.norm(F @ np.asarray(to_global(X)) - B) < 1e-11 * np.linalg.norm(B)


def _grid22():
    import jax
    return el.Grid(jax.devices()[:4], height=2)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_cholesky_upper_multigrid(two_grids, dtype):
    """uplo='U' across the generic + degenerate grid sweep (the adjoint
    round-trip exercises the transpose-exchange chains per grid shape)."""
    n = 21
    A = hermitian_uniform_spectrum(n, 1, 9, two_grids, dtype=dtype, seed=13)
    F = np.asarray(to_global(A))
    U = np.asarray(to_global(el.cholesky(A, uplo="U", nb=8)))
    assert np.allclose(np.tril(U, -1), 0)
    assert np.linalg.norm(F - U.conj().T @ U) / np.linalg.norm(F) < 1e-13


def test_cholesky_upper_2x2_grid():
    n = 24
    g = _grid22()
    A = hermitian_uniform_spectrum(n, 1, 10, g, dtype=np.complex128, seed=14)
    F = np.asarray(to_global(A))
    U = np.asarray(to_global(el.cholesky(A, uplo="U", nb=8)))
    assert np.allclose(np.tril(U, -1), 0)
    assert np.linalg.norm(F - U.conj().T @ U) / np.linalg.norm(F) < 1e-13


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hpd_solve_2x2_grid(uplo):
    n, nrhs = 20, 5
    g = _grid22()
    A = hermitian_uniform_spectrum(n, 1, 8, g, dtype=np.float64, seed=15)
    F = np.asarray(to_global(A))
    B = np.random.default_rng(16).normal(size=(n, nrhs))
    X = el.hpd_solve(A, from_global(B, MC, MR, g), uplo=uplo, nb=8)
    assert np.linalg.norm(F @ np.asarray(to_global(X)) - B) \
        < 1e-12 * np.linalg.norm(B)


@pytest.mark.parametrize("n,dtype", [(24, np.float64), (19, np.complex128)])
def test_cholesky_lookahead_matches_classic(grid24, n, dtype):
    """The pipelined schedule reorders ops but computes the same update
    matmuls element-for-element: factors must agree with the classic
    right-looking driver to roundoff (crossover disabled so both run the
    full distributed loop)."""
    A = hermitian_uniform_spectrum(n, 1, 10, grid24, dtype=dtype, seed=17)
    La = el.cholesky(A, nb=8, lookahead=True, crossover=0)
    Lb = el.cholesky(A, nb=8, lookahead=False)
    np.testing.assert_allclose(np.asarray(to_global(La)),
                               np.asarray(to_global(Lb)),
                               rtol=1e-12, atol=1e-13)


def test_cholesky_lookahead_matches_classic_local():
    """Same agreement on the sequential (1x1 grid) fast path."""
    import jax
    g1 = el.Grid([jax.devices()[0]])
    for n in (40, 37):
        A = hermitian_uniform_spectrum(n, 1, 10, g1, dtype=np.float64,
                                       seed=18)
        La = el.cholesky(A, nb=16, lookahead=True)
        Lb = el.cholesky(A, nb=16, lookahead=False)
        np.testing.assert_allclose(np.asarray(La.local),
                                   np.asarray(Lb.local),
                                   rtol=1e-12, atol=1e-13)


def test_cholesky_crossover_boundary(grid24):
    """Tail crossover at thresholds just below / at / above the remaining
    trailing sizes (n=24, nb=8 leaves tails of 16 then 8): every setting
    must agree with the never-crossing classic factor to roundoff."""
    n = 24
    A = hermitian_uniform_spectrum(n, 1, 10, grid24, dtype=np.float64,
                                   seed=19)
    F = np.asarray(to_global(A))
    ref = np.asarray(to_global(el.cholesky(A, nb=8, lookahead=False)))
    for xo in (7, 8, 16, n):
        L = np.asarray(to_global(el.cholesky(A, nb=8, crossover=xo)))
        np.testing.assert_allclose(L, ref, rtol=1e-12, atol=1e-13)
        assert np.linalg.norm(F - L @ L.T) / np.linalg.norm(F) < 1e-13


@pytest.mark.parametrize("lookahead", [True, False])
def test_cholesky_panel_chain_uses_fused_spread(grid24, lookahead):
    """The [MC,STAR]/[STAR,MR] trailing-update pair must come from the ONE
    collective panel_spread fast path -- not from the three-redistribute
    chain it replaced (pinned via the engine's scoped trace-time call
    counts)."""
    from elemental_tpu.redist.engine import redist_counts
    from elemental_tpu import VC, STAR, MR
    n, nb = 32, 8
    A = hermitian_uniform_spectrum(n, 1, 10, grid24, dtype=np.float64,
                                   seed=20)
    F = np.asarray(to_global(A))
    with redist_counts() as counter:
        L = el.cholesky(A, nb=nb, lookahead=lookahead, crossover=0)
    counts = dict(counter)
    npanels = n // nb
    assert counts.get("panel_spread") == npanels - 1
    assert ((VC, STAR), (MC, STAR)) not in counts
    assert ((STAR, VC), (STAR, MR)) not in counts
    Lh = np.asarray(to_global(L))
    assert np.linalg.norm(F - Lh @ Lh.T) / np.linalg.norm(F) < 1e-13


def test_matrix_gallery(grid24):
    from elemental_tpu.matrices import identity, ones, hilbert, lehmer, minij
    n = 11
    np.testing.assert_allclose(np.asarray(to_global(identity(n, grid=grid24))), np.eye(n))
    np.testing.assert_allclose(np.asarray(to_global(ones(n, grid=grid24))), np.ones((n, n)))
    H = np.asarray(to_global(hilbert(n, grid24)))
    i, j = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    np.testing.assert_allclose(H, 1.0 / (i + j + 1))
    np.testing.assert_allclose(np.asarray(to_global(lehmer(n, grid24))),
                               (np.minimum(i, j) + 1.0) / (np.maximum(i, j) + 1.0))
    np.testing.assert_allclose(np.asarray(to_global(minij(n, grid24))),
                               np.minimum(i, j) + 1.0)
