"""QR / ApplyQ / TSQR / LeastSquares oracles.

Mirrors ``tests/lapack_like/QR.cpp``: factorization residual ||A - QR||,
orthogonality ||I - Q^H Q||, solve residuals (SURVEY.md §5).
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu import MC, MR, VC, STAR, from_global, to_global
from elemental_tpu.lapack.qr import qr, apply_q, explicit_q, least_squares, tsqr


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


@pytest.mark.parametrize("shape", [(24, 24), (32, 16), (16, 32), (19, 13), (13, 19)])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_qr_residual_orthogonality(grid24, shape, dtype):
    m, n = shape
    rng = np.random.default_rng(21)
    F = rng.normal(size=(m, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        F = F + 1j * rng.normal(size=(m, n))
    Ap, tau = qr(_dist(grid24, F), nb=8)
    Q = np.asarray(to_global(explicit_q(Ap, tau, nb=8)))
    k = min(m, n)
    R = np.triu(np.asarray(to_global(Ap)))[:k, :]
    assert np.linalg.norm(np.eye(m) - Q.conj().T @ Q) < 1e-12 * m
    assert np.linalg.norm(F - Q[:, :k] @ R) / np.linalg.norm(F) < 1e-13


def test_qr_vs_numpy_R(grid42):
    m, n = 20, 12
    rng = np.random.default_rng(22)
    F = rng.normal(size=(m, n))
    Ap, tau = qr(_dist(grid42, F), nb=8)
    R = np.triu(np.asarray(to_global(Ap)))[:n, :]
    Rnp = np.linalg.qr(F, mode="r")
    np.testing.assert_allclose(np.abs(R), np.abs(Rnp), atol=1e-12)


def test_apply_q_adjoint_roundtrip(grid24):
    m, n, nrhs = 24, 16, 5
    rng = np.random.default_rng(23)
    F = rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))
    B = rng.normal(size=(m, nrhs)) + 1j * rng.normal(size=(m, nrhs))
    Ap, tau = qr(_dist(grid24, F), nb=8)
    Bd = _dist(grid24, B)
    out = apply_q(Ap, tau, apply_q(Ap, tau, Bd, orient="C", nb=8),
                  orient="N", nb=8)
    np.testing.assert_allclose(np.asarray(to_global(out)), B, atol=1e-12)


@pytest.mark.parametrize("shape", [(32, 8), (40, 12)])
def test_least_squares(grid24, shape):
    m, n = shape
    rng = np.random.default_rng(24)
    F = rng.normal(size=(m, n))
    B = rng.normal(size=(m, 3))
    X = least_squares(_dist(grid24, F), _dist(grid24, B), nb=8)
    Xnp, *_ = np.linalg.lstsq(F, B, rcond=None)
    np.testing.assert_allclose(np.asarray(to_global(X)), Xnp, atol=1e-10)


def test_least_squares_complex_two_grids(two_grids):
    m, n = 26, 7
    rng = np.random.default_rng(25)
    F = rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))
    B = rng.normal(size=(m, 2)) + 1j * rng.normal(size=(m, 2))
    X = least_squares(_dist(two_grids, F), _dist(two_grids, B), nb=4)
    Xnp, *_ = np.linalg.lstsq(F, B, rcond=None)
    np.testing.assert_allclose(np.asarray(to_global(X)), Xnp, atol=1e-10)


def test_tsqr(grid24):
    m, k = 64, 6
    rng = np.random.default_rng(26)
    F = rng.normal(size=(m, k))
    A = from_global(F, VC, STAR, grid24)
    Q, R = tsqr(A)
    Qh = np.asarray(to_global(Q))
    Rh = np.asarray(to_global(R))
    assert np.linalg.norm(Qh.T @ Qh - np.eye(k)) < 1e-13
    np.testing.assert_allclose(Qh @ Rh, F, atol=1e-12)
    assert np.allclose(np.tril(Rh, -1), 0)


def test_qr_jit(grid24):
    import jax
    m, n = 16, 12
    rng = np.random.default_rng(27)
    F = rng.normal(size=(m, n))
    Ap, tau = jax.jit(lambda a: qr(a, nb=8))(_dist(grid24, F))
    R = np.triu(np.asarray(to_global(Ap)))[:n, :]
    Rnp = np.linalg.qr(F, mode="r")
    np.testing.assert_allclose(np.abs(R), np.abs(Rnp), atol=1e-12)


# ---------------------------------------------------------------------
# LQ and column-pivoted QR
# ---------------------------------------------------------------------

def test_lq(grid24):
    import elemental_tpu as el
    rng = np.random.default_rng(30)
    F = rng.normal(size=(8, 20))
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    Ap, tau = el.lq(A)
    L = np.asarray(el.to_global(el.explicit_l(Ap)))
    I_n = el.from_global(np.eye(20), el.MC, el.MR, grid=grid24)
    Q = np.asarray(el.to_global(el.apply_q_lq(Ap, tau, I_n, orient="N")))
    assert np.linalg.norm(np.triu(L, 1)) == 0
    assert np.linalg.norm(Q.T @ Q - np.eye(20)) < 1e-12
    assert np.linalg.norm(L @ Q[:8] - F) / np.linalg.norm(F) < 1e-13


def _check_cpqr(F, grid, nb):
    import elemental_tpu as el
    from elemental_tpu.lapack.qr import qr_col_piv, apply_q
    m, n = F.shape
    A = el.from_global(F, el.MC, el.MR, grid=grid)
    Ap, tau, jpvt = qr_col_piv(A, nb=nb)
    jp = np.asarray(jpvt)
    kend = min(m, n)
    R = np.triu(np.asarray(el.to_global(Ap))[:kend, :])
    I_m = el.from_global(np.eye(m, dtype=F.dtype), el.MC, el.MR, grid=grid)
    Q = np.asarray(el.to_global(apply_q(Ap, tau, I_m, orient="N", nb=nb)))
    perm = np.concatenate([jp, np.setdiff1d(np.arange(n), jp)]) \
        if n > kend else jp
    rec = Q[:, :kend] @ R
    assert np.linalg.norm(rec - F[:, perm]) / np.linalg.norm(F) < 1e-13
    rd = np.abs(np.diag(R))
    assert np.all(rd[:-1] >= rd[1:] - 1e-10)     # greedy pivot order


def test_qr_col_piv(grid24):
    rng = np.random.default_rng(31)
    _check_cpqr(rng.normal(size=(16, 12)), grid24, nb=4)
    _check_cpqr(rng.normal(size=(12, 12)), grid24, nb=12)
    Fc = rng.normal(size=(12, 8)) + 1j * rng.normal(size=(12, 8))
    _check_cpqr(Fc, grid24, nb=4)


def test_qr_col_piv_rank_revealing(grid24):
    import elemental_tpu as el
    from elemental_tpu.lapack.qr import qr_col_piv
    rng = np.random.default_rng(32)
    F = rng.normal(size=(16, 4)) @ rng.normal(size=(4, 12))   # rank 4
    A = el.from_global(F, el.MC, el.MR, grid=grid24)
    Ap, tau, jpvt = qr_col_piv(A, nb=4)
    R = np.triu(np.asarray(el.to_global(Ap))[:12, :])
    assert abs(R[4, 4]) < 1e-10 * abs(R[0, 0])


# ---------------------------------------------------------------------
# ISSUE 4 satellite: the qr/apply_q blocking footgun is closed
# ---------------------------------------------------------------------

def test_apply_q_defaults_to_factorization_blocking(grid24):
    """qr() records the block size it used; apply_q(nb=None) reuses it
    even when the factorization ran with a NON-default nb (previously a
    silent-wrong-results trap)."""
    m, n, nrhs = 24, 16, 5
    rng = np.random.default_rng(31)
    F = rng.normal(size=(m, n))
    B = rng.normal(size=(m, nrhs))
    Ap, tau = qr(_dist(grid24, F), nb=8)      # non-default blocking
    assert getattr(Ap, "_qr_nb") == 8
    Bd = _dist(grid24, B)
    out = apply_q(Ap, tau, apply_q(Ap, tau, Bd, orient="C"), orient="N")
    np.testing.assert_allclose(np.asarray(to_global(out)), B, atol=1e-12)


def test_apply_q_mismatched_nb_raises(grid24):
    m, n = 24, 16
    rng = np.random.default_rng(32)
    Ap, tau = qr(_dist(grid24, rng.normal(size=(m, n))), nb=8)
    Bd = _dist(grid24, rng.normal(size=(m, 3)))
    with pytest.raises(ValueError, match="block size"):
        apply_q(Ap, tau, Bd, nb=4)
    # a matching explicit nb (same derived blocking) is still accepted
    out = apply_q(Ap, tau, apply_q(Ap, tau, Bd, orient="C", nb=8), nb=8)
    np.testing.assert_allclose(np.asarray(to_global(out)),
                               np.asarray(to_global(Bd)), atol=1e-12)


def test_qr_col_piv_records_blocking(grid24):
    from elemental_tpu.lapack.qr import qr_col_piv
    rng = np.random.default_rng(33)
    Ap, tau, jpvt = qr_col_piv(_dist(grid24, rng.normal(size=(16, 12))),
                               nb=4)
    assert getattr(Ap, "_qr_nb") == 4
    Bd = _dist(grid24, rng.normal(size=(16, 2)))
    with pytest.raises(ValueError, match="block size"):
        apply_q(Ap, tau, Bd, nb=12)
