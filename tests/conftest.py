"""Test harness: a virtual 8-device CPU mesh.

The analog of the reference's ``mpirun -np 8`` single-host oversubscription
(SURVEY.md §5): the grid logic is identical at any scale, so host-only runs
exercise every code path.  Must set env BEFORE importing jax.
"""
import os

os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

import jax  # noqa: E402

# jax is pre-imported at interpreter startup in this image (axon plugin .pth),
# so env vars alone are too late; config.update works pre-backend-init.  On
# older jax builds without jax_num_cpu_devices the XLA_FLAGS path above (set
# before any import in a non-pre-imported interpreter) provides the 8 devices.
jax.config.update("jax_platform_name", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: suite wall-time is dominated by compiles
# of the shard_map'd blocked loops, which are identical run-to-run.  The
# cache drops warm non-slow-tier runs from ~10 min to ~1 min.
_cache_dir = os.path.join(os.path.dirname(__file__), os.pardir,
                          ".jax_compile_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
import pytest  # noqa: E402

from elemental_tpu import Grid  # noqa: E402

# vm.max_map_count guard: every LoadedExecutable the suite compiles holds
# mmapped JIT code pages, and one full-suite process accumulates tens of
# thousands of mappings -- once the kernel cap (default 65530) is reached
# XLA segfaults inside compile/deserialize.  The guard below watches this
# process's mapping count after each test and drops jax's compilation
# caches (releasing every executable's mappings) well before the cap; the
# persistent compile cache above turns the forced recompiles into cheap
# deserializes, so the cost is seconds per trip, not minutes.  The cap
# sits ~9.5k below the kernel limit (no single test compiles anywhere
# near that many executables): each trip costs ~8s plus a deserialize
# cascade, so spurious trips are wall-time the whole suite pays.
_MAPS_SOFT_CAP = 56_000


def _n_mappings() -> int:
    try:
        n = 0
        with open("/proc/self/maps", "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    return n
                n += chunk.count(b"\n")
    except OSError:            # non-Linux: no /proc, no known map cap
        return 0


@pytest.fixture(autouse=True)
def _cap_executable_mappings():
    yield
    if _n_mappings() > _MAPS_SOFT_CAP:
        jax.clear_caches()


@pytest.fixture(scope="session", params=[(2, 4), (4, 2), (1, 8), (8, 1)],
                ids=lambda rc: f"grid{rc[0]}x{rc[1]}")
def any_grid(request):
    r, c = request.param
    return Grid(jax.devices()[: r * c], height=r)


@pytest.fixture(scope="session", params=[(2, 4), (1, 8)],
                ids=lambda rc: f"grid{rc[0]}x{rc[1]}")
def two_grids(request):
    """A generic 2-D grid plus one degenerate (stride-1) grid: the cheap
    tier for blocked-algorithm tests (the full 4-grid sweep stays on the
    core redistribution conformance)."""
    r, c = request.param
    return Grid(jax.devices()[: r * c], height=r)


@pytest.fixture(scope="session")
def grid24():
    return Grid(jax.devices(), height=2)


@pytest.fixture
def redist_counter():
    """Scoped redistribute/panel_spread call counter: yields a fresh
    Counter active for this test only (see engine.redist_counts) -- no
    clear()-and-hope on the module global, no state leaking between
    tests."""
    from elemental_tpu.redist.engine import redist_counts
    with redist_counts() as c:
        yield c


@pytest.fixture(scope="session")
def grid42():
    return Grid(jax.devices(), height=4)
