"""Examples double as a smoke suite (the reference's examples/** role,
SURVEY.md §5): every driver runs on the virtual mesh at a tiny size and
its reported residuals/convergence are checked, not just exit status.
"""
import os
import runpy
import sys

import pytest

_EX = os.path.join(os.path.dirname(__file__), os.pardir, "examples")

_CASES = [
    ("cholesky.py", ["--n", "96"], ["factor_resid", "solve_resid"]),
    ("lu.py", ["--n", "96"], ["factor_resid"]),
    ("qr_least_squares.py", ["--m", "120", "--n", "40"], ["lstsq_err"]),
    ("herm_eig.py", ["--n", "80"], ["resid", "orth"]),
    ("svd.py", ["--m", "90", "--n", "40"], ["reconstruct", "sv_err"]),
    ("lp.py", ["--m", "10", "--n", "24"], ["rel_gap"]),
    ("lav.py", ["--m", "120", "--n", "20", "--nnz", "800"],
     ["recovery_err"]),
    ("rpca.py", ["--m", "40", "--n", "40", "--rank", "2"],
     ["recovery_err"]),
    ("pseudospectra.py", ["--n", "40", "--npts", "6"], []),
    ("spd_scaling_sweep.py", ["--n", "64"], ["resid"]),
]


#: the two heaviest example scripts ride the slow tier (they exercise
#: svd/schur stacks already covered by their own lapack suites).
_SLOW_EXAMPLES = {"rpca.py", "pseudospectra.py"}


@pytest.mark.parametrize(
    "script,argv,metrics",
    [pytest.param(*c, id=c[0],
                  marks=(pytest.mark.slow,) if c[0] in _SLOW_EXAMPLES
                  else ()) for c in _CASES])
def test_example(script, argv, metrics, capsys):
    old_argv = sys.argv
    sys.argv = [script] + argv
    sys.path.insert(0, _EX)
    try:
        runpy.run_path(os.path.join(_EX, script), run_name="__main__")
    finally:
        sys.argv = old_argv
        sys.path.remove(_EX)
    out = capsys.readouterr().out
    assert "[" in out, out
    for key in metrics:
        assert f"{key}=" in out, (key, out)
        val = out.split(f"{key}=")[1].split()[0].rstrip(")")
        if val not in ("True", "False"):
            assert abs(float(val)) < 1e-3, (key, val, out)
    if "converged=" in out:
        assert "converged=True" in out, out
