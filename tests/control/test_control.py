"""Sylvester / Lyapunov / Riccati oracles (residuals + scipy cross-check)."""
import numpy as np
import pytest

import elemental_tpu as el


def _dm(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def _stable(rng, n):
    A = rng.normal(size=(n, n))
    return A - (np.abs(np.linalg.eigvals(A).real).max() + 1) * np.eye(n)


def test_sylvester(grid24):
    scipy_linalg = pytest.importorskip("scipy.linalg")
    rng = np.random.default_rng(0)
    A, B = _stable(rng, 12), _stable(rng, 8)
    C = rng.normal(size=(12, 8))
    X = _t(el.sylvester(_dm(A, grid24), _dm(B, grid24), _dm(C, grid24)))
    assert np.linalg.norm(A @ X + X @ B - C) / np.linalg.norm(C) < 1e-12
    Xs = scipy_linalg.solve_sylvester(A, B, C)
    assert np.linalg.norm(X - Xs) / np.linalg.norm(Xs) < 1e-12


def test_lyapunov(grid24):
    rng = np.random.default_rng(1)
    A = _stable(rng, 12)
    C = rng.normal(size=(12, 12))
    C = C + C.T
    X = _t(el.lyapunov(_dm(A, grid24), _dm(C, grid24)))
    assert np.linalg.norm(A @ X + X @ A.T - C) / np.linalg.norm(C) < 1e-12


@pytest.mark.slow
def test_riccati(grid24):
    scipy_linalg = pytest.importorskip("scipy.linalg")
    rng = np.random.default_rng(2)
    n, k = 8, 3
    A = rng.normal(size=(n, n))
    B = rng.normal(size=(n, k))
    G = B @ B.T
    Q = rng.normal(size=(n, n))
    Q = Q @ Q.T / n + np.eye(n)
    X = _t(el.riccati(_dm(A, grid24), _dm(G, grid24), _dm(Q, grid24)))
    r = A.T @ X + X @ A + Q - X @ G @ X
    assert np.linalg.norm(r) / np.linalg.norm(Q) < 1e-10
    Xs = scipy_linalg.solve_continuous_are(A, B, Q, np.eye(k))
    assert np.linalg.norm(X - Xs) / np.linalg.norm(Xs) < 1e-10
