"""The ``panel_impl`` knob (ISSUE 17): plan resolution, the static
VMEM/dtype dispatch gate, complex fallback, and driver integration.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import elemental_tpu as el
from elemental_tpu import MC, MR, from_global, to_global
from elemental_tpu.kernels import (DEFAULT_INNERS, PANEL_IMPLS, PanelPlan,
                                   default_inners, panel_fits, resolve_panel)


def _dist(g, arr):
    return from_global(arr, MC, MR, grid=g)


# ---------------------------------------------------------------- plan

def test_resolve_defaults():
    plan = resolve_panel(None)
    assert plan.impl == "xla" and plan.source == "default"
    assert plan.inners == DEFAULT_INNERS == default_inners()
    assert resolve_panel("pallas").source == "explicit"
    with pytest.raises(ValueError, match="panel_impl"):
        resolve_panel("mosaic")


def test_complex_resolves_to_xla_silently():
    plan = resolve_panel("pallas", dtype=jnp.complex64)
    assert plan.impl == "xla" and plan.source == "complex-xla"


def test_vmem_gate():
    plan = PanelPlan(impl="pallas")
    assert plan.use_pallas((512, 64), jnp.float32)
    # a panel whose padded working set exceeds the 16 MiB budget must
    # route back to xla -- the fused kernel never silently spills
    assert not plan.use_pallas((1 << 20, 2048), jnp.float32)
    assert not panel_fits((1 << 20, 2048), jnp.float32)
    assert not plan.use_pallas((64, 16), jnp.complex64)
    assert not PanelPlan(impl="xla").use_pallas((64, 16), jnp.float32)


def test_inners_flow_through_plan():
    plan = resolve_panel(None, inners=(768, 96))
    assert plan.inners == (768, 96)
    assert plan.pallas_inner == 96


# ------------------------------------------------------------- tuning

def test_registry_has_panel_impl():
    from elemental_tpu.tune.knobs import OPS
    from elemental_tpu.tune.knobs import PANEL_IMPLS as KNOB_IMPLS
    assert KNOB_IMPLS == PANEL_IMPLS          # mirrored literal stays pinned
    for op in ("lu", "cholesky", "qr"):
        assert "panel_impl" in OPS[op].knobs


def test_auto_resolves_xla_on_cpu_pallas_on_tpu(grid24):
    from elemental_tpu.tune import cost_model as cm
    from elemental_tpu.tune.knobs import TuneContext, candidate_configs

    def best(op, backend, machine):
        ctx = TuneContext(op=op, dims=(64, 64), dtype="float32",
                          grid_shape=(2, 2), backend=backend)
        scored = [cm.score_config(op, cfg, ctx=ctx, grid=grid24,
                                  dtype=jnp.float32, machine=machine)
                  for cfg in candidate_configs(ctx)]
        order = sorted(range(len(scored)),
                       key=lambda i: (scored[i].total_s, i))
        return scored[order[0]].config["panel_impl"]

    for op in ("lu", "cholesky", "qr"):
        assert best(op, "cpu", cm.MACHINES["cpu"]) == "xla", op
        assert best(op, "tpu", cm.MACHINES["tpu"]) == "pallas", op


def test_complex_space_is_xla_only():
    from elemental_tpu.tune.knobs import TuneContext, candidate_configs
    ctx = TuneContext(op="cholesky", dims=(64, 64), dtype="complex128",
                      grid_shape=(2, 2), backend="cpu")
    assert {c["panel_impl"] for c in candidate_configs(ctx)} == {"xla"}


# ------------------------------------------------------------- drivers

def test_lu_pallas_matches_xla_pivots(two_grids):
    rng = np.random.default_rng(17)
    F = rng.normal(size=(32, 32))
    A = _dist(two_grids, F)
    LUp, permp = el.lu(A, nb=8, panel_impl="pallas")
    LUx, permx = el.lu(A, nb=8, panel_impl="xla")
    np.testing.assert_array_equal(np.asarray(permp), np.asarray(permx))
    lu_ = np.asarray(to_global(LUp))
    L = np.tril(lu_, -1) + np.eye(32)
    U = np.triu(lu_)
    assert np.linalg.norm(L @ U - F[np.asarray(permp)]) \
        / np.linalg.norm(F) < 1e-12


def test_cholesky_pallas_residual(two_grids):
    rng = np.random.default_rng(18)
    G = rng.normal(size=(32, 32))
    S = G @ G.T / 32 + 32 * np.eye(32)
    L = el.cholesky(_dist(two_grids, S), nb=8, panel_impl="pallas")
    lg = np.asarray(to_global(L))
    assert np.linalg.norm(lg @ lg.T - S) / np.linalg.norm(S) < 1e-12


def test_qr_pallas_matches_xla(two_grids):
    rng = np.random.default_rng(19)
    F = rng.normal(size=(32, 32))
    A = _dist(two_grids, F)
    pp, taup = el.qr(A, nb=8, panel_impl="pallas")
    px, taux = el.qr(A, nb=8, panel_impl="xla")
    np.testing.assert_allclose(np.asarray(to_global(pp)),
                               np.asarray(to_global(px)),
                               rtol=0, atol=1e-11)
    np.testing.assert_allclose(np.asarray(taup), np.asarray(taux),
                               rtol=0, atol=1e-13)


def test_complex_driver_falls_back_bitwise(grid24):
    # panel_impl='pallas' on a complex matrix must factor (never raise)
    # and produce EXACTLY the xla path's bits -- the knob is a
    # performance hint, not a semantics switch
    rng = np.random.default_rng(20)
    F = (rng.normal(size=(24, 24)) + 1j * rng.normal(size=(24, 24)))
    A = _dist(grid24, F)
    LUp, permp = el.lu(A, nb=8, panel_impl="pallas")
    LUx, permx = el.lu(A, nb=8, panel_impl="xla")
    np.testing.assert_array_equal(np.asarray(permp), np.asarray(permx))
    assert np.array_equal(np.asarray(to_global(LUp)),
                          np.asarray(to_global(LUx)))


def test_driver_accepts_panel_impl_auto(grid24):
    rng = np.random.default_rng(21)
    F = rng.normal(size=(24, 24))
    LU, perm = el.lu(_dist(grid24, F), nb=8, panel_impl="auto")
    lu_ = np.asarray(to_global(LU))
    L = np.tril(lu_, -1) + np.eye(24)
    U = np.triu(lu_)
    assert np.linalg.norm(L @ U - F[np.asarray(perm)]) \
        / np.linalg.norm(F) < 1e-12


def test_abft_composes_with_pallas(grid24):
    rng = np.random.default_rng(22)
    F = rng.normal(size=(24, 24))
    LU, perm = el.lu(_dist(grid24, F), nb=8, panel_impl="pallas",
                     abft=True)
    lu_ = np.asarray(to_global(LU))
    L = np.tril(lu_, -1) + np.eye(24)
    U = np.triu(lu_)
    assert np.linalg.norm(L @ U - F[np.asarray(perm)]) \
        / np.linalg.norm(F) < 1e-12
