"""Fused Pallas QR panel (ISSUE 17): larfg chain + larft twin contract.

The kernel mirrors ``_panel_qr``'s exact degenerate guards and HIGHEST-
precision dots; on sublane-aligned heights the reductions see identical
extents and the outputs come out bit-identical to the XLA twin, but the
CONTRACT is residual-bounded (padded reductions may group differently),
so the hard assertions here are residuals + orthonormality with the
bit-comparisons as a documented stronger observation.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from elemental_tpu.kernels import qr_panel
from elemental_tpu.lapack.qr import _larft, _panel_qr, _panel_v

F32_TOL = 3e-6
F64_TOL = 1e-12


def _recon(pg, tg, m, k):
    Q = np.eye(m)
    for j in range(k):
        v = np.zeros(m)
        v[j] = 1.0
        v[j + 1:] = pg[j + 1:, j]
        Q = Q @ (np.eye(m) - tg[j] * np.outer(v, v))
    return Q, np.triu(pg[:k, :])


@pytest.mark.parametrize("shape", [
    (64, 16), (40, 8), (33, 7),
    # the wide rungs ride the full ladder in `tools/check.sh kernels`
    pytest.param((96, 32), marks=pytest.mark.slow),
    pytest.param((128, 64), marks=pytest.mark.slow)])
@pytest.mark.parametrize("dtype,tol", [(np.float32, F32_TOL),
                                       (np.float64, F64_TOL)])
def test_residual_and_ortho(shape, dtype, tol):
    m, k = shape
    rng = np.random.default_rng(m + k)
    F = rng.normal(size=(m, k)).astype(dtype)
    packed, tau, T = qr_panel(jnp.asarray(F))
    pg, tg = np.asarray(packed), np.asarray(tau)
    Q, R = _recon(pg, tg, m, k)
    assert np.linalg.norm(Q[:, :k] @ R - F) / np.linalg.norm(F) < tol
    assert np.linalg.norm(Q.T @ Q - np.eye(m)) / np.sqrt(m) < tol
    # the fused T must satisfy the larft identity through the same V
    V = np.tril(pg, -1) + np.eye(m, k)
    Texp = np.asarray(_larft(jnp.asarray(V.astype(dtype)),
                             jnp.asarray(tg)))
    np.testing.assert_allclose(np.asarray(T), Texp, rtol=0,
                               atol=(1e-5 if dtype == np.float32 else 1e-12))


@pytest.mark.parametrize("shape", [
    (64, 16), (96, 32),
    pytest.param((128, 64), marks=pytest.mark.slow)])
def test_bit_identical_on_aligned_heights(shape):
    # sublane-multiple heights: padded reduction extents match the
    # logical ones exactly, so the twin outputs are bitwise equal --
    # stronger than the contract, pinned so a regression is a loud diff
    m, k = shape
    rng = np.random.default_rng(m * k)
    F = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32))
    packed, tau, T = qr_panel(F)
    packed_x, tau_x = _panel_qr(F)
    T_x = _larft(_panel_v(packed_x), tau_x)
    assert np.array_equal(np.asarray(packed), np.asarray(packed_x))
    assert np.array_equal(np.asarray(tau), np.asarray(tau_x))
    assert np.array_equal(np.asarray(T), np.asarray(T_x))


def test_graded_columns():
    # columns scaled across 10 orders of magnitude: the larfg guards
    # (degenerate norm, sign-of-alpha) must hold as in the reference
    m, k = 64, 16
    rng = np.random.default_rng(5)
    F = rng.normal(size=(m, k)) * np.logspace(0, -10, k)[None, :]
    F = F.astype(np.float64)
    packed, tau, T = qr_panel(jnp.asarray(F))
    Q, R = _recon(np.asarray(packed), np.asarray(tau), m, k)
    assert np.linalg.norm(Q[:, :k] @ R - F) / np.linalg.norm(F) < F64_TOL


def test_zero_column_degenerate():
    # an exactly-zero column hits the degenerate larfg branch: tau = 0,
    # beta = 0, matching the reference guard; the surrounding columns
    # stay within the residual contract (the lane-padded w-dot groups
    # its reduction differently here, so no bit pin)
    m, k = 32, 8
    rng = np.random.default_rng(6)
    F = rng.normal(size=(m, k)).astype(np.float32)
    F[:, 3] = 0.0
    packed, tau, T = qr_panel(jnp.asarray(F))
    packed_x, tau_x = _panel_qr(jnp.asarray(F))
    assert np.asarray(tau)[3] == np.asarray(tau_x)[3] == 0.0
    np.testing.assert_allclose(np.asarray(packed), np.asarray(packed_x),
                               rtol=0, atol=1e-6)


def test_complex_raises():
    P = jnp.ones((16, 4), jnp.complex64)
    with pytest.raises(ValueError, match="real-only"):
        qr_panel(P)
