"""Comm-plan invariance under ``panel_impl='pallas'`` (ISSUE 17).

Panels are replicated-local compute and ``pallas_call`` is a local
primitive with no collectives, so selecting the fused kernels must not
move a single byte of any traced comm plan.  The tier-1 subset here
covers one variant per schedule family on both golden grids; the full
variant sweep is the ``tools/check.sh kernels`` gate.
"""
import json
import os

import pytest

import jax

from elemental_tpu import analysis as an
from elemental_tpu.analysis import diff_docs, golden_doc
from elemental_tpu.analysis.drivers import panel_impl_override
from elemental_tpu.core.grid import Grid

_GOLDEN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "golden", "comm_plans")

#: one variant per schedule family: classic + pipelined lu, pipelined
#: cholesky, plain + tree-panel qr, and one abft transaction
VARIANTS = ("lu_classic", "lu_crossover", "cholesky_lookahead",
            "qr", "qr_tsqr", "lu_abft")


def _grid(r, c):
    return Grid(jax.devices()[: r * c], height=r)


#: tier-1 keeps every variant on 1x1 plus the two main schedule families
#: on 2x2; the remaining 2x2 traces are slow-marked and run (with the
#: full 14-variant sweep) in `tools/check.sh kernels`
_CASES = [(v, (1, 1)) for v in VARIANTS] + [
    ("lu_classic", (2, 2)), ("qr", (2, 2))] + [
    pytest.param(v, (2, 2), marks=pytest.mark.slow)
    for v in VARIANTS if v not in ("lu_classic", "qr")]


@pytest.mark.parametrize("variant,gshape", _CASES)
def test_plan_bytes_invariant(variant, gshape):
    base, _, _ = an.trace_driver(variant, _grid(*gshape))
    base_blob = json.dumps(golden_doc(base), indent=1)
    with panel_impl_override("pallas"):
        plan, _, _ = an.trace_driver(variant, _grid(*gshape))
    doc = golden_doc(plan)
    assert json.dumps(doc, indent=1) == base_blob, \
        f"{variant} {gshape}: plan doc changed under panel_impl='pallas'"
    # and the override-traced plan still passes the repo's golden gate
    path = os.path.join(_GOLDEN, f"{variant}__{gshape[0]}x{gshape[1]}.json")
    with open(path) as f:
        golden = json.load(f)
    assert not diff_docs(golden, doc)


def test_override_restores():
    from elemental_tpu.analysis import drivers as drv
    assert drv._PANEL_IMPL_OVERRIDE is None
    with panel_impl_override("pallas"):
        assert drv._PANEL_IMPL_OVERRIDE == "pallas"
    assert drv._PANEL_IMPL_OVERRIDE is None
