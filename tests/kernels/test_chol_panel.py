"""Fused Pallas ``potrf_inv`` (ISSUE 17): residual-bounded twin contract.

The in-kernel column/row recurrences round differently from XLA's
native potrf/trsm, so the contract is residual-bounded, not bit-pinned:
``L L^H ~ D`` and ``Li L ~ I`` within small multiples of machine eps,
on random and graded (ill-conditioned diagonal) SPD blocks, across the
block-size ladder including the single-block and unpadded cases.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from elemental_tpu.kernels import potrf_inv
from elemental_tpu.lapack.cholesky import _potrf_inv_impl

#: float32 residual ceilings: measured ~1e-7 at w<=256 (see the r17
#: sweep); 30x headroom keeps the bound meaningful without flaking
F32_TOL = 3e-6
F64_TOL = 1e-12


def _spd(w, dtype, graded=False, seed=0):
    rng = np.random.default_rng(seed + w)
    G = rng.normal(size=(w, w)).astype(dtype)
    D = G @ G.T / w + w * np.eye(w, dtype=dtype)
    if graded:
        # graded scaling: diag spans 12 orders of magnitude -- the
        # ill-conditioned class where a sloppy recurrence loses the
        # factorization entirely rather than a few ulps
        s = np.logspace(0, -12, w).astype(dtype)
        D = (D * s[:, None]) * s[None, :]
    return D.astype(dtype)


@pytest.mark.parametrize("w,bs", [
    (48, 16), (96, 32), (16, 512), (128, 64),
    # the single-block and large unpadded rungs ride the full ladder in
    # `tools/check.sh kernels`
    pytest.param(64, 512, marks=pytest.mark.slow),
    pytest.param(128, 512, marks=pytest.mark.slow),
    pytest.param(256, 128, marks=pytest.mark.slow)])
@pytest.mark.parametrize("dtype,tol", [(np.float32, F32_TOL),
                                       (np.float64, F64_TOL)])
def test_residual_random_spd(w, bs, dtype, tol):
    D = _spd(w, dtype)
    L, Li = potrf_inv(jnp.asarray(D), bs=bs)
    L, Li = np.asarray(L), np.asarray(Li)
    assert np.linalg.norm(L @ L.T - D) / np.linalg.norm(D) < tol
    assert np.linalg.norm(Li @ L - np.eye(w)) / np.sqrt(w) < tol


@pytest.mark.parametrize("w,bs", [(64, 16), (96, 512)])
def test_residual_graded_spd(w, bs):
    # relative residual survives grading because both twins factor the
    # SAME symmetrized block; compare against the XLA twin's residual
    # rather than an absolute bound
    D = _spd(w, np.float64, graded=True)
    L, Li = potrf_inv(jnp.asarray(D), bs=bs)
    Lr, _ = _potrf_inv_impl(jnp.asarray(D), None, bs=bs)
    L, Lr = np.asarray(L), np.asarray(Lr)
    res = np.linalg.norm(L @ L.T - D) / np.linalg.norm(D)
    res_ref = np.linalg.norm(Lr @ Lr.T - D) / np.linalg.norm(D)
    assert res < max(10 * res_ref, F64_TOL)


def test_matches_reference_closely():
    D = _spd(96, np.float64)
    L, Li = potrf_inv(jnp.asarray(D), bs=32)
    Lr, Lir = _potrf_inv_impl(jnp.asarray(D), None, bs=32)
    np.testing.assert_allclose(np.asarray(L), np.asarray(Lr),
                               rtol=0, atol=1e-10)
    np.testing.assert_allclose(np.asarray(Li), np.asarray(Lir),
                               rtol=0, atol=1e-8)


def test_complex_raises():
    D = jnp.eye(16, dtype=jnp.complex64)
    with pytest.raises(ValueError, match="complex"):
        potrf_inv(D)
