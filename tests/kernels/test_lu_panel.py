"""Fused Pallas LU panel (ISSUE 17): the bit-twin contract.

The unblocked fused kernel mirrors ``lapack.lu._panel_lu_unb`` op-for-op
-- no reductions, same argmax tie-breaking -- so the pivot sequence AND
the packed panel must be BIT-identical, including on constructed
|pivot| ties.  The chunked mode reorders the forward-substitution dots,
so it is residual-bounded instead.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from elemental_tpu.kernels import lu_panel
from elemental_tpu.lapack.lu import _panel_lu, _panel_lu_unb


@pytest.mark.parametrize("shape,nbw", [
    ((64, 16), 16), ((40, 40), 40), ((8, 3), 3), ((33, 7), 7),
    # the wide rungs ride the full ladder in `tools/check.sh kernels`
    pytest.param((96, 32), 32, marks=pytest.mark.slow),
    pytest.param((128, 64), 64, marks=pytest.mark.slow)])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_unblocked_bit_identical(shape, nbw, dtype):
    rng = np.random.default_rng(sum(shape))
    P = jnp.asarray(rng.normal(size=shape).astype(dtype))
    packed_p, perm_p = lu_panel(P, nbw)
    packed_x, perm_x = _panel_lu_unb(P, nbw)
    np.testing.assert_array_equal(np.asarray(perm_p), np.asarray(perm_x))
    assert np.array_equal(np.asarray(packed_p), np.asarray(packed_x)), \
        "packed panel must be BIT-identical to _panel_lu_unb"


def test_pivot_ties_break_identically():
    # columns engineered so several rows tie on |value| at each pivot
    # search: jnp.argmax takes the FIRST max, and the fused kernel must
    # inherit exactly that choice
    m, w = 32, 8
    P = np.zeros((m, w), dtype=np.float32)
    rng = np.random.default_rng(3)
    for j in range(w):
        P[:, j] = rng.integers(1, 4, size=m).astype(np.float32)
        P[j::5, j] = 3.0                     # repeated maxima
        P[:, j] *= np.sign(rng.normal(size=m)) + 0.5
    P = jnp.asarray(P)
    packed_p, perm_p = lu_panel(P, w)
    packed_x, perm_x = _panel_lu_unb(P, w)
    np.testing.assert_array_equal(np.asarray(perm_p), np.asarray(perm_x))
    assert np.array_equal(np.asarray(packed_p), np.asarray(packed_x))


@pytest.mark.parametrize("inner", [8, 16, 32])
def test_chunked_residual_and_pivots(inner):
    m, w = 96, 64
    rng = np.random.default_rng(9)
    F = rng.normal(size=(m, w)).astype(np.float32)
    packed, perm = lu_panel(jnp.asarray(F), w, inner=inner)
    lu_ = np.asarray(packed)
    p = np.asarray(perm)
    L = np.tril(lu_[:, :w], -1) + np.eye(m, w)
    U = np.triu(lu_[:w, :])
    assert np.linalg.norm(F[p] - L @ U) / np.linalg.norm(F) < 1e-5
    # chunked pivoting IS the unblocked pivoting (chunking only reorders
    # the trailing updates, not the per-column search)
    _, perm_ref = _panel_lu(jnp.asarray(F), w, None, (inner,))
    np.testing.assert_array_equal(p, np.asarray(perm_ref))


def test_complex_raises():
    P = jnp.ones((16, 4), jnp.complex64)
    with pytest.raises(ValueError, match="complex"):
        lu_panel(P, 4)
