"""Sparse-operand IPM oracles (VERDICT r4 item 3).

Reference style: the upstream sparse IPMs are exercised through the model
drivers (``examples/optimization/LAV.cpp`` etc.) printing duality-gap
convergence; here the oracles are scipy/HiGHS objective agreement plus
the "Done" criterion: sparse LAV/BP on 10k x 5k operands converging to
duality gap < 1e-6 on the 8-device mesh.
"""
import numpy as np
import pytest
import scipy.sparse as sp
from scipy.optimize import linprog

import elemental_tpu as el
from elemental_tpu.core.multivec import mv_from_global, mv_to_global
from elemental_tpu.sparse.core import dist_sparse_from_coo
from elemental_tpu.optimization.util import MehrotraCtrl


def _rand_sparse(rng, m, n, nnz):
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.normal(size=nnz)
    return rows, cols, vals


@pytest.mark.parametrize("kkt", ["direct", "cg"])
def test_lp_sparse_oracle(grid24, kkt):
    """Both KKT engines: the host sparse-direct factorization AND the
    fully-distributed jitted while_loop CG (each must converge alone)."""
    rng = np.random.default_rng(0)
    m, n, nnz = 40, 100, 400
    rows, cols, vals = _rand_sparse(rng, m, n, nnz)
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    x0 = rng.uniform(0.5, 1.5, n)
    b = As @ x0
    c = As.T @ rng.normal(size=m) + rng.uniform(0.1, 2.0, n)
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, y, z, info = el.lp_sparse(
        A, mv_from_global(b.reshape(-1, 1), grid=grid24),
        mv_from_global(c.reshape(-1, 1), grid=grid24),
        MehrotraCtrl(tol=1e-6, max_iters=60), kkt=kkt)
    assert info["converged"], info
    if kkt == "cg":
        assert info["cg_iters"] > 0        # the device CG actually ran
    res = linprog(c, A_eq=As.toarray(), b_eq=b, bounds=[(0, None)] * n,
                  method="highs")
    assert res.status == 0
    xg = np.asarray(mv_to_global(x)).ravel()
    assert abs(c @ xg - res.fun) / (1 + abs(res.fun)) < 1e-5


def test_lp_sparse_badly_scaled(grid24):
    """Ruiz preprocessing (on triplets) handles 1e+-5 row scaling."""
    rng = np.random.default_rng(1)
    m, n, nnz = 30, 80, 320
    rows, cols, vals = _rand_sparse(rng, m, n, nnz)
    rsc = np.exp(rng.uniform(-5, 5, m))
    vals = vals * rsc[rows]
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    x0 = rng.uniform(0.5, 1.5, n)
    b = As @ x0
    c = As.T @ rng.normal(size=m) + rng.uniform(0.1, 2.0, n)
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, y, z, info = el.lp_sparse(
        A, mv_from_global(b.reshape(-1, 1), grid=grid24),
        mv_from_global(c.reshape(-1, 1), grid=grid24),
        MehrotraCtrl(tol=1e-6, max_iters=60))
    assert info["converged"], info
    res = linprog(c, A_eq=As.toarray(), b_eq=b, bounds=[(0, None)] * n,
                  method="highs")
    xg = np.asarray(mv_to_global(x)).ravel()
    assert abs(c @ xg - res.fun) / (1 + abs(res.fun)) < 1e-4


def test_bp_sparse_recovery(grid24):
    """BP on a wide sparse operator recovers a sparse signal (classic
    compressed-sensing oracle: the l1 minimizer matches HiGHS)."""
    rng = np.random.default_rng(2)
    m, n = 60, 160
    rows, cols, vals = _rand_sparse(rng, m, n, 900)
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    xs = np.zeros(n)
    sup = rng.choice(n, 6, replace=False)
    xs[sup] = rng.normal(size=6) * 3
    b = As @ xs
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, info = el.bp_sparse(A, mv_from_global(b.reshape(-1, 1), grid=grid24),
                           MehrotraCtrl(tol=1e-6, max_iters=80))
    assert info["converged"], info
    xg = np.asarray(mv_to_global(x)).ravel()
    assert np.linalg.norm(As @ xg - b) / np.linalg.norm(b) < 1e-5
    # l1-objective oracle via HiGHS on the same split-variable LP
    cc = np.ones(2 * n)
    Aeq = sp.hstack([As, -As]).toarray()
    res = linprog(cc, A_eq=Aeq, b_eq=b, bounds=[(0, None)] * (2 * n),
                  method="highs")
    assert abs(np.abs(xg).sum() - res.fun) / (1 + abs(res.fun)) < 1e-4


def test_lav_sparse_small(grid24):
    rng = np.random.default_rng(3)
    m, n = 80, 30
    rows, cols, vals = _rand_sparse(rng, m, n, 600)
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    xt = rng.normal(size=n)
    b = As @ xt
    out = rng.choice(m, 8, replace=False)
    b[out] += rng.normal(size=8) * 20            # gross outliers
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, info = el.lav_sparse(A, mv_from_global(b.reshape(-1, 1), grid=grid24),
                            MehrotraCtrl(tol=1e-6, max_iters=80))
    assert info["converged"], info
    xg = np.asarray(mv_to_global(x)).ravel()
    # LAV is robust to the outliers: recovers xt to high accuracy
    assert np.linalg.norm(xg - xt) / np.linalg.norm(xt) < 1e-5


@pytest.mark.slow
def test_lav_sparse_10k_x_5k(grid24):
    """The VERDICT 'Done' criterion: sparse LAV at 10k x 5k converges to
    duality gap < 1e-6 on the 8-device mesh -- a problem size whose
    dense normal matrix (10k x 10k from a 30k-variable LP) would be
    outside the dense IPM's practical range here.

    The operand has BANDED structure (each observation touches a window
    of ~10 adjacent features), the shape of real sparse LPs.  A random-
    expander pattern at this size is the worst case for ANY sparse
    factorization (the normal matrix's L factor fills to ~4e7 nnz --
    measured; this is exactly why the reference bundles ParMETIS
    orderings, which also presuppose separator structure)."""
    rng = np.random.default_rng(4)
    m, n, w = 10_000, 5_000, 10
    # each row covers a contiguous feature window (no globally-shared
    # column: a dense column makes the normal matrix dense)
    starts = rng.integers(0, n - w, m)
    rows = np.repeat(np.arange(m), w)
    cols = (starts[:, None] + np.arange(w)[None, :]).reshape(-1)
    vals = rng.normal(size=m * w)
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    xt = rng.normal(size=n)
    b = As @ xt
    out = rng.choice(m, m // 50, replace=False)
    b[out] += rng.normal(size=out.size) * 50
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, info = el.lav_sparse(A, mv_from_global(b.reshape(-1, 1), grid=grid24),
                            MehrotraCtrl(tol=1e-6, max_iters=60))
    assert info["converged"], info
    assert info["rel_gap"] < 1e-6
    xg = np.asarray(mv_to_global(x)).ravel()
    # optimality oracle: the LAV objective at the solution beats the
    # planted point (which pays full price for the outliers)
    assert np.abs(As @ xg - b).sum() \
        <= np.abs(As @ xt - b).sum() * (1 + 1e-6)
    # recovery oracle on identifiable features only (windowed coverage
    # leaves a few columns thin or uncovered; those are free variables)
    cover = np.zeros(n, np.int64)
    np.add.at(cover, cols, 1)
    well = cover >= 10
    assert well.sum() > n // 2
    assert np.linalg.norm((xg - xt)[well]) \
        / np.linalg.norm(xt[well]) < 1e-4


@pytest.mark.slow
def test_bp_sparse_5k_x_10k(grid24):
    """At-scale BP companion to the LAV criterion: wide banded operator,
    sparse signal, duality gap < 1e-6 on the 8-device mesh."""
    rng = np.random.default_rng(5)
    m, n, w = 5_000, 10_000, 12
    starts = rng.integers(0, n - w + 1, m)
    rows = np.repeat(np.arange(m), w)
    cols = (starts[:, None] + np.arange(w)[None, :]).reshape(-1)
    vals = rng.normal(size=m * w)
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    xs = np.zeros(n)
    sup = rng.choice(n, 120, replace=False)
    xs[sup] = rng.normal(size=sup.size) * 3
    b = As @ xs
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, info = el.bp_sparse(A, mv_from_global(b.reshape(-1, 1), grid=grid24),
                           MehrotraCtrl(tol=1e-6, max_iters=80), refine=2)
    # the criterion is the duality gap; primal feasibility floors within
    # ~1e-6 of it (the elimination's ||D^2|| amplification of f64 solves)
    assert info["rel_gap"] < 1e-6, info
    assert info["pfeas"] < 1e-5 and info["dfeas"] < 1e-5, info
    xg = np.asarray(mv_to_global(x)).ravel()
    assert np.linalg.norm(As @ xg - b) / np.linalg.norm(b) < 1e-5
    # the l1 minimizer cannot beat itself: objective <= planted signal
    assert np.abs(xg).sum() <= np.abs(xs).sum() * (1 + 1e-6)


@pytest.mark.slow
def test_lav_sparse_10k_cg_engine(grid24):
    """The DISTRIBUTED engine at scale: the same 10k x 5k LAV driven
    through the jitted while_loop CG only (no host factorization), to
    moderate accuracy -- Krylov iteration counts grow as ~1/sqrt(mu), so
    the terminal 1e-6 regime is the direct engine's job (that is the
    whole reason the reference built reg_ldl)."""
    rng = np.random.default_rng(4)
    m, n, w = 10_000, 5_000, 10
    starts = rng.integers(0, n - w, m)
    rows = np.repeat(np.arange(m), w)
    cols = (starts[:, None] + np.arange(w)[None, :]).reshape(-1)
    vals = rng.normal(size=m * w)
    As = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    xt = rng.normal(size=n)
    b = As @ xt
    A = dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid24,
                             dtype=np.float64)
    x, info = el.lav_sparse(A, mv_from_global(b.reshape(-1, 1), grid=grid24),
                            MehrotraCtrl(tol=1e-3, max_iters=25),
                            kkt="cg", cg_maxiter=4000)
    assert info["cg_iters"] > 0            # the device CG did the work
    assert info["rel_gap"] < 1e-3, info
