"""IPM + prox + model oracles.

Reference test style: the ``examples/optimization/*`` drivers check
objective/duality-gap convergence (SURVEY.md §5); here we add ground-truth
comparisons (KKT conditions, sparse recovery, scipy cross-checks where
available).
"""
import numpy as np
import pytest

import elemental_tpu as el
from elemental_tpu.optimization.util import MehrotraCtrl


def _dm(F, grid):
    return el.from_global(F, el.MC, el.MR, grid=grid)


def _t(A):
    return np.asarray(el.to_global(A))


def _feasible_lp(rng, m, n):
    A = rng.normal(size=(m, n))
    x0 = rng.uniform(0.5, 2.0, size=(n, 1))
    b = A @ x0
    y0 = rng.normal(size=(m, 1))
    z0 = rng.uniform(0.5, 2.0, size=(n, 1))
    c = A.T @ y0 + z0
    return A, b, c


def test_lp_mehrotra(grid24):
    rng = np.random.default_rng(0)
    A, b, c = _feasible_lp(rng, 10, 24)
    x, y, z, info = el.lp(_dm(A, grid24), _dm(b, grid24), _dm(c, grid24))
    assert info["converged"] and info["rel_gap"] < 1e-8
    xg, yg, zg = _t(x), _t(y), _t(z)
    assert np.linalg.norm(A @ xg - b) / np.linalg.norm(b) < 1e-7
    assert np.linalg.norm(A.T @ yg + zg - c) / np.linalg.norm(c) < 1e-7
    assert xg.min() > -1e-10 and zg.min() > -1e-10
    assert abs(float(c.T @ xg) - float(b.T @ yg)) < 1e-6 * (1 + abs(float(c.T @ xg)))


@pytest.mark.slow
def test_lp_vs_scipy(grid24):
    scipy_opt = pytest.importorskip("scipy.optimize")
    rng = np.random.default_rng(1)
    A, b, c = _feasible_lp(rng, 8, 20)
    x, _, _, info = el.lp(_dm(A, grid24), _dm(b, grid24), _dm(c, grid24))
    res = scipy_opt.linprog(c.ravel(), A_eq=A, b_eq=b.ravel(),
                            bounds=(0, None), method="highs")
    assert abs(float(c.T @ _t(x)) - res.fun) < 1e-6 * (1 + abs(res.fun))


def test_qp_equality(grid24):
    rng = np.random.default_rng(2)
    n, m = 12, 4
    G0 = rng.normal(size=(n, n))
    Q = G0 @ G0.T / n + np.eye(n)
    A = rng.normal(size=(m, n))
    b = A @ rng.uniform(0.5, 1.5, size=(n, 1))
    c = rng.normal(size=(n, 1))
    x, y, z, info = el.qp(_dm(Q, grid24), _dm(c, grid24), _dm(A, grid24),
                          _dm(b, grid24))
    assert info["converged"]
    xg, yg, zg = _t(x), _t(y), _t(z)
    assert np.linalg.norm(A @ xg - b) < 1e-7 * (1 + np.linalg.norm(b))
    # stationarity: Qx + c - A^T y - z = 0
    r = Q @ xg + c - A.T @ yg - zg
    assert np.linalg.norm(r) < 1e-6 * (1 + np.linalg.norm(c))
    assert float(xg.T @ zg) < 1e-6


@pytest.mark.slow
def test_nnls(grid24):
    rng = np.random.default_rng(3)
    A = rng.normal(size=(20, 10))
    b = rng.normal(size=(20, 1))
    x, info = el.nnls(_dm(A, grid24), _dm(b, grid24))
    xg = _t(x)
    assert xg.min() > -1e-9
    scipy_opt = pytest.importorskip("scipy.optimize")
    xs, _ = scipy_opt.nnls(A, b.ravel())
    assert np.linalg.norm(xg.ravel() - xs) < 1e-6


@pytest.mark.slow
def test_bp_sparse_recovery(grid24):
    rng = np.random.default_rng(4)
    m, n = 10, 24
    A = rng.normal(size=(m, n))
    x_true = np.zeros((n, 1))
    x_true[[2], [0]] = 1.5
    x_true[[9], [0]] = -2.0
    x_true[[17], [0]] = 0.7
    b = A @ x_true
    x, info = el.bp(_dm(A, grid24), _dm(b, grid24))
    assert np.linalg.norm(_t(x) - x_true) < 1e-6


@pytest.mark.slow
def test_lav_outlier_robust(grid24):
    rng = np.random.default_rng(5)
    A = rng.normal(size=(24, 6))
    x_true = rng.normal(size=(6, 1))
    b = A @ x_true
    b[3] += 10.0                              # gross outlier
    x, info = el.lav(_dm(A, grid24), _dm(b, grid24))
    assert np.linalg.norm(_t(x) - x_true) < 1e-6


@pytest.mark.slow
def test_lasso_shrinks(grid24):
    rng = np.random.default_rng(6)
    A = rng.normal(size=(16, 8))
    b = rng.normal(size=(16, 1))
    x, info = el.lasso(_dm(A, grid24), _dm(b, grid24), lam=2.0)
    xg = _t(x)
    # KKT: |A^T(Ax - b)| <= lam (+ slack at active entries)
    kkt = A.T @ (A @ xg - b)
    assert np.all(np.abs(kkt) <= 2.0 + 1e-6)


@pytest.mark.slow
def test_svm_separable(grid24):
    rng = np.random.default_rng(7)
    X = np.vstack([rng.normal(size=(12, 4)) + 2,
                   rng.normal(size=(12, 4)) - 2])
    y = np.concatenate([np.ones(12), -np.ones(12)])
    w, bias, info = el.svm(_dm(X, grid24), y, C=10.0)
    pred = np.sign(X @ _t(w).ravel() + bias)
    assert (pred == y).all()


@pytest.mark.slow
def test_rpca_recovery(grid24):
    rng = np.random.default_rng(8)
    n = 60
    L0 = rng.normal(size=(n, 3)) @ rng.normal(size=(3, n))
    S0 = np.zeros((n, n))
    idx = rng.choice(n * n, n * n // 20, replace=False)
    S0.flat[idx] = rng.normal(size=len(idx)) * 5
    L, S, info = el.rpca(_dm(L0 + S0, grid24), tol=1e-7)
    assert info["converged"]
    assert np.linalg.norm(_t(L) - L0) / np.linalg.norm(L0) < 1e-5


@pytest.mark.slow
def test_prox_operators(grid24):
    rng = np.random.default_rng(9)
    F = rng.normal(size=(9, 7))
    A = _dm(F, grid24)
    st = _t(el.soft_threshold(A, 0.5))
    assert np.allclose(st, np.sign(F) * np.maximum(np.abs(F) - 0.5, 0))
    from elemental_tpu.optimization.prox import clip, svt
    cl = _t(clip(A, -0.3, 0.3))
    assert np.allclose(cl, np.clip(F, -0.3, 0.3))
    # SVT: singular values soft-thresholded
    sv = _t(svt(A, 0.8))
    U, s, Vh = np.linalg.svd(F, full_matrices=False)
    ref = (U * np.maximum(s - 0.8, 0)) @ Vh
    assert np.linalg.norm(sv - ref) < 1e-9


def test_logistic_prox(grid24):
    """prox minimizes rho/2 (x-a)^2 + log(1+e^{-x}) -- check against a
    dense grid search."""
    from elemental_tpu.optimization.prox import logistic_prox
    rng = np.random.default_rng(10)
    F = rng.normal(size=(5, 3)) * 2
    A = _dm(F, grid24)
    rho = 0.5
    got = _t(logistic_prox(A, rho, newton_iters=30))
    grid_x = np.linspace(-20, 20, 400001)
    for a, x in zip(F.ravel(), got.ravel()):
        obj = rho / 2 * (grid_x - a) ** 2 + np.log1p(np.exp(-grid_x))
        assert abs(x - grid_x[np.argmin(obj)]) < 1e-3


def _soc_interior(fi, n, seed):
    v = np.zeros(n)
    r2 = np.random.default_rng(seed)
    for h in np.unique(fi):
        sel = fi == h
        k = sel.sum()
        t = r2.normal(size=k - 1) * 0.3
        v[np.where(sel)[0][1:]] = t
        v[h] = np.linalg.norm(t) + 1.0
    return v


def test_soc_utilities(grid24):
    from elemental_tpu.optimization.soc import (
        make_cone_layout, soc_dets, soc_apply, soc_inverse, soc_identity,
        soc_max_step, soc_nesterov_todd, _arrow_matrix)
    sizes = [3, 5, 2]
    orders, fi = make_cone_layout(sizes)
    n = 10
    x = _soc_interior(fi, n, 1)
    z = _soc_interior(fi, n, 2)
    e = soc_identity(fi, n)
    assert np.allclose(soc_apply(x, soc_inverse(x, fi), fi), e, atol=1e-12)
    w = soc_nesterov_todd(x, z, fi)
    Qw = _arrow_matrix(w, orders, fi)
    assert np.linalg.norm(Qw @ z - x) < 1e-12       # NT defining identity
    assert abs(soc_max_step(x, -x, fi, cap=10.0) - 1.0) < 1e-10
    assert soc_max_step(x, _soc_interior(fi, n, 3), fi, cap=7.0) == 7.0


def test_socp(grid24):
    from elemental_tpu.optimization.soc import socp, make_cone_layout
    rng = np.random.default_rng(20)
    sizes = [3, 4, 3]
    n, m = 10, 4
    orders, fi = make_cone_layout(sizes)
    x0 = _soc_interior(fi, n, 4)
    z0 = _soc_interior(fi, n, 5)
    A = rng.normal(size=(m, n))
    b = (A @ x0).reshape(-1, 1)
    c = (A.T @ rng.normal(size=m) + z0).reshape(-1, 1)
    x, y, z, info = el.socp(_dm(A, grid24), _dm(b, grid24), _dm(c, grid24),
                            sizes, ctrl=MehrotraCtrl(tol=1e-7))
    assert info["converged"] or info.get("stalled")
    assert info["rel_gap"] < 1e-6
    xg = _t(x).ravel()
    yg = _t(y).ravel()
    zg = _t(z).ravel()
    assert np.linalg.norm(A @ xg - b.ravel()) < 1e-5
    assert np.linalg.norm(A.T @ yg + zg - c.ravel()) < 1e-5
    assert abs(xg @ zg) < 1e-5
    # cone membership of the solution
    from elemental_tpu.optimization.soc import soc_dets
    assert np.all(soc_dets(xg, fi) > -1e-9)
