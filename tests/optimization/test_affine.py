"""Affine-form IPM + equilibration oracles.

Reference test style (SURVEY.md §5): objective/duality-gap convergence
checks against scipy/cvx-style reference solutions computed with numpy,
plus the badly-scaled problems (rows/cols spanning 1e+-6) that upstream's
Ruiz equilibration exists to handle (VERDICT r4 item 4).
"""
import numpy as np

import elemental_tpu as el


def _g(F, grid):
    return el.from_global(np.asarray(F, np.float64), el.MC, el.MR, grid=grid)


def _vec(v, grid):
    return _g(np.asarray(v).reshape(-1, 1), grid)


# ---------------------------------------------------------------------
# equilibration
# ---------------------------------------------------------------------

def test_ruiz_unit_norms(grid24):
    rng = np.random.default_rng(0)
    A = rng.normal(size=(12, 20)) * np.exp(rng.uniform(-6, 6, (12, 1))) \
        * np.exp(rng.uniform(-6, 6, (1, 20)))
    As, dr, dc = el.ruiz_equil(_g(A, grid24))
    Ag = np.asarray(el.to_global(As))
    assert np.allclose(Ag, np.asarray(dr)[:, None] * A * np.asarray(dc))
    rowm = np.abs(Ag).max(axis=1)
    colm = np.abs(Ag).max(axis=0)
    # Ruiz converges linearly; 6 sweeps land within ~15% of unit norms
    # (vs the 1e12 dynamic range of the input scaling)
    assert np.all(np.abs(rowm - 1) < 0.15)
    assert np.all(np.abs(colm - 1) < 0.15)


def test_geom_equil_shrinks_range(grid24):
    rng = np.random.default_rng(1)
    A = rng.normal(size=(16, 16)) * np.exp(rng.uniform(-5, 5, (16, 1)))
    As, dr, dc = el.geom_equil(_g(A, grid24))
    Ag = np.asarray(el.to_global(As))
    def dyn(M):
        a = np.abs(M[M != 0])
        return a.max() / a.min()
    assert dyn(Ag) < dyn(A)


def test_symmetric_ruiz(grid24):
    rng = np.random.default_rng(2)
    Q0 = rng.normal(size=(18, 18))
    Q = Q0 @ Q0.T + 18 * np.eye(18)
    s = np.exp(rng.uniform(-4, 4, 18))
    Qbad = s[:, None] * Q * s[None, :]
    Qs, d = el.symmetric_ruiz_equil(_g(Qbad, grid24))
    Qg = np.asarray(el.to_global(Qs))
    assert np.allclose(Qg, Qg.T, atol=1e-10)           # symmetry preserved
    assert np.abs(np.abs(Qg).max(axis=1) - 1).max() < 0.15


# ---------------------------------------------------------------------
# affine-form LP / QP / SOCP
# ---------------------------------------------------------------------

def _box_lp(grid, m=6, n=14, seed=3):
    """min c'x st Ax=b, 0 <= x <= u encoded affine: G = [-I; I], h=[0; u]."""
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(m, n))
    x0 = rng.uniform(0.2, 0.8, n)
    b = A @ x0
    c = rng.normal(size=n)
    u = np.ones(n)
    G = np.vstack([-np.eye(n), np.eye(n)])
    h = np.concatenate([np.zeros(n), u])
    return A, G, b, c, h


def _lp_oracle(A, G, b, c, h):
    from scipy.optimize import linprog
    res = linprog(c, A_ub=G, b_ub=h, A_eq=A, b_eq=b,
                  bounds=[(None, None)] * A.shape[1], method="highs")
    assert res.status == 0
    return res.fun, res.x


def test_lp_affine(grid24):
    A, G, b, c, h = _box_lp(grid24)
    x, y, z, s, info = el.lp_affine(_g(A, grid24), _g(G, grid24),
                                    _vec(b, grid24), _vec(c, grid24),
                                    _vec(h, grid24))
    fref, xref = _lp_oracle(A, G, b, c, h)
    assert info["converged"], info
    assert abs(c @ x - fref) / (1 + abs(fref)) < 1e-6
    assert np.linalg.norm(A @ x - b) < 1e-6
    assert np.all(G @ x - h < 1e-6)


def test_lp_affine_badly_scaled(grid24):
    """Rows/cols spanning 1e+-6: unsolvable without equilibration at f64
    normal-equation conditioning, fine with Ruiz (the VERDICT #4 oracle)."""
    A, G, b, c, h = _box_lp(grid24, seed=4)
    rng = np.random.default_rng(5)
    rs = np.exp(rng.uniform(-6, 6, A.shape[0]))
    A2 = rs[:, None] * A
    b2 = rs * b
    x, y, z, s, info = el.lp_affine(_g(A2, grid24), _g(G, grid24),
                                    _vec(b2, grid24), _vec(c, grid24),
                                    _vec(h, grid24))
    fref, xref = _lp_oracle(A2, G, b2, c, h)
    assert info["converged"], info
    assert abs(c @ x - fref) / (1 + abs(fref)) < 1e-5
    assert np.linalg.norm(A2 @ x - b2) / max(np.linalg.norm(b2), 1) < 1e-6


def test_qp_affine_matches_kkt(grid24):
    """Box QP: min 1/2 x'Qx + c'x st 0<=x<=1; verify the KKT conditions."""
    rng = np.random.default_rng(6)
    n = 10
    Q0 = rng.normal(size=(n, n))
    Q = Q0 @ Q0.T + n * np.eye(n)
    c = rng.normal(size=n)
    A = np.ones((1, n))
    b = np.array([n / 2.0])
    G = np.vstack([-np.eye(n), np.eye(n)])
    h = np.concatenate([np.zeros(n), np.ones(n)])
    x, y, z, s, info = el.qp_affine(_g(Q, grid24), _g(A, grid24),
                                    _g(G, grid24), _vec(b, grid24),
                                    _vec(c, grid24), _vec(h, grid24))
    assert info["converged"], info
    # KKT: Qx + c + A'y + G'z = 0, z >= 0, z.(h - Gx) ~= 0
    kkt = Q @ x + c + A.T @ y + G.T @ z
    assert np.linalg.norm(kkt) < 1e-5
    assert np.all(z > -1e-8)
    assert abs(z @ (h - G @ x)) < 1e-5


def _cone_interior(rng, orders):
    parts = []
    for k in orders:
        v = rng.normal(size=k)
        v[0] = np.linalg.norm(v[1:]) + rng.uniform(0.5, 2.0)
        parts.append(v)
    return np.concatenate(parts)


def test_socp_affine(grid24):
    """Well-posed SOCP built from a strictly feasible primal-dual pair
    (h = Gx0 + s0, b = Ax0, c = -A'y0 - G'z0): strong duality holds, so
    the oracle is the full KKT system at the returned point."""
    rng = np.random.default_rng(7)
    orders = [3, 4, 2]
    k = sum(orders)
    n, m = 6, 2
    A = rng.normal(size=(m, n))
    G = rng.normal(size=(k, n))
    x0 = rng.normal(size=n)
    y0 = rng.normal(size=m)
    s0 = _cone_interior(rng, orders)
    z0 = _cone_interior(rng, orders)
    b = A @ x0
    h = G @ x0 + s0
    c = -A.T @ y0 - G.T @ z0
    x, y, z, s, info = el.socp_affine(_g(A, grid24), _g(G, grid24),
                                      _vec(b, grid24), _vec(c, grid24),
                                      _vec(h, grid24), orders)
    assert info["converged"], info
    assert np.linalg.norm(A @ x - b) < 1e-6
    assert np.linalg.norm(G @ x + s - h) < 1e-6
    assert np.linalg.norm(c + A.T @ y + G.T @ z) < 1e-5
    at = 0
    for kk in orders:       # cone membership of s and z
        assert s[at] >= np.linalg.norm(s[at + 1:at + kk]) - 1e-7
        assert z[at] >= np.linalg.norm(z[at + 1:at + kk]) - 1e-7
        at += kk
    assert abs(s @ z) < 1e-5                        # complementarity


def test_direct_lp_badly_scaled_with_ruiz(grid24):
    """The direct-form lp() now equilibrates by default: a 1e+-6 row/col
    scaled problem converges (it stalls with equilibrate=False)."""
    rng = np.random.default_rng(8)
    m, n = 8, 20
    A = rng.normal(size=(m, n))
    x0 = rng.uniform(0.5, 1.5, n)
    b = A @ x0
    # dual-feasible c (= A'y0 + z0, z0 > 0): strong duality guaranteed
    c = A.T @ rng.normal(size=m) + rng.uniform(0.1, 2.0, n)
    rs = np.exp(rng.uniform(-6, 6, m))
    cs = np.exp(rng.uniform(-3, 3, n))
    A2 = rs[:, None] * A * cs[None, :]
    b2 = rs * b
    c2 = cs * c
    x, y, z, info = el.lp(_g(A2, grid24), _vec(b2, grid24), _vec(c2, grid24))
    from scipy.optimize import linprog
    res = linprog(c2, A_eq=A2, b_eq=b2, bounds=[(0, None)] * n,
                  method="highs")
    assert res.status == 0
    assert info["converged"], info
    assert abs(c2 @ np.asarray(el.to_global(x)).ravel() - res.fun) \
        / (1 + abs(res.fun)) < 1e-5


def test_direct_qp_badly_scaled_with_ruiz(grid24):
    """Direct qp() equilibrates by default (symmetric Ruiz on Q + shared
    column scale on A)."""
    rng = np.random.default_rng(9)
    n, m = 12, 3
    Q0 = rng.normal(size=(n, n))
    Q = Q0 @ Q0.T + n * np.eye(n)
    sc = np.exp(rng.uniform(-4, 4, n))
    Qb = sc[:, None] * Q * sc[None, :]
    A = rng.normal(size=(m, n))
    x0 = rng.uniform(0.5, 1.5, n)
    b = A @ x0
    cvec = rng.normal(size=n)
    x, y, z, info = el.qp(_g(Qb, grid24), _vec(cvec, grid24),
                          _g(A, grid24), _vec(b, grid24))
    assert info["converged"], info
    xg = np.asarray(el.to_global(x)).ravel()
    zg = np.asarray(el.to_global(z)).ravel()
    yg = np.asarray(el.to_global(y)).ravel()
    # KKT: Qx + c - A'y - z = 0, x,z >= 0, x.z ~ 0, Ax = b
    assert np.linalg.norm(Qb @ xg + cvec - A.T @ yg - zg) \
        / max(np.linalg.norm(cvec), 1) < 1e-5
    assert np.linalg.norm(A @ xg - b) / max(np.linalg.norm(b), 1) < 1e-6
    assert xg.min() > -1e-8 and zg.min() > -1e-8
    assert abs(xg @ zg) < 1e-5 * n


def test_direct_socp_equilibrated(grid24):
    """Direct socp() with cone-aware Ruiz matches its own un-equilibrated
    answer on a well-scaled problem (cross-check), and converges on a
    row-scaled one."""
    rng = np.random.default_rng(10)
    orders = [3, 3]
    n = 6; m = 2
    A = rng.normal(size=(m, n))
    x0 = np.concatenate([[2.0, 0.3, 0.1], [1.5, -0.2, 0.4]])
    b = A @ x0
    z0 = np.concatenate([[1.0, 0.2, -0.1], [1.2, 0.3, 0.2]])
    y0 = rng.normal(size=m)
    c = A.T @ y0 + z0
    rs = np.exp(rng.uniform(-3, 3, m))
    A2 = rs[:, None] * A
    b2 = rs * b
    x, y, z, info = el.socp(_g(A2, grid24), _vec(b2, grid24),
                            _vec(c, grid24), orders)
    assert info["converged"], info
    xg = np.asarray(el.to_global(x)).ravel()
    assert np.linalg.norm(A2 @ xg - b2) / max(np.linalg.norm(b2), 1) < 1e-6
    at = 0
    for k in orders:
        assert xg[at] >= np.linalg.norm(xg[at + 1:at + k]) - 1e-7
        at += k
