"""Round-5 model breadth oracles (CP, DS, EN, NMF, SparseInvCov,
LongOnlyPortfolio, TV -- the remaining src/optimization/models/** rows).

Oracles follow SURVEY.md §5: scipy/HiGHS objective agreement where an LP
oracle exists, otherwise optimality conditions / known closed forms.
"""
import numpy as np
import pytest

import elemental_tpu as el


def _g(F, grid):
    return el.from_global(np.atleast_2d(np.asarray(F, np.float64)),
                         el.MC, el.MR, grid=grid)


def test_cp_chebyshev(grid24):
    rng = np.random.default_rng(0)
    m, n = 40, 8
    A = rng.normal(size=(m, n))
    b = rng.normal(size=m)
    x, info = el.cp(_g(A, grid24), _g(b.reshape(-1, 1), grid24))
    assert info["converged"], info
    from scipy.optimize import linprog
    G = np.block([[A, -np.ones((m, 1))], [-A, -np.ones((m, 1))]])
    h = np.concatenate([b, -b])
    c = np.concatenate([np.zeros(n), [1.0]])
    res = linprog(c, A_ub=G, b_ub=h, bounds=[(None, None)] * (n + 1),
                  method="highs")
    assert abs(np.abs(A @ x - b).max() - res.fun) / (1 + res.fun) < 1e-5


def test_ds_dantzig_selector(grid24):
    rng = np.random.default_rng(1)
    m, n = 30, 10
    A = rng.normal(size=(m, n))
    xs = np.zeros(n); xs[[1, 4]] = [2.0, -3.0]
    b = A @ xs
    lam = 0.5
    x, info = el.ds(_g(A, grid24), _g(b.reshape(-1, 1), grid24), lam)
    assert info["converged"], info
    # feasibility + near-support recovery
    assert np.abs(A.T @ (b - A @ x)).max() <= lam + 1e-5
    assert np.abs(x).sum() <= np.abs(xs).sum() + 1e-4


def test_en_elastic_net(grid24):
    rng = np.random.default_rng(2)
    m, n = 40, 12
    A = rng.normal(size=(m, n))
    b = rng.normal(size=m)
    lam1, lam2 = 0.7, 0.3
    x, info = el.en(_g(A, grid24), _g(b.reshape(-1, 1), grid24), lam1, lam2)
    assert info["converged"], info

    def obj(v):
        return 0.5 * np.sum((A @ v - b) ** 2) + lam1 * np.abs(v).sum() \
            + 0.5 * lam2 * np.sum(v * v)
    # subgradient optimality: our objective beats small perturbations
    f0 = obj(x)
    for _ in range(30):
        assert f0 <= obj(x + 1e-3 * rng.normal(size=n)) + 1e-9


@pytest.mark.slow
def test_nmf(grid24):
    rng = np.random.default_rng(3)
    m, n, rk = 30, 24, 4
    W0 = np.abs(rng.normal(size=(m, rk)))
    H0 = np.abs(rng.normal(size=(rk, n)))
    X = W0 @ H0
    W, H, info = el.nmf(_g(X, grid24), rk, max_iters=400)
    Wg = np.asarray(el.to_global(W))
    Hg = np.asarray(el.to_global(H))
    assert np.all(Wg >= 0) and np.all(Hg >= 0)
    assert info["rel_err"] < 5e-2
    assert np.linalg.norm(Wg @ Hg - X) / np.linalg.norm(X) < 5e-2


@pytest.mark.slow
def test_sparse_inv_cov(grid24):
    rng = np.random.default_rng(4)
    n, N = 10, 4000
    # sparse tridiagonal precision matrix ground truth
    P = np.eye(n) * 2.0
    P[np.arange(1, n), np.arange(n - 1)] = 0.6
    P[np.arange(n - 1), np.arange(1, n)] = 0.6
    C = np.linalg.inv(P)
    Xs = rng.multivariate_normal(np.zeros(n), C, size=N)
    S = np.cov(Xs.T)
    lam = 0.05
    X, info = el.sparse_inv_cov(_g(S, grid24), lam, max_iters=200)
    Xg = np.asarray(el.to_global(X))
    assert np.allclose(Xg, Xg.T, atol=1e-8)
    # optimality of the smooth part on the support (KKT of glasso):
    # S - X^{-1} + lam * sign(X) ~ 0 on nonzeros, |.| <= lam on zeros
    Xinv = np.linalg.inv(Xg + 1e-12 * np.eye(n))
    grad = S - Xinv
    on = np.abs(Xg) > 1e-6
    assert np.abs(grad[on] + lam * np.sign(Xg[on])).max() < 5e-2
    assert np.abs(grad[~on]).max() <= lam + 5e-2


def test_long_only_portfolio(grid24):
    rng = np.random.default_rng(5)
    n = 8
    G0 = rng.normal(size=(n, n))
    Sigma = G0 @ G0.T / n + 0.1 * np.eye(n)
    mu = rng.uniform(0.0, 0.2, n)
    x, info = el.long_only_portfolio(_g(Sigma, grid24), mu, gamma=0.5)
    assert info["converged"], info
    assert abs(x.sum() - 1.0) < 1e-6
    assert x.min() > -1e-7
    # objective beats uniform and single-asset corners
    def obj(v):
        return -mu @ v + 0.5 * np.sqrt(v @ Sigma @ v)
    assert obj(x) <= obj(np.ones(n) / n) + 1e-6
    for i in range(n):
        e = np.zeros(n); e[i] = 1.0
        assert obj(x) <= obj(e) + 1e-6


@pytest.mark.slow
def test_tv_denoise(grid24):
    rng = np.random.default_rng(6)
    n = 60
    truth = np.concatenate([np.zeros(n // 3), np.ones(n // 3) * 2,
                            np.zeros(n - 2 * (n // 3))])
    b = truth + 0.15 * rng.normal(size=n)
    x, info = el.tv(b, lam=1.0, grid=grid24)
    assert info["converged"], info
    # denoised signal is closer to the truth than the data, and
    # piecewise-flat (small total variation)
    assert np.linalg.norm(x - truth) < np.linalg.norm(b - truth)
    assert np.abs(np.diff(x)).sum() < np.abs(np.diff(b)).sum() / 3
