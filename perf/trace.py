"""Runtime tracing CLI (ISSUE 5): one eager driver run -> Perfetto trace
+ ``obs_metrics/v1`` document.

The command-line face of ``elemental_tpu/obs``:

    python -m perf.trace run cholesky 4096 --out trace.json
                                            # trace one driver: nested
                                            #   driver/step/phase spans +
                                            #   collective instants ->
                                            #   Chrome-trace JSON (load it
                                            #   at https://ui.perfetto.dev)
                                            #   + one obs_metrics/v1 line
    python -m perf.trace run lu --n 256 --nb 64 --grid 2x2
    python -m perf.trace summary trace.json # per-lane totals of a trace
    python -m perf.trace export phases.json --out trace.json
                                            # convert a phase_timings/v1
                                            #   doc (bench.py --phases /
                                            #   ab_harness.py phases) to
                                            #   the same trace format
    python -m perf.trace serve --out trace.json
                                            # drive a small 2-grid fleet
                                            #   workload (ISSUE 20): the
                                            #   trace carries one track
                                            #   per grid worker plus flow
                                            #   arrows linking each
                                            #   request submit -> worker
                                            #   -> done; also emits the
                                            #   serve_slo/v1 snapshot and
                                            #   a chaos-triggered
                                            #   flight_record/v1 dump

Flags for ``serve``: ``--requests N`` (default 12), ``--grids G``
(default 2), ``--out trace.json``, ``--slo-out slo.json``,
``--flight-out flight.json``, ``--smoke`` (self-check mode: validate
every timeline with ``check_timeline``, require flow events + >= 2
grid-worker tracks in the export, a non-trivial per-tenant SLO
snapshot, and a BIT-IDENTICAL flight-record replay of the grid-loss
chaos cell under the virtual clock; exit 1 on any failure).

Drivers: ``cholesky``, ``lu``, ``qr``, ``gemm``, ``trsm``, ``herk`` (the
six tuned drivers -- all emit spans through ``obs.phase_hook``).  The run
is EAGER (the tracer syncs at every phase boundary; same caveat as
``PhaseTimer``) on the real backend; under ``JAX_PLATFORMS=cpu`` an
8-virtual-device host mesh makes multi-device grids (``--grid 2x2``)
available anywhere, which is what the ``tools/check.sh`` smoke uses.

Flags for ``run``: ``--n N`` (or positional; default 2048 on TPU / 64 on
CPU), ``--nb NB``, ``--grid RxC`` (default 2x2 when >= 4 devices, else
1x1), ``--dtype NAME``, ``--alg {A,B,C,dot,gspmd,auto}`` (gemm),
``--classic`` (lookahead off), ``--crossover X``, ``--out trace.json``,
``--metrics-out metrics.json``.  The metrics document always prints to
stdout as the final line; summary rows are ``#``-prefixed above it.
"""
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVERS = ("cholesky", "lu", "qr", "gemm", "trsm", "herk")


def _bootstrap() -> None:
    """Virtual 8-device mesh on CPU hosts, BEFORE jax initializes (the
    backend itself is whatever the environment provides -- runtime traces
    should see the real chip when there is one)."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (AttributeError, RuntimeError):
        pass      # older jax (XLA_FLAGS path) / backend already initialized


def _grid(spec: str | None):
    import jax
    from elemental_tpu.core.grid import Grid
    devs = jax.devices()
    if spec is None:
        if len(devs) >= 4:
            return Grid(devs[:4], height=2)
        return Grid(devs[:1])
    r, c = (int(x) for x in spec.split("x"))
    if r * c > len(devs):
        raise SystemExit(f"grid {r}x{c} needs {r * c} devices, have {len(devs)}")
    return Grid(devs[: r * c], height=r)


def _run_driver(driver, grid, n, nb, lookahead, crossover, alg, dtype):
    """Build inputs EAGERLY (outside the trace), run the driver once, and
    return the output leaves (synced by the caller's span)."""
    import numpy as np
    import elemental_tpu as el
    rng = np.random.default_rng(0)
    F = rng.normal(size=(n, n)).astype(dtype)
    kw = {}
    if driver in ("cholesky", "lu"):
        kw = {"lookahead": lookahead, "crossover": crossover}
    if driver in ("cholesky", "trsm", "herk"):
        S = (F @ F.T / n + n * np.eye(n)).astype(dtype)
        A = el.from_global(S, el.MC, el.MR, grid=grid)
    else:
        A = el.from_global(F + n * np.eye(n, dtype=dtype), el.MC, el.MR,
                           grid=grid)
    if driver in ("gemm", "trsm"):
        B = el.from_global(rng.normal(size=(n, n)).astype(dtype),
                           el.MC, el.MR, grid=grid)
    import jax
    jax.block_until_ready(A.local)

    if driver == "cholesky":
        return el.cholesky(A, nb=nb, **kw).local
    if driver == "lu":
        LU, perm = el.lu(A, nb=nb, **kw)
        return (LU.local, perm)
    if driver == "qr":
        Ap, tau = el.qr(A, nb=nb)
        return (Ap.local, tau)
    if driver == "gemm":
        return el.gemm(A, B, alg=alg, nb=nb).local
    if driver == "trsm":
        return el.trsm("L", "L", "N", A, B, nb=nb).local
    if driver == "herk":
        return el.herk("L", A, nb=nb).local
    raise SystemExit(f"unknown driver {driver!r}; known: {DRIVERS}")


def cmd_run(driver, n, nb, grid_spec, dtype_name, alg, lookahead, crossover,
            out, metrics_out) -> int:
    import jax
    from elemental_tpu import obs
    grid = _grid(grid_spec)
    if n is None:
        n = 2048 if jax.devices()[0].platform != "cpu" else 64
    meta = {"driver": driver, "n": n, "nb": nb,
            "grid": f"{grid.height}x{grid.width}", "dtype": dtype_name,
            "device": getattr(jax.devices()[0], "device_kind",
                              jax.devices()[0].platform)}
    with obs.metrics_scope() as reg:
        tracer = obs.Tracer()
        with tracer:
            with tracer.span("run", **meta) as sp:
                leaves = _run_driver(driver, grid, n, nb, lookahead,
                                     crossover, alg, dtype_name)
                jax.block_until_ready(leaves)
        trace_doc = obs.chrome_trace_doc(tracer, **meta)
        mdoc = reg.to_doc(**meta)
    if out:
        obs.write_json(out, trace_doc)
        print(f"# trace: {out}  ({len(trace_doc['traceEvents'])} events; "
              "load at https://ui.perfetto.dev)")
    for drv, totals in tracer.phase_totals().items():
        row = "  ".join(f"{p}={t * 1e3:.2f}ms" for p, t in totals.items())
        print(f"# phases[{drv}]: {row}")
    rc = tracer.redist_counts()
    print(f"# collectives: {sum(rc.values())} redistribute/panel_spread "
          f"entries, ~{tracer.redist_bytes_total()} ring-model bytes")
    if metrics_out:
        obs.write_json(metrics_out, mdoc)
        print(f"# metrics: {metrics_out}")
    print(json.dumps(mdoc))
    return 0


def cmd_serve(requests, grids, out, slo_out, flight_out, smoke) -> int:
    """Drive a small pipelined fleet workload under the tracer and emit
    the three ISSUE-20 artifacts: Chrome trace (flow-linked lifecycle),
    ``serve_slo/v1`` snapshot, ``flight_record/v1`` dump."""
    from elemental_tpu import obs
    from elemental_tpu.obs.lifecycle import check_timeline
    from elemental_tpu.serve.chaos import build_workload
    from elemental_tpu.serve.fleet import SolverFleet

    requests = 12 if requests is None else int(requests)
    grids = 2 if grids is None else int(grids)
    tenants = ("acme", "blue")
    fleet = SolverFleet(grids=grids, depth=2, max_batch=4, shed=False,
                        retries=0)
    tracer = obs.Tracer()
    with tracer:
        with tracer.span("serve:fleet", grids=grids, requests=requests):
            work = build_workload("hpd", 16, 2, requests, seed=7)
            futs = [fleet.submit("hpd", A, B,
                                 tenant=tenants[i % len(tenants)])
                    for i, (A, B) in enumerate(work)]
            for f in futs:
                f.result(timeout=300.0)
            fleet.shutdown(drain=True)
    docs = [f.result(timeout=0)[1] for f in futs]
    problems = []
    for f, doc in zip(futs, docs):
        errs = check_timeline(doc.get("timeline"), path=doc.get("path"),
                              fleet=True)
        problems.extend(f"request f{f.fleet_id}: {e}" for e in errs)
    n_ok = sum(1 for d in docs if d.get("status") == "ok")
    print(f"# fleet: {grids} grids, {len(docs)} requests, {n_ok} ok, "
          f"{len(problems)} timeline problems")

    trace_doc = obs.chrome_trace_doc(tracer, mode="serve", grids=grids)
    evs = trace_doc["traceEvents"]
    flows = [ev for ev in evs if ev.get("ph") in ("s", "t", "f")]
    worker_tracks = {ev["args"]["name"] for ev in evs
                     if ev.get("ph") == "M"
                     and ev.get("name") == "thread_name"
                     and str(ev["args"]["name"])
                     .startswith("elemental-serve-worker")}
    print(f"# trace: {len(evs)} events, {len(flows)} flow events, "
          f"{len(worker_tracks)} grid-worker tracks")
    if out:
        obs.write_json(out, trace_doc)
        print(f"# trace file: {out} (load at https://ui.perfetto.dev)")

    sdoc = fleet.slo.snapshot(source="perf.trace serve")
    per_tenant = fleet.slo.per_tenant_p99_ms()
    for t in sorted(per_tenant):
        print(f"# slo[{t}]: p99={per_tenant[t]:.2f}ms")
    if slo_out:
        obs.write_json(slo_out, sdoc)
        print(f"# slo file: {slo_out}")

    # injected chaos trigger: dump the run's lifecycle record
    fdoc = fleet.flight.trigger("chaos_fault", source="perf.trace serve")
    edge_events = sum(1 for ev in fdoc["events"]
                      if str(ev.get("kind", "")).startswith("edge:"))
    print(f"# flight: {len(fdoc['events'])} events in dump "
          f"({edge_events} lifecycle edges, {fdoc['dropped']} dropped)")
    if flight_out:
        obs.write_json(flight_out, fdoc)
        print(f"# flight file: {flight_out}")

    if smoke:
        from elemental_tpu.serve.chaos import fleet_replay_identical
        if problems:
            for p in problems[:10]:
                print(f"SMOKE FAIL timeline: {p}", file=sys.stderr)
            return 1
        if n_ok != len(docs):
            print(f"SMOKE FAIL: only {n_ok}/{len(docs)} requests ok",
                  file=sys.stderr)
            return 1
        if not any(ev["ph"] == "s" for ev in flows) \
                or not any(ev["ph"] == "f" for ev in flows):
            print("SMOKE FAIL: export has no complete s->f flow chains",
                  file=sys.stderr)
            return 1
        if len(worker_tracks) < min(grids, 2):
            print(f"SMOKE FAIL: {len(worker_tracks)} grid-worker tracks "
                  f"in export, want >= {min(grids, 2)}", file=sys.stderr)
            return 1
        missing = [t for t in tenants if t not in per_tenant]
        if missing or not sdoc.get("series"):
            print(f"SMOKE FAIL: SLO snapshot incomplete "
                  f"(missing tenants {missing})", file=sys.stderr)
            return 1
        if edge_events == 0:
            print("SMOKE FAIL: flight dump has no lifecycle edges",
                  file=sys.stderr)
            return 1
        if not fleet_replay_identical(requests=4):
            print("SMOKE FAIL: grid-loss flight record not bit-identical "
                  "on replay", file=sys.stderr)
            return 1
        print("# smoke: timelines complete, flows linked, SLO per-tenant "
              "recorded, flight replay bit-identical")
    print(json.dumps(sdoc))
    return 0


def cmd_summary(path) -> int:
    with open(path) as f:
        doc = json.load(f)
    if "traceEvents" not in doc:
        raise SystemExit(f"{path}: not a Chrome trace document")
    names = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[ev["tid"]] = ev["args"]["name"]
    lanes: dict = {}
    ninstant = nbytes = 0
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "X":
            lane = names.get(ev.get("tid"), str(ev.get("tid")))
            cur = lanes.setdefault(lane, [0, 0.0])
            cur[0] += 1
            cur[1] += ev.get("dur", 0.0)
        elif ev.get("ph") == "i":
            ninstant += 1
            nbytes += ev.get("args", {}).get("bytes", 0)
    other = doc.get("otherData", {})
    print(f"# {path}: schema={doc.get('schema')} "
          + " ".join(f"{k}={v}" for k, v in sorted(other.items())))
    print(f"{'lane':24s} {'spans':>6s} {'total_ms':>10s}")
    for lane, (cnt, dur) in sorted(lanes.items(), key=lambda kv: -kv[1][1]):
        print(f"{lane:24s} {cnt:6d} {dur / 1e3:10.3f}")
    if ninstant:
        print(f"{'collectives':24s} {ninstant:6d} {'~' + str(nbytes):>10s}B")
    return 0


def cmd_export(path, out) -> int:
    from elemental_tpu import obs
    with open(path) as f:
        doc = json.load(f)
    trace = obs.phase_timings_to_chrome(doc)
    if out:
        obs.write_json(out, trace)
        print(f"# trace: {out}  ({len(trace['traceEvents'])} events)")
    else:
        print(json.dumps(trace))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd not in ("run", "summary", "export", "serve"):
        print(__doc__)
        raise SystemExit(f"unknown command {cmd!r}")
    pos = []
    n = nb = crossover = None
    grid_spec = out = metrics_out = None
    requests = serve_grids = slo_out = flight_out = None
    smoke = False
    dtype_name, alg, lookahead = "float32", "auto", True
    it = iter(argv)
    for arg in it:
        if arg == "--n":
            n = int(next(it))
        elif arg == "--requests":
            requests = int(next(it))
        elif arg == "--grids":
            serve_grids = int(next(it))
        elif arg == "--slo-out":
            slo_out = next(it)
        elif arg == "--flight-out":
            flight_out = next(it)
        elif arg == "--smoke":
            smoke = True
        elif arg == "--nb":
            nb = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--dtype":
            dtype_name = next(it)
        elif arg == "--alg":
            alg = next(it)
        elif arg == "--classic":
            lookahead = False
        elif arg == "--crossover":
            crossover = int(next(it))
        elif arg == "--out":
            out = next(it)
        elif arg == "--metrics-out":
            metrics_out = next(it)
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            pos.append(arg)
    if cmd == "run":
        if not pos:
            raise SystemExit(f"run needs a driver ({'/'.join(DRIVERS)})")
        driver = pos.pop(0)
        if driver not in DRIVERS:
            # before _bootstrap: no jax import, no device init, no
            # input-building -- just the registry and a clean exit 1
            print(f"unknown driver {driver!r}; registered drivers:",
                  file=sys.stderr)
            for d in DRIVERS:
                print(f"  {d}", file=sys.stderr)
            return 1
        if pos and n is None:
            n = int(pos.pop(0))
        _bootstrap()
        return cmd_run(driver, n, nb, grid_spec, dtype_name, alg, lookahead,
                       crossover, out, metrics_out)
    if cmd == "serve":
        _bootstrap()
        return cmd_serve(requests, serve_grids, out, slo_out, flight_out,
                         smoke)
    if not pos:
        raise SystemExit(f"{cmd} needs a JSON file path")
    if cmd == "summary":
        return cmd_summary(pos[0])
    _bootstrap()          # export imports elemental_tpu.obs (jax)
    return cmd_export(pos[0], out)


if __name__ == "__main__":
    try:
        import signal
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    except (ImportError, AttributeError, ValueError):
        pass
    raise SystemExit(main())
