"""A/B perf harness for the single-chip Cholesky/LU schedules.

Runs several schedule variants IN ONE PROCESS on the real chip, bracketing
each timing with a matmul roofline measurement so chip-weather is factored
out per-variant (the r4 lesson: never land a "perf" change without a
before/after pair).  Usage:

    python perf/ab_harness.py chol     # Cholesky variants at N=32768
    python perf/ab_harness.py lu       # LU variants at N=16384
    python perf/ab_harness.py phases   # LU phase breakdown (panel vs rest)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache_tpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import importlib                                              # noqa: E402

import elemental_tpu as el                                    # noqa: E402

chol_mod = importlib.import_module("elemental_tpu.lapack.cholesky")
lu_mod = importlib.import_module("elemental_tpu.lapack.lu")

HI = jax.lax.Precision.HIGHEST


def _min3(fn, reps=3):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


LAT = None
_ROOF_R = None


def roofline():
    global LAT, _ROOF_R
    if LAT is None:
        tiny = jax.jit(lambda x: x + 1.0)
        t = jnp.zeros(())
        float(tiny(t))
        LAT = _min3(lambda: float(tiny(t)))
    n = 8192
    if _ROOF_R is None:
        _ROOF_R = jax.random.normal(jax.random.PRNGKey(9), (n, n), jnp.float32)
    mm = jax.jit(lambda x: jnp.matmul(x, x, precision=HI))
    float(mm(_ROOF_R)[0, 0])
    dt = max(_min3(lambda: float(mm(_ROOF_R)[0, 0])) - LAT, 1e-9)
    return 2 * n ** 3 / dt / 1e12


def timed(make_input, step, reps=3):
    out = step(make_input())
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        A = make_input()
        float(jax.tree_util.tree_leaves(A)[0].ravel()[0])
        t0 = time.perf_counter()
        out = step(A)
        float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    del out
    return max(min(times) - LAT, 1e-9)


def report(name, tflops, roof):
    print(f"{name:40s} {tflops:8.3f} TFLOP/s   roof {roof:6.2f}"
          f"   norm {100 * tflops / roof:5.1f}%", flush=True)


def run_chol():
    n, grid = 32768, el.Grid([jax.devices()[0]])

    @jax.jit
    def gen():
        G = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
        return jnp.matmul(G, G.T) / n + n * jnp.eye(n, dtype=jnp.float32)

    def wrap(a):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    from jax import lax

    def native_potrf_inv(D, precision, bs=512):
        w = D.shape[0]
        d = jnp.tril(D)
        d = d + jnp.conj(jnp.tril(d, -1)).T
        L = jnp.linalg.cholesky(d)
        Li = lax.linalg.triangular_solve(L, jnp.eye(w, dtype=D.dtype),
                                         left_side=True, lower=True)
        return L, Li

    orig = chol_mod._potrf_inv
    variants = []
    for nb in (2048, 4096):
        variants.append((f"r4 _potrf_inv bs512 nb={nb}", orig, nb))
    variants.append(("native potrf+trsm-inv nb=2048", native_potrf_inv, 2048))
    variants.append(("_potrf_inv bs1024 nb=4096",
                     lambda D, p, bs=1024: orig(D, p, bs), 4096))
    variants.append(("_potrf_inv bs1024 nb=2048",
                     lambda D, p, bs=1024: orig(D, p, bs), 2048))

    for name, fn, nb in variants:
        chol_mod._potrf_inv = fn
        step = jax.jit(lambda a, _nb=nb: el.cholesky(a, nb=_nb,
                                                     precision=HI).local,
                       donate_argnums=0)
        r0 = roofline()
        dt = timed(lambda: wrap(gen()), step)
        r1 = roofline()
        report(name, (n ** 3 / 3) / dt / 1e12, 0.5 * (r0 + r1))
        del step
    chol_mod._potrf_inv = orig


def run_lu():
    n, grid = 16384, el.Grid([jax.devices()[0]])

    def wrap(a):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    gen = jax.jit(lambda: jax.random.normal(jax.random.PRNGKey(1), (n, n),
                                            jnp.float32))

    orig_inners = lu_mod._INNERS
    cases = []
    for inners in ((512, 64), (256, 64), (512, 64), (1024, 128),
                   (512, 64, 16), (768, 96)):
        cases.append((f"inners={inners} nb=2048", inners, 2048))
    cases.append((f"inners=(512,64) nb=3072", (512, 64), 3072))

    for name, inners, nb in cases:
        lu_mod._INNERS = inners
        lufn = jax.jit(lambda a, _nb=nb: tuple(el.lu(a, nb=_nb,
                                                     precision=HI)),
                       donate_argnums=0)

        def step(A):
            LU, perm = lufn(A)
            return LU.local, perm

        r0 = roofline()
        dt = timed(lambda: wrap(gen()), step)
        r1 = roofline()
        report(name, (2 * n ** 3 / 3) / dt / 1e12, 0.5 * (r0 + r1))
        del lufn
    lu_mod._INNERS = orig_inners


def run_phases():
    """Time the LU panel factorization alone vs a full matmul of the same
    trailing update shape, to see where the 2/3 n^3 budget goes."""
    m, nbw = 16384, 2048

    def sync(x):
        return float(jax.tree_util.tree_leaves(x)[0].ravel()[0])

    P = jax.random.normal(jax.random.PRNGKey(4), (m, nbw), jnp.float32)
    for inners in ((256, 32), (512, 64), (128, 16), (64,), (1024, 128, 16)):
        pan = jax.jit(lambda p, _i=inners: lu_mod._panel_lu(p, nbw, HI, _i))
        sync(pan(P))
        dt = max(_min3(lambda: sync(pan(P))) - LAT, 1e-9)
        print(f"panel m={m} nbw={nbw} inners={inners}: {dt*1e3:8.2f} ms",
              flush=True)
    # trailing update matmul for the first panel: (m-nbw, nbw) @ (nbw, m-nbw)
    A = jax.random.normal(jax.random.PRNGKey(5), (m - nbw, nbw), jnp.float32)
    B = jax.random.normal(jax.random.PRNGKey(6), (nbw, m - nbw), jnp.float32)
    mm = jax.jit(lambda a, b: jnp.matmul(a, b, precision=HI))
    sync(mm(A, B))
    dt = max(_min3(lambda: sync(mm(A, B))) - LAT, 1e-9)
    fl = 2 * (m - nbw) ** 2 * nbw
    print(f"trailing mm {m-nbw}x{nbw}x{m-nbw}: {dt*1e3:8.2f} ms "
          f"({fl/dt/1e12:.2f} TFLOP/s)", flush=True)
    # full-trailing row gather (the swap cost): take + writeback of m x m
    G = jax.random.normal(jax.random.PRNGKey(7), (m, m), jnp.float32)
    pp = jnp.arange(m)[::-1]
    gat = jax.jit(lambda a: a.at[0:].set(jnp.take(a, pp, axis=0)),
                  donate_argnums=0)
    sync(gat(G))
    G = jax.random.normal(jax.random.PRNGKey(7), (m, m), jnp.float32)
    sync(G)
    t0 = time.perf_counter()
    sync(gat(G))
    print(f"full {m}x{m} row-permute: "
          f"{(time.perf_counter()-t0-LAT)*1e3:8.2f} ms", flush=True)
    print(f"roofline now: {roofline():.2f}", flush=True)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "chol"
    tiny = jax.jit(lambda x: x + 1.0)
    t = jnp.zeros(())
    float(tiny(t))
    LAT = _min3(lambda: float(tiny(t)))
    print(f"device {jax.devices()[0].device_kind}, rt latency {LAT*1e3:.2f} ms",
          flush=True)
    if mode == "chol":
        run_chol()
    elif mode == "lu":
        run_lu()
    else:
        run_phases()
