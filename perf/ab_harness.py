"""A/B perf harness for the single-chip Cholesky/LU schedules.

Runs several schedule variants IN ONE PROCESS on the real chip, bracketing
each timing with a matmul roofline measurement so chip-weather is factored
out per-variant (the r4 lesson: never land a "perf" change without a
before/after pair).  Usage:

    python perf/ab_harness.py chol          # _potrf_inv variants at N=32768
    python perf/ab_harness.py lu [N]        # LU: classic vs look-ahead,
                                            #   nb + _INNERS sweep (dflt 16384)
    python perf/ab_harness.py cholesky [N]  # Cholesky: classic vs look-ahead
                                            #   x nb x crossover (dflt 16384)
    python perf/ab_harness.py lu-dist [N]   # distributed LU: classic-panel
                                            #   vs CALU tournament panel x
                                            #   look-ahead x tail crossover
                                            #   x comm_precision wire sweep
                                            #   on ALL visible devices
    python perf/ab_harness.py gemm [N]      # ISSUE 16: the full gemm alg
                                            #   family (A/B/C/dot/gspmd/
                                            #   slice/auto) x shape class
                                            #   (square / tall-skinny m>>n /
                                            #   outer-product k-small) on
                                            #   ALL visible devices, plus
                                            #   comm_precision twins of the
                                            #   slice rows
    python perf/ab_harness.py panel [M]     # ISSUE 17: the three panel
                                            #   primitives, xla op-ladder vs
                                            #   fused Pallas kernel, nb in
                                            #   {64..2048} x dtype (panel
                                            #   height M, dflt 16384/1024)
    python perf/ab_harness.py phases [lu|cholesky] [N NB]
                                            # per-step phase wall-clock as
                                            #   one phase_timings/v1 JSON line

``lu`` is the look-ahead A/B pair from ISSUE 1; ``cholesky`` is ISSUE 2's:
the first two variants are the classic right-looking schedule and the
pipelined look-ahead schedule at identical nb, same process, roofline
bracketed; the rest sweep nb and (on a multi-device grid, where the
distributed loop runs) the tail crossover-to-local threshold.  The
harness uses ALL visible devices -- on CPU export
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` to exercise the
distributed schedule without hardware.

``phases`` drives ``perf.phase_timer.PhaseTimer`` through the real driver
(eagerly, sync at each phase boundary) and emits the ``phase_timings/v1``
JSON -- the hook future perf PRs use to attribute regressions.

``lu-dist`` and ``cholesky`` additionally sweep the ISSUE-8
``comm_precision`` wire-quantization knob on multi-device grids: each
quantized row is the exact twin of the headline look-ahead schedule at
equal nb/crossover/panel, so a row pair is a pure wire-precision A/B
(and the row prints the factor residual next to the throughput -- the
accuracy cost of the narrow wire is part of the measurement).  Override
the swept modes with ``--comm-precision bf16,int8`` (or ``none`` to
disable).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache_tpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import importlib                                              # noqa: E402

import elemental_tpu as el                                    # noqa: E402

chol_mod = importlib.import_module("elemental_tpu.lapack.cholesky")
lu_mod = importlib.import_module("elemental_tpu.lapack.lu")

HI = jax.lax.Precision.HIGHEST
DEF = jax.lax.Precision.DEFAULT


def _min3(fn, reps=3):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


LAT = None
_ROOF_R = None


def roofline():
    global LAT, _ROOF_R
    if LAT is None:
        tiny = jax.jit(lambda x: x + 1.0)
        t = jnp.zeros(())
        float(tiny(t))
        LAT = _min3(lambda: float(tiny(t)))
    # CPU smoke runs: the fixed probe would dominate the sweep (minutes
    # per bracket at HIGHEST precision); the weather-tracking bracket only
    # needs a consistent in-run yardstick, not the TPU-saturating size
    n = 8192 if jax.devices()[0].platform != "cpu" else 512
    if _ROOF_R is None:
        _ROOF_R = jax.random.normal(jax.random.PRNGKey(9), (n, n), jnp.float32)
    mm = jax.jit(lambda x: jnp.matmul(x, x, precision=HI))
    float(mm(_ROOF_R)[0, 0])
    dt = max(_min3(lambda: float(mm(_ROOF_R)[0, 0])) - LAT, 1e-9)
    return 2 * n ** 3 / dt / 1e12


def timed(make_input, step, reps=3):
    out = step(make_input())
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        A = make_input()
        float(jax.tree_util.tree_leaves(A)[0].ravel()[0])
        t0 = time.perf_counter()
        out = step(A)
        float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    del out
    return max(min(times) - LAT, 1e-9)


def report(name, tflops, roof, extra=""):
    print(f"{name:44s} {tflops:8.3f} TFLOP/s   roof {roof:6.2f}"
          f"   norm {100 * tflops / roof:5.1f}%{extra}", flush=True)


def run_chol():
    n, grid = 32768, el.Grid([jax.devices()[0]])

    @jax.jit
    def gen():
        G = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
        return jnp.matmul(G, G.T) / n + n * jnp.eye(n, dtype=jnp.float32)

    def wrap(a):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    from jax import lax

    def native_potrf_inv(D, precision, bs=512):
        w = D.shape[0]
        d = jnp.tril(D)
        d = d + jnp.conj(jnp.tril(d, -1)).T
        L = jnp.linalg.cholesky(d)
        Li = lax.linalg.triangular_solve(L, jnp.eye(w, dtype=D.dtype),
                                         left_side=True, lower=True)
        return L, Li

    orig = chol_mod._potrf_inv
    variants = []
    for nb in (2048, 4096):
        variants.append((f"r4 _potrf_inv bs512 nb={nb}", orig, nb))
    variants.append(("native potrf+trsm-inv nb=2048", native_potrf_inv, 2048))
    variants.append(("_potrf_inv bs1024 nb=4096",
                     lambda D, p, bs=1024: orig(D, p, bs), 4096))
    variants.append(("_potrf_inv bs1024 nb=2048",
                     lambda D, p, bs=1024: orig(D, p, bs), 2048))

    for name, fn, nb in variants:
        chol_mod._potrf_inv = fn
        step = jax.jit(lambda a, _nb=nb: el.cholesky(a, nb=_nb,
                                                     precision=HI).local,
                       donate_argnums=0)
        r0 = roofline()
        dt = timed(lambda: wrap(gen()), step)
        r1 = roofline()
        report(name, (n ** 3 / 3) / dt / 1e12, 0.5 * (r0 + r1))
        del step
    chol_mod._potrf_inv = orig


def run_lu(n=None):
    on_tpu = jax.devices()[0].platform != "cpu"
    n = int(n) if n else (16384 if on_tpu else 512)
    grid = el.Grid([jax.devices()[0]])

    def wrap(a):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    gen = jax.jit(lambda: jax.random.normal(jax.random.PRNGKey(1), (n, n),
                                            jnp.float32))
    nb0 = 2048 if on_tpu else 128

    # (name, lookahead, inners, nb, update_precision, crossover, panel_impl)
    # xover=0 everywhere: this is the SINGLE-CHIP schedule harness (the
    # sequential path has no redistribution tail); the distributed LU
    # crossover A/B is `ab_harness.py lu-dist`, mirroring run_cholesky.
    # inners rides the lu(inners=) kwarg (NOT a lu_mod._INNERS
    # monkeypatch: since ISSUE 17 the resolved ladder flows through the
    # PanelPlan, so patching the module alias would silently go stale).
    cases = [
        (f"classic        inners=(512,64) nb={nb0}", False, (512, 64), nb0,
         None, 0, None),
        (f"look-ahead     inners=(512,64) nb={nb0}", True, (512, 64), nb0,
         None, 0, None),
        (f"look-ahead     inners=(512,64) nb={nb0 // 2}", True, (512, 64),
         nb0 // 2, None, 0, None),
        (f"look-ahead     inners=(512,64) nb={nb0 * 2}", True, (512, 64),
         nb0 * 2, None, 0, None),
        (f"look-ahead     inners=(768,96) nb={nb0}", True, (768, 96), nb0,
         None, 0, None),
        (f"look-ahead     inners=(1024,128) nb={nb0}", True, (1024, 128),
         nb0, None, 0, None),
        (f"look-ahead     inners=(512,128,32) nb={nb0}", True, (512, 128, 32),
         nb0, None, 0, None),
        (f"look-ahead+bf16upd inners=(512,64) nb={nb0}", True, (512, 64),
         nb0, DEF, 0, None),
        # panel_impl twin of the headline look-ahead row: equal
        # nb/inners/schedule, pure fused-kernel A/B (ISSUE 17).  Off-TPU
        # this times the interpret-mode kernel -- slower by construction,
        # the row documents it; the VMEM gate may silently route huge
        # panels back to xla (the resolved impl lands in bench.py
        # provenance, not here).
        (f"look-ahead     inners=(512,64) nb={nb0} panel=pallas", True,
         (512, 64), nb0, None, 0, "pallas"),
    ]

    for name, la, inners, nb, upd, xover, impl in cases:
        lufn = jax.jit(
            lambda a, _nb=nb, _la=la, _u=upd, _x=xover, _in=inners, _pi=impl:
            tuple(el.lu(a, nb=_nb, precision=HI, update_precision=_u,
                        lookahead=_la, crossover=_x, inners=_in,
                        panel_impl=_pi)),
            donate_argnums=0)

        def step(A):
            LU, perm = lufn(A)
            return LU.local, perm

        r0 = roofline()
        dt = timed(lambda: wrap(gen()), step)
        r1 = roofline()
        extra = ""
        if upd is not None:
            # residual at the relaxed trailing precision (documents the
            # bf16 knob's accuracy cost next to its speedup)
            LU, perm = lufn(wrap(gen()))
            mres = gen()
            v = jax.random.normal(jax.random.PRNGKey(3), (n, 1), jnp.float32)
            uv = jnp.matmul(jnp.triu(LU.local), v, precision=HI)
            luv = jnp.matmul(jnp.tril(LU.local, -1), uv, precision=HI) + uv
            pav = jnp.matmul(jnp.take(mres, perm, axis=0), v, precision=HI)
            resid = float(jnp.linalg.norm(pav - luv)
                          / (jnp.linalg.norm(mres) * jnp.linalg.norm(v)))
            extra = f"   resid {resid:.2e}"
            del LU, perm, mres
        report(name, (2 * n ** 3 / 3) / dt / 1e12, 0.5 * (r0 + r1), extra)
        del lufn


def run_lu_dist(n=None, cps=("bf16", "int8")):
    """ISSUE 3 + 6 A/B: distributed LU classic-panel vs CALU tournament
    panel, each under classic and look-ahead x tail-crossover schedules,
    same process and grid (all visible devices), roofline-bracketed --
    the LU twin of :func:`run_cholesky`.  On a single device the
    crossover rows are skipped (the sequential path has no redistribution
    tail to cross over from) and calu degenerates to classic (single
    grid row), so the tournament rows only appear on multi-row grids."""
    on_tpu = jax.devices()[0].platform != "cpu"
    n = int(n) if n else (16384 if on_tpu else 512)
    grid = el.Grid(jax.devices())
    p = grid.size
    nb0 = 2048 if on_tpu else 128

    gen = jax.jit(lambda: jax.random.normal(jax.random.PRNGKey(1), (n, n),
                                            jnp.float32))

    def wrap(a):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    # (name, lookahead, nb, crossover, panel, comm_precision)
    cases = [
        (f"classic        nb={nb0} xover=0", False, nb0, 0, "classic", None),
        (f"look-ahead     nb={nb0} xover=0", True, nb0, 0, "classic", None),
    ]
    if p > 1:
        for xo in (n // 8, n // 4, n // 2):
            cases.append((f"look-ahead     nb={nb0} xover={xo}",
                          True, nb0, xo, "classic", None))
        cases.append((f"classic        nb={nb0} xover={n // 4}",
                      False, nb0, n // 4, "classic", None))
        # wire-precision twins of the headline look-ahead row: equal
        # nb/crossover/panel, so each pair is a pure comm_precision A/B
        for cp in cps:
            cases.append((f"look-ahead     nb={nb0} xover=0 wire={cp}",
                          True, nb0, 0, "classic", cp))
    if grid.height > 1:
        # the calu twins of the headline schedules: equal nb/crossover so
        # every row pair is a pure panel-strategy A/B
        cases.append((f"calu           nb={nb0} xover=0",
                      True, nb0, 0, "calu", None))
        cases.append((f"calu classic-sched nb={nb0} xover=0",
                      False, nb0, 0, "calu", None))
        for xo in (n // 8, n // 4):
            cases.append((f"calu look-ahead nb={nb0} xover={xo}",
                          True, nb0, xo, "calu", None))
        for cp in cps:
            cases.append((f"calu           nb={nb0} xover=0 wire={cp}",
                          True, nb0, 0, "calu", cp))
    print(f"grid {grid.height}x{grid.width}, n={n}", flush=True)
    for name, la, nb, xo, pan, cp in cases:
        step = jax.jit(
            lambda a, _nb=nb, _la=la, _xo=xo, _p=pan, _c=cp: tuple(el.lu(
                a, nb=_nb, precision=HI, lookahead=_la, crossover=_xo,
                panel=_p, comm_precision=_c))[0].local,
            donate_argnums=0)
        r0 = roofline()
        dt = timed(lambda: wrap(gen()), step)
        r1 = roofline()
        report(name, (2 * n ** 3 / 3) / dt / 1e12, 0.5 * (r0 + r1))
        del step


def run_cholesky(n=None, cps=("bf16", "int8")):
    """ISSUE 2 A/B: classic vs look-ahead x nb x tail-crossover, same
    process and grid (all visible devices), roofline-bracketed.  On a
    single device the crossover rows are skipped (the sequential path has
    no redistribution tail to cross over from)."""
    on_tpu = jax.devices()[0].platform != "cpu"
    n = int(n) if n else (16384 if on_tpu else 512)
    grid = el.Grid(jax.devices())
    p = grid.size
    nb0 = 2048 if on_tpu else 128

    @jax.jit
    def gen():
        G = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
        return jnp.matmul(G, G.T) / n + n * jnp.eye(n, dtype=jnp.float32)

    def wrap(a):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    # (name, lookahead, nb, crossover, comm_precision, panel_impl)
    cases = [
        (f"classic        nb={nb0} xover=0", False, nb0, 0, None, None),
        (f"look-ahead     nb={nb0} xover=0", True, nb0, 0, None, None),
        (f"look-ahead     nb={nb0 // 2} xover=0", True, nb0 // 2, 0, None,
         None),
        (f"look-ahead     nb={nb0 * 2} xover=0", True, nb0 * 2, 0, None,
         None),
        # panel_impl twin of the headline look-ahead row: equal
        # nb/crossover, pure fused-_potrf_inv A/B (ISSUE 17)
        (f"look-ahead     nb={nb0} xover=0 panel=pallas", True, nb0, 0,
         None, "pallas"),
    ]
    if p > 1:
        for xo in (n // 8, n // 4, n // 2):
            cases.append((f"look-ahead     nb={nb0} xover={xo}", True, nb0,
                          xo, None, None))
        cases.append((f"classic        nb={nb0} xover={n // 4}",
                      False, nb0, n // 4, None, None))
        # wire-precision twins of the headline look-ahead row (pure
        # comm_precision A/B at equal nb/crossover)
        for cp in cps:
            cases.append((f"look-ahead     nb={nb0} xover=0 wire={cp}",
                          True, nb0, 0, cp, None))
    print(f"grid {grid.height}x{grid.width}, n={n}", flush=True)
    for name, la, nb, xo, cp, impl in cases:
        step = jax.jit(
            lambda a, _nb=nb, _la=la, _xo=xo, _c=cp, _pi=impl: el.cholesky(
                a, nb=_nb, precision=HI, lookahead=_la, crossover=_xo,
                comm_precision=_c, panel_impl=_pi).local,
            donate_argnums=0)
        r0 = roofline()
        dt = timed(lambda: wrap(gen()), step)
        r1 = roofline()
        extra = ""
        if cp is not None:
            # accuracy cost of the narrow wire, printed inline.  The
            # timing rows feed gen()'s output as STORAGE (cheap, and
            # layout-irrelevant for wall-clock); the residual needs the
            # implied global matrix to really be SPD, so this one run
            # goes through the from_global/to_global bridges.
            from elemental_tpu import from_global, to_global
            a = gen()
            Ld = el.cholesky(from_global(a, el.MC, el.MR, grid=grid),
                             nb=nb, precision=HI, lookahead=la,
                             crossover=xo, comm_precision=cp)
            lg = to_global(Ld)
            v = jax.random.normal(jax.random.PRNGKey(2), (n, 1), jnp.float32)
            r = jnp.matmul(a, v, precision=HI) - jnp.matmul(
                lg, jnp.matmul(lg.T, v, precision=HI), precision=HI)
            resid = float(jnp.linalg.norm(r)
                          / (jnp.linalg.norm(a) * jnp.linalg.norm(v)))
            extra = f"   resid {resid:.2e}"
            del Ld, lg, a, v
        report(name, (n ** 3 / 3) / dt / 1e12, 0.5 * (r0 + r1), extra)
        del step


def run_gemm(n=None, cps=("bf16", "int8")):
    """ISSUE 16 A/B: the full gemm alg family x shape class, same
    process and grid (all visible devices), roofline-bracketed.

    Three shape classes cover the regimes the alg space splits on:
    ``square`` (the SUMMA home turf), ``tall-skinny`` (m >> n -- where
    the slicing schedule's three one-shot plans beat the panel rings;
    the bench.py ``gemm_tall_skinny_tflops_per_chip`` headline class)
    and ``outer-product`` (k small).  The ``auto`` row shows what the
    tuner dispatches per class, and the slice rows get comm_precision
    wire twins (equal shape/grid, pure wire-precision A/B).  Rows whose
    schedule cannot run the shape (e.g. dot's replicated-C blowup on
    huge squares) report ``skip`` instead of aborting the sweep."""
    on_tpu = jax.devices()[0].platform != "cpu"
    n = int(n) if n else (8192 if on_tpu else 256)
    grid = el.Grid(jax.devices())
    shapes = [("square", (n, n, n)),
              ("tall-skinny", (16 * n, n, max(n // 4, 1))),
              ("outer-product", (n, max(n // 16, 1), n))]
    algs = ["C", "A", "B", "dot", "gspmd", "slice", "auto"]
    print(f"grid {grid.height}x{grid.width}", flush=True)
    for cls, (m, k, nn) in shapes:
        print(f"-- {cls}: m={m} k={k} n={nn}", flush=True)
        gen = jax.jit(lambda _m=m, _k=k, _n=nn: (
            jax.random.normal(jax.random.PRNGKey(2), (_m, _k), jnp.float32),
            jax.random.normal(jax.random.PRNGKey(3), (_k, _n), jnp.float32)))

        def wrap(ab, _m=m, _k=k, _n=nn):
            a, b = ab
            return (el.from_global(a, el.MC, el.MR, grid=grid),
                    el.from_global(b, el.MC, el.MR, grid=grid))

        rows = [(a, None) for a in algs] + [("slice", cp) for cp in cps]
        for alg, cp in rows:
            name = f"{cls:13s} alg={alg}" + (f" wire={cp}" if cp else "")
            try:
                step = jax.jit(
                    lambda ab, _a=alg, _c=cp: el.gemm(
                        ab[0], ab[1], alg=_a, precision=HI,
                        comm_precision=_c).local,
                    donate_argnums=0)
                r0 = roofline()
                dt = timed(lambda: wrap(gen()), step)
                r1 = roofline()
                report(name, 2 * m * k * nn / dt / 1e12, 0.5 * (r0 + r1))
                del step
            except Exception as e:                     # noqa: BLE001
                print(f"{name:44s} skip ({type(e).__name__}: {e})",
                      flush=True)


def run_panel(n=None, dtypes=None):
    """ISSUE 17 A/B: the three panel primitives, xla op-ladder vs fused
    Pallas kernel, at matched inputs across the nb ladder x dtype --
    roofline-bracketed like every other sweep.  On TPU the pallas rows
    time the compiled Mosaic kernel; off-TPU they time the interpret-
    mode twin (the CPU CI artifact, slower by construction -- the rows
    exist so the gap is measured, not assumed).  Rows whose panel
    exceeds the fused kernel's VMEM budget report ``skip (vmem)``:
    the driver-level dispatch would route them back to xla."""
    from elemental_tpu import kernels
    qr_mod = importlib.import_module("elemental_tpu.lapack.qr")
    on_tpu = jax.devices()[0].platform != "cpu"
    m = int(n) if n else (16384 if on_tpu else 1024)
    if dtypes is None:
        dtypes = (jnp.float32,) if not jax.config.jax_enable_x64 \
            else (jnp.float32, jnp.float64)
    nbs = [nb for nb in (64, 128, 256, 512, 1024, 2048) if nb <= m]
    inner = kernels.default_inners()[-1]
    print(f"panel height m={m}, xla inner ladder {kernels.default_inners()}",
          flush=True)

    def sweep(prim, nb, dt, make, xla_fn, pal_fn, flops, copies):
        for impl, fn in (("xla", xla_fn), ("pallas", pal_fn)):
            name = f"{prim:5s} nb={nb:<5d} {jnp.dtype(dt).name:8s} {impl}"
            if impl == "pallas" and not kernels.panel_fits(
                    make().shape, dt, copies=copies):
                print(f"{name:44s} skip (vmem: dispatch would route to xla)",
                      flush=True)
                continue
            step = jax.jit(fn)
            r0 = roofline()
            dtime = timed(make, step)
            r1 = roofline()
            report(name, flops / dtime / 1e12, 0.5 * (r0 + r1))
            del step

    for dt in dtypes:
        for nb in nbs:
            key = jax.random.PRNGKey(nb)
            P0 = jax.random.normal(key, (m, nb), dt)
            G = jax.random.normal(key, (nb, nb), dt)
            D0 = jnp.matmul(G, G.T, precision=HI) / nb \
                + nb * jnp.eye(nb, dtype=dt)
            # lu: the chunked panel ladder vs the fused kernel at the
            # ladder's finest rung (what PanelPlan.pallas_inner selects)
            sweep("lu", nb, dt, lambda _p=P0: _p,
                  lambda p: lu_mod._panel_lu(p, nb, HI),
                  lambda p: kernels.lu_panel(p, nb, HI, inner=inner),
                  flops=m * nb * nb - nb ** 3 / 3, copies=3)
            # chol: blocked potrf+inverse pair on the diagonal block
            sweep("chol", nb, dt, lambda _d=D0: _d,
                  lambda d: chol_mod._potrf_inv(d, HI),
                  lambda d: kernels.potrf_inv(d, HI),
                  flops=nb ** 3, copies=4)
            # qr: larfg chain + larft build vs the fused single launch
            def xla_qr(p):
                packed, tau = qr_mod._panel_qr(p)
                V = qr_mod._panel_v(packed)
                return packed, tau, qr_mod._larft(V, tau)
            sweep("qr", nb, dt, lambda _p=P0: _p, xla_qr,
                  lambda p: kernels.qr_panel(p),
                  flops=2 * nb * nb * (m - nb / 3), copies=4)


def run_phases(*args):
    """Per-step phase wall-clock through the REAL driver (eager, PhaseTimer
    syncs at each boundary) -> one phase_timings/v1 JSON line.
    ``phases [lu|cholesky] [N NB]`` (driver defaults to lu)."""
    from perf.phase_timer import PhaseTimer
    args = list(args)
    driver = "lu"
    if args and not args[0].isdigit():
        driver = args.pop(0)
    n = int(args[0]) if args else None
    nb = int(args[1]) if len(args) > 1 else None
    on_tpu = jax.devices()[0].platform != "cpu"
    n = n or (16384 if on_tpu else 512)
    nb = nb or (2048 if on_tpu else 128)
    grid = el.Grid([jax.devices()[0]])
    t = PhaseTimer()
    if driver == "cholesky":
        G = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
        a = jnp.matmul(G, G.T) / n + n * jnp.eye(n, dtype=jnp.float32)
        A = el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)
        jax.block_until_ready(a)
        L = el.cholesky(A, nb=nb, precision=HI, lookahead=True, timer=t)
        jax.block_until_ready(L.local)
        meta = dict(driver="cholesky", flops=n ** 3 / 3,
                    crossover=chol_mod._CROSSOVER)
    else:
        a = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
        A = el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)
        jax.block_until_ready(a)
        LU, perm = el.lu(A, nb=nb, precision=HI, lookahead=True, timer=t)
        jax.block_until_ready((LU.local, perm))
        from elemental_tpu.kernels import default_inners
        meta = dict(driver="lu", flops=2 * n ** 3 / 3,
                    inners=list(default_inners()))
    r = roofline()
    print(t.json(n=n, nb=nb, lookahead=True, roofline_tflops=round(r, 2),
                 device=jax.devices()[0].device_kind, **meta), flush=True)


if __name__ == "__main__":
    argv = sys.argv[1:]
    cps = ("bf16", "int8")
    if "--comm-precision" in argv:
        i = argv.index("--comm-precision")
        raw = argv[i + 1] if i + 1 < len(argv) else "none"
        del argv[i: i + 2]
        cps = tuple(c for c in raw.split(",") if c and c != "none")
    mode = argv[0] if argv else "chol"
    tiny = jax.jit(lambda x: x + 1.0)
    t = jnp.zeros(())
    float(tiny(t))
    LAT = _min3(lambda: float(tiny(t)))
    if mode != "phases":
        print(f"device {jax.devices()[0].device_kind}, "
              f"rt latency {LAT*1e3:.2f} ms", flush=True)
    if mode == "chol":
        run_chol()
    elif mode == "lu":
        run_lu(*argv[1:2])
    elif mode == "lu-dist":
        run_lu_dist(*argv[1:2], cps=cps)
    elif mode == "cholesky":
        run_cholesky(*argv[1:2], cps=cps)
    elif mode == "gemm":
        run_gemm(*argv[1:2], cps=cps)
    elif mode == "panel":
        run_panel(*argv[1:2])
    else:
        run_phases(*argv[1:4])
