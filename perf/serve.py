"""Solver-service CLI (ISSUE 9): run, smoke-test, or chaos-test the
batched serving front-end.

The command-line face of ``elemental_tpu/serve``:

    python -m perf.serve run --requests 32 --n 96 --grid 2x2
                                            # drive a mixed workload
                                            #   through SolverService;
                                            #   per-request summary rows
                                            #   (#-prefixed) + one JSON
                                            #   tally line on stdout
    python -m perf.serve run --budget 0.5 --fault redistribute:nan:2:every
                                            # deadline-bounded requests
                                            #   under fault injection
    python -m perf.serve smoke              # the tools/check.sh gate:
                                            #   mixed-size serving on 1x1
                                            #   AND 2x2 grids, all ok,
                                            #   exec-cache reuse proven,
                                            #   plus the ISSUE-14 lstsq
                                            #   fast path and the async
                                            #   pipelined front (streamed
                                            #   callbacks, no thread
                                            #   leak); exit 1 on failure
    python -m perf.serve chaos              # the acceptance matrix
                                            #   {bitflip,scale,nan} x
                                            #   {redistribute,compute} x
                                            #   {oneshot,persistent} plus
                                            #   the abft-guarded qr op
                                            #   column (ISSUE 15: all
                                            #   kinds gate, one-panel
                                            #   recovery pinned) and the
                                            #   ISSUE-14 async column
                                            #   (mid-pipeline isolation +
                                            #   hard-stop flush):
                                            #   chaos_report/v1 on stdout,
                                            #   exit 1 on any violation
    python -m perf.serve fleet-smoke        # the tools/check.sh fleet
                                            #   gate (ISSUE 19): 2-grid
                                            #   CPU-mesh fleet --
                                            #   pipelined multi-tenant
                                            #   serving with grid/tenant
                                            #   provenance, structured
                                            #   quota rejects, grid-loss
                                            #   re-routing (replayed
                                            #   bit-identically), and
                                            #   saturation shedding with
                                            #   flat admitted latency

Runs are CPU-safe (same virtual 8-device mesh as ``perf.trace``);
float32 workloads so certification tolerances match the unforced-x64
CLI environment.  ``--fault`` shares ``perf.certify``'s
``target:kind:call[:every]`` syntax, now including the ``compute``
target.
"""
import json
import sys

from .trace import _bootstrap, _grid
from .certify import _parse_fault


def _workload(rng, count, n):
    """Mixed lu/hpd problems around size n (two adjacent buckets)."""
    import numpy as np
    out = []
    for i in range(count):
        op = "lu" if i % 2 else "hpd"
        ni = n if i % 3 else max(8, (3 * n) // 4)
        F = rng.normal(size=(ni, ni)).astype(np.float32)
        A = (F @ F.T / ni + ni * np.eye(ni)).astype(np.float32) \
            if op == "hpd" else F + ni * np.eye(ni, dtype=np.float32)
        B = rng.normal(size=(ni, 2)).astype(np.float32)
        out.append((op, A, B))
    return out


def _tally(svc, docs) -> dict:
    st: dict = {}
    for doc in docs.values():
        st[doc["status"]] = st.get(doc["status"], 0) + 1
    lat = sorted(d["latency_s"] for d in docs.values())
    return {"schema": "serve_run/v1", "requests": len(docs), "status": st,
            "p50_ms": 1e3 * lat[len(lat) // 2] if lat else None,
            "p99_ms": 1e3 * lat[min(len(lat) - 1,
                                    (99 * len(lat)) // 100)] if lat else None,
            "exec_cache": svc.executor.cache.stats()}


def cmd_run(requests, n, grid_spec, budget, faults, seed, fastpath) -> int:
    import numpy as np
    from elemental_tpu.resilience import FaultPlan, fault_injection
    from elemental_tpu.serve import SolverService
    grid = _grid(grid_spec)
    svc = SolverService(grid, fastpath=fastpath)
    rng = np.random.default_rng(seed)
    rejects = 0
    for op, A, B in _workload(rng, requests, n):
        rid = svc.submit(op, A, B, budget_s=budget)
        if isinstance(rid, dict):
            rejects += 1
            print(f"# reject: {rid['reason']} bucket={rid['bucket']}")
    if faults:
        plan = FaultPlan(seed=seed, faults=faults)
        with fault_injection(plan):
            docs = svc.drain()
        print(f"# faults fired: {plan.fired()}")
    else:
        docs = svc.drain()
    for rid in sorted(docs):
        d = docs[rid]
        res = d["residual"]
        print(f"# req {rid:3d} {d['op']:3s} n={d['n']:5d} "
              f"{d['status']:9s} path={d['path']:9s} "
              f"rung={str(d['rung']):8s} "
              f"residual={'-' if res is None else format(res, '.2e')} "
              f"latency={1e3 * d['latency_s']:.2f}ms")
    tally = _tally(svc, docs)
    tally["rejects"] = rejects
    print(json.dumps(tally))
    bad = sum(1 for d in docs.values()
              if d["status"] not in ("ok", "failed", "timed_out"))
    return 1 if bad else 0


def cmd_smoke() -> int:
    """The check.sh gate: mixed-size workloads must ALL certify on the
    fast path on 1x1 and 2x2 grids, the executable cache must be reused
    (second drain of the same geometry compiles nothing), and one
    escalated solve must certify end-to-end."""
    import numpy as np
    from elemental_tpu.obs import metrics as _metrics
    from elemental_tpu.serve import SolverService
    rc = 0
    for spec in ("1x1", "2x2"):
        grid = _grid(spec)
        svc = SolverService(grid)
        rng = np.random.default_rng(0)
        with _metrics.scoped() as reg:
            for op, A, B in _workload(rng, 8, 48):
                rid = svc.submit(op, A, B)
                if isinstance(rid, dict):
                    print(f"# smoke {spec}: unexpected reject {rid}")
                    rc = 1
            docs = svc.drain()
            ok = sum(d["status"] == "ok" for d in docs.values())
            # same geometries again: every batch must hit the exec cache
            for op, A, B in _workload(rng, 8, 48):
                svc.submit(op, A, B)
            docs2 = svc.drain()
            ok2 = sum(d["status"] == "ok" for d in docs2.values())
            compiles = sum(v for (name, labels), v in reg.counters(
                "serve_exec_cache_events").items()
                if dict(labels).get("event") == "compile")
            hits = sum(v for (name, labels), v in reg.counters(
                "serve_exec_cache_events").items()
                if dict(labels).get("event") == "hit")
        print(f"# smoke {spec}: ok={ok}/8 + {ok2}/8 "
              f"exec compiles={compiles} hits={hits}")
        if ok != 8 or ok2 != 8 or hits == 0:
            rc = 1
    # escalated path: fastpath off, must certify through certified_solve
    grid = _grid("2x2")
    svc = SolverService(grid, fastpath=False)
    rng = np.random.default_rng(1)
    F = rng.normal(size=(32, 32)).astype(np.float32)
    X, doc = svc.solve("lu", F + 32 * np.eye(32, dtype=np.float32),
                       rng.normal(size=(32, 2)).astype(np.float32))
    print(f"# smoke escalate: status={doc['status']} rung={doc['rung']}")
    if doc["status"] != "ok" or doc["path"] != "escalated":
        rc = 1
    # batched QR least-squares executor (ISSUE 14): a tall lstsq must
    # certify on the fast path against the normal-equations residual
    svc = SolverService(_grid("1x1"))
    At = rng.normal(size=(40, 12)).astype(np.float32)
    Bt = rng.normal(size=(40, 2)).astype(np.float32)
    X, doc = svc.solve("lstsq", At, Bt)
    print(f"# smoke lstsq: status={doc['status']} bucket={doc['bucket']}")
    if doc["status"] != "ok" or doc["path"] != "fastpath":
        rc = 1
    # async pipelined front (ISSUE 14): the same mixed workload streams
    # through AsyncSolverService -- all ok, every completion streamed
    # via callback, and the worker thread joined (no leak)
    import threading
    from elemental_tpu.serve import AsyncSolverService
    front = AsyncSolverService(grid=_grid("1x1"))
    streamed: list = []
    futs = [front.submit(op, A, B,
                         callback=lambda f: streamed.append(f.id))
            for op, A, B in _workload(rng, 8, 32)]
    outs = [f.result(timeout=300.0) for f in futs]
    ok_async = sum(d["status"] == "ok" for _, d in outs)
    front.shutdown(drain=True)
    leak = any(t.name.startswith("elemental-serve-worker") and t.is_alive()
               for t in threading.enumerate())
    occ = front.pipeline_stats()["occupancy"]
    print(f"# smoke async: ok={ok_async}/8 streamed={len(streamed)} "
          f"leak={leak} occupancy={occ:.2f}")
    if ok_async != 8 or len(streamed) != 8 or leak:
        rc = 1
    print("# serve smoke:", "ok" if rc == 0 else "FAILED")
    return rc


def cmd_fleet_smoke(seed) -> int:
    """The check.sh fleet gate (ISSUE 19): partition the virtual mesh
    into a 2-grid fleet and pin the four contracts end to end --
    (1) pipelined multi-tenant serving uses BOTH members and stamps
    grid/tenant provenance into every doc, with a clean shutdown;
    (2) tenant quotas reject structurally (``reason='quota'``);
    (3) the grid-loss chaos cell re-routes around an opened member,
    replayed bit-identically; (4) the saturation cell sheds structurally
    with flat admitted latency."""
    import threading
    import numpy as np
    from elemental_tpu.serve import SolverFleet, TenantQuota
    from elemental_tpu.serve.chaos import (fleet_replay_identical,
                                           run_fleet_grid_loss_cell,
                                           run_fleet_saturation_cell)
    rc = 0
    # leg 1: pipelined 2-grid fleet, two tenants, full provenance
    fleet = SolverFleet(grids=2, depth=2, shed=False)
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(16):
        ni = 24
        F = rng.normal(size=(ni, ni)).astype(np.float32)
        A = (F @ F.T / ni + ni * np.eye(ni)).astype(np.float32)
        B = rng.normal(size=(ni, 2)).astype(np.float32)
        reqs.append((A, B, f"t{i % 2}"))
    futs = [fleet.submit("hpd", A, B, tenant=t) for A, B, t in reqs]
    outs = [f.result(timeout=300.0) for f in futs]
    fleet.shutdown(drain=True)
    ok = sum(d["status"] == "ok" for _, d in outs)
    grids_used = {d["grid"] for _, d in outs}
    tenants = {d["tenant"] for _, d in outs}
    leak = any(t.name.startswith("elemental-serve-worker") and t.is_alive()
               for t in threading.enumerate())
    print(f"# fleet smoke pipelined: ok={ok}/16 grids={sorted(grids_used)} "
          f"tenants={sorted(tenants)} leak={leak}")
    if ok != 16 or grids_used != {"g0", "g1"} \
            or tenants != {"t0", "t1"} or leak:
        rc = 1
    # leg 2: max_outstanding quota rejects fast and structured
    fleet = SolverFleet(grids=2, pipelined=False, shed=False,
                        quotas={"q": TenantQuota(max_outstanding=4)})
    futs = [fleet.submit("hpd", A, B, tenant="q") for A, B, _ in reqs[:8]]
    quota_rej = [f.result(timeout=0)[1] for f in futs if f.done()
                 and f.result(timeout=0)[1].get("reason") == "quota"]
    fleet.drain()
    fleet.shutdown(drain=True)
    served = sum(1 for f in futs
                 if f.result(timeout=0)[1].get("status") == "ok")
    print(f"# fleet smoke quota: served={served} rejects={len(quota_rej)}")
    if len(quota_rej) != 4 or served != 4 \
            or any(d.get("tenant") != "q" for d in quota_rej):
        rc = 1
    # leg 3: grid loss re-routes, bit-identical replay
    cell, _ = run_fleet_grid_loss_cell(seed=seed + 7)
    replay = fleet_replay_identical(seed=seed + 7)
    print(f"# fleet smoke grid-loss: verdict={cell['verdict']} "
          f"ok={cell['ok']}/{cell['requests']} replay={replay}")
    if cell["violations"] or not replay:
        for v in cell["violations"]:
            print(f"#   violation: {v}")
        rc = 1
    # leg 4: saturation sheds structurally, admitted latency flat
    cell, _ = run_fleet_saturation_cell(seed=seed + 11)
    sheds = sum(w["sheds"] for w in cell["waves"])
    print(f"# fleet smoke saturation: verdict={cell['verdict']} "
          f"waves={cell['waves']}")
    if cell["violations"] or sheds == 0:
        for v in cell["violations"]:
            print(f"#   violation: {v}")
        rc = 1
    print("# fleet smoke:", "ok" if rc == 0 else "FAILED")
    return rc


def cmd_chaos(seed) -> int:
    from elemental_tpu.serve import chaos_matrix, replay_identical
    grid = _grid("2x2")
    report = chaos_matrix(grid, seed=seed)
    for cell in report["cells"]:
        print(f"# {cell['op']:3s} {cell['target']:12s} {cell['kind']:8s} "
              f"{cell['mode']:10s} -> {cell['verdict']:10s} "
              f"ok={cell['ok']}/{cell['requests']} fired={cell['fired']} "
              f"violations={len(cell['violations'])}")
    replay = replay_identical(grid, seed=seed + 16)
    print(f"# replay deterministic: {replay}")
    print(json.dumps(report))
    ok = report["ok"] and replay
    print("# serve chaos:", "ok" if ok else "FAILED")
    return 0 if ok else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd not in ("run", "smoke", "chaos", "fleet-smoke"):
        print(__doc__)
        raise SystemExit(f"unknown command {cmd!r}")
    requests, n, budget = 16, 64, None
    grid_spec = None
    seed = 0
    fastpath = True
    faults = []
    it = iter(argv)
    for arg in it:
        if arg == "--requests":
            requests = int(next(it))
        elif arg == "--n":
            n = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--budget":
            budget = float(next(it))
        elif arg == "--seed":
            seed = int(next(it))
        elif arg == "--fault":
            faults.append(next(it))
        elif arg == "--no-fastpath":
            fastpath = False
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            raise SystemExit(f"unexpected argument {arg!r}")
    _bootstrap()
    if cmd == "smoke":
        return cmd_smoke()
    if cmd == "chaos":
        return cmd_chaos(seed)
    if cmd == "fleet-smoke":
        return cmd_fleet_smoke(seed)
    fspecs = tuple(_parse_fault(s) for s in faults)
    return cmd_run(requests, n, grid_spec, budget, fspecs, seed, fastpath)


if __name__ == "__main__":
    raise SystemExit(main())
