"""Perf tooling: same-process A/B harness + reusable phase timing."""
