"""ABFT-guarded factorization CLI (ISSUE 11 + 15): run a checksum-
guarded lu/cholesky/qr, print the ``abft_report/v1``; optionally under
deterministic (windowed) fault injection.

The command-line face of ``elemental_tpu/resilience/abft``:

    python -m perf.abft run lu 256 --grid 2x2
                                            # lu(..., abft=True): one
                                            #   abft_report/v1 line on
                                            #   stdout, human summary
                                            #   rows # -prefixed
    python -m perf.abft run hpd --n 128 --nb 32 --comm-precision bf16
                                            # quantized wire: widened
                                            #   thresholds, still zero
                                            #   violations on clean data
    python -m perf.abft run qr --fault compute:bitflip --window 1:2
                                            # corrupt the panel factor at
                                            #   step 1; watch detection
                                            #   AND the single panel
                                            #   re-execution
    python -m perf.abft smoke               # the tools/check.sh gate:
                                            #   clean guarded runs on 1x1
                                            #   AND 2x2 for lu+cholesky+qr
                                            #   (zero violations), plus
                                            #   one injected fault per op
                                            #   recovered at panel
                                            #   granularity (recompute
                                            #   count pinned to 1); exit 1
                                            #   on any violation

``--fault`` is ``target:kind[:call[:every]]`` (see ``resilience.faults``);
``--window start:stop`` scopes the LAST ``--fault`` to those panel steps.
Runs are CPU-safe: the same virtual 8-device host mesh as ``perf.trace``.

Flags for ``run``: ``--n N`` (or positional; default 128), ``--nb NB``
(default 32), ``--grid RxC``, ``--dtype NAME``, ``--comm-precision P``,
``--seed S``, ``--fault SPEC`` (repeatable), ``--window A:B``,
``--retries K``, ``--json`` (report only, no summary rows).
"""
import json
import sys
import time

from .trace import _bootstrap, _grid


def _build(op, n, dtype, grid):
    import numpy as np
    import elemental_tpu as el
    rng = np.random.default_rng(0)
    F = rng.normal(size=(n, n)).astype(dtype)
    M = (F @ F.T / n + n * np.eye(n)).astype(dtype) if op == "hpd" \
        else (F + n * np.eye(n, dtype=dtype))
    return M, el.from_global(M, el.MC, el.MR, grid=grid)


def _residual(op, M, out):
    import numpy as np
    import elemental_tpu as el
    n = M.shape[0]
    if op == "lu":
        LU, perm = out
        g = np.asarray(el.to_global(LU))
        L = np.tril(g, -1) + np.eye(n, dtype=g.dtype)
        U = np.triu(g)
        return float(np.linalg.norm(M[np.asarray(perm)] - L @ U)
                     / np.linalg.norm(M))
    if op == "qr":
        Ap, tau = out
        Q = np.asarray(el.to_global(el.explicit_q(Ap, tau)))
        R = np.triu(np.asarray(el.to_global(Ap)))
        return float(np.linalg.norm(M - Q @ R) / np.linalg.norm(M))
    Lg = np.asarray(el.to_global(out))
    return float(np.linalg.norm(M - Lg @ Lg.conj().T) / np.linalg.norm(M))


def _run_one(op, n, nb, grid, dtype, faults, seed, retries,
             comm_precision=None):
    """One guarded factorization; returns (report, residual, plan, secs)."""
    import elemental_tpu as el
    from elemental_tpu.resilience import (AbftGuard, FaultPlan,
                                          fault_injection)
    M, A = _build(op, n, dtype, grid)
    guard = AbftGuard(max_retries=retries)
    if op == "lu":
        drv = lambda: el.lu(A, nb=nb, abft=guard,
                            comm_precision=comm_precision)
    elif op == "qr":
        drv = lambda: el.qr(A, nb=nb, abft=guard,
                            comm_precision=comm_precision)
    else:
        drv = lambda: el.cholesky(A, nb=nb, abft=guard,
                                  comm_precision=comm_precision)
    t0 = time.perf_counter()
    if faults:
        plan = FaultPlan(seed=seed, faults=faults)
        with fault_injection(plan):
            out = drv()
    else:
        plan = None
        out = drv()
    secs = time.perf_counter() - t0
    return guard.report(), _residual(op, M, out), plan, secs


def _parse_fault(spec: str):
    from elemental_tpu.resilience import FaultSpec
    parts = spec.split(":")
    if len(parts) < 2:
        raise SystemExit(f"--fault needs target:kind[:call[:every]], "
                         f"got {spec!r}")
    call = int(parts[2]) if len(parts) > 2 else 0
    every = len(parts) > 3 and parts[3] == "every"
    return FaultSpec(target=parts[0], kind=parts[1], call=call, every=every)


def cmd_run(op, n, nb, grid_spec, dtype, faults, seed, retries,
            comm_precision, as_json) -> int:
    grid = _grid(grid_spec)
    rep, res, plan, secs = _run_one(op, n, nb, grid, dtype, faults, seed,
                                    retries, comm_precision)
    if not as_json:
        print(f"# abft {op} n={n} nb={nb} "
              f"grid={grid.height}x{grid.width} "
              f"quantized_wire={rep['quantized_wire']} "
              f"wall={secs:.3f}s")
        print(f"#   panels={rep['panels']} checks={rep['checks']} "
              f"violations={len(rep['violations'])} "
              f"recompute_count={rep['recompute_count']} "
              f"recovered={rep['recovered_panels']} "
              f"unrecovered={rep['unrecovered_panels']}")
        for v in rep["violations"]:
            print(f"#   step={v['step']} attempt={v['attempt']} "
                  f"phase={v['phase']} kind={v['kind']} "
                  f"nonfinite={v['nonfinite']} columns={v['columns']}")
        if plan is not None:
            print(f"# faults fired: {plan.fired()} "
                  f"({json.dumps(plan.summary())})")
        print(f"# residual={res:.3e} -> "
              f"{'OK' if rep['ok'] else 'UNRECOVERED'}")
    print(json.dumps(rep))
    return 0 if rep["ok"] else 1


def cmd_smoke() -> int:
    """The check.sh gate: clean guarded runs on 1x1 and 2x2 for all
    three ops (zero violations, zero recomputes) + one windowed fault
    per op that must be detected at the injected panel and repaired by
    exactly ONE panel re-execution -- qr's injected kind is a bitflip,
    the class only checksums catch.  Small n, CPU-safe, exit 1 on any
    violation."""
    from elemental_tpu.resilience import FaultSpec
    rc = 0
    n, nb = 32, 8
    for spec in ("1x1", "2x2"):
        grid = _grid(spec)
        for op in ("lu", "hpd", "qr"):
            rep, res, _, secs = _run_one(op, n, nb, grid, "float32", (),
                                         0, 2)
            clean = (rep["ok"] and not rep["violations"]
                     and rep["recompute_count"] == 0 and res < 1e-4)
            print(f"# smoke {op} {spec}: checks={rep['checks']} "
                  f"violations={len(rep['violations'])} "
                  f"residual={res:.2e} wall={secs:.3f}s "
                  f"{'ok' if clean else 'FAILED'}")
            if not clean:
                rc = 1
    # one injected fault per op on the 2x2 grid: panel-granular recovery
    # (qr's cell is a BITFLIP -- the kind only the ISSUE-15 checksums
    # catch; health growth/nonfinite guards cannot see it)
    grid = _grid("2x2")
    for op, target, kind in (("lu", "redistribute", "scale"),
                             ("hpd", "compute", "scale"),
                             ("qr", "compute", "bitflip")):
        fault = FaultSpec(target, kind, nelem=2, window=(1, 2))
        rep, res, plan, _ = _run_one(op, n, nb, grid, "float32", (fault,),
                                     7, 2)
        steps = sorted({v["step"] for v in rep["violations"]})
        good = (plan.fired() >= 1 and steps == [1]
                and rep["recompute_count"] == 1
                and rep["recovered_panels"] == [1]
                and rep["ok"] and res < 1e-4)
        print(f"# smoke fault({op} {target} {kind}@panel1): "
              f"fired={plan.fired()} viol_steps={steps} "
              f"recompute={rep['recompute_count']} "
              f"recovered={rep['recovered_panels']} residual={res:.2e} "
              f"{'ok' if good else 'FAILED'}")
        if not good:
            rc = 1
    print("# abft smoke:", "ok" if rc == 0 else "FAILED")
    return rc


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd not in ("run", "smoke"):
        print(__doc__)
        raise SystemExit(f"unknown command {cmd!r}")
    if cmd == "smoke":
        _bootstrap()
        return cmd_smoke()
    pos = []
    n = nb = None
    grid_spec = None
    dtype, seed, retries, as_json = "float32", 0, 2, False
    comm_precision = None
    faults = []
    window = None
    it = iter(argv)
    for arg in it:
        if arg == "--n":
            n = int(next(it))
        elif arg == "--nb":
            nb = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--dtype":
            dtype = next(it)
        elif arg == "--seed":
            seed = int(next(it))
        elif arg == "--retries":
            retries = int(next(it))
        elif arg == "--comm-precision":
            comm_precision = next(it)
        elif arg == "--fault":
            faults.append(next(it))    # parsed after _bootstrap
        elif arg == "--window":
            window = tuple(int(x) for x in next(it).split(":"))
        elif arg == "--json":
            as_json = True
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            pos.append(arg)
    if not pos:
        raise SystemExit("run needs an op (lu/hpd/qr)")
    op = pos.pop(0)
    if op == "cholesky":
        op = "hpd"
    if op not in ("lu", "hpd", "qr"):
        raise SystemExit(f"unknown op {op!r}; expected lu, hpd, or qr")
    if pos and n is None:
        n = int(pos.pop(0))
    n = 128 if n is None else n
    nb = 32 if nb is None else nb
    _bootstrap()
    fspecs = [_parse_fault(s) for s in faults]
    if window is not None:
        if not fspecs:
            raise SystemExit("--window needs a preceding --fault")
        import dataclasses
        fspecs[-1] = dataclasses.replace(fspecs[-1], window=window)
    return cmd_run(op, n, nb, grid_spec, dtype, tuple(fspecs), seed,
                   retries, comm_precision, as_json)


if __name__ == "__main__":
    raise SystemExit(main())
