"""Certified-solve CLI (ISSUE 7): run a residual-certified solve, print
the certificate; optionally under deterministic fault injection.

The command-line face of ``elemental_tpu/resilience``:

    python -m perf.certify run lu 256 --grid 2x2
                                            # certified_solve('lu', ...):
                                            #   one solve_certificate/v1
                                            #   line on stdout, human
                                            #   summary rows # -prefixed
    python -m perf.certify run hpd --n 128 --tol 1e-12 --nb 32
    python -m perf.certify run lu --fault redistribute:nan:2 --seed 7
                                            # corrupt the 3rd redistribute
                                            #   payload; watch the ladder
                                            #   escalate (add ':every' to
                                            #   corrupt every call onward)
    python -m perf.certify smoke            # the tools/check.sh gate:
                                            #   clean certification on 1x1
                                            #   AND 2x2 grids for lu+hpd,
                                            #   plus one injected-fault
                                            #   escalation; exit 1 on any
                                            #   silent-garbage outcome

``--fault`` is ``target:kind:call[:every]`` with target one of
``redistribute`` / ``panel_spread`` and kind one of ``bitflip`` /
``scale`` / ``nan`` (see ``resilience.faults``).  Runs are CPU-safe: the
same virtual 8-device host mesh as ``perf.trace``.

Flags for ``run``: ``--n N`` (or positional; default 128), ``--nb NB``,
``--grid RxC`` (default 2x2 when >= 4 devices), ``--dtype NAME``,
``--tol X``, ``--seed S`` (fault plan seed, default 0), ``--fault SPEC``
(repeatable), ``--health/--no-health``, ``--json`` (certificate only,
no summary rows).
"""
import json
import sys

from .trace import _bootstrap, _grid


def _build(op, n, dtype, grid):
    import numpy as np
    import elemental_tpu as el
    rng = np.random.default_rng(0)
    F = rng.normal(size=(n, n)).astype(dtype)
    if op == "hpd":
        Fh = (F @ F.T / n + n * np.eye(n)).astype(dtype)
    else:
        Fh = (F + n * np.eye(n, dtype=dtype))
    B = rng.normal(size=(n, max(1, min(4, n)))).astype(dtype)
    A = el.from_global(Fh, el.MC, el.MR, grid=grid)
    Bd = el.from_global(B, el.MC, el.MR, grid=grid)
    return A, Bd


def _parse_fault(spec: str):
    from elemental_tpu.resilience import FaultSpec
    parts = spec.split(":")
    if len(parts) < 2:
        raise SystemExit(f"--fault needs target:kind[:call[:every]], "
                         f"got {spec!r}")
    target, kind = parts[0], parts[1]
    call = int(parts[2]) if len(parts) > 2 else 0
    every = len(parts) > 3 and parts[3] == "every"
    return FaultSpec(target=target, kind=kind, call=call, every=every)


def _run_one(op, n, nb, grid, dtype, tol, faults, seed, health):
    """One certified solve; returns (info, plan-or-None)."""
    from elemental_tpu.resilience import (FaultPlan, certified_solve,
                                          fault_injection)
    A, B = _build(op, n, dtype, grid)
    if faults:
        plan = FaultPlan(seed=seed, faults=faults)
        with fault_injection(plan):
            _, info = certified_solve(op, A, B, tol=tol, nb=nb,
                                      health=health)
        return info, plan
    _, info = certified_solve(op, A, B, tol=tol, nb=nb, health=health)
    return info, None


def cmd_run(op, n, nb, grid_spec, dtype, tol, faults, seed, health,
            as_json) -> int:
    grid = _grid(grid_spec)
    info, plan = _run_one(op, n, nb, grid, dtype, tol, faults, seed, health)
    if not as_json:
        print(f"# certify {op} n={n} grid={grid.height}x{grid.width} "
              f"tol={info['tol']:.3e}")
        for att in info["attempts"]:
            res = att["residual"]
            print(f"#   rung={att['rung']:8s} residual="
                  f"{'nan' if res is None else format(res, '.3e')} "
                  f"refine={att['refine_iters']} "
                  f"singular={att['singular']}")
        if plan is not None:
            print(f"# faults fired: {plan.fired()} "
                  f"({json.dumps(plan.summary())})")
        verdict = (f"CERTIFIED at rung {info['rung']!r}" if info["certified"]
                   else f"NOT certified (failing phase: "
                        f"{info['failing_phase']})")
        print(f"# {verdict}")
    print(json.dumps(info))
    return 0 if info["certified"] or info["failing_phase"] is not None else 1


def cmd_smoke() -> int:
    """The check.sh gate: clean certification on 1x1 and 2x2 for both ops
    + one injected persistent-NaN run that must be repaired or surfaced
    (never silent).  Small n, CPU-safe, exit 1 on any violation."""
    from elemental_tpu.resilience import FaultSpec
    rc = 0
    n, nb = 32, 8
    for spec in ("1x1", "2x2"):
        grid = _grid(spec)
        for op in ("lu", "hpd"):
            info, _ = _run_one(op, n, nb, grid, "float32", None, (), 0, True)
            ok = info["certified"]
            print(f"# smoke {op} {spec}: certified={ok} "
                  f"rung={info['rung']} residual={info['residual']}")
            if not ok:
                rc = 1
    # injected fault on the 2x2 grid: escalation must repair it (one-shot)
    grid = _grid("2x2")
    info, plan = _run_one("hpd", n, nb, grid, "float32", None,
                          (FaultSpec("panel_spread", "nan", call=0),), 0,
                          True)
    print(f"# smoke fault(one-shot nan): certified={info['certified']} "
          f"rung={info['rung']} fired={plan.fired()}")
    if not (plan.fired() and info["certified"]):
        rc = 1
    # persistent corruption: must be SURFACED, never silently certified
    info, plan = _run_one("lu", n, nb, grid, "float32", None,
                          (FaultSpec("redistribute", "nan", call=1,
                                     every=True),), 0, True)
    surfaced = (not info["certified"]) and info["failing_phase"] is not None
    print(f"# smoke fault(persistent nan): surfaced={surfaced} "
          f"failing_phase={info['failing_phase']} fired={plan.fired()}")
    if not (plan.fired() and surfaced):
        rc = 1
    print("# certify smoke:", "ok" if rc == 0 else "FAILED")
    return rc


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd not in ("run", "smoke"):
        print(__doc__)
        raise SystemExit(f"unknown command {cmd!r}")
    if cmd == "smoke":
        _bootstrap()
        return cmd_smoke()
    pos = []
    n = nb = tol = None
    grid_spec = None
    dtype, seed, health, as_json = "float32", 0, True, False
    faults = []
    it = iter(argv)
    for arg in it:
        if arg == "--n":
            n = int(next(it))
        elif arg == "--nb":
            nb = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--dtype":
            dtype = next(it)
        elif arg == "--tol":
            tol = float(next(it))
        elif arg == "--seed":
            seed = int(next(it))
        elif arg == "--fault":
            faults.append(next(it))
        elif arg == "--health":
            health = True
        elif arg == "--no-health":
            health = False
        elif arg == "--json":
            as_json = True
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            pos.append(arg)
    if not pos:
        raise SystemExit("run needs an op (lu/hpd)")
    op = pos.pop(0)
    if op == "cholesky":
        op = "hpd"
    if op not in ("lu", "hpd"):
        print("unknown op; registered ops:", file=sys.stderr)
        for o in ("lu", "hpd"):
            print(f"  {o}", file=sys.stderr)
        return 1
    if pos and n is None:
        n = int(pos.pop(0))
    if n is None:
        n = 128
    _bootstrap()
    fspecs = tuple(_parse_fault(s) for s in faults)
    return cmd_run(op, n, nb, grid_spec, dtype, tol, fspecs, seed, health,
                   as_json)


if __name__ == "__main__":
    raise SystemExit(main())
