"""Autotuning CLI: search / show / clear / explain (ISSUE 4).

The command-line face of ``elemental_tpu/tune``:

    python -m perf.tune explain cholesky                 # cost-model
                                                         #   breakdown per
                                                         #   candidate
    python -m perf.tune explain gemm --n 8192 --grid 2x2
    python -m perf.tune search cholesky --n 4096         # MEASURE the top
                                                         #   cost-ranked
                                                         #   configs, record
                                                         #   the winner
    python -m perf.tune show [op]                        # cache contents
    python -m perf.tune clear [op]                       # drop entries

``explain`` and the cache commands are trace-only / filesystem-only: they
force an 8-virtual-device CPU backend (like ``perf.comm_audit``) and run
identically on any host; ``explain`` doubles as the cost-model self-check
wired into ``tools/check.sh`` -- it exits non-zero if any candidate
scores non-finite/non-positive or if the pipelined cholesky/lu schedules
stop ranking at-or-above classic (the invariant ``tests/tune`` pins
against the golden comm plans).  ``search`` runs on the REAL backend (the
point is to measure) and persists a ``tuning_cache/v1`` winner that every
subsequent ``'auto'`` resolution on the same key picks up first.

Flags: ``--n N`` (square problem size; search default 2048 on TPU / 256
on CPU, explain default 2048), ``--grid RxC``, ``--dtype NAME``,
``--machine {tpu,gpu,cpu}`` (cost-model constants override), ``--top K``
(search: how many cost-ranked candidates to measure), ``--reps R``,
``--dry-run`` (search without writing the cache).
"""
import math
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bootstrap(force_cpu: bool) -> None:
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    if force_cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if force_cpu:
        jax.config.update("jax_platform_name", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


def _grid(spec: str | None):
    import jax
    from elemental_tpu.core.grid import Grid
    devs = jax.devices()
    if spec is None:
        if len(devs) >= 4:
            return Grid(devs[:4], height=2)
        return Grid(devs[:1])
    r, c = (int(x) for x in spec.split("x"))
    if r * c > len(devs):
        raise SystemExit(f"grid {r}x{c} needs {r * c} devices, "
                         f"have {len(devs)}")
    return Grid(devs[: r * c], height=r)


def _dims(op: str, n: int):
    return (n, n, n) if op == "gemm" else (n, n)


def _fmt_cfg(cfg: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(cfg.items()))


def cmd_explain(op, n, grid_spec, dtype_name, machine_name) -> int:
    import jax.numpy as jnp
    from elemental_tpu import tune
    from elemental_tpu.tune.cost_model import MACHINES
    grid = _grid(grid_spec)
    machine = MACHINES.get(machine_name) if machine_name else None
    dims = _dims(op, n)
    ctx, scored = tune.explain(op, gshape=dims, dtype=jnp.dtype(dtype_name),
                               grid=grid, machine=machine)
    mname = (machine.name if machine else ctx.backend)
    print(f"# {op} dims={tuple(dims)} dtype={ctx.dtype} "
          f"grid={ctx.grid_shape[0]}x{ctx.grid_shape[1]} "
          f"machine-model={mname}  ({len(scored)} candidates, best first)")
    print(f"{'config':42s} {'total':>10s} {'compute':>10s} {'latency':>10s} "
          f"{'bandwidth':>10s} {'rounds':>7s} {'bytes':>12s}")
    bad = 0
    for b in scored:
        t = b.total_s
        if not math.isfinite(t) or t <= 0:
            bad += 1
        print(f"{_fmt_cfg(b.config):42s} {t:10.3e} {b.compute_s:10.3e} "
              f"{b.latency_s:10.3e} {b.bandwidth_s:10.3e} {b.rounds:7.0f} "
              f"{b.comm_bytes:12.0f}")
    best = scored[0]
    print(f"chosen: {_fmt_cfg(best.config)}  "
          f"(cost model; a measured cache entry would take precedence)")
    if bad:
        print(f"SELF-CHECK FAILED: {bad} candidate(s) scored non-finite or "
              "non-positive", file=sys.stderr)
        return 1
    # pipelined-schedule invariant at the GOLDEN comm-plan geometry
    # (n=64, nb=16, tail crossover=32 -- the regime the golden snapshots
    # and tests/tune pin): lookahead+crossover must rank at or above
    # classic.  (At the displayed n the ordering may legitimately differ,
    # e.g. crossover >= n degenerates to gather-all + replicated factor.)
    if op in ("cholesky", "lu"):
        from elemental_tpu.tune import TuneContext
        from elemental_tpu.tune import cost_model as _cm
        gctx = TuneContext(op, (64, 64), "float32", ctx.grid_shape,
                           ctx.backend)

        def _score(la, xo):
            return _cm.score_config(
                op, {"nb": 16, "lookahead": la, "crossover": xo},
                ctx=gctx, grid=grid, dtype=jnp.float32, machine=machine)

        cl, xo = _score(False, 0), _score(True, 32)
        tag = (f"golden-geometry invariant (n=64 nb=16): "
               f"lookahead+crossover {xo.total_s:.3e} "
               f"({xo.prim_counts.get('all_gather', 0)} all_gathers) vs "
               f"classic {cl.total_s:.3e} "
               f"({cl.prim_counts.get('all_gather', 0)} all_gathers)")
        if xo.total_s > cl.total_s * (1 + 1e-9):
            print(f"SELF-CHECK FAILED: {tag}", file=sys.stderr)
            return 1
        print(f"self-check ok: {tag}")
    return 0


def cmd_search(op, n, grid_spec, dtype_name, top, reps, dry_run) -> int:
    import jax
    import jax.numpy as jnp
    from elemental_tpu.tune import measure
    grid = _grid(grid_spec)
    if n is None:
        on_tpu = jax.devices()[0].platform != "cpu"
        n = 2048 if on_tpu else 256
    dims = _dims(op, n)
    winner, measured, key = measure.search(
        op, dims, grid, jnp.dtype(dtype_name), top=top, reps=reps,
        write_cache=not dry_run, verbose=True)
    print(f"winner: {_fmt_cfg(winner.config)}  {winner.seconds * 1e3:.2f} ms "
          f"{winner.tflops:.3f} TFLOP/s")
    if dry_run:
        print("dry run: cache not written")
    else:
        print(f"recorded: {key.path()}")
    return 0


def cmd_show(op) -> int:
    from elemental_tpu import tune
    from elemental_tpu.obs import metrics as obs_metrics
    docs, rejects = tune.cache_scan()
    if op:
        docs = [d for d in docs if d.get("op") == op]
        rejects = [r for r in rejects if r["file"].startswith(f"{op}__")]
    print(f"# cache dir: {tune.cache_dir()}  ({len(docs)} entries, "
          f"{len(rejects)} invalid)")
    for d in docs:
        metric = d.get("metric", {})
        extra = f"  {metric.get('tflops', 0):.3f} TFLOP/s" if metric else ""
        print(f"{d['_file']:64s} {_fmt_cfg(d['config'])} "
              f"[{d.get('source', '?')}]{extra}")
    for r in rejects:
        # a schema-mismatch file used to be rejected with zero visibility;
        # now it is both printed here and counted on the metrics registry
        print(f"INVALID {r['file']:56s} ({r['reason']}; ignored by the "
              "resolver)")
    events = obs_metrics.current().counters("tune_cache_events")
    if events:
        tally: dict = {}
        for (_, labels), v in events.items():
            ev = dict(labels).get("event", "?")
            tally[ev] = tally.get(ev, 0) + v
        row = "  ".join(f"{k}={int(v)}" for k, v in sorted(tally.items()))
        print(f"# tune_cache_events (this process): {row}")
    return 0


def cmd_clear(op) -> int:
    from elemental_tpu import tune
    n = tune.clear_cache(op)
    print(f"removed {n} entr{'y' if n == 1 else 'ies'} from "
          f"{tune.cache_dir()}")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd not in ("search", "show", "clear", "explain"):
        print(__doc__)
        raise SystemExit(f"unknown command {cmd!r}")
    op = None
    n = None
    grid_spec = dtype_name = machine_name = None
    top, reps, dry_run = 8, 3, False
    dtype_name = "float32"
    it = iter(argv)
    for arg in it:
        if arg == "--n":
            n = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--dtype":
            dtype_name = next(it)
        elif arg == "--machine":
            machine_name = next(it)
        elif arg == "--top":
            top = int(next(it))
        elif arg == "--reps":
            reps = int(next(it))
        elif arg == "--dry-run":
            dry_run = True
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            op = arg
    if cmd in ("search", "explain") and op is None:
        raise SystemExit(f"{cmd} needs an op "
                         "(cholesky/lu/qr/gemm/trsm/herk)")
    _bootstrap(force_cpu=cmd != "search")
    if cmd == "explain":
        return cmd_explain(op, n if n is not None else 2048, grid_spec,
                           dtype_name, machine_name)
    if cmd == "search":
        return cmd_search(op, n, grid_spec, dtype_name, top, reps, dry_run)
    if cmd == "show":
        return cmd_show(op)
    return cmd_clear(op)


if __name__ == "__main__":
    try:
        import signal
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)   # `| head` etc.
    except (ImportError, AttributeError, ValueError):
        pass
    raise SystemExit(main())
