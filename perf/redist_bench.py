"""Redistribution microbench: src->dst x geometry x path matrix (ISSUE 12).

Times the SAME redistribution through the chained multi-hop engine
(``path='chain'``) and the one-shot compiled plan (``path='direct'``) on
the live device grid, roofline-bracketed like ``perf/ab_harness.py`` so
chip weather is factored out of an A/B pair.  Each row prints as one
``redist_bench/v1`` JSON line:

    {"schema": "redist_bench/v1", "pair": "[MC,MR]->[MR,STAR]",
     "grid": "2x4", "n": 4096, "path": "direct", "plan": "a2a",
     "rounds": 1, "model_bytes": ..., "seconds": ..., "gbps": ...,
     "roof_tflops": [r_before, r_after], "match": true}

``model_bytes`` is the ring-model per-device wire estimate (the same
alpha-beta terms the tuner's cost model and the ``'auto'`` path arbiter
price: chain legs at all_gather/all_to_all/ppermute ring cost, the direct
plan at its single-collective slot volume), so ``gbps`` is MODEL bytes
over measured seconds -- comparable across paths, not a NIC counter.
``match`` cross-checks the two paths bit-identically via ``to_global``
before timing (the bench never reports a speedup for a wrong answer).

Usage:

    python -m perf.redist_bench                   # default pair matrix on
                                                  #   the full device grid
    python -m perf.redist_bench --smoke           # 1x1 grid, n=64, two
                                                  #   pairs, tiny roofline
    python -m perf.redist_bench --n 4096 --grid 2x4 --paths chain,direct
    python -m perf.redist_bench --pairs "MC,MR->MR,STAR;VC,STAR->VR,STAR"
    python -m perf.redist_bench --record   # also least-squares-fit alpha
                                           #   (s/round) + bandwidth from the
                                           #   measured rows and save them as
                                           #   redist_constants/v1 in the
                                           #   tuning cache; the engine's
                                           #   'auto' arbitration consults
                                           #   them before the ring model

On a CPU-only host run under
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (set automatically
when unset) so the multi-chip grids exist; timings there are functional,
not representative -- the bench is for TPU pods, the smoke mode for CI.
"""
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default src->dst matrix: one representative of each plan regime --
#: the 3-hop gather chains gemm feeds on, a pure relabeling (ppermute),
#: a replication (fused all_gather chain vs one-shot a2a+concat), and a
#: transpose-style move.
DEFAULT_PAIRS = (
    ("MC,MR", "MR,STAR"),
    ("MC,MR", "STAR,VC"),
    ("MC,MR", "STAR,STAR"),
    ("VC,STAR", "VR,STAR"),
    ("MC,MR", "MR,MC"),
    ("VC,STAR", "MC,STAR"),
)

SMOKE_PAIRS = DEFAULT_PAIRS[:2]


def _bootstrap():
    """Make multi-device grids exist on CPU-only hosts (virtual devices
    must be requested BEFORE jax initializes); never downgrades a TPU."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _min_t(fn, reps):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _dist_pair(spec: str):
    import elemental_tpu as el
    by_name = {d.value: d for d in
               (el.MC, el.MR, el.VC, el.VR, el.STAR, el.MD, el.CIRC)}
    try:
        c, r = (by_name[s.strip().upper()] for s in spec.split(","))
    except (KeyError, ValueError):
        raise SystemExit(f"bad dist pair {spec!r}; want e.g. 'MC,MR'")
    return (c, r)


def _parse_pairs(arg: str):
    out = []
    for leg in arg.split(";"):
        src, _, dst = leg.partition("->")
        if not dst:
            raise SystemExit(f"bad pair {leg!r}; want 'MC,MR->MR,STAR'")
        out.append((src.strip(), dst.strip()))
    return tuple(out)


def _label(pair) -> str:
    return f"[{pair[0].value},{pair[1].value}]"


def _roofline(n: int) -> float:
    """Matmul roofline at size n (chip-weather bracket, ab_harness idiom)."""
    import jax
    import jax.numpy as jnp
    HI = jax.lax.Precision.HIGHEST
    x = jax.random.normal(jax.random.PRNGKey(9), (n, n), jnp.float32)
    mm = jax.jit(lambda a: jnp.matmul(a, a, precision=HI))
    float(mm(x)[0, 0])                       # compile, untimed
    dt = max(_min_t(lambda: float(mm(x)[0, 0]), 3), 1e-9)
    return 2 * n ** 3 / dt / 1e12


def _model_bytes(src, dst, gshape, grid_shape, itemsize, path):
    """Ring-model per-device wire estimate for one redistribution: the
    chain priced leg by leg, the direct path by its compiled plan."""
    from elemental_tpu.redist.engine import chain_cost
    from elemental_tpu.redist.plan import compile_plan
    if path == "direct":
        plan = compile_plan(src, dst, gshape, grid_shape)
        if plan is not None:
            return plan.rounds, plan.wire_bytes(itemsize), plan.kind
        path = "chain"                       # engine falls back identically
    rounds, nbytes = chain_cost(src, dst, gshape, grid_shape, itemsize)
    return rounds, nbytes, "chain"


def run_pair(grid, n, src, dst, paths, reps=3, check=True):
    """Time one src->dst move under each path; returns a list of row dicts
    (no JSON printing -- the CLI and bench.py both feed from here)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import elemental_tpu as el

    host = np.asarray(
        np.arange(n * n, dtype=np.float32).reshape(n, n) % 1013 / 7.0)
    A = el.from_global(jnp.asarray(host), src[0], src[1], grid)
    grid_shape = (grid.height, grid.width)
    itemsize = jnp.dtype(A.dtype).itemsize

    match = None
    if check:
        outs = [np.asarray(el.to_global(
            el.redistribute(A, dst[0], dst[1], path=p))) for p in paths]
        match = all(np.array_equal(outs[0], o) for o in outs[1:]) \
            and np.array_equal(outs[0], host)

    rows = []
    for path in paths:
        out = el.redistribute(A, dst[0], dst[1], path=path)   # warm cache
        jax.block_until_ready(out.local)

        def _step(p=path):
            o = el.redistribute(A, dst[0], dst[1], path=p)
            float(jnp.ravel(o.local)[0])     # force completion (ab_harness)

        dt = max(_min_t(_step, reps), 1e-9)
        rounds, nbytes, plan_kind = _model_bytes(
            src, dst, (n, n), grid_shape, itemsize, path)
        rows.append({
            "schema": "redist_bench/v1",
            "pair": f"{_label(src)}->{_label(dst)}",
            "grid": f"{grid.height}x{grid.width}",
            "n": n,
            "path": path,
            "plan": plan_kind,
            "rounds": rounds,
            "model_bytes": nbytes,
            "seconds": dt,
            "gbps": nbytes / dt / 1e9,
            "match": match,
        })
    return rows


def p2p_gbps(grid, n=None, reps=3):
    """Informational chain-vs-direct GB/s for ONE representative move
    ([MC,MR]->[MR,STAR], the 3-hop chain gemm's stationary-C schedule
    feeds on) -- the ``redist_p2p_gbps`` row bench.py embeds in its obs
    block.  Returns ``{"chain": gbps, "direct": gbps, ...}``; on a 1x1
    grid both model-byte counts are zero, so both rates report 0.0.
    Never raises past bad geometry: callers gate it defensively anyway."""
    import elemental_tpu as el
    if n is None:
        n = 256 if grid.size <= 8 else 4096
    src = _dist_pair("MC,MR")
    dst = _dist_pair("MR,STAR")
    rows = run_pair(grid, n, src, dst, ("chain", "direct"),
                    reps=reps, check=False)
    doc = {"pair": rows[0]["pair"], "n": n,
           "grid": rows[0]["grid"]}
    for row in rows:
        doc[row["path"]] = round(row["gbps"], 4)
    return doc


def fit_constants(rows):
    """Least-squares fit ``seconds = alpha * rounds + model_bytes / bw``
    over measured rows; returns ``(alpha_s, bw_bytes_per_s, nsamples)`` or
    None when the system is degenerate (e.g. a 1x1 grid where every row
    has zero rounds and zero bytes -- nothing to fit)."""
    import numpy as np
    samples = [(row["rounds"], row["model_bytes"], row["seconds"])
               for row in rows if row["rounds"] > 0 and row["seconds"] > 0]
    if len(samples) < 2:
        return None
    M = np.array([[float(r_), float(b_)] for r_, b_, _ in samples])
    t = np.array([s_ for _, _, s_ in samples])
    if np.linalg.matrix_rank(M) < 2:
        return None
    coef, *_ = np.linalg.lstsq(M, t, rcond=None)
    alpha = float(max(coef[0], 1e-9))        # s per collective round
    beta = float(max(coef[1], 1e-15))        # s per wire byte
    return alpha, 1.0 / beta, len(samples)


def record_constants(grid_shape, rows):
    """Fit + persist ``redist_constants/v1`` for one grid; returns the doc
    (with ``_path``) or None when the fit is degenerate."""
    import jax
    from elemental_tpu.tune.cache import (load_redist_constants,
                                          save_redist_constants)
    fit = fit_constants(rows)
    if fit is None:
        return None
    alpha, bw, nsamples = fit
    backend = jax.default_backend()
    path = save_redist_constants(grid_shape, backend, alpha, bw,
                                 nsamples=nsamples)
    doc = dict(load_redist_constants(grid_shape, backend) or
               {"schema": "redist_constants/v1", "alpha_s": alpha,
                "bw_bytes_per_s": bw})
    doc["_path"] = path
    return doc


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    _bootstrap()
    import jax
    import elemental_tpu as el

    smoke = "--smoke" in argv
    record = "--record" in argv
    n = 64 if smoke else None
    grids = None
    paths = ("chain", "direct")
    pairs = SMOKE_PAIRS if smoke else DEFAULT_PAIRS
    reps = 3
    it = iter(argv)
    for arg in it:
        if arg in ("--smoke", "--record"):
            continue
        elif arg == "--n":
            n = int(next(it))
        elif arg == "--grid":
            r, c = next(it).split("x")
            grids = [(int(r), int(c))]
        elif arg == "--paths":
            paths = tuple(p.strip() for p in next(it).split(","))
        elif arg == "--pairs":
            pairs = _parse_pairs(next(it))
        elif arg == "--reps":
            reps = int(next(it))
        else:
            raise SystemExit(f"unknown flag {arg!r}")

    devs = jax.devices()
    if grids is None:
        if smoke:
            grids = [(1, 1)]
        else:
            # full device grid, plus a 1-row layout when it differs (the
            # same chips as a different geometry move different bytes)
            p = len(devs)
            r = 1
            for q in range(int(p ** 0.5), 0, -1):
                if p % q == 0:
                    r = q
                    break
            grids = [(r, p // r)] if r == 1 else [(r, p // r), (1, p)]
    if n is None:
        n = 256 if devs[0].platform == "cpu" else 4096

    roof_n = 256 if smoke or devs[0].platform == "cpu" else 8192
    for gr, gc in grids:
        if gr * gc > len(devs):
            print(f"# skip {gr}x{gc}: only {len(devs)} device(s)",
                  file=sys.stderr)
            continue
        grid = el.Grid(devs[: gr * gc], height=gr)
        r0 = _roofline(roof_n)
        rows = []
        for src_s, dst_s in pairs:
            src, dst = _dist_pair(src_s), _dist_pair(dst_s)
            rows += run_pair(grid, n, src, dst, paths, reps=reps)
        r1 = _roofline(roof_n)
        for row in rows:
            row["roof_tflops"] = [round(r0, 3), round(r1, 3)]
            print(json.dumps(row))
            if row["match"] is False:
                print(f"# MISMATCH {row['pair']} on {row['grid']}",
                      file=sys.stderr)
                return 1
        if record:
            doc = record_constants((gr, gc), rows)
            if doc is None:
                print(f"# record: degenerate fit on {gr}x{gc} "
                      f"(no multi-device rows), nothing written",
                      file=sys.stderr)
            else:
                print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
