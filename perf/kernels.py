"""Pallas panel-kernel smoke CLI (ISSUE 17).

    python -m perf.kernels smoke      # interpret-mode clean runs of all
                                      #   three fused panel primitives
                                      #   through the real drivers on the
                                      #   1x1 and 2x2 grids

``smoke`` is the cheap always-on gate ``tools/check.sh kernels`` runs:
every driver factors a small matrix with ``panel_impl='pallas'`` (the
fused kernels run under ``pallas_call(interpret=True)`` off-TPU), the
factor residuals must sit inside the documented bounds, and the LU
pivot sequence must be IDENTICAL to the XLA ladder's -- the bit-twin
contract of ``kernels.lu_panel``.  Exits non-zero on any violation, so
CI catches a broken kernel without waiting for the full pytest sweep
(the heavyweight sweeps live in tests/kernels/, slow-marked).
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: residual ceilings of the smoke gate, generous multiples of the
#: measured float32 residuals (~1e-7 at n=48; see tests/kernels/ for the
#: tight per-primitive bounds on bigger sweeps)
TOL = 5e-5


def _bootstrap():
    """CPU-friendly device setup BEFORE jax initializes (the comm_audit
    convention): 8 virtual devices so the 2x2 grid exists off-hardware."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _run_smoke() -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp
    import elemental_tpu as el

    n, nb = 48, 8
    rng = np.random.default_rng(17)
    F = rng.normal(size=(n, n)).astype(np.float32)
    S = (F @ F.T / n + n * np.eye(n)).astype(np.float32)
    failures = []

    def check(tag, resid, tol=TOL):
        ok = resid < tol
        print(f"{'ok ' if ok else 'FAIL'} {tag:40s} resid {resid:.2e}"
              f" (tol {tol:.0e})", flush=True)
        if not ok:
            failures.append(tag)

    for r, c in ((1, 1), (2, 2)):
        grid = el.Grid(jax.devices()[: r * c], height=r)
        A = el.from_global(jnp.asarray(F), el.MC, el.MR, grid=grid)
        Aspd = el.from_global(jnp.asarray(S), el.MC, el.MR, grid=grid)

        # lu: residual + pivot bit-identity vs the XLA ladder
        LU, perm = el.lu(A, nb=nb, panel_impl="pallas")
        lu_ = np.asarray(el.to_global(LU))
        L = np.tril(lu_, -1) + np.eye(n, dtype=np.float32)
        U = np.triu(lu_)
        check(f"lu {r}x{c} pallas",
              np.linalg.norm(L @ U - F[np.asarray(perm)])
              / np.linalg.norm(F))
        _, perm_x = el.lu(A, nb=nb, panel_impl="xla")
        if not np.array_equal(np.asarray(perm), np.asarray(perm_x)):
            print(f"FAIL lu {r}x{c} pivot sequence differs from xla",
                  flush=True)
            failures.append(f"lu {r}x{c} pivots")
        else:
            print(f"ok  lu {r}x{c} pivots identical to xla", flush=True)

        # cholesky: factor residual of the fused _potrf_inv
        Ld = el.cholesky(Aspd, nb=nb, panel_impl="pallas")
        lg = np.asarray(el.to_global(Ld))
        check(f"cholesky {r}x{c} pallas",
              np.linalg.norm(lg @ lg.T - S) / np.linalg.norm(S))

        # qr: reconstruction through the geqrf reflectors of the fused
        # larfg+larft kernel (Q = H_0 ... H_{k-1}, R = triu(packed))
        packed, tau = el.qr(A, nb=nb, panel_impl="pallas")
        pg = np.asarray(el.to_global(packed))
        tg = np.asarray(tau)
        Qm = np.eye(n, dtype=np.float64)
        for j in range(n):
            v = np.zeros(n)
            v[j] = 1.0
            v[j + 1:] = pg[j + 1:, j]
            Qm = Qm @ (np.eye(n) - tg[j] * np.outer(v, v))
        Rm = np.triu(pg)
        check(f"qr {r}x{c} pallas recon",
              np.linalg.norm(Qm @ Rm - F) / np.linalg.norm(F))
        check(f"qr {r}x{c} pallas ortho",
              np.linalg.norm(Qm.T @ Qm - np.eye(n)) / np.sqrt(n))

    if failures:
        print(f"SMOKE FAILED: {failures}", flush=True)
        return 1
    print("kernels smoke OK", flush=True)
    return 0


def main(argv) -> int:
    mode = argv[0] if argv else "smoke"
    if mode != "smoke":
        print(__doc__)
        return 2
    _bootstrap()
    return _run_smoke()


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
