"""Compat shim: ``PhaseTimer`` now lives in ``elemental_tpu/obs/``.

The per-phase wall-clock attribution tool (ISSUEs 1-2) was folded into
the unified observability subsystem (ISSUE 5); this module re-exports it
so every historical import path keeps working unchanged::

    from perf.phase_timer import PhaseTimer, SCHEMA, PHASES

The ``phase_timings/v1`` schema is byte-identical (pinned by
``tests/perf/test_phase_smoke.py``); ``PhaseTimer`` is now a thin wrapper
over ``elemental_tpu.obs.Tracer`` -- see
``elemental_tpu/obs/phase_timer.py`` for the full documentation, and
``python -m perf.trace`` for the full-subsystem CLI (nested spans,
collective events, Perfetto export, metrics).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elemental_tpu.obs.phase_timer import (  # noqa: E402,F401
    PHASES, SCHEMA, PhaseTimer)
