"""Reusable per-phase wall-clock attribution for blocked drivers.

The observability half of the look-ahead LU/Cholesky work (ISSUEs 1-2): any
driver that accepts a ``timer`` argument (today ``lapack.lu.lu`` and
``lapack.cholesky.cholesky``, both grid and sequential paths) calls
``timer.tick(phase, step, *arrays)`` at its phase boundaries.  The timer
synchronizes on the phase's outputs (``jax.block_until_ready``) and charges
the elapsed wall-clock since the previous tick to ``(phase, step)``, so a
run yields a machine-readable panel / swap / solve / update breakdown per
blocked step.

Usage (EAGER -- wrapping the driver in jit would fuse the phases away and
make the ticks no-ops on tracers):

    from perf.phase_timer import PhaseTimer
    t = PhaseTimer()
    LU, perm = el.lu(A, nb=2048, timer=t)
    print(t.json(driver="lu", n=n, nb=2048))

``python perf/ab_harness.py phases [lu|cholesky]`` is the CLI wrapper; the
JSON schema is pinned by ``tests/perf/test_phase_smoke.py`` so the
observability path cannot silently rot.  Schema (``phase_timings/v1``;
LU emits panel/swap/solve/update, Cholesky diag/panel/spread/update and
``tail`` on the crossover step)::

    {"schema": "phase_timings/v1",
     "steps":  [{"step": 0, "panel": s, "swap": s, "solve": s, "update": s},
                ...],                      # seconds; phases may be absent
     "totals": {"panel": s, "swap": s, "solve": s, "update": s},
     "total_seconds": s,
     ...caller metadata (driver, n, nb, device, ...)}

Timing note: eager dispatch is asynchronous, so the sync INSIDE tick is
what makes the attribution honest; each phase's time includes its share of
dispatch overhead (the same caveat as any op-by-op profile).  Use the A/B
modes of ``perf/ab_harness.py`` for end-to-end fused-program numbers.
"""
from __future__ import annotations

import json
import time

import jax

SCHEMA = "phase_timings/v1"

#: canonical phase order for reports (drivers emit a subset: LU ticks
#: panel/swap/solve/update, Cholesky diag/panel/spread/update + tail)
PHASES = ("diag", "panel", "swap", "solve", "spread", "update", "tail")


class PhaseTimer:
    """Accumulates (phase, step, seconds) records from a driver's ticks."""

    def __init__(self):
        self.records: list[dict] = []
        self._t = None

    def start(self):
        """(Re)arm the clock at a driver's entry."""
        self._t = time.perf_counter()

    def tick(self, phase, step, *arrays):
        """Block on ``arrays`` and charge the elapsed time to (phase, step)."""
        if arrays:
            jax.block_until_ready(arrays)
        now = time.perf_counter()
        if self._t is None:
            self._t = now
        self.records.append({"phase": str(phase), "step": int(step),
                             "seconds": now - self._t})
        self._t = now

    def report(self, **meta) -> dict:
        """The schema dict above; ``meta`` keys merge at top level."""
        steps: dict[int, dict] = {}
        totals: dict[str, float] = {}
        for r in self.records:
            d = steps.setdefault(r["step"], {})
            d[r["phase"]] = d.get(r["phase"], 0.0) + r["seconds"]
            totals[r["phase"]] = totals.get(r["phase"], 0.0) + r["seconds"]
        out = {
            "schema": SCHEMA,
            "steps": [{"step": k, **v} for k, v in sorted(steps.items())],
            "totals": {p: totals[p] for p in PHASES if p in totals}
            | {p: t for p, t in totals.items() if p not in PHASES},
            "total_seconds": sum(totals.values()),
        }
        out.update(meta)
        return out

    def json(self, **meta) -> str:
        return json.dumps(self.report(**meta))
