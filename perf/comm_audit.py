"""Comm-plan audit CLI: extract / pin / lint driver collective schedules.

The command-line face of ``elemental_tpu/analysis`` (ISSUE 3).  Traces
registered distributed drivers abstractly (no device execution; forces an
8-virtual-device CPU backend, so it runs anywhere) and works with the
``comm_plan/v1`` JSON documents:

    python -m perf.comm_audit audit cholesky           # print plans (all
                                                       #   cholesky_* x grids)
    python -m perf.comm_audit audit lu_classic --grid 2x2 --events
    python -m perf.comm_audit audit --all
    python -m perf.comm_audit diff                     # all drivers vs the
                                                       #   golden snapshots
    python -m perf.comm_audit diff cholesky --update-golden
    python -m perf.comm_audit lint --all               # rule-based lints;
                                                       #   exit 1 on findings
    python -m perf.comm_audit lint --all --fix-hint    # + print each
                                                       #   finding's rewrite

Memory-plan twins (ISSUE 18) of the three commands work with the
``memory_plan/v1`` documents (per-device peak live bytes, high-water
timeline, replicated-materialization census) and the EL006-EL009 rules:

    python -m perf.comm_audit mem cholesky             # print memory plans
    python -m perf.comm_audit mem-diff                 # all drivers vs
                                                       #   tests/golden/memory_plans/
    python -m perf.comm_audit mem-diff --update-golden
    python -m perf.comm_audit mem-lint --all           # EL006-EL009; exit 1
                                                       #   on findings

``diff``/``mem-diff`` exit non-zero when any plan deviates from its
golden snapshot under ``tests/golden/comm_plans/`` /
``tests/golden/memory_plans/`` (regenerate with ``--update-golden``
after an INTENTIONAL schedule change and review the diff like any other
code change); ``lint``/``mem-lint`` exit non-zero on any finding.
``tools/check.sh`` runs them as the pre-commit gate (``static`` gate for
the memory side).

A driver name selects by exact match or prefix: ``audit cholesky`` covers
``cholesky_classic`` / ``cholesky_lookahead`` / ``cholesky_crossover``.
"""
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_DIR = os.path.join(_REPO, "tests", "golden", "comm_plans")
MEM_GOLDEN_DIR = os.path.join(_REPO, "tests", "golden", "memory_plans")

#: grids every audit runs on: the degenerate single device and the
#: smallest genuinely 2-D grid (both redistribution regimes)
GRIDS = ((1, 1), (2, 2))


def _bootstrap():
    """CPU backend with 8 virtual devices, BEFORE jax initializes."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platform_name", "cpu")
    # match the test harness (tests/conftest.py): the comm plans are
    # x64-invariant (their goldens pass in both modes) but the MEMORY
    # plans are not -- integer pivot avals double under x64 -- so the
    # CLI must trace in the same mode the golden gate tests run in
    jax.config.update("jax_enable_x64", True)
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


def _grid(r: int, c: int):
    import jax
    from elemental_tpu.core.grid import Grid
    return Grid(jax.devices()[: r * c], height=r)


def _select(name: str | None) -> list:
    from elemental_tpu import analysis as an
    names = an.driver_names()
    if name is None or name == "--all":
        return names
    if name in names:
        return [name]
    picked = [d for d in names if d.startswith(name)]
    if not picked:
        raise SystemExit(f"unknown driver {name!r}; known: {names}")
    return picked


def golden_path(driver: str, grid) -> str:
    return os.path.join(GOLDEN_DIR, f"{driver}__{grid[0]}x{grid[1]}.json")


def mem_golden_path(driver: str, grid) -> str:
    return os.path.join(MEM_GOLDEN_DIR,
                        f"{driver}__{grid[0]}x{grid[1]}.json")


def _trace(driver: str, grid, n=None, nb=None):
    from elemental_tpu import analysis as an
    kwargs = {}
    if n is not None:
        kwargs["n"] = n
    if nb is not None:
        kwargs["nb"] = nb
    return an.trace_driver(driver, _grid(*grid), **kwargs)


def cmd_audit(drivers, grids, n, nb, events: bool) -> int:
    for driver in drivers:
        for grid in grids:
            plan, _, _ = _trace(driver, grid, n, nb)
            print(plan.to_json(events=events))
    return 0


def cmd_diff(drivers, grids, n, nb, update: bool) -> int:
    from elemental_tpu.analysis import golden_doc, diff_docs
    bad = 0
    for driver in drivers:
        for grid in grids:
            plan, _, _ = _trace(driver, grid, n, nb)
            doc = golden_doc(plan)
            path = golden_path(driver, grid)
            tag = f"{driver} {grid[0]}x{grid[1]}"
            if update:
                os.makedirs(GOLDEN_DIR, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=False)
                    f.write("\n")
                print(f"updated {tag}: {os.path.relpath(path, _REPO)}")
                continue
            if not os.path.exists(path):
                print(f"MISSING golden for {tag} ({path}); "
                      f"run with --update-golden")
                bad += 1
                continue
            with open(path) as f:
                golden = json.load(f)
            lines = diff_docs(golden, doc)
            if lines:
                bad += 1
                print(f"DIFF {tag}:")
                for ln in lines:
                    print(f"  {ln}")
            else:
                print(f"ok {tag}")
    return 1 if bad else 0


def cmd_lint(drivers, grids, n, nb, fix_hint: bool = False) -> int:
    from elemental_tpu.analysis import lint_plan
    total = 0
    for driver in drivers:
        for grid in grids:
            plan, closed, log = _trace(driver, grid, n, nb)
            findings = lint_plan(plan, log, closed)
            for f in findings:
                print(f"{driver} {grid[0]}x{grid[1]}: {f}")
                if fix_hint and f.fix_hint:
                    print(f"  fix: {f.fix_hint}")
            total += len(findings)
    print(f"{total} finding(s)")
    return 1 if total else 0


def _trace_mem(driver: str, grid, n=None, nb=None):
    from elemental_tpu.analysis import trace_memory
    return trace_memory(driver, _grid(*grid), n=n, nb=nb)


def cmd_mem(drivers, grids, n, nb) -> int:
    for driver in drivers:
        for grid in grids:
            mplan, _, _ = _trace_mem(driver, grid, n, nb)
            print(mplan.to_json())
    return 0


def cmd_mem_diff(drivers, grids, n, nb, update: bool) -> int:
    from elemental_tpu.analysis import golden_mem_doc, diff_mem_docs
    bad = 0
    for driver in drivers:
        for grid in grids:
            mplan, _, _ = _trace_mem(driver, grid, n, nb)
            doc = golden_mem_doc(mplan)
            path = mem_golden_path(driver, grid)
            tag = f"{driver} {grid[0]}x{grid[1]}"
            if update:
                os.makedirs(MEM_GOLDEN_DIR, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=False)
                    f.write("\n")
                print(f"updated {tag}: {os.path.relpath(path, _REPO)}")
                continue
            if not os.path.exists(path):
                print(f"MISSING memory golden for {tag} ({path}); "
                      f"run with --update-golden")
                bad += 1
                continue
            with open(path) as f:
                golden = json.load(f)
            lines = diff_mem_docs(golden, doc)
            if lines:
                bad += 1
                print(f"DIFF {tag}:")
                for ln in lines:
                    print(f"  {ln}")
            else:
                print(f"ok {tag}")
    return 1 if bad else 0


def cmd_mem_lint(drivers, grids, n, nb, fix_hint: bool = False) -> int:
    from elemental_tpu.analysis import lint_memory
    total = 0
    for driver in drivers:
        for grid in grids:
            mplan, closed, log = _trace_mem(driver, grid, n, nb)
            findings = lint_memory(mplan, log, closed)
            for f in findings:
                print(f"{driver} {grid[0]}x{grid[1]}: {f}")
                if fix_hint and f.fix_hint:
                    print(f"  fix: {f.fix_hint}")
            total += len(findings)
    print(f"{total} finding(s)")
    return 1 if total else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd not in ("audit", "diff", "lint", "mem", "mem-diff", "mem-lint"):
        print(__doc__)
        raise SystemExit(f"unknown command {cmd!r}")
    _bootstrap()
    name = None
    grids = list(GRIDS)
    n = nb = None
    events = update = fix_hint = False
    it = iter(argv)
    for arg in it:
        if arg == "--grid":
            r, c = next(it).split("x")
            grids = [(int(r), int(c))]
        elif arg == "--n":
            n = int(next(it))
        elif arg == "--nb":
            nb = int(next(it))
        elif arg == "--events":
            events = True
        elif arg == "--update-golden":
            update = True
        elif arg == "--fix-hint":
            fix_hint = True
        elif arg == "--all":
            name = None
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            name = arg
    drivers = _select(name)
    if cmd == "audit":
        return cmd_audit(drivers, grids, n, nb, events)
    if cmd == "diff":
        return cmd_diff(drivers, grids, n, nb, update)
    if cmd == "mem":
        return cmd_mem(drivers, grids, n, nb)
    if cmd == "mem-diff":
        return cmd_mem_diff(drivers, grids, n, nb, update)
    if cmd == "mem-lint":
        return cmd_mem_lint(drivers, grids, n, nb, fix_hint)
    return cmd_lint(drivers, grids, n, nb, fix_hint)


if __name__ == "__main__":
    try:
        import signal
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)   # `| head` etc.
    except (ImportError, AttributeError, ValueError):
        pass
    raise SystemExit(main())
