"""Driver benchmark: blocked distributed Cholesky TFLOPS on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = measured TFLOP/s / north-star (60% of the chip's fp32-class
matmul peak; BASELINE.json "north_star").  fp32-class = HIGHEST precision
(6-pass bf16), so the peak table is bf16-peak / 6.

NOTE on timing: on tunneled devices (axon) ``block_until_ready`` returns
before remote execution completes, and every host round-trip costs a fixed
latency.  We force completion with a scalar device->host read and subtract
the measured round-trip latency of a trivial op.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


#: approximate dense-matmul bf16 peaks per chip, TFLOP/s
_BF16_PEAKS = {
    "v5 lite": 197.0,    # v5e
    "v5p": 459.0,
    "v4": 275.0,
    "v6": 918.0,
    "cpu": 0.1,
}


def _fp32_peak(kind: str) -> float:
    kind = kind.lower()
    for key, bf16 in _BF16_PEAKS.items():
        if key in kind:
            return bf16 / 6.0
    return 197.0 / 6.0


def _roundtrip_latency() -> float:
    tiny = jax.jit(lambda x: x + 1.0)
    t = jnp.zeros(())
    float(tiny(t))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(tiny(t))
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    import elemental_tpu as el

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    n = 16384 if on_tpu else 512
    nb = 1024 if on_tpu else 64
    grid = el.Grid([dev])

    rng = np.random.default_rng(0)
    G = rng.normal(size=(n, n)).astype(np.float32)
    F = (G @ G.T) / n + n * np.eye(n, dtype=np.float32)
    A = el.from_global(F, el.MC, el.MR, grid=grid)

    step = jax.jit(lambda a: el.cholesky(a, nb=nb,
                                         precision=jax.lax.Precision.HIGHEST))
    L = step(A)
    float(L.local[0, 0])               # compile + warm (forces completion)
    lat = _roundtrip_latency()

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        L = step(A)
        float(L.local[0, 0])
        times.append(time.perf_counter() - t0)
    dt = max(min(times) - lat, 1e-9)

    flops = n ** 3 / 3
    tflops = flops / dt / 1e12
    north_star = 0.6 * _fp32_peak(getattr(dev, "device_kind", dev.platform))

    # sanity: factorization residual (not timed)
    Lh = np.tril(np.asarray(el.to_global(L)).astype(np.float64))
    resid = float(np.linalg.norm(F - Lh @ Lh.T) / np.linalg.norm(F))
    if not np.isfinite(resid) or resid > 1e-2:
        print(json.dumps({"metric": f"cholesky_n{n}_tflops_per_chip", "value": 0.0,
                          "unit": "TFLOP/s", "vs_baseline": 0.0,
                          "error": f"residual {resid:.3e}"}))
        return 1

    print(json.dumps({
        "metric": f"cholesky_n{n}_tflops_per_chip",
        "value": round(tflops, 3),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / north_star, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
