"""Driver benchmark: blocked Cholesky + HPL-style LU TFLOPS on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
headline Cholesky config, plus "lu_*" keys for the LU entry and "gemm_*"
keys for the tall-skinny rectangular GEMM entry (ISSUE 16; the driver
metric names all three).  vs_baseline = measured TFLOP/s / north-star (60% of the
chip's fp32-class matmul peak; BASELINE.json "north_star").  fp32-class =
HIGHEST precision (6-pass bf16), so the peak table is bf16-peak / 6.

Memory budget (v5e: 16 GB HBM): at N = 32768 the operand is 4.3 GB, so the
factorization jit DONATES its input and every rep regenerates the matrix
on device from the same PRNG key (untimed).  Residual checks are matvec
based (||A v - L L^T v||), so they cost O(n^2) and no extra buffers.

NOTE on timing: on tunneled devices (axon) ``block_until_ready`` returns
before remote execution completes, and every host round-trip costs a fixed
latency.  We force completion with a scalar device->host read and subtract
the measured round-trip latency of a trivial op.

``--phases`` additionally drives one EAGER Cholesky through the
``perf.phase_timer.PhaseTimer`` hook and emits its per-step
diag/panel/update breakdown as a second ``phase_timings/v1`` JSON line
after the headline (at a reduced N on TPU: the eager run holds more live
buffers than the donate-input jit).

The headline line embeds a versioned ``"obs"`` key (``obs_bench/v1``):
the run's ``obs_metrics/v1`` document (op invocation counts, tuner cache
events, phase histograms) plus the ``--phases`` totals -- the trail
``tools/bench_diff.py`` gates and future perf PRs attribute against
(ISSUE 5).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp


#: dense-matmul bf16 peaks per chip, TFLOP/s (vendor-published; there is no
#: runtime API for peak FLOPs, so this is keyed on ``device.device_kind``).
#: Measured check on this pod's "TPU v5 lite": 173.6 bf16 / 31.4 fp32-class
#: sustained on an 8192^3 matmul, consistent with 197 / 32.8 theoretical.
_BF16_PEAKS = {
    "v5 lite": 197.0,    # v5e
    "v5p": 459.0,
    "v5": 459.0,         # bare "TPU v5" reports as v5p
    "v4": 275.0,
    "v6 lite": 918.0,    # v6e (Trillium)
    "v6": 918.0,
    "cpu": 0.1,
}


def _fp32_peak(kind: str) -> float:
    kind = kind.lower()
    for key, bf16 in sorted(_BF16_PEAKS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return bf16 / 6.0
    return 197.0 / 6.0


def _roundtrip_latency() -> float:
    tiny = jax.jit(lambda x: x + 1.0)
    t = jnp.zeros(())
    float(tiny(t))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(tiny(t))
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    import elemental_tpu as el

    # Wire-byte accounting (ISSUE 8): a lightweight engine observer
    # totals the ring-model byte estimate of every public redistribute /
    # panel_spread entry at BOTH the logical dtype and the actual wire
    # dtype (the two differ under comm_precision).  Entries fire at
    # trace time, so jit-compiled reps count once per traced schedule --
    # the totals are "estimated bytes per factorization", the same
    # quantity the comm-plan goldens pin.  Defensive: obs must never
    # fail a bench.
    _wire_totals = {"redist_bytes": 0, "redist_wire_bytes": 0}
    _unobserve = None
    try:
        from elemental_tpu.redist.engine import add_redist_observer
        from elemental_tpu.obs.tracer import ring_bytes

        def _on_redist(rec):
            grid_shape = getattr(rec, "grid_shape", ())
            _wire_totals["redist_bytes"] += ring_bytes(
                rec.gshape, rec.dtype, grid_shape)
            wire = getattr(rec, "wire_dtype", "") or rec.dtype
            _wire_totals["redist_wire_bytes"] += ring_bytes(
                rec.gshape, wire, grid_shape)

        _unobserve = add_redist_observer(_on_redist)
    except Exception:
        pass

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    n_chol = 32768 if on_tpu else 256
    # N=32768 LU became feasible on v5e's 16 GB HBM once the bench path
    # donated its input (the 4.3 GB operand is regenerated per rep); the
    # bigger trailing matmuls lift MXU utilization vs the old N=16384.
    n_lu = 32768 if on_tpu else 256
    nb = 2048 if on_tpu else 64
    grid = el.Grid([dev])
    lat = _roundtrip_latency()
    HI = jax.lax.Precision.HIGHEST

    # The tunneled chip's sustained throughput varies ~2x run to run
    # (shared/throttled), so the baseline is the fp32-class matmul roofline
    # MEASURED IN THIS RUN (capped by the nameplate table): vs_baseline then
    # reflects algorithmic efficiency, not chip weather.
    table_peak = _fp32_peak(getattr(dev, "device_kind", dev.platform))
    if on_tpu:
        nroof = 8192
        R = jax.random.normal(jax.random.PRNGKey(9), (nroof, nroof),
                              jnp.float32)
        mm = jax.jit(lambda x: jnp.matmul(x, x, precision=HI))
        float(mm(R)[0, 0])
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(mm(R)[0, 0])
            ts.append(time.perf_counter() - t0)
        roofline = min(2 * nroof ** 3 / max(min(ts) - lat, 1e-9) / 1e12,
                       table_peak)
        del R
    else:
        roofline = table_peak
    north_star = 0.6 * roofline

    def wrap(a, n):
        return el.DistMatrix(a, (n, n), el.MC, el.MR, 0, 0, grid)

    def timed(make_input, step, reps=3):
        """min-of-reps wall time; the input is regenerated (untimed) per rep
        because ``step`` donates it."""
        out = step(make_input())       # compile + warm
        jax.block_until_ready(out)
        times = []
        for _ in range(reps):
            A = make_input()
            float(jax.tree_util.tree_leaves(A)[0].ravel()[0])  # gen done
            t0 = time.perf_counter()
            out = step(A)
            float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            times.append(time.perf_counter() - t0)
        return out, max(min(times) - lat, 1e-9)

    # ---- Cholesky (SPD solve headline config) -------------------------
    @jax.jit
    def gen_spd():
        G = jax.random.normal(jax.random.PRNGKey(0), (n_chol, n_chol),
                              jnp.float32)
        return jnp.matmul(G, G.T) / n_chol \
            + n_chol * jnp.eye(n_chol, dtype=jnp.float32)

    chol = jax.jit(lambda a: el.cholesky(a, nb=nb, precision=HI).local,
                   donate_argnums=0)
    l_arr, dt = timed(lambda: wrap(gen_spd(), n_chol), chol)
    chol_tflops = (n_chol ** 3 / 3) / dt / 1e12

    # untimed matvec residual: ||A v - L (L^T v)|| / (||A||_F ||v||)
    @jax.jit
    def chol_resid(l):
        a = gen_spd()
        v = jax.random.normal(jax.random.PRNGKey(2), (n_chol, 1), jnp.float32)
        r = jnp.matmul(a, v, precision=HI) \
            - jnp.matmul(l, jnp.matmul(l.T, v, precision=HI), precision=HI)
        return jnp.linalg.norm(r) / (jnp.linalg.norm(a) * jnp.linalg.norm(v))

    resid = float(chol_resid(l_arr))
    del l_arr
    if resid > 1e-3 or resid != resid:
        print(json.dumps({"metric": f"cholesky_n{n_chol}_tflops_per_chip",
                          "value": 0.0, "unit": "TFLOP/s", "vs_baseline": 0.0,
                          "error": f"cholesky residual {resid:.3e}"}))
        return 1

    # ---- LU with partial pivoting (HPL-style) -------------------------
    def gen_lu():
        return jax.random.normal(jax.random.PRNGKey(1), (n_lu, n_lu),
                                 jnp.float32)

    lufn = jax.jit(lambda a: tuple(el.lu(a, nb=nb, precision=HI)),
                   donate_argnums=0)

    def lu_step(A):
        LU, perm = lufn(A)
        return LU.local, perm

    (lu_arr, perm), dt_lu = timed(lambda: wrap(jax.jit(gen_lu)(), n_lu), lu_step)
    lu_tflops = (2 * n_lu ** 3 / 3) / dt_lu / 1e12

    @jax.jit
    def lu_resid_fn(lu_loc, perm):
        m = gen_lu()
        v = jax.random.normal(jax.random.PRNGKey(3), (n_lu, 1), jnp.float32)
        pav = jnp.matmul(jnp.take(m, perm, axis=0), v, precision=HI)
        # unit-lower L: L (U v) = tril(lu,-1) (U v) + (U v)
        uv = jnp.matmul(jnp.triu(lu_loc), v, precision=HI)
        luv = jnp.matmul(jnp.tril(lu_loc, -1), uv, precision=HI) + uv
        return jnp.linalg.norm(pav - luv) / (jnp.linalg.norm(m)
                                             * jnp.linalg.norm(v))

    lu_resid = float(lu_resid_fn(lu_arr, perm))
    if lu_resid > 1e-3 or lu_resid != lu_resid:
        print(json.dumps({"metric": f"lu_n{n_lu}_tflops_per_chip",
                          "value": 0.0, "unit": "TFLOP/s", "vs_baseline": 0.0,
                          "error": f"lu residual {lu_resid:.3e}",
                          "cholesky_value": round(chol_tflops, 3)}))
        return 1

    # ---- rectangular GEMM (ISSUE 16: the tall-skinny headline) --------
    # The serving tier's real matmul class: m >> n.  alg='auto' so the
    # timed run IS the tuner's dispatch (provenance recorded below --
    # 'dot' on this single-chip grid via the pinned early-out, 'slice'
    # on the multi-chip tall-skinny grids).
    m_g, k_g, n_g = (65536, 512, 512) if on_tpu else (4096, 128, 128)

    @jax.jit
    def gen_gemm():
        return (jax.random.normal(jax.random.PRNGKey(4), (m_g, k_g),
                                  jnp.float32),
                jax.random.normal(jax.random.PRNGKey(5), (k_g, n_g),
                                  jnp.float32))

    def wrap_gemm(ab):
        a, b = ab
        return (el.DistMatrix(a, (m_g, k_g), el.MC, el.MR, 0, 0, grid),
                el.DistMatrix(b, (k_g, n_g), el.MC, el.MR, 0, 0, grid))

    gemm_fn = jax.jit(
        lambda ab: el.gemm(ab[0], ab[1], alg="auto", precision=HI).local,
        donate_argnums=0)
    c_arr, dt_g = timed(lambda: wrap_gemm(gen_gemm()), gemm_fn)
    gemm_tflops = 2 * m_g * k_g * n_g / dt_g / 1e12

    @jax.jit
    def gemm_resid_fn(c_loc):
        a, b = gen_gemm()
        v = jax.random.normal(jax.random.PRNGKey(6), (n_g, 1), jnp.float32)
        r = jnp.matmul(c_loc, v, precision=HI) \
            - jnp.matmul(a, jnp.matmul(b, v, precision=HI), precision=HI)
        return jnp.linalg.norm(r) / (jnp.linalg.norm(a) * jnp.linalg.norm(b)
                                     * jnp.linalg.norm(v))

    gemm_resid = float(gemm_resid_fn(c_arr))
    del c_arr
    if gemm_resid > 1e-3 or gemm_resid != gemm_resid:
        print(json.dumps({"metric": f"cholesky_n{n_chol}_tflops_per_chip",
                          "value": round(chol_tflops, 3), "unit": "TFLOP/s",
                          "error": f"gemm residual {gemm_resid:.3e}",
                          "lu_value": round(lu_tflops, 3)}))
        return 1

    # Tuner self-description (ISSUE 4 + 6): record the config the autotuner
    # resolves for each headline op -- and whether it came from a measured
    # cache entry or the analytic cost model -- so this BENCH line says
    # not just how fast, but under WHICH knobs a tuned run would execute.
    # Since ISSUE 6 the LU resolution includes the panel strategy
    # ('classic' | 'calu'): on this single-chip grid 'auto' resolves to
    # 'classic' (calu degenerates on single-row grids), and a multi-row
    # bench would record 'calu' here -- the provenance the trajectory
    # gate reads next to the renamed lu_n32768 metric.  (The timed runs
    # above use the pinned nb/panel for baseline comparability.)
    # panel_impl + inners join ran_with (ISSUE 17): the timed runs above
    # execute the status-quo XLA panel ladder at the pinned chunk widths
    # (read from kernels.default_inners(), the single source -- NOT the
    # lu module alias, which a tuner/harness override would leave stale),
    # and the per-op resolutions below record which implementation
    # 'auto' would dispatch on THIS backend (pallas on TPU, xla
    # elsewhere -- the interpret-penalty term of the cost model).
    from elemental_tpu.kernels import default_inners
    tuner: dict = {"ran_with": {"nb": nb, "lookahead": True,
                                "crossover": None, "panel": "classic",
                                "comm_precision": None,
                                "redist_path": None,
                                "panel_impl": None,
                                "inners": list(default_inners())}}
    try:
        from elemental_tpu import tune as el_tune
        for op, gshape in (("cholesky", (n_chol, n_chol)),
                           ("lu", (n_lu, n_lu)),
                           ("gemm", (m_g, k_g, n_g))):
            # comm_precision joins the resolved provenance (ISSUE 8): on
            # this single-chip grid 'auto' resolves to None (the knob is
            # dead without collectives); a multi-device bench records the
            # tuner's wire-precision pick here next to nb/panel
            # redist_path joins the provenance (ISSUE 12/13): 'auto'
            # resolves chain vs one-shot per grid -- None on single-chip
            # (every plan is 'local'), and a multi-chip bench records the
            # arbiter's pick (measured constants when recorded, the ring
            # model otherwise) next to nb/panel
            if op == "gemm":
                # the gemm headline's provenance (ISSUE 16): which alg
                # family the tuner dispatched the tall-skinny class to --
                # 'dot' on this single-chip grid (pinned early-out),
                # 'slice' on multi-chip tall-skinny grids
                requested = {"alg": "auto", "nb": "auto",
                             "comm_precision": "auto",
                             "redist_path": "auto"}
            else:
                requested = {"nb": "auto", "lookahead": "auto",
                             "crossover": "auto", "comm_precision": "auto",
                             "redist_path": "auto", "panel_impl": "auto"}
                if op == "lu":
                    requested["panel"] = "auto"
            res = el_tune.resolve(
                op, gshape=gshape, dtype=jnp.float32, grid=grid,
                requested=requested)
            tuner[op] = {"config": dict(res.config), "source": res.source}
        tuner["cache_dir"] = el_tune.cache_dir()
    except Exception as e:                     # never fail the benchmark
        tuner["error"] = f"{type(e).__name__}: {e}"

    ph_line = None
    ph_summary = None
    if "--phases" in sys.argv[1:]:
        # cholesky phase attribution alongside the headline: one eager run
        # through the PhaseTimer hook (smaller N on TPU -- the eager driver
        # cannot donate its input)
        from perf.phase_timer import PhaseTimer
        del lu_arr, perm
        n_ph = min(n_chol, 16384) if on_tpu else n_chol

        @jax.jit
        def gen_ph():
            G = jax.random.normal(jax.random.PRNGKey(0), (n_ph, n_ph),
                                  jnp.float32)
            return jnp.matmul(G, G.T) / n_ph \
                + n_ph * jnp.eye(n_ph, dtype=jnp.float32)

        Ap = wrap(gen_ph(), n_ph)
        jax.block_until_ready(Ap.local)
        t = PhaseTimer()
        Lp = el.cholesky(Ap, nb=nb, precision=HI, timer=t)
        jax.block_until_ready(Lp.local)
        ph_doc = t.report(driver="cholesky", n=n_ph, nb=nb, lookahead=True,
                          flops=n_ph ** 3 / 3,
                          device=getattr(dev, "device_kind", dev.platform))
        ph_line = json.dumps(ph_doc)
        ph_summary = {"schema": ph_doc["schema"], "driver": "cholesky",
                      "n": n_ph, "nb": nb, "totals": ph_doc["totals"],
                      "total_seconds": ph_doc["total_seconds"]}
        del Lp, Ap

    # Observability doc (ISSUE 5): the run's metrics registry (op
    # invocation counts, tuner cache events, phase histograms from the
    # --phases run) plus the phase breakdown, under one versioned key --
    # the machine-readable trail tools/bench_diff.py and future perf PRs
    # read.  Collected defensively: observability must never fail a bench.
    obs_doc: dict = {"schema": "obs_bench/v1"}
    try:
        from elemental_tpu.obs import metrics as obs_metrics
        obs_doc["metrics"] = obs_metrics.current().to_doc(
            device=getattr(dev, "device_kind", dev.platform))
        obs_doc["phases"] = ph_summary
        # estimated redistribution bytes, logical vs on-the-wire (equal
        # unless a comm_precision mode ran); tools/bench_diff.py accepts
        # the new key without tripping its rename guard
        obs_doc["redist_bytes"] = int(_wire_totals["redist_bytes"])
        obs_doc["redist_wire_bytes"] = int(
            _wire_totals["redist_wire_bytes"])
        if _unobserve is not None:
            _unobserve()
        # chain-vs-direct redistribution GB/s for one representative
        # move on ALL visible chips (ISSUE 12) -- informational only,
        # never gated by bench_diff; on a 1-chip host both rates are 0.0
        # (no wire bytes in the ring model)
        try:
            from perf.redist_bench import p2p_gbps
            obs_doc["redist_p2p_gbps"] = p2p_gbps(el.Grid(jax.devices()))
        except Exception as e:
            obs_doc["redist_p2p_gbps"] = {
                "error": f"{type(e).__name__}: {e}"}
    except Exception as e:                     # never fail the benchmark
        obs_doc["error"] = f"{type(e).__name__}: {e}"

    print(json.dumps({
        "metric": f"cholesky_n{n_chol}_tflops_per_chip",
        "value": round(chol_tflops, 3),
        "unit": "TFLOP/s",
        "vs_baseline": round(chol_tflops / north_star, 4),
        "lu_metric": f"lu_n{n_lu}_tflops_per_chip",
        "lu_value": round(lu_tflops, 3),
        "lu_vs_baseline": round(lu_tflops / north_star, 4),
        "gemm_metric": "gemm_tall_skinny_tflops_per_chip",
        "gemm_value": round(gemm_tflops, 3),
        "gemm_vs_baseline": round(gemm_tflops / north_star, 4),
        "gemm_dims": [m_g, k_g, n_g],
        "vs_nameplate": round(chol_tflops / (0.6 * table_peak), 4),
        "lu_vs_nameplate": round(lu_tflops / (0.6 * table_peak), 4),
        "roofline_tflops": round(roofline, 2),
        "nameplate_tflops": round(table_peak, 2),
        "resid": f"{resid:.2e}",
        "lu_resid": f"{lu_resid:.2e}",
        "gemm_resid": f"{gemm_resid:.2e}",
        "tuner": tuner,
        "obs": obs_doc,
    }))

    if ph_line is not None:
        print(ph_line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
