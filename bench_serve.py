"""Serving benchmark: latency percentiles + throughput of the solver
service (ISSUE 9).

Prints ONE JSON line (``bench_serve/v1``)::

    {"schema": "bench_serve/v1", "serve_p50_ms": ..., "serve_p99_ms": ...,
     "serve_solves_per_sec": ..., "requests": N, "ok": N, "batches": ...,
     "exec_compiles": ..., "exec_hits": ..., "grid": [r, c],
     "backend": "cpu", "n": ..., "warmup_requests": ...}

into the BENCH flow: ``tools/bench_diff.py`` gates ``serve_p99_ms``
(lower-is-better) and ``serve_solves_per_sec`` alongside the TFLOP/s
headlines, so a serving-latency regression fails the gate exactly like a
factorization-throughput regression.

Methodology: a WARMUP pass first touches every (bucket, batch-slot)
geometry so AOT compiles happen outside the measured window (that is the
executor cache's contract: no serving request pays compile) -- then the
measured pass submits ``--requests`` mixed lu/hpd problems and drains.
Latency is per-request submit->finalize wall clock as recorded in each
``serve_result/v1``; throughput is requests completed / drain seconds.

Flags: ``--requests N`` (default 64), ``--n N`` (system size, default
96), ``--grid RxC``, ``--seed S``, ``--smoke`` (tiny sizes + schema
sanity only -- the check.sh path).  CPU-safe via the same virtual
8-device mesh as ``perf.trace``.
"""
import json
import sys
import time

BENCH_SERVE_SCHEMA = "bench_serve/v1"


def _percentile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def run_bench(requests: int, n: int, grid_spec, seed: int) -> dict:
    import numpy as np
    from perf.trace import _grid
    from perf.serve import _workload
    from elemental_tpu.obs import metrics as _metrics
    from elemental_tpu.serve import SolverService

    grid = _grid(grid_spec)
    svc = SolverService(grid)
    rng = np.random.default_rng(seed)

    # warmup: a full-size pass, so every (bucket, batch-slot) geometry of
    # the measured workload -- including the max_batch slot count the
    # drain's batching produces -- compiles here, outside the window
    warm = _workload(rng, requests, n)
    for op, A, B in warm:
        svc.submit(op, A, B)
    svc.drain()

    with _metrics.scoped() as reg:
        work = _workload(rng, requests, n)
        t0 = time.perf_counter()
        for op, A, B in work:
            svc.submit(op, A, B)
        docs = svc.drain()
        wall = time.perf_counter() - t0
        events: dict = {}
        for (name, labels), v in \
                reg.counters("serve_exec_cache_events").items():
            ev = dict(labels).get("event")
            events[ev] = events.get(ev, 0) + v
        batches = sum(v for (name, labels), v
                      in reg.counters("serve_batches").items())

    lats = sorted(d["latency_s"] for d in docs.values())
    ok = sum(d["status"] == "ok" for d in docs.values())
    import jax
    return {
        "schema": BENCH_SERVE_SCHEMA,
        "serve_p50_ms": 1e3 * _percentile(lats, 0.50),
        "serve_p99_ms": 1e3 * _percentile(lats, 0.99),
        "serve_solves_per_sec": len(docs) / wall if wall > 0 else None,
        "requests": len(docs), "ok": ok, "batches": int(batches),
        "exec_compiles": int(events.get("compile", 0)),
        "exec_hits": int(events.get("hit", 0)),
        "grid": [grid.height, grid.width],
        "backend": jax.default_backend(), "n": n,
        "warmup_requests": len(warm),
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    requests, n = 64, 96
    grid_spec = None
    seed = 0
    smoke = False
    it = iter(argv)
    for arg in it:
        if arg == "--requests":
            requests = int(next(it))
        elif arg == "--n":
            n = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--seed":
            seed = int(next(it))
        elif arg == "--smoke":
            smoke = True
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            raise SystemExit(f"unexpected argument {arg!r}")
    if smoke:
        requests, n = min(requests, 12), min(n, 24)
    from perf.trace import _bootstrap
    _bootstrap()
    doc = run_bench(requests, n, grid_spec, seed)
    print(json.dumps(doc))
    if smoke:
        # schema sanity: the gateable keys must be present and numeric
        bad = [k for k in ("serve_p50_ms", "serve_p99_ms",
                           "serve_solves_per_sec")
               if not isinstance(doc.get(k), (int, float))]
        if bad or doc["ok"] != doc["requests"]:
            print(f"# bench_serve smoke FAILED: bad={bad} "
                  f"ok={doc['ok']}/{doc['requests']}", file=sys.stderr)
            return 1
        print("# bench_serve smoke: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
