"""Serving benchmark: latency percentiles + throughput of the solver
service, sync (ISSUE 9) and async pipelined (ISSUE 14).

Prints ONE JSON line (``bench_serve/v1``)::

    {"schema": "bench_serve/v1", "serve_p50_ms": ..., "serve_p99_ms": ...,
     "serve_solves_per_sec": ..., "requests": N, "ok": N, "batches": ...,
     "exec_compiles": ..., "exec_hits": ...,
     "serve_async_p50_ms": ..., "serve_async_p99_ms": ...,
     "serve_async_solves_per_sec": ..., "serve_async_speedup": ...,
     "serve_async_exec_compiles": 0, "serve_async_batches": ...,
     "serve_pipeline_occupancy": ..., "serve_async_payload_identical":
     true, "grid": [r, c], "backend": "cpu", "n": ...,
     "warmup_requests": ...,
     "serve_fleet_p50_ms": ..., "serve_fleet_p99_ms": ...,
     "serve_fleet_solves_per_sec": ..., "serve_fleet_requests": ...,
     "serve_fleet_ok": ..., "serve_fleet_n": ...,
     "serve_fleet_grids_used": ["g0", "g1"], "serve_fleet_scaling": ...,
     "serve_fleet_busy_single_s": ..., "serve_fleet_busy_per_grid_s":
     [...], "serve_fleet_scaling_ok": ...,
     "serve_slo_p99_ms": ..., "serve_slo": {serve_slo/v1 doc}}

The ``serve_slo_*`` keys (ISSUE 20) come from the fleet's windowed
:class:`~elemental_tpu.obs.slo.SLOMonitor`: ``serve_slo`` is the full
``serve_slo/v1`` snapshot of the measured fleet pass (per-tenant/grid/
bucket percentiles, error/shed rates, burn rates) and
``serve_slo_p99_ms`` the worst per-tenant windowed p99 -- the single
scalar ``tools/bench_diff.py`` gates lower-is-better.

into the BENCH flow: ``tools/bench_diff.py`` gates ``serve_p99_ms`` /
``serve_async_p99_ms`` / ``serve_fleet_p99_ms`` (lower-is-better) and
``serve_solves_per_sec`` / ``serve_async_solves_per_sec`` /
``serve_fleet_solves_per_sec`` alongside the TFLOP/s headlines, so a
serving-latency regression fails the gate exactly like a
factorization-throughput regression.  The ``serve_fleet_*`` section is
the ISSUE-19 multi-grid fleet (see :func:`run_fleet_bench`): real-wall
percentiles through a pipelined 2-member fleet plus the device-busy
2-grid-vs-1-grid scaling ratio with its 1.8x acceptance floor.

Methodology: a WARMUP pass first touches every (bucket, batch-slot)
geometry so AOT compiles happen outside the measured window (that is the
executor cache's contract: no serving request pays compile) -- then the
measured pass submits ``--requests`` mixed lu/hpd problems and drains.
Latency is per-request submit->finalize wall clock as recorded in each
``serve_result/v1``; throughput is requests completed / drain seconds.
The ASYNC section replays the identical workload (same seed stream)
through :class:`AsyncSolverService` -- warmed the same way, measured
the same way -- and additionally asserts the pipelining contract:
``serve_async_exec_compiles == 0`` in the measured window (donated
executables are warmed variants, not recompiles), bit-identical
solutions and semantically identical ``serve_result/v1`` payloads vs
the sync pass, and no leaked worker thread after shutdown.

Flags: ``--requests N`` (default 64), ``--n N`` (system size, default
96), ``--grid RxC``, ``--seed S``, ``--smoke`` (tiny sizes + schema
sanity only -- the check.sh path).  CPU-safe via the same virtual
8-device mesh as ``perf.trace``.
"""
import json
import sys
import time

BENCH_SERVE_SCHEMA = "bench_serve/v1"


def _percentile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


#: serve_result/v1 keys that must be IDENTICAL sync vs async for the
#: same request (timing keys excluded -- latency/seconds are wall clock)
_SEM_KEYS = ("op", "n", "nrhs", "bucket", "status", "path", "rung",
             "residual", "tol", "retries", "bisected", "timed_out")


def run_bench(requests: int, n: int, grid_spec, seed: int) -> dict:
    import threading

    import numpy as np
    from perf.trace import _grid
    from perf.serve import _workload
    from elemental_tpu.obs import metrics as _metrics
    from elemental_tpu.serve import AsyncSolverService, SolverService

    grid = _grid(grid_spec)
    svc = SolverService(grid)
    rng = np.random.default_rng(seed)

    # warmup: a full-size pass, so every (bucket, batch-slot) geometry of
    # the measured workload -- including the max_batch slot count the
    # drain's batching produces -- compiles here, outside the window
    warm = _workload(rng, requests, n)
    for op, A, B in warm:
        svc.submit(op, A, B)
    svc.drain()

    with _metrics.scoped() as reg:
        work = _workload(rng, requests, n)
        rids = []
        t0 = time.perf_counter()
        for op, A, B in work:
            rids.append(svc.submit(op, A, B))
        docs = svc.drain()
        wall = time.perf_counter() - t0
        events: dict = {}
        for (name, labels), v in \
                reg.counters("serve_exec_cache_events").items():
            ev = dict(labels).get("event")
            events[ev] = events.get(ev, 0) + v
        batches = sum(v for (name, labels), v
                      in reg.counters("serve_batches").items())

    lats = sorted(d["latency_s"] for d in docs.values())
    ok = sum(d["status"] == "ok" for d in docs.values())
    sps = len(docs) / wall if wall > 0 else None

    # ---- async pipelined pass: the IDENTICAL workload (replayed seed
    # stream) through AsyncSolverService, warmed the same way.  Where
    # the backend donates (donation_safe), the __donated executables
    # are distinct cache variants and the async warmup pays its own
    # compiles; either way the measured window must show zero.
    front = AsyncSolverService(SolverService(grid), donate=True)
    rng2 = np.random.default_rng(seed)
    warm2 = _workload(rng2, requests, n)
    for f in [front.submit(op, A, B) for op, A, B in warm2]:
        f.result()
    with _metrics.scoped() as reg2:
        work2 = _workload(rng2, requests, n)
        t1 = time.perf_counter()
        futs = [front.submit(op, A, B) for op, A, B in work2]
        outs = [f.result() for f in futs]
        wall2 = time.perf_counter() - t1
        compiles2 = sum(
            v for (name, labels), v in
            reg2.counters("serve_exec_cache_events").items()
            if dict(labels).get("event") == "compile")
        batches2 = sum(v for (name, labels), v
                       in reg2.counters("serve_batches").items())
    stats = front.pipeline_stats()
    front.shutdown(drain=True)
    leak = any(t.name.startswith("elemental-serve-worker") and t.is_alive()
               for t in threading.enumerate())

    # bit-identical payloads: same solutions, same serve_result/v1
    # semantics per request (sync rids and async futures are both in
    # submission order over the same replayed workload)
    identical = len(rids) == len(futs)
    for rid, fut, (x2, d2) in zip(rids, futs, outs):
        d1 = docs[rid]
        if any(d1.get(k) != d2.get(k) for k in _SEM_KEYS):
            identical = False
            break
        p1 = (d1.get("dispatch") or {}).get("route")
        p2 = (d2.get("dispatch") or {}).get("route")
        x1 = svc.solutions.get(rid)
        same_x = (x1 is None and x2 is None) or (
            x1 is not None and x2 is not None
            and x1.dtype == x2.dtype and np.array_equal(x1, x2))
        if p1 != p2 or not same_x:
            identical = False
            break

    lats2 = sorted(d["latency_s"] for _, d in outs)
    ok2 = sum(d["status"] == "ok" for _, d in outs)
    sps2 = len(outs) / wall2 if wall2 > 0 else None
    import jax
    return {
        "schema": BENCH_SERVE_SCHEMA,
        "serve_p50_ms": 1e3 * _percentile(lats, 0.50),
        "serve_p99_ms": 1e3 * _percentile(lats, 0.99),
        "serve_solves_per_sec": sps,
        "requests": len(docs), "ok": ok, "batches": int(batches),
        "exec_compiles": int(events.get("compile", 0)),
        "exec_hits": int(events.get("hit", 0)),
        "serve_async_p50_ms": 1e3 * _percentile(lats2, 0.50),
        "serve_async_p99_ms": 1e3 * _percentile(lats2, 0.99),
        "serve_async_solves_per_sec": sps2,
        "serve_async_speedup": (sps2 / sps) if sps and sps2 else None,
        "serve_async_ok": ok2,
        "serve_async_exec_compiles": int(compiles2),
        "serve_async_batches": int(batches2),
        "serve_pipeline_occupancy": stats["occupancy"],
        "serve_async_payload_identical": bool(identical),
        "serve_async_thread_leak": bool(leak),
        "grid": [grid.height, grid.width],
        "backend": jax.default_backend(), "n": n,
        "warmup_requests": len(warm),
    }


class _BusyMeter:
    """Executor shim metering device-busy wall seconds per fleet member
    (the denominator of the multi-grid scaling metric)."""

    def __init__(self, inner):
        self._inner = inner
        self.busy_s = 0.0

    def run(self, bucket, reqs):
        t0 = time.perf_counter()
        out = self._inner.run(bucket, reqs)
        self.busy_s += time.perf_counter() - t0
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


def run_fleet_bench(requests: int, n: int, seed: int) -> dict:
    """The multi-grid fleet section (ISSUE 19).

    Two measurements over a SINGLE-bucket hpd workload (identical
    geometry per request, so every batch fills completely and the
    grids=1 vs grids=2 comparison is slot-for-slot fair; the request
    count rounds UP to a multiple of ``grids x max_batch`` so neither
    geometry pays padding the other does not):

      * ``serve_fleet_solves_per_sec`` / ``serve_fleet_p50_ms`` /
        ``serve_fleet_p99_ms`` -- real wall clock through the PIPELINED
        2-grid fleet (each member depth-2 on its own pinned device),
        warmed so no measured request pays compile;
      * ``serve_fleet_scaling`` -- aggregate throughput of the 2-grid
        fleet vs ONE grid at equal total device count, computed in
        DEVICE-BUSY time: (single-grid total batch-execution seconds) /
        (the 2-grid fleet's most-loaded member's seconds), the median of
        five interleaved repeats.  Perfect partitioning gives 2.0; the
        acceptance floor is 1.8.  Busy time
        rather than wall clock because this host is frequently a
        single-core CI runner where two members' real batches serialize
        on the CPU -- busy time measures what the partition would buy on
        hardware that can actually run members concurrently, the same
        honest-numbers convention as the async occupancy gauge.
    """
    import numpy as np
    from elemental_tpu.serve import SolverFleet

    # floor the problem size: sub-millisecond batches are dispatch-
    # overhead-dominated and jitter 30%+ on a shared core, which is
    # noise the 1.8x scaling floor cannot absorb; n=96 batches run
    # ~7 ms and repeat within a few percent
    n = max(n, 96)

    def workload(rng, count):
        out = []
        for _ in range(count):
            F = rng.normal(size=(n, n)).astype(np.float32)
            A = (F @ F.T / n + n * np.eye(n)).astype(np.float32)
            B = rng.normal(size=(n, 2)).astype(np.float32)
            out.append((A, B))
        return out

    probe = SolverFleet(grids=2, pipelined=False, shed=False)
    mb = probe.max_batch
    probe.shutdown(drain=True)
    span = 2 * mb
    count = max(span, -(-requests // span) * span)

    # real-wall pipelined fleet: warm pass (compiles per pinned device),
    # then the measured pass
    fleet = SolverFleet(grids=2, depth=2, shed=False)
    rng = np.random.default_rng(seed)
    for f in [fleet.submit("hpd", A, B, tenant=f"t{i % 2}")
              for i, (A, B) in enumerate(workload(rng, count))]:
        f.result(timeout=600.0)
    # equalize member EWMAs after warmup: warm routing hands members
    # different batch SIZES (the EWMA tracks batch seconds, not
    # per-request seconds), and over a window this short the skew would
    # route the whole measured pass to whichever member happened to run
    # small warm batches -- start symmetric so the split reflects load
    keys = set()
    for svc in fleet.services:
        keys |= set(svc.admission._ewma)
    for k in keys:
        vals = [svc.admission._ewma[k] for svc in fleet.services
                if k in svc.admission._ewma]
        for svc in fleet.services:
            svc.admission._ewma[k] = max(vals)
    t0 = time.perf_counter()
    futs = [fleet.submit("hpd", A, B, tenant=f"t{i % 2}")
            for i, (A, B) in enumerate(workload(rng, count))]
    outs = [f.result(timeout=600.0) for f in futs]
    wall = time.perf_counter() - t0
    fleet.shutdown(drain=True)
    lats = sorted(d["latency_s"] for _, d in outs)
    ok = sum(d["status"] == "ok" for _, d in outs)
    grids_used = sorted({d["grid"] for _, d in outs})
    # windowed SLO view of the measured pass (ISSUE 20): the fleet's
    # monitor saw every settled doc; the worst per-tenant p99 is the
    # gateable scalar, the full serve_slo/v1 snapshot rides along
    slo_doc = fleet.slo.snapshot(gauges=False, source="bench_serve")
    slo_p99 = fleet.slo.worst_p99_ms()

    # device-busy scaling: the same workload through sync fleets of 1
    # and 2 grids over the SAME total device set, each warmed, each
    # member's executor metered
    def busy_fleet(grids):
        fl = SolverFleet(grids=grids, pipelined=False, shed=False)
        meters = []
        for svc in fl.services:
            m = _BusyMeter(svc.executor)
            svc.executor = m
            meters.append(m)
        rngb = np.random.default_rng(seed + 1)
        for A, B in workload(rngb, count):
            fl.submit("hpd", A, B)
        fl.drain()
        return fl, meters

    def busy_repeat(fl, meters):
        for m in meters:
            m.busy_s = 0.0
        rngb = np.random.default_rng(seed + 2)
        futs = [fl.submit("hpd", A, B) for A, B in workload(rngb, count)]
        fl.drain()
        okb = sum(f.result(timeout=0)[1].get("status") == "ok"
                  for f in futs)
        return [m.busy_s for m in meters], okb

    # both fleets warmed up front, then INTERLEAVED repeats with a
    # per-repeat ratio: single batches on a shared CI core jitter 30%+
    # and the host drifts between seconds, so back-to-back pairing
    # cancels the common mode and the median ratio ignores the one
    # repeat the host stepped on
    fl1, meters1 = busy_fleet(1)
    fl2, meters2 = busy_fleet(2)
    pairs, ok1, ok2 = [], count, count
    for _ in range(5):
        b1, o1 = busy_repeat(fl1, meters1)
        b2, o2 = busy_repeat(fl2, meters2)
        ok1, ok2 = min(ok1, o1), min(ok2, o2)
        if max(b2) > 0:
            pairs.append((sum(b1) / max(b2), b1, b2))
    fl1.shutdown(drain=True)
    fl2.shutdown(drain=True)
    scaling, busy1, busy2 = (sorted(pairs)[len(pairs) // 2]
                             if pairs else (None, [0.0], [0.0]))
    return {
        "serve_fleet_p50_ms": 1e3 * _percentile(lats, 0.50),
        "serve_fleet_p99_ms": 1e3 * _percentile(lats, 0.99),
        "serve_fleet_solves_per_sec": len(outs) / wall if wall > 0
        else None,
        "serve_fleet_requests": count, "serve_fleet_ok": ok,
        "serve_fleet_n": n,
        "serve_fleet_grids_used": grids_used,
        "serve_fleet_scaling": scaling,
        "serve_fleet_busy_single_s": sum(busy1),
        "serve_fleet_busy_per_grid_s": busy2,
        "serve_fleet_scaling_ok": int(ok1) + int(ok2),
        "serve_slo_p99_ms": slo_p99,
        "serve_slo": slo_doc,
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    requests, n = 64, 96
    grid_spec = None
    seed = 0
    smoke = False
    it = iter(argv)
    for arg in it:
        if arg == "--requests":
            requests = int(next(it))
        elif arg == "--n":
            n = int(next(it))
        elif arg == "--grid":
            grid_spec = next(it)
        elif arg == "--seed":
            seed = int(next(it))
        elif arg == "--smoke":
            smoke = True
        elif arg.startswith("--"):
            raise SystemExit(f"unknown flag {arg!r}")
        else:
            raise SystemExit(f"unexpected argument {arg!r}")
    if smoke:
        requests, n = min(requests, 12), min(n, 24)
    from perf.trace import _bootstrap
    _bootstrap()
    doc = run_bench(requests, n, grid_spec, seed)
    doc.update(run_fleet_bench(requests, n, seed))
    print(json.dumps(doc))
    if smoke:
        # schema sanity: the gateable keys must be present and numeric,
        # and the async pipelining contract must hold even at tiny sizes
        bad = [k for k in ("serve_p50_ms", "serve_p99_ms",
                           "serve_solves_per_sec", "serve_async_p50_ms",
                           "serve_async_p99_ms",
                           "serve_async_solves_per_sec",
                           "serve_pipeline_occupancy",
                           "serve_fleet_p50_ms", "serve_fleet_p99_ms",
                           "serve_fleet_solves_per_sec",
                           "serve_fleet_scaling", "serve_slo_p99_ms")
               if not isinstance(doc.get(k), (int, float))]
        contract = []
        slo_tenants = {r["tenant"]
                       for r in (doc.get("serve_slo") or {}).get("series",
                                                                 ())}
        if not {"t0", "t1"} <= slo_tenants:
            contract.append(f"SLO snapshot missing tenants "
                            f"(saw {sorted(slo_tenants)})")
        if doc["serve_fleet_ok"] != doc["serve_fleet_requests"]:
            contract.append("fleet requests not all ok")
        if doc["serve_fleet_grids_used"] != ["g0", "g1"]:
            contract.append("fleet left a member idle")
        if doc["serve_fleet_scaling_ok"] != 2 * doc["serve_fleet_requests"]:
            contract.append("scaling passes not all ok")
        if isinstance(doc.get("serve_fleet_scaling"), (int, float)) \
                and doc["serve_fleet_scaling"] < 1.8:
            contract.append(
                f"fleet scaling {doc['serve_fleet_scaling']:.2f} < 1.8")
        if doc["serve_async_exec_compiles"] != 0:
            contract.append("async measured window compiled")
        if not doc["serve_async_payload_identical"]:
            contract.append("sync/async payloads differ")
        if doc["serve_async_thread_leak"]:
            contract.append("worker thread leaked")
        if doc["serve_async_ok"] != doc["requests"]:
            contract.append("async requests not all ok")
        if bad or contract or doc["ok"] != doc["requests"]:
            print(f"# bench_serve smoke FAILED: bad={bad} "
                  f"contract={contract} "
                  f"ok={doc['ok']}/{doc['requests']}", file=sys.stderr)
            return 1
        print("# bench_serve smoke: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
