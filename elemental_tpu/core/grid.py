"""Process grid over a TPU device mesh.

The reference's ``El::Grid`` (``src/core/Grid.cpp``) splits an MPI
communicator into an r x c logical grid and derives the MC / MR / VC / VR /
MD sub-communicators.  Here the grid IS a ``jax.sharding.Mesh`` with named
axes ``('mc', 'mr')``; the "sub-communicators" are simply the axis names
handed to collectives inside ``shard_map``:

  MC comm (size r)  -> axis 'mc'
  MR comm (size c)  -> axis 'mr'
  VC comm (size p)  -> axes ('mr','mc')  (column-major rank = mc + r*mr)
  VR comm (size p)  -> axes ('mc','mr')  (row-major rank    = mr + c*mc)

Grid is hashable/immutable so it can ride in DistMatrix pytree metadata
(static under jit).
"""
from __future__ import annotations

import math

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _near_square_height(p: int) -> int:
    r = int(math.isqrt(p))
    while p % r != 0:
        r -= 1
    return r


class Grid:
    """An r x c logical device grid backed by a named-axis Mesh."""

    def __init__(self, devices=None, height: int | None = None):
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        p = len(devices)
        r = _near_square_height(p) if height is None else height
        if p % r != 0:
            raise ValueError(f"grid height {r} does not divide device count {p}")
        c = p // r
        self._r, self._c = r, c
        self._devices = tuple(devices)
        self.mesh = Mesh(np.asarray(devices).reshape(r, c), ("mc", "mr"))

    @property
    def height(self) -> int:  # r == |MC|
        return self._r

    @property
    def width(self) -> int:   # c == |MR|
        return self._c

    @property
    def size(self) -> int:    # p
        return self._r * self._c

    @property
    def devices(self) -> tuple:
        """The grid's devices in row-major (mc, mr) order."""
        return self._devices

    @property
    def lcm(self) -> int:     # MD stride in the reference
        return self._r * self._c // math.gcd(self._r, self._c)

    def sharding(self, spec: PartitionSpec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # --- hashable static metadata -------------------------------------
    def _key(self):
        return (self._r, self._c, tuple(id(d) for d in self._devices))

    def __eq__(self, other):
        return isinstance(other, Grid) and self._key() == other._key()

    def __hash__(self):
        return hash((self._r, self._c, len(self._devices)))

    def __repr__(self):
        return f"Grid({self._r}x{self._c})"


_default_grid: Grid | None = None


def default_grid() -> Grid:
    """Lazily-built grid over all visible devices (``Grid::Default()``)."""
    global _default_grid
    if _default_grid is None:
        _default_grid = Grid()
    return _default_grid


def set_default_grid(g: Grid) -> None:
    global _default_grid
    _default_grid = g
