"""BlockMatrix: the tiled (XLA-native) second layout.

Reference: ``DistMatrix<T,U,V,BLOCK>`` / ``BlockMatrix<T>``
(``include/El/core/DistMatrix/Block/**``): upstream's second wrap, a
block(-cyclic) layout kept mainly for ScaLAPACK interop.  On TPU the
roles invert (SURVEY.md §3.8): CONTIGUOUS TILES are the native XLA
sharding -- ``P('mc','mr')`` on the padded global array -- so BlockMatrix
is the zero-cost interop wrap for ordinary XLA-sharded arrays, while the
elemental (cyclic) ``DistMatrix`` remains the load-balanced layout of
the blocked factorizations.

The storage leaf IS the global array (padded to uniform tiles), so
``block_from_global``/``block_to_global`` are just device_put/slice; the
cyclic<->tiled conversions are the per-dim index permutations between the
two storage orders (tiled row i <-> cyclic slot (i%r)*lr + i//r), which
GSPMD lowers to the minimal all_to_all -- exactly the re-layout cost the
reference pays between elemental and BLOCK operands.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import indexing as ix
from .dist import MC, MR
from .distmatrix import DistMatrix
from .grid import Grid, default_grid


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["local"],
    meta_fields=["gshape", "grid"],
)
@dataclasses.dataclass(frozen=True)
class BlockMatrix:
    """Tiled 2-D layout: device (i, j) owns the contiguous tile
    rows [i*tr, (i+1)*tr) x cols [j*tc, (j+1)*tc) of the padded global
    array (tr = ceil(m/r), tc = ceil(n/c))."""
    local: Any                    # (r*tr, c*tc) padded global, P('mc','mr')
    gshape: tuple
    grid: Grid

    @property
    def tile_rows(self) -> int:
        return ix.max_local_length(self.gshape[0], self.grid.height)

    @property
    def tile_cols(self) -> int:
        return ix.max_local_length(self.gshape[1], self.grid.width)

    @property
    def spec(self) -> P:
        return P("mc", "mr")

    @property
    def dtype(self):
        return self.local.dtype

    def with_local(self, local) -> "BlockMatrix":
        return dataclasses.replace(self, local=local)

    def __repr__(self):
        return (f"BlockMatrix(gshape={self.gshape}, grid={self.grid}, "
                f"dtype={self.local.dtype})")


def block_from_global(arr, grid: Grid | None = None,
                      device_put: bool = True) -> BlockMatrix:
    """Wrap a global array in the tiled layout (pad + device_put)."""
    grid = grid or default_grid()
    arr = jnp.asarray(arr)
    m, n = arr.shape
    r, c = grid.height, grid.width
    tr, tc = ix.max_local_length(m, r), ix.max_local_length(n, c)
    pad = jnp.zeros((r * tr, c * tc), arr.dtype).at[:m, :n].set(arr)
    B = BlockMatrix(pad, (m, n), grid)
    if device_put:
        B = B.with_local(jax.device_put(pad, grid.sharding(B.spec)))
    return B


def block_from_array(arr, grid: Grid | None = None) -> BlockMatrix:
    """Adopt an ALREADY-SHARDED XLA array whose sharding matches the
    tiled layout (zero-copy interop edge); shapes must be pre-padded."""
    grid = grid or default_grid()
    m, n = arr.shape
    return BlockMatrix(arr, (m, n), grid)


def block_to_global(B: BlockMatrix):
    """Recover the (m, n) array (slice off tile padding)."""
    return B.local[: B.gshape[0], : B.gshape[1]]


@partial(jax.jit, static_argnums=())
def block_to_cyclic(B: BlockMatrix) -> DistMatrix:
    """BlockMatrix -> elemental [MC,MR] DistMatrix (one all_to_all-class
    re-layout per dim, inserted by GSPMD from the index permutation)."""
    m, n = B.gshape
    g = B.grid
    r, c = g.height, g.width
    lr, lc = ix.max_local_length(m, r), ix.max_local_length(n, c)
    # cyclic storage slot q*l + t holds global index t*S + q
    ri = (jnp.arange(r * lr) % lr) * r + jnp.arange(r * lr) // lr
    cj = (jnp.arange(c * lc) % lc) * c + jnp.arange(c * lc) // lc
    stor = jnp.take(B.local, jnp.minimum(ri, B.local.shape[0] - 1), axis=0)
    stor = jnp.take(stor, jnp.minimum(cj, B.local.shape[1] - 1), axis=1)
    stor = jnp.where((ri < m)[:, None] & (cj < n)[None, :], stor, 0)
    out = DistMatrix(stor, (m, n), MC, MR, 0, 0, g)
    return out.with_local(jax.lax.with_sharding_constraint(
        stor, g.sharding(out.spec)))


@partial(jax.jit, static_argnums=())
def block_from_cyclic(A: DistMatrix) -> BlockMatrix:
    """Elemental [MC,MR] DistMatrix -> BlockMatrix (inverse re-layout)."""
    if (A.cdist, A.rdist) != (MC, MR) or A.calign or A.ralign:
        raise ValueError("block_from_cyclic needs a zero-aligned [MC,MR]")
    m, n = A.gshape
    g = A.grid
    r, c = g.height, g.width
    lr, lc = A.local_rows, A.local_cols
    tr, tc = ix.max_local_length(m, r), ix.max_local_length(n, c)
    # tiled row i holds global i; its cyclic slot is (i%r)*lr + i//r
    i = jnp.arange(r * tr)
    j = jnp.arange(c * tc)
    ri = (i % r) * lr + i // r
    cj = (j % c) * lc + j // c
    pad = jnp.take(A.local, jnp.minimum(ri, A.local.shape[0] - 1), axis=0)
    pad = jnp.take(pad, jnp.minimum(cj, A.local.shape[1] - 1), axis=1)
    pad = jnp.where((i < m)[:, None] & (j < n)[None, :], pad, 0)
    out = BlockMatrix(pad, (m, n), g)
    return out.with_local(jax.lax.with_sharding_constraint(
        pad, g.sharding(out.spec)))


def as_elemental(x) -> DistMatrix:
    """Read-proxy coercion (``DistMatrixReadProxy``): BlockMatrix operands
    convert to the elemental layout; DistMatrix passes through."""
    if isinstance(x, BlockMatrix):
        return block_to_cyclic(x)
    return x
