"""Environment: blocksize policy, timers, CLI argument parsing.

Reference: Elemental ``src/core/environment.cpp`` --
``El::Blocksize``/``SetBlocksize``/``PushBlocksizeStack``/``PopBlocksizeStack``
(the global algorithmic blocksize stack, default 128), ``El::Timer``
(``include/El/core/Timer.hpp``), and the ``El::Input``/``ProcessInput``/
``PrintInputReport`` typed CLI flag parser (``El::Args``) used by every
test and example driver.

TPU-native notes: the blocksize is a *trace-time* constant (it shapes the
jitted blocked loops), so the stack is plain Python state consulted when an
algorithm's ``nb`` argument is None; a with-statement context manager
replaces the reference's push/pop pairs.  ``Timer`` can optionally
``block_until_ready`` a pytree so device work is actually fenced -- the
analog of the reference's barrier-then-``mpi::Time`` idiom.
"""
from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field


# ---------------------------------------------------------------------------
# Blocksize stack (El::Blocksize / SetBlocksize / Push/PopBlocksizeStack)
#
# jit caveat: the blocksize is read at TRACE time (it shapes the blocked
# loops), so it is baked into every compiled executable.  Changing it and
# re-calling a jitted driver triggers a fresh XLA compile (and jit caching
# keyed only on shapes/dtypes will NOT notice a blocksize change inside an
# already-traced closure -- pass nb explicitly to jitted entry points, or
# jit after setting the blocksize).
# ---------------------------------------------------------------------------

_DEFAULT_BLOCKSIZE = 128
_blocksize_stack: list[int] = [_DEFAULT_BLOCKSIZE]


def blocksize() -> int:
    """Current algorithmic blocksize (``El::Blocksize``)."""
    return _blocksize_stack[-1]


def set_blocksize(nb: int) -> None:
    """Replace the top of the blocksize stack (``El::SetBlocksize``)."""
    if nb < 1:
        raise ValueError(f"blocksize must be >= 1, got {nb}")
    _blocksize_stack[-1] = int(nb)


def push_blocksize(nb: int) -> None:
    """``El::PushBlocksizeStack``."""
    if nb < 1:
        raise ValueError(f"blocksize must be >= 1, got {nb}")
    _blocksize_stack.append(int(nb))


def pop_blocksize() -> int:
    """``El::PopBlocksizeStack``; the default base entry is never popped."""
    if len(_blocksize_stack) == 1:
        raise RuntimeError("blocksize stack underflow")
    return _blocksize_stack.pop()


class blocksize_scope:
    """``with blocksize_scope(256): ...`` == push/pop pair."""

    def __init__(self, nb: int):
        self.nb = nb

    def __enter__(self):
        push_blocksize(self.nb)
        return self.nb

    def __exit__(self, *exc):
        pop_blocksize()
        return False


# ---------------------------------------------------------------------------
# Timer (El::Timer; barrier-then-time idiom via block_until_ready)
# ---------------------------------------------------------------------------

class Timer:
    """Accumulating wall-clock timer.

    ``start()``/``stop()`` accumulate into ``total()``; ``partial()`` reads
    the running split without stopping.  Passing a pytree to ``stop(x)``
    fences outstanding device work on it first (the reference's
    ``mpi::Barrier(); timer.Stop()`` pattern).
    """

    def __init__(self, name: str = ""):
        self.name = name
        self._total = 0.0
        self._t0 = None

    def start(self) -> None:
        if self._t0 is not None:
            raise RuntimeError(f"Timer {self.name!r} already running")
        self._t0 = time.perf_counter()

    def stop(self, fence=None) -> float:
        if fence is not None:
            import jax
            jax.block_until_ready(fence)
        if self._t0 is None:
            raise RuntimeError(f"Timer {self.name!r} not running")
        split = time.perf_counter() - self._t0
        self._total += split
        self._t0 = None
        return split

    def partial(self) -> float:
        return 0.0 if self._t0 is None else time.perf_counter() - self._t0

    def total(self) -> float:
        return self._total + self.partial()

    def reset(self) -> None:
        self._total, self._t0 = 0.0, None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            self.stop()
        return False

    def __repr__(self):
        state = "running" if self._t0 is not None else "stopped"
        return f"Timer({self.name!r}, total={self.total():.6f}s, {state})"


# ---------------------------------------------------------------------------
# CLI input (El::Args / El::Input / ProcessInput / PrintInputReport)
# ---------------------------------------------------------------------------

@dataclass
class _Flag:
    name: str
    description: str
    default: object
    type: type
    required: bool
    value: object = None
    found: bool = False


class Args:
    """Typed flag parser mirroring ``El::Input`` semantics.

    >>> args = Args(["--m", "500", "--upper"])
    >>> m = args.input("--m", "matrix height", 100)
    >>> upper = args.input("--upper", "use upper triangle", False)
    >>> args.process()           # validates; raises on unknown/missing
    >>> m, upper
    (500, True)

    Booleans are presence flags when the next token is another flag (or
    absent), else parse the token (``--upper 1``/``true``/``false``).
    """

    def __init__(self, argv: list[str] | None = None):
        self.argv = list(sys.argv[1:] if argv is None else argv)
        self._flags: dict[str, _Flag] = {}
        self._processed = False

    def input(self, name: str, description: str, default=None, *,
              required: bool = False):
        """Register a flag and return its parsed value (``El::Input<T>``)."""
        if not name.startswith("--"):
            raise ValueError(f"flag names start with '--': {name!r}")
        ftype = type(default) if default is not None else str
        flag = _Flag(name, description, default, ftype, required)
        self._flags[name] = flag
        flag.value, flag.found = self._parse(flag)
        return flag.value

    def _parse(self, flag: _Flag):
        for i, tok in enumerate(self.argv):
            if tok != flag.name:
                continue
            nxt = self.argv[i + 1] if i + 1 < len(self.argv) else None
            if flag.type is bool:
                if nxt is None or nxt.startswith("--"):
                    return True, True
                return nxt.lower() in ("1", "true", "yes", "on"), True
            if nxt is None:
                raise ValueError(f"flag {flag.name} expects a value")
            if flag.type is int:
                return int(nxt), True
            if flag.type is float:
                return float(nxt), True
            if flag.type is complex:
                return complex(nxt), True
            return nxt, True
        return flag.default, False

    def process(self, report: bool = False) -> None:
        """Validate (``El::ProcessInput``): every required flag present, no
        unknown flags in argv."""
        self._processed = True
        missing = [f.name for f in self._flags.values()
                   if f.required and not f.found]
        if missing:
            self.print_report()
            raise ValueError(f"missing required flags: {missing}")
        i = 0
        while i < len(self.argv):
            tok = self.argv[i]
            if tok.startswith("--"):
                if tok == "--help":
                    self.print_report()
                    raise SystemExit(0)
                flag = self._flags.get(tok)
                if flag is None:
                    raise ValueError(f"unknown flag {tok}")
                nxt = self.argv[i + 1] if i + 1 < len(self.argv) else None
                # skip exactly the tokens _parse consumed: non-bool flags
                # always consume the next token; bool flags consume it only
                # when it is a value, not another flag
                if flag.type is not bool:
                    if nxt is not None:
                        i += 1
                elif nxt is not None and not nxt.startswith("--"):
                    i += 1
            i += 1
        if report:
            self.print_report()

    def print_report(self, stream=None) -> None:
        """``El::PrintInputReport``."""
        stream = stream or sys.stdout
        stream.write("Input flags:\n")
        for f in self._flags.values():
            mark = "*" if f.found else " "
            stream.write(f" {mark} {f.name:<16} {f.value!r:<12}"
                         f" ({f.type.__name__}) -- {f.description}\n")


# ---------------------------------------------------------------------------
# Structured progress logging (§6.5 metrics/logging minimum)
# ---------------------------------------------------------------------------

@dataclass
class ProgressLog:
    """Per-iteration metric sink used by the IPMs / iterative drivers.

    ``log(it, **metrics)`` records a row and, when ``print_every`` > 0,
    prints a compact line -- the analog of the reference's ``ctrl.progress``
    flag inside ``MehrotraCtrl``/``PseudospecCtrl``.
    """

    name: str = ""
    print_every: int = 0
    rows: list[dict] = field(default_factory=list)

    def log(self, it: int, **metrics) -> None:
        row = {"it": it, **{k: float(v) for k, v in metrics.items()}}
        self.rows.append(row)
        if self.print_every and it % self.print_every == 0:
            body = " ".join(f"{k}={v:.3e}" for k, v in row.items() if k != "it")
            print(f"[{self.name or 'iter'} {it:4d}] {body}")

    def history(self, key: str) -> list[float]:
        return [r[key] for r in self.rows if key in r]
