"""Views: contiguous global sub-matrices of a DistMatrix.

The analog of the reference's FLAME partitioning + ``View``/``LockedView``
(Elemental ``include/El/core/FlamePart/``, ``View.hpp``): blocked algorithms
walk a matrix by repeatedly taking contiguous index-range views.

With the element-cyclic layout, a global range [s, e) whose start is a
multiple of the distribution stride maps to the contiguous LOCAL range
[s/S, ceil(e/S)) on every device -- so a view is a pure-local (zero-comm)
slice of the stacked storage array, done with static offsets (jit-friendly).

Constraint (the "grain" rule, SURVEY.md §8.1 item 3): slice starts must be
multiples of the dim's stride; ends must be multiples or the true extent.
Blocked algorithms pick block sizes as multiples of lcm(r, c) (or r*c when
V-distributions are involved) so this always holds.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from . import indexing as ix
from .distmatrix import DistMatrix


def _local_range(s: int, e: int, extent: int, S: int, align: int):
    if align != 0:
        raise ValueError("views require zero alignment")
    if s % S != 0:
        raise ValueError(f"view start {s} not a multiple of stride {S}")
    if e < s or e > extent:
        raise ValueError(f"view range [{s},{e}) out of bounds for extent {extent}")
    if e != extent and e % S != 0:
        raise ValueError(f"view end {e} not a multiple of stride {S} nor the extent")
    sl = s // S
    el = ix.max_local_length(e, S)
    return sl, el


def _blocked(stor, Sc, Sr):
    lr = stor.shape[0] // Sc
    lc = stor.shape[1] // Sr
    return stor.reshape(Sc, lr, Sr, lc), lr, lc


def view(A: DistMatrix, rows=None, cols=None) -> DistMatrix:
    """A[rows[0]:rows[1], cols[0]:cols[1]] as a DistMatrix (same dists)."""
    m, n = A.gshape
    rows = (0, m) if rows is None else rows
    cols = (0, n) if cols is None else cols
    Sc, Sr = A.col_stride, A.row_stride
    rsl, rel = _local_range(rows[0], rows[1], m, Sc, A.calign)
    csl, cel = _local_range(cols[0], cols[1], n, Sr, A.ralign)
    b, lr, lc = _blocked(A.local, Sc, Sr)
    sub = b[:, rsl:rel, :, csl:cel].reshape(Sc * (rel - rsl), Sr * (cel - csl))
    gshape = (min(rows[1], m) - rows[0], min(cols[1], n) - cols[0])
    return dataclasses.replace(A, local=sub, gshape=gshape)


def update_view(A: DistMatrix, B: DistMatrix, rows=None, cols=None) -> DistMatrix:
    """Functionally write sub-matrix B into A at the given global ranges."""
    m, n = A.gshape
    rows = (0, m) if rows is None else rows
    cols = (0, n) if cols is None else cols
    Sc, Sr = A.col_stride, A.row_stride
    rsl, rel = _local_range(rows[0], rows[1], m, Sc, A.calign)
    csl, cel = _local_range(cols[0], cols[1], n, Sr, A.ralign)
    b, lr, lc = _blocked(A.local, Sc, Sr)
    bB = B.local.reshape(Sc, rel - rsl, Sr, cel - csl)
    out = b.at[:, rsl:rel, :, csl:cel].set(bB)
    return A.with_local(out.reshape(A.local.shape))


def round_up(x: int, grain: int) -> int:
    return -(-x // grain) * grain


def split_point(n: int, grain: int) -> int:
    """A near-halving split that respects the grain rule."""
    half = round_up(n // 2, grain)
    if half == 0 or half >= n:
        half = grain
    return min(half, n)


def pad_matrix(A: DistMatrix, M: int, N: int) -> DistMatrix:
    """Extend the global shape to (M, N) >= gshape with explicit zeros.

    Pure-local storage reshape (the cyclic layout keeps each device's block
    contiguous per residue class) -- the ragged-edge tool for algorithms that
    need grain-aligned extents (SURVEY.md §8.3 item 5).
    """
    m, n = A.gshape
    if M < m or N < n:
        raise ValueError(f"pad_matrix target ({M},{N}) smaller than {A.gshape}")
    Sc, Sr = A.col_stride, A.row_stride
    lr2 = ix.max_local_length(M, Sc)
    lc2 = ix.max_local_length(N, Sr)
    b, lr, lc = _blocked(A.local, Sc, Sr)
    b = jnp.pad(b, ((0, 0), (0, lr2 - lr), (0, 0), (0, lc2 - lc)))
    out = dataclasses.replace(A, local=b.reshape(Sc * lr2, Sr * lc2),
                              gshape=(M, N))
    return out


def shrink_matrix(A: DistMatrix, m: int, n: int) -> DistMatrix:
    """Restrict the global shape to (m, n) <= gshape, re-zeroing the newly
    out-of-range entries (keeps the padding-is-zero invariant)."""
    M, N = A.gshape
    if m > M or n > N:
        raise ValueError(f"shrink_matrix target ({m},{n}) larger than {A.gshape}")
    Sc, Sr = A.col_stride, A.row_stride
    lr2 = ix.max_local_length(m, Sc)
    lc2 = ix.max_local_length(n, Sr)
    b, lr, lc = _blocked(A.local, Sc, Sr)
    b = b[:, :lr2, :, :lc2]
    out = dataclasses.replace(A, local=b.reshape(Sc * lr2, Sr * lc2),
                              gshape=(m, n))
    # zero entries whose global index is now out of range
    q = jnp.arange(Sc)[:, None]
    il = jnp.arange(lr2)[None, :]
    I = (il * Sc + (q - A.calign) % Sc).reshape(-1)
    q2 = jnp.arange(Sr)[:, None]
    jl = jnp.arange(lc2)[None, :]
    J = (jl * Sr + (q2 - A.ralign) % Sr).reshape(-1)
    keep = (I[:, None] < m) & (J[None, :] < n)
    return out.with_local(jnp.where(keep, out.local, 0))
