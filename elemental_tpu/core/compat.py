"""jax version compatibility shims.

The library targets current jax (``jax.shard_map`` with ``check_vma``);
older builds still ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` keyword.
Routing every shard_map call through :func:`shard_map` keeps the rest of
the codebase on the modern spelling while remaining runnable on the older
runtimes some CI/dev containers carry.
"""
from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:                                       # pragma: no cover - old jax only
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
