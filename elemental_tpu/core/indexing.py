"""Cyclic-layout index arithmetic.

The element-cyclic distribution of the reference (Elemental's
``include/El/core/environment`` ``Shift``/``Length`` helpers, used by every
pack/unpack loop in ``src/blas_like/level1/Copy/``) boils down to four pure
functions.  We keep them as plain-int functions (shapes must be static under
jit) plus traced variants where the device rank is only known inside
``shard_map``.

Layout convention (matching Elemental): a 1-D index space of extent ``n``
distributed with stride ``S`` (number of owning ranks) and alignment ``a``:

  * owner(i)        = (i + a) mod S            -- rank that owns global index i
  * shift(q)        = (q - a) mod S            -- first global index owned by q
  * local index     iLoc = i // S
  * global index    i = iLoc * S + shift(q)
  * local length    Length(n, shift, S) = ceil((n - shift) / S)

All ranks store ``max_local_length(n, S) = ceil(n / S)`` rows (SPMD needs
uniform shapes); the tail beyond ``Length`` is padding and is kept ZERO as a
library-wide invariant.
"""
from __future__ import annotations


def shift(rank, align: int, stride: int):
    """First global index owned by ``rank`` (works on ints and traced ints)."""
    if stride == 1:
        return rank * 0
    return (rank - align) % stride


def owner(i, align: int, stride: int):
    """Rank owning global index ``i``."""
    if stride == 1:
        return i * 0
    return (i + align) % stride


def length(n: int, shft: int, stride: int) -> int:
    """Number of local entries for a rank with shift ``shft`` (static ints)."""
    if n <= shft:
        return 0
    return (n - shft + stride - 1) // stride


def max_local_length(n: int, stride: int) -> int:
    """ceil(n / stride): the uniform (padded) local extent all ranks store."""
    return -(-n // stride)


def padded_length(n: int, stride: int) -> int:
    """stride * ceil(n/stride): global extent after padding."""
    return stride * max_local_length(n, stride)
