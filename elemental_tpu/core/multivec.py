"""DistMultiVec: tall-skinny dense matrix with contiguous row-block layout.

Reference: ``El::DistMultiVec<T>`` (``include/El/core/DistMultiVec/``,
``src/core/DistMultiVec.cpp``): rows distributed in CONTIGUOUS blocks (not
cyclic) over all p ranks; the operand type of the sparse solvers and IPMs,
with queued ``RemoteUpdate`` batched writes.

TPU-native design: contiguous row-block IS XLA's natural tiled sharding,
so the leaf is simply the global array zero-padded to ``p * ceil(m/p)``
rows and device_put with ``PartitionSpec(('mc','mr'), None)`` -- device d
owns padded-global rows [d*blk, (d+1)*blk).  Because blocks are contiguous
and uniform, storage row index == global row index (padding lives at the
tail), so host bridges are slices, elementwise ops and reductions run
directly on the leaf (padding zero), and batched remote updates are one
``.at[].add``.  ``shard_map`` kernels (the sparse layer) see the (blk, n)
local block with spec ``P(('mc','mr'), None)``.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .grid import Grid, default_grid


def _blk(m: int, p: int) -> int:
    return -(-max(m, 1) // p)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["local"],
    meta_fields=["gshape", "grid"],
)
@dataclasses.dataclass(frozen=True)
class DistMultiVec:
    local: Any        # (p*blk, width) zero-padded global array, row-sharded
    gshape: tuple     # true (m, width)
    grid: Grid

    @property
    def block(self) -> int:
        """Rows owned per device (uniform, padded)."""
        return _blk(self.gshape[0], self.grid.size)

    @property
    def spec(self) -> P:
        return P(("mc", "mr"), None)

    @property
    def dtype(self):
        return self.local.dtype

    @property
    def width(self) -> int:
        return self.gshape[1]

    def row_owner(self, i: int) -> int:
        """Rank owning global row i (``DistMultiVec::RowOwner``)."""
        return i // self.block

    def with_local(self, local) -> "DistMultiVec":
        return dataclasses.replace(self, local=local)

    def __repr__(self):
        return (f"DistMultiVec(gshape={self.gshape}, grid={self.grid}, "
                f"dtype={self.local.dtype})")


def mv_from_global(arr, grid: Grid | None = None,
                   device_put: bool = True) -> DistMultiVec:
    """Build from a replicated (m, width) array (pad tail rows to p*blk)."""
    grid = grid or default_grid()
    arr = jnp.asarray(arr)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    m, w = arr.shape
    blk = _blk(m, grid.size)
    stor = jnp.zeros((grid.size * blk, w), arr.dtype).at[:m].set(arr)
    mv = DistMultiVec(stor, (m, w), grid)
    if device_put:
        mv = mv.with_local(jax.device_put(stor, grid.sharding(mv.spec)))
    return mv


def mv_to_global(v: DistMultiVec):
    """Recover the (m, width) array (drop tail padding)."""
    return v.local[: v.gshape[0]]


def mv_zeros(m: int, width: int = 1, grid: Grid | None = None,
             dtype=jnp.float32) -> DistMultiVec:
    grid = grid or default_grid()
    blk = _blk(m, grid.size)
    mv = DistMultiVec(None, (m, width), grid)
    stor = jnp.zeros((grid.size * blk, width), dtype)
    return mv.with_local(jax.device_put(stor, grid.sharding(mv.spec)))


# ---- elementwise / reductions (padding-oblivious on the padded leaf) ----

def mv_axpy(alpha, X: DistMultiVec, Y: DistMultiVec) -> DistMultiVec:
    _check_same(X, Y)
    return Y.with_local(alpha * X.local + Y.local)


def mv_scale(alpha, X: DistMultiVec) -> DistMultiVec:
    return X.with_local(alpha * X.local)


def mv_dot(X: DistMultiVec, Y: DistMultiVec):
    """<X, Y> = sum conj(X) * Y (tail padding is zero on both sides)."""
    _check_same(X, Y)
    return jnp.sum(jnp.conj(X.local) * Y.local)


def mv_nrm2(X: DistMultiVec):
    return jnp.linalg.norm(X.local)


def _check_same(X: DistMultiVec, Y: DistMultiVec):
    if X.gshape != Y.gshape or X.grid != Y.grid:
        raise ValueError(f"DistMultiVec mismatch: {X} vs {Y}")


# ---- batched remote updates (Reserve/QueueUpdate/ProcessQueues) ------

def _validate_update_indices(rows, cols, m: int, n: int, gshape) -> None:
    """Host-side bounds check for queued remote updates (skipped for
    traced indices, where the caller guarantees bounds; writes into the
    zero-padding tail would corrupt padding-oblivious reductions)."""
    import numpy as _np
    from jax.errors import TracerArrayConversionError
    try:
        ri = _np.asarray(rows)
        ci = _np.asarray(cols)
    except TracerArrayConversionError:
        return                      # traced: caller guarantees bounds
    if ri.size and (ri.min() < 0 or ri.max() >= m
                    or ci.min() < 0 or ci.max() >= n):
        raise ValueError(f"remote update out of bounds for gshape {gshape}")


def mv_remote_updates(v: DistMultiVec, rows, cols, vals) -> DistMultiVec:
    """Apply a batch of ``v[rows[k], cols[k]] += vals[k]`` updates.

    The analog of the reference's queued ``RemoteUpdate`` +
    ``ProcessQueues``: callers batch arbitrary (possibly duplicate) global
    updates; one scatter-add lands them, XLA routing the cross-device
    writes (the all-to-all the reference does by hand)."""
    m, w = v.gshape
    _validate_update_indices(rows, cols, m, w, v.gshape)
    rows = jnp.asarray(rows)
    cols = jnp.asarray(cols)
    vals = jnp.asarray(vals, v.dtype)
    return v.with_local(v.local.at[rows, cols].add(vals))


# ---- bridges to DistMatrix (API edge) --------------------------------

def mv_to_distmatrix(v: DistMultiVec, cdist=None, rdist=None):
    """Convert to a [MC,MR] (default) DistMatrix via the global bridge.

    API-edge op (the reference's DistMultiVec <-> DistMatrix copies also
    funnel through gather/scatter); the sparse/IPM hot paths never call it."""
    from .dist import MC, MR
    from .distmatrix import from_global
    cdist = MC if cdist is None else cdist
    rdist = MR if rdist is None else rdist
    return from_global(mv_to_global(v), cdist, rdist, grid=v.grid)


def mv_from_distmatrix(A) -> DistMultiVec:
    from .distmatrix import to_global
    return mv_from_global(to_global(A), grid=A.grid)
