"""Ctrl dataclasses: the per-call tuning-struct system.

Reference: Elemental's dominant configuration pattern (SURVEY.md §6.6) --
plain structs of tolerances/switches threaded explicitly through calls:
``QRCtrl``, ``LDLPivotCtrl``, ``HermitianEigCtrl``, ``SVDCtrl``,
``SchurCtrl``/``SDCCtrl``, ``SignCtrl``, ``PseudospecCtrl``,
``LeastSquaresCtrl`` (``MehrotraCtrl`` lives in ``optimization``).

TPU-native notes: every Ctrl here is a FROZEN dataclass, hence hashable --
safe to pass as a jit static argument.  Each maps 1:1 onto the keyword
arguments of the corresponding driver; ``ctrl.kwargs()`` expands it so
``f(A, **ctrl.kwargs())`` is the explicit-threading idiom.  Fields left at
None defer to the callee's defaults (e.g. ``nb=None`` -> the environment
blocksize stack).
"""
from __future__ import annotations

from dataclasses import dataclass, fields


class _Ctrl:
    def kwargs(self) -> dict:
        """Expand into keyword arguments, dropping None-valued fields."""
        return {f.name: getattr(self, f.name) for f in fields(self)
                if getattr(self, f.name) is not None}


@dataclass(frozen=True)
class SignCtrl(_Ctrl):
    """Newton sign-iteration knobs (``El::SignCtrl``)."""
    maxiter: int = 40
    tol: float | None = None
    nb: int | None = None


@dataclass(frozen=True)
class PolarCtrl(_Ctrl):
    """QDWH polar knobs (``El::PolarCtrl``)."""
    nb: int | None = None


@dataclass(frozen=True)
class HermitianEigCtrl(_Ctrl):
    """``El::HermitianEigCtrl``: approach = 'auto' | 'tridiag' | 'qdwh'."""
    vectors: bool = True
    approach: str = "auto"
    subset: tuple | None = None
    nb: int | None = None


@dataclass(frozen=True)
class SVDCtrl(_Ctrl):
    """``El::SVDCtrl``: approach = 'auto' | 'chan' | 'polar' | 'golub' |
    'local'."""
    vectors: bool = True
    approach: str = "auto"
    nb: int | None = None


@dataclass(frozen=True)
class SchurCtrl(_Ctrl):
    """Spectral divide-and-conquer knobs (``El::SchurCtrl``/``SDCCtrl``)."""
    base: int | None = None
    nb: int | None = None


@dataclass(frozen=True)
class PseudospecCtrl(_Ctrl):
    """``El::PseudospecCtrl``: window resolution + power-iteration count."""
    nx: int = 20
    ny: int = 20
    iters: int = 30
    nb: int | None = None


@dataclass(frozen=True)
class LDLPivotCtrl(_Ctrl):
    """``El::LDLPivotCtrl``: Bunch-Kaufman is the only pivot type."""
    conjugate: bool | None = None
    nb: int | None = None


@dataclass(frozen=True)
class QRCtrl(_Ctrl):
    """``El::QRCtrl`` (col-pivoting selected by calling ``qr_col_piv``)."""
    nb: int | None = None


@dataclass(frozen=True)
class LeastSquaresCtrl(_Ctrl):
    """``El::LeastSquaresCtrl``."""
    nb: int | None = None
