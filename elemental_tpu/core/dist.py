"""Distribution taxonomy.

Mirrors the reference's ``enum Dist {MC, MD, MR, VC, VR, STAR, CIRC}``
(Elemental ``include/El/core/types.hpp``) and its 13 legal (ColDist, RowDist)
pairs, re-expressed against a 2-D named-axis TPU mesh ``Mesh(('mc','mr'))``
of shape r x c (p = r*c):

  MC    -- distributed over the mesh's 'mc' axis (grid column comm), stride r
  MR    -- distributed over 'mr' (grid row comm), stride c
  VC    -- 1-D cyclic over all p devices, column-major rank  q = mc + r*mr
  VR    -- 1-D cyclic over all p devices, row-major rank     q = mr + c*mc
  STAR  -- replicated
  MD    -- matrix diagonal distribution.  v1 stores MD *physically replicated*
           (the logical owner math -- entry k on device (k%r, k%c) -- is only
           used by GetDiagonal/SetDiagonal, which on TPU are cheap masked
           collectives; a dedicated sparse storage buys nothing on the MXU).
  CIRC  -- all data on the root.  v1 stores CIRC physically replicated as
           well (gather-to-all); the tag preserves the reference's IO-path
           semantics ([CIRC,CIRC] gather underlies Print/Write).

``jax.lax.all_gather`` over a tuple of axis names orders the gathered blocks
with the FIRST name MAJOR, so VC's column-major rank order is produced by
``('mr','mc')`` and VR's row-major order by ``('mc','mr')`` (verified
empirically; tests/core/test_redist.py covers it).
"""
from __future__ import annotations

import enum


class Dist(enum.Enum):
    MC = "MC"
    MD = "MD"
    MR = "MR"
    VC = "VC"
    VR = "VR"
    STAR = "STAR"
    CIRC = "CIRC"

    def __repr__(self):  # compact in error messages
        return self.value


MC, MD, MR, VC, VR, STAR, CIRC = (
    Dist.MC, Dist.MD, Dist.MR, Dist.VC, Dist.VR, Dist.STAR, Dist.CIRC,
)

#: The legal (ColDist, RowDist) pairs -- the reference's 13 plus [CIRC,CIRC].
LEGAL_PAIRS = (
    (MC, MR), (MC, STAR), (STAR, MR),
    (MR, MC), (MR, STAR), (STAR, MC),
    (VC, STAR), (STAR, VC),
    (VR, STAR), (STAR, VR),
    (MD, STAR), (STAR, MD),
    (STAR, STAR),
    (CIRC, CIRC),
)


def stride(d: Dist, r: int, c: int) -> int:
    """Number of ranks the dimension is split over (physical storage)."""
    if d is Dist.MC:
        return r
    if d is Dist.MR:
        return c
    if d in (Dist.VC, Dist.VR):
        return r * c
    # STAR replicated; MD/CIRC physically replicated in v1.
    return 1


def gather_axes(d: Dist):
    """Mesh axis names (ordered major-first) whose all_gather rebuilds the
    dimension in rank order."""
    if d is Dist.MC:
        return ("mc",)
    if d is Dist.MR:
        return ("mr",)
    if d is Dist.VC:
        return ("mr", "mc")   # q = mc + r*mr  (mr major)
    if d is Dist.VR:
        return ("mc", "mr")   # q = mr + c*mc  (mc major)
    return ()


def spec_component(d: Dist):
    """PartitionSpec entry for this dimension of the stacked storage array."""
    if d is Dist.MC:
        return "mc"
    if d is Dist.MR:
        return "mr"
    if d is Dist.VC:
        return ("mr", "mc")
    if d is Dist.VR:
        return ("mc", "mr")
    return None


def rank_of(d: Dist, r: int, c: int):
    """This device's rank within the distribution (traced; shard_map only)."""
    import jax

    if d is Dist.MC:
        return jax.lax.axis_index("mc")
    if d is Dist.MR:
        return jax.lax.axis_index("mr")
    if d is Dist.VC:
        return jax.lax.axis_index("mc") + r * jax.lax.axis_index("mr")
    if d is Dist.VR:
        return jax.lax.axis_index("mr") + c * jax.lax.axis_index("mc")
    return 0
