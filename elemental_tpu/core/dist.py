"""Distribution taxonomy.

Mirrors the reference's ``enum Dist {MC, MD, MR, VC, VR, STAR, CIRC}``
(Elemental ``include/El/core/types.hpp``) and its 13 legal (ColDist, RowDist)
pairs, re-expressed against a 2-D named-axis TPU mesh ``Mesh(('mc','mr'))``
of shape r x c (p = r*c):

  MC    -- distributed over the mesh's 'mc' axis (grid column comm), stride r
  MR    -- distributed over 'mr' (grid row comm), stride c
  VC    -- 1-D cyclic over all p devices, column-major rank  q = mc + r*mr
  VR    -- 1-D cyclic over all p devices, row-major rank     q = mr + c*mc
  STAR  -- replicated
  MD    -- matrix diagonal distribution: entry k on device (k%r, k%c),
           stride lcm(r, c).  TRUE distributed storage: the storage leaf
           has p slot-ranges (VR-nested mc-major sharding) of length
           ceil(n/lcm); device (i,j) owns entries k ~ CRT(i mod r, j mod c)
           (no entries -- all-zero slots -- when (i-j) % gcd(r,c) != 0).
           The slot permutation is pack/unpack index math exactly like
           the cyclic layouts (SURVEY.md §8.1 item 2); diagonals of
           [MC,MR] matrices extract PURE-LOCALLY into this layout.
  CIRC  -- all data on the root: the storage leaf is the full array
           placed on device 0 only (SingleDeviceSharding) -- the
           reference's gather-to-root under Print/Write, O(mn) on the
           root and nothing elsewhere.  CIRC never enters shard_map;
           the engine converts to/from it at the redistribute() edge.

``jax.lax.all_gather`` over a tuple of axis names orders the gathered blocks
with the FIRST name MAJOR, so VC's column-major rank order is produced by
``('mr','mc')`` and VR's row-major order by ``('mc','mr')`` (verified
empirically; tests/core/test_redist.py covers it).
"""
from __future__ import annotations

import enum
import math


class Dist(enum.Enum):
    MC = "MC"
    MD = "MD"
    MR = "MR"
    VC = "VC"
    VR = "VR"
    STAR = "STAR"
    CIRC = "CIRC"

    def __repr__(self):  # compact in error messages
        return self.value


MC, MD, MR, VC, VR, STAR, CIRC = (
    Dist.MC, Dist.MD, Dist.MR, Dist.VC, Dist.VR, Dist.STAR, Dist.CIRC,
)

#: The legal (ColDist, RowDist) pairs -- the reference's 13 plus [CIRC,CIRC].
LEGAL_PAIRS = (
    (MC, MR), (MC, STAR), (STAR, MR),
    (MR, MC), (MR, STAR), (STAR, MC),
    (VC, STAR), (STAR, VC),
    (VR, STAR), (STAR, VR),
    (MD, STAR), (STAR, MD),
    (STAR, STAR),
    (CIRC, CIRC),
)


def stride(d: Dist, r: int, c: int) -> int:
    """Number of ranks the dimension is split over (index-math stride)."""
    if d is Dist.MC:
        return r
    if d is Dist.MR:
        return c
    if d in (Dist.VC, Dist.VR):
        return r * c
    if d is Dist.MD:
        return r * c // math.gcd(r, c)      # lcm(r, c)
    # STAR replicated; CIRC root-only (handled at the redistribute edge).
    return 1


def storage_slots(d: Dist, r: int, c: int) -> int:
    """Slot count of the stacked-storage dimension.  Equals the stride for
    every cyclic layout; MD stacks p slot-ranges (mc-major) even though
    its stride is lcm(r, c), because its owner map (k%r, k%c) is not a
    nested axis order -- devices outside the diagonal comm hold zeros."""
    if d is Dist.MD:
        return r * c
    return stride(d, r, c)


def md_params(r: int, c: int):
    """(gcd, lcm, inv) with inv = (r/gcd)^{-1} mod (c/gcd): the static CRT
    data for the MD owner map.  Device (i, j) owns diagonal entries
    k = k0 + t*lcm with k0 = i + r * (((j - i)//g * inv) % (c//g)),
    defined only when (i - j) % g == 0."""
    g = math.gcd(r, c)
    cg = c // g
    inv = pow((r // g) % cg, -1, cg) if cg > 1 else 0
    return g, r * c // g, inv


def md_slot_of_global(r: int, c: int, n: int):
    """Static numpy map: global index k -> flat storage slot
    (mc-major device id (k%r)*c + (k%c), local offset k // lcm)."""
    import numpy as np
    _, L, _ = md_params(r, c)
    l = -(-n // L) if n else 1
    k = np.arange(n)
    return ((k % r) * c + (k % c)) * l + k // L


def gather_axes(d: Dist):
    """Mesh axis names (ordered major-first) whose all_gather rebuilds the
    dimension in rank order."""
    if d is Dist.MC:
        return ("mc",)
    if d is Dist.MR:
        return ("mr",)
    if d is Dist.VC:
        return ("mr", "mc")   # q = mc + r*mr  (mr major)
    if d is Dist.VR:
        return ("mc", "mr")   # q = mr + c*mc  (mc major)
    return ()


def spec_component(d: Dist):
    """PartitionSpec entry for this dimension of the stacked storage array."""
    if d is Dist.MC:
        return "mc"
    if d is Dist.MR:
        return "mr"
    if d is Dist.VC:
        return ("mr", "mc")
    if d is Dist.VR:
        return ("mc", "mr")
    if d is Dist.MD:
        return ("mc", "mr")   # p slot-ranges, mc-major (see storage_slots)
    return None


def rank_of(d: Dist, r: int, c: int):
    """This device's rank within the distribution (traced; shard_map only).

    For MD the "rank" is k0, the first diagonal entry this device owns
    (< lcm), or the out-of-range sentinel lcm for devices outside the
    diagonal comm -- callers mask with :func:`md_owner_mask`."""
    import jax

    if d is Dist.MC:
        return jax.lax.axis_index("mc")
    if d is Dist.MR:
        return jax.lax.axis_index("mr")
    if d is Dist.VC:
        return jax.lax.axis_index("mc") + r * jax.lax.axis_index("mr")
    if d is Dist.VR:
        return jax.lax.axis_index("mr") + c * jax.lax.axis_index("mc")
    if d is Dist.MD:
        g, L, inv = md_params(r, c)
        i = jax.lax.axis_index("mc")
        j = jax.lax.axis_index("mr")
        k0 = (i + r * ((((j - i) // g) * inv) % (c // g))) % L
        return jax.numpy.where((i - j) % g == 0, k0, L)
    return 0
