"""DistMatrix: a distributed matrix as a JAX pytree.

The TPU-native re-design of the reference's
``DistMatrix<T,ColDist,RowDist>`` (Elemental
``include/El/core/DistMatrix/``): one dataclass whose single array leaf is

  * INSIDE ``shard_map``: this device's local cyclic block, shape
    ``(local_rows, local_cols)`` -- exactly Elemental's local ``Matrix<T>``
    (local(iLoc,jLoc) = global(iLoc*colStride + colShift, ...)), padded to the
    uniform per-device extent ``ceil(extent/stride)`` with ZEROS (SPMD needs
    static uniform shapes; keeping padding zero makes matmul-family ops
    padding-oblivious).

  * OUTSIDE ``shard_map``: the "stacked storage" array of shape
    ``(S_col*local_rows, S_row*local_cols)`` sharded with
    ``PartitionSpec(spec_component(cdist), spec_component(rdist))`` -- each
    device's tile of the storage array IS its local block.  The storage array
    is an index-permutation of the mathematical matrix, never interpreted
    directly; use ``to_global``/``from_global`` at the API edge.

All metadata (global shape, distribution tags, alignments, grid) is static
pytree aux data, so jit re-specializes per distribution -- the moral analog
of the reference's one-template-specialization-per-pair design.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from . import indexing as ix
from .dist import (Dist, LEGAL_PAIRS, stride as dist_stride,
                   storage_slots, spec_component, rank_of, md_slot_of_global)
from .grid import Grid, default_grid


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["local"],
    meta_fields=["gshape", "cdist", "rdist", "calign", "ralign", "grid"],
)
@dataclasses.dataclass(frozen=True)
class DistMatrix:
    local: Any                    # jax.Array leaf (local block / stacked storage)
    gshape: tuple                 # true (unpadded) global shape (m, n)
    cdist: Dist
    rdist: Dist
    calign: int
    ralign: int
    grid: Grid

    # ---- static layout math -----------------------------------------
    @property
    def col_stride(self) -> int:
        return dist_stride(self.cdist, self.grid.height, self.grid.width)

    @property
    def row_stride(self) -> int:
        return dist_stride(self.rdist, self.grid.height, self.grid.width)

    @property
    def local_rows(self) -> int:
        return ix.max_local_length(self.gshape[0], self.col_stride)

    @property
    def local_cols(self) -> int:
        return ix.max_local_length(self.gshape[1], self.row_stride)

    @property
    def local_shape(self) -> tuple:
        return (self.local_rows, self.local_cols)

    @property
    def spec(self) -> P:
        return P(spec_component(self.cdist), spec_component(self.rdist))

    @property
    def dist(self) -> tuple:
        return (self.cdist, self.rdist)

    @property
    def dtype(self):
        return self.local.dtype

    def col_shift(self):
        """Traced: first global row owned by this device (shard_map only)."""
        g = self.grid
        return ix.shift(rank_of(self.cdist, g.height, g.width), self.calign, self.col_stride)

    def row_shift(self):
        g = self.grid
        return ix.shift(rank_of(self.rdist, g.height, g.width), self.ralign, self.row_stride)

    # ---- functional update helpers ----------------------------------
    def with_local(self, local) -> "DistMatrix":
        return dataclasses.replace(self, local=local)

    def like(self, local, gshape=None) -> "DistMatrix":
        return dataclasses.replace(
            self, local=local, gshape=self.gshape if gshape is None else gshape
        )

    def astype(self, dtype) -> "DistMatrix":
        return self.with_local(self.local.astype(dtype))

    def __repr__(self):
        return (
            f"DistMatrix[{self.cdist.value},{self.rdist.value}]"
            f"(gshape={self.gshape}, grid={self.grid}, dtype={self.local.dtype})"
        )


def _check_pair(cdist: Dist, rdist: Dist):
    if (cdist, rdist) not in LEGAL_PAIRS:
        raise ValueError(f"illegal distribution pair [{cdist},{rdist}]")


# ---------------------------------------------------------------------
# Global <-> storage bridges (the API edge; cf. SURVEY.md §8.1 item 2)
# ---------------------------------------------------------------------

def _storage_index(extent: int, stride: int, align: int):
    """Flat index map: storage position (q*l + iLoc) <- global index.

    Returns int array of length stride*l whose entries are global indices
    (>= extent for padding positions).
    """
    l = ix.max_local_length(extent, stride)
    q = jnp.arange(stride).reshape(stride, 1)
    il = jnp.arange(l).reshape(1, l)
    gi = il * stride + (q - align) % stride
    # mark padding (gi >= extent handled by take-fill)
    return gi.reshape(-1)


def _storage_index_dim(extent: int, d: Dist, r: int, c: int, align: int):
    """Storage-position -> global-index map for one dimension, MD-aware."""
    if d is Dist.MD:
        if align:
            raise ValueError("MD alignments are unsupported")
        L = dist_stride(d, r, c)
        l = ix.max_local_length(extent, L)
        slots = r * c * l
        inv = np.full(slots, extent, np.int64)        # padding sentinel
        inv[np.asarray(md_slot_of_global(r, c, extent))] = np.arange(extent)
        return jnp.asarray(inv)
    return _storage_index(extent, dist_stride(d, r, c), align)


def from_global(arr, cdist: Dist, rdist: Dist, grid: Grid | None = None,
                calign: int = 0, ralign: int = 0, device_put: bool = True) -> DistMatrix:
    """Build a DistMatrix (stacked-storage form) from a replicated global array."""
    _check_pair(cdist, rdist)
    grid = grid or default_grid()
    arr = jnp.asarray(arr)
    m, n = arr.shape
    r, c = grid.height, grid.width
    if cdist is Dist.CIRC:
        # root-only: the full array on device 0, nothing elsewhere
        dm = DistMatrix(arr, (m, n), cdist, rdist, 0, 0, grid)
        if device_put:
            dm = dm.with_local(jax.device_put(
                arr, jax.sharding.SingleDeviceSharding(grid.mesh.devices.flat[0])))
        return dm
    ridx = _storage_index_dim(m, cdist, r, c, calign)
    cidx = _storage_index_dim(n, rdist, r, c, ralign)
    stor = jnp.take(arr, ridx, axis=0, mode="fill", fill_value=0)
    stor = jnp.take(stor, cidx, axis=1, mode="fill", fill_value=0)
    dm = DistMatrix(stor, (m, n), cdist, rdist, calign, ralign, grid)
    if device_put:
        dm = dm.with_local(jax.device_put(stor, grid.sharding(dm.spec)))
    return dm


def to_global(A: DistMatrix):
    """Recover the mathematical (m, n) array from stacked storage."""
    m, n = A.gshape
    if A.cdist is Dist.CIRC:
        return A.local
    r, c = A.grid.height, A.grid.width
    sc, sr = A.col_stride, A.row_stride
    lr, lc = A.local_rows, A.local_cols
    stor = A.local
    if A.cdist is Dist.MD:
        ri = jnp.asarray(md_slot_of_global(r, c, m))
    else:
        ri = ((jnp.arange(m) + A.calign) % sc) * lr + jnp.arange(m) // sc
    if A.rdist is Dist.MD:
        cj = jnp.asarray(md_slot_of_global(r, c, n))
    else:
        cj = ((jnp.arange(n) + A.ralign) % sr) * lc + jnp.arange(n) // sr
    out = jnp.take(stor, ri, axis=0)
    out = jnp.take(out, cj, axis=1)
    return out


def zeros(m: int, n: int, cdist: Dist = Dist.MC, rdist: Dist = Dist.MR,
          grid: Grid | None = None, dtype=jnp.float32,
          calign: int = 0, ralign: int = 0) -> DistMatrix:
    _check_pair(cdist, rdist)
    grid = grid or default_grid()
    r, c = grid.height, grid.width
    if cdist is Dist.CIRC:
        dm = DistMatrix(None, (m, n), cdist, rdist, 0, 0, grid)
        stor = jnp.zeros((m, n), dtype)
        return dm.with_local(jax.device_put(
            stor, jax.sharding.SingleDeviceSharding(grid.mesh.devices.flat[0])))
    qc, qr_ = storage_slots(cdist, r, c), storage_slots(rdist, r, c)
    sc, sr = dist_stride(cdist, r, c), dist_stride(rdist, r, c)
    lr, lc = ix.max_local_length(m, sc), ix.max_local_length(n, sr)
    dm = DistMatrix(None, (m, n), cdist, rdist, calign, ralign, grid)
    stor = jnp.zeros((qc * lr, qr_ * lc), dtype)
    return dm.with_local(jax.device_put(stor, grid.sharding(dm.spec)))


def remote_updates(A: DistMatrix, rows, cols, vals) -> DistMatrix:
    """Batched global updates ``A[rows[k], cols[k]] += vals[k]`` -- the
    ``AxpyInterface`` / ``Reserve+QueueUpdate+ProcessQueues`` analog for
    DistMatrix (upstream ``include/El/core/AxpyInterface.hpp``): callers
    queue arbitrary (possibly duplicate) global updates; one scatter-add
    on the storage array lands them, XLA routing the cross-device writes
    (the nonblocking two-sided exchange the reference does by hand).

    Indices are validated host-side when concrete; cyclic layouts only
    (MD/CIRC route through a redistribution first)."""
    from .multivec import _validate_update_indices
    if Dist.MD in A.dist or Dist.CIRC in A.dist:
        raise ValueError("remote_updates supports cyclic layouts; "
                         "redistribute MD/CIRC operands first")
    m, n = A.gshape
    _validate_update_indices(rows, cols, m, n, A.gshape)
    i = jnp.asarray(rows)
    j = jnp.asarray(cols)
    vals = jnp.asarray(vals, A.dtype)
    sc, sr = A.col_stride, A.row_stride
    lr, lc = A.local_rows, A.local_cols
    si = ((i + A.calign) % sc) * lr + i // sc
    sj = ((j + A.ralign) % sr) * lc + j // sr
    return A.with_local(A.local.at[si, sj].add(vals))
