"""Solver service: deadline-bounded, batched, fault-isolated serving.

The ISSUE-9 front-end that turns eight PRs of single-solve machinery
into a system that survives production traffic -- the ROADMAP's
"millions of users" workload of many small-to-medium solves (arXiv
2112.09017):

  :mod:`.admission`  shape-bucketing into the tuner's pow2 buckets,
                     per-request :class:`Deadline` objects threaded
                     through dispatch, and load shedding that
                     rejects-fast with ``serve_reject/v1``
  :mod:`.executor`   padded ``vmap``'d Cholesky/LU batch solves with a
                     persistent AOT-compiled executable cache (no
                     request pays compile)
  :mod:`.policy`     deadline-aware retry with seeded backoff+jitter,
                     the per-bucket circuit breaker (trip / half-open
                     probe / close), and the load-aware degradation
                     ladder (quant-first under pressure)
  :mod:`.service`    :class:`SolverService` -- submit/drain, trusted
                     per-request certification, bisect fault isolation,
                     escalation through ``certified_solve(deadline=)``
  :mod:`.async_front` :class:`AsyncSolverService` -- the ISSUE-14
                     pipelined front: one worker thread double-buffers
                     host staging against device execution (donated
                     batch buffers), completions stream as
                     :class:`ServeFuture` resolutions
  :mod:`.chaos`      the acceptance-matrix harness over the ISSUE-7
                     ``FaultPlan`` machinery, grown a fleet column
                     (saturation + grid loss, ISSUE 19)
  :mod:`.scheduler`  :class:`FairScheduler` -- per-tenant deficit-round-
                     robin queues and :class:`TenantQuota` outstanding
                     caps (ISSUE 19)
  :mod:`.fleet`      :class:`SolverFleet` -- the ISSUE-19 tentpole:
                     devices partitioned into independent solver grids
                     (own executor cache / breakers / tuner namespace /
                     EWMA each), depth-k pipelined workers, and
                     tenant-aware routing by measured per-grid latency

CLI: ``python -m perf.serve {run,smoke,chaos,fleet-smoke}``; bench:
``python bench_serve.py`` (p50/p99 + solves/sec + the multi-grid fleet
section, gated by ``tools/bench_diff.py``); gates: ``tools/check.sh
serve`` and ``tools/check.sh fleet``.
"""
from .admission import (REJECT_SCHEMA, AdmissionController, Bucket,
                        Deadline, SolveRequest, make_bucket, reject_doc,
                        validate_problem)
from .executor import (EXEC_SCHEMA, ExecutableCache, Executor, batch_slots,
                       ls_residual, pad_problem, pad_problem_ls, residual,
                       route_for, tune_token)
from .policy import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker, RetryPolicy,
                     select_ladder)
from .service import RESULT_SCHEMA, SolverService
from .async_front import (AsyncSolverService, ServeFuture,
                          donation_safe, serve_async)
from .chaos import (CHAOS_SCHEMA, build_workload, chaos_matrix,
                    fleet_replay_identical, replay_identical,
                    run_async_cell, run_async_shutdown_cell, run_cell,
                    run_fleet_grid_loss_cell, run_fleet_saturation_cell,
                    run_qr_cell)
from .scheduler import DEFAULT_TENANT, FairScheduler, TenantQuota
from .fleet import (FleetFuture, GridWorker, SolverFleet,
                    partition_devices)

__all__ = [
    "REJECT_SCHEMA", "AdmissionController", "Bucket", "Deadline",
    "SolveRequest", "make_bucket", "reject_doc",
    "EXEC_SCHEMA", "ExecutableCache", "Executor", "batch_slots",
    "ls_residual", "pad_problem", "pad_problem_ls", "residual",
    "route_for", "tune_token",
    "CLOSED", "HALF_OPEN", "OPEN", "CircuitBreaker", "RetryPolicy",
    "select_ladder",
    "RESULT_SCHEMA", "SolverService",
    "AsyncSolverService", "ServeFuture", "serve_async",
    "donation_safe",
    "CHAOS_SCHEMA", "build_workload", "chaos_matrix", "replay_identical",
    "run_async_cell", "run_async_shutdown_cell", "run_cell", "run_qr_cell",
    "fleet_replay_identical", "run_fleet_grid_loss_cell",
    "run_fleet_saturation_cell",
    "DEFAULT_TENANT", "FairScheduler", "TenantQuota",
    "FleetFuture", "GridWorker", "SolverFleet", "partition_devices",
    "validate_problem",
]
