"""Batched bucket executor: vmap'd solves + a persistent AOT-compiled
executable cache.

The throughput core of the solver service (ISSUE 9).  All requests in
one :class:`~.admission.Bucket` are PADDED to the bucket's canonical
geometry (the system embedded top-left, identity on the padded diagonal,
zero right-hand sides -- the padded solution's extra rows are exactly
zero, so truncation is lossless), stacked, and solved by ONE dispatch of
a ``jax.vmap``'d Cholesky/LU kernel: hundreds of small systems amortize
one launch, exactly the serving workload the ROADMAP names.

No request ever pays compile: executables are AOT-lowered and compiled
ONCE per ``(op, bucket, batch-slot, dtype, backend)`` key -- the same
key vocabulary as ``tuning_cache/v1`` -- and cached for the life of the
process (``serve_exec_cache/v1``; hits/misses/compiles are counted on
the obs metrics registry as ``serve_exec_cache_events``).  Batch sizes
are pow2-bucketed too (``batch_slots``), so a queue draining 3, 5, then
6 requests reuses the 4- and 8-slot executables instead of compiling
three shapes.

The batch output routes through the engine's ``'compute'`` fault seam
(:func:`~elemental_tpu.redist.engine.apply_fault`) before certification,
so chaos tests can model a soft error in the batched local math -- the
serve-side twin of the driver panel seams.

Certification is the same TRUSTED measurement ``certified_solve`` uses:
host-side float64 residuals per request (a corrupted executor can
corrupt the solve, never the measurement).
"""
from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as _metrics
from ..redist.engine import apply_fault
from .admission import Bucket

EXEC_SCHEMA = "serve_exec_cache/v1"


def batch_slots(k: int) -> int:
    """Pow2 slot count for a batch of ``k`` requests (>= 1)."""
    k = max(int(k), 1)
    return 1 << (k - 1).bit_length()


def pad_problem(A: np.ndarray, B: np.ndarray, bucket: Bucket):
    """Embed one (n, n) system into the bucket's canonical geometry.

    Returns ``(Ap, Bp)`` with ``Ap = [[A, 0], [0, I]]`` (nonsingular and
    HPD-preserving by construction) and ``Bp = [[B], [0]]`` zero-padded
    on both dims, so ``Xp[:n, :nrhs]`` IS the original solution."""
    n, nrhs = A.shape[0], B.shape[1]
    dt = np.dtype(bucket.dtype)
    Ap = np.eye(bucket.n, dtype=dt)
    Ap[:n, :n] = A
    Bp = np.zeros((bucket.n, bucket.nrhs), dtype=dt)
    Bp[:n, :nrhs] = B
    return Ap, Bp


def _kernel(op: str):
    """The one-problem solve kernel ``(A, B) -> X`` that gets vmapped."""
    import jax
    import jax.numpy as jnp

    if op == "lu":
        def solve(a, b):
            lu_, piv = jax.scipy.linalg.lu_factor(a)
            return jax.scipy.linalg.lu_solve((lu_, piv), b)
    elif op == "hpd":
        def solve(a, b):
            L = jnp.linalg.cholesky(a)
            y = jax.scipy.linalg.solve_triangular(L, b, lower=True)
            return jax.scipy.linalg.solve_triangular(
                jnp.conj(L).T, y, lower=False)
    else:
        raise ValueError(f"executor op must be 'lu' or 'hpd', got {op!r}")
    return solve


class ExecutableCache:
    """AOT-compiled batched solvers, keyed like ``tuning_cache/v1``.

    One entry per ``(op, bucket, slots, dtype, backend)``; the first
    request of a geometry pays ``lower().compile()`` ONCE, every later
    batch calls the compiled executable directly.  In-process persistent
    (executable serialization is backend-specific; the jax persistent
    compilation cache makes cold processes cheap where available)."""

    def __init__(self):
        self._cache: dict = {}

    @staticmethod
    def key(op: str, bucket: Bucket, slots: int, backend: str) -> str:
        return (f"{op}__b{bucket.n}x{bucket.nrhs}__x{slots}"
                f"__{bucket.dtype}__{backend}")

    def get(self, op: str, bucket: Bucket, slots: int):
        """The compiled batched executable for this geometry."""
        import jax

        backend = jax.default_backend()
        key = self.key(op, bucket, slots, backend)
        hit = self._cache.get(key)
        if hit is not None:
            _metrics.inc("serve_exec_cache_events", op=op, event="hit")
            return hit
        _metrics.inc("serve_exec_cache_events", op=op, event="miss")
        a = jax.ShapeDtypeStruct((slots, bucket.n, bucket.n),
                                 np.dtype(bucket.dtype))
        b = jax.ShapeDtypeStruct((slots, bucket.n, bucket.nrhs),
                                 np.dtype(bucket.dtype))
        compiled = jax.jit(jax.vmap(_kernel(op))).lower(a, b).compile()
        _metrics.inc("serve_exec_cache_events", op=op, event="compile")
        self._cache[key] = compiled
        return compiled

    def stats(self) -> dict:
        return {"schema": EXEC_SCHEMA, "entries": sorted(self._cache)}

    def clear(self) -> None:
        self._cache.clear()


class Executor:
    """Runs padded batches through the cached executables."""

    def __init__(self, *, clock=time.monotonic):
        self.cache = ExecutableCache()
        self.clock = clock

    def run(self, bucket: Bucket, requests):
        """Solve every request of one bucket in ONE batched dispatch.

        Returns ``(xs, seconds)``: ``xs[i]`` is request i's UNPADDED host
        solution (float64), ``seconds`` the wall-clock of the dispatch
        (what the admission EWMA feeds on).  The batch output crosses the
        ``'compute'`` fault seam before truncation."""
        import jax
        import jax.numpy as jnp

        k = len(requests)
        if k == 0:
            return [], 0.0
        slots = batch_slots(k)
        dt = np.dtype(bucket.dtype)
        a = np.broadcast_to(np.eye(bucket.n, dtype=dt),
                            (slots, bucket.n, bucket.n)).copy()
        b = np.zeros((slots, bucket.n, bucket.nrhs), dtype=dt)
        for i, req in enumerate(requests):
            a[i], b[i] = pad_problem(req.A, req.B, bucket)
        compiled = self.cache.get(bucket.op, bucket, slots)
        t0 = self.clock()
        X = compiled(jnp.asarray(a), jnp.asarray(b))
        X.block_until_ready()
        seconds = self.clock() - t0
        X, = apply_fault("compute", (X,))
        Xh = np.asarray(X, dtype=np.float64)
        xs = [Xh[i, :req.n, :req.nrhs] for i, req in enumerate(requests)]
        _metrics.inc("serve_batches", op=bucket.op)
        _metrics.inc("serve_batched_solves", k, op=bucket.op)
        return xs, seconds


def residual(A: np.ndarray, B: np.ndarray, X: np.ndarray) -> float:
    """TRUSTED host-float64 normwise relative backward error -- the same
    certificate measurement ``resilience.certify`` uses, computed from
    the caller-held problem data (never the executor's arrays)."""
    An = np.asarray(A, dtype=np.float64)
    Bn = np.asarray(B, dtype=np.float64)
    Xn = np.asarray(X, dtype=np.float64)
    with np.errstate(over="ignore", invalid="ignore"):
        den = (np.linalg.norm(An) * np.linalg.norm(Xn)
               + np.linalg.norm(Bn))
        if not np.isfinite(den) or den == 0.0:
            return float("inf")
        res = np.linalg.norm(Bn - An @ Xn) / den
    return float(res) if np.isfinite(res) else float("inf")
