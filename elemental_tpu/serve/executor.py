"""Batched bucket executor: vmap'd solves + a persistent AOT-compiled
executable cache.

The throughput core of the solver service (ISSUE 9, async-pipelined in
ISSUE 14).  All requests in one :class:`~.admission.Bucket` are PADDED
to the bucket's canonical geometry (the system embedded top-left,
identity on the padded diagonal, zero right-hand sides -- the padded
solution's extra rows are exactly zero, so truncation is lossless),
stacked, and solved by ONE dispatch of a ``jax.vmap``'d
Cholesky/LU/QR kernel: hundreds of small systems amortize one launch,
exactly the serving workload the ROADMAP names.

The batch path is split into three stages so an async front-end can
overlap them across batches (ISSUE 14 tentpole):

  * :meth:`Executor.stage`    -- host work: pad/stack + executable lookup
  * :meth:`Executor.dispatch` -- device launch; returns BEFORE the device
    finishes (jax async dispatch), so the host is free to stage batch
    k+1 while batch k runs
  * :meth:`Executor.collect`  -- ``block_until_ready`` + fault seam +
    host truncation + certifiable float64 slices

``Executor.run`` is the synchronous composition of the three and keeps
PR-9 semantics bit-for-bit.  With ``donate=True`` the compiled batch
executable is built with ``donate_argnums=(0, 1)``: steady-state serving
re-uses the batch buffers instead of allocating (on backends where an
operand can alias the output -- the B operand here; the A operand never
can, which jax reports as an ignorable "donated buffers were not
usable" warning, suppressed at compile time).

No request ever pays compile: executables are AOT-lowered and compiled
ONCE per ``(op, bucket, batch-slot, dtype, backend, tuner-provenance,
donation)`` key -- the geometry part is the same key vocabulary as
``tuning_cache/v1`` -- and cached for the life of the process
(``serve_exec_cache/v1``; hits/misses/compiles are counted on the obs
metrics registry as ``serve_exec_cache_events``).  Batch sizes are
pow2-bucketed too (``batch_slots``), so a queue draining 3, 5, then 6
requests reuses the 4- and 8-slot executables instead of compiling
three shapes.  The tuner-provenance component (:func:`tune_token`) is a
digest of the resolved tuning-cache winner for the mapped driver op, so
a tuner re-sweep (every ``tune.cache.save``/``clear`` bumps the
in-process epoch) can never serve a stale executable.

Dispatch is tuner-fed (:func:`route_for`): when the tuning cache holds a
MEASURED winner for the mapped distributed driver whose seconds beat the
replicated vmap path's per-request estimate, the request leaves the
batch path for the grid path -- and either way the decision lands in
``serve_result/v1`` provenance.

The batch output routes through the engine's ``'compute'`` fault seam
(:func:`~elemental_tpu.redist.engine.apply_fault`) before certification,
so chaos tests can model a soft error in the batched local math -- the
serve-side twin of the driver panel seams.

Certification is the same TRUSTED measurement ``certified_solve`` uses:
host-side float64 residuals per request (a corrupted executor can
corrupt the solve, never the measurement).  Least-squares requests
certify on the normal-equations residual (:func:`ls_residual`), which
vanishes at the LS minimizer even when ``B - A X`` cannot.
"""
from __future__ import annotations

import dataclasses
import json
import time
import warnings
import zlib

import numpy as np

from ..obs import metrics as _metrics
from ..redist.engine import apply_fault
from ..tune import cache as _tune
from .admission import Bucket

EXEC_SCHEMA = "serve_exec_cache/v1"

#: serve op -> distributed-driver op in the ``tuning_cache/v1`` vocabulary
#: (what :func:`tune_token` digests and :func:`route_for` consults)
DRIVER_OPS = {"hpd": "cholesky", "lu": "lu", "lstsq": "qr"}


def batch_slots(k: int) -> int:
    """Pow2 slot count for a batch of ``k`` requests (>= 1)."""
    k = max(int(k), 1)
    return 1 << (k - 1).bit_length()


def pad_problem(A: np.ndarray, B: np.ndarray, bucket: Bucket):
    """Embed one (n, n) system into the bucket's canonical geometry.

    Returns ``(Ap, Bp)`` with ``Ap = [[A, 0], [0, I]]`` (nonsingular and
    HPD-preserving by construction) and ``Bp = [[B], [0]]`` zero-padded
    on both dims, so ``Xp[:n, :nrhs]`` IS the original solution."""
    n, nrhs = A.shape[0], B.shape[1]
    dt = np.dtype(bucket.dtype)
    Ap = np.eye(bucket.n, dtype=dt)
    Ap[:n, :n] = A
    Bp = np.zeros((bucket.n, bucket.nrhs), dtype=dt)
    Bp[:n, :nrhs] = B
    return Ap, Bp


def pad_problem_ls(A: np.ndarray, B: np.ndarray, bucket: Bucket):
    """Embed one (m, n) least-squares problem into the bucket geometry.

    ``Ap[:m, :n] = A`` and an identity block fills the EXTRA columns in
    the EXTRA rows: ``Ap[m : m + (N - n), n:] = I``.  The pad columns
    are therefore orthogonal to A's columns, the padded normal equations
    decouple, and ``Xp[:n]`` is exactly the original LS minimizer
    (``Xp[n:] = 0`` since the pad rows of B are zero).  ``make_bucket``
    guarantees ``M >= m + (N - n)`` so the identity always fits."""
    m, n = A.shape
    nrhs = B.shape[1]
    dt = np.dtype(bucket.dtype)
    N, M = bucket.n, bucket.m
    Ap = np.zeros((M, N), dtype=dt)
    Ap[:m, :n] = A
    if N > n:
        Ap[m:m + (N - n), n:] = np.eye(N - n, dtype=dt)
    Bp = np.zeros((M, bucket.nrhs), dtype=dt)
    Bp[:m, :nrhs] = B
    return Ap, Bp


def _kernel(op: str):
    """The one-problem solve kernel ``(A, B) -> X`` that gets vmapped."""
    import jax
    import jax.numpy as jnp

    if op == "lu":
        def solve(a, b):
            lu_, piv = jax.scipy.linalg.lu_factor(a)
            return jax.scipy.linalg.lu_solve((lu_, piv), b)
    elif op == "hpd":
        def solve(a, b):
            L = jnp.linalg.cholesky(a)
            y = jax.scipy.linalg.solve_triangular(L, b, lower=True)
            return jax.scipy.linalg.solve_triangular(
                jnp.conj(L).T, y, lower=False)
    elif op == "lstsq":
        def solve(a, b):
            q, r = jnp.linalg.qr(a, mode="reduced")
            return jax.scipy.linalg.solve_triangular(
                r, jnp.conj(q).T @ b, lower=False)
    else:
        raise ValueError(
            f"executor op must be 'lu', 'hpd' or 'lstsq', got {op!r}")
    return solve


#: memoized static batch peaks: (op, dims, nrhs, dtype, slots) -> bytes
_PEAK_MEMO: dict = {}


def batch_peak_bytes(bucket: Bucket, slots: int) -> int:
    """Statically derived peak live bytes of ONE ``slots``-wide batch of
    this bucket: the SAME vmapped solve kernel the executor compiles,
    abstractly traced and liveness-walked (``analysis.memory``) -- no
    device execution, no compile.  Feeds the admission controller's
    memory-pressure shed decision (ISSUE 18)."""
    m, n = _bucket_dims(bucket)
    key = (bucket.op, m, n, bucket.nrhs, str(bucket.dtype), int(slots))
    hit = _PEAK_MEMO.get(key)
    if hit is not None:
        return hit
    import jax
    from ..analysis.memory import analyze_jaxpr
    a = jax.ShapeDtypeStruct((int(slots), m, n), bucket.dtype)
    b = jax.ShapeDtypeStruct((int(slots), m, bucket.nrhs), bucket.dtype)
    closed = jax.make_jaxpr(jax.vmap(_kernel(bucket.op)))(a, b)
    peak = analyze_jaxpr(closed, grid_size=1).peak_bytes
    _PEAK_MEMO[key] = peak
    return peak


#: memoized tuner-provenance tokens: (cache_dir, driver_op, dims, dtype,
#: backend) -> (tune-cache epoch, token).  Recomputed only when the
#: in-process tuning-cache write generation moves (ISSUE 14 satellite:
#: a re-sweep invalidates without a file read per batch).
_TOKEN_MEMO: dict = {}


def _bucket_dims(bucket: Bucket) -> tuple:
    return (bucket.m, bucket.n) if bucket.m is not None \
        else (bucket.n, bucket.n)


def tune_token(op: str, bucket: Bucket, backend: str, ns: str = "") -> str:
    """Digest of the resolved tuning-cache winner for this geometry.

    Empty string when the mapped driver op has no cache entry (the
    common cold case -- executable keys stay byte-identical to PR 9).
    Otherwise a crc32 over the winner's config/created/source, so any
    re-sweep that changes the resolved knobs changes the executable key
    and forces a fresh compile instead of serving a stale binary.
    ``ns`` scopes the lookup to a fleet member's namespaced entries
    (ISSUE 19): two pool grids can resolve DIFFERENT winners."""
    driver_op = DRIVER_OPS.get(op)
    if driver_op is None:
        return ""
    dims = _bucket_dims(bucket)
    memo_key = (_tune.cache_dir(), driver_op, dims, bucket.dtype, backend,
                ns)
    ep = _tune.epoch()
    cached = _TOKEN_MEMO.get(memo_key)
    if cached is not None and cached[0] == ep:
        return cached[1]
    doc = _tune.load(
        _tune.make_key(driver_op, dims, bucket.dtype, (1, 1), backend,
                       ns=ns))
    if doc is None:
        token = ""
    else:
        blob = json.dumps(
            [doc.get("config"), doc.get("created"), doc.get("source")],
            sort_keys=True)
        token = format(zlib.crc32(blob.encode()), "08x")
    _TOKEN_MEMO[memo_key] = (ep, token)
    return token


def route_for(bucket: Bucket, grid_shape, backend: str,
              est_vmap_s: float | None, ns: str = ""):
    """Tuner-fed dispatch decision for ONE request of ``bucket``.

    Returns ``(route, provenance)`` with route ``'vmap'`` (the batched
    replicated path) or ``'grid'`` (the distributed driver path).  The
    request leaves the vmap path ONLY when the tuning cache holds a
    MEASURED winner for the mapped driver op at this geometry on
    ``grid_shape`` whose recorded seconds strictly beat the vmap path's
    per-request estimate (``est_vmap_s``, the admission EWMA / cold
    flops model) -- a missing or unmeasured entry always stays on vmap,
    so routing is deterministic on a cold cache.  ``ns`` scopes the
    lookup to a fleet member's namespaced constants (ISSUE 19).  The
    provenance dict is what ``serve_result/v1`` records as its
    ``dispatch`` field."""
    driver_op = DRIVER_OPS.get(bucket.op)
    prov = {"route": "vmap", "driver_op": driver_op,
            "grid": list(grid_shape), "source": "default",
            "tune_token": "", "measured_s": None,
            "vmap_est_s": None if est_vmap_s is None else float(est_vmap_s)}
    if driver_op is None:
        return "vmap", prov
    prov["tune_token"] = tune_token(bucket.op, bucket, backend, ns=ns)
    doc = _tune.load(_tune.make_key(driver_op, _bucket_dims(bucket),
                                    bucket.dtype, tuple(grid_shape),
                                    backend, ns=ns))
    if doc is None or doc.get("source") != "measured":
        return "vmap", prov
    prov["source"] = "measured"
    sec = (doc.get("metric") or {}).get("seconds")
    if sec is None:
        return "vmap", prov
    prov["measured_s"] = float(sec)
    if est_vmap_s is not None and float(sec) < float(est_vmap_s):
        prov["route"] = "grid"
        return "grid", prov
    return "vmap", prov


class ExecutableCache:
    """AOT-compiled batched solvers, keyed like ``tuning_cache/v1``.

    One entry per ``(op, bucket, slots, dtype, backend)`` plus -- when
    set -- the resolved tuner-provenance token and the donation flag
    (ISSUE 14): a re-sweep or a donating front-end gets its OWN
    executable instead of a stale or non-donating one.  The first
    request of a geometry pays ``lower().compile()`` ONCE, every later
    batch calls the compiled executable directly.  In-process persistent
    (executable serialization is backend-specific; the jax persistent
    compilation cache makes cold processes cheap where available)."""

    def __init__(self):
        self._cache: dict = {}

    @staticmethod
    def key(op: str, bucket: Bucket, slots: int, backend: str,
            tune: str = "", donate: bool = False,
            device=None) -> str:
        if bucket.m is not None:
            geo = f"b{bucket.m}x{bucket.n}x{bucket.nrhs}"
        else:
            geo = f"b{bucket.n}x{bucket.nrhs}"
        key = f"{op}__{geo}__x{slots}__{bucket.dtype}__{backend}"
        if tune:
            key += f"__t{tune}"
        if donate:
            key += "__donated"
        if device is not None:
            # fleet members pin their batches to the grid's lead device
            # (ISSUE 19): one executable per pinned placement; the
            # unpinned key stays byte-identical to PR 9
            key += f"__d{device.id}"
        return key

    def get(self, op: str, bucket: Bucket, slots: int, *,
            donate: bool = False, device=None, tune_ns: str = ""):
        """The compiled batched executable for this geometry.

        ``device`` (ISSUE 19) AOT-lowers the executable with its inputs
        pinned to that device (``SingleDeviceSharding``), so each fleet
        grid's batches execute on ITS devices instead of the backend
        default; ``tune_ns`` scopes the tuner-provenance token to the
        member's namespaced constants."""
        import jax

        backend = jax.default_backend()
        key = self.key(op, bucket, slots, backend,
                       tune=tune_token(op, bucket, backend, ns=tune_ns),
                       donate=donate, device=device)
        hit = self._cache.get(key)
        if hit is not None:
            _metrics.inc("serve_exec_cache_events", op=op, event="hit")
            return hit
        _metrics.inc("serve_exec_cache_events", op=op, event="miss")
        rows = bucket.m if bucket.m is not None else bucket.n
        sharding = None if device is None \
            else jax.sharding.SingleDeviceSharding(device)
        skw = {} if sharding is None else {"sharding": sharding}
        a = jax.ShapeDtypeStruct((slots, rows, bucket.n),
                                 np.dtype(bucket.dtype), **skw)
        b = jax.ShapeDtypeStruct((slots, rows, bucket.nrhs),
                                 np.dtype(bucket.dtype), **skw)
        fn = jax.jit(jax.vmap(_kernel(op)),
                     donate_argnums=(0, 1) if donate else ())
        with warnings.catch_warnings():
            # the A operand's shape can never alias the X output, so jax
            # reports its donation as unusable; only B's aliasing is the
            # point, and the warning is not actionable
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onated buffers.*")
            compiled = fn.lower(a, b).compile()
        _metrics.inc("serve_exec_cache_events", op=op, event="compile")
        self._cache[key] = compiled
        return compiled

    def stats(self) -> dict:
        return {"schema": EXEC_SCHEMA, "entries": sorted(self._cache)}

    def clear(self) -> None:
        self._cache.clear()


@dataclasses.dataclass
class Staged:
    """One staged batch in flight: padded operands + its executable.

    Produced by :meth:`Executor.stage`; :meth:`Executor.dispatch` fills
    ``X``/``t0`` (and drops the operand references when they were
    donated -- they are invalid afterwards); :meth:`Executor.collect`
    consumes it."""
    bucket: Bucket
    requests: list
    compiled: object
    a: object
    b: object
    donate: bool
    X: object = None
    t0: float = 0.0


class Executor:
    """Runs padded batches through the cached executables.

    ``run`` is the synchronous path (PR-9 semantics); the async front
    drives the same three stages itself so batch k+1's host staging
    overlaps batch k's device execution.  ``device``/``tune_ns`` (ISSUE
    19) pin a fleet member's batches to its grid's lead device and scope
    its tuner provenance to the member's constant namespace."""

    def __init__(self, *, clock=time.monotonic, device=None,
                 tune_ns: str = ""):
        self.cache = ExecutableCache()
        self.clock = clock
        self.device = device
        self.tune_ns = str(tune_ns)

    @staticmethod
    def _mark(staged: "Staged", edge: str, **attrs) -> None:
        """Lifecycle edge on every request of the batch (ISSUE 20)."""
        for req in staged.requests:
            tr = getattr(req, "trace", None)
            if tr is not None:
                tr.mark(edge, batch=len(staged.requests), **attrs)

    def stage(self, bucket: Bucket, requests, *, donate: bool = False):
        """HOST stage: pad + stack every request, look up the executable.

        This is the work the async pipeline overlaps with the previous
        batch's device execution.  Returns a :class:`Staged`."""
        import jax
        import jax.numpy as jnp

        t0 = self.clock()
        k = len(requests)
        slots = batch_slots(k)
        dt = np.dtype(bucket.dtype)
        if bucket.m is not None:
            a = np.zeros((slots, bucket.m, bucket.n), dtype=dt)
            a[:, :bucket.n, :] = np.eye(bucket.n, dtype=dt)
            b = np.zeros((slots, bucket.m, bucket.nrhs), dtype=dt)
            for i, req in enumerate(requests):
                a[i], b[i] = pad_problem_ls(req.A, req.B, bucket)
        else:
            a = np.broadcast_to(np.eye(bucket.n, dtype=dt),
                                (slots, bucket.n, bucket.n)).copy()
            b = np.zeros((slots, bucket.n, bucket.nrhs), dtype=dt)
            for i, req in enumerate(requests):
                a[i], b[i] = pad_problem(req.A, req.B, bucket)
        compiled = self.cache.get(bucket.op, bucket, slots, donate=donate,
                                  device=self.device, tune_ns=self.tune_ns)
        if self.device is not None:
            sharding = jax.sharding.SingleDeviceSharding(self.device)
            da = jax.device_put(a, sharding)
            db = jax.device_put(b, sharding)
        else:
            da, db = jnp.asarray(a), jnp.asarray(b)
        staged = Staged(bucket=bucket, requests=list(requests),
                        compiled=compiled, a=da, b=db, donate=donate)
        _metrics.observe("serve_stage_seconds", self.clock() - t0,
                         op=bucket.op, stage="stage")
        self._mark(staged, "staged", slots=slots)
        return staged

    def dispatch(self, staged: Staged) -> Staged:
        """DEVICE launch: returns as soon as the work is enqueued (jax
        async dispatch) -- the host is free to stage the next batch."""
        t0 = self.clock()
        staged.t0 = t0
        staged.X = staged.compiled(staged.a, staged.b)
        if staged.donate:
            staged.a = staged.b = None       # donated: buffers are dead
        _metrics.observe("serve_stage_seconds", self.clock() - t0,
                         op=staged.bucket.op, stage="dispatch")
        self._mark(staged, "dispatched")
        return staged

    def collect(self, staged: Staged):
        """Block for the device result, cross the fault seam, truncate.

        Returns ``(xs, seconds)``: ``xs[i]`` is request i's UNPADDED
        host solution (float64); ``seconds`` the dispatch->ready
        wall-clock (what the admission EWMA feeds on)."""
        bucket, requests = staged.bucket, staged.requests
        X = staged.X
        X.block_until_ready()
        seconds = self.clock() - staged.t0
        t1 = self.clock()
        X, = apply_fault("compute", (X,))
        # OWNED copy, never a zero-copy view: on CPU ``np.asarray`` of a
        # float64 jax array aliases the device buffer, which is freed
        # when the batch's array drops and REUSED by a later batch --
        # already-resolved solutions would silently mutate under the
        # pipelined front (and latently under drain)
        Xh = np.array(X, dtype=np.float64)
        # the padded solution is (bucket.n, bucket.nrhs) for every op --
        # lstsq included (QR of the (M, N) pad yields an (N, nrhs) X);
        # a request's true solution is its A's COLUMN count deep
        xs = [Xh[i, :req.A.shape[1], :req.nrhs]
              for i, req in enumerate(requests)]
        _metrics.inc("serve_batches", op=bucket.op)
        _metrics.inc("serve_batched_solves", len(requests), op=bucket.op)
        _metrics.observe("serve_stage_seconds", self.clock() - t1,
                         op=bucket.op, stage="collect")
        self._mark(staged, "collected", seconds=seconds)
        return xs, seconds

    def run(self, bucket: Bucket, requests, *, donate: bool = False):
        """Solve every request of one bucket in ONE batched dispatch
        (synchronous stage -> dispatch -> collect composition)."""
        if len(requests) == 0:
            return [], 0.0
        return self.collect(self.dispatch(
            self.stage(bucket, requests, donate=donate)))


def residual(A: np.ndarray, B: np.ndarray, X: np.ndarray) -> float:
    """TRUSTED host-float64 normwise relative backward error -- the same
    certificate measurement ``resilience.certify`` uses, computed from
    the caller-held problem data (never the executor's arrays)."""
    An = np.asarray(A, dtype=np.float64)
    Bn = np.asarray(B, dtype=np.float64)
    Xn = np.asarray(X, dtype=np.float64)
    with np.errstate(over="ignore", invalid="ignore"):
        den = (np.linalg.norm(An) * np.linalg.norm(Xn)
               + np.linalg.norm(Bn))
        if not np.isfinite(den) or den == 0.0:
            return float("inf")
        res = np.linalg.norm(Bn - An @ Xn) / den
    return float(res) if np.isfinite(res) else float("inf")


def ls_residual(A: np.ndarray, B: np.ndarray, X: np.ndarray) -> float:
    """TRUSTED host-float64 least-squares certificate: the scaled
    normal-equations residual ``|A' (B - A X)| / (|A|^2 |X| + |A| |B|)``.
    Unlike the plain residual, this vanishes at the LS minimizer even
    when the overdetermined system leaves ``B - A X`` nonzero."""
    An = np.asarray(A, dtype=np.float64)
    Bn = np.asarray(B, dtype=np.float64)
    Xn = np.asarray(X, dtype=np.float64)
    with np.errstate(over="ignore", invalid="ignore"):
        nA = np.linalg.norm(An)
        den = nA * nA * np.linalg.norm(Xn) + nA * np.linalg.norm(Bn)
        if not np.isfinite(den) or den == 0.0:
            return float("inf")
        res = np.linalg.norm(An.conj().T @ (Bn - An @ Xn)) / den
    return float(res) if np.isfinite(res) else float("inf")
