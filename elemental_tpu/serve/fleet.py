"""Solver fleet: a pool of independent solver grids behind one front
door (ISSUE 19 tentpole).

One :class:`~.service.SolverService` owns ONE device grid; past a few
hosts that is the wrong shape for serving -- dense-solve scaling flattens
(the source paper's weak-scaling curves) while request throughput keeps
growing linearly with devices.  The fleet takes the other axis:
**partition** the device set into several SMALL grids, give each its own
full serve stack, and put a tenant-aware router in front::

    submit --> quota gate --> FairScheduler (per-tenant DRR queues)
                                  |
                              router: argmin over grids of
                                ceil((backlog+1)/max_batch)
                                  x per-grid EWMA batch seconds
                              (skip OPEN breakers + memory-shedding)
                                  |
               +------------------+------------------+
               v                  v                  v
           grid g0            grid g1            grid g2
        SolverService      SolverService      SolverService
        own executor       own executor       own executor
        cache, breaker,    cache, breaker,    cache, breaker,
        tuner ns, EWMA     tuner ns, EWMA     tuner ns, EWMA

Each member is a COMPLETE, unmodified serve stack: its executor cache
compiles against its own pinned device, its circuit breakers trip and
probe independently, its tuner constants live under its own cache
namespace (``tune_ns='g0'`` -- two members can hold DIFFERENT measured
winners for the same bucket), and its admission EWMA measures only its
own batches.  The sync :class:`~.service.SolverService` semantics stay
bit-pinned per grid: a fleet of one with no tenants is the PR-9 service.

Routing is load x speed: a request's bucket is known BEFORE a grid is
chosen (``validate_problem``), so the router scores every member by the
batches queued ahead of the request times that member's measured EWMA
for the bucket -- a slow or busy grid loses traffic to a fast idle one,
and the estimate converges per member as batches complete.  Members
whose breaker is OPEN for the bucket (cooldown not elapsed) or whose
per-device memory budget cannot hold the bucket are skipped; when NO
member can take it the reject is structured (``breaker_open`` /
``memory_pressure``, with the blocking grid's id).

Fairness is the :class:`~.scheduler.FairScheduler`'s deficit round
robin plus per-tenant ``max_outstanding`` quotas -- the quota reject
(``reason='quota'``) fires at submit, before anything queues.  Requests
are released to members only as capacity frees (``max_batch x depth``
outstanding per member), so a burst tenant queues in ITS OWN lane
instead of ahead of everyone in a member's FIFO.

Two execution modes:

  * ``pipelined=True`` (default): each member is wrapped in a depth-k
    :class:`~.async_front.AsyncSolverService` worker -- one thread per
    grid, all grids solving concurrently, completions streaming.
  * ``pipelined=False``: members stay synchronous and :meth:`drain`
    round-robins one batch per grid per sweep -- single-threaded and
    deterministic under injected clocks (the chaos-cell mode).

Every submit returns a :class:`FleetFuture` (a
:class:`~.async_front.ServeFuture` that also carries the fleet id,
tenant, and routed grid); result/reject docs carry ``grid`` and
``tenant`` fields (``serve_result/v1`` / ``serve_reject/v1``, absent ==
None for old readers).  Zero silent drops: every future issued resolves,
through results, structured rejects, or shutdown flushes.
"""
from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from ..core.grid import Grid
from ..obs import metrics as _metrics
from ..obs.flight import FlightRecorder
from ..obs.lifecycle import RequestTrace
from ..obs.slo import SLOMonitor
from ..obs.tracer import phase_hook
from .admission import Deadline, reject_doc, validate_problem
from .async_front import AsyncSolverService, ServeFuture
from .policy import OPEN
from .scheduler import DEFAULT_TENANT, FairScheduler
from .service import SolverService


def _fleet_device_order() -> list:
    """All devices, ordered so CONSECUTIVE slices are good grids.

    Single-process (the common case): ``jax.devices()``.  Multi-host:
    the hybrid ICI/DCN mesh order (``mesh_utils.create_hybrid_device_mesh``)
    so a partition slice stays ICI-contiguous within a host group and
    grid collectives never straddle the data-center network needlessly;
    guarded -- any failure falls back to plain device order."""
    import jax
    if jax.process_count() > 1:
        try:
            from jax.experimental import mesh_utils
            mesh = mesh_utils.create_hybrid_device_mesh(
                (jax.local_device_count(),), (jax.process_count(),))
            return list(np.asarray(mesh).reshape(-1))
        except Exception:
            pass
    return list(jax.devices())


def partition_devices(devices=None, grids=2) -> list:
    """Split the device set into per-member device tuples.

    ``grids`` is an int (equal split; must divide the device count) or a
    sequence of sizes (must sum to at most the device count; leftovers
    stay unused).  Slices are consecutive in fleet device order, so each
    member's devices are as tightly coupled as the topology allows."""
    devices = list(_fleet_device_order() if devices is None else devices)
    p = len(devices)
    if isinstance(grids, int):
        g = max(int(grids), 1)
        if p % g != 0:
            raise ValueError(
                f"{g} equal grids do not divide {p} devices; pass "
                f"explicit sizes instead")
        sizes = [p // g] * g
    else:
        sizes = [int(s) for s in grids]
        if any(s < 1 for s in sizes):
            raise ValueError(f"grid sizes must be >= 1, got {sizes}")
        if sum(sizes) > p:
            raise ValueError(
                f"grid sizes {sizes} need {sum(sizes)} devices, "
                f"have {p}")
    out, at = [], 0
    for s in sizes:
        out.append(tuple(devices[at:at + s]))
        at += s
    return out


class FleetFuture(ServeFuture):
    """One fleet completion: a :class:`ServeFuture` plus routing
    provenance -- ``fleet_id`` (fleet-global, unlike per-member request
    ids, which collide across members), ``tenant``, and ``grid`` (the
    member name once routed, None if rejected before routing)."""

    __slots__ = ("fleet_id", "tenant", "grid", "t0")

    def __init__(self, fleet_id: int, tenant: str):
        super().__init__()
        self.fleet_id = fleet_id
        self.tenant = tenant
        self.grid: str | None = None
        self.t0: float | None = None     # fleet submit time (fleet clock)


class _FleetSub:
    """One scheduled submission (held in the FairScheduler until a
    member has capacity)."""

    __slots__ = ("op", "A", "B", "bucket", "deadline", "future", "trace")

    def __init__(self, op, A, B, bucket, deadline, future, trace=None):
        self.op, self.A, self.B = op, A, B
        self.bucket, self.deadline, self.future = bucket, deadline, future
        self.trace = trace


class GridWorker(AsyncSolverService):
    """A fleet member's depth-k async worker (thin naming/introspection
    shell over :class:`AsyncSolverService`)."""

    @property
    def name(self) -> str:
        return self.service.name

    def backlog_requests(self) -> int:
        """Requests inside this worker not yet settled (ingest queue +
        unresolved futures) -- introspection only; the fleet's routing
        backlog is its own lock-consistent counter."""
        return self._qin.qsize() + len(self._futures)


class SolverFleet:
    """See module docstring.

    ``devices=None`` partitions all visible devices into ``grids``
    members (int or explicit sizes, :func:`partition_devices`);
    ``quotas`` maps tenant -> :class:`~.scheduler.TenantQuota` (or
    kwargs dict).  ``depth`` is each member's pipeline depth;
    ``pipelined=False`` keeps members synchronous (drive with
    :meth:`drain` -- the deterministic chaos mode).  Remaining
    ``**core_kw`` (max_batch, shed, breaker_threshold, retries,
    hbm_bytes, ...) go to every member's :class:`SolverService`."""

    def __init__(self, devices=None, *, grids=2, depth: int = 3,
                 quotas: dict | None = None, pipelined: bool = True,
                 autostart: bool = True, clock=time.monotonic,
                 sleep=None, flight=None, slo=None, **core_kw):
        parts = partition_devices(devices, grids)
        self.pipelined = bool(pipelined)
        self.depth = max(int(depth), 1)
        self.clock = clock
        #: ONE flight recorder shared by every member (ISSUE 20): a
        #: breaker trip on g1 dumps the record of what g0 was doing too
        self.flight = flight if flight is not None \
            else FlightRecorder(clock=clock)
        #: windowed per-(tenant, grid, bucket) SLO estimators, fed by
        #: every settled doc
        self.slo = slo if slo is not None else SLOMonitor()
        self.scheduler = FairScheduler(quotas=quotas)
        self.services: list = []         # per-member SolverService cores
        self.workers: list = []          # pipelined mode: GridWorker per core
        for i, devs in enumerate(parts):
            name = f"g{i}"
            svc = SolverService(
                Grid(list(devs)), name=name, tune_ns=name,
                pipeline_depth=self.depth, device=devs[0],
                clock=clock, sleep=sleep, flight=self.flight, **core_kw)
            self.services.append(svc)
            if self.pipelined:
                self.workers.append(GridWorker(
                    service=svc, depth=self.depth, autostart=autostart))
            else:
                svc.on_result = self._make_on_result(i)
        self.max_batch = self.services[0].max_batch
        #: outstanding per member counts ROUTED, unsettled requests; a
        #: member accepts at most ``max_batch x depth`` (pipelined) or
        #: ``max_batch`` (sync) before the scheduler holds the rest
        self._grid_cap = self.max_batch * (self.depth if self.pipelined
                                           else 1)
        self._grid_out = [0] * len(self.services)
        self._tenant_out: dict = {}      # tenant -> unsettled count
        self._pending: list = [dict() for _ in self.services]  # sync mode
        self._ids = itertools.count()
        self.results: dict = {}          # fleet_id -> final doc
        self._settled: list = []         # (fleet_id, doc) ledger, in order
        self._stop = False
        # RLock: future resolution (inside _pump, under the lock) fires
        # the accounting callback, which re-enters _pump
        self._lock = threading.RLock()

    # ---- member plumbing --------------------------------------------
    def _make_on_result(self, gi: int):
        def on_result(rid, doc, x):
            self._grid_settled(gi, rid, doc, x)
        return on_result

    def _grid_settled(self, gi: int, rid, doc, x) -> None:
        """Sync-mode member completion: map the member's request id back
        to its fleet future and settle it."""
        with self._lock:
            fut = self._pending[gi].pop(rid, None)
            self._grid_out[gi] = max(self._grid_out[gi] - 1, 0)
        if fut is not None:
            self._settle(fut, doc, x)

    def _settle(self, fut: FleetFuture, doc, x) -> None:
        if isinstance(doc, dict) and "latency_s" in doc \
                and fut.t0 is not None:
            # the member measured from ITS arrival; the tenant waited
            # from fleet submit, scheduler hold included -- re-stamp on
            # a copy so the member's own ledger keeps its view
            doc = dict(doc)
            doc["latency_s"] = self.clock() - fut.t0
        with self._lock:
            self.results[fut.fleet_id] = doc
            self._settled.append((fut.fleet_id, doc))
            if isinstance(doc, dict):    # windowed SLO feed (ISSUE 20)
                self.slo.record(doc)
        fut._resolve(doc, x)

    def _account(self, fut) -> None:
        """Done-callback on every issued future: release the tenant's
        quota slot and pump held work into the freed capacity."""
        with self._lock:
            t = fut.tenant
            self._tenant_out[t] = max(self._tenant_out.get(t, 0) - 1, 0)
        self._pump()

    # ---- submit ------------------------------------------------------
    def submit(self, op: str, A, B, *, budget_s: float | None = None,
               deadline: Deadline | None = None,
               tenant: str | None = None, callback=None) -> FleetFuture:
        """Enqueue one request; returns its :class:`FleetFuture`.

        The deadline clock starts HERE.  Rejections (quota, shutdown,
        bad request, no capable grid, member-level sheds) resolve the
        future with a structured ``serve_reject/v1`` -- nothing raises.
        ``tenant=None`` bills the shared ``'default'`` tenant."""
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        fut = FleetFuture(next(self._ids), tenant)
        fut.t0 = self.clock()
        # fleet-global id keys the lifecycle flow: member request ids
        # collide across grids, fleet ids never do
        trace = RequestTrace(id=f"f{fut.fleet_id}", clock=self.clock,
                             tenant=tenant, op=op, flight=self.flight)
        trace.mark("submitted", op=op)
        if callback is not None:
            fut.add_done_callback(callback)
        if deadline is None and budget_s is not None:
            deadline = Deadline(budget_s, clock=self.clock)
        if self._stop:
            _metrics.inc("serve_rejects", reason="shutdown")
            self.flight.record("reject", reason="shutdown", tenant=tenant)
            self._settle(fut, reject_doc(
                "shutdown", deadline=deadline, tenant=tenant,
                detail="fleet has shut down", trace=trace), None)
            return fut
        v = validate_problem(op, A, B)
        if isinstance(v, dict):
            v["tenant"] = tenant
            _metrics.inc("serve_rejects", reason=v["reason"])
            self.flight.record("reject", reason=v["reason"], tenant=tenant)
            trace.mark("shed", reason=v["reason"])
            trace.mark("rejected")
            v["timeline"] = trace.to_doc()
            self._settle(fut, v, None)
            return fut
        op, A, B, bucket = v
        with self._lock:
            q = self.scheduler.quota(tenant)
            if q.max_outstanding is not None \
                    and self._tenant_out.get(tenant, 0) >= q.max_outstanding:
                _metrics.inc("serve_rejects", reason="quota")
                # kind='reject' reason='quota' is what arms the flight
                # recorder's quota-storm trigger
                self.flight.record("reject", reason="quota", tenant=tenant)
                self._settle(fut, reject_doc(
                    "quota", bucket=bucket,
                    queue_depth=self.scheduler.pending(tenant),
                    deadline=deadline, tenant=tenant,
                    detail=f"tenant {tenant!r} at max_outstanding="
                           f"{q.max_outstanding}", trace=trace), None)
                return fut
            self._tenant_out[tenant] = self._tenant_out.get(tenant, 0) + 1
            fut.add_done_callback(self._account)
            self.scheduler.push(
                tenant, _FleetSub(op, A, B, bucket, deadline, fut, trace),
                cost=bucket.solve_flops())
        self._pump()
        return fut

    # ---- routing -----------------------------------------------------
    def _blocked(self, gi: int, bucket) -> str | None:
        """Why member ``gi`` cannot take ``bucket`` right now: 'memory'
        (static peak over its HBM budget), 'breaker' (OPEN, cooldown not
        elapsed -- the same peek-only check as ``SolverService.submit``),
        or None when capable."""
        svc = self.services[gi]
        if svc.admission.memory_pressure(bucket) is not None:
            return "memory"
        br = svc.breakers.get(bucket.key())
        if br is not None and br.state == OPEN:
            elapsed_ok = br.opened_at is not None \
                and svc.clock() - br.opened_at >= br.cooldown_s
            if not elapsed_ok:
                return "breaker"
        return None

    def _score(self, gi: int, bucket) -> tuple:
        """Routing score (lower wins): queued batches ahead x the
        member's measured EWMA for the bucket, tie-broken by raw backlog
        then member index (deterministic, and backlog ties alternate)."""
        out = self._grid_out[gi]
        batches = -(-(out + 1) // self.max_batch)
        est = self.services[gi].admission.estimate_batch_s(bucket)
        return (batches * est, out, gi)

    def _route_one(self, sub: _FleetSub):
        """Pick a member for one scheduled submission.  Returns the
        member index, a reject doc (no member can EVER take it right
        now), or None (capable members exist but all are at capacity --
        caller re-queues and waits for a completion)."""
        blocked: list = []
        best = None
        capable = False
        for gi in range(len(self.services)):
            why = self._blocked(gi, sub.bucket)
            if why is not None:
                blocked.append((gi, why))
                continue
            capable = True
            if self._grid_out[gi] >= self._grid_cap:
                continue
            s = self._score(gi, sub.bucket)
            if best is None or s < best[0]:
                best = (s, gi)
        if best is not None:
            return best[1]
        if capable:
            return None                  # all capable members full: hold
        # nobody can take this bucket: structured reject, attributed to
        # the first blocking member (memory wins when uniform)
        reasons = {why for _, why in blocked}
        reason = "memory_pressure" if reasons == {"memory"} \
            else "breaker_open"
        gi, why = blocked[0]
        _metrics.inc("serve_rejects", reason=reason)
        self.flight.record("reject", reason=reason,
                           tenant=sub.future.tenant,
                           bucket=sub.bucket.key())
        return reject_doc(
            reason, bucket=sub.bucket, deadline=sub.deadline,
            grid=self.services[gi].name, tenant=sub.future.tenant,
            detail=f"no fleet member can take {sub.bucket.key()}: "
                   + ", ".join(f"{self.services[g].name}={w}"
                               for g, w in blocked),
            trace=sub.trace)

    def _pump(self) -> int:
        """Release scheduled work into member capacity, fairest first.
        Returns how many submissions were routed or rejected."""
        moved = 0
        with self._lock:
            while self.scheduler.pending() > 0:
                if all(o >= self._grid_cap for o in self._grid_out):
                    break
                sub = self.scheduler.pop()
                routed = self._route_one(sub)
                if routed is None:       # capable members all full
                    self.scheduler.push_front(
                        sub.future.tenant, sub,
                        cost=sub.bucket.solve_flops())
                    break
                moved += 1
                if isinstance(routed, dict):
                    self._settle(sub.future, routed, None)
                    continue
                self._dispatch(routed, sub)
            _metrics.set_gauge("serve_fleet_pending",
                               self.scheduler.pending())
            for gi, svc in enumerate(self.services):
                _metrics.set_gauge("serve_grid_outstanding",
                                   self._grid_out[gi], grid=svc.name)
        return moved

    def _dispatch(self, gi: int, sub: _FleetSub) -> None:
        """Hand one submission to member ``gi`` (lock held)."""
        svc = self.services[gi]
        sub.future.grid = svc.name
        if sub.trace is not None:
            sub.trace.annotate(grid=svc.name)
        self._grid_out[gi] += 1
        if self.pipelined:
            fut = sub.future

            def chain(inner, gi=gi, fut=fut):
                with self._lock:
                    self._grid_out[gi] = max(self._grid_out[gi] - 1, 0)
                self._settle(fut, inner._doc, inner._x)

            self.workers[gi].submit(
                sub.op, sub.A, sub.B, deadline=sub.deadline,
                tenant=fut.tenant, callback=chain, trace=sub.trace)
            return
        out = svc.submit(sub.op, sub.A, sub.B, deadline=sub.deadline,
                         tenant=sub.future.tenant, trace=sub.trace)
        if isinstance(out, dict):        # member-level fast reject
            self._grid_out[gi] = max(self._grid_out[gi] - 1, 0)
            self._settle(sub.future, out, None)
        else:
            self._pending[gi][out] = sub.future

    # ---- sync drive (chaos / deterministic mode) ---------------------
    def drain(self) -> dict:
        """Sync mode only: process everything scheduled + queued.  One
        batch per member per sweep (members take turns, so one member's
        deep queue cannot monopolize the host), pumping freed capacity
        between sweeps.  Returns ``{fleet_id: doc}`` settled by this
        call."""
        if self.pipelined:
            raise RuntimeError("drain() drives pipelined=False fleets; "
                               "pipelined members run their own workers")
        tm = phase_hook("serve")
        tm.start()
        n0 = len(self._settled)
        bi = 0
        while True:
            moved = self._pump()
            ran = False
            for svc in self.services:
                popped = svc._pop_batch()
                if popped is None:
                    continue
                bucket, batch = popped
                svc._run_batch(bucket, batch, tm, bi)
                bi += 1
                ran = True
            if not ran and moved == 0:
                break
        return dict(self._settled[n0:])

    # ---- lifecycle ---------------------------------------------------
    def shutdown(self, drain: bool = True) -> dict:
        """Stop the fleet.  ``drain=True`` finishes everything scheduled
        and queued through the normal pipeline; ``drain=False`` flushes
        scheduled work with structured shutdown rejects and emergency-
        stops every member (their in-flight batches still complete).
        Every outstanding future resolves either way -- zero silent
        drops.  Idempotent.  Returns ``{fleet_id: doc}`` for everything
        settled by this call."""
        n0 = len(self._settled)
        with self._lock:
            already = self._stop
            self._stop = True
        if drain and not already:
            if self.pipelined:
                # held submissions release as member completions free
                # capacity; poll until the scheduler empties
                while True:
                    self._pump()
                    with self._lock:
                        if self.scheduler.pending() == 0:
                            break
                    time.sleep(0.002)
                for w in self.workers:
                    w.shutdown(drain=True)
            else:
                self.drain()
                for svc in self.services:
                    svc.shutdown(drain=True)
        else:
            with self._lock:
                held = self.scheduler.flush()
            for sub in held:
                _metrics.inc("serve_rejects", reason="shutdown")
                self.flight.record("reject", reason="shutdown",
                                   tenant=sub.future.tenant)
                self._settle(sub.future, reject_doc(
                    "shutdown", bucket=sub.bucket, deadline=sub.deadline,
                    tenant=sub.future.tenant,
                    detail="flushed by fleet shutdown(drain=False)",
                    trace=sub.trace), None)
            if self.pipelined:
                for w in self.workers:
                    w.shutdown(drain=False)
            else:
                for svc in self.services:
                    svc.shutdown(drain=False)
        return dict(self._settled[n0:])

    # ---- introspection ----------------------------------------------
    def stats(self) -> dict:
        """One structured snapshot: per-member identity/backlog/EWMA,
        scheduler queues and deficits, tenant outstanding counts."""
        with self._lock:
            members = []
            for gi, svc in enumerate(self.services):
                members.append({
                    "grid": svc.name, "devices": len(svc.grid.devices),
                    "shape": [svc.grid.height, svc.grid.width],
                    "outstanding": self._grid_out[gi],
                    "capacity": self._grid_cap,
                    "queued": svc.queue_depth(),
                    "ewma_s": dict(svc.admission._ewma),
                    "breakers": {k: b.state
                                 for k, b in svc.breakers.items()},
                })
            return {"members": members,
                    "scheduler": self.scheduler.to_doc(),
                    "tenants_outstanding": dict(self._tenant_out),
                    "pipelined": self.pipelined, "depth": self.depth}
