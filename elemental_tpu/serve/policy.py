"""Resilience policy: retry/backoff, circuit breaking, degradation.

The decision layer between the service and ``certified_solve`` (ISSUE
9).  Three policies, all deterministic under replay (seeded jitter,
injectable clocks):

  * **Retry with exponential backoff + jitter** -- a request whose
    escalation fails may be retried (a fresh ``certified_solve`` run
    absorbs transient faults the first run hit); delays are
    ``base * 2^attempt * (1 + jitter*u)`` with ``u`` drawn from a
    per-(seed, request, attempt) ``numpy`` stream -- the same
    determinism contract as :class:`~elemental_tpu.resilience.FaultPlan`
    -- and always clamped to the request's remaining deadline.

  * **Per-bucket circuit breaker** -- ``threshold`` CONSECUTIVE
    certification failures of a bucket's fast path trip it OPEN: new
    submissions reject fast (``serve_reject/v1`` reason
    ``breaker_open``), queued requests bypass the poisoned fast path
    straight to escalation.  After ``cooldown`` seconds the breaker goes
    HALF-OPEN and admits ONE probe batch; success closes it, failure
    re-opens.  State is a gauge (``serve_breaker_state``: 0 closed /
    1 open / 2 half-open) and every transition a counter
    (``serve_breaker_transitions``) on the obs metrics registry.

  * **Graceful degradation** -- the EQuARX-style load-aware trade
    (arXiv 2506.17615): under queue pressure escalations START at the
    cheap-but-narrow ``quant`` rung (int8 wire + refinement) and climb
    only within the remaining deadline; an unloaded service starts at
    the full-wire ``fast`` rung instead, spending bandwidth to skip the
    quant rung's refinement budget.  :func:`select_ladder` is the single
    decision point.
"""
from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as _metrics
from ..resilience.certify import default_ladder

#: breaker states (gauge encoding pinned by tests/serve)
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

#: queue pressure (depth / capacity) at or above which escalations start
#: at the quant rung
DEGRADE_PRESSURE = 0.5


class RetryPolicy:
    """Deterministic exponential backoff + jitter, deadline-clamped."""

    def __init__(self, *, retries: int = 1, base_s: float = 0.05,
                 jitter: float = 0.5, seed: int = 0):
        self.retries = max(int(retries), 0)
        self.base_s = float(base_s)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delay_s(self, request_id: int, attempt: int,
                deadline=None) -> float:
        """Backoff before retry ``attempt`` (1-based) of ``request_id``;
        0 means retry immediately, negative means do not retry (no
        budget left)."""
        rng = np.random.default_rng(
            [self.seed, int(request_id), int(attempt)])
        d = self.base_s * (2.0 ** (attempt - 1)) \
            * (1.0 + self.jitter * float(rng.random()))
        if deadline is not None:
            rem = deadline.remaining()
            if rem <= 0.0:
                return -1.0
            d = min(d, max(rem - self.base_s, 0.0))
        return d


class CircuitBreaker:
    """One bucket's trip-open / half-open-probe / close state machine.

    Purely clock-driven (no threads): :meth:`allow` both reports whether
    the fast path may run AND performs the open -> half-open transition
    when the cooldown has elapsed.  ``record_success`` /
    ``record_failure`` feed it certification outcomes."""

    def __init__(self, bucket_key: str, *, threshold: int = 3,
                 cooldown_s: float = 1.0, clock=time.monotonic,
                 grid: str | None = None, flight=None):
        self.bucket_key = str(bucket_key)
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        #: owning fleet member (ISSUE 19): labels the breaker metric
        #: series per grid so one pool member tripping is attributable;
        #: None (direct single-service) keeps the PR-9 label set
        self.grid = grid
        #: flight recorder (ISSUE 20): every transition is a structured
        #: event; tripping OPEN is a DUMP TRIGGER -- the retrospective
        #: record of the requests that burned the breaker down
        self.flight = flight
        self.state = CLOSED
        self.failures = 0            # consecutive certification failures
        self.opened_at: float | None = None
        self._gauge()

    # ---- transitions -------------------------------------------------
    def _labels(self) -> dict:
        if self.grid is None:
            return {"bucket": self.bucket_key}
        return {"bucket": self.bucket_key, "grid": self.grid}

    def _gauge(self) -> None:
        _metrics.set_gauge("serve_breaker_state", _STATE_GAUGE[self.state],
                           **self._labels())

    def _transition(self, state: str) -> None:
        if state == self.state:
            return
        prev, self.state = self.state, state
        _metrics.inc("serve_breaker_transitions", to=state,
                     **self._labels())
        self._gauge()
        if self.flight is not None:
            self.flight.record("breaker", bucket=self.bucket_key,
                               grid=self.grid, frm=prev, to=state,
                               failures=self.failures)
            if state == OPEN:
                self.flight.trigger("breaker_open",
                                    bucket=self.bucket_key, grid=self.grid,
                                    failures=self.failures)

    def allow(self) -> bool:
        """May the fast path run?  Closed: yes.  Open: no, unless the
        cooldown elapsed -- then transition to half-open and admit ONE
        probe.  Half-open: the probe is already in flight, no."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self.opened_at is not None \
                    and self.clock() - self.opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN)
                return True
            return False
        return False                 # HALF_OPEN: one probe at a time

    def record_success(self) -> None:
        self.failures = 0
        if self.state in (HALF_OPEN, OPEN):
            self.opened_at = None
            self._transition(CLOSED)

    def record_failure(self) -> None:
        if self.state == HALF_OPEN:
            self.opened_at = self.clock()    # probe failed: re-open
            self._transition(OPEN)
            return
        self.failures += 1
        if self.state == CLOSED and self.failures >= self.threshold:
            self.opened_at = self.clock()
            self._transition(OPEN)

    def to_doc(self) -> dict:
        return {"bucket": self.bucket_key, "state": self.state,
                "consecutive_failures": self.failures,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s}


def select_ladder(op: str, pressure: float,
                  degrade_pressure: float = DEGRADE_PRESSURE):
    """The degradation decision: the escalation ladder for one request.

    ``pressure`` is queue depth / service capacity.  At or above
    ``degrade_pressure`` the FULL ladder runs, quant rung first (cheap
    narrow wire, refinement pays it back); below it the quant rung is
    skipped -- full-precision wire straight away, nothing to refine
    back.  Deadline enforcement happens inside ``certified_solve``.

    Every returned ladder includes the 'abft' rung (ISSUE 11) ahead of
    the fp32/classic refactorizations: a batch member that failed on a
    TRANSIENT fault gets panel-granular checksum recovery -- one
    recomputed panel inside the guarded driver -- before the service
    pays for bisect re-execution or a whole-solve escalation."""
    rungs = default_ladder(op)
    if pressure >= degrade_pressure:
        return rungs
    return tuple(r for r in rungs if r.name != "quant")
